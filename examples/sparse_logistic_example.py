"""Criteo-style sparse training: hashed high-dimensional features in ELL
layout with gather/scatter aggregators (the path SURVEY §7 flags as the
hard part — no dense equivalent fits on a chip at real Criteo width)."""

import numpy as np

from cycloneml_tpu.context import CycloneContext
from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
from cycloneml_tpu.ml.optim.lbfgs import LBFGS
from cycloneml_tpu.ml.optim.loss import DistributedLossFunction
from cycloneml_tpu.ml.optim.sparse_aggregators import binary_logistic_sparse


def main():
    ctx = CycloneContext.get_or_create()
    rng = np.random.RandomState(0)
    n, k, hashed_dim = 20_000, 16, 1 << 14
    indices = rng.randint(0, 10**6, size=(n, k))  # raw categorical ids
    values = np.ones((n, k), dtype=np.float32)
    true = rng.randn(hashed_dim)

    # labels from the true weights via the same hashed gather (no densify)
    from cycloneml_tpu.dataset.sparse import hash_features
    hidx, hval = hash_features(indices, values, hashed_dim)
    margins = (true[hidx] * hval).sum(axis=1)
    y = (margins > 0).astype(float)
    ds = SparseInstanceDataset.from_rows(
        ctx, [(indices[i], values[i]) for i in range(n)], y=y,
        hash_dim=hashed_dim)

    loss = DistributedLossFunction(
        ds, binary_logistic_sparse(hashed_dim, fit_intercept=False))
    state = LBFGS(max_iter=15).minimize(loss, np.zeros(hashed_dim))
    print(f"d={hashed_dim} nnz/row={k}: loss "
          f"{state.loss_history[0]:.4f} -> {state.value:.4f} "
          f"in {state.iteration} iterations")
    return state.value


if __name__ == "__main__":
    main()
