"""Distributed LogisticRegression on the mesh (≈ examples/src/main/python/
ml/logistic_regression_with_elastic_net.py in the reference).

Run: python -m cycloneml_tpu.submit --master local-mesh[8] \
         examples/logistic_regression_example.py
(local-mesh needs JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""

import numpy as np

from cycloneml_tpu.context import CycloneContext
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.classification import LogisticRegression


def main():
    ctx = CycloneContext.get_or_create()
    rng = np.random.RandomState(7)
    x = rng.randn(2000, 10)
    y = (x @ rng.randn(10) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})

    lr = LogisticRegression(maxIter=20, regParam=0.01, elasticNetParam=0.5)
    model = lr.fit(frame)
    print("coefficients:", np.asarray(model.coefficients))
    print("intercept:", model.intercept)
    summary = model.summary
    print("final loss:", summary.objective_history[-1])
    pred = model.transform(frame)
    acc = float((pred["prediction"] == y).mean())
    print(f"train accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
