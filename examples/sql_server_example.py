"""Remote SQL service (the Thriftserver role): start a CycloneSQLServer
over a shared session and query it from SQLClient connections — DDL made
by one connection is visible to the next (≈ the reference's
examples using beeline against the thriftserver)."""

import numpy as np

from cycloneml_tpu.sql.server import CycloneSQLServer, SQLClient
from cycloneml_tpu.sql.session import CycloneSession


def main():
    session = CycloneSession()
    sales = session.create_data_frame({
        "region": np.array(["east", "west", "east", "south"], dtype=object),
        "amount": np.array([120.0, 80.0, 200.0, 50.0]),
    })
    session.register_temp_view("sales", sales)

    server = CycloneSQLServer(session)
    print(f"serving SQL on {server.address}")
    try:
        with SQLClient(server.address) as c:
            cols, rows = c.execute(
                "SELECT region, SUM(amount) AS total FROM sales "
                "GROUP BY region ORDER BY total DESC")
            print(cols)
            for r in rows:
                print(r)
            c.execute("CREATE TABLE top AS SELECT region FROM sales "
                      "WHERE amount > 100")
        with SQLClient(server.address) as c2:  # new connection, same catalog
            _, rows2 = c2.execute("SELECT COUNT(*) AS n FROM top")
            print("top regions:", rows2[0][0])
        return {"regions": len(rows), "top": rows2[0][0]}
    finally:
        server.stop()


if __name__ == "__main__":
    main()
