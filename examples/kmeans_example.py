"""KMeans clustering on the mesh (≈ examples/src/main/python/ml/
kmeans_example.py)."""

import numpy as np

from cycloneml_tpu.context import CycloneContext
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.clustering import KMeans


def main():
    ctx = CycloneContext.get_or_create()
    rng = np.random.RandomState(1)
    centers = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]])
    x = np.concatenate([rng.randn(300, 2) + c for c in centers])
    frame = MLFrame(ctx, {"features": x})

    model = KMeans(k=3, seed=1).fit(frame)
    print("centers:")
    for c in model.cluster_centers:
        print("  ", np.round(np.asarray(c), 2))
    print("training cost:", model.training_cost)
    return model


if __name__ == "__main__":
    main()
