"""pandas-facade analytics round trip (≈ the pandas-on-Spark quickstart,
ref: python/pyspark/pandas — frame.py/groupby.py/namespace.py).

Builds a small sales table, walks the r5 long-tail surface — groupby
transform/rank, merge-on-index, cut/get_dummies, duplicated, nlargest,
pivot — then bridges to the SQL tier where a 3-table star query runs
through the cost-based join reorderer, and brings the result back as a
frame.
"""

import numpy as np

import cycloneml_tpu.pandas as cp
from cycloneml_tpu.pandas import CycloneFrame, cut, get_dummies
from cycloneml_tpu.sql.session import CycloneSession


def main():
    rng = np.random.RandomState(7)
    n = 400
    sales = CycloneFrame({
        "store": rng.randint(0, 4, n).astype(np.int64),
        "sku": rng.randint(0, 3, n).astype(np.int64),
        "units": rng.poisson(5, n).astype(np.int64),
        "price": np.round(rng.uniform(1, 30, n), 2),
    })
    sales["revenue"] = sales["units"].to_numpy() * sales["price"].to_numpy()

    # groupby row-shaped ops: share of the store's revenue, rank in store
    g = sales.groupby("store")
    share = sales["revenue"].to_numpy() / g.transform("sum")["revenue"].values
    sales["rev_share"] = share
    sales["rev_rank"] = g.rank()["revenue"].values

    # binning + one-hot
    sales["price_band"] = cut(sales["price"], [0, 10, 20, 30],
                              labels=["lo", "mid", "hi"]).values
    bands = get_dummies(sales["price_band"])
    print("price bands:", {c: int(bands[c].sum()) for c in bands.columns})

    # top sellers and dedup
    top = sales.nlargest(3, "revenue")
    print("top-3 revenue rows:", np.round(top["revenue"].values, 2))
    dup_pairs = int(sales.duplicated(subset=["store", "sku"]).sum())
    print(f"{dup_pairs} rows repeat a (store, sku) pair")

    # merge-on-index: store dimension table
    stores = CycloneFrame({
        "store": np.arange(4, dtype=np.int64),
        "city": np.array(["tokyo", "osaka", "kyoto", "nara"], dtype=object),
    }).set_index("store")
    by_store = g.sum().join(stores)  # index-on-index
    print("revenue by city:",
          {c: round(float(r), 1) for c, r in zip(by_store["city"].values,
                                                 by_store["revenue"].values)})

    # SQL bridge: the 3-table star rides the cost-based join reorderer
    s = CycloneSession()
    s.register_temp_view("sales", sales[["store", "sku", "revenue"]]
                         .to_sql_df(s))
    s.register_temp_view("stores", stores.reset_index().to_sql_df(s))
    s.register_temp_view("skus", CycloneFrame({
        "sku": np.arange(3, dtype=np.int64),
        "name": np.array(["widget", "gadget", "gizmo"], dtype=object),
    }).to_sql_df(s))
    df = s.sql(
        "SELECT city, name, SUM(revenue) AS rev FROM sales "
        "JOIN stores ON sales.store = stores.store "
        "JOIN skus ON sales.sku = skus.sku "
        "GROUP BY city, name ORDER BY rev DESC LIMIT 5")
    out = CycloneFrame(df.to_dict())
    print("top city/sku pairs:")
    for _, row in out.iterrows():
        print(f"  {row['city']:6s} {row['name']:7s} {row['rev']:8.1f}")


if __name__ == "__main__":
    main()
