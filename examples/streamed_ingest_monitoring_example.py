"""Round-trip of the ingest → train → observe path: stream a libsvm file
onto the mesh chunk-by-chunk (bounded driver memory, the Criteo-class
entrance), train bounded-coefficient logistic regression with the chunked
device optimizer, and watch the job in the live web UI's REST surface."""

import json
import os
import tempfile
import urllib.request

import numpy as np

from cycloneml_tpu.context import CycloneContext
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
from cycloneml_tpu.ml.classification import LogisticRegression
from cycloneml_tpu.ml.optim.lbfgs import LBFGS
from cycloneml_tpu.ml.optim.loss import DistributedLossFunction
from cycloneml_tpu.ml.optim.sparse_aggregators import binary_logistic_sparse


def main():
    ctx = CycloneContext.get_or_create()
    ui = ctx.start_ui()
    print(f"status UI at {ui.url}")

    # 1. write a synthetic libsvm file and stream it onto the mesh — the
    #    driver never holds more than one chunk
    rng = np.random.RandomState(0)
    n, k, d = 20_000, 12, 2048
    path = os.path.join(tempfile.mkdtemp(), "train.libsvm")
    true = rng.randn(d)
    with open(path, "w") as fh:
        for _ in range(n):
            cols = np.sort(rng.choice(d, size=k, replace=False))
            vals = rng.randn(k)
            label = int(vals @ true[cols] > 0)
            feats = " ".join(f"{c + 1}:{v:.6f}" for c, v in zip(cols, vals))
            fh.write(f"{label} {feats}\n")
    ds = SparseInstanceDataset.from_libsvm_stream(ctx, path, chunk_rows=4096)
    print(f"streamed {ds.n_rows} rows x {ds.n_features} features onto "
          f"{ctx.mesh_runtime.n_devices} devices")

    # 2. sparse-tier training on the streamed dataset
    loss = DistributedLossFunction(
        ds, binary_logistic_sparse(ds.n_features, fit_intercept=False))
    state = LBFGS(max_iter=15, tol=1e-8).minimize(
        loss, np.zeros(ds.n_features))
    print(f"sparse fit: loss {state.loss_history[0]:.4f} -> "
          f"{state.value:.4f} in {state.iteration} iterations")

    # 3. dense estimator with box constraints (LBFGS-B) + chunked device
    #    optimizer for the unconstrained comparison fit
    x = rng.randn(4000, 16)
    y = (x @ rng.randn(16) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    free = LogisticRegression(maxIter=40, regParam=0.02).fit(frame)
    nneg = LogisticRegression(
        maxIter=40, regParam=0.02,
        lowerBoundsOnCoefficients=np.zeros((1, 16))).fit(frame)
    print(f"unconstrained fit: {free.summary.total_iterations} iterations "
          f"in {free.summary.total_dispatches} device dispatches")
    print(f"nonnegative fit  : min coefficient "
          f"{nneg.coefficients.to_array().min():.3g} (>= 0)")

    # 4. the jobs showed up in the live status UI
    jobs = json.loads(urllib.request.urlopen(
        ui.url + "api/v1/jobs", timeout=5).read())
    print(f"status store tracked {len(jobs)} jobs; last: "
          f"{jobs[-1]['description']} [{jobs[-1]['status']}]")
    assert any("fit" in j["description"] for j in jobs)
    return {"rows": ds.n_rows, "sparse_loss": state.value,
            "jobs_tracked": len(jobs)}


if __name__ == "__main__":
    main()
