"""SQL + DataFrame basics with a Python UDF (≈ the reference's
examples/src/main/python/sql/basic.py)."""

import numpy as np

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.column import col
from cycloneml_tpu.sql.session import CycloneSession


def main():
    s = CycloneSession()
    people = s.create_data_frame({
        "name": ["Michael", "Andy", "Justin"],
        "age": [29, 30, 19],
        "dept": ["eng", "eng", "sales"],
    })
    s.register_temp_view("people", people)

    adults = s.sql("SELECT name, age FROM people WHERE age > 20 ORDER BY age")
    adults.show()

    by_dept = people.group_by("dept").agg(
        F.avg("age").alias("avg_age"), F.count("*").alias("n"))
    by_dept.show()

    shout = F.udf(lambda name: name.upper(), name="shout")
    people.select(shout(col("name")).alias("loud")).show()

    stats = people.to_pandas_frame()
    print("pandas bridge mean age:", stats["age"].mean())
    return [r.name for r in adults.collect()]


if __name__ == "__main__":
    main()
