"""Criteo-class end-to-end demo (BASELINE config-1 analog, round-3 item 10).

Generates a synthetic hashed-sparse libsvm file of the requested size
INCREMENTALLY (the generator never holds the dataset), streams it through
the native bounded-memory scanner onto the mesh as ELL blocks, fits the
sparse-tier LogisticRegression, and evaluates AUC — printing wall-clock
per stage and the driver RSS high-water so the ledger row is auditable.

Usage: python examples/criteo_class_demo.py [target_gb] [hash_dim_log2]
"""

import os
import resource
import sys
import time

import numpy as np


def rss_mb() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024


def generate(path: str, target_bytes: int, d_hash: int, k_nnz: int = 30,
             seed: int = 0) -> int:
    """Write rows until the file reaches target_bytes; labels follow a
    sparse ground-truth weight vector so AUC is learnable. Returns rows."""
    rng = np.random.default_rng(seed)
    beta_idx = rng.choice(d_hash, 4096, replace=False)
    beta_val = rng.standard_normal(4096)
    beta = {int(i): float(v) for i, v in zip(beta_idx, beta_val)}
    rows = 0
    chunk = 20_000
    with open(path, "w") as fh:
        while fh.tell() < target_bytes:
            idx = rng.integers(0, d_hash, (chunk, k_nnz))
            val = np.abs(rng.standard_normal((chunk, k_nnz))).round(4)
            margins = np.zeros(chunk)
            for r in range(chunk):
                margins[r] = sum(beta.get(int(j), 0.0) * v
                                 for j, v in zip(idx[r], val[r]))
            # noise scaled so the Bayes-optimal AUC is ~0.85-0.9 — a
            # separable problem would prove nothing about the fit
            y = (margins + 3.0 * rng.standard_normal(chunk) > 0).astype(int)
            lines = []
            for r in range(chunk):
                order = np.argsort(idx[r])
                toks = " ".join(f"{idx[r][j] + 1}:{val[r][j]}"
                                for j in order)
                lines.append(f"{y[r]} {toks}\n")
            fh.write("".join(lines))
            rows += chunk
    return rows


def main() -> None:
    target_gb = float(sys.argv[1]) if len(sys.argv) > 1 else 0.3
    d_hash = 1 << (int(sys.argv[2]) if len(sys.argv) > 2 else 20)
    path = os.environ.get("CRITEO_DEMO_PATH", "/tmp/criteo_demo.svm")

    t0 = time.perf_counter()
    n_rows = generate(path, int(target_gb * (1 << 30)), d_hash)
    gen_s = time.perf_counter() - t0
    size_gb = os.path.getsize(path) / (1 << 30)
    print(f"generated {size_gb:.2f} GB / {n_rows} rows in {gen_s:.0f}s, "
          f"rss={rss_mb()} MB", flush=True)

    from cycloneml_tpu.conf import CycloneConf
    from cycloneml_tpu.context import CycloneContext
    from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
    from cycloneml_tpu.ml.classification import LogisticRegression

    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "criteo-demo"))
    rss_before = rss_mb()
    t0 = time.perf_counter()
    labels: list = []
    n_readers = int(os.environ.get("CRITEO_READERS", "4"))
    ds = SparseInstanceDataset.from_libsvm_stream(
        ctx, path, hash_dim=d_hash, chunk_rows=65536,
        n_readers=n_readers, collect_labels=labels)
    ingest_s = time.perf_counter() - t0
    print(f"streamed ELL ingest: {ingest_s:.0f}s "
          f"({size_gb / max(ingest_s, 1e-9) * 1024:.0f} MB/s), "
          f"rss={rss_mb()} MB (+{rss_mb() - rss_before} over pre-ingest)",
          flush=True)

    t0 = time.perf_counter()
    model = LogisticRegression(maxIter=15, regParam=1e-6,
                               tol=1e-8).fit(ds)
    fit_s = time.perf_counter() - t0
    print(f"sparse LR fit: {fit_s:.0f}s, "
          f"{model.summary.total_iterations} iterations, rss={rss_mb()} MB",
          flush=True)

    # AUC on the training stream (the config-1 analog's quality gate):
    # per-row margins via the same device gather the trainer uses — margins
    # are monotone in probability, so AUC needs no sigmoid
    import jax
    import jax.numpy as jnp
    from cycloneml_tpu.ml.evaluation.evaluators import binary_curve_points
    from cycloneml_tpu.ml.optim.sparse_aggregators import _margins

    t0 = time.perf_counter()
    coef = jnp.asarray(model.coefficients, ds.values.dtype)
    b0 = jnp.asarray(float(model.intercept), ds.values.dtype)
    margins = np.asarray(jax.jit(_margins)(ds.indices, ds.values, coef, b0))
    mask = np.asarray(ds.w) > 0
    score = margins[mask].astype(np.float64)
    y = np.concatenate([np.concatenate(dev) for dev in labels if dev])
    assert len(y) == len(score) == n_rows, (len(y), len(score), n_rows)
    _, tps, fps, tp_tot, fp_tot = binary_curve_points(score, y)
    auc = float(np.trapezoid(np.concatenate([[0.0], tps / tp_tot]),
                             np.concatenate([[0.0], fps / fp_tot])))
    print(f"AUC={auc:.4f} (eval {time.perf_counter() - t0:.0f}s), "
          f"final rss={rss_mb()} MB", flush=True)
    os.unlink(path)
    assert auc > 0.65, auc


if __name__ == "__main__":
    main()
