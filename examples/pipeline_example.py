"""Estimator/Transformer/Pipeline with persistence (≈ the reference's
examples/src/main/python/ml/pipeline_example.py)."""

import tempfile

import numpy as np

from cycloneml_tpu.context import CycloneContext
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Pipeline, PipelineModel
from cycloneml_tpu.ml.classification import LogisticRegression
from cycloneml_tpu.ml.feature import StandardScaler


def main():
    ctx = CycloneContext.get_or_create()
    rng = np.random.RandomState(0)
    x = rng.randn(500, 6) * 10 + 3
    y = (x @ rng.randn(6) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})

    pipeline = Pipeline(stages=[
        StandardScaler(inputCol="features", outputCol="scaled",
                       withMean=True),
        LogisticRegression(featuresCol="scaled", maxIter=15),
    ])
    model = pipeline.fit(frame)
    out = model.transform(frame)
    acc = float((out["prediction"] == y).mean())
    print(f"pipeline train accuracy: {acc:.3f}")

    path = tempfile.mkdtemp(prefix="pipeline-model-") + "/model"
    model.save(path)
    reloaded = PipelineModel.load(path)
    out2 = reloaded.transform(frame)
    assert (out2["prediction"] == out["prediction"]).all()
    print("persistence round-trip OK:", path)
    return acc


if __name__ == "__main__":
    main()
