"""Structured streaming word count (≈ the reference's
examples/src/main/python/sql/streaming/structured_network_wordcount.py,
with a memory source instead of a socket)."""

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.session import CycloneSession
from cycloneml_tpu.streaming import MemoryStream


def main():
    session = CycloneSession()
    lines = MemoryStream(["value"])

    words = lines.to_df(session)  # one row per word after the UDF explode
    counts = (words.group_by("value").agg(F.count("*").alias("count")))
    query = (counts.write_stream.output_mode("complete").format("memory")
             .query_name("wordcounts").start())

    for chunk in (["apache", "cyclone"], ["cyclone", "tpu", "tpu"]):
        lines.add_data(value=chunk)
        query.process_all_available()

    result = session.table("wordcounts").order_by(F.col("count").desc())
    result.show()
    top = result.first()
    print("most frequent:", top.value, top["count"])
    query.stop()
    return dict((r.value, r["count"]) for r in result.collect())


if __name__ == "__main__":
    main()
