"""Streaming ETL: file source → windowed aggregation → parquet-ready table
(≈ the reference's structured streaming file-sink examples)."""

import tempfile
from pathlib import Path

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.session import CycloneSession


def main():
    workdir = Path(tempfile.mkdtemp(prefix="stream-etl-"))
    indir = workdir / "incoming"
    indir.mkdir()
    (indir / "batch0.csv").write_text(
        "ts,sensor,temp\n10,1,20.5\n12,2,21.0\n14,1,22.5\n")

    s = CycloneSession()
    stream = s.read_stream.format("csv").load(str(indir))
    agg = (stream.with_watermark("ts", 5.0)
           .group_by(F.window("ts", 10.0).alias("bucket"), "sensor")
           .agg(F.avg("temp").alias("avg_temp"),
                F.count("*").alias("n")))
    # complete mode: the table holds the CURRENT aggregate only (update mode
    # into a memory sink would accumulate superseded group versions)
    q = (agg.write_stream.output_mode("complete").format("memory")
         .query_name("sensor_stats")
         .option("checkpointLocation", str(workdir / "ckpt")).start())
    q.process_all_available()

    (indir / "batch1.csv").write_text("ts,sensor,temp\n16,1,23.0\n31,2,19.0\n")
    q.process_all_available()

    table = s.table("sensor_stats").order_by("bucket", "sensor")
    table.show()
    # land the aggregate as parquet for downstream batch consumers
    out = workdir / "sensor_stats.parquet"
    table.write.mode("overwrite").parquet(str(out))
    back = s.read_parquet(str(out))
    print("rows landed:", back.count(), "->", out)
    q.stop()
    return back.count()


if __name__ == "__main__":
    main()
