"""Sessionization-style window analytics (≈ the reference's window-function
examples in examples/src/main/python/sql/)."""

import numpy as np

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.column import col
from cycloneml_tpu.sql.session import CycloneSession
from cycloneml_tpu.sql.window import Window, lag, rank, row_number


def main():
    s = CycloneSession()
    df = s.create_data_frame({
        "user": ["u1", "u1", "u1", "u2", "u2"],
        "ts": [1.0, 5.0, 9.0, 2.0, 3.0],
        "spend": [10.0, 20.0, 5.0, 50.0, 25.0],
    })
    w = Window.partition_by("user").order_by("ts")
    out = (df.with_column("visit", row_number().over(w))
             .with_column("cum_spend", F.sum("spend").over(w))
             .with_column("gap", col("ts") - lag("ts").over(w))
             .with_column("spend_rank",
                          rank().over(Window.partition_by("user")
                                      .order_by(col("spend").desc()))))
    out.order_by("user", "ts").show()
    top = out.filter(col("spend_rank") == 1).order_by("user").collect()
    print("biggest purchase per user:",
          [(r.user, r.spend) for r in top])
    return [(r.user, r.spend) for r in top]


if __name__ == "__main__":
    main()
