"""PageRank over a property graph (≈ examples/src/main/python/pagerank.py
and the GraphX lib, ref: graphx/.../lib/PageRank.scala)."""

import numpy as np

from cycloneml_tpu.context import CycloneContext
from cycloneml_tpu.graph.graph import Graph
from cycloneml_tpu.graph.lib import pagerank


def main():
    ctx = CycloneContext.get_or_create()
    # tiny web: 0 <-> 1, both point at 2
    g = Graph.from_edges(ctx, [(0, 1), (1, 0), (0, 2), (1, 2)])
    ranks = pagerank(g, tol=1e-6)
    for v, r in enumerate(np.asarray(ranks)):
        print(f"vertex {v}: rank {r:.4f}")
    assert np.argmax(ranks) == 2
    return ranks


if __name__ == "__main__":
    main()
