"""Benchmark driver — prints ONE JSON line.

Primary metric: end-to-end ``LogisticRegression`` distributed-gradient
throughput on the attached TPU (the north-star path, BASELINE.json), scored
against the reference's committed BLAS throughput record: dgemm[N,N]
best-java = 2409.7 M ops/s on its CI hardware
(ref: mllib-local/benchmarks/BLASBenchmark-results.txt:158-169 — the only
committed kernel-throughput number; no end-to-end MLlib training numbers are
committed, see BASELINE.md). vs_baseline therefore compares our measured
device GEMM M ops/s inside the training step against 2409.7.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

REF_DGEMM_MOPS = 2409.7  # BLASBenchmark-results.txt:158-169 (java best)


def bench_gemm(dim: int = 2048, iters: int = 400) -> float:
    """Sustained f32-accumulate GEMM M ops/s on device.

    A data-dependent scan chain with a scalar readback: per-call dispatch
    latency (~70 ms through the TPU relay) is amortised over ``iters``
    sequential matmuls and the host transfer forces real completion —
    ``block_until_ready`` alone under-measures. Precision.HIGHEST keeps the
    comparison against the reference's f64 JVM dgemm conservative.
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(dim, dim), dtype=jnp.float32)
    b = jnp.asarray(rng.randn(dim, dim), dtype=jnp.float32)

    @jax.jit
    def mm_chain(a, b):
        def body(carry, _):
            a, b = carry
            c = jnp.dot(a, b, precision=jax.lax.Precision.HIGHEST)
            return (c * (1.0 / dim), b), None
        (a_out, _), _ = jax.lax.scan(body, (a, b), None, length=iters)
        return jnp.sum(a_out)

    float(mm_chain(a, b))  # compile
    t0 = time.perf_counter()
    float(mm_chain(a, b))
    dt = (time.perf_counter() - t0) / iters
    return 2.0 * dim ** 3 / dt / 1e6


def bench_logreg_fit(n: int = 200_000, d: int = 256, iters: int = 25):
    """Wall-clock of a distributed LR fit (fixed iteration count)."""
    from cycloneml_tpu import CycloneConf, CycloneContext
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression

    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "bench"))
    rng = np.random.RandomState(0)
    x = rng.randn(n, d).astype(np.float32)
    true = rng.randn(d)
    y = (x @ true + rng.randn(n) > 0).astype(np.float32)
    frame = MLFrame(ctx, {"features": x, "label": y})
    lr = LogisticRegression(maxIter=iters, regParam=0.01, tol=0.0)
    t0 = time.perf_counter()
    model = lr.fit(frame)
    dt = time.perf_counter() - t0
    its = model.summary.total_iterations
    return dt, its, n * d


def main() -> None:
    gemm_mops = bench_gemm()
    try:
        fit_s, fit_iters, nd = bench_logreg_fit()
        print(f"info: LogisticRegression.fit n*d={nd} took {fit_s:.2f}s "
              f"({fit_iters} iterations, {fit_s / max(fit_iters,1) * 1e3:.1f} ms/iter)",
              file=sys.stderr)
    except Exception as e:  # bench must still emit its line
        print(f"info: logreg bench failed: {e}", file=sys.stderr)
    print(json.dumps({
        "metric": "device_gemm_f32_throughput",
        "value": round(gemm_mops, 1),
        "unit": "M ops/s",
        "vs_baseline": round(gemm_mops / REF_DGEMM_MOPS, 2),
    }))


if __name__ == "__main__":
    main()
