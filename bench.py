"""Benchmark driver — prints ONE JSON line.

Headline metric: END-TO-END ``LogisticRegression.fit`` sustained aggregator
throughput (the north-star path, BASELINE.json parity condition is fit
wall-clock). Each loss/grad evaluation does 4·n·d flops (forward margin
matmul + transpose-matmul gradient — ref BinaryLogisticBlockAggregator
gemv:97/:130); we report achieved M ops/s over the whole fit wall-clock,
including dispatch, line search, optimizer state updates and readbacks.

``vs_baseline`` scores that end-to-end rate against the reference's best
COMMITTED kernel rate: dgemm[N,N] hand-optimized-java = 2409.7 M ops/s
(ref: mllib-local/benchmarks/BLASBenchmark-results.txt:158-169). That is the
reference's compute-bound upper bound — its real fit pays Spark job dispatch,
RPC and shuffle on top of the kernel, so beating its *kernel* rate end-to-end
is a strictly conservative comparison (no end-to-end MLlib training numbers
are committed in the reference, see BASELINE.md).

Secondary (stderr): raw device GEMM throughput and fit latency breakdown.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

REF_DGEMM_MOPS = 2409.7  # BLASBenchmark-results.txt:158-169 (java best)


def bench_gemm(dim: int = 2048, iters: int = 400) -> float:
    """Sustained f32-accumulate GEMM M ops/s on device (secondary metric).

    A data-dependent scan chain with a scalar readback: per-call dispatch
    latency (~70 ms through the TPU relay) is amortised over ``iters``
    sequential matmuls and the host transfer forces real completion —
    ``block_until_ready`` alone under-measures. Precision.HIGHEST keeps the
    comparison against the reference's f64 JVM dgemm conservative.
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(dim, dim), dtype=jnp.float32)
    b = jnp.asarray(rng.randn(dim, dim), dtype=jnp.float32)

    @jax.jit
    def mm_chain(a, b):
        def body(carry, _):
            a, b = carry
            c = jnp.dot(a, b, precision=jax.lax.Precision.HIGHEST)
            return (c * (1.0 / dim), b), None
        (a_out, _), _ = jax.lax.scan(body, (a, b), None, length=iters)
        return jnp.sum(a_out)

    float(mm_chain(a, b))  # compile
    t0 = time.perf_counter()
    float(mm_chain(a, b))
    dt = (time.perf_counter() - t0) / iters
    return 2.0 * dim ** 3 / dt / 1e6


def bench_logreg_fit(n: int = 1_000_000, d: int = 512, iters: int = 25):
    """End-to-end distributed LR fit (fixed iteration budget).

    Returns (wall_s, iterations, evals, dispatches, n, d). A first fit at the
    SAME shapes warms the XLA compile cache (and the relay), so the timed
    second fit measures steady-state training — data placement included,
    compilation excluded, matching how the reference's training benchmarks
    time warmed persisted-input fits.
    """
    from cycloneml_tpu import CycloneConf, CycloneContext
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression

    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "bench"))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, d), dtype=np.float32)
    true = rng.standard_normal(d)
    y = (x @ true + rng.standard_normal(n) > 0).astype(np.float32)
    frame = MLFrame(ctx, {"features": x, "label": y})
    lr = LogisticRegression(maxIter=iters, regParam=0.01, tol=0.0)
    t0 = time.perf_counter()
    lr.fit(frame)
    warm_s = time.perf_counter() - t0
    print(f"info: warm-up fit (compiles + relay warmup) took {warm_s:.2f}s",
          file=sys.stderr)
    t0 = time.perf_counter()
    model = lr.fit(frame)
    dt = time.perf_counter() - t0
    its = model.summary.total_iterations
    evals = getattr(model.summary, "total_evals", None)
    dispatches = getattr(model.summary, "total_dispatches", None)
    return dt, its, evals, dispatches, n, d


def main() -> None:
    err = None
    try:
        fit_s, its, evals, dispatches, n, d = bench_logreg_fit()
    except Exception as e:  # bench must still emit its line
        err = e
        fit_s = None
    try:
        gemm_mops = bench_gemm()
        print(f"info: device_gemm_f32 {gemm_mops:.1f} M ops/s "
              f"({gemm_mops / REF_DGEMM_MOPS:.0f}x ref java dgemm)",
              file=sys.stderr)
    except Exception as e:
        gemm_mops = None
        print(f"info: gemm bench failed: {e}", file=sys.stderr)

    if fit_s is not None:
        evals_n = evals if evals else its  # conservative if not exposed
        mops = 4.0 * n * d * evals_n / fit_s / 1e6
        print(f"info: LogisticRegression.fit n={n} d={d} took {fit_s:.2f}s: "
              f"{its} iterations ({fit_s / max(its, 1) * 1e3:.1f} ms/iter), "
              f"{evals_n} loss/grad evals, {dispatches} device dispatches",
              file=sys.stderr)
        print(json.dumps({
            "metric": "logreg_fit_e2e_throughput",
            "value": round(mops, 1),
            "unit": "M ops/s",
            "vs_baseline": round(mops / REF_DGEMM_MOPS, 2),
        }))
    elif gemm_mops is not None:
        print(f"info: logreg bench failed: {err}", file=sys.stderr)
        print(json.dumps({
            "metric": "device_gemm_f32_throughput",
            "value": round(gemm_mops, 1),
            "unit": "M ops/s",
            "vs_baseline": round(gemm_mops / REF_DGEMM_MOPS, 2),
        }))
    else:
        # both benches errored: say so instead of faking a 0.0 measurement
        print(json.dumps({
            "metric": "bench_error",
            "value": 0.0,
            "unit": "error",
            "vs_baseline": 0.0,
        }))


if __name__ == "__main__":
    main()
