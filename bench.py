"""Benchmark driver — prints ONE JSON line.

Headline metric: END-TO-END ``LogisticRegression.fit`` sustained aggregator
throughput (the north-star path, BASELINE.json parity condition is fit
wall-clock). Each loss/grad evaluation does 4·n·d flops (forward margin
matmul + transpose-matmul gradient — ref BinaryLogisticBlockAggregator
gemv:97/:130); we report achieved M ops/s over the whole fit wall-clock,
including dispatch, line search, optimizer state updates and readbacks.

``vs_baseline`` scores that end-to-end rate against the reference's best
COMMITTED kernel rate: dgemm[N,N] hand-optimized-java = 2409.7 M ops/s
(ref: mllib-local/benchmarks/BLASBenchmark-results.txt:158-169). That is the
reference's compute-bound upper bound — its real fit pays Spark job dispatch,
RPC and shuffle on top of the kernel, so beating its *kernel* rate end-to-end
is a strictly conservative comparison (no end-to-end MLlib training numbers
are committed in the reference, see BASELINE.md).

Secondary (stderr): raw device GEMM throughput and fit latency breakdown.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REF_DGEMM_MOPS = 2409.7  # BLASBenchmark-results.txt:158-169 (java best)


def device_peaks():
    """(matmul peak flop/s, HBM bytes/s) per device — the roofline table
    lives in observe/costs.py (one table for bench, FitProfile and docs;
    None/None on backends with no published figure, e.g. CPU test runs)."""
    from cycloneml_tpu.observe import costs
    return costs.backend_peaks()


def bench_meta():
    """The BENCH json ``meta`` block: run identity for the regression
    sentinel's history ledger (observe/regress.py). Deliberately NO
    wall-clock field — the gated path must stay byte-deterministic for
    a given (env, git) state, so ordering comes from the caller-supplied
    logical timestamp (BENCH_T_LOGICAL), not a clock read."""
    sha = os.environ.get("BENCH_GIT_SHA")
    if sha is None:
        try:
            import subprocess
            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip()
        except Exception:
            sha = ""
    t_logical = int(os.environ.get("BENCH_T_LOGICAL", "0"))
    run_id = os.environ.get("BENCH_RUN_ID") or f"{sha or 'local'}-t{t_logical}"
    return {"schema_version": 1, "run_id": run_id, "git_sha": sha,
            "t_logical": t_logical}


def hardware_meta():
    """The BENCH json ``hardware`` block: backend, device count, dtype
    tier, roofline peaks and live-telemetry availability — the denominator
    context that makes the perf trajectory utilization-denominated."""
    import jax
    from cycloneml_tpu.dataset.instance import compute_dtype, data_dtype
    from cycloneml_tpu.observe import costs
    dev = jax.devices()[0]
    peak_flops, peak_bw = costs.backend_peaks()
    return {
        "backend": dev.platform,
        "device_kind": dev.device_kind,
        "device_count": jax.device_count(),
        # the two precision tiers: accumulator (optimizer state, psums)
        # and data (what a materialized X is stored as — bf16 by default)
        "dtype": str(np.dtype(compute_dtype())),
        "data_dtype": str(np.dtype(data_dtype())),
        # the second rung: what an fp8-capable fit's X resolves to under
        # the live conf (== data_dtype unless cyclone.data.dtype is
        # auto8/float8)
        "data_dtype_fp8": str(np.dtype(data_dtype(None, fp8_capable=True))),
        "peak_flops_per_device": peak_flops,
        "peak_hbm_bytes_per_s": peak_bw,
        "memory_stats_available": costs.memory_stats_available(),
    }


def profile_cost_fields(profile) -> dict:
    """flops / hbm_peak_bytes / achieved_flops for a benchmark's BENCH
    json block, read from the SAME observe/costs.py rollup the FitProfile
    carries — no second harvesting path. ``profile`` is a FitProfile dict
    (or FitProfile); None values mean the backend reported nothing."""
    if hasattr(profile, "to_dict"):
        profile = profile.to_dict()
    profile = profile or {}
    return {
        "flops": profile.get("total_flops"),
        "hbm_peak_bytes": profile.get("hbm_peak_bytes"),
        "achieved_flops": profile.get("achieved_flops"),
        "arithmetic_intensity": profile.get("arithmetic_intensity"),
        "roofline_fraction": profile.get("roofline_fraction"),
    }


def bench_gemm(dim: int = 2048, iters: int = 400) -> float:
    """Sustained f32-accumulate GEMM M ops/s on device (secondary metric).

    A data-dependent scan chain with a scalar readback: per-call dispatch
    latency (~70 ms through the TPU relay) is amortised over ``iters``
    sequential matmuls and the host transfer forces real completion —
    ``block_until_ready`` alone under-measures. Precision.HIGHEST keeps the
    comparison against the reference's f64 JVM dgemm conservative.
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(dim, dim), dtype=jnp.float32)
    b = jnp.asarray(rng.randn(dim, dim), dtype=jnp.float32)

    @jax.jit
    def mm_chain(a, b):
        def body(carry, _):
            a, b = carry
            c = jnp.dot(a, b, precision=jax.lax.Precision.HIGHEST)
            return (c * (1.0 / dim), b), None
        (a_out, _), _ = jax.lax.scan(body, (a, b), None, length=iters)
        return jnp.sum(a_out)

    float(mm_chain(a, b))  # compile
    t0 = time.perf_counter()
    float(mm_chain(a, b))
    dt = (time.perf_counter() - t0) / iters
    return 2.0 * dim ** 3 / dt / 1e6


def bench_logreg_fit(n: int | None = None, d: int | None = None,
                     iters: int = 25):
    """End-to-end distributed LR fit (fixed iteration budget).

    Returns (wall_s, iterations, evals, dispatches, n, d). The dataset is
    generated ON DEVICE (``RandomDatasets.classification``) — shipping 4+ GB
    of synthetic features through the TPU relay at ~5 MB/s would bench the
    tunnel, not the framework; the reference's training benchmarks likewise
    time warmed fits with inputs already persisted on executors. A first fit
    at the SAME shapes warms the XLA compile cache, so the timed second fit
    measures steady-state training — data placement included, compilation
    excluded.

    Default shape n=2M × d=1280: one loss/grad eval streams the feature
    block ONCE at the data tier's width — 5.1 GB at the default bf16 tier
    (10.2 GB with cyclone.data.dtype=float32) — and
    ``usePallasKernels=auto`` makes the fused single-pass Pallas kernel
    the sweep (margin + loss + gradient in one VMEM-resident row pass,
    storage-width reads, fp32 accumulation, Kahan-compensated grid; see
    benchmarks/PALLAS_AB.md) with standardization folded into the read —
    so the fit is HBM-bound, the honest ceiling for a generalized-linear
    sweep on any hardware. No standardized copy exists
    (r4: binary_logistic_scaled), so X itself is the working set and n can
    fill one chip's 16 GB HBM twice over at bf16.
    """
    from cycloneml_tpu import CycloneConf, CycloneContext
    from cycloneml_tpu.dataset.random import generate_classification
    from cycloneml_tpu.ml.classification import LogisticRegression

    n = n or int(os.environ.get("BENCH_N", 2_000_000))
    d = d or int(os.environ.get("BENCH_D", 1280))
    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "bench")
        # whole 25-iteration budget in ONE device dispatch
        .set("cyclone.ml.lbfgs.deviceChunk", str(iters + 8))
        # trace the WARM-UP fit only: its FitProfile attributes the
        # trace/compile phase; tracing is disabled before the timed trials
        .set("cyclone.trace.enabled", "true"))
    t0 = time.perf_counter()
    ds = generate_classification(ctx, n, d, seed=0)
    gen_s = time.perf_counter() - t0
    print(f"info: on-device data generation n={n} d={d} took {gen_s:.2f}s",
          file=sys.stderr)

    # measured streaming ceiling: the fastest any kernel can touch X on
    # THIS device (a pure jnp.sum sweep). Paper HBM bandwidth is not
    # reachable here — report the fit against both.
    import jax
    import jax.numpy as jnp
    sum_fn = jax.jit(lambda x: jnp.sum(x))
    jax.block_until_ready(sum_fn(ds.x))
    t0 = time.perf_counter()
    for _ in range(4):
        r = sum_fn(ds.x)
    jax.block_until_ready(r)
    # bytes at the DATA tier's width (bf16 X streams 2 bytes/element)
    x_item = np.dtype(str(ds.x.dtype)).itemsize
    ceiling_bw = n * d * x_item * 4 / (time.perf_counter() - t0)
    print(f"info: measured streaming ceiling (jit sum over X): "
          f"{ceiling_bw / 1e9:.0f} GB/s", file=sys.stderr)

    # bytes-accessed ground truth for ONE optimizer sweep at the live data
    # tier (observe/costs.py rollup — the sweep-byte reduction is a
    # first-class BENCH metric per PR). Lower-only: XLA analyzes the jnp
    # aggregator program at the dataset's dtypes, nothing executes.
    import jax.numpy as jnp
    from cycloneml_tpu.dataset.instance import compute_dtype
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.observe import costs
    adt = compute_dtype()
    sweep = costs.sweep_cost(
        ds.tree_aggregate_fn(aggregators.binary_logistic_scaled(d, True)),
        jnp.ones(d, adt), jnp.zeros(d, adt), jnp.zeros(d + 1, adt),
        name="bench.sweep")
    bytes_per_sweep = sweep.bytes_accessed_total
    data_dtype = str(ds.x.dtype)
    if bytes_per_sweep:
        print(f"info: bytes_per_sweep={bytes_per_sweep / 1e9:.3f} GB at "
              f"data_dtype={data_dtype} (X alone is "
              f"{n * d * np.dtype(data_dtype).itemsize / 1e9:.3f} GB)",
              file=sys.stderr)
    # per-tier sweep bytes at a small PROBE shape (lower-only; building
    # three full-size datasets just to lower them would dwarf the bench):
    # the ratios are shape-stable once X dominates the (n,)-temporaries,
    # which d>=256 guarantees — the same ground truth `make bench-bytes`
    # gates on
    bytes_by_tier = {}
    try:
        from cycloneml_tpu.dataset.dataset import InstanceDataset
        from cycloneml_tpu.dataset.instance import data_dtype as _dd
        rngp = np.random.RandomState(0)
        n_probe, d_probe = 4096, max(min(d, 256), 128)
        xp = rngp.randn(n_probe, d_probe)
        yp = (rngp.rand(n_probe) > 0.5).astype(np.float64)
        from cycloneml_tpu.conf import DATA_DTYPE
        saved_tier = str(ctx.conf.get(DATA_DTYPE))
        try:
            for tier in ("float32", "bfloat16", "float8"):
                ctx.conf.set("cyclone.data.dtype", tier)
                dsp = InstanceDataset.from_numpy(
                    ctx, xp, yp, dtype=_dd(ctx.conf, fp8_capable=True))
                c = costs.sweep_cost(
                    dsp.tree_aggregate_fn(
                        aggregators.binary_logistic_scaled(d_probe, True)),
                    jnp.ones(d_probe, adt), jnp.zeros(d_probe, adt),
                    jnp.zeros(d_probe + 1, adt), name=f"bench.sweep.{tier}")
                if c.bytes_accessed_total:
                    bytes_by_tier[tier] = c.bytes_accessed_total
        finally:
            # a mid-loop failure must not leave the rest of the BENCH
            # run pinned to a probe tier
            ctx.conf.set("cyclone.data.dtype", saved_tier)
        if bytes_by_tier.get("float32"):
            ratios = {t: round(v / bytes_by_tier["float32"], 4)
                      for t, v in bytes_by_tier.items()}
            print(f"info: per-tier sweep bytes (probe n={n_probe} "
                  f"d={d_probe}): {ratios}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the probe must not fail BENCH
        print(f"info: per-tier sweep probe failed: {e}", file=sys.stderr)

    lr = LogisticRegression(maxIter=iters, regParam=0.01, tol=0.0)
    t0 = time.perf_counter()
    lr.fit(ds)
    warm_s = time.perf_counter() - t0
    print(f"info: warm-up fit (compiles + relay warmup) took {warm_s:.2f}s",
          file=sys.stderr)
    # per-fit profile of the warm-up fit: how much of warm_s was staging
    # (trace + XLA compile) vs dispatch vs readback
    from cycloneml_tpu.observe import tracing as _tracing
    ctx.listener_bus.wait_until_empty()
    warm_profile = ctx.fit_profile() or {}
    _tracing.disable()  # timed trials below run with tracing off
    # >=3 timed trials, MEDIAN reported: the relay shows ~15% run-to-run
    # spread, so a single-trial headline is not quotable (r4 verdict)
    trials = max(3, int(os.environ.get("BENCH_TRIALS", 3)))
    times = []
    model = None
    for _ in range(trials):
        t0 = time.perf_counter()
        model = lr.fit(ds)
        times.append(time.perf_counter() - t0)
    import statistics
    times.sort()
    dt = statistics.median(times)
    spread = (times[-1] - times[0]) / dt * 100
    print(f"info: {trials} timed trials: median {dt:.3f}s, "
          f"min {times[0]:.3f}s, max {times[-1]:.3f}s "
          f"(spread {spread:.0f}% of median)", file=sys.stderr)
    its = model.summary.total_iterations
    evals = getattr(model.summary, "total_evals", None)
    dispatches = getattr(model.summary, "total_dispatches", None)
    phases = {
        "warm_fit_s": round(warm_s, 3),
        "compile_s": round(warm_profile.get("compile_seconds", 0.0), 3),
        "compile_count": warm_profile.get("compile_count", 0),
        "cache_hits": warm_profile.get("cache_hits", 0),
        "cache_misses": warm_profile.get("cache_misses", 0),
        "steady_fit_s": round(dt, 3),
        "steady_per_iter_ms": round(dt / max(its, 1) * 1e3, 2),
        "transfer_s": round(warm_profile.get("transfer_seconds", 0.0), 4),
        "transfer_bytes": warm_profile.get("transfer_bytes", 0),
        "bytes_per_sweep": bytes_per_sweep,
        "data_dtype": data_dtype,
        # per-tier ground truth at the probe shape (f32/bf16/fp8) — the
        # storage-rung trajectory in one dict
        "bytes_per_sweep_by_tier": bytes_by_tier,
    }
    phases.update(profile_cost_fields(warm_profile))
    print(f"info: phase breakdown: warm fit {phases['warm_fit_s']}s "
          f"(compile {phases['compile_s']}s over "
          f"{phases['compile_count']} program(s), program cache "
          f"{phases['cache_hits']} hits / {phases['cache_misses']} misses) "
          f"vs steady-state {phases['steady_fit_s']}s "
          f"({phases['steady_per_iter_ms']} ms/iter)", file=sys.stderr)
    return dt, its, evals, dispatches, n, d, ceiling_bw, phases


def bench_ovr_stacked(n: int | None = None, d: int | None = None,
                      k: int | None = None, iters: int = 100):
    """Multi-class OneVsRest: stacked (vmapped model-axis, ONE SPMD
    program) vs the serialized PR-2 path (K back-to-back binary fits).

    Reports models-per-compile (the compile-amortization the stacked
    engine buys: K models share one optimizer-step compile) and the
    end-to-end stacked-vs-serial speedup. Both paths run ``tol=0`` with a
    budget generous enough to reach the per-model fixed point, so the
    comparison is step-aligned AND the coefficient agreement is a
    fixed-point comparison (acceptance: ≤ 1e-5; a mid-descent cutoff would
    instead measure L-BFGS trajectory sensitivity to last-ulp noise).
    Note the serialized path also re-places X once per class (each
    relabeled sub-frame carries its own device cache) — cost the shared
    design matrix of the stacked path simply does not have.
    """
    from cycloneml_tpu import CycloneConf, CycloneContext
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression, OneVsRest
    from cycloneml_tpu.observe import tracing as _tracing

    # modest by default: the serialized path re-places X once per class per
    # fit (each relabeled sub-frame carries its own device cache), and
    # through a TPU relay that transfer should bound, not dominate, the run
    n = n or int(os.environ.get("BENCH_OVR_N", 20_000))
    d = d or int(os.environ.get("BENCH_OVR_D", 64))
    k = k or int(os.environ.get("BENCH_OVR_K", 8))
    iters = int(os.environ.get("BENCH_OVR_ITERS", iters))
    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "bench"))
    rng = np.random.RandomState(7)
    centers = rng.randn(k, d).astype(np.float32) * 3.0
    y = rng.randint(0, k, n).astype(np.float64)
    x = centers[y.astype(int)] + rng.randn(n, d).astype(np.float32)
    frame = MLFrame(ctx, {"features": x, "label": y})
    clf = LogisticRegression(maxIter=iters, regParam=0.01, tol=0.0)

    # warm + traced stacked fit: proves the one-compile-for-K contract
    tracer = _tracing.enable()
    mark = tracer.mark()
    try:
        stacked_model = OneVsRest(classifier=clf, parallelism=k).fit(frame)
        prof = tracer.profile_for(since=mark)
        step_compiles = sum(
            1 for s in tracer.snapshot(mark)
            if s.kind == "compile" and s.name == "lbfgs.stacked_chunk")
    finally:
        # a failed fit must not leave process-global tracing on for the
        # rest of the bench (it would skew every later timed section)
        _tracing.disable()

    trials = max(3, int(os.environ.get("BENCH_TRIALS", 3)))
    import statistics

    def timed(est):
        times = []
        model = None
        for _ in range(trials):
            t0 = time.perf_counter()
            model = est.fit(frame)
            times.append(time.perf_counter() - t0)
        return statistics.median(times), model

    stacked_s, stacked_model = timed(OneVsRest(classifier=clf,
                                               parallelism=k))
    # serialized PR-2 path: parallelism=1 → K back-to-back fits
    serial_est = OneVsRest(classifier=clf, parallelism=1)
    serial_est.fit(frame)  # warm its programs too
    serial_s, serial_model = timed(serial_est)

    coef_diff = max(
        float(np.abs(ms._coef - mr._coef).max())
        for ms, mr in zip(stacked_model.models, serial_model.models))
    # relative agreement: the absolute diff rides the data-tier dtype (f32
    # here accumulates ~1e-5 abs at these coefficient scales; the x64
    # equivalence suite in tests/test_stacked.py pins ~1e-9)
    coef_rel = max(
        float((np.abs(ms._coef - mr._coef)
               / np.maximum(np.abs(mr._coef), 1.0)).max())
        for ms, mr in zip(stacked_model.models, serial_model.models))
    speedup = serial_s / stacked_s if stacked_s > 0 else 0.0
    out = {
        "n": n, "d": d, "n_models": k, "iters": iters,
        "stacked_fit_s": round(stacked_s, 3),
        "serial_fit_s": round(serial_s, 3),
        "ovr_stacked_speedup": round(speedup, 2),
        "optimizer_step_compiles": step_compiles,
        "models_per_compile": round(k / max(step_compiles, 1), 1),
        "profile_n_models": prof.n_models,
        "coef_max_abs_diff": float(coef_diff),
        "coef_max_rel_diff": float(coef_rel),
    }
    out.update(profile_cost_fields(prof))
    print(f"info: OneVsRest n={n} d={d} K={k}: stacked {stacked_s:.2f}s vs "
          f"serialized {serial_s:.2f}s ({speedup:.2f}x), "
          f"{out['models_per_compile']} models/compile "
          f"(profile n_models={prof.n_models}), "
          f"max coef diff {coef_diff:.2e}", file=sys.stderr)
    return out


def bench_trace_overhead(n: int | None = None, d: int | None = None,
                         iters: int = 12):
    """The ``trace_overhead`` BENCH block: the SAME warmed fit timed
    untraced, under the flight-recorder-only ring, and fully traced.

    This pins the "always-on is cheap" claim as a number instead of
    prose: ``flight_overhead_pct`` is the steady-state cost of the
    always-on flight recorder (span ring only — no XLA cost harvest, no
    metrics bridge; the acceptance bar is < 3%), ``traced_overhead_pct``
    is full tracing's (cost harvest + rollups + metrics, expected
    higher). Medians over BENCH_TRIALS fits per mode on one warmed
    program set.
    """
    import statistics

    from cycloneml_tpu import CycloneConf, CycloneContext
    from cycloneml_tpu.dataset.random import generate_classification
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.observe import flight, tracing

    n = n or int(os.environ.get("BENCH_TRACE_N", 200_000))
    d = d or int(os.environ.get("BENCH_TRACE_D", 128))
    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "bench"))
    ds = generate_classification(ctx, n, d, seed=3)
    lr = LogisticRegression(maxIter=iters, regParam=0.01, tol=0.0)
    trials = max(3, int(os.environ.get("BENCH_TRIALS", 3)))

    def timed():
        times = []
        for _ in range(trials):
            t0 = time.perf_counter()
            lr.fit(ds)
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    # warm compiles once; every mode then replays the same programs
    tracing.disable()
    flight.disable()
    lr.fit(ds)
    untraced_s = timed()
    flight.enable()
    try:
        flight_s = timed()
    finally:
        flight.disable()
    # full tracing as a real context runs it: WITH the metrics bridge
    # (per-span timer updates), so the reported overhead is honest
    tracing.enable(registry=ctx.metrics.registry)
    try:
        traced_s = timed()
    finally:
        tracing.disable()

    def pct(x):
        return round((x / untraced_s - 1.0) * 100.0, 2) if untraced_s else None

    out = {
        "n": n, "d": d, "iters": iters, "trials": trials,
        "untraced_s": round(untraced_s, 4),
        "flight_s": round(flight_s, 4),
        "traced_s": round(traced_s, 4),
        "flight_overhead_pct": pct(flight_s),
        "traced_overhead_pct": pct(traced_s),
    }
    print(f"info: trace overhead n={n} d={d}: untraced {untraced_s:.3f}s, "
          f"flight-only {flight_s:.3f}s ({out['flight_overhead_pct']}%), "
          f"traced {traced_s:.3f}s ({out['traced_overhead_pct']}%)",
          file=sys.stderr)
    return out


def bench_usage(n: int | None = None, d: int | None = None,
                iters: int = 12):
    """The ``usage`` BENCH block: the SAME warmed fit timed with usage
    attribution off, enabled-but-unscoped, and enabled-with-a-scope.

    Pins the attribution hot-path discipline as numbers: with the ledger
    off the dispatch path pays ONE module-global read
    (``off_overhead_pct`` vs the pre-change baseline is definitionally ~0
    — they run identical code); ``unscoped_overhead_pct`` adds a
    thread-local peek; ``scoped_overhead_pct`` is the full metering cost
    (two clock reads + one locked ledger add per dispatch; the < 3% bar
    matches the flight recorder's). Also cross-checks the ledger sum
    invariant: the scoped run's per-scope rows must sum to the totals row
    within 1% on every additive field."""
    import statistics

    from cycloneml_tpu import CycloneConf, CycloneContext
    from cycloneml_tpu.dataset.random import generate_classification
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.observe import attribution, flight, tracing

    n = n or int(os.environ.get("BENCH_USAGE_N", 200_000))
    d = d or int(os.environ.get("BENCH_USAGE_D", 128))
    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "bench"))
    ds = generate_classification(ctx, n, d, seed=3)
    lr = LogisticRegression(maxIter=iters, regParam=0.01, tol=0.0)
    trials = max(3, int(os.environ.get("BENCH_TRIALS", 3)))

    def timed(scope_name=None):
        times = []
        for _ in range(trials):
            t0 = time.perf_counter()
            if scope_name is None:
                lr.fit(ds)
            else:
                with attribution.scope(scope_name):
                    lr.fit(ds)
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    # isolate the attribution cost: no tracer, no flight ring
    tracing.disable()
    flight.disable()
    attribution.disable()
    lr.fit(ds)          # warm compiles once; every mode replays
    off_s = timed()
    attribution.enable()
    try:
        unscoped_s = timed()
        scoped_s = timed("bench-usage")
        snap = attribution.active().snapshot()
    finally:
        attribution.disable()

    # sum invariant: per-scope additive fields vs the totals row
    totals = snap.pop(attribution.TOTALS)
    sums_ok = True
    for fld in ("deviceSeconds", "dispatches", "flops", "bytesAccessed",
                "h2dBytes"):
        want = totals.get(fld, 0)
        got = sum(row.get(fld, 0) for row in snap.values())
        if want and abs(got - want) / want > 0.01:
            sums_ok = False
            print(f"info: usage sum invariant VIOLATED on {fld}: "
                  f"scopes sum {got} vs totals {want}", file=sys.stderr)

    def pct(x):
        return round((x / off_s - 1.0) * 100.0, 2) if off_s else None

    out = {
        "n": n, "d": d, "iters": iters, "trials": trials,
        "off_s": round(off_s, 4),
        "unscoped_s": round(unscoped_s, 4),
        "scoped_s": round(scoped_s, 4),
        "unscoped_overhead_pct": pct(unscoped_s),
        "scoped_overhead_pct": pct(scoped_s),
        "sum_invariant_ok": sums_ok,
    }
    print(f"info: usage attribution n={n} d={d}: off {off_s:.3f}s, "
          f"unscoped {unscoped_s:.3f}s ({out['unscoped_overhead_pct']}%), "
          f"scoped {scoped_s:.3f}s ({out['scoped_overhead_pct']}%), "
          f"sums {'ok' if sums_ok else 'VIOLATED'}", file=sys.stderr)
    return out


def _serving_admission(d: int, budget_peaks: float = 4.0) -> dict:
    """Admission capacity under the quantized predict tier: the largest
    gang width whose single-row-bucket program peak fits a fixed HBM
    budget, plain vs quantized — XLA memory-analysis ground truth (the
    same ``observe/costs`` accounting the PR-8 admission path consults).
    The budget is ``budget_peaks`` x the plain K=16 peak, so the two
    counts are directly comparable; peaks grow ~linearly in K, so two
    analyze() calls per mode suffice."""
    import jax

    from cycloneml_tpu.observe import costs
    from cycloneml_tpu.serving.servable import (
        _quantize_rows, stacked_linear_margins,
        stacked_quantized_linear_margins,
    )
    rng = np.random.RandomState(3)
    bucket = 1

    def peak(k: int, quant: bool):
        coefs = rng.randn(k, 1, d)
        icpts = rng.randn(k, 1)
        x0 = np.zeros((bucket, d))
        if quant:
            q = _quantize_rows(coefs, icpts, np.float64)
            c = costs.analyze(jax.jit(stacked_quantized_linear_margins),
                              (*q, x0), name=f"serve.adm.q{k}")
        else:
            c = costs.analyze(jax.jit(stacked_linear_margins),
                              (coefs, icpts, x0), name=f"serve.adm.p{k}")
        return c.peak_bytes

    def admitted(quant: bool, budget: float) -> int:
        base = peak(1, quant)
        p17 = peak(17, quant)
        if base is None or p17 is None or base > budget:
            return 0
        marginal = max((p17 - base) / 16.0, 1.0)
        return 1 + int((budget - base) // marginal)

    p16 = peak(16, False)
    if not p16:
        return {"admission_available": False}
    budget = budget_peaks * p16
    return {
        "admission_available": True,
        "admission_bucket": bucket,
        "admission_budget_bytes": int(budget),
        "admitted_models_plain": admitted(False, budget),
        "admitted_models_quantized": admitted(True, budget),
    }


def bench_elastic(n: int | None = None, d: int | None = None):
    """The ``elastic`` BENCH block: TIME-TO-RESUME after a mesh-shape
    change, reshard-in-place vs checkpoint round-trip (ISSUE 15).

    One seeded fit runs to completion with optimizer checkpoints on disk;
    then the SAME full→half transition is timed two ways, trials×
    medians:

    - **reshard**: host-bounce the live optimizer state, apply a
      CapacityEvent through ``MeshSupervisor.reshape`` (in-memory dataset
      migration + program-cache clear + rebuild), rebuild the loss from
      LIVE host data, and run the first post-transition loss/grad eval.
    - **checkpoint**: ``MeshSupervisor.recover`` (the crash path: rebuild
      over survivors, dataset restored from its npz checkpoint), restore
      the newest VERIFIABLE optimizer checkpoint (read + sha256 verify),
      and run the same first eval.

    Both legs pay the new mesh's program compile; the difference is pure
    state-motion cost — memory vs disk+hash. The checkpoint leg runs
    SECOND each trial, giving it any warm-page-cache advantage, so the
    ``make bench-elastic`` gate (reshard strictly faster) is
    conservative. Returns None (with a reason on stderr) on single-device
    meshes, where no half-shape exists.
    """
    import statistics
    import tempfile

    import jax

    from cycloneml_tpu import CycloneConf, CycloneContext
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.elastic import CapacityEvent, host_bounce_state
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.ml.optim.lbfgs import LBFGS, OptimState
    from cycloneml_tpu.ml.optim.loss import DistributedLossFunction
    from cycloneml_tpu.parallel.resilience import MeshSupervisor
    from cycloneml_tpu.util.checkpoint import TrainingCheckpointer
    from cycloneml_tpu.parallel.resilience import train_with_checkpoints

    n = n or int(os.environ.get("BENCH_ELASTIC_N", 400_000))
    d = d or int(os.environ.get("BENCH_ELASTIC_D", 64))
    trials = max(3, int(os.environ.get("BENCH_TRIALS", 3)))
    n_dev = len(jax.local_devices())
    if n_dev < 2:
        print("info: elastic bench skipped: needs >= 2 local devices "
              "(run `make bench-elastic` for the 8-device CPU smoke)",
              file=sys.stderr)
        return None
    full = f"local-mesh[{n_dev}]"
    half = f"local-mesh[{n_dev // 2}]"
    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "bench"))
    rng = np.random.RandomState(0)
    x = rng.randn(n, d)
    y = (x @ rng.randn(d) > 0).astype(np.float64)

    with tempfile.TemporaryDirectory() as tmp:
        ctx.rebuild_mesh(full)
        # the LIVE dataset is PERSISTED (registered with the storage
        # manager): reshape() migrates its already-blockified device
        # blocks to the host tier and re-places them on the new mesh —
        # the decommission block-migration hop, no re-ingest, no disk
        ds_live = InstanceDataset.from_numpy(ctx, x, y).persist()

        def live_loss(_rt=None):
            return DistributedLossFunction(
                ds_live, aggregators.binary_logistic(d, fit_intercept=False))

        data_ck = os.path.join(tmp, "data")
        ds_live.checkpoint(data_ck)
        opt_ck = TrainingCheckpointer(os.path.join(tmp, "opt"))
        state = train_with_checkpoints(
            LBFGS(max_iter=12, tol=1e-12), live_loss(), np.zeros(d),
            opt_ck, interval=2)

        sup = MeshSupervisor(ctx, on_reshard=live_loss,
                             max_reshapes=trials + 1)
        sup_ck = MeshSupervisor(
            ctx, worker_devices={"h0": n_dev - n_dev // 2,
                                 "h1": n_dev // 2},
            on_rebuild=lambda rt: DistributedLossFunction(
                InstanceDataset.restore(ctx, data_ck),
                aggregators.binary_logistic(d, fit_intercept=False)),
            max_rebuilds=trials + 1)

        reshard_s, checkpoint_s = [], []
        try:
            for _ in range(trials):
                t0 = time.perf_counter()
                st = host_bounce_state(state)
                loss_a = sup.reshape(CapacityEvent(master=half,
                                                   reason="bench"))
                loss_a(st.x)
                reshard_s.append(time.perf_counter() - t0)
                ctx.rebuild_mesh(full)

                t0 = time.perf_counter()
                loss_b = sup_ck.recover("bench transition",
                                        lost_workers=["h0"])
                step, tree = opt_ck.restore_newest_verifiable()
                st2 = OptimState.from_pytree(tree)
                loss_b(st2.x)
                checkpoint_s.append(time.perf_counter() - t0)
                ctx.rebuild_mesh(full)
        finally:
            ds_live.unpersist()
            ctx.rebuild_mesh()   # back to the conf master

    out = {
        "reshard_resume_s": round(statistics.median(reshard_s), 4),
        "checkpoint_resume_s": round(statistics.median(checkpoint_s), 4),
        "resume_speedup": round(statistics.median(checkpoint_s)
                                / max(statistics.median(reshard_s), 1e-9),
                                2),
        "n": n, "d": d, "trials": trials,
        "devices_from": n_dev, "devices_to": n_dev // 2,
    }
    print(f"info: elastic time-to-resume {full}->{half}: reshard-in-place "
          f"{out['reshard_resume_s'] * 1e3:.0f} ms vs checkpoint "
          f"round-trip {out['checkpoint_resume_s'] * 1e3:.0f} ms "
          f"({out['resume_speedup']}x)", file=sys.stderr)
    return out


def bench_serving(d: int | None = None, n_requests: int | None = None,
                  n_threads: int | None = None):
    """The ``serving`` BENCH block: two fitted models behind the model
    server, concurrent mixed-size requests through the micro-batcher.

    Reports what the serving SLO cares about: p50/p99 request latency
    (milliseconds), sustained requests/s and rows/s, the batch-size
    distribution the window actually achieved (coalescing evidence), and
    the compile ledger — compiles must equal the bucket count, all paid at
    registration, zero during the request storm.
    """
    import threading

    from cycloneml_tpu import CycloneConf, CycloneContext
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.serving import ModelServer, bucket_sizes

    d = d or int(os.environ.get("BENCH_SERVE_D", 64))
    n_requests = n_requests or int(os.environ.get("BENCH_SERVE_REQS", 400))
    n_threads = n_threads or int(os.environ.get("BENCH_SERVE_THREADS", 8))
    max_batch = int(os.environ.get("BENCH_SERVE_MAXBATCH", 64))
    window_ms = float(os.environ.get("BENCH_SERVE_WINDOW_MS", 2.0))
    ctx = CycloneContext.get_or_create(
        CycloneConf().set("cyclone.app.name", "bench"))
    rng = np.random.RandomState(11)
    n_fit = 4096
    x = rng.randn(n_fit, d).astype(np.float32)
    w = rng.randn(d)
    y = (x @ w + 0.3 * rng.randn(n_fit) > 0).astype(np.float64)
    frame = MLFrame(ctx, {"features": x, "label": y})
    model_a = LogisticRegression(maxIter=15, regParam=0.01).fit(frame)
    model_b = LogisticRegression(maxIter=15, regParam=0.1).fit(frame)

    sizes = [1, 2, 3, 5, 8, 13]
    reqs = [(("a", "b")[i % 2], rng.randn(sizes[i % len(sizes)], d))
            for i in range(n_requests)]
    errors: list = []

    def storm(srv):
        it = iter(reqs)
        it_lock = threading.Lock()

        def client():
            while True:
                with it_lock:
                    job = next(it, None)
                if job is None:
                    return
                try:
                    srv.predict(job[0], job[1])
                except Exception as e:  # noqa: BLE001 — reported below
                    errors.append(repr(e))

        threads = [threading.Thread(target=client)
                   for _ in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    srv = ModelServer(ctx=ctx, max_batch=max_batch, window_ms=window_ms)
    srv.register("a", model_a)
    srv.register("b", model_b)
    wall = storm(srv)
    stats = srv.stats()
    srv.stop()

    # the QUANTIZED tier's leg: same models, same storm, fp8 coefficient
    # codes + per-row scales in the predict programs
    # (cyclone.serving.quantize) — p99 must hold while the per-bucket
    # peaks (and so the HBM admission budget's model capacity) shrink
    srv_q = ModelServer(ctx=ctx, max_batch=max_batch, window_ms=window_ms,
                        quantize=True)
    srv_q.register("a", model_a)
    srv_q.register("b", model_b)
    wall_q = storm(srv_q)
    stats_q = srv_q.stats()
    srv_q.stop()
    lat_q = {}
    for m in stats_q["models"].values():
        for k2, v in m["latencyMs"].items():
            lat_q[k2] = max(lat_q.get(k2, 0.0), v)
    quantized = {
        "requests_per_s": round(
            stats_q["totals"]["requests"] / wall_q, 1),
        "p50_ms": round(lat_q.get("p50", 0.0), 3),
        "p99_ms": round(lat_q.get("p99", 0.0), 3),
        "compiles": stats_q["totals"]["compiles"],
    }
    quantized.update(_serving_admission(d))
    totals = stats["totals"]
    lat_ms = {}
    for m in stats["models"].values():
        for k2, v in m["latencyMs"].items():
            lat_ms[k2] = max(lat_ms.get(k2, 0.0), v)  # worst model
    batch_rows = srv.registry.histogram("serving.batchRows").snapshot()
    batch_reqs = srv.registry.histogram("serving.batchRequests").snapshot()
    out = {
        "requests": totals["requests"],
        "rows": totals["rows"],
        "wall_seconds": round(wall, 3),
        "requests_per_s": round(totals["requests"] / wall, 1),
        "rows_per_s": round(totals["rows"] / wall, 1),
        "p50_ms": round(lat_ms.get("p50", 0.0), 3),
        "p99_ms": round(lat_ms.get("p99", 0.0), 3),
        "window_ms": window_ms,
        "batches": totals["batches"],
        "coalesced_requests": totals["coalesced"],
        "batch_rows": {k2: round(v, 2) for k2, v in batch_rows.items()},
        "batch_requests": {k2: round(v, 2) for k2, v in batch_reqs.items()},
        "compiles": totals["compiles"],
        "buckets": len(bucket_sizes(max_batch)),
        "models": totals["models"],
        "shed": totals["shed"],
        "quantized": quantized,
        "errors": errors[:3],
    }
    print(f"info: serving quantized leg: "
          f"{quantized['requests_per_s']} req/s, "
          f"p99 {quantized['p99_ms']:.2f} ms, admitted gang models "
          f"{quantized.get('admitted_models_plain')} plain -> "
          f"{quantized.get('admitted_models_quantized')} quantized "
          f"under the same budget", file=sys.stderr)
    print(f"info: serving {totals['requests']} requests "
          f"({totals['rows']} rows) in {wall:.2f}s: "
          f"{out['requests_per_s']} req/s, p50 {out['p50_ms']:.2f} ms, "
          f"p99 {out['p99_ms']:.2f} ms, {totals['batches']} batches, "
          f"{totals['compiles']} compiles over {out['buckets']} buckets "
          f"x {totals['models']} models", file=sys.stderr)
    return out


def main() -> None:
    err = None
    ceiling_bw = None
    phases = None
    meta = bench_meta()
    try:
        hardware = hardware_meta()
    except Exception as e:
        hardware = None
        print(f"info: hardware meta failed: {e}", file=sys.stderr)
    try:
        (fit_s, its, evals, dispatches, n, d, ceiling_bw,
         phases) = bench_logreg_fit()
    except Exception as e:  # bench must still emit its line
        err = e
        fit_s = None
    ovr = None
    if os.environ.get("BENCH_OVR", "1") != "0":
        try:
            ovr = bench_ovr_stacked()
        except Exception as e:
            print(f"info: ovr stacked bench failed: {e}", file=sys.stderr)
    serving = None
    if os.environ.get("BENCH_SERVING", "1") != "0":
        try:
            serving = bench_serving()
        except Exception as e:
            print(f"info: serving bench failed: {e}", file=sys.stderr)
    trace_overhead = None
    if os.environ.get("BENCH_TRACE_OVERHEAD", "1") != "0":
        try:
            trace_overhead = bench_trace_overhead()
        except Exception as e:
            print(f"info: trace overhead bench failed: {e}", file=sys.stderr)
    usage = None
    if os.environ.get("BENCH_USAGE", "1") != "0":
        try:
            usage = bench_usage()
        except Exception as e:
            print(f"info: usage bench failed: {e}", file=sys.stderr)
    elastic = None
    if os.environ.get("BENCH_ELASTIC", "1") != "0":
        try:
            elastic = bench_elastic()
        except Exception as e:
            print(f"info: elastic bench failed: {e}", file=sys.stderr)
    try:
        gemm_mops = bench_gemm()
        print(f"info: device_gemm_f32 {gemm_mops:.1f} M ops/s "
              f"({gemm_mops / REF_DGEMM_MOPS:.0f}x ref java dgemm)",
              file=sys.stderr)
    except Exception as e:
        gemm_mops = None
        print(f"info: gemm bench failed: {e}", file=sys.stderr)

    if fit_s is not None:
        evals_n = evals if evals else its  # conservative if not exposed
        mops = 4.0 * n * d * evals_n / fit_s / 1e6
        print(f"info: LogisticRegression.fit n={n} d={d} took {fit_s:.2f}s: "
              f"{its} iterations ({fit_s / max(its, 1) * 1e3:.1f} ms/iter), "
              f"{evals_n} loss/grad evals, {dispatches} device dispatches",
              file=sys.stderr)
        peak_flops, peak_bw = device_peaks()
        if peak_flops is None and gemm_mops is not None:
            peak_flops = gemm_mops * 1e6  # measured same-precision GEMM rate
        if peak_flops:
            # MFU of an end-to-end GLM fit. Context: one loss/grad eval is
            # two (n,d) matvecs = 0.5 flop/byte arithmetic intensity, so the
            # op's own roofline is bandwidth, not the MXU — the bandwidth
            # fraction below is the number that says how close the fit runs
            # to the hardware ceiling; MFU is reported because the verdict
            # asked for it, and is inherently small for matvec workloads.
            print(f"info: mfu={mops * 1e6 / peak_flops * 100:.3f}% "
                  f"(end-to-end fit flops vs device matmul peak "
                  f"{peak_flops / 1e12:.0f} Tflop/s)", file=sys.stderr)
        if peak_bw:
            # X is streamed ONCE per eval at the DATA tier's width: the
            # scaled aggregator reads raw blocks and XLA fuses
            # margin+gradient per tile (verified: a standalone eval costs
            # ~a pure jnp.sum sweep of X)
            x_item = np.dtype(phases.get("data_dtype", "float32")).itemsize \
                if phases else 4
            bw = 1.0 * n * d * x_item * evals_n / fit_s
            line = (f"info: hbm_bandwidth={bw / 1e9:.1f} GB/s "
                    f"({bw / peak_bw * 100:.1f}% of {peak_bw / 1e9:.0f} "
                    f"GB/s paper peak")
            if ceiling_bw:
                line += (f"; {bw / ceiling_bw * 100:.0f}% of the "
                         f"{ceiling_bw / 1e9:.0f} GB/s MEASURED streaming "
                         f"ceiling — paper peak is unreachable by any "
                         f"kernel on this device")
            print(line + ")", file=sys.stderr)
        print(json.dumps({
            "metric": "logreg_fit_e2e_throughput",
            "value": round(mops, 1),
            "unit": "M ops/s",
            "vs_baseline": round(mops / REF_DGEMM_MOPS, 2),
            "meta": meta,
            "hardware": hardware,
            "phases": phases,
            "ovr": ovr,
            "serving": serving,
            "trace_overhead": trace_overhead,
            "usage": usage,
            "elastic": elastic,
        }))
    elif gemm_mops is not None:
        print(f"info: logreg bench failed: {err}", file=sys.stderr)
        print(json.dumps({
            "metric": "device_gemm_f32_throughput",
            "value": round(gemm_mops, 1),
            "unit": "M ops/s",
            "vs_baseline": round(gemm_mops / REF_DGEMM_MOPS, 2),
            "meta": meta,
            "hardware": hardware,
            "ovr": ovr,
            "serving": serving,
            "trace_overhead": trace_overhead,
            "usage": usage,
            "elastic": elastic,
        }))
    else:
        # both benches errored: say so instead of faking a 0.0 measurement
        print(json.dumps({
            "metric": "bench_error",
            "value": 0.0,
            "unit": "error",
            "vs_baseline": 0.0,
            "meta": meta,
            "hardware": hardware,
        }))


if __name__ == "__main__":
    main()
