"""RFormula + SQLTransformer tests (ref: RFormulaSuite, SQLTransformerSuite
— the reference's suites assert dummy-coded features against R)."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.feature import RFormula, RFormulaModel, SQLTransformer


@pytest.fixture
def frame(ctx):
    return MLFrame(ctx, {
        "y": np.array([1.0, 0.0, 1.0, 0.0]),
        "a": np.array([1.0, 2.0, 3.0, 4.0]),
        "b": np.array([10.0, 20.0, 30.0, 40.0]),
        "s": np.array(["x", "y", "x", "z"], dtype=object),
    })


def test_rformula_numeric_terms(frame, ctx):
    model = RFormula(formula="y ~ a + b").fit(frame)
    out = model.transform(frame)
    np.testing.assert_allclose(out["features"],
                               np.column_stack([frame["a"], frame["b"]]))
    np.testing.assert_allclose(out["label"], frame["y"])


def test_rformula_dot_and_exclusion(frame, ctx):
    model = RFormula(formula="y ~ . - s").fit(frame)
    out = model.transform(frame)
    assert out["features"].shape == (4, 2)  # a, b; s excluded, y is label


def test_rformula_string_dummy_coding(frame, ctx):
    """String columns one-hot with the LAST category dropped (R dummy
    coding; category order = frequency desc, ties lexicographic)."""
    model = RFormula(formula="y ~ s").fit(frame)
    out = model.transform(frame)
    # counts: x=2, y=1, z=1 → order [x, y, z]; dropped category = z
    feats = out["features"]
    assert feats.shape == (4, 2)
    np.testing.assert_allclose(feats[0], [1.0, 0.0])  # x
    np.testing.assert_allclose(feats[1], [0.0, 1.0])  # y
    np.testing.assert_allclose(feats[3], [0.0, 0.0])  # z (dropped)


def test_rformula_interaction(frame, ctx):
    model = RFormula(formula="y ~ a:b").fit(frame)
    out = model.transform(frame)
    np.testing.assert_allclose(out["features"][:, 0], frame["a"] * frame["b"])


def test_rformula_string_label(ctx):
    frame = MLFrame(ctx, {"cls": np.array(["pos", "neg", "pos"], dtype=object),
                          "v": np.array([1.0, 2.0, 3.0])})
    model = RFormula(formula="cls ~ v").fit(frame)
    out = model.transform(frame)
    # pos is more frequent → index 0
    np.testing.assert_allclose(out["label"], [0.0, 1.0, 0.0])


def test_rformula_persistence(frame, ctx, tmp_path):
    model = RFormula(formula="y ~ a + s").fit(frame)
    path = str(tmp_path / "rf")
    model.save(path)
    back = RFormulaModel.load(path)
    np.testing.assert_allclose(back.transform(frame)["features"],
                               model.transform(frame)["features"])


def test_rformula_rejects_unsupported_operators(frame, ctx):
    with pytest.raises(ValueError, match="unsupported formula"):
        RFormula(formula="y ~ a*b").fit(frame)
    with pytest.raises(ValueError, match="no terms"):
        RFormula(formula="y ~ ").fit(frame)
    # adjacent terms with no operator (typo for a:b / a+b) must also fail
    with pytest.raises(ValueError, match="unsupported formula"):
        RFormula(formula="y ~ a b").fit(frame)


def test_rformula_unseen_category_errors(frame, ctx):
    model = RFormula(formula="y ~ s").fit(frame)
    bad = MLFrame(ctx, {"y": np.array([1.0]),
                        "s": np.array(["never-seen"], dtype=object)})
    with pytest.raises(ValueError, match="unseen at fit time"):
        model.transform(bad)


def test_rformula_nonstring_categories_survive_persistence(ctx, tmp_path):
    """Object columns holding non-str values (ints) must encode identically
    before and after save/load (categories are canonical str labels)."""
    frame = MLFrame(ctx, {"y": np.array([1.0, 0.0, 1.0]),
                          "c": np.array([10, 20, 10], dtype=object)})
    model = RFormula(formula="y ~ c").fit(frame)
    before = model.transform(frame)["features"]
    path = str(tmp_path / "rf")
    model.save(path)
    after = RFormulaModel.load(path).transform(frame)["features"]
    np.testing.assert_allclose(before, after)


def test_sql_transformer_scalar(frame, ctx):
    t = SQLTransformer(statement="SELECT a, b, a + b AS ab FROM __THIS__ "
                                 "WHERE a > 1")
    out = t.transform(frame)
    assert out.columns == ["a", "b", "ab"]
    np.testing.assert_allclose(out["ab"], [22.0, 33.0, 44.0])


def test_sql_transformer_vector_passthrough(ctx):
    frame = MLFrame(ctx, {"features": np.arange(8.0).reshape(4, 2),
                          "v": np.array([1.0, 2.0, 3.0, 4.0])})
    t = SQLTransformer(statement="SELECT features, v * 10 AS v10 "
                                 "FROM __THIS__")
    out = t.transform(frame)
    assert out["features"].shape == (4, 2)  # 2-D column survives projection
    np.testing.assert_allclose(out["v10"], [10.0, 20.0, 30.0, 40.0])
    # aliased vector projections re-stack too
    t2 = SQLTransformer(statement="SELECT features AS f FROM __THIS__")
    assert t2.transform(frame)["f"].shape == (4, 2)
    # filtering away every row keeps the (0, k) vector shape — aliased too
    t3 = SQLTransformer(statement="SELECT features FROM __THIS__ "
                                  "WHERE v > 99")
    assert t3.transform(frame)["features"].shape == (0, 2)
    t4 = SQLTransformer(statement="SELECT features AS f FROM __THIS__ "
                                  "WHERE v > 99")
    assert t4.transform(frame)["f"].shape == (0, 2)


def test_sql_transformer_in_pipeline(ctx, tmp_path):
    """(ref SQLTransformer extends Transformer for exactly this)"""
    from cycloneml_tpu.ml.base import Pipeline, PipelineModel
    from cycloneml_tpu.ml.classification import LogisticRegression
    rng = np.random.RandomState(0)
    frame = MLFrame(ctx, {"a": rng.randn(100), "b": rng.randn(100),
                          "label": (rng.rand(100) > 0.5).astype(float)})
    pipe = Pipeline(stages=[
        SQLTransformer(statement="SELECT a, b, a * b AS ab, label "
                                 "FROM __THIS__"),
        RFormula(formula="label ~ a + b + ab"),
        LogisticRegression(maxIter=5),
    ])
    model = pipe.fit(frame)
    out = model.transform(frame)
    assert out["features"].shape == (100, 3)
    path = str(tmp_path / "pipe")
    model.save(path)
    reloaded = PipelineModel.load(path)
    np.testing.assert_allclose(reloaded.transform(frame)["prediction"],
                               out["prediction"])
