"""Param system tests (ref: ml/param/params.scala semantics)."""

import pytest

from cycloneml_tpu.ml.param import Param, ParamMap, Params, ParamValidators


class Thing(Params):
    def __init__(self, uid=None):
        super().__init__(uid)
        self.maxIter = self._param("maxIter", "max iterations",
                                   ParamValidators.gt_eq(0), default=100)
        self.regParam = self._param("regParam", "regularization",
                                    ParamValidators.gt_eq(0.0), default=0.0)
        self.solver = self._param("solver", "solver name",
                                  ParamValidators.in_array(["auto", "l-bfgs"]),
                                  default="auto")


def test_defaults_and_set():
    t = Thing()
    assert t.get("maxIter") == 100
    t.set("maxIter", 5)
    assert t.get("maxIter") == 5
    assert t.is_set(t.maxIter)
    t.clear(t.maxIter)
    assert t.get("maxIter") == 100


def test_validation():
    t = Thing()
    with pytest.raises(ValueError):
        t.set("maxIter", -1)
    with pytest.raises(ValueError):
        t.set("solver", "bogus")


def test_copy_isolated():
    t = Thing()
    t.set("regParam", 0.5)
    c = t.copy()
    c.set("regParam", 0.9)
    assert t.get("regParam") == 0.5
    assert c.get("regParam") == 0.9
    assert c.uid == t.uid  # copy keeps uid like the reference


def test_extract_param_map_and_extra():
    t = Thing()
    t.set("maxIter", 7)
    extra = ParamMap().put(t.regParam, 0.3)
    m = t.extract_param_map(extra)
    assert m.get(t.maxIter) == 7
    assert m.get(t.regParam) == 0.3
    assert m.get(t.solver) == "auto"


def test_json_roundtrip():
    t = Thing()
    t.set("maxIter", 42).set("solver", "l-bfgs")
    d = t._params_to_json()
    t2 = Thing()
    t2._set_params_from_json(d)
    assert t2.get("maxIter") == 42
    assert t2.get("solver") == "l-bfgs"


def test_explain_params():
    t = Thing()
    s = t.explain_params()
    assert "maxIter" in s and "default: 100" in s
