"""graftlint: per-rule precision tests + the tier-1 self-run gate.

Each rule has a paired should-flag / should-pass fixture under
``tests/fixtures/graftlint/``. Flag fixtures carry a ``# JXnnn`` marker
comment on every line the rule must report — the test asserts the
reported line set EQUALS the marker line set, pinning both recall (no
missed hazard) and precision (no extra noise) per rule.

The gate test runs the analyzer over ``cycloneml_tpu/`` exactly the way
the CLI does and fails on any finding not grandfathered in
``cycloneml_tpu/analysis/baseline.json`` — this is the permanent CI gate
for every future PR. Pure ``ast``: no jax import, no device work.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from cycloneml_tpu.analysis import analyze_paths
from cycloneml_tpu.analysis.baseline import (apply_baseline, load_baseline,
                                             write_baseline)
from cycloneml_tpu.analysis.__main__ import main as graftlint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")
PACKAGE = os.path.join(REPO, "cycloneml_tpu")
BASELINE = os.path.join(PACKAGE, "analysis", "baseline.json")

RULES = ("JX001", "JX002", "JX003", "JX004", "JX005", "JX006", "JX007",
         "JX008", "JX009", "JX010", "JX011", "JX012", "JX013", "JX014",
         "JX015", "JX016", "JX017", "JX018", "JX019", "JX020", "JX021",
         "JX022", "JX023")


def marker_lines(path: str, rule: str):
    """1-based lines carrying a `# <rule>` marker comment."""
    pat = re.compile(rf"#.*{rule}")
    with open(path) as fh:
        return {i for i, line in enumerate(fh, 1) if pat.search(line)}


def findings_for(path: str, rule: str):
    return [f for f in analyze_paths([path]) if f.rule == rule]


@pytest.mark.parametrize("rule", RULES)
def test_rule_flags_exactly_the_marked_lines(rule):
    path = os.path.join(FIXTURES, f"{rule.lower()}_flag.py")
    expected = marker_lines(path, rule)
    assert expected, f"fixture {path} has no marker lines"
    got = {f.line for f in findings_for(path, rule)}
    assert got == expected, (
        f"{rule}: flagged lines {sorted(got)} != marked {sorted(expected)}")


@pytest.mark.parametrize("rule", RULES)
def test_pass_fixture_is_totally_clean(rule):
    # pass fixtures must be clean under the WHOLE pack, not just their
    # own rule — a pass example for one rule must not trip another
    path = os.path.join(FIXTURES, f"{rule.lower()}_pass.py")
    findings = analyze_paths([path])
    assert findings == [], [
        f"{f.rule}@{f.line}: {f.message}" for f in findings]


def test_tracer_aware_instrumentation_is_clean():
    """The observe/ instrumentation pattern — tracer check BEFORE any span
    on a path reachable at trace time, spans + one batched device_get in
    host code — must be clean under the whole rule pack (the PR-3
    tentpole's JX001 contract)."""
    path = os.path.join(FIXTURES, "jx001_tracing_pass.py")
    findings = analyze_paths([path])
    assert findings == [], [
        f"{f.rule}@{f.line}: {f.message}" for f in findings]


def test_doctor_span_walk_is_clean():
    """The performance doctor's shape — a read-only walk over captured
    spans joining evidence with pure host arithmetic — must be clean
    under the whole pack (the observe/diagnose contract)."""
    path = os.path.join(FIXTURES, "jx018_doctor_pass.py")
    findings = analyze_paths([path])
    assert findings == [], [
        f"{f.rule}@{f.line}: {f.message}" for f in findings]


def test_noncanonical_ledger_append_flags_exactly_the_marked_lines():
    """A bench-ledger append whose row order / content depends on hash
    order, unseeded jitter or a wall-clock read is a JX023 determinism
    hazard — the replayed ledger would not be byte-stable."""
    path = os.path.join(FIXTURES, "jx023_ledger_flag.py")
    expected = marker_lines(path, "JX023")
    assert expected, f"fixture {path} has no marker lines"
    got = {f.line for f in findings_for(path, "JX023")}
    assert got == expected, (
        f"JX023: flagged lines {sorted(got)} != marked {sorted(expected)}")


def test_canonical_ledger_append_is_clean():
    """sorted() row order + sort_keys JSON (the observe/regress idiom)
    must pass the whole pack."""
    path = os.path.join(FIXTURES, "jx023_ledger_pass.py")
    findings = analyze_paths([path])
    assert findings == [], [
        f"{f.rule}@{f.line}: {f.message}" for f in findings]


# -- suppressions -----------------------------------------------------------

def test_inline_suppression(tmp_path):
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(jnp.max(x))  # graftlint: disable=JX001\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert analyze_paths([str(p)]) == []


def test_own_line_suppression_covers_next_line(tmp_path):
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    # graftlint: disable=JX001\n"
        "    return float(jnp.max(x))\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert analyze_paths([str(p)]) == []


def test_suppression_is_rule_specific(tmp_path):
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(jnp.max(x))  # graftlint: disable=JX002\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert [f.rule for f in analyze_paths([str(p)])] == ["JX001"]


def test_multiline_statement_suppression(tmp_path):
    """A `# graftlint: disable=RULE` on ANY physical line of a multi-line
    statement covers a finding anchored to the statement's first line."""
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(\n"
        "        jnp.max(\n"
        "            x))  # graftlint: disable=JX001\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert analyze_paths([str(p)]) == []
    # without the directive the same source flags
    p.write_text(src.replace("  # graftlint: disable=JX001", ""))
    assert [f.rule for f in analyze_paths([str(p)])] == ["JX001"]


def test_suppression_covers_statement_beyond_flagged_node(tmp_path):
    """The directive may sit on a physical line of the ENCLOSING
    statement past the flagged node's own extent — the finding anchors
    on the first coercion, the disable on the statement's last line."""
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "def _agg(x, w):\n"
        "    return jnp.max(x), jnp.sum(w)\n"
        "def pulls(x, w):\n"
        "    run = jax.jit(_agg)\n"
        "    out = run(x, w)\n"
        "    total = float(\n"
        "        out[0]\n"
        "    ) + int(\n"
        "        out[1])  # graftlint: disable=JX001\n"
        "    return total\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert analyze_paths([str(p)]) == []
    p.write_text(src.replace("  # graftlint: disable=JX001", ""))
    assert [f.rule for f in analyze_paths([str(p)])] == ["JX001"]


def test_suppression_on_line_above_flagged_expression(tmp_path):
    """The directive may also sit on a physical line of the statement
    ABOVE where the finding anchors — coverage is the whole statement,
    both directions."""
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "def _agg(x, w):\n"
        "    return jnp.max(x), jnp.sum(w)\n"
        "def pulls(x, w):\n"
        "    run = jax.jit(_agg)\n"
        "    out = run(x, w)\n"
        "    total = (1.0 +  # graftlint: disable=JX001\n"
        "             float(out[0]) + int(out[1]))\n"
        "    return total\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert analyze_paths([str(p)]) == []
    p.write_text(src.replace("  # graftlint: disable=JX001", ""))
    assert [f.rule for f in analyze_paths([str(p)])] == ["JX001"]


def test_suppression_inside_branch_body_does_not_cover_the_branch(tmp_path):
    """Statement-extent suppression stops at a compound statement's
    HEADER: a disable buried in the body must not silence a finding on
    the branch itself."""
    src = (
        "import jax\n"
        "def agg(dataset, coef):\n"
        "    if jax.process_index() == 0:\n"
        "        return dataset.tree_aggregate(coef)"
        "  # graftlint: disable=JX010\n"
        "    return None\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    # the finding anchors to the `if` (line 3); the directive sits on the
    # body line and does not reach it
    assert [f.rule for f in analyze_paths([str(p)])] == ["JX010"]


# -- baseline ---------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    flag = os.path.join(FIXTURES, "jx001_flag.py")
    findings = analyze_paths([flag])
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings)
    new, grandfathered = apply_baseline(findings, load_baseline(str(bl)))
    assert new == [] and grandfathered == len(findings)


def test_baseline_does_not_cover_new_occurrences(tmp_path):
    flag = os.path.join(FIXTURES, "jx001_flag.py")
    findings = analyze_paths([flag])
    bl = tmp_path / "baseline.json"
    # grandfather all but one occurrence
    write_baseline(str(bl), findings[:-1])
    new, _ = apply_baseline(findings, load_baseline(str(bl)))
    assert len(new) == 1


# -- the ratchet ------------------------------------------------------------

def test_baseline_ratchet_shrinks_but_never_grows(tmp_path):
    from cycloneml_tpu.analysis.baseline import (BaselineRatchetError,
                                                 check_ratchet)
    flag = os.path.join(FIXTURES, "jx001_flag.py")
    findings = analyze_paths([flag])
    assert len(findings) >= 2
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings[:2])
    assert check_ratchet(str(bl)) == (2, 2)
    # growing past the ratchet refuses ...
    with pytest.raises(BaselineRatchetError):
        write_baseline(str(bl), findings[:2] + findings[:1])
    # ... shrinking is free, and the ratchet FOLLOWS the baseline down
    write_baseline(str(bl), findings[:1])
    assert check_ratchet(str(bl)) == (1, 1)
    # once shrunk, even the old size is a violation
    with pytest.raises(BaselineRatchetError):
        write_baseline(str(bl), findings[:2])
    # the explicit escape hatch allows deliberate debt, and resets
    write_baseline(str(bl), findings[:2], allow_grow=True)
    assert check_ratchet(str(bl)) == (2, 2)


def test_hand_grown_baseline_fails_ratchet_check(tmp_path):
    from cycloneml_tpu.analysis.baseline import (BaselineRatchetError,
                                                 check_ratchet)
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({
        "version": 1, "ratchet": 0,
        "findings": [{"rule": "JX001", "path": "x.py", "function": "f",
                      "count": 1}]}))
    with pytest.raises(BaselineRatchetError):
        check_ratchet(str(bl))


def test_cli_enforces_ratchet_on_baseline_read(tmp_path, capsys):
    """A hand-grown baseline must fail `make lint` itself — the gate the
    ratchet protects — not just the direct check_ratchet tests."""
    flag = os.path.join(FIXTURES, "jx001_flag.py")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({
        "version": 1, "ratchet": 0,
        "findings": [{"rule": "JX001", "path": "x.py", "function": "f",
                      "count": 1}]}))
    assert graftlint_main([flag, "--baseline", str(bl)]) == 2
    assert "ratchet" in capsys.readouterr().err


def test_committed_baseline_is_empty_with_zero_ratchet():
    """The standing contract: all self-run findings are FIXED, none
    baselined — and the ratchet pins it at zero so no future PR can
    quietly grandfather new debt."""
    from cycloneml_tpu.analysis.baseline import check_ratchet
    assert check_ratchet(BASELINE) == (0, 0)


# -- CLI --------------------------------------------------------------------

def test_cli_exit_codes_and_json(tmp_path, capsys):
    flag = os.path.join(FIXTURES, "jx002_flag.py")
    assert graftlint_main([flag, "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["count"] >= 1
    assert all(f["rule"] == "JX002" for f in payload["findings"])

    clean = os.path.join(FIXTURES, "jx002_pass.py")
    assert graftlint_main([clean]) == 0

    assert graftlint_main([]) == 2


def test_cli_rule_subset(capsys):
    flag = os.path.join(FIXTURES, "jx001_flag.py")
    # jx001_flag also has no JX005 hazards; restricting to JX005 is clean
    assert graftlint_main([flag, "--rules", "JX005"]) == 0
    assert graftlint_main([flag, "--rules", "JX001"]) == 1
    capsys.readouterr()


def test_cli_sarif_schema_shape(tmp_path, capsys):
    """SARIF 2.1.0 shape: schema/version headers, a run with tool.driver
    rule metadata for the whole pack, and results whose locations carry
    1-based regions + the graftlint fingerprint."""
    flag = os.path.join(FIXTURES, "jx009_flag.py")
    assert graftlint_main([flag, "--sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert set(RULES) <= rule_ids
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    assert run["results"], "flag fixture must produce results"
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert res["level"] == "error"
        assert res["message"]["text"]
        (loc,) = res["locations"]
        region = loc["physicalLocation"]["region"]
        assert region["startLine"] >= 1
        assert region["startColumn"] >= 1
        assert region["endLine"] >= region["startLine"]
        assert loc["physicalLocation"]["artifactLocation"]["uri"].endswith(
            "jx009_flag.py")
        assert res["partialFingerprints"]["graftlint/v1"].startswith("JX")


def test_cli_changed_mode(tmp_path, capsys):
    """--changed in a scratch git repo: only the touched file is checked,
    but the interprocedural facts still come from the whole set."""
    import shutil
    repo = tmp_path / "repo"
    repo.mkdir()
    helper = (
        "import jax\n"
        "def _update(state, x):\n"
        "    return state * 0.9 + x\n"
        "_step = jax.jit(_update, donate_argnums=(0,))\n"
        "def advance(state, x):\n"
        "    return _step(state, x)\n")
    clean_caller = (
        "from pkg.helper import advance\n"
        "def driver(state, x):\n"
        "    return advance(state, x)\n")
    bad_caller = (
        "from pkg.helper import advance\n"
        "def driver(state, x):\n"
        "    out = advance(state, x)\n"
        "    return out + state.sum()\n")
    pkg = repo / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(helper)
    (pkg / "caller.py").write_text(clean_caller)

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, check=True,
                       capture_output=True)

    git("init", "-q")
    git("-c", "user.email=t@t", "-c", "user.name=t", "add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "x")

    old = os.getcwd()
    os.chdir(repo)
    try:
        # nothing changed -> nothing to lint, exit 0
        assert graftlint_main(["pkg", "--changed", "--no-cache"]) == 0
        assert "0 changed file(s)" in capsys.readouterr().out
        # introduce a use-after-donate in the CALLER only
        (pkg / "caller.py").write_text(bad_caller)
        assert graftlint_main(["pkg", "--changed", "--no-cache",
                               "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert [f["rule"] for f in payload["findings"]] == ["JX009"]
        assert payload["findings"][0]["path"].endswith("caller.py")
        # the cache round-trips: a second run reuses parsed modules
        assert graftlint_main(["pkg", "--changed",
                               "--cache", str(tmp_path / "c.pkl"),
                               "--json"]) == 1
        capsys.readouterr()
        assert graftlint_main(["pkg", "--changed",
                               "--cache", str(tmp_path / "c.pkl"),
                               "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert [f["rule"] for f in payload["findings"]] == ["JX009"]
        # a change OUTSIDE the analyzed roots is not part of this gate:
        # it must not inflate the checked-file set (nor get linted)
        (repo / "scratch.py").write_text("x = 1\n")
        assert graftlint_main(["pkg", "--changed", "--no-cache",
                               "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert {f["path"] for f in payload["findings"]} \
            == {"pkg/caller.py"}
        # cwd-independence: git emits repo-root-relative paths whatever
        # directory the CLI runs from — resolving them against the cwd
        # instead of the git toplevel silently linted NOTHING from a
        # subdirectory
        os.chdir(repo / "pkg")
        assert graftlint_main([str(repo / "pkg"), "--changed",
                               "--no-cache", "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert [f["rule"] for f in payload["findings"]] == ["JX009"]
        # ... and the DEFAULT repo-root-relative root anchors to the git
        # toplevel — from a subdirectory it must find the finding, not
        # print "0 changed file(s)" and exit 0 (a false-green gate)
        assert graftlint_main(["pkg", "--changed", "--no-cache",
                               "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert [f["rule"] for f in payload["findings"]] == ["JX009"]
        # a root that exists nowhere is a usage error, not a silent pass
        assert graftlint_main(["no_such_pkg", "--changed",
                               "--no-cache"]) == 2
        # a BASE that isn't a git ref is a usage error with a real
        # diagnosis — NOT a silent "git unavailable" full-run fallback
        os.chdir(repo)
        assert graftlint_main(["pkg", "--changed", "pkg",
                               "--no-cache"]) == 2
        assert "not a git ref" in capsys.readouterr().err
        assert graftlint_main(["pkg", "--changed", "no-such-ref",
                               "--no-cache"]) == 2
        capsys.readouterr()
        # the check set widens over reverse call edges: with the bad
        # caller COMMITTED, a diff touching only the helper must still
        # report the caller's finding — not green-light it
        git("-c", "user.email=t@t", "-c", "user.name=t", "add", ".")
        git("-c", "user.email=t@t", "-c", "user.name=t", "commit",
            "-qm", "y")
        with open(pkg / "helper.py", "a") as fh:
            fh.write("# touched\n")
        assert graftlint_main(["pkg", "--changed", "--no-cache",
                               "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert [f["rule"] for f in payload["findings"]] == ["JX009"]
        assert payload["findings"][0]["path"] == "pkg/caller.py"
    finally:
        os.chdir(old)


def test_cli_changed_fault_table_diff_rechecks_site_modules(tmp_path,
                                                            capsys):
    """Registry-edge widening for JX020: a diff touching ONLY the
    fault-table module must re-check every module holding an injection
    site — renaming a table row orphans the untouched sites, and the
    incremental gate has to say so, not green-light them."""
    table = (
        '"""Fault points.\n'
        "\n"
        "===============  ==========\n"
        "point            fired from\n"
        "===============  ==========\n"
        "``demo.stage``   site.py\n"
        "===============  ==========\n"
        '"""\n'
        "def inject(point, **info):\n"
        "    return None\n")
    site = (
        "from pkg.faults import inject\n"
        "def stage(shard):\n"
        "    inject('demo.stage', shard=shard)\n"
        "    return shard\n")
    repo = tmp_path / "repo"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "faults.py").write_text(table)
    (pkg / "site.py").write_text(site)

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, check=True,
                       capture_output=True)

    git("init", "-q")
    git("-c", "user.email=t@t", "-c", "user.name=t", "add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "x")

    old = os.getcwd()
    os.chdir(repo)
    try:
        assert graftlint_main(["pkg", "--changed", "--no-cache"]) == 0
        capsys.readouterr()
        # rename the registered point IN THE TABLE ONLY: site.py still
        # fires the old name, which now never matches a schedule
        (pkg / "faults.py").write_text(
            table.replace("``demo.stage``   site.py",
                          "``demo.staging``  site.py"))
        assert graftlint_main(["pkg", "--changed", "--no-cache",
                               "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        by_path = {f["path"]: f["rule"] for f in payload["findings"]}
        # the UNTOUCHED site module was re-checked and convicted...
        assert by_path.get("pkg/site.py") == "JX020"
        # ...and the renamed row itself is unfired, anchored on the table
        assert by_path.get("pkg/faults.py") == "JX020"
    finally:
        os.chdir(old)


def test_cli_changed_rejects_write_baseline(tmp_path):
    """--changed carries only the changed files' findings; writing those
    as the baseline would drop every grandfathered entry for unchanged
    files. The combination is a usage error, not a silent rewrite."""
    rc = graftlint_main(["pkg", "--changed",
                         "--write-baseline", str(tmp_path / "b.json")])
    assert rc == 2
    assert not (tmp_path / "b.json").exists()


def test_cli_runs_as_module():
    # the exact invocation docs/Makefile/CI use
    proc = subprocess.run(
        [sys.executable, "-m", "cycloneml_tpu.analysis", "cycloneml_tpu",
         "--baseline", os.path.join("cycloneml_tpu", "analysis",
                                    "baseline.json")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- the tier-1 gate --------------------------------------------------------

def test_self_run_is_clean_modulo_baseline():
    """The permanent gate: every non-baselined finding in cycloneml_tpu/
    fails tier-1. Fix the hazard, or — only where a fix needs a design
    change — regenerate the baseline (docs/graftlint.md)."""
    findings = analyze_paths([PACKAGE])
    new, _ = apply_baseline(findings, load_baseline(BASELINE))
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f"  {f.path}:{f.line}: {f.rule} {f.message}" for f in new)


def test_mesh_axes_discovered_from_source():
    """JX005 must validate against the axes mesh.py DECLARES, not a
    hardcoded copy that could drift."""
    from cycloneml_tpu.analysis.engine import (ModuleInfo, _discover_axes,
                                               load_module)
    mesh_py = os.path.join(PACKAGE, "mesh.py")
    mod = load_module(mesh_py, "cycloneml_tpu/mesh.py")
    axes, names, mapping = _discover_axes({mod.path: mod})
    assert set(axes) == {"data", "replica", "model"}
    assert names == {"DATA_AXIS", "REPLICA_AXIS", "MODEL_AXIS"}
    # the constant->value map feeds the abstract interpreter's spec
    # resolution (P((REPLICA_AXIS, DATA_AXIS)))
    assert mapping == {"DATA_AXIS": "data", "REPLICA_AXIS": "replica",
                       "MODEL_AXIS": "model"}


# -- golden CLI output for the concurrency rules (JX011/JX013) ---------------

def test_cli_json_golden_jx011(capsys):
    """Stable machine-readable JX011 output: rule ids, functions, and
    region lines (pinned via the fixture's own marker lines, the same
    contract the precision tests enforce)."""
    flag = os.path.join(FIXTURES, "jx011_flag.py")
    assert graftlint_main([flag, "--rules", "JX011", "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    # racy_reset, racy_mean×2, size_racy, evict_racy, peek_racy
    assert payload["count"] == 6
    assert {f["rule"] for f in payload["findings"]} == {"JX011"}
    assert {f["function"] for f in payload["findings"]} == {
        "Tally.racy_reset", "Tally.racy_mean", "Pipeline.size_racy",
        "RacyRollup.evict_racy", "RacyRollup.peek_racy"}
    assert {f["line"] for f in payload["findings"]} \
        == marker_lines(flag, "JX011")
    for f in payload["findings"]:
        assert f["end_line"] >= f["line"]
        assert "unguarded" in f["message"]


def test_cli_sarif_golden_jx013(capsys):
    """Stable SARIF for JX013: ruleId, 1-based regions on the pop lines,
    and the rule:path:function partialFingerprints baselining keys on."""
    flag = os.path.join(FIXTURES, "jx013_flag.py")
    assert graftlint_main([flag, "--rules", "JX013", "--sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    (run,) = doc["runs"]
    results = run["results"]
    assert {r["ruleId"] for r in results} == {"JX013"}
    lines = {r["locations"][0]["physicalLocation"]["region"]["startLine"]
             for r in results}
    assert lines == marker_lines(flag, "JX013")
    fps = {r["partialFingerprints"]["graftlint/v1"] for r in results}
    assert "JX013:jx013_flag.py:Lane.leaks_on_error_path" in fps
    assert "JX013:jx013_flag.py:Lane2.helper_never_completes" in fps
    rule_meta = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"JX011", "JX012", "JX013", "JX014",
            "JX020", "JX021", "JX022", "JX023"} <= rule_meta
    # every driver rule ships a non-empty shortDescription (module
    # docstring first line) — the v5 rules included, ordering pinned
    driver_rules = run["tool"]["driver"]["rules"]
    assert [r["id"] for r in driver_rules] == sorted(rule_meta)
    for r in driver_rules:
        assert r["shortDescription"]["text"].strip()


# -- fixture sweep: the registry and the test sweep cannot drift -------------

def test_rule_registry_matches_fixture_sweep():
    """Every registered rule is in this file's RULES sweep (so its flag
    fixture is proven to fire and its pass fixture to stay silent), and
    both fixture files exist on disk. A rule added without fixtures
    fails here, not silently skips the gate."""
    from cycloneml_tpu.analysis.rules import ALL_RULES
    assert tuple(cls.rule_id for cls in ALL_RULES) == RULES
    for rule in RULES:
        for suffix in ("flag", "pass"):
            path = os.path.join(FIXTURES, f"{rule.lower()}_{suffix}.py")
            assert os.path.exists(path), f"missing fixture {path}"


def test_rule_registry_matches_docs():
    """Every registered rule has a `### JXnnn` section in
    docs/graftlint.md — docs drift used to go uncaught; a rule added
    without its docs page fails here."""
    docs = os.path.join(REPO, "docs", "graftlint.md")
    with open(docs, encoding="utf-8") as fh:
        text = fh.read()
    from cycloneml_tpu.analysis.rules import ALL_RULES
    missing = [cls.rule_id for cls in ALL_RULES
               if not re.search(rf"^### {cls.rule_id}\b", text,
                                flags=re.MULTILINE)]
    assert missing == [], f"rules without docs/graftlint.md sections: " \
                          f"{missing}"


# -- deterministic report ordering (golden) ----------------------------------

def test_report_ordering_is_deterministic():
    """--json and --sarif emit findings sorted by (path, line, rule)
    regardless of discovery order — CI diffs and SARIF fingerprint
    ordering must not churn when unrelated rules reorder."""
    from cycloneml_tpu.analysis.engine import Finding
    from cycloneml_tpu.analysis.report import render_json, render_sarif
    shuffled = [
        Finding("JX009", "b.py", 4, 0, "m3"),
        Finding("JX001", "b.py", 4, 0, "m2"),
        Finding("JX002", "a.py", 9, 0, "m1"),
        Finding("JX001", "a.py", 2, 0, "m0"),
    ]
    payload = json.loads(render_json(shuffled))
    assert [(f["path"], f["line"], f["rule"])
            for f in payload["findings"]] == [
        ("a.py", 2, "JX001"), ("a.py", 9, "JX002"),
        ("b.py", 4, "JX001"), ("b.py", 4, "JX009")]
    doc = json.loads(render_sarif(shuffled))
    results = doc["runs"][0]["results"]
    keys = [(r["locations"][0]["physicalLocation"]["artifactLocation"]
             ["uri"],
             r["locations"][0]["physicalLocation"]["region"]["startLine"],
             r["ruleId"]) for r in results]
    assert keys == sorted(keys)
    # golden: byte-identical output for the same findings in any order
    assert render_json(shuffled) == render_json(list(reversed(shuffled)))
    assert render_sarif(shuffled) == render_sarif(list(reversed(shuffled)))


# -- per-rule timings ---------------------------------------------------------

def test_json_carries_per_rule_timings(capsys):
    """--json gains a per-rule wall-time block: one entry per rule id
    plus the shared JXSHAPE analysis, all non-negative floats."""
    flag = os.path.join(FIXTURES, "jx002_flag.py")
    assert graftlint_main([flag, "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    timings = payload["timings"]
    for rule in RULES:
        assert rule in timings, f"no timing entry for {rule}"
        assert timings[rule] >= 0.0
    assert "JXSHAPE" in timings   # the shared abstract shape analysis
    assert "JXFAULT" in timings   # the shared fault-reachability fixpoint


def test_text_output_prints_slowest_rules(capsys):
    """`make lint` (the plain text reporter) surfaces the top-3 slowest
    rules so rule authors see their cost on every run."""
    clean = os.path.join(FIXTURES, "jx002_pass.py")
    assert graftlint_main([clean]) == 0
    out = capsys.readouterr().out
    assert "slowest rules:" in out


# -- full-run parse cache (CI reuse via CYCLONE_LINT_CACHE) ------------------

def test_full_run_cache_via_env(tmp_path, monkeypatch, capsys):
    """A full-scope run reuses the ParseCache when CYCLONE_LINT_CACHE
    names one (CI restores the pickle between jobs); the second run
    serves parses from the cache."""
    from cycloneml_tpu.analysis.incremental import ParseCache
    cache_file = tmp_path / "ci-cache.pkl"
    monkeypatch.setenv("CYCLONE_LINT_CACHE", str(cache_file))
    flag = os.path.join(FIXTURES, "jx002_flag.py")
    assert graftlint_main([flag]) == 1
    capsys.readouterr()
    assert cache_file.exists()
    assert graftlint_main([flag]) == 1
    capsys.readouterr()
    probe = ParseCache(str(cache_file))
    rel = [k for k in probe._entries]
    assert any(k.endswith("jx002_flag.py") for k in rel)
    # --no-cache still disables it
    monkeypatch.setenv("CYCLONE_LINT_CACHE", str(tmp_path / "other.pkl"))
    assert graftlint_main([flag, "--no-cache"]) == 1
    capsys.readouterr()
    assert not (tmp_path / "other.pkl").exists()


# -- parse cache: schema-keyed invalidation ----------------------------------

def test_parse_cache_rejects_pre_v3_schema(tmp_path):
    """A cache pickle written before the concurrency rules (old version,
    or same version but a different dataflow-rule schema) must be
    DISCARDED — stale lockset/obligation facts served from a pre-v3
    cache would silently weaken the gate."""
    import pickle

    from cycloneml_tpu.analysis.incremental import (CACHE_VERSION,
                                                    ParseCache,
                                                    summary_schema)
    src = tmp_path / "m.py"
    src.write_text("import threading\n_lock = threading.Lock()\n")
    cache_path = tmp_path / "cache.pkl"

    c1 = ParseCache(str(cache_path))
    assert c1.load_module(str(src), "m.py") is not None
    assert (c1.hits, c1.misses) == (0, 1)
    c1.save()

    # same version + same schema: entries are served
    c2 = ParseCache(str(cache_path))
    assert c2.load_module(str(src), "m.py") is not None
    assert (c2.hits, c2.misses) == (1, 0)

    def rewrite(**patch):
        with open(cache_path, "rb") as fh:
            payload = pickle.load(fh)
        payload.update(patch)
        for k, v in list(patch.items()):
            if v is None:
                payload.pop(k, None)
        with open(cache_path, "wb") as fh:
            pickle.dump(payload, fh)

    # a pre-v3 cache: old version field, no schema field
    rewrite(version=2, schema=None)
    c3 = ParseCache(str(cache_path))
    assert c3.load_module(str(src), "m.py") is not None
    assert (c3.hits, c3.misses) == (0, 1)   # fresh parse, nothing served

    # version matches but the rule pack's dataflow schema differs (a
    # future rule added/removed): likewise discarded
    rewrite(version=CACHE_VERSION, schema="JX004,JX999")
    c4 = ParseCache(str(cache_path))
    assert c4.load_module(str(src), "m.py") is not None
    assert (c4.hits, c4.misses) == (0, 1)

    # sanity: the live schema names the concurrency analyses
    assert {"JX011", "JX012", "JX013", "JX014"} <= set(
        summary_schema().split(","))


def test_jx021_transitive_subclass_without_base_text(tmp_path):
    """A second-level event subclass (`class Ghost(BlocksMoved)`) lives
    in a module that never spells `CycloneEvent` — registry discovery
    must scan every module's class bases, not text-gate on the base
    name, or the subclass silently never enters the closure."""
    (tmp_path / "events.py").write_text(
        "class CycloneEvent:\n    pass\n\n\n"
        "class BlocksMoved(CycloneEvent):\n    pass\n\n\n"
        "def handle(kind):\n    return kind == 'BlocksMoved'\n")
    (tmp_path / "emit.py").write_text(
        "from events import BlocksMoved\n\n\n"
        "class GhostEvent(BlocksMoved):\n    pass\n\n\n"
        "def post(bus):\n    bus.post(GhostEvent())\n")
    found = [f for f in analyze_paths([str(tmp_path)])
             if f.rule == "JX021"]
    assert [os.path.basename(f.path) for f in found] == ["emit.py"]
    assert "GhostEvent" in found[0].message
