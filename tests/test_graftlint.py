"""graftlint: per-rule precision tests + the tier-1 self-run gate.

Each rule has a paired should-flag / should-pass fixture under
``tests/fixtures/graftlint/``. Flag fixtures carry a ``# JXnnn`` marker
comment on every line the rule must report — the test asserts the
reported line set EQUALS the marker line set, pinning both recall (no
missed hazard) and precision (no extra noise) per rule.

The gate test runs the analyzer over ``cycloneml_tpu/`` exactly the way
the CLI does and fails on any finding not grandfathered in
``cycloneml_tpu/analysis/baseline.json`` — this is the permanent CI gate
for every future PR. Pure ``ast``: no jax import, no device work.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from cycloneml_tpu.analysis import analyze_paths
from cycloneml_tpu.analysis.baseline import (apply_baseline, load_baseline,
                                             write_baseline)
from cycloneml_tpu.analysis.__main__ import main as graftlint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")
PACKAGE = os.path.join(REPO, "cycloneml_tpu")
BASELINE = os.path.join(PACKAGE, "analysis", "baseline.json")

RULES = ("JX001", "JX002", "JX003", "JX004", "JX005", "JX006", "JX007")


def marker_lines(path: str, rule: str):
    """1-based lines carrying a `# <rule>` marker comment."""
    pat = re.compile(rf"#.*{rule}")
    with open(path) as fh:
        return {i for i, line in enumerate(fh, 1) if pat.search(line)}


def findings_for(path: str, rule: str):
    return [f for f in analyze_paths([path]) if f.rule == rule]


@pytest.mark.parametrize("rule", RULES)
def test_rule_flags_exactly_the_marked_lines(rule):
    path = os.path.join(FIXTURES, f"{rule.lower()}_flag.py")
    expected = marker_lines(path, rule)
    assert expected, f"fixture {path} has no marker lines"
    got = {f.line for f in findings_for(path, rule)}
    assert got == expected, (
        f"{rule}: flagged lines {sorted(got)} != marked {sorted(expected)}")


@pytest.mark.parametrize("rule", RULES)
def test_pass_fixture_is_totally_clean(rule):
    # pass fixtures must be clean under the WHOLE pack, not just their
    # own rule — a pass example for one rule must not trip another
    path = os.path.join(FIXTURES, f"{rule.lower()}_pass.py")
    findings = analyze_paths([path])
    assert findings == [], [
        f"{f.rule}@{f.line}: {f.message}" for f in findings]


def test_tracer_aware_instrumentation_is_clean():
    """The observe/ instrumentation pattern — tracer check BEFORE any span
    on a path reachable at trace time, spans + one batched device_get in
    host code — must be clean under the whole rule pack (the PR-3
    tentpole's JX001 contract)."""
    path = os.path.join(FIXTURES, "jx001_tracing_pass.py")
    findings = analyze_paths([path])
    assert findings == [], [
        f"{f.rule}@{f.line}: {f.message}" for f in findings]


# -- suppressions -----------------------------------------------------------

def test_inline_suppression(tmp_path):
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(jnp.max(x))  # graftlint: disable=JX001\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert analyze_paths([str(p)]) == []


def test_own_line_suppression_covers_next_line(tmp_path):
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    # graftlint: disable=JX001\n"
        "    return float(jnp.max(x))\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert analyze_paths([str(p)]) == []


def test_suppression_is_rule_specific(tmp_path):
    src = (
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(jnp.max(x))  # graftlint: disable=JX002\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert [f.rule for f in analyze_paths([str(p)])] == ["JX001"]


# -- baseline ---------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    flag = os.path.join(FIXTURES, "jx001_flag.py")
    findings = analyze_paths([flag])
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings)
    new, grandfathered = apply_baseline(findings, load_baseline(str(bl)))
    assert new == [] and grandfathered == len(findings)


def test_baseline_does_not_cover_new_occurrences(tmp_path):
    flag = os.path.join(FIXTURES, "jx001_flag.py")
    findings = analyze_paths([flag])
    bl = tmp_path / "baseline.json"
    # grandfather all but one occurrence
    write_baseline(str(bl), findings[:-1])
    new, _ = apply_baseline(findings, load_baseline(str(bl)))
    assert len(new) == 1


# -- CLI --------------------------------------------------------------------

def test_cli_exit_codes_and_json(tmp_path, capsys):
    flag = os.path.join(FIXTURES, "jx002_flag.py")
    assert graftlint_main([flag, "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["count"] >= 1
    assert all(f["rule"] == "JX002" for f in payload["findings"])

    clean = os.path.join(FIXTURES, "jx002_pass.py")
    assert graftlint_main([clean]) == 0

    assert graftlint_main([]) == 2


def test_cli_rule_subset(capsys):
    flag = os.path.join(FIXTURES, "jx001_flag.py")
    # jx001_flag also has no JX005 hazards; restricting to JX005 is clean
    assert graftlint_main([flag, "--rules", "JX005"]) == 0
    assert graftlint_main([flag, "--rules", "JX001"]) == 1
    capsys.readouterr()


def test_cli_runs_as_module():
    # the exact invocation docs/Makefile/CI use
    proc = subprocess.run(
        [sys.executable, "-m", "cycloneml_tpu.analysis", "cycloneml_tpu",
         "--baseline", os.path.join("cycloneml_tpu", "analysis",
                                    "baseline.json")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- the tier-1 gate --------------------------------------------------------

def test_self_run_is_clean_modulo_baseline():
    """The permanent gate: every non-baselined finding in cycloneml_tpu/
    fails tier-1. Fix the hazard, or — only where a fix needs a design
    change — regenerate the baseline (docs/graftlint.md)."""
    findings = analyze_paths([PACKAGE])
    new, _ = apply_baseline(findings, load_baseline(BASELINE))
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f"  {f.path}:{f.line}: {f.rule} {f.message}" for f in new)


def test_mesh_axes_discovered_from_source():
    """JX005 must validate against the axes mesh.py DECLARES, not a
    hardcoded copy that could drift."""
    from cycloneml_tpu.analysis.engine import (ModuleInfo, _discover_axes,
                                               load_module)
    mesh_py = os.path.join(PACKAGE, "mesh.py")
    mod = load_module(mesh_py, "cycloneml_tpu/mesh.py")
    axes, names = _discover_axes({mod.path: mod})
    assert set(axes) == {"data", "replica", "model"}
    assert names == {"DATA_AXIS", "REPLICA_AXIS", "MODEL_AXIS"}
