"""bf16 data tier with fp32 accumulation (ISSUE 6 acceptance suite).

Three contracts pinned here:

1. **Byte reduction is real and measured** — the bf16 logistic sweep
   accesses < 60% of the fp32 sweep's bytes by XLA's own accounting
   (``observe/costs.sweep_cost``, lower-only — nothing executes), not by
   dtype-width arithmetic.
2. **Accuracy survives the tier** — seeded logreg/linreg coefficient
   parity between the bf16 and fp32 tiers within the documented tolerance
   (docs/mixed-precision.md: ~2% relative for well-scaled problems), and
   stacked == serial stays tight *within* a tier.
3. **The opt-out is exact** — ``cyclone.data.dtype=float32`` takes the
   pre-tier code path: full-width aggregator math is bit-identical to the
   pre-PR formula (no ``preferred_element_type``, no downcasts anywhere).

Tests run under the x64 CPU config like the rest of tier-1; the bf16 tier
is forced per-test via conf and restored afterwards (auto resolves to
float64 under x64, which is what keeps every OTHER suite byte-identical).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.dataset.instance import (compute_dtype, data_dtype,
                                            is_narrow_dtype)
from cycloneml_tpu.ml.optim import aggregators


@pytest.fixture
def tier(ctx):
    """Set cyclone.data.dtype for one test, always restoring 'auto'."""
    def set_tier(name):
        ctx.conf.set("cyclone.data.dtype", name)
    yield set_tier
    ctx.conf.set("cyclone.data.dtype", "auto")


def _fresh_frame(ctx, x, y):
    # a new MLFrame per tier: the frame's dataset cache is keyed by dtype,
    # but distinct frames make each test's placement explicit
    return MLFrame(ctx, {"features": x, "label": y})


# -- tier resolution ---------------------------------------------------------

def test_data_dtype_auto_is_float64_under_x64(ctx):
    assert jax.config.jax_enable_x64
    assert np.dtype(data_dtype(ctx.conf)) == np.float64
    assert np.dtype(compute_dtype()) == np.float64


def test_data_dtype_overrides(ctx, tier):
    tier("bfloat16")
    assert str(np.dtype(data_dtype(ctx.conf))) == "bfloat16"
    assert is_narrow_dtype(data_dtype(ctx.conf))
    tier("float32")
    assert np.dtype(data_dtype(ctx.conf)) == np.float32
    assert not is_narrow_dtype(np.float32)


def test_data_dtype_validator_rejects_junk(ctx, tier):
    tier("int8")
    with pytest.raises(ValueError):
        data_dtype(ctx.conf)


# -- dataset plumbing --------------------------------------------------------

def test_bf16_dataset_stores_x_narrow_yw_wide(ctx, tier):
    tier("bfloat16")
    rng = np.random.RandomState(0)
    x = rng.randn(100, 8)
    y = (rng.rand(100) > 0.5).astype(np.float64)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    assert str(ds.x.dtype) == "bfloat16"
    # labels/weights stay in the accumulator tier: weight sums, label
    # moments and optimizer state must not round at storage width
    assert np.dtype(str(ds.y.dtype)) == np.dtype(compute_dtype())
    assert np.dtype(str(ds.w.dtype)) == np.dtype(compute_dtype())
    # storage accounting reflects the split tiers
    n_pad = int(ds.x.shape[0])
    assert ds.padded_bytes() == n_pad * (8 * 2 + 2 * 8)


def test_bf16_npz_spill_and_checkpoint_roundtrip(ctx, tier, tmp_path):
    tier("bfloat16")
    rng = np.random.RandomState(1)
    x = rng.randn(64, 5)
    ds = InstanceDataset.from_numpy(ctx, x)
    x_before = np.asarray(ds.x)
    # DISK tier spill: npz drops extension dtypes unless packed
    ds.persist_disk(str(tmp_path / "spill.npz"))
    assert str(ds.x.dtype) == "bfloat16"  # transparent restore
    np.testing.assert_array_equal(np.asarray(ds.x), x_before)
    # checkpoint/restore round trip
    ds2 = InstanceDataset.from_numpy(ctx, x)
    path = ds2.checkpoint(str(tmp_path / "ckpt.npz"))
    ds3 = InstanceDataset.restore(ctx, path)
    assert str(ds3.x.dtype) == "bfloat16"
    np.testing.assert_array_equal(np.asarray(ds3.x), x_before)
    # y can ride the data tier too (fit_stacked derives a bf16 label
    # matrix) — the pack must cover it, not just x
    import ml_dtypes
    rt = ctx.mesh_runtime
    y_stackish = rng.rand(64, 2) > 0.5
    ds4 = InstanceDataset.from_numpy(ctx, x).derive(
        y=rt.device_put_sharded_rows(
            y_stackish.astype(ml_dtypes.bfloat16)))
    y_before = np.asarray(ds4.y)
    path4 = ds4.checkpoint(str(tmp_path / "ckpt_y.npz"))
    ds5 = InstanceDataset.restore(ctx, path4)
    assert str(ds5.y.dtype) == "bfloat16"
    np.testing.assert_array_equal(np.asarray(ds5.y), y_before)


def test_summarizer_counts_exact_over_bf16(ctx, tier):
    from cycloneml_tpu.ml.stat import Summarizer
    tier("bfloat16")
    rng = np.random.RandomState(2)
    n = 2000  # far past bf16's 256-integer exactness limit
    x = rng.randn(n, 3)
    x[:, 2] = 0.0
    ds = InstanceDataset.from_numpy(ctx, x)
    s = Summarizer.summarize(ds)
    assert s.count == n
    assert s.num_nonzeros[2] == 0
    assert s.num_nonzeros[0] == np.count_nonzero(
        np.asarray(ds.unpad(np.asarray(ds.x))[:, 0]))
    # means/stds at bf16 input resolution
    np.testing.assert_allclose(s.mean[:2], x[:, :2].mean(0), atol=2e-2)


# -- seeded parity: bf16 vs fp32 tier ---------------------------------------

# documented accuracy expectation (docs/mixed-precision.md): coefficient
# agreement for well-scaled dense problems within ~2% relative; the
# tolerance here is the contract the docs quote
BF16_COEF_RTOL = 5e-2


def test_logreg_bf16_vs_fp32_coef_parity(ctx, tier):
    from cycloneml_tpu.ml.classification import LogisticRegression
    rng = np.random.RandomState(7)
    n, d = 2000, 16
    x = rng.randn(n, d) * (1.0 + np.arange(d) / 4.0) + 0.3
    beta = rng.randn(d)
    y = (x @ beta + rng.randn(n) > 0).astype(np.float64)

    def fit(t):
        tier(t)
        return LogisticRegression(maxIter=80, regParam=0.01, tol=1e-10).fit(
            _fresh_frame(ctx, x, y))

    m32, mbf = fit("float32"), fit("bfloat16")
    c32 = np.asarray(m32.coefficients.to_array())
    cbf = np.asarray(mbf.coefficients.to_array())
    rel = np.abs(cbf - c32) / np.maximum(np.abs(c32), 1e-2)
    assert rel.max() < BF16_COEF_RTOL, rel.max()
    # and the tier is genuinely narrow, not silently promoted
    dsbf = _fresh_frame(ctx, x, y).to_instance_dataset("features", "label")
    assert str(dsbf.x.dtype) == "bfloat16"


def test_linreg_bf16_vs_fp32_coef_parity(ctx, tier):
    from cycloneml_tpu.ml.regression import LinearRegression
    rng = np.random.RandomState(11)
    n, d = 2000, 12
    x = rng.randn(n, d) * 2.0 + 1.0
    beta = rng.randn(d)
    y = x @ beta + 0.05 * rng.randn(n)

    def fit(t):
        tier(t)
        return LinearRegression(maxIter=80, solver="l-bfgs",
                                regParam=0.001, tol=1e-10).fit(
            _fresh_frame(ctx, x, y))

    m32, mbf = fit("float32"), fit("bfloat16")
    c32 = np.asarray(m32.coefficients.to_array())
    cbf = np.asarray(mbf.coefficients.to_array())
    rel = np.abs(cbf - c32) / np.maximum(np.abs(c32), 1e-2)
    assert rel.max() < BF16_COEF_RTOL, rel.max()


def test_stacked_equals_serial_within_bf16_tier(ctx, tier):
    """The stacked engine's equivalence contract holds INSIDE the narrow
    tier too: both paths read the same bf16 X with the same fp32/f64
    accumulation, so their fixed points agree far tighter than either
    agrees with the fp32 tier."""
    from cycloneml_tpu.ml.classification import LogisticRegression, OneVsRest
    tier("bfloat16")
    rng = np.random.RandomState(5)
    n, d, k = 900, 10, 3
    centers = rng.randn(k, d) * 3.0
    y = rng.randint(0, k, n).astype(np.float64)
    x = centers[y.astype(int)] + rng.randn(n, d)
    frame = _fresh_frame(ctx, x, y)
    clf = LogisticRegression(maxIter=150, regParam=0.01, tol=1e-10)
    stacked = OneVsRest(classifier=clf, parallelism=k).fit(frame)
    serial = OneVsRest(classifier=clf, parallelism=1).fit(frame)
    diff = max(float(np.abs(a._coef - b._coef).max())
               for a, b in zip(stacked.models, serial.models))
    assert diff < 1e-5, diff
    # the OvR label stack rides the data tier
    from cycloneml_tpu.dataset.instance import data_dtype as _dd
    assert str(np.dtype(_dd(ctx.conf))) == "bfloat16"


# -- the acceptance pin: measured byte reduction -----------------------------

def test_bf16_sweep_accesses_under_60_percent_of_fp32_bytes(ctx, tier):
    """ISSUE-6 acceptance: bytes-accessed per logreg optimizer sweep
    (observe/costs registry, XLA cost analysis on CPU — lower-only, no
    execution) drops >= 40% at equal n×d when the data tier narrows to
    bf16. d is wide enough that X dominates the (n,)-vector temporaries,
    as in every shape the roofline motivation is about."""
    from cycloneml_tpu.observe import costs
    rng = np.random.RandomState(3)
    n, d = 4096, 256
    x = rng.randn(n, d)
    y = (rng.rand(n) > 0.5).astype(np.float64)

    def measure(t):
        tier(t)
        ds = InstanceDataset.from_numpy(ctx, x, y)
        # extras/coef in f32 regardless of the x64 test config: the
        # measurement must mirror the production (non-x64) program, where
        # the accumulator tier is f32 — f64 extras under x64 would inflate
        # the fp32 sweep via operand promotion and flatter the ratio
        f32 = np.float32
        cost = costs.sweep_cost(
            ds.tree_aggregate_fn(aggregators.binary_logistic_scaled(d, True)),
            jnp.ones(d, f32), jnp.zeros(d, f32), jnp.zeros(d + 1, f32),
            name=f"sweep.{t}")
        return cost.bytes_accessed_total

    fp32_bytes = measure("float32")
    bf16_bytes = measure("bfloat16")
    assert fp32_bytes and bf16_bytes  # CPU reports cost analysis
    ratio = bf16_bytes / fp32_bytes
    assert ratio < 0.60, (bf16_bytes, fp32_bytes, ratio)


# -- the opt-out guard: float32 tier is bit-identical pre-PR math ------------

def test_float32_tier_aggregator_is_bitwise_pre_tier(ctx, tier):
    """cyclone.data.dtype=float32 restores the pre-PR sweep exactly: the
    full-width branch of the tier-aware dot IS the pre-tier jnp.dot — no
    preferred_element_type, no casts — pinned bitwise against a local
    reimplementation of the pre-PR formula."""
    tier("float32")
    rng = np.random.RandomState(4)
    n, d = 256, 9
    x = jnp.asarray(rng.randn(n, d), jnp.float32)
    y = jnp.asarray((rng.rand(n) > 0.5), jnp.float32)
    w = jnp.asarray(rng.rand(n) + 0.5, jnp.float32)
    inv_std = jnp.asarray(rng.rand(d) + 0.5, jnp.float32)
    mu = jnp.asarray(rng.randn(d), jnp.float32)
    coef = jnp.asarray(rng.randn(d + 1), jnp.float32)

    got = aggregators.binary_logistic_scaled(d, True)(
        x, y, w, inv_std, mu, coef)

    prec = jax.lax.Precision.HIGHEST
    beta, b0 = coef[:d], coef[d]
    sb = inv_std * beta
    margin = (jnp.dot(x, sb, precision=prec)
              - jnp.dot(mu, beta, precision=prec) + b0)
    loss = jnp.sum(w * (jax.nn.softplus(margin) - y * margin))
    mult = w * (jax.nn.sigmoid(margin) - y)
    msum = jnp.sum(mult)
    g = inv_std * jnp.dot(x.T, mult, precision=prec) - mu * msum
    grad = jnp.concatenate([g, msum[None]])

    assert float(got["loss"]) == float(loss)
    np.testing.assert_array_equal(np.asarray(got["grad"]),
                                  np.asarray(grad))


def test_float32_tier_fit_is_deterministic(ctx, tier):
    from cycloneml_tpu.ml.classification import LogisticRegression
    tier("float32")
    rng = np.random.RandomState(9)
    x = rng.randn(500, 7)
    y = (x[:, 0] > 0).astype(np.float64)
    fits = [LogisticRegression(maxIter=30, regParam=0.01).fit(
        _fresh_frame(ctx, x, y)) for _ in range(2)]
    np.testing.assert_array_equal(
        np.asarray(fits[0].coefficients.to_array()),
        np.asarray(fits[1].coefficients.to_array()))


# -- narrow labels stay exact ------------------------------------------------

def test_bf16_label_stack_is_exact(ctx, tier):
    """{0, 1} is exactly representable in bf16 — the stacked label matrix
    rides the data tier without any label distortion."""
    import ml_dtypes
    y = np.array([0.0, 1.0, 2.0, 1.0])
    stack = (np.arange(3)[:, None] == y[None, :]).astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(
        stack.astype(np.float64),
        (np.arange(3)[:, None] == y[None, :]).astype(np.float64))


# -- the second rung: fp8 (e4m3) storage with per-column scales ---------------

# documented fp8 accuracy envelope (docs/mixed-precision.md): coefficient
# agreement with the fp32 tier within 20% of the coefficient scale for
# probe-passing problems (observed ~6-17% across seeds); the envelope
# probe falls back to bf16 for anything wilder
FP8_COEF_NORMREL = 0.20


def _norm_rel(a, b):
    return float(np.abs(a - b).max() / max(np.abs(b).max(), 1e-9))


def test_fp8_tier_resolution(ctx, tier):
    from cycloneml_tpu.dataset.instance import is_fp8_dtype
    tier("float8")
    # forced form: e4m3 for capable callers even under the x64 parity
    # config; NON-capable callers land on the bf16 rung — raw codes must
    # never reach an estimator that would read them as values
    assert str(np.dtype(data_dtype(ctx.conf, fp8_capable=True))) \
        == "float8_e4m3fn"
    assert str(np.dtype(data_dtype(ctx.conf))) == "bfloat16"
    assert is_fp8_dtype(data_dtype(ctx.conf, fp8_capable=True))
    assert not is_fp8_dtype(np.float32)
    tier("auto8")
    # auto8 keeps the x64 parity tier full-width, like auto
    assert jax.config.jax_enable_x64
    assert np.dtype(data_dtype(ctx.conf, fp8_capable=True)) == np.float64
    assert np.dtype(data_dtype(ctx.conf)) == np.float64


def test_fp8_dataset_quantizes_with_scales(ctx, tier):
    tier("float8")
    rng = np.random.RandomState(21)
    x = rng.randn(200, 6) * np.array([1.0, 10.0, 0.1, 5.0, 2.0, 1.0])
    y = (rng.rand(200) > 0.5).astype(np.float64)
    ds = InstanceDataset.from_numpy(
        ctx, x, y, dtype=data_dtype(ctx.conf, fp8_capable=True))
    assert str(ds.x.dtype) == "float8_e4m3fn"
    assert ds.x_scale is not None and ds.x_scale.shape == (6,)
    # y/w stay at accumulator width
    assert np.dtype(str(ds.y.dtype)) == np.dtype(compute_dtype())
    # storage accounting sees the 1-byte itemsize
    n_pad = int(ds.x.shape[0])
    assert ds.padded_bytes() == n_pad * (6 * 1 + 2 * 8)
    # every stored code is finite (e4m3fn overflow is NaN, not saturate)
    codes = np.asarray(ds.x).astype(np.float32)
    assert np.isfinite(codes).all()
    # dequantized values match the raw data at e4m3 resolution (2^-4
    # relative half-ulp), column scales included
    deq, _, _ = ds.to_numpy()
    col_scale = np.abs(x).max(axis=0)
    assert np.abs(deq - x).max(axis=0).max() < 0.07 * col_scale.max()
    np.testing.assert_allclose(np.abs(deq - x).max(axis=0),
                               np.zeros(6), atol=(0.07 * col_scale).max())


def test_fp8_npz_spill_and_checkpoint_roundtrip(ctx, tier, tmp_path):
    tier("float8")
    rng = np.random.RandomState(22)
    x = rng.randn(64, 5)
    dt = data_dtype(ctx.conf, fp8_capable=True)
    ds = InstanceDataset.from_numpy(ctx, x, dtype=dt)
    x_before = np.asarray(ds.x)
    scale_before = ds.x_scale.copy()
    # DISK spill: fp8 packs as a uint8 bit-view + dtype tag + scales
    ds.persist_disk(str(tmp_path / "spill8.npz"))
    assert str(ds.x.dtype) == "float8_e4m3fn"  # transparent restore
    np.testing.assert_array_equal(np.asarray(ds.x), x_before)
    np.testing.assert_array_equal(ds.x_scale, scale_before)
    # checkpoint/restore round trip keeps codes AND scales
    ds2 = InstanceDataset.from_numpy(ctx, x, dtype=dt)
    path = ds2.checkpoint(str(tmp_path / "ckpt8.npz"))
    ds3 = InstanceDataset.restore(ctx, path)
    assert str(ds3.x.dtype) == "float8_e4m3fn"
    np.testing.assert_array_equal(np.asarray(ds3.x), x_before)
    np.testing.assert_array_equal(ds3.x_scale, scale_before)


def test_fp8_npz_torn_tag_is_a_loud_error(ctx, tier, tmp_path):
    """A corrupt dtype tag must fail the LOAD with a clear error — never
    silently reinterpret packed bytes as a different tier."""
    tier("float8")
    rng = np.random.RandomState(23)
    ds = InstanceDataset.from_numpy(
        ctx, rng.randn(32, 4), dtype=data_dtype(ctx.conf, fp8_capable=True))
    path = ds.checkpoint(str(tmp_path / "torn.npz"))
    z = dict(np.load(path, allow_pickle=False))
    # torn tag case 1: tag names a WIDER dtype than the packed payload
    z1 = dict(z)
    z1["x_dtype"] = "bfloat16"
    np.savez(str(tmp_path / "torn1.npz"), **z1)
    with pytest.raises(ValueError, match="corrupt npz dtype tag"):
        InstanceDataset.restore(ctx, str(tmp_path / "torn1.npz"))
    # torn tag case 2: tag is garbage
    z2 = dict(z)
    z2["x_dtype"] = "float8_e4m3fnX"
    np.savez(str(tmp_path / "torn2.npz"), **z2)
    with pytest.raises(ValueError, match="corrupt npz dtype tag"):
        InstanceDataset.restore(ctx, str(tmp_path / "torn2.npz"))


def test_summarizer_dequantizes_fp8_moments(ctx, tier):
    from cycloneml_tpu.ml.stat import Summarizer
    tier("float8")
    rng = np.random.RandomState(24)
    x = rng.randn(1500, 4) * np.array([1.0, 8.0, 0.25, 3.0]) + 0.5
    ds = InstanceDataset.from_numpy(
        ctx, x, dtype=data_dtype(ctx.conf, fp8_capable=True))
    s = Summarizer.summarize(ds)
    assert s.count == 1500
    # moments are in VALUE space (scales folded in _finalize), at e4m3
    # resolution
    np.testing.assert_allclose(s.mean, x.mean(0), atol=0.1)
    np.testing.assert_allclose(s.std, x.std(0, ddof=0), rtol=0.1)
    np.testing.assert_allclose(s.max, x.max(0), rtol=0.08)
    np.testing.assert_allclose(s.min, x.min(0), rtol=0.08)


def test_logreg_fp8_vs_fp32_coef_parity(ctx, tier):
    from cycloneml_tpu.ml.classification import LogisticRegression
    rng = np.random.RandomState(25)
    n, d = 2000, 16
    x = rng.randn(n, d) * (1.0 + np.arange(d) / 4.0) + 0.3
    beta = rng.randn(d)
    y = (x @ beta + rng.randn(n) > 0).astype(np.float64)

    def fit(t):
        tier(t)
        return LogisticRegression(maxIter=80, regParam=0.01, tol=1e-10).fit(
            _fresh_frame(ctx, x, y))

    m32, m8 = fit("float32"), fit("float8")
    c32 = np.asarray(m32.coefficients.to_array())
    c8 = np.asarray(m8.coefficients.to_array())
    assert _norm_rel(c8, c32) < FP8_COEF_NORMREL, _norm_rel(c8, c32)
    # and the tier is genuinely 1-byte, not silently promoted
    ds8 = _fresh_frame(ctx, x, y).to_instance_dataset(
        "features", "label", fp8_capable=True)
    assert str(ds8.x.dtype) == "float8_e4m3fn"
    assert ds8.x_scale is not None


def test_linreg_fp8_vs_fp32_coef_parity(ctx, tier):
    from cycloneml_tpu.ml.regression import LinearRegression
    rng = np.random.RandomState(26)
    n, d = 2000, 12
    x = rng.randn(n, d) * 2.0 + 1.0
    beta = rng.randn(d)
    y = x @ beta + 0.05 * rng.randn(n)

    def fit(t):
        tier(t)
        return LinearRegression(maxIter=80, solver="l-bfgs",
                                regParam=0.001, tol=1e-10).fit(
            _fresh_frame(ctx, x, y))

    m32, m8 = fit("float32"), fit("float8")
    c32 = np.asarray(m32.coefficients.to_array())
    c8 = np.asarray(m8.coefficients.to_array())
    assert _norm_rel(c8, c32) < FP8_COEF_NORMREL, _norm_rel(c8, c32)


def test_fp8_sweep_accesses_under_45_percent_of_fp32_bytes(ctx, tier):
    """ISSUE-14 acceptance: the fp8 logistic sweep's bytes-accessed
    (XLA cost analysis, lower-only) lands under 0.45x the fp32 sweep at
    n=4096 d=256 — `make bench-bytes` gates the same ratio off-x64
    (measured ~0.35 there; the x64 config's f64 y/w overheads make the
    fp32 baseline heavier, so the measured ratio here is lower still)."""
    from cycloneml_tpu.observe import costs
    rng = np.random.RandomState(27)
    n, d = 4096, 256
    x = rng.randn(n, d)
    y = (rng.rand(n) > 0.5).astype(np.float64)

    def measure(t):
        tier(t)
        ds = InstanceDataset.from_numpy(
            ctx, x, y, dtype=data_dtype(ctx.conf, fp8_capable=True))
        f32 = np.float32
        cost = costs.sweep_cost(
            ds.tree_aggregate_fn(aggregators.binary_logistic_scaled(d, True)),
            jnp.ones(d, f32), jnp.zeros(d, f32), jnp.zeros(d + 1, f32),
            name=f"sweep8.{t}")
        return cost.bytes_accessed_total

    fp32_bytes = measure("float32")
    fp8_bytes = measure("float8")
    assert fp32_bytes and fp8_bytes
    ratio = fp8_bytes / fp32_bytes
    assert ratio < 0.45, (fp8_bytes, fp32_bytes, ratio)


def test_fp8_envelope_probe_triggers_bf16_fallback(ctx, tier):
    """The safety rail, end to end: an ill-conditioned feature (absmax
    >> std) makes the pre-fit probe decline e4m3; the fit falls back to
    bf16 storage, trains fine, and the decision surfaces as BOTH a
    PrecisionFallback event and the FitProfile.fp8_fallbacks field."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.observe import tracing
    from cycloneml_tpu.observe.profile import FitProfile
    from cycloneml_tpu.util.events import PrecisionFallback
    tier("float8")
    rng = np.random.RandomState(28)
    n, d = 800, 8
    x = rng.randn(n, d)
    x[:, 2] = 1000.0 + 0.01 * rng.randn(n)  # absmax/std ~ 1e5
    y = (x[:, 0] > 0).astype(np.float64)

    events = []
    ctx.listener_bus.add_listener(events.append)
    tracer = tracing.enable(max_spans=50_000)
    try:
        model = LogisticRegression(maxIter=25, regParam=0.01).fit(
            _fresh_frame(ctx, x, y))
        ctx.listener_bus.wait_until_empty()
        spans = tracer.snapshot()
    finally:
        tracing.disable()
        ctx.listener_bus.remove_listener(events.append)
    assert np.all(np.isfinite(np.asarray(model.coefficients.to_array())))
    fallbacks = [e for e in events if isinstance(e, PrecisionFallback)]
    assert len(fallbacks) == 1
    assert fallbacks[0].estimator == "LogisticRegression"
    assert fallbacks[0].from_dtype == "float8_e4m3fn"
    assert fallbacks[0].to_dtype == "bfloat16"
    assert "absmax/std" in fallbacks[0].reason
    profile = FitProfile.from_spans(spans)
    assert profile.fp8_fallbacks == 1
    # a well-scaled fit under the same tier does NOT fall back
    events2 = []
    ctx.listener_bus.add_listener(events2.append)
    try:
        x2 = rng.randn(n, d)
        y2 = (x2[:, 0] > 0).astype(np.float64)
        LogisticRegression(maxIter=25, regParam=0.01).fit(
            _fresh_frame(ctx, x2, y2))
        ctx.listener_bus.wait_until_empty()
    finally:
        ctx.listener_bus.remove_listener(events2.append)
    assert not [e for e in events2 if isinstance(e, PrecisionFallback)]


def test_fp8_probe_heuristics(ctx):
    from types import SimpleNamespace
    from cycloneml_tpu.dataset.instance import fp8_probe_ok
    good = SimpleNamespace(std=np.ones(3), max=np.full(3, 3.0),
                           min=np.full(3, -3.0))
    assert fp8_probe_ok(good) is None
    # constant columns are exempt (standardization drops them)
    const = SimpleNamespace(std=np.array([1.0, 0.0]),
                            max=np.array([3.0, 500.0]),
                            min=np.array([-3.0, 500.0]))
    assert fp8_probe_ok(const) is None
    bad = SimpleNamespace(std=np.array([1.0, 0.01]),
                          max=np.array([3.0, 100.0]),
                          min=np.array([-3.0, 99.0]))
    assert "absmax/std" in fp8_probe_ok(bad)
    # weight overflow: |w * residual| past e4m3's finite range
    assert "weight" in fp8_probe_ok(good, w_max=1000.0)


def test_fp8_generic_consumers_get_bf16(ctx, tier):
    """Structural safety: under the fp8 tiers, every consumer that has
    NOT declared fp8 capability materializes at the bf16 rung — raw
    e4m3 codes never reach an estimator that would read them as
    values — and a quantized dataset handed to a non-capable bridge
    dequantizes."""
    tier("float8")
    rng = np.random.RandomState(29)
    x = rng.randn(100, 4)
    ds = InstanceDataset.from_numpy(ctx, x)  # no explicit dtype
    assert str(ds.x.dtype) == "bfloat16"
    frame = _fresh_frame(ctx, x, (x[:, 0] > 0).astype(np.float64))
    assert str(frame.to_instance_dataset("features", "label").x.dtype) \
        == "bfloat16"
    # a quantized dataset through the non-capable bridge dequantizes
    ds8 = InstanceDataset.from_numpy(
        ctx, x, dtype=data_dtype(ctx.conf, fp8_capable=True))
    assert str(ds8.x.dtype) == "float8_e4m3fn"
    ds_view = ds8.to_instance_dataset()
    assert str(ds_view.x.dtype) == "bfloat16"
    assert ds_view.x_scale is None


def test_ovr_stacked_rides_fp8(ctx, tier):
    """OneVsRest under the fp8 tier: X stays e4m3 codes (shared via
    derive), the label stack rides the bf16 rung ({0,1} exact; fp8
    refuses implicit promotion by design), and the stacked fixed points
    stay within the fp8 envelope of the serial ones."""
    from cycloneml_tpu.ml.classification import LogisticRegression, OneVsRest
    tier("float8")
    rng = np.random.RandomState(30)
    n, d, k = 900, 10, 3
    centers = rng.randn(k, d) * 3.0
    y = rng.randint(0, k, n).astype(np.float64)
    x = centers[y.astype(int)] + rng.randn(n, d)
    frame = _fresh_frame(ctx, x, y)
    clf = LogisticRegression(maxIter=120, regParam=0.01, tol=1e-10)
    stacked = OneVsRest(classifier=clf, parallelism=k).fit(frame)
    serial = OneVsRest(classifier=clf, parallelism=1).fit(frame)
    for a, b in zip(stacked.models, serial.models):
        assert np.all(np.isfinite(a._coef))
        assert _norm_rel(a._coef, b._coef) < FP8_COEF_NORMREL


def test_fp8_streamed_fit_streams_codes(ctx, tier):
    """A quantized dataset routed to the streaming engine (oocore force
    mode / budget-guard degradation) keeps its e4m3 CODES on the shard
    set — the in-core envelope probe already admitted this data to the
    fp8 rung, the stream stages 1-byte codes, and the per-column dequant
    scale folds into the aggregator read exactly like the in-core fp8
    fit — so the streamed coefficients land ulp-close to the in-core fp8
    ones and the host→device byte bill stays halved. Only a
    ``streamDtype=bfloat16`` pin forces the codes back up, visibly
    (PrecisionFallback)."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.oocore import shard_set_cache
    from cycloneml_tpu.util.events import PrecisionFallback
    tier("float8")
    shard_set_cache().clear()
    rng = np.random.RandomState(31)
    n, d = 900, 6
    x = rng.randn(n, d) * np.array([1.0, 8.0, 0.5, 2.0, 1.0, 4.0])
    y = (x[:, 1] - x[:, 2] > 0).astype(np.float64)
    est = LogisticRegression(maxIter=40, regParam=0.01, tol=1e-10)
    m_incore = est.fit(_fresh_frame(ctx, x, y))
    events = []
    ctx.listener_bus.add_listener(events.append)
    ctx.conf.set("cyclone.oocore.mode", "force")
    try:
        m_streamed = est.fit(_fresh_frame(ctx, x, y))
        ctx.listener_bus.wait_until_empty()
        # the codes spilled AS codes: no precision fallback fired
        assert not [e for e in events if isinstance(e, PrecisionFallback)]
        assert m_streamed.summary.streamed
        # same codes, same set-level scale, same stats → the streamed
        # fit agrees with the in-core fp8 fit far inside the envelope
        c_in = np.asarray(m_incore.coefficients.to_array())
        c_st = np.asarray(m_streamed.coefficients.to_array())
        assert _norm_rel(c_st, c_in) < 1e-6, _norm_rel(c_st, c_in)
        # pinning the stream to the bf16 rung forces the codes up — the
        # dequant leaves the fp8 tier visibly, never silently
        ctx.conf.set("cyclone.oocore.streamDtype", "bfloat16")
        m_pinned = est.fit(_fresh_frame(ctx, x, y))
        ctx.listener_bus.wait_until_empty()
        assert any(isinstance(e, PrecisionFallback)
                   and e.estimator == "StreamingDataset.from_dataset"
                   for e in events)
        c_pin = np.asarray(m_pinned.coefficients.to_array())
        assert _norm_rel(c_pin, c_in) < FP8_COEF_NORMREL
    finally:
        ctx.conf.set("cyclone.oocore.mode", "auto")
        ctx.conf.remove("cyclone.oocore.streamDtype")
        ctx.listener_bus.remove_listener(events.append)
        shard_set_cache().clear()
