"""Model-server acceptance suite (ISSUE 8).

Pins the serving contract end to end: one XLA compile per shape bucket
(paid at registration, never by a request), window-bounded coalescing of
concurrent requests into one dispatch, admission control that queues/
sheds under a tiny memory budget instead of raising from XLA, gang
serving bit-equal to serial predicts, bucket-padding numeric parity, and
the ingestion surfaces (SQL scoring endpoint, streaming ScoringSink).

Compile-count determinism note: the serving program cache and jit's
per-shape cache are process-global, so every test here uses a DISTINCT
feature count (d) — a reused (d, dtype) shape would legitimately reuse an
earlier test's executable and report zero compiles.
"""

import threading
import time

import numpy as np
import pytest

from cycloneml_tpu.conf import CycloneConf
from cycloneml_tpu.ml.classification.logistic_regression import (
    LogisticRegressionModel,
)
from cycloneml_tpu.ml.regression.linear_regression import LinearRegressionModel
from cycloneml_tpu.observe import tracing
from cycloneml_tpu.serving import (
    ModelServer, ServingError, ServingOverloaded, as_servable, bucket_for,
    bucket_sizes, pad_rows,
)

rng = np.random.default_rng(7)


def _binary_lr(d, seed=0):
    r = np.random.default_rng(seed)
    return LogisticRegressionModel(r.normal(size=(1, d)),
                                   r.normal(size=(1,)), 2, False)


# -- buckets --------------------------------------------------------------------

def test_bucket_helpers():
    assert bucket_sizes(64) == (1, 2, 4, 8, 16, 32, 64)
    assert bucket_sizes(100) == (1, 2, 4, 8, 16, 32, 64, 128)
    assert bucket_for(1, 64) == 1
    assert bucket_for(33, 64) == 64
    assert bucket_for(100, 100) == 128
    with pytest.raises(ValueError):
        bucket_for(65, 64)
    with pytest.raises(ValueError):
        bucket_for(0, 64)
    x = np.ones((3, 2))
    p = pad_rows(x, 8)
    assert p.shape == (8, 2) and np.all(p[3:] == 0) and np.all(p[:3] == 1)
    assert pad_rows(x, 3) is x  # exact fit: no copy


# -- compile-once-per-bucket -----------------------------------------------------

def test_one_compile_per_bucket_never_per_request():
    """N concurrent mixed-row-count requests leave the compile ledger
    exactly where registration warm-up put it: one compile per bucket,
    pinned via the jit program cache size AND the warm-up compile spans."""
    d = 23  # unique to this test (see module docstring)
    tracer = tracing.enable()
    try:
        srv = ModelServer(ctx=None, max_batch=16, window_ms=2)
        srv.register("m", _binary_lr(d))
        lane = srv._lane("m")
        n_buckets = len(lane.buckets)
        assert lane.buckets == (1, 2, 4, 8, 16)
        compile_spans = [s for s in tracer.snapshot()
                         if s.kind == "compile" and s.name == "serving/m"]
        assert len(compile_spans) == n_buckets
        assert all(s.attrs.get("compiled") for s in compile_spans)
        assert srv.compile_counts()["m"] == n_buckets
        cache_after_warmup = lane._cache_size()

        errors = []

        def fire(n_rows):
            try:
                srv.predict("m", rng.normal(size=(n_rows, d)))
            except Exception as e:  # surfaced below
                errors.append(e)

        threads = [threading.Thread(target=fire, args=(n,))
                   for n in (1, 2, 3, 5, 7, 8, 11, 16, 1, 4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        # steady state: zero new compiles, by every ledger
        assert srv.compile_counts()["m"] == n_buckets
        assert lane._cache_size() == cache_after_warmup
        assert len([s for s in tracer.snapshot()
                    if s.kind == "compile"
                    and s.name == "serving/m"]) == n_buckets
        srv.stop()
    finally:
        tracing.disable()


# -- coalescing ------------------------------------------------------------------

def test_batcher_coalesces_concurrent_requests():
    d = 24
    srv = ModelServer(ctx=None, max_batch=64, window_ms=150)
    srv.register("m", _binary_lr(d))
    model = srv._lane("m").servable.model
    x = rng.normal(size=(2, d))
    ref = model._predict_batch(x)
    results, errors = [], []
    barrier = threading.Barrier(4)

    def fire():
        try:
            barrier.wait(timeout=10)
            results.append(srv.predict("m", x))
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=fire) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors and len(results) == 4
    for r in results:  # split-back correctness: everyone gets THEIR answer
        assert np.array_equal(r, ref)
    st = srv.stats()["models"]["m"]
    assert st["requests"] == 4
    # the 150 ms window coalesced barrier-released requests into fewer
    # dispatches, at least one of them carrying >= 2 requests
    assert st["batches"] < 4
    assert st["coalesced"] >= 2
    srv.stop()


# -- admission control -----------------------------------------------------------

def test_admission_queues_then_sheds_under_tiny_budget():
    """An impossible memory budget (budgetFraction over one byte of
    'device memory') must shed with a 503-style ServingOverloaded after
    queued patience — never a MemoryBudgetError (even under
    budgetAction=raise), never an XLA OOM, never a hang."""
    d = 25
    conf = (CycloneConf()
            .set("cyclone.memory.budgetFraction", 0.5)
            .set("cyclone.memory.deviceBytes", 1)
            .set("cyclone.memory.budgetAction", "raise"))
    srv = ModelServer(ctx=None, conf=conf, max_batch=8, window_ms=5,
                      shed_after_ms=80)
    srv.register("m", _binary_lr(d))
    lane = srv._lane("m")
    assert lane.pids, "budget conf must arm the warm-up cost harvest"
    t0 = time.perf_counter()
    with pytest.raises(ServingOverloaded) as ei:
        srv.predict("m", rng.normal(size=(3, d)), timeout=30)
    assert ei.value.status == 503
    assert time.perf_counter() - t0 < 20  # shed, not hung
    st = srv.stats()["models"]["m"]
    assert st["shed"] >= 1
    assert st["requeues"] >= 1  # it QUEUED (backpressure) before shedding
    assert st["batches"] == 0   # the over-budget program never dispatched
    srv.stop()


def test_admission_verdict_cached_and_harvest_shared(monkeypatch):
    """The requeue loop must not re-post MemoryBudgetExceeded every
    window: check_budget runs ONCE per bucket (verdict cached; only live
    occupancy re-samples). And a second same-signature model reuses the
    cost-registry entries — zero extra AOT analyze calls."""
    from cycloneml_tpu.observe import costs
    d = 19
    calls = []
    real = costs.check_budget

    def counting(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(costs, "check_budget", counting)
    conf = (CycloneConf()
            .set("cyclone.memory.budgetFraction", 0.5)
            .set("cyclone.memory.deviceBytes", 1))
    srv = ModelServer(ctx=None, conf=conf, max_batch=8, window_ms=2,
                      shed_after_ms=60)
    srv.register("a", _binary_lr(d, seed=1))
    before = costs.analyze_call_count()
    srv.register("b", _binary_lr(d, seed=2))   # same signature as "a"
    assert costs.analyze_call_count() == before  # registry entries reused
    with pytest.raises(ServingOverloaded):
        srv.predict("a", rng.normal(size=(2, d)), timeout=30)
    assert srv.stats()["models"]["a"]["requeues"] >= 1
    assert len(calls) == 1  # one verdict for the one touched bucket
    srv.stop()


def test_try_cancel_fails_queued_sibling():
    from cycloneml_tpu.serving.batcher import ModelLane
    d = 20
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0)
    srv.register("m", _binary_lr(d))
    # a lane whose worker never starts: submissions stay queued, which is
    # exactly the state predict()'s unwind path sees
    lane = ModelLane("probe", srv._lane("m").servable, srv)
    fut = lane.submit(np.zeros((2, d)))
    assert lane.try_cancel(fut)
    with pytest.raises(ServingOverloaded, match="shed as a unit"):
        fut.result(timeout=1)
    assert not lane.try_cancel(fut)  # already gone
    # a requeue racing stop() fails the futures instead of stranding them
    # in a dead lane (admission's _shed_or_requeue path)
    from cycloneml_tpu.serving.batcher import _Request
    req = _Request(np.zeros((1, d)))
    lane._stop = True
    lane._requeue_front([req])
    with pytest.raises(ServingOverloaded, match="stopped"):
        req.future.result(timeout=1)
    srv.stop()


def test_queue_full_backpressure_sheds_fast():
    d = 26
    from cycloneml_tpu.parallel.faults import FaultInjector, FaultSchedule
    sched = FaultSchedule(seed=0)
    # slow every dispatch so the queue can actually fill
    sched.window("serving.dispatch", 1, 1000, delay_s=0.05)
    srv = ModelServer(ctx=None, max_batch=1, window_ms=0, max_queue=2)
    srv.register("m", _binary_lr(d))
    outcomes = []

    def fire():
        try:
            srv.predict("m", rng.normal(size=(1, d)))
            outcomes.append("ok")
        except ServingOverloaded:
            outcomes.append("shed")

    with FaultInjector(sched):
        threads = [threading.Thread(target=fire) for _ in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    assert len(outcomes) == 12
    assert "shed" in outcomes   # bounded queue pushed back
    assert "ok" in outcomes     # while admitted requests kept serving
    srv.stop()


# -- gang serving ----------------------------------------------------------------

def test_gang_serving_matches_serial_predict():
    d, k = 27, 3
    models = [_binary_lr(d, seed=s) for s in range(k)]
    srv = ModelServer(ctx=None, max_batch=16, window_ms=2)
    info = srv.register_gang("gang", models)
    assert info["gang"] == k
    x = rng.normal(size=(9, d))
    preds = srv.predict("gang", x)
    assert isinstance(preds, list) and len(preds) == k
    for kk in range(k):
        assert np.array_equal(preds[kk], models[kk]._predict_batch(x))
    # one vmapped program: K models, ONE bucket set worth of compiles
    assert srv.compile_counts()["gang"] == len(bucket_sizes(16))
    srv.stop()


def test_gang_requires_homogeneous_models():
    from cycloneml_tpu.serving import GangServable
    with pytest.raises(ValueError, match="homogeneous"):
        GangServable([as_servable(_binary_lr(5)), as_servable(_binary_lr(6))])
    with pytest.raises(TypeError, match="no servable adapter"):
        as_servable(object())


def test_duplicate_and_oversize_guards():
    d = 18
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0)
    srv.register("m", _binary_lr(d))
    with pytest.raises(ValueError, match="already registered"):
        srv.register("m", _binary_lr(d))
    # a direct ModelLane.submit past maxBatch must fail, not wedge the
    # lane (ModelServer.predict pre-splits; this guards other callers)
    with pytest.raises(ValueError, match="exceeds maxBatch"):
        srv._lane("m").submit(np.zeros((9, d)))
    # the lane is still healthy afterwards
    x = rng.normal(size=(3, d))
    assert srv.predict("m", x).shape == (3,)
    srv.stop()


def test_stream_writer_custom_format_without_sink_rejected():
    from cycloneml_tpu.sql.session import CycloneSession
    from cycloneml_tpu.streaming.sources import MemoryStream
    s = CycloneSession()
    ms = MemoryStream(["f"])
    with pytest.raises(ValueError, match="unknown sink format"):
        ms.to_df(s).write_stream.format("custom").start()


# -- bucket-padding parity -------------------------------------------------------

def _bucketed_margins(lane, x, bucket, dtype):
    xpad = pad_rows(np.asarray(x, dtype=dtype), bucket)
    return np.asarray(lane.program(*lane._params, xpad))


@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_bucket_padding_parity_serial(dtype):
    """A row's margins are BITWISE identical whatever bucket carries it
    (n=1, n=bucket, n=bucket+1 all hit different programs), and the final
    predictions match direct model.predict: exactly for the thresholded
    labels, <= 1e-6 for the f32-tier scores against the float64 host
    reference (f64-tier scores match to accumulator precision)."""
    d = 28 if dtype == "float32" else 29
    model = _binary_lr(d, seed=3)
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0, dtype=dtype)
    srv.register("m", model)
    lane = srv._lane("m")
    B = 8
    x = rng.normal(size=(B + 1, d))
    # n=1 -> bucket 1, n=B -> bucket B (exact fit), n=B+1 -> split by the
    # batcher; compare the shared rows across ALL bucket programs
    m1 = _bucketed_margins(lane, x[:1], 1, dtype)
    mB = _bucketed_margins(lane, x[:B], B, dtype)
    m_pad = _bucketed_margins(lane, x[:3], B, dtype)[:3]  # padded dispatch
    assert np.array_equal(m1[0], mB[0])
    assert np.array_equal(mB[:3], m_pad)
    # the served predictions agree with the direct host predict
    preds = srv.predict("m", x)
    assert np.array_equal(preds, model._predict_batch(x))
    host = model._predict_batch(x)  # labels; margins below
    host_margins = lane.servable.host_margins(x)
    tol = 1e-6 if dtype == "float32" else 1e-12
    mfull = np.concatenate(
        [mB, _bucketed_margins(lane, x[B:], 1, dtype)])
    assert np.max(np.abs(mfull - host_margins)) <= tol * max(
        1.0, np.max(np.abs(host_margins)))
    assert host.shape == preds.shape
    srv.stop()


def test_bucket_padding_parity_bf16_tier_fit():
    """A model FIT under the bf16 data tier serves through the f32
    serving kernel within 1e-6 of its own host predict — the data tier
    narrows training storage, never serving numerics."""
    d = 30
    r = np.random.default_rng(5)
    # coefficients as a bf16-tier fit would leave them: float64 master
    # copies of values learned from bf16-stored data
    import jax.numpy as jnp
    coef = np.asarray(r.normal(size=(1, d)).astype(jnp.bfloat16),
                      dtype=np.float64)
    model = LogisticRegressionModel(coef, r.normal(size=(1,)), 2, False)
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0, dtype="float32")
    srv.register("m", model)
    lane = srv._lane("m")
    x = r.normal(size=(9, d))
    got = np.concatenate([
        _bucketed_margins(lane, x[:8], 8, "float32")[:8],
        _bucketed_margins(lane, x[8:], 1, "float32")])
    host = lane.servable.host_margins(x)
    assert np.max(np.abs(got - host)) <= 1e-6 * max(
        1.0, np.max(np.abs(host)))
    assert np.array_equal(srv.predict("m", x), model._predict_batch(x))
    srv.stop()


def test_bucket_padding_parity_stacked():
    """Stacked (gang) margins: bitwise bucket-invariant per row AND
    bitwise equal to the serial program's margins for every member."""
    d, k = 31, 3
    models = [_binary_lr(d, seed=10 + s) for s in range(k)]
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0, dtype="float32")
    srv.register_gang("g", models)
    for m_i, m in enumerate(models):
        srv.register(f"s{m_i}", m)
    glane = srv._lane("g")
    x = rng.normal(size=(9, d)).astype("float32")
    g1 = _bucketed_margins(glane, x[:1], 1, "float32")      # (k, 1, 1)
    g8 = _bucketed_margins(glane, x[:8], 8, "float32")      # (k, 8, 1)
    gpad = _bucketed_margins(glane, x[:3], 8, "float32")[:, :3, :]
    assert np.array_equal(g1[:, 0], g8[:, 0])
    assert np.array_equal(g8[:, :3], gpad)
    for m_i in range(k):
        slane = srv._lane(f"s{m_i}")
        serial = _bucketed_margins(slane, x[:8], 8, "float32")
        assert np.array_equal(g8[m_i], serial)
    # end to end: gang predictions == per-model serial predictions
    gp = srv.predict("g", x)
    for m_i in range(k):
        assert np.array_equal(gp[m_i], srv.predict(f"s{m_i}", x))
    srv.stop()


# -- servable coverage ------------------------------------------------------------

def test_multinomial_and_regression_servables():
    d, k = 13, 4
    r = np.random.default_rng(11)
    mn = LogisticRegressionModel(r.normal(size=(k, d)), r.normal(size=(k,)),
                                 k, True)
    reg = LinearRegressionModel(r.normal(size=(d,)), 0.25)
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0)
    srv.register("mn", mn)
    srv.register("reg", reg)
    x = r.normal(size=(6, d))
    assert np.array_equal(srv.predict("mn", x), mn._predict_batch(x))
    assert np.allclose(srv.predict("reg", x), reg._predict_batch(x),
                       rtol=0, atol=1e-9)
    # single-row convenience + empty batch
    assert srv.predict("reg", x[0]).shape == (1,)
    assert srv.predict("reg", np.zeros((0, d))).shape == (0,)
    with pytest.raises(ValueError, match="expects"):
        srv.predict("reg", np.zeros((2, d + 1)))
    with pytest.raises(KeyError, match="no model"):
        srv.predict("nope", x)
    srv.stop()


# -- observability ----------------------------------------------------------------

def test_request_spans_and_latency_metrics():
    from cycloneml_tpu.util.metrics import MetricsRegistry
    d = 32
    tracer = tracing.enable()
    try:
        # private registry: under the full suite an active session context
        # exists and ModelServer would otherwise share ITS registry, where
        # earlier serving tests already fed serving.latency
        srv = ModelServer(ctx=None, max_batch=8, window_ms=2,
                          registry=MetricsRegistry())
        srv.register("m", _binary_lr(d))
        srv.predict("m", rng.normal(size=(3, d)))
        spans = tracer.snapshot()
        batch_spans = [s for s in spans
                       if s.kind == "serving" and s.name == "m"]
        req_spans = [s for s in spans
                     if s.kind == "serving" and s.name == "request"]
        assert batch_spans and req_spans
        rs = req_spans[0]
        assert rs.parent_id == batch_spans[0].span_id
        assert rs.attrs["model"] == "m" and rs.attrs["rows"] == 3
        assert rs.attrs["queue_s"] >= 0 and rs.attrs["dispatch_s"] > 0
        assert rs.duration_s >= rs.attrs["dispatch_s"]
        lat = srv.registry.timer("serving.latency").snapshot()
        assert lat["count"] == 1 and lat["p99"] >= lat["p50"] > 0
        srv.stop()
    finally:
        tracing.disable()


def test_histogram_p99_and_prometheus_summary():
    from cycloneml_tpu.util.metrics import (
        MetricsRegistry, prometheus_text,
    )
    reg = MetricsRegistry()
    t = reg.timer("serving.latency")
    for i in range(100):
        t.update(i / 1000.0)
    snap = t.snapshot()
    assert snap["p50"] <= snap["p95"] <= snap["p99"] <= snap["max"]
    assert snap["p99"] == 0.098  # 99th of 0..99 ms
    text = prometheus_text(reg.values(), types=reg.types())
    assert 'cyclone_serving_latency{quantile="0.5"}' in text
    assert 'cyclone_serving_latency{quantile="0.99"} 0.098' in text
    # quantile components are consumed by the summary, not re-emitted flat
    assert "cyclone_serving_latency_p99" not in text


def test_serving_stats_reach_status_store(ctx):
    d = 14
    srv = ModelServer(ctx=ctx, max_batch=8, window_ms=2)
    srv.register("store-m", _binary_lr(d))
    srv.predict("store-m", rng.normal(size=(2, d)))
    srv.stop()  # force-posts the final rollup
    assert ctx.listener_bus.wait_until_empty(timeout=10)
    from cycloneml_tpu.util.status import api_v1
    stats = api_v1(ctx.status_store, "serving")
    assert "store-m" in stats["models"]
    m = stats["models"]["store-m"]
    assert m["requests"] >= 1 and m["compiles"] >= 1
    assert stats["totals"]["models"] >= 1
    assert m["latencyMs"]["p99"] >= m["latencyMs"]["p50"] > 0


# -- ingestion surfaces -----------------------------------------------------------

def test_sql_server_scoring_endpoint():
    from cycloneml_tpu.sql.server import CycloneSQLServer, SQLClient
    from cycloneml_tpu.sql.session import CycloneSession
    d = 15
    model = _binary_lr(d, seed=21)
    srv = ModelServer(ctx=None, max_batch=16, window_ms=2)
    srv.register("lr", model)
    session = CycloneSession()
    session.register_temp_view("t", session.create_data_frame(
        {"v": np.array([1.0, 2.0, 3.0])}))
    sql = CycloneSQLServer(session, model_server=srv)
    try:
        with SQLClient(sql.address) as c:
            x = rng.normal(size=(5, d))
            preds = c.predict("lr", x.tolist())
            assert preds == [float(v) for v in model._predict_batch(x)]
            assert c.predict("lr", []) == []  # empty payload, empty result
            # SQL and scoring share the connection and framing
            cols, rows = c.execute("SELECT COUNT(*) AS n FROM t")
            assert cols == ["n"] and rows == [[3]]
            with pytest.raises(RuntimeError, match="no model"):
                c.predict("nope", x.tolist())
            # the connection survives a scoring error
            assert c.predict("lr", x[:1].tolist())
    finally:
        sql.stop()
        srv.stop()


def test_sql_scoring_overload_maps_to_503():
    from cycloneml_tpu.sql.server import CycloneSQLServer, SQLClient
    from cycloneml_tpu.sql.session import CycloneSession
    d = 16
    conf = (CycloneConf()
            .set("cyclone.memory.budgetFraction", 0.5)
            .set("cyclone.memory.deviceBytes", 1))
    srv = ModelServer(ctx=None, conf=conf, max_batch=8, window_ms=2,
                      shed_after_ms=50)
    srv.register("lr", _binary_lr(d))
    sql = CycloneSQLServer(CycloneSession(), model_server=srv)
    try:
        with SQLClient(sql.address) as c:
            with pytest.raises(ServingOverloaded):
                c.predict("lr", rng.normal(size=(2, d)).tolist())
    finally:
        sql.stop()
        srv.stop()


def test_streaming_featurize_predict_sink_kafka():
    """Kafka source -> cast featurize -> ScoringSink -> memory: one
    streaming pipeline scoring through the same micro-batcher."""
    from types import SimpleNamespace

    from cycloneml_tpu.serving.streaming import ScoringSink
    from cycloneml_tpu.sql.column import col
    from cycloneml_tpu.sql.dataframe import DataFrame
    from cycloneml_tpu.sql.session import CycloneSession
    from cycloneml_tpu.streaming.kafka import KafkaSource
    from cycloneml_tpu.streaming.sinks import MemorySink
    from cycloneml_tpu.streaming.sources import StreamingScan

    class FakeConsumer:
        def __init__(self):
            self._pending = []
            self.committed = 0

        def feed(self, *records):
            self._pending.extend(records)

        def poll(self, timeout_ms=0):
            out, self._pending = {"tp0": list(self._pending)}, []
            return out

        def commit(self):
            self.committed += 1

    model = LinearRegressionModel(np.array([2.0]), 1.0)  # y = 2x + 1
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0)
    srv.register("m", model)
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    s = CycloneSession()
    df = DataFrame(StreamingScan(src, "kafka"), s)
    inner = MemorySink()
    sink = ScoringSink(srv, "m", ["f"], inner)
    q = (df.select(col("value").cast("double").alias("f"))
         .write_stream.sink_to(sink).start())
    try:
        consumer.feed(
            SimpleNamespace(key=b"a", value=b"1.5", topic="t", partition=0,
                            offset=0, timestamp=0),
            SimpleNamespace(key=b"b", value=b"-2.0", topic="t", partition=0,
                            offset=1, timestamp=0))
        q.process_all_available()
        batch = inner.to_batch()
        assert sorted(batch) == ["f", "prediction"]
        got = dict(zip(batch["f"], batch["prediction"]))
        assert got[1.5] == pytest.approx(4.0, abs=1e-9)
        assert got[-2.0] == pytest.approx(-3.0, abs=1e-9)
    finally:
        q.stop()
        srv.stop()


def test_streaming_scoring_sink_gang_and_empty():
    from cycloneml_tpu.serving.streaming import ScoringSink
    from cycloneml_tpu.streaming.sinks import MemorySink
    d, k = 17, 2
    models = [_binary_lr(d, seed=30 + s) for s in range(k)]
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0)
    srv.register_gang("g", models)
    inner = MemorySink()
    sink = ScoringSink(srv, "g", [f"f{i}" for i in range(d)], inner)
    x = rng.normal(size=(3, d))
    batch = {f"f{i}": x[:, i] for i in range(d)}
    sink.add_batch(0, batch, "append")
    sink.add_batch(1, {f"f{i}": np.array([]) for i in range(d)}, "append")
    out = inner.to_batch()
    for kk in range(k):
        assert np.array_equal(out[f"prediction.{kk}"],
                              models[kk]._predict_batch(x))
    srv.stop()


# -- quantized predict tier (cyclone.serving.quantize) ---------------------------

def test_quantized_predictions_within_envelope():
    """fp8 coefficient codes + per-row scales: regression margins agree
    with the unquantized server within e4m3's documented envelope (a few
    percent of the margin scale), and classification predictions agree
    away from the decision boundary."""
    from cycloneml_tpu.serving.servable import Servable
    d = 41
    r = np.random.default_rng(3)
    coef, icpt = r.normal(size=(1, d)), r.normal(size=(1,))
    x = r.normal(size=(13, d))
    srv_p = ModelServer(ctx=None, max_batch=16, window_ms=0)
    srv_p.register("m", Servable(None, coef, icpt, "scalar"))
    plain = srv_p.predict("m", x)
    srv_p.stop()
    srv_q = ModelServer(ctx=None, max_batch=16, window_ms=0, quantize=True)
    srv_q.register("m", Servable(None, coef, icpt, "scalar"))
    quant = srv_q.predict("m", x)
    assert srv_q.stats()["quantize"] is True
    assert srv_q.stats()["models"]["m"]["quantized"] is True
    srv_q.stop()
    scale = max(float(np.abs(plain).max()), 1e-9)
    assert float(np.abs(quant - plain).max()) / scale < 0.06


def test_quantized_bucket_padding_is_bitwise_stable():
    """The dequant multiply is per margin row — independent of the batch
    dim — so the bucket-padding bitwise-neutrality contract survives
    quantization: the same row scores identically in every bucket."""
    d = 43
    srv = ModelServer(ctx=None, max_batch=16, window_ms=0, quantize=True)
    srv.register("m", _binary_lr(d, seed=5))
    x = rng.normal(size=(5, d))
    whole = srv.predict("m", x)
    singles = np.concatenate([srv.predict("m", x[i:i + 1])
                              for i in range(len(x))])
    assert np.array_equal(whole, singles)
    srv.stop()


def test_quantized_gang_admits_more_models_per_budget():
    """THE admission acceptance: the quantized gang program's
    XLA-predicted per-bucket peak is strictly smaller, so a fixed HBM
    budget admits strictly more gang models — measured by the same
    observe/costs accounting the admission path consults."""
    import jax

    from cycloneml_tpu.observe import costs
    from cycloneml_tpu.serving.servable import (
        _quantize_rows, stacked_linear_margins,
        stacked_quantized_linear_margins,
    )
    r = np.random.default_rng(11)
    d, bucket = 128, 1

    def peak(k, quant):
        coefs, icpts = r.normal(size=(k, 1, d)), r.normal(size=(k, 1))
        x0 = np.zeros((bucket, d))
        if quant:
            q = _quantize_rows(coefs, icpts, np.float64)
            c = costs.analyze(jax.jit(stacked_quantized_linear_margins),
                              (*q, x0), name=f"t.adm.q{k}")
        else:
            c = costs.analyze(jax.jit(stacked_linear_margins),
                              (coefs, icpts, x0), name=f"t.adm.p{k}")
        return c.peak_bytes

    p_plain, p_quant = peak(16, False), peak(16, True)
    if not p_plain or not p_quant:
        pytest.skip("memory analysis unavailable on this backend")
    assert p_quant < p_plain
    budget = 4 * p_plain

    def admitted(quant):
        base, p17 = peak(1, quant), peak(17, quant)
        marginal = max((p17 - base) / 16.0, 1.0)
        return 1 + int((budget - base) // marginal)

    assert admitted(True) > admitted(False)


def test_quantized_gang_matches_plain_gang():
    """Gang quantized scoring: one vmapped program, per-model results
    within the envelope of the plain gang, same compile discipline (one
    compile per bucket, zero steady-state)."""
    d, k = 37, 4
    models = [_binary_lr(d, seed=20 + s) for s in range(k)]
    x = rng.normal(size=(6, d))
    srv_q = ModelServer(ctx=None, max_batch=8, window_ms=0, quantize=True)
    srv_q.register_gang("gq", models)
    before = srv_q.compile_counts()["gq"]
    assert before == len(bucket_sizes(8))
    preds = srv_q.predict("gq", x)
    assert srv_q.compile_counts()["gq"] == before  # zero steady compiles
    srv_q.stop()
    # margins (via model predict parity) — predictions may flip only at
    # the threshold; compare against each model's own margins instead
    for kk in range(k):
        m = models[kk]
        margins = x @ m._coef[0] + m._icpt[0]
        away = np.abs(margins) > 0.25  # away from the decision boundary
        ref = (margins > 0).astype(np.float64)
        assert np.array_equal(preds[kk][away], ref[away])


def test_retry_backoff_jitter_is_seeded_per_lane():
    """The dispatch retry backoff draws jitter from a per-lane rng seeded
    off the lane NAME — not the process-global ``random`` — so a chaos
    replay of a transient-failure schedule sees the identical sleep
    sequence in every process (str hash is salted across interpreters;
    the byte-sum seed is not). Pinned from a graftlint JX023 self-run
    finding."""
    import random

    from cycloneml_tpu.parallel.resilience import backoff_delay
    from cycloneml_tpu.serving.batcher import ModelLane

    d = 6
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0)
    srv.register("m", _binary_lr(d))
    try:
        a = ModelLane("probe", srv._lane("m").servable, srv)
        b = ModelLane("probe", srv._lane("m").servable, srv)
        other = ModelLane("probe2", srv._lane("m").servable, srv)
        seq = [backoff_delay(i, base_s=0.01, max_s=0.2, rng=a._rng)
               for i in range(6)]
        # same lane name -> identical jitter stream (replay determinism)
        assert seq == [backoff_delay(i, base_s=0.01, max_s=0.2, rng=b._rng)
                       for i in range(6)]
        # and it is exactly the documented name-derived seed
        ref = random.Random(sum(b"probe"))
        assert seq == [backoff_delay(i, base_s=0.01, max_s=0.2, rng=ref)
                       for i in range(6)]
        # distinct lanes decorrelate (no thundering-herd retries)
        assert seq != [backoff_delay(i, base_s=0.01, max_s=0.2,
                                     rng=other._rng) for i in range(6)]
    finally:
        srv.stop()
