"""Chunked device L-BFGS: trajectory parity with the host optimizer."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.ml.optim import LBFGS, aggregators
from cycloneml_tpu.ml.optim.device_lbfgs import DeviceLBFGS
from cycloneml_tpu.ml.optim.loss import (DistributedLossFunction,
                                         l2_regularization)


def _loss(ctx, n=400, d=12, seed=0, reg=0.0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    y = (x @ rng.randn(d) > 0).astype(np.float64)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    l2 = l2_regularization(reg, d, True, standardize=True) if reg else None
    return DistributedLossFunction(
        ds, aggregators.binary_logistic(d, fit_intercept=True), l2), d


def test_device_chunk_matches_host_trajectory(ctx):
    """Under the f64 CPU config the chunked program runs the SAME two-loop
    + Wolfe machine as the host path — final states must agree tightly."""
    for reg in (0.0, 0.1):
        host_f, d = _loss(ctx, seed=3, reg=reg)
        host = LBFGS(max_iter=30, tol=1e-10).minimize(host_f, np.zeros(d + 1))
        dev_f, _ = _loss(ctx, seed=3, reg=reg)
        dev = DeviceLBFGS(max_iter=30, tol=1e-10, chunk=8).minimize(
            dev_f, np.zeros(d + 1))
        np.testing.assert_allclose(dev.x, host.x, rtol=1e-7, atol=1e-9)
        np.testing.assert_allclose(dev.value, host.value, rtol=1e-10)
        assert dev.converged_reason == host.converged_reason
        # the whole point: far fewer dispatches than evaluations
        assert dev_f.n_dispatches < dev_f.n_evals
        assert dev_f.n_dispatches <= (dev.iteration // 8 + 2)


def test_device_chunk_loss_history_per_iteration(ctx):
    f, d = _loss(ctx, seed=5, reg=0.05)
    state = DeviceLBFGS(max_iter=12, tol=0.0, chunk=4).minimize(
        f, np.zeros(d + 1))
    # initial loss + one entry per iteration, monotone-ish decreasing
    assert len(state.loss_history) == state.iteration + 1
    assert state.loss_history[-1] < state.loss_history[0]


def test_device_chunk_respects_max_iter(ctx):
    f, d = _loss(ctx, seed=7)
    state = DeviceLBFGS(max_iter=5, tol=0.0, chunk=8).minimize(
        f, np.zeros(d + 1))
    assert state.iteration == 5
    assert state.converged_reason == "max iterations reached"


def test_device_chunk_resume_exact(ctx):
    """Chunk-boundary states carry the full curvature ring: resuming from
    one reproduces the uninterrupted trajectory."""
    f, d = _loss(ctx, seed=9, reg=0.02)
    opt = DeviceLBFGS(max_iter=24, tol=1e-12, chunk=4)
    full = opt.minimize(f, np.zeros(d + 1))
    f2, _ = _loss(ctx, seed=9, reg=0.02)
    it = opt.iterations(f2, np.zeros(d + 1))
    next(it)           # initial state
    mid = next(it)     # after one chunk
    f3, _ = _loss(ctx, seed=9, reg=0.02)
    resumed = opt.minimize(f3, np.zeros(d + 1), resume=mid)
    np.testing.assert_allclose(resumed.x, full.x, rtol=1e-8, atol=1e-10)


def test_lr_estimator_uses_device_chunk(ctx):
    from cycloneml_tpu.conf import LBFGS_DEVICE_CHUNK
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression
    rng = np.random.RandomState(11)
    x = rng.randn(300, 8)
    y = (x @ rng.randn(8) > 0).astype(np.float64)
    frame = MLFrame(ctx, {"features": x, "label": y})
    m1 = LogisticRegression(maxIter=40, regParam=0.05, tol=1e-9).fit(frame)
    assert m1.summary.total_dispatches < m1.summary.total_evals
    # disabling the chunk reproduces the same model via the host loop
    old = ctx.conf.get(LBFGS_DEVICE_CHUNK)
    ctx.conf.set(LBFGS_DEVICE_CHUNK, 0)
    try:
        m0 = LogisticRegression(maxIter=40, regParam=0.05, tol=1e-9).fit(frame)
    finally:
        ctx.conf.set(LBFGS_DEVICE_CHUNK, old)
    np.testing.assert_allclose(m1.coefficients.to_array(),
                               m0.coefficients.to_array(),
                               rtol=1e-6, atol=1e-9)
