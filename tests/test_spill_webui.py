"""Host-tier spill (ExternalAppendOnlyMap) + status web UI tests."""

import json
import os
import subprocess
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pytest

from cycloneml_tpu.dataset.spill import ExternalAppendOnlyMap, stable_hash


def test_stable_hash_deterministic_across_processes():
    """Partition assignment must not depend on PYTHONHASHSEED (the builtin
    hash is salted per process — the round-1 advisory)."""
    keys = ["alpha", "beta", ("k", 3), 42, 3.5]
    ours = [stable_hash(k) % 16 for k in keys]
    code = ("from cycloneml_tpu.dataset.spill import stable_hash;"
            "print([stable_hash(k) % 16 for k in "
            "['alpha', 'beta', ('k', 3), 42, 3.5]])")
    for seed in ("0", "12345"):
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={"PYTHONHASHSEED": seed, "PATH": "/usr/bin:/bin",
                 "PYTHONPATH": REPO},
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr[-500:]
        assert eval(out.stdout.strip()) == ours


def test_external_map_no_spill_matches_dict():
    m = ExternalAppendOnlyMap(row_budget=1000)
    for i in range(100):
        m.insert(i % 7, i)
    got = dict(m.items())
    assert m.spill_count == 0
    for k in range(7):
        assert got[k] == list(range(k, 100, 7))


def test_external_map_spills_and_merges(tmp_path):
    """Past the budget, sorted runs hit disk; items() must still yield each
    key exactly once with ALL its values."""
    m = ExternalAppendOnlyMap(row_budget=50, spill_dir=str(tmp_path))
    n, k = 1000, 13
    for i in range(n):
        m.insert(f"key{i % k}", i)
    assert m.spill_count >= n // 50 - 1
    got = dict(m.items())
    assert len(got) == k
    for j in range(k):
        assert sorted(got[f"key{j}"]) == list(range(j, n, k))
    # spill files are cleaned up after the merge
    assert not list(tmp_path.glob("spill-*"))


def test_external_map_mixed_key_types(tmp_path):
    m = ExternalAppendOnlyMap(row_budget=10, spill_dir=str(tmp_path))
    keys = [1, "one", (1, 2), 2.5]
    for rep in range(30):
        for key in keys:
            m.insert(key, rep)
    got = dict(m.items())
    assert set(got) == set(keys)
    for key in keys:
        assert sorted(got[key]) == list(range(30))


def test_group_by_key_spills_with_small_budget(ctx):
    """The dataset path spills under a small conf budget and produces the
    same groups as the in-memory path."""
    from cycloneml_tpu.conf import SHUFFLE_SPILL_ROW_BUDGET
    data = [(i % 5, i) for i in range(500)]
    old = ctx.conf.get(SHUFFLE_SPILL_ROW_BUDGET)
    ctx.conf.set(SHUFFLE_SPILL_ROW_BUDGET, 64)
    try:
        grouped = dict(ctx.parallelize(data, 4).group_by_key().collect())
    finally:
        ctx.conf.set(SHUFFLE_SPILL_ROW_BUDGET, old)
    assert set(grouped) == set(range(5))
    for k in range(5):
        assert sorted(grouped[k]) == list(range(k, 500, 5))


def test_reduce_by_key_unchanged(ctx):
    data = [("a", 1), ("b", 2), ("a", 3), ("b", 4)]
    out = dict(ctx.parallelize(data, 2).reduce_by_key(lambda x, y: x + y).collect())
    assert out == {"a": 4, "b": 6}


# -- web UI ---------------------------------------------------------------------

def test_webui_serves_page_and_api(ctx):
    ui = ctx.start_ui()
    try:
        page = urllib.request.urlopen(ui.url, timeout=5).read().decode()
        assert "Cyclone" in page and "/api/v1/" in page
        apps = json.loads(urllib.request.urlopen(
            ui.url + "api/v1/applications", timeout=5).read())
        assert apps and apps[0]["id"] == ctx.app_id
        jobs = json.loads(urllib.request.urlopen(
            ui.url + "api/v1/jobs", timeout=5).read())
        assert isinstance(jobs, list)
        with pytest.raises(Exception):
            urllib.request.urlopen(ui.url + "api/v1/nope", timeout=5)
        # idempotent: second call returns the same server
        assert ctx.start_ui() is ui
    finally:
        ui.stop()
        ctx._web_ui = None


def test_stable_hash_equal_keys_copartition():
    """1 == 1.0 == True must land in the same partition AND the same group
    (the builtin-hash invariant the stable hash must preserve)."""
    assert stable_hash(1) == stable_hash(1.0) == stable_hash(True)
    assert stable_hash(np.int64(3)) == stable_hash(3)
    m = ExternalAppendOnlyMap(row_budget=2)
    m.insert(1, "a"); m.insert(1.0, "b"); m.insert(True, "c")
    m.insert(2, "x")
    got = {k: sorted(v) for k, v in m.items()}
    assert len(got) == 2
    assert sorted(got[1]) == ["a", "b", "c"]


def test_mutually_recursive_views_rejected():
    from cycloneml_tpu.sql.session import CycloneSession
    s = CycloneSession()
    s.register_temp_view("emp", s.create_data_frame({"id": [1, 2]}))
    s.sql("CREATE VIEW a AS SELECT id FROM emp")
    s.sql("CREATE VIEW b AS SELECT id FROM a")
    with pytest.raises(ValueError, match="recursive"):
        s.sql("CREATE OR REPLACE VIEW a AS SELECT id FROM b")


def test_union_tail_on_first_branch_rejected():
    from cycloneml_tpu.sql.session import CycloneSession
    s = CycloneSession()
    s.register_temp_view("emp", s.create_data_frame({"id": [1, 2]}))
    with pytest.raises(ValueError, match="wrap the union"):
        s.sql("SELECT id FROM emp ORDER BY id UNION ALL SELECT id FROM emp")


def test_webui_bad_job_id_is_404(ctx):
    ui = ctx.start_ui()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(ui.url + "api/v1/jobs/abc", timeout=5)
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(ui.url + "api/v1/workers/oops", timeout=5)
    finally:
        ui.stop()
        ctx._web_ui = None
