"""Estimator-level sparse training: LogisticRegression.fit on the sparse
tier must match the dense estimator on identical data (the reference trains
on sparse vectors transparently; here fit() accepts a SparseInstanceDataset
directly)."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
from cycloneml_tpu.ml.classification import LogisticRegression
from tests.test_sparse import _random_sparse, _random_varlen_sparse  # noqa: E501


def _both(ctx, seed=0, n=300, d=30, hybrid=False):
    if hybrid:
        rows, dense, y, w = _random_varlen_sparse(n=n, d=d, seed=seed)
        sds = SparseInstanceDataset.from_rows_hybrid(
            ctx, rows, y=y, w=w, n_features=d, k_ell=8)
    else:
        rows, dense, y, w = _random_sparse(n=n, d=d, k=5, seed=seed)
        sds = SparseInstanceDataset.from_rows(ctx, rows, y=y, w=w,
                                              n_features=d)
    frame = MLFrame(ctx, {"features": dense, "label": y, "w": w})
    return sds, frame


@pytest.mark.parametrize("hybrid", [False, True])
def test_sparse_fit_matches_dense_fit(ctx, hybrid):
    sds, frame = _both(ctx, seed=3, d=30, hybrid=hybrid)
    lr = LogisticRegression(maxIter=60, regParam=0.05, tol=1e-10,
                            weightCol="w")
    dense_model = lr.fit(frame)
    sparse_model = lr.fit(sds)  # weights ride inside the dataset
    # the two tiers compute features_std through different f32 reduction
    # orders; the standardized-space penalty therefore differs in the last
    # few ulps, legitimately shifting the regularized optimum ~1e-3
    np.testing.assert_allclose(sparse_model.coefficients.to_array(),
                               dense_model.coefficients.to_array(),
                               rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(sparse_model.intercept, dense_model.intercept,
                               rtol=1e-2, atol=1e-3)
    # sparse fits are tracked jobs too
    assert sparse_model.summary.total_iterations > 0


def test_sparse_fit_elastic_net_and_bounds(ctx):
    sds, frame = _both(ctx, seed=7, d=24)
    # OWL-QN path: L1 drives coefficients to exact zeros on both tiers
    lr = LogisticRegression(maxIter=80, regParam=0.1, elasticNetParam=0.6,
                            weightCol="w", tol=1e-9)
    sm, dm = lr.fit(sds), lr.fit(frame)
    s_zero = sm.coefficients.to_array() == 0.0
    d_zero = dm.coefficients.to_array() == 0.0
    assert s_zero.any() and (s_zero == d_zero).mean() > 0.9
    # LBFGS-B path: nonnegative coefficients
    nn = LogisticRegression(maxIter=80, regParam=0.05, weightCol="w",
                            lowerBoundsOnCoefficients=np.zeros((1, 24)))
    m = nn.fit(sds)
    assert np.all(m.coefficients.to_array() >= -1e-9)


def test_sparse_fit_no_standardization(ctx):
    sds, frame = _both(ctx, seed=11, d=20)
    lr = LogisticRegression(maxIter=60, regParam=0.05, weightCol="w",
                            standardization=False, tol=1e-10)
    np.testing.assert_allclose(lr.fit(sds).coefficients.to_array(),
                               lr.fit(frame).coefficients.to_array(),
                               rtol=1e-2, atol=1e-4)


def test_sparse_fit_rejects_multinomial(ctx):
    rows, dense, y, w = _random_sparse(n=60, d=10, k=3, seed=1)
    y3 = (np.arange(60) % 3).astype(float)
    sds = SparseInstanceDataset.from_rows(ctx, rows, y=y3, n_features=10)
    with pytest.raises(NotImplementedError, match="binomial"):
        LogisticRegression(maxIter=5).fit(sds)


def test_sparse_fit_checkpoints_and_resumes(ctx, tmp_path):
    """checkpointDir works on the sparse path too (shared optimize tail)."""
    sds, _ = _both(ctx, seed=13, d=16)
    ck = str(tmp_path / "ck")
    full = LogisticRegression(maxIter=30, regParam=0.02, tol=1e-11,
                              weightCol="w").fit(sds)
    LogisticRegression(maxIter=4, regParam=0.02, tol=1e-11, weightCol="w",
                       checkpointDir=ck, checkpointInterval=1).fit(sds)
    resumed = LogisticRegression(maxIter=30, regParam=0.02, tol=1e-11,
                                 weightCol="w", checkpointDir=ck,
                                 checkpointInterval=1).fit(sds)
    np.testing.assert_allclose(resumed.coefficients.to_array(),
                               full.coefficients.to_array(),
                               rtol=1e-6, atol=1e-8)


def test_sparse_fit_binomial_family_rejects_multiclass(ctx):
    rows, dense, y, w = _random_sparse(n=60, d=10, k=3, seed=2)
    y3 = (np.arange(60) % 3).astype(float)
    sds = SparseInstanceDataset.from_rows(ctx, rows, y=y3, n_features=10)
    with pytest.raises(ValueError, match="Binomial family"):
        LogisticRegression(maxIter=5, family="binomial").fit(sds)


@pytest.mark.slow
def test_criteo_class_end_to_end(tmp_path, monkeypatch):
    """BASELINE config-1 analog at committed-test scale: synthetic
    hashed-sparse libsvm (~0.25 GB) -> streamed bounded-memory ELL ingest
    -> sparse-tier LR fit -> AUC, with the driver's ingest staging bounded
    (the full-size 2 GB run is recorded in BASELINE.md's round-3 ledger).
    Runs examples/criteo_class_demo.py verbatim — the demo IS the test."""
    import io
    import runpy
    import sys
    monkeypatch.setenv("CRITEO_DEMO_PATH", str(tmp_path / "criteo.svm"))
    monkeypatch.setattr(sys, "argv", ["criteo_class_demo", "0.25", "19"])
    out = io.StringIO()
    from contextlib import redirect_stdout
    with redirect_stdout(out):
        runpy.run_path("examples/criteo_class_demo.py", run_name="__main__")
    text = out.getvalue()
    assert "AUC=" in text, text
    auc = float(text.split("AUC=")[1].split()[0].rstrip(","))
    assert auc > 0.65, text
