"""Chaos harness: seeded fault injection driven through the real train loop.

Five injected fault classes (ISSUE 2 acceptance), each deterministic under a
fixed seed, each either RECOVERED (train_with_checkpoints lands on the same
final state as a fault-free run) or CLEANLY ABORTED (a loud, classified
error) — never silently wrong:

1. transient collective failure  -> backoff + stream rebuild, exact recovery
2. device loss                   -> MeshSupervisor mesh rebuild + re-shard
                                    + resume-from-checkpoint
3. mid-save crash                -> atomic-commit contract: no corrupt
                                    checkpoint visible; resume recovers
4. corrupt latest checkpoint     -> checksum fallback to newest verifiable
5. heartbeat-driven worker loss  -> receiver expiry feeds the same recovery
                                    path as step failures

Plus the TCP leg (injected connection resets must not kill a worker) and
the determinism contract of the schedule itself.
"""

import os
import time

import numpy as np
import pytest

from cycloneml_tpu.ml.optim.lbfgs import LBFGS
from cycloneml_tpu.parallel.faults import (DeviceLostError, FaultInjector,
                                           FaultSchedule,
                                           InjectedConnectionReset,
                                           MidSaveCrash,
                                           TransientCollectiveError)
from cycloneml_tpu.parallel.resilience import (HeartbeatReceiver,
                                               MeshDegradedError,
                                               MeshSupervisor,
                                               train_with_checkpoints)
from cycloneml_tpu.util.checkpoint import CheckpointCorrupt, TrainingCheckpointer


def _quadratic(d=6, seed=3):
    rng = np.random.RandomState(seed)
    a = rng.randn(d, d)
    h = a @ a.T + d * np.eye(d)
    b = rng.randn(d)

    def f(x):
        return 0.5 * x @ h @ x - b @ x, h @ x - b

    return f, np.zeros(d)


def _logistic_problem(ctx, n=256, d=6, seed=0):
    """Distributed logistic loss over the ctx mesh — every evaluation is a
    real tree_aggregate dispatch through the collectives.step injection
    point."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.ml.optim.loss import DistributedLossFunction

    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    y = (x @ rng.randn(d) > 0).astype(np.float64)
    ds = InstanceDataset.from_numpy(ctx, x, y)

    def make_loss(dataset):
        return DistributedLossFunction(
            dataset, aggregators.binary_logistic(d, fit_intercept=False))

    return ds, make_loss, np.zeros(d)


# -- fault class 1: transient collective failure --------------------------------

def test_transient_collective_failure_recovers(ctx, tmp_path):
    """Flaky DCN hops at scheduled dispatches: the loop backs off, rebuilds
    the stream from the last good state, and lands EXACTLY on the fault-free
    trajectory — twice, identically, under the same seed."""
    ds, make_loss, x0 = _logistic_problem(ctx)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(ds), x0)

    runs = []
    for attempt in ("a", "b"):  # same seed twice: the determinism contract
        sched = FaultSchedule(seed=7)
        sched.at("collectives.step", [4, 5],
                 TransientCollectiveError("injected DCN flake"))
        ck = TrainingCheckpointer(str(tmp_path / f"ck-{attempt}"))
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=30, tol=1e-9), make_loss(ds), x0, ck,
                interval=5, max_step_failures=3, backoff_base_s=0.001,
                seed=7)
        assert [(p, n) for p, n, _ in inj.log] == \
            [("collectives.step", 4), ("collectives.step", 5)]
        runs.append(final)
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-10)
        assert final.iteration == baseline.iteration

    np.testing.assert_array_equal(runs[0].x, runs[1].x)
    assert runs[0].loss_history == runs[1].loss_history


def test_slow_step_fault_delays_but_does_not_corrupt(ctx):
    """A delay fault (degraded interconnect) slows the step without
    changing the result."""
    ds, make_loss, x0 = _logistic_problem(ctx)
    loss = make_loss(ds)
    want = loss(x0)
    sched = FaultSchedule().at("collectives.step", 1, delay_s=0.2)
    t0 = time.monotonic()
    with FaultInjector(sched) as inj:
        got = loss(x0)
    assert time.monotonic() - t0 >= 0.2
    assert inj.log == [("collectives.step", 1, "SlowStep")]
    assert got[0] == want[0]
    np.testing.assert_array_equal(got[1], want[1])


# -- fault class 2: device loss -> mesh rebuild ---------------------------------

def test_device_loss_rebuilds_mesh_and_resumes(ctx, tmp_path):
    """A lost worker's DeviceLostError mid-step: the supervisor clears the
    program cache, rebuilds local-mesh[4] over the survivors, re-shards the
    dataset from its checkpoint, and training resumes from the optimizer
    checkpoint — same answer as the undisturbed 8-device run."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset

    ds8, make_loss, x0 = _logistic_problem(ctx)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(ds8), x0)
    data_ck = str(tmp_path / "data")
    ds8.checkpoint(data_ck)
    opt_ck = TrainingCheckpointer(str(tmp_path / "opt"))

    sup = ctx.mesh_supervisor(
        worker_devices={"h0": 4, "h1": 4},
        on_rebuild=lambda rt: make_loss(InstanceDataset.restore(ctx, data_ck)))
    sched = FaultSchedule(seed=1)
    sched.at("collectives.step", 12,
             DeviceLostError("ICI link down", lost_workers=["h1"]))
    try:
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=30, tol=1e-9), make_loss(ds8), x0, opt_ck,
                interval=2, supervisor=sup, backoff_base_s=0.001, seed=1)
        assert inj.log == [("collectives.step", 12, "DeviceLostError")]
        assert sup.rebuilds == 1
        assert "h1" in sup.lost_workers()
        assert ctx.mesh_runtime.n_devices == 4  # degraded but alive
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5, atol=1e-8)
        assert final.iteration == baseline.iteration
    finally:
        ctx.rebuild_mesh("local-mesh[8]")  # restore fixture invariant


def test_device_loss_without_supervisor_aborts_cleanly(tmp_path):
    """No supervisor: device loss burns the transient budget and aborts
    with the classified step-failure error, never spinning forever."""
    f, x0 = _quadratic()
    calls = {"n": 0}

    def lossy(x):
        calls["n"] += 1
        if calls["n"] >= 4:
            raise DeviceLostError("slice gone")
        return f(x)

    ck = TrainingCheckpointer(str(tmp_path))
    with pytest.raises(RuntimeError, match="failed 2 times"):
        train_with_checkpoints(LBFGS(max_iter=30, tol=1e-10), lossy, x0, ck,
                               interval=2, max_step_failures=2,
                               backoff_base_s=0.0)


def test_mesh_rebuild_budget_exhaustion(ctx, tmp_path):
    """Device loss recurring past max_rebuilds must abort with
    MeshDegradedError instead of thrashing rebuilds forever."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset

    ds8, make_loss, x0 = _logistic_problem(ctx)
    data_ck = str(tmp_path / "data")
    ds8.checkpoint(data_ck)
    sup = MeshSupervisor(
        ctx, worker_devices={"h0": 4, "h1": 4}, max_rebuilds=1,
        on_rebuild=lambda rt: make_loss(InstanceDataset.restore(ctx, data_ck)))
    sched = FaultSchedule(seed=2)
    # inv 6 kills the first mesh; inv 7 is the rebuilt loss's weight-sum
    # dispatch inside recover(), so the relapse window starts at 8 — the
    # first TRAINING dispatch on the rebuilt mesh
    sched.at("collectives.step", 6,
             DeviceLostError("flapping link", lost_workers=["h1"]))
    sched.window("collectives.step", 8, 10_000,
                 DeviceLostError("flapping link", lost_workers=["h1"]))
    try:
        with FaultInjector(sched):
            with pytest.raises(MeshDegradedError, match="max_rebuilds"):
                train_with_checkpoints(
                    LBFGS(max_iter=30, tol=1e-9), make_loss(ds8), x0,
                    TrainingCheckpointer(str(tmp_path / "opt")), interval=2,
                    supervisor=sup, backoff_base_s=0.0, seed=2)
        assert sup.rebuilds == 1
    finally:
        ctx.rebuild_mesh("local-mesh[8]")


# -- fault class 3: mid-save crash ----------------------------------------------

def test_mid_save_crash_never_leaves_corrupt_checkpoint(tmp_path):
    """Crash between writing checkpoint files and the commit rename: the
    run aborts, the half-written step is INVISIBLE, and a resumed run lands
    on the fault-free answer."""
    f, x0 = _quadratic(d=8, seed=11)
    baseline = LBFGS(max_iter=40, tol=1e-12).minimize(f, x0)
    ck = TrainingCheckpointer(str(tmp_path), keep_last=5)

    sched = FaultSchedule().at("checkpoint.commit", 2,
                               MidSaveCrash("power cut mid-save"))
    with FaultInjector(sched) as inj:
        with pytest.raises(MidSaveCrash):
            train_with_checkpoints(LBFGS(max_iter=40, tol=1e-12), f, x0, ck,
                                   interval=2)
    assert inj.log == [("checkpoint.commit", 2, "MidSaveCrash")]
    assert ck.steps() == [2]  # the crashed save (step 4) never surfaced
    leftovers = [n for n in os.listdir(tmp_path) if ".tmp" in n]
    assert not leftovers  # no orphaned tmp dirs either
    assert ck.verify(2)

    final = train_with_checkpoints(LBFGS(max_iter=40, tol=1e-12), f, x0, ck,
                                   interval=2)
    np.testing.assert_allclose(final.x, baseline.x, rtol=1e-12, atol=1e-12)
    assert final.loss_history == pytest.approx(baseline.loss_history)


# -- fault class 4: corrupt latest checkpoint -----------------------------------

def test_corrupt_latest_checkpoint_falls_back_to_verifiable(tmp_path):
    """Truncate the newest committed checkpoint after the fact (bit rot /
    torn disk): resume detects the checksum mismatch, falls back to the
    newest VERIFIABLE step, and still converges to the fault-free answer."""
    f, x0 = _quadratic(d=10, seed=5)
    baseline = LBFGS(max_iter=50, tol=1e-12).minimize(f, x0)
    ck = TrainingCheckpointer(str(tmp_path), keep_last=5)
    final = train_with_checkpoints(LBFGS(max_iter=50, tol=1e-12), f, x0, ck,
                                   interval=2)
    latest = ck.latest_step()
    assert latest == final.iteration and len(ck.steps()) >= 2

    pkl = os.path.join(tmp_path, f"step_{latest:012d}", "state.pkl")
    with open(pkl, "r+b") as fh:  # truncate to half: commit happened, then rot
        fh.truncate(os.path.getsize(pkl) // 2)

    assert not ck.verify(latest)
    with pytest.raises(CheckpointCorrupt, match="checksum mismatch"):
        ck.restore(latest)
    fallback = ck.latest_verifiable_step()
    assert fallback is not None and fallback < latest
    ck.restore()  # step=None walks back to the verifiable one — no raise

    resumed = train_with_checkpoints(LBFGS(max_iter=50, tol=1e-12), f, x0,
                                     ck, interval=2)
    np.testing.assert_allclose(resumed.x, baseline.x, rtol=1e-12, atol=1e-12)
    assert resumed.iteration == baseline.iteration


def test_all_checkpoints_corrupt_aborts_loudly(tmp_path):
    """When every checkpoint fails verification, resuming must raise
    CheckpointCorrupt — not silently restart from scratch."""
    f, x0 = _quadratic()
    ck = TrainingCheckpointer(str(tmp_path), keep_last=3)
    train_with_checkpoints(LBFGS(max_iter=40, tol=1e-12), f, x0, ck,
                           interval=2)
    for step in ck.steps():
        pkl = os.path.join(tmp_path, f"step_{step:012d}", "state.pkl")
        with open(pkl, "wb") as fh:
            fh.write(b"garbage")
    with pytest.raises(CheckpointCorrupt, match="failed verification"):
        train_with_checkpoints(LBFGS(max_iter=40, tol=1e-12), f, x0, ck,
                               interval=2)


# -- fault class 5: heartbeat-driven worker loss --------------------------------

def test_heartbeat_worker_loss_triggers_recovery(ctx, tmp_path):
    """The liveness leg: a worker stops heartbeating mid-training, the
    receiver expires it, the supervisor picks the loss up BEFORE the next
    step and runs the same rebuild+resume path — same final answer."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset

    ds8, make_loss, x0 = _logistic_problem(ctx)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(ds8), x0)
    data_ck = str(tmp_path / "data")
    ds8.checkpoint(data_ck)
    opt_ck = TrainingCheckpointer(str(tmp_path / "opt"))

    recv = HeartbeatReceiver(timeout_s=0.05)  # swept manually: deterministic
    sup = MeshSupervisor(
        ctx, worker_devices={"h0": 4, "h1": 4},
        on_rebuild=lambda rt: make_loss(InstanceDataset.restore(ctx, data_ck))
    ).attach(recv)
    recv.register("h0")
    recv.register("h1")

    tripped = {"done": False}

    def maybe_kill_h1(s):
        if s.iteration == 6 and not tripped["done"]:
            tripped["done"] = True
            time.sleep(0.06)        # both workers go stale...
            recv.heartbeat("h0")    # ...h0's ping arrives in time...
            recv.check_now()        # ...h1 is expired -> supervisor notified

    try:
        final = train_with_checkpoints(
            LBFGS(max_iter=30, tol=1e-9), make_loss(ds8), x0, opt_ck,
            interval=2, on_step=maybe_kill_h1, supervisor=sup,
            backoff_base_s=0.001, seed=3)
        assert tripped["done"]
        assert sup.rebuilds == 1
        assert "h1" in sup.lost_workers()
        assert sup.health.is_excluded("h1") is False  # one strike so far
        assert ctx.mesh_runtime.n_devices == 4
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5, atol=1e-8)
        assert final.iteration == baseline.iteration
    finally:
        ctx.rebuild_mesh("local-mesh[8]")


# -- the TCP leg: injected connection resets ------------------------------------

def test_heartbeat_connection_resets_do_not_kill_worker():
    """Scheduled connection resets on the sender's pings: the sender
    retries at the next interval (the production contract for a flaky
    driver link) and the worker never expires."""
    from cycloneml_tpu.parallel.resilience import (HeartbeatSender,
                                                   HeartbeatServer)

    recv = HeartbeatReceiver(timeout_s=5.0)
    server = HeartbeatServer(recv)
    sched = FaultSchedule()
    sched.window("heartbeat.send", 2, 4,
                 InjectedConnectionReset("peer reset"))
    try:
        with FaultInjector(sched) as inj:
            sender = HeartbeatSender("w0", server.address, interval_s=0.05)
            deadline = time.time() + 5
            while inj.counts.get("heartbeat.send", 0) < 6:
                assert time.time() < deadline
                time.sleep(0.02)
            sender.stop()
        assert [(p, n) for p, n, _ in inj.log] == [
            ("heartbeat.send", 2), ("heartbeat.send", 3),
            ("heartbeat.send", 4)]
        assert recv.live_workers() == ["w0"]  # survived all three resets
        assert not recv.lost_workers()
    finally:
        server.stop()


# -- schedule determinism --------------------------------------------------------

def test_probabilistic_schedule_is_deterministic_under_seed():
    """A probabilistic fault window replays the identical fire pattern for
    the same seed — the property every chaos test above leans on."""
    from cycloneml_tpu.parallel import faults

    def drive(seed):
        sched = FaultSchedule(seed=seed)
        sched.window("p", 1, 40, TransientCollectiveError("x"), p=0.35)
        fired = []
        with FaultInjector(sched) as inj:
            for i in range(40):
                try:
                    faults.inject("p")
                except TransientCollectiveError:
                    fired.append(i)
        assert [n for _, n, _ in inj.log] == [i + 1 for i in fired]
        return fired

    a, b = drive(seed=123), drive(seed=123)
    assert a == b and 0 < len(a) < 40  # fired some, not all


def test_injector_installs_exclusively():
    inj = FaultInjector(FaultSchedule())
    with inj:
        with pytest.raises(RuntimeError, match="already installed"):
            FaultInjector(FaultSchedule()).__enter__()
    # uninstalled on exit: a fresh injector can install now
    with FaultInjector(FaultSchedule()):
        pass


# -- serving dispatch faults (ISSUE 8) ------------------------------------------

def _serving_fixture(d, **kw):
    from cycloneml_tpu.ml.classification.logistic_regression import (
        LogisticRegressionModel,
    )
    from cycloneml_tpu.serving import ModelServer
    r = np.random.default_rng(0)
    model = LogisticRegressionModel(r.normal(size=(1, d)),
                                    r.normal(size=(1,)), 2, False)
    srv = ModelServer(ctx=None, max_batch=8, window_ms=0, **kw)
    srv.register("m", model)
    return srv, model


def test_serving_transient_dispatch_fault_is_retried():
    """A DCN-flake-class fault on serving.dispatch is retried with
    backoff: the request still gets the CORRECT answer, and the retry is
    visible in both the injector log and the lane's retry ledger."""
    d = 41
    srv, model = _serving_fixture(d)
    sched = FaultSchedule(seed=0)
    sched.at("serving.dispatch", 1,
             TransientCollectiveError("injected serving flake"))
    x = np.random.default_rng(1).normal(size=(3, d))
    with FaultInjector(sched) as inj:
        preds = srv.predict("m", x, timeout=30)
    assert np.array_equal(preds, model._predict_batch(x))
    assert inj.log == [("serving.dispatch", 1, "TransientCollectiveError")]
    st = srv.stats()["models"]["m"]
    assert st["retries"] >= 1 and st["requests"] == 1
    srv.stop()


def test_serving_permanent_dispatch_fault_sheds_5xx_never_hangs():
    """A permanent fault (broken step function class: TypeError) must NOT
    be retried: every request in the batch fails fast with a 5xx
    ServingError carrying the cause — and the lane stays alive for the
    next request. A hang here would strand client futures forever."""
    from cycloneml_tpu.serving import ServingError
    d = 42
    srv, model = _serving_fixture(d)
    sched = FaultSchedule(seed=0)
    sched.at("serving.dispatch", 1, TypeError("injected broken dispatch"))
    x = np.random.default_rng(2).normal(size=(2, d))
    t0 = time.perf_counter()
    with FaultInjector(sched) as inj:
        with pytest.raises(ServingError) as ei:
            srv.predict("m", x, timeout=30)
        assert 500 <= ei.value.status < 600
        assert isinstance(ei.value.cause, TypeError)
        assert time.perf_counter() - t0 < 10  # shed, not hung
        # not retried: exactly one injection, zero retry ledger entries
        assert len(inj.log) == 1
        assert srv.stats()["models"]["m"]["retries"] == 0
        # the worker survived and keeps serving
        preds = srv.predict("m", x, timeout=30)
    assert np.array_equal(preds, model._predict_batch(x))
    srv.stop()


def test_serving_transient_faults_exhaust_to_5xx():
    """Transient faults past cyclone.serving.maxRetries stop retrying and
    shed with a 5xx — bounded recovery, no infinite retry loop."""
    from cycloneml_tpu.serving import ServingError
    d = 43
    srv, model = _serving_fixture(d, max_retries=2)
    sched = FaultSchedule(seed=0)
    sched.at("serving.dispatch", [1, 2, 3, 4],
             TransientCollectiveError("persistent flake"))
    x = np.random.default_rng(3).normal(size=(1, d))
    with FaultInjector(sched) as inj:
        with pytest.raises(ServingError) as ei:
            srv.predict("m", x, timeout=30)
    assert 500 <= ei.value.status < 600
    assert len(inj.log) == 3  # initial attempt + maxRetries, then shed
    srv.stop()


# -- out-of-core shard staging faults (ISSUE 11) --------------------------------

def _oocore_fixture(ctx, n=1200, d=6, seed=9, shard_rows=400):
    from cycloneml_tpu.oocore import StreamingDataset
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    y = (x[:, 0] + x[:, 1] > 0).astype(float)

    def chunks():
        for lo in range(0, n, 300):
            yield x[lo:lo + 300], y[lo:lo + 300], None

    return StreamingDataset.from_chunks(ctx, chunks(), d,
                                        shard_rows=shard_rows)


def test_oocore_transient_stage_fault_retries_mid_epoch(ctx):
    """A transient shard-staging failure (DCN flake class) retries with
    backoff MID-EPOCH: the streamed fit completes and lands on the exact
    fault-free coefficients — the retry re-stages the same shard, so the
    epoch's accumulated partials are untouched."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    sds = _oocore_fixture(ctx)
    try:
        ref = LogisticRegression(maxIter=8, regParam=0.1).fit(sds)
        sched = FaultSchedule(seed=0)
        sched.at("oocore.stage", 2,
                 TransientCollectiveError("mid-epoch transfer flake"))
        with FaultInjector(sched) as inj:
            m = LogisticRegression(maxIter=8, regParam=0.1).fit(sds)
        assert inj.log == [("oocore.stage", 2, "TransientCollectiveError")]
        assert m.summary.streamed
        np.testing.assert_array_equal(np.asarray(m._coef),
                                      np.asarray(ref._coef))
    finally:
        sds.close()


def test_oocore_permanent_stage_fault_aborts_cleanly(ctx):
    """A permanent staging failure aborts the epoch LOUDLY: the original
    error surfaces on the consumer, the prefetch queue is drained (device
    shard refs released) and the staging thread exits — never a hang,
    never a leaked thread — and the shard set stays usable afterwards."""
    import threading

    from cycloneml_tpu.ml.classification import LogisticRegression
    sds = _oocore_fixture(ctx)
    try:
        sched = FaultSchedule(seed=0)
        sched.at("oocore.stage", 2, TypeError("injected corrupt shard"))
        with FaultInjector(sched) as inj:
            with pytest.raises(TypeError, match="corrupt shard"):
                LogisticRegression(maxIter=8, regParam=0.1).fit(sds)
        assert inj.log == [("oocore.stage", 2, "TypeError")]
        deadline = time.time() + 5.0
        while time.time() < deadline and any(
                t.name.startswith("cyclone-oocore")
                for t in threading.enumerate()):
            time.sleep(0.05)
        assert not any(t.name.startswith("cyclone-oocore")
                       for t in threading.enumerate())
        # state released, stream machinery reusable: a fault-free fit runs
        m = LogisticRegression(maxIter=4, regParam=0.1).fit(sds)
        assert m.summary.streamed
    finally:
        sds.close()


def test_oocore_transient_faults_exhaust_to_abort(ctx):
    """Transient staging faults past cyclone.oocore.maxRetries stop
    retrying and abort — bounded recovery, no infinite retry loop; the
    injector ledger pins initial attempt + maxRetries firings."""
    from cycloneml_tpu.conf import OOCORE_MAX_RETRIES
    from cycloneml_tpu.ml.classification import LogisticRegression
    sds = _oocore_fixture(ctx)
    try:
        sched = FaultSchedule(seed=0)
        sched.window("oocore.stage", 1, 100,
                     TransientCollectiveError("persistent flake"))
        with FaultInjector(sched) as inj:
            with pytest.raises(TransientCollectiveError):
                LogisticRegression(maxIter=8, regParam=0.1).fit(sds)
        max_retries = int(ctx.conf.get(OOCORE_MAX_RETRIES))
        assert len(inj.log) == 1 + max_retries
    finally:
        sds.close()


def test_oocore_fp8_transient_stage_fault_retries_bitwise(ctx):
    """The fp8 stream under a transient staging fault (ISSUE 19): the
    retry re-stages the SAME 1-byte e4m3 codes with the same set-level
    dequant scale, so the epoch's accumulated partials are untouched and
    the fit lands bitwise on the fault-free coefficients — precision
    rung and recovery path compose."""
    import ml_dtypes

    from cycloneml_tpu.ml.classification import LogisticRegression
    ctx.conf.set("cyclone.oocore.streamDtype", "float8")
    try:
        sds = _oocore_fixture(ctx)
        try:
            assert sds.x_dtype == np.dtype(ml_dtypes.float8_e4m3fn)
            assert sds.x_scale is not None
            ref = LogisticRegression(maxIter=8, regParam=0.1).fit(sds)
            sched = FaultSchedule(seed=0)
            sched.at("oocore.stage", 2,
                     TransientCollectiveError("fp8 stream flake"))
            with FaultInjector(sched) as inj:
                m = LogisticRegression(maxIter=8, regParam=0.1).fit(sds)
            assert inj.log == [("oocore.stage", 2,
                                "TransientCollectiveError")]
            assert m.summary.streamed
            np.testing.assert_array_equal(np.asarray(m._coef),
                                          np.asarray(ref._coef))
        finally:
            sds.close()
    finally:
        ctx.conf.remove("cyclone.oocore.streamDtype")


def test_oocore_corrupt_cached_shard_evicts_and_rebuilds(ctx):
    """Shard-set cache integrity (ISSUE 19): a cached spill whose bytes
    rot on disk (torn write, disk rot — injected here by flipping bytes
    in a shard file directly) must never be trained on. The attach-time
    per-shard sha256 check catches the mismatch, evicts the entry,
    rebuilds from the source dataset, and the fit completes bitwise on
    the clean-spill coefficients."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.oocore import shard_dataset, shard_set_cache

    cache = shard_set_cache()
    cache.clear()
    rng = np.random.RandomState(10)
    x = rng.randn(1000, 6)
    y = (x[:, 0] > 0).astype(float)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    st0 = cache.stats()
    first = shard_dataset(ds, shard_rows=300)
    ref = LogisticRegression(maxIter=8, regParam=0.1).fit(first)
    victim = first._shards[1].path
    first.close()   # ref released; the entry stays cached
    with open(victim, "r+b") as fh:
        fh.seek(64)
        fh.write(b"\xff" * 32)
    again = shard_dataset(ds, shard_rows=300)
    try:
        st = cache.stats()
        assert st["evictionsCorrupt"] == st0["evictionsCorrupt"] + 1
        assert st["hits"] == st0["hits"]          # the rot never served
        assert st["misses"] == st0["misses"] + 2  # build + rebuild
        assert not os.path.exists(victim)         # corrupt files removed
        m = LogisticRegression(maxIter=8, regParam=0.1).fit(again)
        np.testing.assert_array_equal(np.asarray(m._coef),
                                      np.asarray(ref._coef))
    finally:
        again.close()
        cache.clear()


# -- fault class 6: whole-HOST loss (multihost.host) ----------------------------

def test_host_loss_rebuilds_mesh_and_resumes(ctx, tmp_path):
    """Seeded chaos host loss (ISSUE 13 acceptance): a HostLostError at
    the ``multihost.host`` fault point mid-fit — the chaos stand-in for a
    killed worker process — runs the whole recovery: flight-ring dump
    PRE-teardown, program-cache clear, mesh rebuild over the surviving
    host's devices, re-shard, resume-from-checkpoint; the resumed fit's
    coefficients match an uninterrupted run at the documented parity
    tolerance (docs/multihost.md)."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.observe import flight
    from cycloneml_tpu.parallel.faults import HostLostError

    ds8, make_loss, x0 = _logistic_problem(ctx)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(ds8), x0)
    data_ck = str(tmp_path / "data")
    ds8.checkpoint(data_ck)
    opt_ck = TrainingCheckpointer(str(tmp_path / "opt"))

    sup = ctx.mesh_supervisor(
        worker_devices={"w0": 4, "w1": 4},
        worker_hosts={"w0": "hostA", "w1": "hostB"},
        on_rebuild=lambda rt: make_loss(InstanceDataset.restore(ctx, data_ck)))
    sched = FaultSchedule(seed=7)
    sched.at("multihost.host", 9,
             HostLostError("host hostB unreachable", lost_hosts=["hostB"]))
    from cycloneml_tpu.observe import tracing
    own_ring = tracing.active() is None  # an earlier test may have
    # disabled the ctx-installed flight ring; the dump pin needs one
    if own_ring:
        flight.enable()
    flight.reset()
    flight.configure(min_interval_s=0.0)  # the fault fires a dump first;
    # un-throttle so the recovery's own pre-teardown dump is visible too
    try:
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=30, tol=1e-9), make_loss(ds8), x0, opt_ck,
                interval=2, supervisor=sup, backoff_base_s=0.001, seed=7)
        assert inj.log == [("multihost.host", 9, "HostLostError")]
        assert sup.rebuilds == 1
        # host granularity: the whole host and its worker are casualties
        assert "hostB" in sup.lost_hosts()
        assert "w1" in sup.lost_workers()
        assert "hostA" not in sup.lost_hosts()
        assert ctx.mesh_runtime.n_devices == 4  # survivors only
        # flight recorder satellite: host-loss recovery dumped the ring
        # PRE-teardown, exactly like device-loss recovery
        reasons = [d["reason"] for d in flight.dumps()]
        assert "mesh.rebuild" in reasons
        rebuild_dump = next(d for d in flight.dumps()
                            if d["reason"] == "mesh.rebuild")
        assert rebuild_dump["attrs"]["lost_hosts"] == "hostB"
        assert rebuild_dump["n_spans"] >= 1
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5, atol=1e-8)
        assert final.iteration == baseline.iteration
    finally:
        flight.configure(min_interval_s=1.0)
        if own_ring:
            flight.disable()
        ctx.rebuild_mesh("local-mesh[8]")  # restore fixture invariant


def test_host_loss_via_heartbeat_marks_whole_host(ctx):
    """note_host_lost (the missed-heartbeat-host path) marks every worker
    the host ran, feeds the health tracker, and arms pending recovery —
    without touching the mesh until the training thread recovers."""
    sup = MeshSupervisor(
        ctx, worker_devices={"w0": 2, "w1": 2, "w2": 4},
        worker_hosts={"w0": "hostA", "w1": "hostA", "w2": "hostB"})
    sup.note_worker_lost("w0", "no heartbeat")
    # hostA still has w1 alive: not a whole-host loss yet
    assert "hostA" not in sup.lost_hosts()
    assert sup.surviving_devices() == 6
    sup.note_host_lost("hostA", "host unreachable")
    assert sup.lost_hosts() == {"hostA": "host unreachable"}
    assert set(sup.lost_workers()) == {"w0", "w1"}
    assert sup.surviving_devices() == 4
    assert sup.pending_loss() is not None


# -- elastic meshes (ISSUE 15): scale, drain, re-dispatch ------------------------

def _elastic_problem(ctx, n=256, d=6, seed=0):
    """Problem whose dataset can be rebuilt from LIVE host memory on
    whatever mesh is active — the in-place re-shard hook (no checkpoint
    anywhere on the path)."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.ml.optim.loss import DistributedLossFunction

    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    y = (x @ rng.randn(d) > 0).astype(np.float64)

    def make_loss(_rt=None):
        ds = InstanceDataset.from_numpy(ctx, x, y)
        return DistributedLossFunction(
            ds, aggregators.binary_logistic(d, fit_intercept=False))

    return make_loss, np.zeros(d)


def test_elastic_scale_down_then_up_resumes_in_place(ctx, tmp_path):
    """THE ISSUE-15 acceptance e2e: a seeded `elastic.capacity` event
    scales the mesh 8 -> 4 mid-fit, a second one scales it back 4 -> 8;
    each lands at a SAFE step boundary, re-shards the live optimizer
    state + dataset through memory, and resumes IN PLACE. Zero
    checkpoint restores anywhere on the path (the chaos point counts
    them), and the final coefficients match the uninterrupted 8-device
    run at the documented tolerance."""
    from cycloneml_tpu.elastic import capacity as ecap

    make_loss, x0 = _elastic_problem(ctx)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(), x0)

    chan = ecap.channel()
    chan.clear()
    sup = MeshSupervisor(ctx, on_reshard=lambda rt: make_loss(rt),
                         capacity=chan, max_reshapes=4)
    sched = FaultSchedule(seed=5)
    sched.at("elastic.capacity", 6,
             ecap.scale_to("local-mesh[4]", reason="capacity reclaimed"))
    sched.at("elastic.capacity", 14,
             ecap.scale_to("local-mesh[8]", reason="replacement slice up"))
    try:
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=30, tol=1e-9), make_loss(), x0,
                TrainingCheckpointer(str(tmp_path / "opt")), interval=5,
                supervisor=sup, backoff_base_s=0.001, seed=5)
        # both transitions fired at their seeded boundaries, nothing else
        assert [(p, n) for p, n, _ in inj.log] == \
            [("elastic.capacity", 6), ("elastic.capacity", 14)]
        assert sup.reshapes == 2
        assert sup.rebuilds == 0          # planned, not a failure
        # IN PLACE: the reshape path never touched a checkpoint
        assert inj.counts.get("checkpoint.restore", 0) == 0
        assert ctx.mesh_runtime.n_devices == 8  # scaled back up
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5,
                                   atol=1e-8)
        assert final.iteration == baseline.iteration
    finally:
        chan.clear()
        ctx.rebuild_mesh("local-mesh[8]")


def test_elastic_pure_reshard_matches_at_ulp(ctx, tmp_path):
    """The pure-reshard leg: a capacity event onto the SAME shape (a
    replacement slice) moves state through the host bounce and recompiled
    programs only — under the f64 test config the resumed trajectory is
    ulp-identical to the uninterrupted run, proving the reshard itself
    adds no numeric drift."""
    from cycloneml_tpu.elastic import capacity as ecap

    make_loss, x0 = _elastic_problem(ctx, seed=3)
    baseline = LBFGS(max_iter=25, tol=1e-9).minimize(make_loss(), x0)

    chan = ecap.channel()
    chan.clear()
    sup = MeshSupervisor(ctx, on_reshard=lambda rt: make_loss(rt),
                         capacity=chan)
    sched = FaultSchedule(seed=11)
    sched.at("elastic.capacity", 5,
             ecap.scale_to("local-mesh[8]", reason="slice replacement"))
    try:
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=25, tol=1e-9), make_loss(), x0,
                TrainingCheckpointer(str(tmp_path / "opt")), interval=5,
                supervisor=sup, backoff_base_s=0.001, seed=11)
        assert [(p, n) for p, n, _ in inj.log] == [("elastic.capacity", 5)]
        assert sup.reshapes == 1
        assert inj.counts.get("checkpoint.restore", 0) == 0
        np.testing.assert_array_max_ulp(final.x, baseline.x, maxulp=2)
        assert final.iteration == baseline.iteration
    finally:
        chan.clear()
        ctx.rebuild_mesh("local-mesh[8]")


def test_preempt_notice_drain_resumes_from_handoff(ctx, tmp_path):
    """Preemption-aware draining: a PreemptionNotice at the
    `multihost.preempt_notice` point (the tpu decommission signal's CPU
    stand-in) triggers a flight dump + in-memory state handoff BEFORE
    teardown; the rebuild over the survivors resumes from the drained
    state — zero checkpoint restores — and matches the uninterrupted
    run."""
    from cycloneml_tpu.observe import flight, tracing
    from cycloneml_tpu.parallel.faults import PreemptionNotice

    make_loss, x0 = _elastic_problem(ctx, seed=7)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(), x0)

    sup = MeshSupervisor(
        ctx, worker_devices={"w0": 4, "w1": 4},
        worker_hosts={"w0": "hostA", "w1": "hostB"},
        on_rebuild=lambda rt: make_loss(rt))
    sched = FaultSchedule(seed=7)
    sched.at("multihost.preempt_notice", 9,
             PreemptionNotice("slice hostB scheduled for reclaim",
                              lost_hosts=["hostB"], drain_window_s=60.0))
    own_ring = tracing.active() is None
    if own_ring:
        flight.enable()
    flight.reset()
    flight.configure(min_interval_s=0.0)
    try:
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=30, tol=1e-9), make_loss(), x0,
                TrainingCheckpointer(str(tmp_path / "opt")), interval=2,
                supervisor=sup, backoff_base_s=0.001, seed=7)
        assert inj.log == \
            [("multihost.preempt_notice", 9, "PreemptionNotice")]
        assert sup.rebuilds == 1           # the drain's rebuild
        assert sup.drain_resumes == 1 and sup.drain_expired == 0
        # resumed from the in-memory handoff, not a checkpoint
        assert inj.counts.get("checkpoint.restore", 0) == 0
        assert "hostB" in sup.lost_hosts()
        assert ctx.mesh_runtime.n_devices == 4
        # the drain froze the flight ring BEFORE teardown
        reasons = [d["reason"] for d in flight.dumps()]
        assert "preempt.drain" in reasons
        drain_dump = next(d for d in flight.dumps()
                          if d["reason"] == "preempt.drain")
        assert drain_dump["attrs"]["hosts"] == "hostB"
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5,
                                   atol=1e-8)
        assert final.iteration == baseline.iteration
    finally:
        flight.configure(min_interval_s=1.0)
        if own_ring:
            flight.disable()
        ctx.rebuild_mesh("local-mesh[8]")


def test_preempt_drain_window_expired_falls_back_to_checkpoint(ctx,
                                                               tmp_path):
    """The drain-window contract: a notice whose window has already
    expired (drain_window_s=0) DISCARDS the handed-off state — stale
    drained state is never silently resumed — and recovery falls back to
    the newest VERIFIABLE checkpoint (the restore chaos point counts
    exactly that), still landing on the uninterrupted answer."""
    from cycloneml_tpu.parallel.faults import PreemptionNotice

    make_loss, x0 = _elastic_problem(ctx, seed=9)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(), x0)

    sup = MeshSupervisor(
        ctx, worker_devices={"w0": 4, "w1": 4},
        worker_hosts={"w0": "hostA", "w1": "hostB"},
        on_rebuild=lambda rt: make_loss(rt))
    sched = FaultSchedule(seed=9)
    sched.at("multihost.preempt_notice", 9,
             PreemptionNotice("hostB reclaimed NOW", lost_hosts=["hostB"],
                              drain_window_s=0.0))
    try:
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=30, tol=1e-9), make_loss(), x0,
                TrainingCheckpointer(str(tmp_path / "opt")), interval=2,
                supervisor=sup, backoff_base_s=0.001, seed=9)
        assert inj.log == \
            [("multihost.preempt_notice", 9, "PreemptionNotice")]
        assert sup.drain_expired == 1 and sup.drain_resumes == 0
        # the fallback really read a checkpoint
        assert inj.counts.get("checkpoint.restore", 0) >= 1
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5,
                                   atol=1e-8)
        assert final.iteration == baseline.iteration
    finally:
        ctx.rebuild_mesh("local-mesh[8]")


def test_elastic_straggler_lane_redispatch_first_result_wins(ctx):
    """Straggler re-dispatch e2e (Spark speculation): a seeded chaos
    delay slows one oocore shard lane until the detector latches it;
    `supervisor.stragglers()` feeds the armed Speculator, the lane's
    NEXT staging re-dispatches a concurrent duplicate, the first result
    wins and the duplicate dedups BITWISE — and the fit's numbers are
    bit-identical to the unspeculated run."""
    from cycloneml_tpu.elastic import speculation
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.observe import skew
    from cycloneml_tpu.oocore.objective import StreamingLossFunction

    det = skew.SkewDetector(window=32, min_samples=4)
    prev = skew.install(det)
    sds = _oocore_fixture(ctx)   # 1200 rows / 400-row shards = 3 lanes
    sup = MeshSupervisor(ctx).attach_skew(det)
    sp = speculation.Speculator(sup.stragglers)
    speculation.install(sp)
    try:
        n_shards = sds.n_shards
        assert n_shards == 3
        d = 6
        loss = StreamingLossFunction(
            sds, aggregators.binary_logistic(d, fit_intercept=False))
        coef = np.zeros(d)
        ref = loss(coef)         # clean epoch: the bitwise reference
        epochs = 8
        # staging walks shards in order: delaying invocations 3, 6, 9...
        # (1-based, counted from the injector install) slows EXACTLY the
        # shard-2 lane every epoch
        sched = FaultSchedule(seed=0)
        sched.at("oocore.stage",
                 range(n_shards, epochs * n_shards + 1, n_shards), None,
                 delay_s=0.03)
        with FaultInjector(sched) as inj:
            for _ in range(epochs):
                loss(coef)
        assert len(inj.log) == epochs
        # detection latched and reached the supervisor's mitigation input
        assert "oocore.stage:shard2" in sup.stragglers()
        # the NEXT epoch re-dispatches the latched lane's staging
        out = loss(coef)
        st = sp.stats()
        lanes = [r["lane"] for r in st["re_dispatches"]]
        assert "oocore.stage:shard2" in lanes
        # the losing duplicate dedups off the critical path — poll
        deadline = time.time() + 5.0
        while sp.stats()["dedup_hits"] < 1 and time.time() < deadline:
            time.sleep(0.01)
        st = sp.stats()
        assert st["dedup_hits"] >= 1      # duplicate deduped bitwise
        assert st["mismatches"] == 0
        # first-result-wins changed NOTHING: bit-identical epoch numbers
        assert out[0] == ref[0]
        np.testing.assert_array_equal(out[1], ref[1])
    finally:
        speculation.uninstall(sp)
        sp.close()
        skew.uninstall(det)
        if prev is not None:
            skew.install(prev)
        sds.close()


def test_elastic_max_reshapes_budget_exhaustion(ctx, tmp_path):
    """Capacity events past max_reshapes abort with MeshDegradedError —
    a flapping autoscaler is refused loudly, exactly as a flapping mesh
    is, WITHOUT eating the failure-recovery rebuild budget."""
    from cycloneml_tpu.elastic import capacity as ecap

    make_loss, x0 = _elastic_problem(ctx, seed=4)
    chan = ecap.channel()
    chan.clear()
    sup = MeshSupervisor(ctx, on_reshard=lambda rt: make_loss(rt),
                         capacity=chan, max_reshapes=1)
    sched = FaultSchedule(seed=4)
    sched.at("elastic.capacity", 4, ecap.scale_to("local-mesh[4]"))
    sched.at("elastic.capacity", 8, ecap.scale_to("local-mesh[8]"))
    try:
        with FaultInjector(sched):
            with pytest.raises(MeshDegradedError, match="max_reshapes"):
                train_with_checkpoints(
                    LBFGS(max_iter=30, tol=1e-9), make_loss(), x0,
                    TrainingCheckpointer(str(tmp_path / "opt")),
                    interval=5, supervisor=sup, backoff_base_s=0.001,
                    seed=4)
        assert sup.reshapes == 1
        assert sup.rebuilds == 0   # the reshape budget is its own
    finally:
        chan.clear()
        ctx.rebuild_mesh("local-mesh[8]")


# -- the autoscale control plane (ISSUE 17): sensors -> policy -> actuator ------

def test_autoscale_closed_loop_scales_up_on_slo_breach(ctx, tmp_path):
    """THE ISSUE-17 acceptance e2e, fully closed loop: an injected
    step-SLO breach latches in the skew detector, the autoscaler (ticked
    deterministically at every safe step boundary with LOGICAL time)
    accumulates the hysteresis streak, decides scale-up, ACQUIRES the
    platform's 8 visible devices within the bounded deadline, announces
    on the capacity channel — and the supervisor reshapes 4 -> 8 at that
    same boundary with zero checkpoint restores and rtol<=1e-5 parity.
    The breach then PERSISTS: cooldown bounds the re-decide rate, the
    second decision's acquire (wanting >8 devices) expires to a graceful
    no-op, the third attempt hits the decision budget and degrades to
    ONE latched warn-hold — so the whole flapping run costs 1 reshape
    against a max_reshapes=4 budget that is never threatened."""
    from cycloneml_tpu.elastic import capacity as ecap
    from cycloneml_tpu.elastic.autoscale import Autoscaler
    from cycloneml_tpu.elastic.policy import AutoscalePolicy
    from cycloneml_tpu.observe.skew import SkewDetector

    ctx.rebuild_mesh("local-mesh[4]")
    make_loss, x0 = _elastic_problem(ctx, seed=7)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(), x0)

    chan = ecap.channel()
    chan.clear()
    det = SkewDetector(slo_s={"collectives.step": 0.05}, min_samples=2)
    policy = AutoscalePolicy(scale_up_after=2, cooldown_ms=3000,
                             max_decisions=2, seed=7)
    auto = Autoscaler(policy, channel=chan, detector=det,
                      used_fn=lambda: ctx.mesh_runtime.n_devices,
                      acquire_timeout_s=0.05)
    sup = MeshSupervisor(ctx, on_reshard=lambda rt: make_loss(rt),
                         capacity=chan, max_reshapes=4)

    def _drive(point, invocation, **info):
        # the sensor leg: healthy step times for 2 boundaries, then a
        # sustained breach; the SLO latch holds while samples stay over
        # target, so the policy streak measures real persistence
        det.observe("collectives.step", "prog",
                    0.2 if invocation >= 3 else 0.001)
        auto.tick(now_ms=invocation * 1000)

    sched = FaultSchedule(seed=7)
    sched.window("elastic.capacity", 1, 99, _drive)
    try:
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=30, tol=1e-9), make_loss(), x0,
                TrainingCheckpointer(str(tmp_path / "opt")), interval=5,
                supervisor=sup, backoff_base_s=0.001, seed=7)
        # the policy's whole life, pinned: breach at t3/t4 -> scale-up
        # (applied, 4->8); persisting breach re-decides after cooldown
        # -> scale-up whose acquire expires (no 9th device exists);
        # budget exhausted -> one warn-hold; then silence
        assert [d.action for d in policy.log] == \
            ["scale-up", "scale-up", "warn-hold"]
        assert [d.t_ms for d in policy.log] == [4000, 7000, 10000]
        assert policy.decisions_applied == 2
        assert sup.reshapes == 1           # one real mesh change
        assert sup.rebuilds == 0           # planned, not a failure
        assert inj.counts.get("checkpoint.restore", 0) == 0
        assert ctx.mesh_runtime.n_devices == 8
        assert len(chan) == 0              # nothing left un-consumed
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5,
                                   atol=1e-8)
        assert final.iteration == baseline.iteration
    finally:
        auto.stop()
        chan.clear()
        ctx.rebuild_mesh("local-mesh[8]")


def test_autoscale_decide_faults_drop_duplicate_delay(ctx, tmp_path):
    """The controller-misbehaving leg: the seeded `autoscale.decide`
    point drops the first decision (the loop survives and re-decides
    after cooldown), DUPLICATES the second (two announcements -> a real
    4->8 reshape plus a same-shape reshape, both absorbed), and delays
    the third (which then gracefully times out its acquire) — training
    still lands on baseline parity with zero checkpoint restores, and
    straggler pressure (not SLO this time) is the breach signal."""
    from cycloneml_tpu.elastic import capacity as ecap
    from cycloneml_tpu.elastic.autoscale import (Autoscaler, drop_decision,
                                                 duplicate_decision)
    from cycloneml_tpu.elastic.policy import AutoscalePolicy
    from cycloneml_tpu.observe.skew import SkewDetector

    ctx.rebuild_mesh("local-mesh[4]")
    make_loss, x0 = _elastic_problem(ctx, seed=9)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(), x0)

    chan = ecap.channel()
    chan.clear()
    det = SkewDetector(min_samples=2, window=8)
    policy = AutoscalePolicy(scale_up_after=2, cooldown_ms=2000,
                             max_decisions=3, seed=9)
    auto = Autoscaler(policy, channel=chan, detector=det,
                      used_fn=lambda: ctx.mesh_runtime.n_devices,
                      acquire_timeout_s=0.05)
    sup = MeshSupervisor(ctx, on_reshard=lambda rt: make_loss(rt),
                         capacity=chan, max_reshapes=4)

    def _drive(point, invocation, **info):
        # three fit lanes, one persistently slow: the straggler verdict
        # latches once medians exist (boundary 2) and holds — sustained
        # training pressure, the tentpole's second signal leg
        det.observe("fit.lane", "a", 0.01)
        det.observe("fit.lane", "c", 0.01)
        det.observe("fit.lane", "b", 0.2)
        auto.tick(now_ms=invocation * 1000)

    sched = FaultSchedule(seed=9)
    sched.at("autoscale.decide", 1, drop_decision)
    sched.at("autoscale.decide", 2, duplicate_decision)
    sched.at("autoscale.decide", 3, None, delay_s=0.01)
    sched.window("elastic.capacity", 1, 99, _drive)
    try:
        with FaultInjector(sched) as inj:
            final = train_with_checkpoints(
                LBFGS(max_iter=30, tol=1e-9), make_loss(), x0,
                TrainingCheckpointer(str(tmp_path / "opt")), interval=5,
                supervisor=sup, backoff_base_s=0.001, seed=9)
        decide_log = [(p, n, f) for p, n, f in inj.log
                      if p == "autoscale.decide"]
        assert decide_log == [
            ("autoscale.decide", 1, "drop_decision"),
            ("autoscale.decide", 2, "duplicate_decision"),
            ("autoscale.decide", 3, "SlowStep")]
        # decision 1 dropped (no reshape), decision 2 doubled (4->8 then
        # a same-shape reshape), decision 3 delayed then acquire-expired
        assert [d.action for d in policy.log][:3] == \
            ["scale-up", "scale-up", "scale-up"]
        assert sup.reshapes == 2
        assert sup.rebuilds == 0
        assert inj.counts.get("checkpoint.restore", 0) == 0
        assert ctx.mesh_runtime.n_devices == 8
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5,
                                   atol=1e-8)
    finally:
        auto.stop()
        chan.clear()
        ctx.rebuild_mesh("local-mesh[8]")


# -- checkpoint save/restore entry points ---------------------------------------

def test_save_entry_fault_leaves_prior_checkpoint_intact(tmp_path):
    """A crash at the checkpoint.save entry (before any file is written):
    the prior committed step stays the newest verifiable one and nothing
    half-written surfaces."""
    ck = TrainingCheckpointer(str(tmp_path), keep_last=3)
    ck.save(1, {"x": 1})
    sched = FaultSchedule().at("checkpoint.save", 1,
                               MidSaveCrash("died before writing"))
    with FaultInjector(sched) as inj:
        with pytest.raises(MidSaveCrash):
            ck.save(2, {"x": 2})
    assert inj.log == [("checkpoint.save", 1, "MidSaveCrash")]
    assert ck.steps() == [1]
    assert ck.verify(1)
    leftovers = [n for n in os.listdir(tmp_path) if ".tmp" in n]
    assert not leftovers


def test_restore_entry_fault_surfaces_not_swallowed(tmp_path):
    """An injected failure at the checkpoint.restore point surfaces to
    the caller — resume never silently restarts from scratch."""
    ck = TrainingCheckpointer(str(tmp_path), keep_last=3)
    ck.save(1, {"x": 1})
    sched = FaultSchedule().at("checkpoint.restore", 1,
                               TransientCollectiveError("torn read"))
    with FaultInjector(sched) as inj:
        with pytest.raises(TransientCollectiveError):
            ck.restore(1)
    assert inj.log == [("checkpoint.restore", 1,
                        "TransientCollectiveError")]


# -- the table <-> suite correspondence sweep -----------------------------------

def test_every_fault_point_has_a_chaos_case():
    """JX020's pytest twin: every point registered in the faults.py
    docstring table is SCHEDULED (a `.at(...)` / `.window(...)` literal)
    by at least one case in this file, so the chaos suite cannot
    silently fall behind the table — and vice versa: every scheduled
    dotted point must be a registered one (a typo'd schedule waits
    forever)."""
    import ast as pyast

    from cycloneml_tpu.analysis.registries import parse_fault_table
    from cycloneml_tpu.parallel import faults as faults_mod

    table = {name for name, _ in
             parse_fault_table(faults_mod.__doc__ or "", 1)}
    assert table, "fault-point table went missing from faults.py"

    with open(__file__, encoding="utf-8") as fh:
        tree = pyast.parse(fh.read())
    scheduled = set()
    for node in pyast.walk(tree):
        if isinstance(node, pyast.Call) \
                and isinstance(node.func, pyast.Attribute) \
                and node.func.attr in ("at", "window") \
                and node.args \
                and isinstance(node.args[0], pyast.Constant) \
                and isinstance(node.args[0].value, str):
            scheduled.add(node.args[0].value)

    unexercised = sorted(table - scheduled)
    assert unexercised == [], (
        f"fault points registered in the faults.py table but scheduled "
        f"by no chaos case: {unexercised}")
    dotted = {p for p in scheduled if "." in p}
    phantom = sorted(dotted - table)
    assert phantom == [], (
        f"chaos cases schedule points missing from the faults.py table "
        f"(the schedule matches exact strings and waits forever): "
        f"{phantom}")
