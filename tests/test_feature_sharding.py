"""Model-axis (feature-dim) tensor parallelism tests (SURVEY §5.7a).

Parity model: on an 8-device mesh laid out data=4 × model=2, the
feature-sharded loss/gradient/Gramian/trained-coefficients must match the
replicated path to float tolerance — the same data, cut along the other
axis.
"""

from types import SimpleNamespace

import numpy as np
import pytest

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.mesh import MeshRuntime
from cycloneml_tpu.ml.optim import aggregators
from cycloneml_tpu.ml.optim.lbfgs import LBFGS
from cycloneml_tpu.ml.optim.loss import (DistributedLossFunction,
                                         l2_regularization)
from cycloneml_tpu.parallel import feature_sharding as fs


@pytest.fixture(scope="module")
def tp_ctx():
    """8 devices as data=4 × model=2 (replica=1)."""
    rt = MeshRuntime("local-mesh[8]", n_replicas=1, model_parallelism=2)
    return SimpleNamespace(mesh_runtime=rt)


def _problem(n=256, d=24, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    true = rng.randn(d)
    y = (x @ true + 0.5 * rng.randn(n) > 0).astype(np.float64)
    return x, y


def test_tp_loss_grad_matches_replicated(tp_ctx, ctx):
    x, y = _problem()
    d = x.shape[1]
    ds_rep = InstanceDataset.from_numpy(ctx, x, y)
    rep = DistributedLossFunction(
        ds_rep, aggregators.binary_logistic(d, fit_intercept=True))

    rt = tp_ctx.mesh_runtime
    ds_tp = InstanceDataset.from_numpy(tp_ctx, x, y)
    x_tp = fs.feature_sharded_put(rt, ds_tp.x)
    tp = fs.FeatureShardedLossFunction(rt, x_tp, ds_tp.y, ds_tp.w, d,
                                       fit_intercept=True)
    assert tp.weight_sum == rep.weight_sum

    rng = np.random.RandomState(1)
    for _ in range(3):
        coef = rng.randn(d + 1)
        l1, g1 = rep(coef)
        l2v, g2 = tp(coef)
        np.testing.assert_allclose(l2v, l1, rtol=1e-9)
        np.testing.assert_allclose(g2, g1, rtol=1e-8, atol=1e-10)


def test_tp_training_matches_replicated(tp_ctx, ctx):
    """Full L-BFGS fits land on the same coefficients."""
    x, y = _problem(n=400, d=16, seed=3)
    d = x.shape[1]
    l2 = l2_regularization(0.1, d, True, standardize=True)

    ds_rep = InstanceDataset.from_numpy(ctx, x, y)
    rep = DistributedLossFunction(
        ds_rep, aggregators.binary_logistic(d, True), l2)
    s_rep = LBFGS(max_iter=50, tol=1e-10).minimize(rep, np.zeros(d + 1))

    rt = tp_ctx.mesh_runtime
    ds_tp = InstanceDataset.from_numpy(tp_ctx, x, y)
    x_tp = fs.feature_sharded_put(rt, ds_tp.x)
    tp = fs.FeatureShardedLossFunction(rt, x_tp, ds_tp.y, ds_tp.w, d, True, l2)
    s_tp = LBFGS(max_iter=50, tol=1e-10).minimize(tp, np.zeros(d + 1))

    np.testing.assert_allclose(s_tp.x, s_rep.x, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(s_tp.value, s_rep.value, rtol=1e-9)
    # the fused device line search ran (one dispatch per Wolfe search)
    assert tp.n_fused_searches > 0


def test_tp_logistic_regression_estimator(tp_ctx, ctx):
    """The estimator auto-selects the feature-sharded path on a model-axis
    mesh and produces the same model as the replicated mesh."""
    from cycloneml_tpu.ml.classification import LogisticRegression

    x, y = _problem(n=300, d=20, seed=5)
    ds_tp = InstanceDataset.from_numpy(tp_ctx, x, y)
    ds_rep = InstanceDataset.from_numpy(ctx, x, y)
    lr = LogisticRegression(maxIter=40, regParam=0.05, tol=1e-9)
    m_tp = lr._fit_dataset(ds_tp)
    m_rep = lr._fit_dataset(ds_rep)
    np.testing.assert_allclose(m_tp.coefficients, m_rep.coefficients,
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(m_tp.intercept, m_rep.intercept,
                               rtol=1e-5, atol=1e-8)


def test_gramian_ring_matches_replicated(tp_ctx, ctx):
    from cycloneml_tpu.linalg.distributed import RowMatrix

    rng = np.random.RandomState(7)
    x = rng.randn(200, 12)
    g_rep = RowMatrix(InstanceDataset.from_numpy(ctx, x)).compute_gramian()

    ds_tp = InstanceDataset.from_numpy(tp_ctx, x)
    rm = RowMatrix(ds_tp)
    sharded = rm.compute_gramian_sharded()
    assert sharded is not None
    from cycloneml_tpu.mesh import MODEL_AXIS
    assert sharded.sharding.spec[0] == MODEL_AXIS
    np.testing.assert_allclose(np.asarray(sharded), g_rep.to_array(),
                               rtol=1e-9, atol=1e-9)
    # the host-facing API routes through the ring on this mesh
    np.testing.assert_allclose(rm.compute_gramian().to_array(),
                               g_rep.to_array(), rtol=1e-9, atol=1e-9)


def test_tp_requires_divisible_features(tp_ctx):
    rt = tp_ctx.mesh_runtime
    with pytest.raises(ValueError, match="divisible"):
        fs.feature_sharded_put(rt, np.zeros((16, 7)))


def test_gramian_sharded_none_without_model_axis(ctx):
    from cycloneml_tpu.linalg.distributed import RowMatrix
    rm = RowMatrix(InstanceDataset.from_numpy(ctx, np.eye(8)))
    assert rm.compute_gramian_sharded() is None
