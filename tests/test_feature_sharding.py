"""Model-axis (feature-dim) tensor parallelism tests (SURVEY §5.7a).

Parity model: on an 8-device mesh laid out data=4 × model=2, the
feature-sharded loss/gradient/Gramian/trained-coefficients must match the
replicated path to float tolerance — the same data, cut along the other
axis.
"""

from types import SimpleNamespace

import numpy as np
import pytest

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.mesh import MeshRuntime
from cycloneml_tpu.ml.optim import aggregators
from cycloneml_tpu.ml.optim.lbfgs import LBFGS
from cycloneml_tpu.ml.optim.loss import (DistributedLossFunction,
                                         l2_regularization)
from cycloneml_tpu.parallel import feature_sharding as fs


@pytest.fixture(scope="module")
def tp_ctx():
    """8 devices as data=4 × model=2 (replica=1)."""
    rt = MeshRuntime("local-mesh[8]", n_replicas=1, model_parallelism=2)
    return SimpleNamespace(mesh_runtime=rt)


def _problem(n=256, d=24, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    true = rng.randn(d)
    y = (x @ true + 0.5 * rng.randn(n) > 0).astype(np.float64)
    return x, y


def test_tp_loss_grad_matches_replicated(tp_ctx, ctx):
    x, y = _problem()
    d = x.shape[1]
    ds_rep = InstanceDataset.from_numpy(ctx, x, y)
    rep = DistributedLossFunction(
        ds_rep, aggregators.binary_logistic(d, fit_intercept=True))

    rt = tp_ctx.mesh_runtime
    ds_tp = InstanceDataset.from_numpy(tp_ctx, x, y)
    x_tp = fs.feature_sharded_put(rt, ds_tp.x)
    tp = fs.FeatureShardedLossFunction(rt, x_tp, ds_tp.y, ds_tp.w, d,
                                       fit_intercept=True)
    assert tp.weight_sum == rep.weight_sum

    rng = np.random.RandomState(1)
    for _ in range(3):
        coef = rng.randn(d + 1)
        l1, g1 = rep(coef)
        l2v, g2 = tp(coef)
        np.testing.assert_allclose(l2v, l1, rtol=1e-9)
        np.testing.assert_allclose(g2, g1, rtol=1e-8, atol=1e-10)


def test_tp_training_matches_replicated(tp_ctx, ctx):
    """Full L-BFGS fits land on the same coefficients."""
    x, y = _problem(n=400, d=16, seed=3)
    d = x.shape[1]
    l2 = l2_regularization(0.1, d, True, standardize=True)

    ds_rep = InstanceDataset.from_numpy(ctx, x, y)
    rep = DistributedLossFunction(
        ds_rep, aggregators.binary_logistic(d, True), l2)
    s_rep = LBFGS(max_iter=50, tol=1e-10).minimize(rep, np.zeros(d + 1))

    rt = tp_ctx.mesh_runtime
    ds_tp = InstanceDataset.from_numpy(tp_ctx, x, y)
    x_tp = fs.feature_sharded_put(rt, ds_tp.x)
    tp = fs.FeatureShardedLossFunction(rt, x_tp, ds_tp.y, ds_tp.w, d, True, l2)
    s_tp = LBFGS(max_iter=50, tol=1e-10).minimize(tp, np.zeros(d + 1))

    np.testing.assert_allclose(s_tp.x, s_rep.x, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(s_tp.value, s_rep.value, rtol=1e-9)
    # the fused device line search ran (one dispatch per Wolfe search)
    assert tp.n_fused_searches > 0


def test_tp_logistic_regression_estimator(tp_ctx, ctx):
    """The estimator auto-selects the feature-sharded path on a model-axis
    mesh and produces the same model as the replicated mesh."""
    from cycloneml_tpu.ml.classification import LogisticRegression

    x, y = _problem(n=300, d=20, seed=5)
    ds_tp = InstanceDataset.from_numpy(tp_ctx, x, y)
    ds_rep = InstanceDataset.from_numpy(ctx, x, y)
    lr = LogisticRegression(maxIter=40, regParam=0.05, tol=1e-9)
    m_tp = lr._fit_dataset(ds_tp)
    m_rep = lr._fit_dataset(ds_rep)
    np.testing.assert_allclose(m_tp.coefficients, m_rep.coefficients,
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(m_tp.intercept, m_rep.intercept,
                               rtol=1e-5, atol=1e-8)


def test_gramian_ring_matches_replicated(tp_ctx, ctx):
    from cycloneml_tpu.linalg.distributed import RowMatrix

    rng = np.random.RandomState(7)
    x = rng.randn(200, 12)
    g_rep = RowMatrix(InstanceDataset.from_numpy(ctx, x)).compute_gramian()

    ds_tp = InstanceDataset.from_numpy(tp_ctx, x)
    rm = RowMatrix(ds_tp)
    sharded = rm.compute_gramian_sharded()
    assert sharded is not None
    from cycloneml_tpu.mesh import MODEL_AXIS
    assert sharded.sharding.spec[0] == MODEL_AXIS
    np.testing.assert_allclose(np.asarray(sharded), g_rep.to_array(),
                               rtol=1e-9, atol=1e-9)
    # the host-facing API routes through the ring on this mesh
    np.testing.assert_allclose(rm.compute_gramian().to_array(),
                               g_rep.to_array(), rtol=1e-9, atol=1e-9)


def test_tp_requires_divisible_features(tp_ctx):
    rt = tp_ctx.mesh_runtime
    with pytest.raises(ValueError, match="divisible"):
        fs.feature_sharded_put(rt, np.zeros((16, 7)))


def test_gramian_sharded_none_without_model_axis(ctx):
    from cycloneml_tpu.linalg.distributed import RowMatrix
    rm = RowMatrix(InstanceDataset.from_numpy(ctx, np.eye(8)))
    assert rm.compute_gramian_sharded() is None


def test_tp_scaled_fold_matches_replicated_scaled(tp_ctx, ctx):
    """r4 verdict item 3: the TP program folds standardization into the
    read. Features with wildly different scales + centering: the TP fit
    must land on the replicated scaled-aggregator fit."""
    rng = np.random.RandomState(11)
    n, d = 320, 16
    scales = np.logspace(-2, 3, d)
    x = rng.randn(n, d) * scales[None, :] + 5.0
    logits = ((x - 5.0) / scales) @ rng.randn(d)  # O(1) per-feature signal
    y = (logits + 0.3 * rng.randn(n) > 0).astype(np.float64)
    assert 0.2 < y.mean() < 0.8  # well-posed two-class problem

    from cycloneml_tpu.ml.classification import LogisticRegression
    ds_tp = InstanceDataset.from_numpy(tp_ctx, x, y)
    ds_rep = InstanceDataset.from_numpy(ctx, x, y)
    lr = LogisticRegression(maxIter=80, regParam=0.05, tol=1e-10)
    m_tp = lr._fit_dataset(ds_tp)
    m_rep = lr._fit_dataset(ds_rep)
    np.testing.assert_allclose(m_tp.coefficients.to_array(),
                               m_rep.coefficients.to_array(),
                               rtol=1e-5, atol=1e-9)
    np.testing.assert_allclose(m_tp.intercept, m_rep.intercept, rtol=1e-5)


def test_tp_fit_working_set_has_no_standardized_copy(tp_ctx):
    """Assert the fit's extra device footprint is ONE resharded copy of X
    (the TP placement), not two (+ a standardized copy, as before r5)."""
    import gc

    import jax

    from cycloneml_tpu.ml.classification import LogisticRegression
    rng = np.random.RandomState(7)
    n, d = 4096, 64
    x = (rng.randn(n, d) * np.linspace(0.1, 30, d)[None, :])
    y = (rng.rand(n) > 0.5).astype(np.float64)

    def live_bytes():
        gc.collect()
        return sum(a.nbytes for a in jax.live_arrays())

    # NEW regime: the fit reshards RAW X only (standardization folded)
    ds = InstanceDataset.from_numpy(tp_ctx, x, y)
    _ = ds.x  # materialize the dataset's device representation
    x_bytes = ds.x.nbytes
    base = live_bytes()
    LogisticRegression(maxIter=8, regParam=0.1).fit(ds)
    new_delta = live_bytes() - base

    # OLD regime (pre-r5): a standardized COPY of the dataset is built
    # and THAT is resharded — reconstruct it to measure what the fold
    # saves, robust to backend-internal reshard overheads
    from cycloneml_tpu.ml.optim.loss import standardize_dataset
    base2 = live_bytes()
    ds_std, _inv = standardize_dataset(ds, x.std(axis=0))
    x_tp_old = fs.feature_sharded_put(tp_ctx.mesh_runtime, ds_std.x)
    old_delta = live_bytes() - base2
    del x_tp_old, ds_std

    assert new_delta <= old_delta - x_bytes, (
        f"fit footprint {new_delta} not >=1×X below the old "
        f"standardized-copy construction {old_delta} (X={x_bytes})")


def test_pallas_scaled_kernel_matches_scaled_aggregator(ctx):
    """fused_binary_logistic_scaled (interpret mode) == the XLA scaled
    aggregator on raw blocks with centering."""
    from cycloneml_tpu.ops.kernels import fused_binary_logistic_scaled
    rng = np.random.RandomState(3)
    n, d = 300, 20
    x = rng.randn(n, d) * np.linspace(0.5, 8, d)[None, :] + 2.0
    y = (rng.rand(n) > 0.4).astype(np.float64)
    w = rng.rand(n) + 0.25
    std = x.std(axis=0)
    inv_std = 1.0 / std
    scaled_mean = x.mean(axis=0) * inv_std
    coef = rng.randn(d + 1)

    agg = aggregators.binary_logistic_scaled(d, fit_intercept=True)
    import jax.numpy as jnp
    exp = agg(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w),
              jnp.asarray(inv_std), jnp.asarray(scaled_mean),
              jnp.asarray(coef))
    got = fused_binary_logistic_scaled(
        x, y, w, inv_std, scaled_mean, coef, d, True, interpret=True)
    np.testing.assert_allclose(float(got["loss"]), float(exp["loss"]),
                               rtol=2e-5)
    np.testing.assert_allclose(np.asarray(got["grad"]),
                               np.asarray(exp["grad"]), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(float(got["count"]), float(exp["count"]),
                               rtol=1e-6)
