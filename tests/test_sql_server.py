"""Remote SQL service (the Thriftserver role): concurrent clients over
TCP against one shared session/catalog, DDL/DML visible across
connections, typed error propagation."""

import threading

import numpy as np
import pytest

from cycloneml_tpu.sql.analyzer import AnalysisException
from cycloneml_tpu.sql.server import CycloneSQLServer, SQLClient
from cycloneml_tpu.sql.session import CycloneSession


@pytest.fixture()
def server():
    s = CycloneSession()
    df = s.create_data_frame({
        "k": np.array(["a", "b", "a", "c"], dtype=object),
        "v": np.array([1.0, 2.0, 3.0, np.nan]),
    })
    s.register_temp_view("t", df)
    srv = CycloneSQLServer(s)
    yield srv
    srv.stop()


def test_query_and_null_mapping(server):
    with SQLClient(server.address) as c:
        cols, rows = c.execute(
            "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k")
        assert cols == ["k", "s"]
        assert rows == [["a", 4.0], ["b", 2.0], ["c", None]]  # NaN -> NULL


def test_ddl_visible_across_connections(server):
    with SQLClient(server.address) as c1:
        c1.execute("CREATE TABLE agg AS SELECT k, COUNT(*) AS n FROM t "
                   "GROUP BY k")
    with SQLClient(server.address) as c2:  # shared catalog, new connection
        cols, rows = c2.execute("SELECT * FROM agg ORDER BY k")
        assert cols == ["k", "n"]
        assert [r[0] for r in rows] == ["a", "b", "c"]


def test_typed_errors_propagate(server):
    with SQLClient(server.address) as c:
        with pytest.raises(AnalysisException, match="cannot resolve"):
            c.execute("SELECT nope FROM t")
        # the connection survives an error and keeps serving
        cols, rows = c.execute("SELECT COUNT(*) AS n FROM t")
        assert rows == [[4]]


def test_concurrent_clients(server):
    results = []
    errors = []

    def run(i):
        try:
            with SQLClient(server.address) as c:
                _, rows = c.execute(
                    f"SELECT COUNT(*) AS n FROM t WHERE v >= {i % 3}")
                results.append(rows[0][0])
        except Exception as e:  # surfaced in the main thread
            errors.append(e)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert len(results) == 8
