"""Out-of-core dense tier (round-3 verdict item 2).

Parity: every chunked reader must agree with its whole-file twin up to the
documented row permutation (chunk-round-robin over devices) — compared via
order-insensitive statistics (row multiset hash, Gram matrix, label moments).

Boundedness: the loader's driver-side staging must be O(chunk), not O(file).
On the CPU test mesh "device" memory IS process RAM, so the full-fit check
runs in a subprocess and asserts peak RSS stays under ~2x the dataset bytes
(one device-resident copy + chunk slack) — the whole-file path costs ~4x
(f64 parse + padded blockify copy + device placement), so the bound cleanly
separates the two. On real TPU hardware the same loader keeps the matrix in
HBM only; see BASELINE.md's config-3 ledger row.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.dataset.io import (read_csv_chunked, read_libsvm,
                                      read_npy_chunked)


def _row_stats(ds):
    """Order-insensitive fingerprint of the (unpadded, weighted) rows."""
    x, y, w = ds.to_numpy()
    order = np.lexsort(x.T)
    return x[order], y[order], w[order]


def test_from_dense_chunks_matches_from_numpy(ctx):
    rng = np.random.RandomState(0)
    x = rng.randn(1000, 7)
    y = rng.randint(0, 2, 1000).astype(float)

    def chunks():
        for lo in range(0, 1000, 128):
            yield x[lo:lo + 128], y[lo:lo + 128], None

    ds = InstanceDataset.from_dense_chunks(ctx, chunks(), 7)
    ref = InstanceDataset.from_numpy(ctx, x, y)
    assert ds.n_rows == 1000 and ds.n_features == 7
    xs, ys, ws = _row_stats(ds)
    xr, yr, wr = _row_stats(ref)
    np.testing.assert_allclose(xs, xr, rtol=1e-6)
    np.testing.assert_allclose(ys, yr)
    # host label twins attached without a readback
    assert ds._yw_host is not None
    # an aggregate over the mesh agrees (padding stays neutral)
    g1 = ds.tree_aggregate_fn(lambda a, b, c: (a * c[:, None]).T @ a)()
    g2 = ref.tree_aggregate_fn(lambda a, b, c: (a * c[:, None]).T @ a)()
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5)


def test_from_dense_chunks_rejects_bad_width(ctx):
    with pytest.raises(ValueError, match="expected"):
        InstanceDataset.from_dense_chunks(
            ctx, iter([(np.zeros((4, 3)), None, None)]), n_features=5)


def test_read_libsvm_streamed_matches_whole_file(ctx, tmp_path):
    rng = np.random.RandomState(1)
    p = str(tmp_path / "data.svm")
    n, d = 3000, 12
    with open(p, "w") as fh:
        for i in range(n):
            idx = np.sort(rng.choice(d, 4, replace=False))
            toks = " ".join(f"{j + 1}:{rng.randn():.6f}" for j in idx)
            fh.write(f"{i % 2} {toks}\n")
    whole = read_libsvm(ctx, p, n_features=d, streamed=False)
    chunked = read_libsvm(ctx, p, n_features=d, streamed=True)
    assert chunked.n_rows == whole.n_rows == n
    xs, ys, _ = _row_stats(chunked)
    xr, yr, _ = _row_stats(whole)
    np.testing.assert_allclose(xs, xr, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ys, yr)
    # streamed path refuses an undersized declared width instead of clipping
    with pytest.raises(ValueError, match="n_features"):
        read_libsvm(ctx, p, n_features=3, streamed=True)


def test_read_npy_chunked_matches_numpy(ctx, tmp_path):
    rng = np.random.RandomState(2)
    data = rng.randn(5000, 9).astype(np.float32)
    data[:, 0] = rng.randint(0, 2, 5000)
    p = str(tmp_path / "data.npy")
    np.save(p, data)
    ds = read_npy_chunked(ctx, p, label_col=0, chunk_rows=700)
    assert ds.shape == (5000, 8)
    xs, ys, _ = _row_stats(ds)
    ref = np.delete(data, 0, axis=1).astype(np.float64)
    order = np.lexsort(ref.T)
    np.testing.assert_allclose(xs, ref[order], rtol=1e-6)
    np.testing.assert_allclose(ys, data[order, 0])


def test_read_csv_chunked_matches_read_csv(ctx, tmp_path):
    from cycloneml_tpu.dataset.io import read_csv
    rng = np.random.RandomState(3)
    data = rng.randn(2000, 5)
    p = str(tmp_path / "data.csv")
    np.savetxt(p, data, delimiter=",", header="y,a,b,c,d", comments="")
    whole = read_csv(ctx, p, label_col=0, skip_header=True)
    chunked = read_csv_chunked(ctx, p, label_col=0, skip_header=True,
                               chunk_rows=300)
    assert chunked.shape == whole.shape
    xs, ys, _ = _row_stats(chunked)
    xr, yr, _ = _row_stats(whole)
    np.testing.assert_allclose(xs, xr, rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(ys, yr, rtol=1e-5, atol=1e-8)


_RSS_SCRIPT = textwrap.dedent("""
    import os, resource, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from cycloneml_tpu.conf import CycloneConf
    from cycloneml_tpu.context import CycloneContext
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.dataset.io import read_npy_chunked
    from cycloneml_tpu.ml.clustering import KMeans

    mode, path, n, d = sys.argv[1:5]
    n, d = int(n), int(d)
    ctx = CycloneContext(CycloneConf().set("cyclone.master", "local-mesh[8]"))
    if mode == "streamed":
        ds = read_npy_chunked(ctx, path, chunk_rows=32768)
    else:  # whole-file materialization, what the loader replaces
        ds = InstanceDataset.from_numpy(ctx, np.load(path).astype(np.float64))
    assert ds.shape == (n, d), ds.shape
    m = KMeans(k=8, maxIter=2, seed=1).fit(ds)
    assert len(m.cluster_centers) == 8
    print("PEAK_RSS_KB", resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
""")


def _peak_kb(mode, path, n, d, env):
    out = subprocess.run(
        [sys.executable, "-c", _RSS_SCRIPT, mode, path, str(n), str(d)],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    return int(out.stdout.split("PEAK_RSS_KB")[1])


def _write_big_npy(p, n, d, chunk=32768):
    # write incrementally — the writer must not hold the matrix either
    import numpy.lib.format as npf
    rng = np.random.RandomState(4)
    with open(p, "wb") as fh:
        npf.write_array_header_2_0(
            fh, {"descr": "<f4", "fortran_order": False, "shape": (n, d)})
        for lo in range(0, n, chunk):
            m = min(chunk, n - lo)
            fh.write(rng.randn(m, d).astype(np.float32).tobytes())


def test_npy_reader_staging_is_chunk_bounded(tmp_path):
    """The reader's HOST staging is O(chunk), not O(file): draining the raw
    chunk iterator over a 160 MB file moves peak RSS by less than 30 MB
    (one 16 MB block + buffers). Device placement is excluded — on the CPU
    test platform mesh memory IS process RAM, and through the TPU relay the
    transfer client buffers h2d payloads; both are outside the loader's
    control (same methodology as the sparse tier's bounded-RSS test)."""
    import resource
    from cycloneml_tpu.dataset import io as dio

    n, d = 320_000, 128  # 160 MB f32
    p = str(tmp_path / "big.npy")
    _write_big_npy(p, n, d)
    ds_bytes = n * d * 4
    assert os.path.getsize(p) > ds_bytes  # sanity

    # reuse read_npy_chunked's own chunk loop via a capturing stub mesh: we
    # drain the identical code path by calling the module-level reader with
    # a fake from_dense_chunks that just iterates
    captured = {"rows": 0}

    class _Probe:
        @staticmethod
        def from_dense_chunks(ctx, chunks, n_features, dtype=None):
            for cx, cy, cw in chunks:
                captured["rows"] += cx.shape[0]
            return None

    orig = dio.InstanceDataset
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    dio.InstanceDataset = _Probe
    try:
        dio.read_npy_chunked(None, p, chunk_rows=32768)
    finally:
        dio.InstanceDataset = orig
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    assert captured["rows"] == n
    assert (rss1 - rss0) * 1024 < 30e6, (rss0, rss1)


@pytest.mark.slow
def test_kmeans_out_of_core_end_to_end(tmp_path):
    """KMeans trains end-to-end on a chunk-streamed 160 MB dataset in a
    fresh subprocess with a sanity memory cap: < 5x dataset over an
    identical tiny-file baseline (one mesh-resident copy on the CPU test
    platform + concat transient + XLA-CPU unfused elementwise temps; on
    TPU the matrix lives in HBM and host staging is chunk-bounded, proven
    separately above). Anything beyond 5x means the loader regressed to
    holding the file host-side."""
    n, d = 320_000, 128
    p = str(tmp_path / "big.npy")
    _write_big_npy(p, n, d)
    ds_bytes = n * d * 4

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    tiny = str(tmp_path / "tiny.npy")
    np.save(tiny, np.random.RandomState(0).randn(256, d).astype(np.float32))
    base_kb = _peak_kb("streamed", tiny, 256, d, env)
    peak_kb = _peak_kb("streamed", p, n, d, env)
    extra = (peak_kb - base_kb) * 1024
    assert extra < 5.0 * ds_bytes, (base_kb, peak_kb, ds_bytes)


def test_chunk_split_keeps_shards_balanced(ctx):
    """Few large chunks must not inflate padding: each chunk is split across
    all devices, so per-shard row counts differ by at most the chunk count
    and total padding stays within one sublane multiple per shard."""
    x = np.random.RandomState(5).randn(5 * 65536 // 64, 4)  # ~5120 rows

    def chunks():
        for lo in range(0, len(x), 1024):  # 5 chunks on an 8-device mesh
            yield x[lo:lo + 1024], None, None

    ds = InstanceDataset.from_dense_chunks(ctx, chunks(), 4)
    n_pad = int(ds.x.shape[0])
    assert ds.n_rows == len(x)
    # whole-chunk round-robin would pad to 2x1024x8 = 16384; balanced
    # splitting stays within one sublane multiple (8 rows) per shard
    assert n_pad <= len(x) + 8 * 8 * 2, n_pad


def test_read_csv_chunked_leading_blank_lines(ctx, tmp_path):
    p = str(tmp_path / "gap.csv")
    with open(p, "w") as fh:
        fh.write("y,a\n\n\n1.0,2.0\n\n0.0,4.0\n")
    ds = read_csv_chunked(ctx, p, label_col=0, skip_header=True)
    assert ds.shape == (2, 1)
    x, y, _ = ds.to_numpy()
    np.testing.assert_allclose(sorted(y.tolist()), [0.0, 1.0])


# -- streaming fit mode (oocore/: the out-of-core epoch engine) ---------------


def _binary_problem(n=3000, d=10, seed=11):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    y = (x @ rng.randn(d) + 0.3 * rng.randn(n) > 0).astype(float)
    return x, y


def _streaming_ds(ctx, x, y, shard_rows=700):
    from cycloneml_tpu.oocore import StreamingDataset

    def chunks():
        for lo in range(0, len(x), 450):  # chunk != shard boundaries
            yield x[lo:lo + 450], y[lo:lo + 450], None

    return StreamingDataset.from_chunks(ctx, chunks(), x.shape[1],
                                        shard_rows=shard_rows)


def test_streaming_dataset_stats_match_summarizer(ctx):
    """The shard WRITE pass harvests the Summarizer moment set: mean/std/
    weight_sum (and the label histogram) must match the in-core psum pass
    over the same rows."""
    from cycloneml_tpu.ml.stat import Summarizer
    x, y = _binary_problem()
    sds = _streaming_ds(ctx, x, y)
    try:
        ref = Summarizer.summarize(InstanceDataset.from_numpy(ctx, x, y))
        got = sds.summary()
        np.testing.assert_allclose(got.mean, ref.mean, rtol=1e-12)
        np.testing.assert_allclose(got.std, ref.std, rtol=1e-12)
        assert got.weight_sum == ref.weight_sum
        assert got.count == ref.count
        np.testing.assert_allclose(got.max, ref.max)
        np.testing.assert_allclose(got.min, ref.min)
        hist = sds.label_histogram()
        np.testing.assert_allclose(
            hist, np.bincount(y.astype(int), minlength=2))
        assert sds.num_classes == 2
    finally:
        sds.close()


def test_streamed_logreg_matches_incore(ctx):
    """Fit-mode acceptance: a streamed LogisticRegression fit (each loss/
    grad evaluation = one double-buffered epoch over shards) lands on the
    in-core coefficients. Under the f64 CPU test config the only
    difference is summation ORDER (shard partials vs device partials), so
    the envelope is ulp-level; under bf16 storage (TPU default tier) the
    documented envelope is the mixed-precision suite's ~1e-3 relative
    (docs/out-of-core.md)."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    x, y = _binary_problem()
    sds = _streaming_ds(ctx, x, y)
    try:
        est = LogisticRegression(maxIter=25, regParam=0.05)
        m_stream = est.fit(sds)
        m_ref = LogisticRegression(maxIter=25, regParam=0.05).fit(
            InstanceDataset.from_numpy(ctx, x, y))
        assert m_stream.summary.streamed
        assert not m_ref.summary.streamed
        np.testing.assert_allclose(np.asarray(m_stream._coef),
                                   np.asarray(m_ref._coef),
                                   rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(np.asarray(m_stream._icpt),
                                   np.asarray(m_ref._icpt),
                                   rtol=1e-9, atol=1e-12)
        # one sweep dispatches one program per shard; evals count epochs
        assert m_stream.summary.total_dispatches \
            >= m_stream.summary.total_evals * sds.n_shards
    finally:
        sds.close()


def test_streamed_linreg_matches_incore(ctx):
    from cycloneml_tpu.ml.regression import LinearRegression
    rng = np.random.RandomState(12)
    n, d = 2500, 8
    x = rng.randn(n, d)
    y = x @ rng.randn(d) + 0.1 * rng.randn(n)
    sds = _streaming_ds(ctx, x, y)
    try:
        m_stream = LinearRegression(maxIter=25, regParam=0.1,
                                    solver="l-bfgs").fit(sds)
        m_ref = LinearRegression(maxIter=25, regParam=0.1,
                                 solver="l-bfgs").fit(
            InstanceDataset.from_numpy(ctx, x, y))
        assert m_stream.summary.streamed
        np.testing.assert_allclose(np.asarray(m_stream._coef),
                                   np.asarray(m_ref._coef),
                                   rtol=1e-9, atol=1e-12)
        # the normal solver needs the in-core matrix: explicit request fails
        # loudly, auto routes to l-bfgs
        with pytest.raises(ValueError, match="in-core"):
            LinearRegression(solver="normal").fit(sds)
        auto = LinearRegression(maxIter=25, solver="auto").fit(sds)
        assert auto.summary.streamed
    finally:
        sds.close()


def test_streamed_gradient_descent_matches_incore(ctx):
    """Partial-sweep SGD accumulation: the streamed optimizer folds every
    shard's psummed partial into one accumulator-tier gradient per step —
    the same update math as the in-core full-batch GradientDescent."""
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.ml.optim.gradient_descent import (GradientDescent,
                                                         SquaredL2Updater)
    from cycloneml_tpu.oocore import StreamingGradientDescent
    x, y = _binary_problem(n=1500, d=6, seed=13)
    sds = _streaming_ds(ctx, x, y, shard_rows=400)
    try:
        agg = aggregators.binary_logistic(6, fit_intercept=False)
        kw = dict(step_size=1.0, num_iterations=25, reg_param=0.01,
                  updater=SquaredL2Updater(), seed=3)
        w_s, hist_s = StreamingGradientDescent(**kw).optimize(
            sds, agg, np.zeros(6))
        w_r, hist_r = GradientDescent(**kw).optimize(
            InstanceDataset.from_numpy(ctx, x, y), agg, np.zeros(6))
        assert len(hist_s) == len(hist_r)
        np.testing.assert_allclose(w_s, w_r, rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(hist_s, hist_r, rtol=1e-9)
    finally:
        sds.close()


def test_over_budget_fit_degrades_to_streaming(ctx):
    """The acceptance pin: an in-core fit whose chunk program exceeds the
    memory budget at deviceChunk=1 DEGRADES to the streaming engine and
    completes — even under budgetAction=raise — matching the unbudgeted
    coefficients; cyclone.oocore.mode=off restores the raise."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.observe.costs import MemoryBudgetError
    x, y = _binary_problem(n=1200, d=6, seed=14)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    est = lambda: LogisticRegression(maxIter=12, regParam=0.1)  # noqa: E731
    ref = est().fit(ds)
    assert not ref.summary.streamed
    warnings_before = len(ctx.status_store.memory_warnings)
    ctx.conf.set("cyclone.memory.budgetFraction", "1e-12")
    ctx.conf.set("cyclone.memory.budgetAction", "raise")
    try:
        m = est().fit(ds)
        assert m.summary.streamed  # degraded, not OOM'd, not raised
        np.testing.assert_allclose(np.asarray(m._coef),
                                   np.asarray(ref._coef),
                                   rtol=1e-9, atol=1e-12)
        assert ctx.listener_bus.wait_until_empty()
        warns = ctx.status_store.memory_warnings[warnings_before:]
        assert warns  # the exceeded-budget events still posted
        ctx.conf.set("cyclone.oocore.mode", "off")
        with pytest.raises(MemoryBudgetError):
            est().fit(ds)
    finally:
        ctx.conf.remove("cyclone.memory.budgetFraction")
        ctx.conf.remove("cyclone.memory.budgetAction")
        ctx.conf.remove("cyclone.oocore.mode")


def test_oocore_mode_force_streams_eligible_fits(ctx):
    from cycloneml_tpu.ml.classification import LogisticRegression
    x, y = _binary_problem(n=1000, d=5, seed=15)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    ref = LogisticRegression(maxIter=10, regParam=0.1).fit(ds)
    ctx.conf.set("cyclone.oocore.mode", "force")
    try:
        m = LogisticRegression(maxIter=10, regParam=0.1).fit(ds)
        assert m.summary.streamed
        np.testing.assert_allclose(np.asarray(m._coef),
                                   np.asarray(ref._coef),
                                   rtol=1e-9, atol=1e-12)
    finally:
        ctx.conf.remove("cyclone.oocore.mode")


def test_streamed_sweep_cost_is_o_shard(ctx):
    """costs.streamed_sweep_cost: whole-epoch WORK scales with the shard
    count while the per-dispatch MEMORY footprint stays O(shard) — the
    reason the streamed fit cannot OOM."""
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.oocore import StreamingLossFunction
    x, y = _binary_problem(n=2000, d=8, seed=16)
    sds = _streaming_ds(ctx, x, y, shard_rows=500)
    try:
        f = StreamingLossFunction(
            sds, aggregators.binary_logistic(8, fit_intercept=False))
        cost = f.sweep_cost(n_coef=8)
        assert cost.cost_available and cost.memory_available
        per_shard_x_bytes = sds.pad_rows * 8 * np.dtype(np.float64).itemsize
        # epoch bytes cover all shards' X at least once...
        assert cost.bytes_accessed_total >= sds.n_shards * per_shard_x_bytes
        # ...but peak HBM is one padded shard's program, not the epoch
        assert cost.peak_bytes < 3 * per_shard_x_bytes
    finally:
        sds.close()


def test_stream_spans_show_stage_and_compute(ctx):
    """Stream-phase observability: a traced streamed fit records
    ``oocore.stage`` transfer spans (staging thread, bytes annotated),
    ``oocore.shard`` dispatch spans (consumer thread) and the cumulative
    ``oocore.bytes_staged`` counter track — the spans the bench's overlap
    measurement reads."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.observe import tracing
    x, y = _binary_problem(n=1200, d=6, seed=17)
    sds = _streaming_ds(ctx, x, y, shard_rows=400)
    tr = tracing.enable()
    mark = tr.mark()
    try:
        LogisticRegression(maxIter=4, regParam=0.1).fit(sds)
        spans = tr.snapshot(since=mark)
        stage = [s for s in spans if s.name == "oocore.stage"]
        shard = [s for s in spans if s.name == "oocore.shard"]
        counters = [s for s in spans if s.name == "oocore.bytes_staged"]
        assert stage and shard and counters
        assert all(s.kind == "transfer" for s in stage)
        assert all(s.attrs.get("bytes", 0) > 0 for s in stage)
        # staging runs on its own thread — the overlap is observable
        assert {s.tid for s in stage} != {s.tid for s in shard}
        per_epoch = sds.n_shards
        assert len(shard) % per_epoch == 0
    finally:
        tracing.disable()
        sds.close()


_STREAM_RSS_SCRIPT = textwrap.dedent("""
    import os, resource, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from cycloneml_tpu.conf import CycloneConf
    from cycloneml_tpu.context import CycloneContext
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.oocore import StreamingDataset

    n, d, shard_rows = (int(a) for a in sys.argv[1:4])
    ctx = CycloneContext(CycloneConf().set("cyclone.master", "local-mesh[8]"))
    rng = np.random.RandomState(4)
    beta = rng.randn(d)

    def chunks():
        done = 0
        while done < n:
            m = min(32768, n - done)
            xc = rng.randn(m, d).astype(np.float32)
            yc = (xc @ beta > 0).astype(np.float64)
            yield xc, yc, None
            done += m

    sds = StreamingDataset.from_chunks(ctx, chunks(), d,
                                       shard_rows=shard_rows)
    model = LogisticRegression(maxIter=3, regParam=0.1).fit(sds)
    assert model.summary.streamed
    assert sds.n_rows == n
    sds.close()
    print("PEAK_RSS_KB", resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
""")


def test_streamed_fit_rss_is_shard_bounded(tmp_path):
    """A FULL streamed fit in a fresh subprocess: generate → shard → fit
    without the matrix ever materializing. Peak RSS over an identical
    tiny-problem baseline must stay well under the dataset's own f32
    bytes — the fit's host working set is O(shard), the shards live on
    disk, and on the CPU test platform 'device' memory IS process RAM, so
    this bounds the device residency too (depth+1 padded shards)."""
    n, d, shard_rows = 320_000, 64, 32768
    ds_bytes = n * d * 4
    env = dict(os.environ)

    def run(n_):
        out = subprocess.run(
            [sys.executable, "-c", _STREAM_RSS_SCRIPT, str(n_), str(d),
             str(shard_rows)],
            capture_output=True, text=True, env=env, timeout=600)
        assert out.returncode == 0, out.stderr[-2000:]
        return int(out.stdout.split("PEAK_RSS_KB")[1])

    base_kb = run(4096)
    peak_kb = run(n)
    extra = (peak_kb - base_kb) * 1024
    assert extra < 0.5 * ds_bytes, (base_kb, peak_kb, ds_bytes)


def test_chunked_dataset_trains_tree_mlp_svc(ctx):
    """Estimators that read labels/features back to host must honor the
    interleaved padding mask (review r3: trees/MLP/SVC sliced [:n_rows])."""
    from cycloneml_tpu.ml.classification import (
        DecisionTreeClassifier, LinearSVC, MultilayerPerceptronClassifier)
    rng = np.random.RandomState(6)
    x = rng.randn(900, 6)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(float)

    def chunks():
        for lo in range(0, 900, 200):
            yield x[lo:lo + 200], y[lo:lo + 200], None

    ds = InstanceDataset.from_dense_chunks(ctx, chunks(), 6)
    ref = InstanceDataset.from_numpy(ctx, x, y)
    assert ds._valid_mask is not None and not ds._valid_mask.all()
    for est in (DecisionTreeClassifier(maxDepth=4, seed=3),
                MultilayerPerceptronClassifier(layers=[6, 8, 2], maxIter=40, seed=3),
                LinearSVC(maxIter=20, regParam=0.01)):
        m_chunked = est.fit(ds)
        m_ref = est.fit(ref)
        px = np.asarray(m_chunked.transform(
            MLFrame(ctx, {"features": x, "label": y}))["prediction"])
        acc = float((px == y).mean())
        assert acc > 0.85, (type(est).__name__, acc)
        pr = np.asarray(m_ref.transform(
            MLFrame(ctx, {"features": x, "label": y}))["prediction"])
        # chunked row order is a permutation; models need not be identical,
        # but both must learn the same signal
        assert float((pr == y).mean()) > 0.85


def test_shuffled_sgd_matches_fixed_order(ctx):
    """Epoch shard shuffling (ROADMAP 1a): the streamed SGD walks a
    SEEDED permutation of the shard order per epoch. Because the step's
    gradient is the whole-epoch accumulation and the Bernoulli mask keys
    on the TRUE shard index, a shuffled run agrees with the fixed-order
    run at matched seeds up to float summation order — and a shuffled
    re-run at the same seed is bitwise-identical."""
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.ml.optim.gradient_descent import SquaredL2Updater
    from cycloneml_tpu.oocore import StreamingGradientDescent
    x, y = _binary_problem(n=1600, d=6, seed=21)
    sds = _streaming_ds(ctx, x, y, shard_rows=300)
    try:
        agg = aggregators.binary_logistic(6, fit_intercept=False)
        kw = dict(step_size=1.0, num_iterations=12, reg_param=0.01,
                  updater=SquaredL2Updater(), seed=5,
                  mini_batch_fraction=0.6)
        w_fix, hist_fix = StreamingGradientDescent(
            shuffle=False, **kw).optimize(sds, agg, np.zeros(6))
        w_shuf, hist_shuf = StreamingGradientDescent(
            shuffle=True, **kw).optimize(sds, agg, np.zeros(6))
        w_shuf2, _ = StreamingGradientDescent(
            shuffle=True, **kw).optimize(sds, agg, np.zeros(6))
        # parity vs the fixed order at matched seeds (same masks, same
        # per-shard partials — only the fold order differs)
        np.testing.assert_allclose(w_shuf, w_fix, rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(hist_shuf, hist_fix, rtol=1e-9)
        # seeded determinism: same seed, same permutations, same bits
        np.testing.assert_array_equal(w_shuf, w_shuf2)
    finally:
        sds.close()


def test_shuffle_conf_key_and_order_validation(ctx):
    """cyclone.oocore.shuffle routes the engine default; a bogus order
    passed to the stream is rejected loudly."""
    from cycloneml_tpu.conf import OOCORE_SHUFFLE
    from cycloneml_tpu.oocore import StreamingGradientDescent
    from cycloneml_tpu.oocore.stream import ShardStream
    assert ctx.conf.get(OOCORE_SHUFFLE) is False
    ctx.conf.set("cyclone.oocore.shuffle", "true")
    try:
        assert ctx.conf.get(OOCORE_SHUFFLE) is True
        assert StreamingGradientDescent().shuffle is None  # conf-resolved
    finally:
        ctx.conf.set("cyclone.oocore.shuffle", "false")
    x, y = _binary_problem(n=600, d=4, seed=22)
    sds = _streaming_ds(ctx, x, y, shard_rows=300)
    try:
        with pytest.raises(ValueError, match="permutation"):
            ShardStream(sds, order=[0, 0, 1]).close()
    finally:
        sds.close()


def test_streaming_dataset_close_race_single_unlink(ctx, monkeypatch):
    """Explicit close races ``__del__`` (GC runs finalizers on another
    thread's allocation path): the ``_closed`` latch is taken under a
    lock, so concurrent closers unlink each spill file EXACTLY once —
    never a double-unlink that could tear down a path a new dataset just
    reused. Pinned from a graftlint JX022 check-then-act self-run
    finding."""
    import threading
    from collections import Counter

    x, y = _binary_problem(n=600, d=4)
    sds = _streaming_ds(ctx, x, y, shard_rows=200)
    paths = [s.path for s in sds._shards]
    assert paths and all(os.path.exists(p) for p in paths)

    counts: Counter = Counter()
    count_lock = threading.Lock()
    real_unlink = os.unlink

    def counted(p, *a, **k):
        with count_lock:
            counts[p] += 1
        return real_unlink(p, *a, **k)

    monkeypatch.setattr(os, "unlink", counted)
    barrier = threading.Barrier(4)

    def closer():
        barrier.wait()
        sds.close()

    threads = [threading.Thread(target=closer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert {counts[p] for p in paths} == {1}
    assert not any(os.path.exists(p) for p in paths)
    sds.close()   # idempotent after the race: the latch stays down
    assert {counts[p] for p in paths} == {1}


# -- fp8 shard stream + stacked streamed epochs + shard-set cache (ISSUE 19) --


def test_fp8_shard_stream_matches_incore_fp8(ctx):
    """Tentpole leg (a): under ``streamDtype=float8`` the spill stores
    e4m3 codes + ONE set-level per-column dequant scale — the identical
    codes and scale an in-core fp8 quantization of the same rows
    produces — so the streamed fit lands ulp-close to the in-core fp8
    fit (the only difference is summation order), and the staged X bytes
    drop to 1 per element."""
    import ml_dtypes
    from cycloneml_tpu.dataset.instance import data_dtype
    from cycloneml_tpu.ml.classification import LogisticRegression
    x, y = _binary_problem(n=1500, d=8, seed=31)
    ctx.conf.set("cyclone.oocore.streamDtype", "float8")
    ctx.conf.set("cyclone.data.dtype", "float8")
    try:
        sds = _streaming_ds(ctx, x, y)
        try:
            assert sds.x_dtype == np.dtype(ml_dtypes.float8_e4m3fn)
            assert sds.x_scale is not None and sds.x_scale.shape == (8,)
            est = lambda: LogisticRegression(maxIter=30,  # noqa: E731
                                             regParam=0.01, tol=1e-10)
            m_st = est().fit(sds)
            assert m_st.summary.streamed
            ds8 = InstanceDataset.from_numpy(
                ctx, x, y, dtype=data_dtype(ctx.conf, fp8_capable=True))
            # the finalize pass and the in-core quantizer agree bitwise
            # on the set-level scale
            np.testing.assert_array_equal(sds.x_scale,
                                          np.asarray(ds8.x_scale))
            m_in = est().fit(ds8)
            np.testing.assert_allclose(np.asarray(m_st._coef),
                                       np.asarray(m_in._coef),
                                       rtol=1e-9, atol=1e-12)
            np.testing.assert_allclose(np.asarray(m_st._icpt),
                                       np.asarray(m_in._icpt),
                                       rtol=1e-9, atol=1e-12)
            # the staged stream really is 1-byte codes
            x0, _, _ = sds.load_shard(0)
            assert x0.dtype == np.dtype(ml_dtypes.float8_e4m3fn)
            assert x0.itemsize == 1
        finally:
            sds.close()
    finally:
        ctx.conf.remove("cyclone.oocore.streamDtype")
        ctx.conf.set("cyclone.data.dtype", "auto")


def test_fp8_stream_probe_refusal_stays_wide_and_visible(ctx):
    """The fp8 stream's safety rail: an ill-conditioned column (absmax
    >> std) makes the materialization-time envelope probe refuse the fp8
    rung for the shard SET — the spill stays at the write rung, the fit
    completes, and the decision surfaces as a PrecisionFallback event
    (automatic and visible, never silent)."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.util.events import PrecisionFallback
    x, y = _binary_problem(n=900, d=6, seed=32)
    x[:, 2] = 1000.0 + 0.01 * np.random.RandomState(1).randn(900)
    events = []
    ctx.listener_bus.add_listener(events.append)
    ctx.conf.set("cyclone.oocore.streamDtype", "float8")
    try:
        sds = _streaming_ds(ctx, x, y)
        try:
            ctx.listener_bus.wait_until_empty()
            assert sds.x_scale is None  # the requantize was refused
            assert sds.x_dtype.itemsize > 1
            falls = [e for e in events if isinstance(e, PrecisionFallback)]
            assert len(falls) == 1
            assert falls[0].from_dtype == "float8_e4m3fn"
            assert "absmax/std" in falls[0].reason
            m = LogisticRegression(maxIter=8, regParam=0.1).fit(sds)
            assert m.summary.streamed
            assert np.all(np.isfinite(np.asarray(m._coef)))
        finally:
            sds.close()
    finally:
        ctx.conf.remove("cyclone.oocore.streamDtype")
        ctx.listener_bus.remove_listener(events.append)


def test_streamed_stacked_fit_matches_serial_streamed(ctx):
    """Tentpole leg (b): ``fit_stacked`` over a StreamingDataset drives K
    models through ONE double-buffered epoch per optimizer round (vmap
    over the per-shard partials, per-model convergence masks on the host
    fold). Coefficient parity with K serial streamed fits at matched
    regs is 1e-9, and the stacked run's epoch count is the MAX of the
    serial counts, not their sum."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    x, y = _binary_problem(n=2000, d=8, seed=33)
    sds = _streaming_ds(ctx, x, y)
    regs = [0.0, 0.01, 0.1, 1.0]
    try:
        models = LogisticRegression(maxIter=40, tol=1e-9).fit_stacked(
            sds, reg_params=regs)
        assert len(models) == len(regs)
        serial_evals = []
        for kk, r in enumerate(regs):
            m_ref = LogisticRegression(maxIter=40, tol=1e-9,
                                       regParam=r).fit(sds)
            np.testing.assert_allclose(np.asarray(models[kk]._coef),
                                       np.asarray(m_ref._coef),
                                       rtol=1e-9, atol=1e-12)
            np.testing.assert_allclose(np.asarray(models[kk]._icpt),
                                       np.asarray(m_ref._icpt),
                                       rtol=1e-9, atol=1e-12)
            serial_evals.append(m_ref.summary.total_evals)
        s = models[0].summary
        assert s.streamed and s.n_models == len(regs)
        # ONE streamed epoch serves all K models per round
        assert s.total_evals <= max(serial_evals)
        assert s.total_evals < sum(serial_evals)
    finally:
        sds.close()


def test_streamed_stacked_sgd_matches_serial(ctx):
    """``optimize_stacked`` is the model-axis twin of the streamed SGD:
    per-model labels via ``y_stack`` (OvR relabelings), a shared
    mini-batch mask keyed on the true shard index, and per-model
    convergence — each model's trajectory matches its serial streamed
    run at matched seeds."""
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.ml.optim.gradient_descent import SquaredL2Updater
    from cycloneml_tpu.oocore import StreamingGradientDescent
    x, y = _binary_problem(n=1200, d=6, seed=34)
    sds = _streaming_ds(ctx, x, y, shard_rows=400)
    sds_flip = _streaming_ds(ctx, x, 1.0 - y, shard_rows=400)
    try:
        agg = aggregators.binary_logistic(6, fit_intercept=False)
        kw = dict(step_size=1.0, num_iterations=15, reg_param=0.01,
                  updater=SquaredL2Updater(), seed=7,
                  mini_batch_fraction=0.6)
        y_stack = np.stack([y, 1.0 - y])
        W, hists = StreamingGradientDescent(**kw).optimize_stacked(
            sds, agg, np.zeros((2, 6)), y_stack=y_stack)
        w0, h0 = StreamingGradientDescent(**kw).optimize(
            sds, agg, np.zeros(6))
        w1, h1 = StreamingGradientDescent(**kw).optimize(
            sds_flip, agg, np.zeros(6))
        np.testing.assert_allclose(W[0], w0, rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(W[1], w1, rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(hists[0], h0, rtol=1e-9)
        np.testing.assert_allclose(hists[1], h1, rtol=1e-9)
    finally:
        sds.close()
        sds_flip.close()


def test_shard_set_cache_attach_hit_zero_respill(ctx):
    """Tentpole leg (c): the second attach over the same dataset is a
    HIT — a shared view onto the existing spill files, ZERO spill-write
    bytes — and closing one handle releases its refcount without tearing
    the cached files down from under the other."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.oocore import shard_dataset, shard_set_cache
    cache = shard_set_cache()
    cache.clear()
    x, y = _binary_problem(n=900, d=5, seed=35)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    st0 = cache.stats()
    s1 = shard_dataset(ds, shard_rows=300)
    st1 = cache.stats()
    assert st1["misses"] == st0["misses"] + 1
    wrote = st1["spillWriteBytes"] - st0["spillWriteBytes"]
    assert wrote > 0
    try:
        s2 = shard_dataset(ds, shard_rows=300)
        st2 = cache.stats()
        assert st2["hits"] == st1["hits"] + 1
        assert st2["spillWriteBytes"] == st1["spillWriteBytes"]  # 0 re-spill
        assert [a.path for a in s2._shards] == [a.path for a in s1._shards]
        m = LogisticRegression(maxIter=6, regParam=0.1).fit(s2)
        assert m.summary.streamed
        s2.close()
        # s1 still holds a ref: the files survive s2's close
        assert all(os.path.exists(a.path) for a in s1._shards)
        m2 = LogisticRegression(maxIter=6, regParam=0.1).fit(s1)
        np.testing.assert_array_equal(np.asarray(m2._coef),
                                      np.asarray(m._coef))
    finally:
        s1.close()
        cache.clear()


def test_shard_set_cache_keying_negatives(ctx):
    """The content key covers everything that changes the spilled bytes:
    different data, different shard geometry, and a different stream
    tier each MISS — attaching never serves a spill built for other
    bytes."""
    from cycloneml_tpu.oocore import shard_dataset, shard_set_cache
    cache = shard_set_cache()
    cache.clear()
    x, y = _binary_problem(n=800, d=5, seed=36)
    x2 = x.copy()
    x2[0, 0] += 1.0
    ds = InstanceDataset.from_numpy(ctx, x, y)
    ds2 = InstanceDataset.from_numpy(ctx, x2, y)
    st0 = cache.stats()
    handles = [shard_dataset(ds, shard_rows=300)]
    try:
        handles.append(shard_dataset(ds, shard_rows=128))  # geometry
        handles.append(shard_dataset(ds2, shard_rows=300))  # content
        ctx.conf.set("cyclone.oocore.streamDtype", "float8")
        try:
            handles.append(shard_dataset(ds, shard_rows=300))  # tier
        finally:
            ctx.conf.remove("cyclone.oocore.streamDtype")
        st = cache.stats()
        assert st["hits"] == st0["hits"]
        assert st["misses"] == st0["misses"] + 4
    finally:
        for h in handles:
            h.close()
        cache.clear()


def test_shard_set_cache_eviction_pins_live_streams(ctx):
    """The byte bound LRU-evicts — but NEVER an entry with a live handle:
    under a bound that fits one entry, the pinned set survives two
    further builds (the released one is the victim) and still serves a
    fit afterwards."""
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.oocore import shard_dataset, shard_set_cache
    cache = shard_set_cache()
    cache.clear()
    probs = [_binary_problem(n=900, d=6, seed=s) for s in (37, 38, 39)]
    dss = [InstanceDataset.from_numpy(ctx, x, y) for x, y in probs]
    st0 = cache.stats()
    live = shard_dataset(dss[0], shard_rows=300)
    nb = cache.stats()["bytes"]
    assert nb > 0
    ctx.conf.set("cyclone.oocore.cacheBytes", str(nb))  # one entry fits
    try:
        other = shard_dataset(dss[1], shard_rows=300)
        other_paths = [s.path for s in other._shards]
        other.close()   # refs 0 → evictable; live stays pinned
        third = shard_dataset(dss[2], shard_rows=300)
        third.close()
        st = cache.stats()
        assert st["evictionsLru"] >= st0["evictionsLru"] + 1
        # the released entry's files are gone, the pinned one's remain
        assert not any(os.path.exists(p) for p in other_paths)
        assert all(os.path.exists(s.path) for s in live._shards)
        m = LogisticRegression(maxIter=5, regParam=0.1).fit(live)
        assert m.summary.streamed
    finally:
        ctx.conf.remove("cyclone.oocore.cacheBytes")
        live.close()
        cache.clear()


def test_shard_set_cache_bypass_modes(ctx):
    """cacheBytes=0 and an explicit spill_dir both restore the pre-cache
    contract: a direct build that OWNS its files (closed → unlinked)."""
    from cycloneml_tpu.oocore import shard_dataset, shard_set_cache
    cache = shard_set_cache()
    cache.clear()
    x, y = _binary_problem(n=600, d=4, seed=40)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    ctx.conf.set("cyclone.oocore.cacheBytes", "0")
    try:
        st0 = cache.stats()
        sds = shard_dataset(ds, shard_rows=200)
        assert cache.stats() == st0    # the cache never saw it
        paths = [s.path for s in sds._shards]
        sds.close()
        assert not any(os.path.exists(p) for p in paths)  # owned + removed
    finally:
        ctx.conf.remove("cyclone.oocore.cacheBytes")
        cache.clear()


def test_fp8_stream_attribution_bytes_and_cache_hits(ctx):
    """Usage attribution across the new planes: staged h2dBytes bill at
    the staged arrays' ACTUAL itemsize — an fp8 epoch's X stream bills 1
    byte/element where the bf16 rung bills 2 — and shard-set cache hits
    land on the calling scope's ``cacheHits`` ledger field."""
    import jax.numpy as jnp
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.observe import attribution
    from cycloneml_tpu.oocore import (StreamingDataset, StreamingLossFunction,
                                      shard_dataset, shard_set_cache)
    d = 64
    x, y = _binary_problem(n=1600, d=d, seed=41)

    def chunks():
        for lo in range(0, len(x), 400):
            yield x[lo:lo + 400], y[lo:lo + 400], None

    attribution.disable()
    led = attribution.enable()
    cache = shard_set_cache()
    cache.clear()
    try:
        staged = {}
        for tier in ("bfloat16", "float8"):
            sds = StreamingDataset.from_chunks(ctx, chunks(), d,
                                               shard_rows=400,
                                               stream_dtype=tier)
            try:
                agg = aggregators.binary_logistic(d, fit_intercept=False)
                f = StreamingLossFunction(sds, agg)
                with attribution.scope(f"epoch-{tier}"):
                    f.sweep(jnp.zeros(d, jnp.float32))
                staged[tier] = led.row(f"epoch-{tier}")["h2dBytes"]
                geom = (sds.n_shards, sds.pad_rows)
            finally:
                sds.close()
        assert staged["float8"] > 0
        assert staged["float8"] < staged["bfloat16"]
        # exact byte math: X bytes halve (1 vs 2 per element) while y/w
        # ride the accumulator tier in both, so the delta is EXACTLY one
        # epoch of X at one byte per element over the padded geometry —
        # the ledger bills the staged arrays' actual itemsize, not an
        # assumed bf16 width
        n_shards, pad_rows = geom
        assert staged["bfloat16"] - staged["float8"] \
            == n_shards * pad_rows * d
        ds = InstanceDataset.from_numpy(ctx, *_binary_problem(
            n=600, d=4, seed=42))
        with attribution.scope("cache-job"):
            a = shard_dataset(ds, shard_rows=200)
            b = shard_dataset(ds, shard_rows=200)
        assert led.row("cache-job")["cacheHits"] == 1
        a.close()
        b.close()
    finally:
        cache.clear()
        attribution.disable()
