"""Concurrency regressions pinned by the graftlint JX011–JX014 self-run.

Each test pins a product fix the PR-9 self-run surfaced (see
docs/graftlint.md, "the self-run ledger"): torn metric moments, context
double-stop, heartbeat start/stop races. These are real-schedule tests —
they hammer the fixed path from threads and assert the invariant the lock
now guarantees. Post-fix they are deterministic passes; pre-fix the
metric ones fail with high probability and the rest are racy-by-schedule.
"""

import threading

import pytest

from cycloneml_tpu.util.metrics import Counter, Histogram


def _hammer(n_threads, fn):
    stop = threading.Event()
    errs = []

    def run():
        try:
            while not stop.is_set():
                fn()
        except Exception as e:   # pragma: no cover - the failure path
            errs.append(e)
            stop.set()

    threads = [threading.Thread(target=run, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    return stop, threads, errs


def test_histogram_mean_is_never_torn_under_concurrent_observe():
    """Histogram.mean used to read `_sum` then `_count` without the lock:
    an update between the two reads pairs a stale sum with a fresh count.
    With every sample == 1.0 the true mean is exactly 1.0 ALWAYS — any
    other value is a torn read."""
    h = Histogram(window=64)
    h.update(1.0)
    stop, threads, errs = _hammer(4, lambda: h.update(1.0))
    try:
        for _ in range(20000):
            m = h.mean
            assert m == 1.0, f"torn mean {m!r} (sum/count mismatch)"
            c = h.count
            assert c >= 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    assert not errs
    snap = h.snapshot()
    assert snap["mean"] == 1.0 and snap["p50"] == 1.0


def test_counter_count_reads_under_the_lock():
    c = Counter()
    n_threads, per = 8, 2000
    barrier = threading.Barrier(n_threads)

    def run():
        barrier.wait()
        for _ in range(per):
            c.inc()

    threads = [threading.Thread(target=run) for _ in range(n_threads)]
    for t in threads:
        t.start()
    seen = 0
    for _ in range(1000):
        now = c.count
        assert now >= seen   # monotone under concurrent inc
        seen = now
    for t in threads:
        t.join(timeout=10)
    assert c.count == n_threads * per


def test_context_stop_is_idempotent_under_concurrent_calls():
    """stop() used to check-then-act on `_stopped` without a lock: two
    concurrent stop() calls both passed the check and double-posted
    ApplicationEnd (and double-shutdown plugins). Now the flag flips
    under `_hb_lock` — exactly one ApplicationEnd however many threads
    race the call."""
    from cycloneml_tpu import context as ctx_mod
    from cycloneml_tpu.context import CycloneContext

    # run a private context beside whatever the session fixture holds
    with ctx_mod._active_lock:
        old = ctx_mod._active_context
        ctx_mod._active_context = None
    try:
        # same master as the session fixture: a second mesh master would
        # refuse to initialise beside the active local-mesh
        ctx = CycloneContext(master="local-mesh[8]", app_name="stop-race")
        ends = []
        ctx.listener_bus.add_listener(
            lambda e: ends.append(e)
            if type(e).__name__ == "ApplicationEnd" else None)
        barrier = threading.Barrier(8)

        def race():
            barrier.wait()
            ctx.stop()

        threads = [threading.Thread(target=race) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        ctx.listener_bus.wait_until_empty()
        assert len(ends) == 1, f"stop() ran {len(ends)} times"
    finally:
        with ctx_mod._active_lock:
            ctx_mod._active_context = old


def test_heartbeat_receiver_start_stop_race_leaves_no_thread():
    """start() used to check-then-create `_thread` without the lock
    (double-start orphans a sweep thread); stop() read and nulled it
    unguarded. Both now hold the receiver's own lock; after any
    interleaving of concurrent start/stop + a final stop, no sweep
    thread survives."""
    from cycloneml_tpu.parallel.resilience import HeartbeatReceiver

    # other fixtures (the session context) legitimately run their own
    # sweep thread — only threads born in THIS test count as leaks
    pre_existing = {id(t) for t in threading.enumerate()
                    if t.name == "cyclone-heartbeat"}
    for _ in range(20):
        hb = HeartbeatReceiver(timeout_s=30.0, check_interval_s=30.0)
        barrier = threading.Barrier(4)

        def flip(i, hb=hb, barrier=barrier):
            barrier.wait()
            (hb.start if i % 2 == 0 else hb.stop)()

        threads = [threading.Thread(target=flip, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        hb.stop()
        assert hb._thread is None
    alive = [t.name for t in threading.enumerate()
             if t.name == "cyclone-heartbeat" and t.is_alive()
             and id(t) not in pre_existing]
    assert not alive, f"orphaned sweep threads: {alive}"


def test_lane_tallies_exact_after_concurrent_predicts():
    """ModelLane.stats() used to read the tally fields one by one with no
    lock while the worker updated them under the cv — a scrape racing a
    dispatch could pair this batch's `rows` with last batch's `batches`.
    Now the whole tally row is one cv acquisition: every snapshot obeys
    the tally invariants, and the final counts are exact."""
    np = pytest.importorskip("numpy")
    pytest.importorskip("jax")
    from cycloneml_tpu.ml.classification.logistic_regression import (
        LogisticRegressionModel,
    )
    from cycloneml_tpu.serving.server import ModelServer

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 4))
    model = LogisticRegressionModel(rng.normal(size=(1, 4)),
                                    rng.normal(size=(1,)), 2, False)

    with ModelServer(ctx=None, max_batch=8, window_ms=1.0,
                     max_queue=256) as server:
        server.register("m", model)
        n_threads, per = 4, 25
        rows_each = 2
        errs = []
        snapshots = []
        done = threading.Event()

        def client():
            try:
                for _ in range(per):
                    server.predict("m", X[:rows_each])
            except Exception as e:   # pragma: no cover
                errs.append(e)

        def scraper():
            while not done.is_set():
                s = server.stats()["models"]["m"]
                # tally-row invariants: a torn read can violate these
                assert s["requests"] >= s["batches"] >= 0
                assert s["rows"] >= s["requests"] * 0  # non-negative
                assert s["coalesced"] <= s["requests"]
                snapshots.append(s)

        threads = [threading.Thread(target=client)
                   for _ in range(n_threads)]
        sc = threading.Thread(target=scraper, daemon=True)
        sc.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        done.set()
        sc.join(timeout=10)
        assert not errs
        final = server.stats()["models"]["m"]
        assert final["requests"] == n_threads * per
        assert final["rows"] == n_threads * per * rows_each
        assert snapshots, "scraper never ran"
