"""GLM / AFT / Isotonic parity tests.

GLM families are checked against sklearn's unpenalized GLM solvers (exact MLE
for the same likelihood — the reference's own suites assert against R glm the
same way). AFT is checked against a scipy.optimize fit of the identical
censored-Weibull NLL; Isotonic against sklearn's PAV.
"""

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.regression import (
    AFTSurvivalRegression, GeneralizedLinearRegression, IsotonicRegression,
)


def _xy(seed=0, n=400, d=4):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    beta = np.array([0.5, -0.3, 0.2, 0.1])[:d]
    return rng, x, beta


# -- GLM ----------------------------------------------------------------------

def test_glm_poisson_log_vs_sklearn(ctx):
    from sklearn.linear_model import PoissonRegressor
    rng, x, beta = _xy(0)
    y = rng.poisson(np.exp(x @ beta + 0.3)).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    m = GeneralizedLinearRegression(family="poisson", maxIter=50).fit(frame)
    sk = PoissonRegressor(alpha=0.0, max_iter=500, tol=1e-10).fit(x, y)
    np.testing.assert_allclose(m.coefficients.to_array(), sk.coef_, atol=1e-6)
    np.testing.assert_allclose(m.intercept, sk.intercept_, atol=1e-6)
    s = m.summary
    assert s.deviance < s.null_deviance
    assert s.num_iterations <= 50
    assert np.isfinite(s.aic)
    # significant features have small p-values, noise intercept-ish ones don't
    assert (s.p_values[:2] < 1e-4).all()


def test_glm_gamma_log_vs_sklearn(ctx):
    from sklearn.linear_model import GammaRegressor
    rng, x, beta = _xy(1)
    y = rng.gamma(2.0, np.exp(x @ beta + 0.3) / 2.0)
    frame = MLFrame(ctx, {"features": x, "label": y})
    m = GeneralizedLinearRegression(family="gamma", link="log",
                                    maxIter=50).fit(frame)
    sk = GammaRegressor(alpha=0.0, max_iter=500, tol=1e-10).fit(x, y)
    np.testing.assert_allclose(m.coefficients.to_array(), sk.coef_, atol=1e-6)
    # dispersion via Pearson chi2 / dof should be near 1/shape = 0.5
    assert 0.3 < m.summary.dispersion < 0.8


def test_glm_binomial_logit_matches_logreg(ctx):
    from sklearn.linear_model import LogisticRegression as SKL
    rng, x, beta = _xy(2)
    p = 1.0 / (1.0 + np.exp(-(x @ beta + 0.3)))
    y = (rng.rand(len(p)) < p).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    m = GeneralizedLinearRegression(family="binomial").fit(frame)
    sk = SKL(C=np.inf, tol=1e-10, max_iter=1000).fit(x, y)
    np.testing.assert_allclose(m.coefficients.to_array(), sk.coef_[0], atol=1e-5)


def test_glm_gaussian_identity_is_ols(ctx):
    rng, x, beta = _xy(3)
    y = x @ beta + 0.3 + 0.1 * rng.randn(len(x))
    frame = MLFrame(ctx, {"features": x, "label": y})
    m = GeneralizedLinearRegression().fit(frame)
    ref = np.linalg.lstsq(np.c_[x, np.ones(len(y))], y, rcond=None)[0]
    np.testing.assert_allclose(m.coefficients.to_array(), ref[:-1], atol=1e-8)
    np.testing.assert_allclose(m.intercept, ref[-1], atol=1e-8)
    # standard errors match the classic OLS formula
    resid = y - (x @ ref[:-1] + ref[-1])
    sigma2 = resid @ resid / (len(y) - x.shape[1] - 1)
    xa = np.c_[x, np.ones(len(y))]
    se_ref = np.sqrt(np.diag(np.linalg.inv(xa.T @ xa)) * sigma2)
    np.testing.assert_allclose(m.summary.coefficient_standard_errors, se_ref,
                               rtol=1e-6)


def test_glm_tweedie_vs_sklearn(ctx):
    from sklearn.linear_model import TweedieRegressor
    rng, x, beta = _xy(4)
    y = np.maximum(rng.gamma(2.0, np.exp(x @ beta) / 2.0)
                   * (rng.rand(len(x)) > 0.2), 0.0)
    frame = MLFrame(ctx, {"features": x, "label": y})
    m = GeneralizedLinearRegression(family="tweedie", variancePower=1.5,
                                    linkPower=0.0, maxIter=100,
                                    tol=1e-10).fit(frame)
    sk = TweedieRegressor(power=1.5, alpha=0.0, link="log", max_iter=20000,
                          tol=1e-14).fit(x, y)
    np.testing.assert_allclose(m.coefficients.to_array(), sk.coef_, atol=1e-6)


def test_glm_offset(ctx):
    rng, x, beta = _xy(5)
    y = rng.poisson(np.exp(x @ beta + 0.5)).astype(float)
    offset = np.full(len(y), 0.5)
    frame = MLFrame(ctx, {"features": x, "label": y, "off": offset})
    m = GeneralizedLinearRegression(family="poisson",
                                    offsetCol="off").fit(frame)
    # with the true offset supplied, the intercept should shrink toward 0
    m0 = GeneralizedLinearRegression(family="poisson").fit(frame)
    assert abs(m.intercept) < abs(m0.intercept)
    np.testing.assert_allclose(m.coefficients.to_array(),
                               m0.coefficients.to_array(), atol=0.05)


def test_glm_offset_transform_and_residuals(ctx):
    rng, x, beta = _xy(8)
    y = rng.poisson(np.exp(x @ beta + 0.5)).astype(float)
    offset = np.full(len(y), 0.5)
    frame = MLFrame(ctx, {"features": x, "label": y, "off": offset})
    m = GeneralizedLinearRegression(family="poisson", offsetCol="off",
                                    linkPredictionCol="eta").fit(frame)
    out = m.transform(frame)
    # transform must apply the offset: prediction == exp(Xβ + b + offset)
    eta = x @ m.coefficients.to_array() + m.intercept + offset
    np.testing.assert_allclose(out["prediction"], np.exp(eta), rtol=1e-10)
    np.testing.assert_allclose(out["eta"], eta, rtol=1e-10)
    # all four residual types are finite and consistent
    for rt in ("response", "working", "pearson", "deviance"):
        r = m.summary.residuals(rt)
        assert np.isfinite(r).all() and r.shape == y.shape
    # deviance residuals sum of squares equals the model deviance
    dev_r = m.summary.residuals("deviance")
    np.testing.assert_allclose((dev_r ** 2).sum(), m.summary.deviance,
                               rtol=1e-8)


def test_glm_tweedie_residuals_no_crash(ctx):
    rng, x, beta = _xy(9)
    y = np.maximum(rng.gamma(2.0, np.exp(x @ beta) / 2.0)
                   * (rng.rand(len(x)) > 0.2), 0.0)
    frame = MLFrame(ctx, {"features": x, "label": y})
    m = GeneralizedLinearRegression(family="tweedie",
                                    variancePower=1.5).fit(frame)
    for rt in ("response", "working", "pearson", "deviance"):
        assert np.isfinite(m.summary.residuals(rt)).all()


def test_glm_bad_variance_power_rejected(ctx):
    rng, x, beta = _xy(10)
    y = np.abs(x @ beta) + 1.0
    frame = MLFrame(ctx, {"features": x, "label": y})
    with pytest.raises(ValueError):
        GeneralizedLinearRegression(family="tweedie",
                                    variancePower=-1.0).fit(frame)


def test_aft_quantiles_col(ctx):
    x, y, censor = _aft_data(seed=13)
    frame = MLFrame(ctx, {"features": x, "label": y, "censor": censor})
    m = AFTSurvivalRegression(quantilesCol="q",
                              quantileProbabilities=[0.25, 0.5]).fit(frame)
    out = m.transform(frame)
    assert out["q"].shape == (len(y), 2)
    np.testing.assert_allclose(out["q"], m.predict_quantiles(x), rtol=1e-12)


def test_glm_weights(ctx):
    # integer weights ≡ row replication (the defining property of weighted GLM)
    rng, x, beta = _xy(6, n=120)
    y = rng.poisson(np.exp(x @ beta)).astype(float)
    w = rng.randint(1, 4, len(y)).astype(float)
    frame_w = MLFrame(ctx, {"features": x, "label": y, "w": w})
    rep = np.repeat(np.arange(len(y)), w.astype(int))
    frame_r = MLFrame(ctx, {"features": x[rep], "label": y[rep]})
    mw = GeneralizedLinearRegression(family="poisson", weightCol="w").fit(frame_w)
    mr = GeneralizedLinearRegression(family="poisson").fit(frame_r)
    np.testing.assert_allclose(mw.coefficients.to_array(),
                               mr.coefficients.to_array(), atol=1e-7)


def test_glm_persistence(ctx, tmp_path):
    rng, x, beta = _xy(7)
    y = rng.poisson(np.exp(x @ beta)).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    m = GeneralizedLinearRegression(family="poisson", link="log").fit(frame)
    path = str(tmp_path / "glm")
    m.save(path)
    from cycloneml_tpu.ml.regression import GeneralizedLinearRegressionModel
    m2 = GeneralizedLinearRegressionModel.load(path)
    np.testing.assert_allclose(m2.coefficients.to_array(),
                               m.coefficients.to_array())
    assert m2.get("family") == "poisson"
    pred1 = m.transform(frame)["prediction"]
    pred2 = m2.transform(frame)["prediction"]
    np.testing.assert_allclose(pred1, pred2)


# -- AFT ----------------------------------------------------------------------

def _aft_data(seed=10, n=500, d=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    beta = np.array([0.4, -0.2, 0.3])[:d]
    sigma = 0.7
    # Weibull AFT: log T = Xβ + b + σ W, W ~ Gumbel(min)
    w_noise = np.log(-np.log(1.0 - rng.rand(n)))
    t = np.exp(x @ beta + 1.0 + sigma * w_noise)
    c = np.exp(x @ np.zeros(d) + 1.5 + rng.randn(n))  # censoring times
    y = np.minimum(t, c)
    censor = (t <= c).astype(float)  # 1 = event observed
    return x, y, censor


def _aft_nll_numpy(params, x, y, censor):
    d = x.shape[1]
    beta, icpt, log_sigma = params[:d], params[d], params[d + 1]
    sigma = np.exp(log_sigma)
    eps = (np.log(y) - x @ beta - icpt) / sigma
    ll = censor * (eps - log_sigma) - np.exp(eps)
    return -ll.mean()


def test_aft_matches_scipy_mle(ctx):
    from scipy.optimize import minimize
    x, y, censor = _aft_data()
    frame = MLFrame(ctx, {"features": x, "label": y, "censor": censor})
    m = AFTSurvivalRegression(maxIter=200, tol=1e-9).fit(frame)
    res = minimize(_aft_nll_numpy, np.zeros(x.shape[1] + 2),
                   args=(x, y, censor), method="L-BFGS-B",
                   options={"maxiter": 1000, "ftol": 1e-14, "gtol": 1e-10})
    ref_beta = res.x[:x.shape[1]]
    np.testing.assert_allclose(m.coefficients.to_array(), ref_beta, atol=1e-3)
    np.testing.assert_allclose(m.intercept, res.x[x.shape[1]], atol=1e-3)
    np.testing.assert_allclose(m.scale, np.exp(res.x[-1]), atol=1e-3)
    # recovered parameters near the generating ones
    assert abs(m.scale - 0.7) < 0.15


def test_aft_quantiles_median_consistency(ctx):
    x, y, censor = _aft_data(seed=11)
    frame = MLFrame(ctx, {"features": x, "label": y, "censor": censor})
    m = AFTSurvivalRegression(quantileProbabilities=[0.5]).fit(frame)
    q = m.predict_quantiles(x[:5])
    lam = np.exp(x[:5] @ m.coefficients.to_array() + m.intercept)
    np.testing.assert_allclose(
        q[:, 0], lam * (-np.log(0.5)) ** m.scale, rtol=1e-10)


def test_aft_persistence(ctx, tmp_path):
    x, y, censor = _aft_data(seed=12)
    frame = MLFrame(ctx, {"features": x, "label": y, "censor": censor})
    m = AFTSurvivalRegression().fit(frame)
    path = str(tmp_path / "aft")
    m.save(path)
    from cycloneml_tpu.ml.regression import AFTSurvivalRegressionModel
    m2 = AFTSurvivalRegressionModel.load(path)
    np.testing.assert_allclose(m2.coefficients.to_array(),
                               m.coefficients.to_array())
    assert m2.scale == m.scale


# -- Isotonic -----------------------------------------------------------------

def test_isotonic_vs_sklearn(ctx):
    from sklearn.isotonic import IsotonicRegression as SKIso
    rng = np.random.RandomState(20)
    f = rng.uniform(0, 10, 300)
    y = 0.5 * f + rng.randn(300)
    frame = MLFrame(ctx, {"features": f, "label": y})
    m = IsotonicRegression().fit(frame)
    sk = SKIso(out_of_bounds="clip").fit(f, y)
    np.testing.assert_allclose(m.transform(frame)["prediction"],
                               sk.predict(f), atol=1e-9)
    # out-of-range clamping
    np.testing.assert_allclose(
        m._predict_batch(np.array([-100.0, 100.0])),
        sk.predict(np.array([-100.0, 100.0])), atol=1e-9)


def test_isotonic_weighted_and_antitonic(ctx):
    from sklearn.isotonic import IsotonicRegression as SKIso
    rng = np.random.RandomState(21)
    f = rng.uniform(0, 5, 200)
    y = -0.7 * f + rng.randn(200)
    w = rng.uniform(0.5, 2.0, 200)
    frame = MLFrame(ctx, {"features": f, "label": y, "w": w})
    m = IsotonicRegression(isotonic=False, weightCol="w").fit(frame)
    sk = SKIso(increasing=False, out_of_bounds="clip").fit(f, y, sample_weight=w)
    np.testing.assert_allclose(m.transform(frame)["prediction"],
                               sk.predict(f), atol=1e-9)


def test_isotonic_persistence(ctx, tmp_path):
    rng = np.random.RandomState(22)
    f = rng.uniform(0, 10, 100)
    y = f + rng.randn(100)
    frame = MLFrame(ctx, {"features": f, "label": y})
    m = IsotonicRegression().fit(frame)
    path = str(tmp_path / "iso")
    m.save(path)
    from cycloneml_tpu.ml.regression import IsotonicRegressionModel
    m2 = IsotonicRegressionModel.load(path)
    np.testing.assert_allclose(m2.boundaries, m.boundaries)
    np.testing.assert_allclose(m2.predictions, m.predictions)


def test_tweedie_label_domain_validation(ctx):
    """ref Tweedie.initialize:624-632: y=0 is legal in the compound-
    Poisson band (1<=p<2) but must RAISE for p>=2 — silently NaN
    deviances are not an answer (review r5)."""
    from cycloneml_tpu.ml.regression import GeneralizedLinearRegression
    x = np.array([[1.0], [2.0], [3.0]])
    y0 = np.array([0.0, 1.0, 2.0])
    frame = MLFrame(ctx, {"features": x, "label": y0})
    m = GeneralizedLinearRegression(family="tweedie", variancePower=1.5,
                                    maxIter=5).fit(frame)
    assert np.isfinite(m.summary.deviance)
    with pytest.raises(ValueError, match="positive"):
        GeneralizedLinearRegression(family="tweedie", variancePower=2.5,
                                    maxIter=5).fit(frame)
    with pytest.raises(ValueError, match="non-negative"):
        GeneralizedLinearRegression(
            family="tweedie", variancePower=1.5, maxIter=5).fit(
                MLFrame(ctx, {"features": x,
                              "label": np.array([-1.0, 1.0, 2.0])}))
