"""Observability tests: metrics system, status store, history replay.

Models the reference's status/metrics coverage (ref:
AppStatusListenerSuite, MetricsSystemSuite, FsHistoryProviderSuite).
"""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from cycloneml_tpu.util.events import (ApplicationEnd, ApplicationStart,
                                       CheckpointWritten, JobEnd, JobStart,
                                       ListenerBus, MeshUp, StepCompleted,
                                       WorkerLost)
from cycloneml_tpu.util.metrics import (ConsoleSink, CsvSink, MetricsRegistry,
                                        MetricsSystem, prometheus_text)
from cycloneml_tpu.util.status import (AppStatusListener, HistoryProvider,
                                       api_v1)


# -- metrics primitives ----------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(2)
    state = {"v": 7.0}
    reg.gauge("g", lambda: state["v"])
    for i in range(10):
        reg.histogram("h").update(float(i))
    with reg.timer("t"):
        pass
    vals = reg.values()
    assert vals["c"] == 3
    assert vals["g"] == 7.0
    assert vals["h.count"] == 10 and vals["h.mean"] == 4.5
    assert vals["h.p50"] == 4.0 and vals["h.max"] == 9.0
    assert vals["t.count"] == 1
    state["v"] = 9.0
    assert reg.values()["g"] == 9.0


def test_timer_nesting_and_threads():
    """One shared registry timer must survive nesting (Pipeline.fit wraps
    stage fits) and concurrent use without corrupting durations."""
    import threading as th
    reg = MetricsRegistry()
    t = reg.timer("d")
    with t:
        time.sleep(0.05)
        with t:
            time.sleep(0.01)
    snap = t.snapshot()
    assert snap["count"] == 2
    assert snap["max"] >= 0.055  # outer duration not clobbered by inner

    def worker():
        with t:
            time.sleep(0.02)

    threads = [th.Thread(target=worker) for _ in range(4)]
    for x in threads:
        x.start()
    for x in threads:
        x.join()
    assert t.count == 6


def test_csv_sink(tmp_path):
    sink = CsvSink(str(tmp_path))
    sink.report({"a.b": 1.5})
    sink.report({"a.b": 2.5})
    lines = open(tmp_path / "a.b.csv").read().strip().split("\n")
    assert lines[0] == "t,value"
    assert len(lines) == 3
    assert lines[1].endswith(",1.5") and lines[2].endswith(",2.5")


def test_csv_sink_sanitizes_hostile_metric_names(tmp_path):
    """Names with path separators / traversal / absolute paths must stay
    inside the sink directory and must not crash open()."""
    sink = CsvSink(str(tmp_path / "sink"))
    sink.report({
        "sql/exchange/bytes": 1.0,
        "../escape": 2.0,
        "/etc/passwd": 3.0,
        "..": 4.0,
    })
    written = sorted(os.listdir(tmp_path / "sink"))
    assert written == ["_.csv", "_escape.csv", "_etc_passwd.csv",
                       "sql_exchange_bytes.csv"]
    # nothing escaped the sink directory
    assert sorted(os.listdir(tmp_path)) == ["sink"]


def test_histogram_sliding_window_evicts_in_order():
    """deque(maxlen) window: totals keep counting, quantiles see only the
    newest `window` samples."""
    from cycloneml_tpu.util.metrics import Histogram
    h = Histogram(window=4)
    for i in range(10):
        h.update(float(i))
    assert h.count == 10  # lifetime count, not window count
    assert h.quantile(0.25) == 6.0 and h.quantile(1.0) == 9.0
    snap = h.snapshot()
    assert snap["max"] == 9.0 and snap["count"] == 10


def test_prometheus_text_format():
    text = prometheus_text({"jobs.started": 3, "step.loss.mean": 0.25})
    assert "cyclone_jobs_started 3" in text
    assert "cyclone_step_loss_mean 0.25" in text


def test_prometheus_text_skips_non_finite_and_emits_types():
    values = {"ok": 1.0, "bad_nan": float("nan"), "bad_inf": float("inf"),
              "bad_ninf": float("-inf"), "hits": 5,
              "lat.count": 2, "lat.mean": 0.5, "lat.p50": 0.4,
              "lat.p95": 0.9, "lat.max": 1.0}
    text = prometheus_text(values, types={"hits": "counter", "ok": "gauge",
                                          "lat": "summary"})
    assert "bad_nan" not in text and "bad_inf" not in text \
        and "bad_ninf" not in text
    assert "# TYPE cyclone_hits counter" in text
    assert "# TYPE cyclone_ok gauge" in text
    assert "# TYPE cyclone_lat summary" in text
    assert 'cyclone_lat{quantile="0.5"} 0.4' in text
    assert "cyclone_lat_sum 1.0" in text and "cyclone_lat_count 2" in text
    # summary stats are not double-emitted as flat gauges
    assert "cyclone_lat_mean" not in text
    # untyped callers (no types arg) keep the flat legacy format
    legacy = prometheus_text(values)
    assert "# TYPE" not in legacy and "cyclone_lat_mean 0.5" in legacy


def test_prometheus_text_labeled_series():
    """Names carrying a `{k="v"}` suffix (the attribution ledger's
    per-scope gauges) render canonical labeled series: values re-escaped,
    labeled + unlabeled series of one base name under ONE # TYPE line."""
    values = {'usage.deviceSeconds{scope="acme/fit",tenant="acme"}': 1.5,
              'usage.deviceSeconds{scope="solo"}': 0.5,
              "usage.deviceSeconds": 2.0}
    text = prometheus_text(values, types={"usage.deviceSeconds": "gauge"})
    assert text.count("# TYPE cyclone_usage_deviceSeconds gauge") == 1
    assert ('cyclone_usage_deviceSeconds'
            '{scope="acme/fit",tenant="acme"} 1.5') in text
    assert 'cyclone_usage_deviceSeconds{scope="solo"} 0.5' in text
    assert "\ncyclone_usage_deviceSeconds 2.0" in text  # unlabeled sibling


def test_prometheus_text_escapes_hostile_label_values():
    """Quotes/backslashes in a scope key must not break the exposition
    line; hostile label KEYS sanitize to metric-name charset; an
    outright malformed label block flattens into the metric name rather
    than emitting broken exposition."""
    values = {'usage.requests{scope="a\\"b\\\\c",bad.key="v"}': 3}
    text = prometheus_text(values, types={"usage.requests": "counter"})
    line = [ln for ln in text.splitlines()
            if ln.startswith("cyclone_usage_requests{")][0]
    assert 'scope="a\\"b\\\\c"' in line
    assert 'bad_key="v"' in line and "bad.key" not in line
    assert line.endswith(" 3")
    mangled = prometheus_text({'usage.requests{scope=unquoted}': 1})
    assert "{" not in mangled  # flattened, never half-parsed


def test_ledger_gauges_register_and_unregister_with_scope_rows():
    """The attribution ledger's per-scope gauge surface: a new scope row
    registers labeled gauges reading live ledger values; eviction
    unregisters the victim's family so the registry stays bounded."""
    from cycloneml_tpu.observe.attribution import Scope, UsageLedger
    reg = MetricsRegistry()
    led = UsageLedger(max_scopes=2, registry=reg)
    led.charge(Scope("j1", tenant="acme"), deviceSeconds=1.25, requests=2)
    vals = reg.values()
    key = 'usage.deviceSeconds{scope="acme/j1",tenant="acme"}'
    assert vals[key] == 1.25
    assert vals['usage.requests{scope="acme/j1",tenant="acme"}'] == 2
    # the gauge is a live read, not a snapshot
    led.charge(Scope("j1", tenant="acme"), deviceSeconds=0.75)
    assert reg.values()[key] == 2.0
    # evicting acme/j1 (bound 2: j2 + j3 push it out) drops its gauges
    led.charge(Scope("j2"), requests=1)
    led.charge(Scope("j3"), requests=1)
    assert key not in reg.values()
    # and the whole surface exports cleanly through the text format
    text = prometheus_text(reg.values(), types=reg.types())
    assert 'cyclone_usage_requests{scope="j3"} 1.0' in text


def test_registry_types():
    reg = MetricsRegistry()
    reg.counter("c")
    reg.gauge("g", lambda: 1.0)
    reg.histogram("h")
    reg.timer("t")
    assert reg.types() == {"c": "counter", "g": "gauge",
                           "h": "summary", "t": "summary"}


def test_prometheus_http_endpoint():
    ms = MetricsSystem("driver", period_s=100)
    ms.registry.counter("hits").inc(5)
    port = ms.start_prometheus(0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "cyclone_hits 5" in body
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=5)
    finally:
        ms.stop()


def test_prometheus_endpoint_under_concurrent_scrape_and_updates():
    """ThreadingHTTPServer path under contention: N scrapers hammer
    /metrics while M writers update counters/timers — every response must
    be a complete, parseable exposition (HTTP 200, terminated by a
    newline, no interleaving corruption), and no request may error."""
    import threading as th
    ms = MetricsSystem("driver", period_s=100)
    reg = ms.registry
    reg.counter("hits").inc()
    port = ms.start_prometheus(0)
    stop = th.Event()
    errors = []
    bodies = []

    def writer(i):
        while not stop.is_set():
            reg.counter("hits").inc()
            reg.timer(f"lat{i}").update(0.001)
            reg.histogram("shared").update(float(i))

    def scraper():
        try:
            for _ in range(20):
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10
                ).read().decode()
                bodies.append(body)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    writers = [th.Thread(target=writer, args=(i,)) for i in range(3)]
    scrapers = [th.Thread(target=scraper) for _ in range(4)]
    for t in writers + scrapers:
        t.start()
    for t in scrapers:
        t.join(timeout=60)
    stop.set()
    for t in writers:
        t.join(timeout=10)
    ms.stop()
    assert not errors
    assert len(bodies) == 80
    for body in bodies:
        assert body.endswith("\n")
        assert "cyclone_hits" in body
        for line in body.strip().split("\n"):
            # every line is a comment or "name value" with a finite value
            if line.startswith("#"):
                continue
            name, value = line.rsplit(" ", 1)
            float(value)  # parseable, and
            assert value.lower() not in ("nan", "inf", "-inf")


def test_poisoned_gauge_is_skipped_not_fatal(tmp_path):
    """A gauge whose callback raises must be SKIPPED by the scrape — not
    reported as NaN, and never allowed to kill registry.values(), every
    Sink.report, or the Prometheus endpoint (the device-memory gauges
    poll live backend state that can start failing mid-run)."""
    reg = MetricsRegistry()
    reg.counter("ok.counter").inc(3)
    reg.gauge("ok.gauge", lambda: 1.5)

    def poisoned():
        raise RuntimeError("device went away")

    reg.gauge("bad.gauge", poisoned)
    vals = reg.values()
    assert "bad.gauge" not in vals  # skipped, not NaN
    assert vals["ok.counter"] == 3 and vals["ok.gauge"] == 1.5
    # sinks keep reporting the healthy metrics
    sink = CsvSink(str(tmp_path))
    sink.report(vals)
    assert sorted(os.listdir(tmp_path)) == ["ok.counter.csv", "ok.gauge.csv"]
    # a full MetricsSystem scrape + prometheus exposition stays alive
    ms = MetricsSystem("driver", period_s=100)
    ms.registry.gauge("bad", poisoned)
    ms.registry.counter("alive").inc()
    ms.register_sink(CsvSink(str(tmp_path / "sys")))
    port = ms.start_prometheus(0)
    try:
        ms.report()  # must not raise
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "cyclone_alive 1" in body and "bad" not in body
    finally:
        ms.stop()


def test_metrics_system_periodic_report():
    ms = MetricsSystem("driver", period_s=0.02)
    seen = []

    class Probe:
        def report(self, values):
            seen.append(dict(values))

    ms.register_sink(Probe())
    ms.registry.counter("x").inc()
    ms.start()
    deadline = time.time() + 5
    while time.time() < deadline and not seen:
        time.sleep(0.02)
    ms.stop()
    assert seen and seen[-1]["x"] == 1


# -- status store ---------------------------------------------------------------

def _feed(listener):
    listener(ApplicationStart(app_name="app", app_id="app-1"))
    listener(MeshUp(n_devices=8, platform="cpu", mesh_shape="{}"))
    listener(JobStart(job_id=1, description="LogisticRegression.fit"))
    listener(StepCompleted(job_id=1, step=0, metrics={"loss": 0.69}))
    listener(StepCompleted(job_id=1, step=1, metrics={"loss": 0.42}))
    listener(JobEnd(job_id=1, succeeded=True))
    listener(JobStart(job_id=2, description="bad"))
    listener(JobEnd(job_id=2, succeeded=False, error="boom"))
    listener(CheckpointWritten(path="/ck/step2", step=2))
    listener(WorkerLost(worker_id="w0", reason="heartbeat timeout"))
    listener(ApplicationEnd(app_id="app-1"))


def test_status_listener_folds_events():
    listener = AppStatusListener()
    _feed(listener)
    s = listener.store
    info = s.application_info()
    assert info["id"] == "app-1" and info["endTime"] is not None
    assert info["mesh"]["nDevices"] == 8
    jobs = {j["jobId"]: j for j in s.job_list()}
    assert jobs[1]["status"] == "SUCCEEDED" and jobs[1]["numSteps"] == 2
    assert jobs[2]["status"] == "FAILED" and jobs[2]["error"] == "boom"
    steps = s.steps(1)
    assert [st["metrics"]["loss"] for st in steps] == [0.69, 0.42]
    assert s.checkpoints[0]["step"] == 2
    assert s.worker_failures[0]["workerId"] == "w0"


def test_api_v1_routes():
    listener = AppStatusListener()
    _feed(listener)
    s = listener.store
    assert api_v1(s, "applications")[0]["name"] == "app"
    assert len(api_v1(s, "jobs")) == 2
    assert api_v1(s, "jobs/<id>", 1)["status"] == "SUCCEEDED"
    assert len(api_v1(s, "jobs/<id>/steps", 1)) == 2
    assert api_v1(s, "checkpoints")[0]["path"] == "/ck/step2"
    assert api_v1(s, "workers/failures")[0]["reason"] == "heartbeat timeout"
    with pytest.raises(KeyError):
        api_v1(s, "nope")


def test_untracked_steps_do_not_break_job_list():
    """StepCompleted outside any run_job bracket (job_id 0) and out-of-order
    JobEnd must still yield fully-formed job dicts."""
    listener = AppStatusListener()
    listener(StepCompleted(job_id=0, step=0, metrics={"loss": 1.0}))
    listener(JobEnd(job_id=7, succeeded=True))  # JobEnd before JobStart
    jobs = {j["jobId"]: j for j in listener.store.job_list()}
    assert jobs[0]["description"] == "(untracked)"
    assert jobs[7]["status"] == "SUCCEEDED" and jobs[7]["description"] == ""
    for j in jobs.values():
        assert {"description", "status", "submissionTime",
                "completionTime"} <= set(j)


def test_history_provider_replays_journal(tmp_path):
    """History-server path: JSON-lines journal → same store as live bus
    (ref: FsHistoryProvider.scala:84)."""
    from cycloneml_tpu.util.events import EventJournal
    path = tmp_path / "app-42.jsonl"
    journal = EventJournal(str(path))
    bus = ListenerBus()
    bus.add_listener(journal)
    _feed(bus.post)  # synchronous dispatch (bus not started)
    journal.close()

    hp = HistoryProvider(str(tmp_path))
    apps = hp.applications()
    assert [a["id"] for a in apps] == ["app-42"]
    store = hp.load("app-42")
    assert store.application_info()["id"] == "app-1"
    assert store.job(1)["status"] == "SUCCEEDED"
    assert [st["metrics"]["loss"] for st in store.steps(1)] == [0.69, 0.42]


def test_history_provider_tolerates_torn_journal_lines(tmp_path):
    """A process killed mid-write leaves a truncated trailing JSONL line —
    the exact artifact the chaos harness produces. load() must skip it
    (with a warning) and still serve everything before it; a corrupt line
    in the MIDDLE is likewise skipped rather than truncating the replay."""
    from cycloneml_tpu.util.events import EventJournal
    path = tmp_path / "app-torn.jsonl"
    journal = EventJournal(str(path))
    _feed(journal)
    journal.close()
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"Event": "StepCompleted", "job_id": 1, "st')  # torn tail

    hp = HistoryProvider(str(tmp_path))
    store = hp.load("app-torn")
    assert store.application_info()["id"] == "app-1"
    assert store.job(1)["status"] == "SUCCEEDED"
    assert [st["metrics"]["loss"] for st in store.steps(1)] == [0.69, 0.42]

    # corrupt middle line: later events still replay
    lines = open(path, encoding="utf-8").read().splitlines()
    lines[2] = lines[2][: len(lines[2]) // 2]
    broken = tmp_path / "app-mid.jsonl"
    broken.write_text("\n".join(lines) + "\n", encoding="utf-8")
    events = EventJournal.replay(str(broken))
    assert len(events) == len(lines) - 2  # both bad lines skipped
    assert events[-1]["Event"] == "ApplicationEnd"


def test_journal_roundtrip_matches_live_store_for_traced_fit(ctx, tmp_path):
    """History-server fidelity for the full observability surface: replay
    a traced fit's on-disk journal into a fresh store and the job, its
    steps and its FitProfile — including PR 4's n_models and the cost
    fields — must match the live store exactly."""
    import numpy as np
    from cycloneml_tpu.observe import tracing
    from cycloneml_tpu.util.events import EventJournal

    path = tmp_path / "roundtrip.jsonl"
    journal = EventJournal(str(path))
    tracing.disable()
    tracing.enable(max_spans=50_000)
    ctx.listener_bus.add_listener(journal)
    try:
        from cycloneml_tpu.dataset.frame import MLFrame
        from cycloneml_tpu.ml.classification import LogisticRegression
        rng = np.random.RandomState(13)
        x = rng.randn(128, 6)
        y = (x @ rng.randn(6) > 0).astype(float)
        frame = MLFrame(ctx, {"features": x, "label": y})
        LogisticRegression(maxIter=5, regParam=0.01, tol=0.0).fit(frame)
        assert ctx.listener_bus.wait_until_empty()
    finally:
        ctx.listener_bus.remove_listener(journal)
        journal.close()
        tracing.disable()

    live = ctx.status_store
    jid = max(j["jobId"] for j in live.job_list()
              if "LogisticRegression.fit" in j["description"])
    replayed = AppStatusListener()
    for e in EventJournal.replay(str(path)):
        replayed.on_event(e)
    rs = replayed.store
    assert rs.job(jid) == live.job(jid)
    assert rs.steps(jid) == live.steps(jid)
    live_prof = live.profile(jid)
    assert rs.profile(jid) == live_prof
    # the profile that travelled through disk really carries the rollup
    assert live_prof["n_models"] == 1
    assert live_prof["total_flops"] and live_prof["total_flops"] > 0
    assert live_prof["programs"]
    assert "hbm_peak_bytes" in live_prof  # populated-or-explicitly-null


# -- end-to-end: a real fit shows up in status + metrics ------------------------

def test_fit_tracked_in_status_store(ctx):
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression

    rng = np.random.RandomState(0)
    x = rng.randn(64, 4)
    y = (x @ np.array([1.0, -2.0, 0.5, 0.0]) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    from cycloneml_tpu.conf import LBFGS_DEVICE_CHUNK
    before = len(ctx.status_store.job_list())
    old_chunk = ctx.conf.get(LBFGS_DEVICE_CHUNK)
    ctx.conf.set(LBFGS_DEVICE_CHUNK, 2)  # force >= 2 recorded steps
    try:
        LogisticRegression(maxIter=5, tol=0.0).fit(frame)
    finally:
        ctx.conf.set(LBFGS_DEVICE_CHUNK, old_chunk)
    assert ctx.listener_bus.wait_until_empty()
    jobs = ctx.status_store.job_list()
    assert len(jobs) > before
    fit_jobs = [j for j in jobs if "LogisticRegression.fit" in j["description"]]
    assert fit_jobs and fit_jobs[-1]["status"] == "SUCCEEDED"
    steps = ctx.status_store.steps(fit_jobs[-1]["jobId"])
    # chunked device L-BFGS records one step PER CHUNK (covering several
    # iterations); the host path records one per gradient evaluation
    total_iters = sum(st["metrics"].get("chunk_iterations", 1)
                      for st in steps)
    assert total_iters >= 2 and len(steps) >= 2
    losses = [st["metrics"]["loss"] for st in steps]
    assert losses[-1] < losses[0]  # loss decreased over the fit
    vals = ctx.metrics.registry.values()
    assert vals["steps.completed"] >= len(steps)
    assert vals["jobs.succeeded"] >= 1
    assert vals["mesh.devices"] == 8


# -- the 2-process deploy-harness acceptance (ISSUE 12 tentpole) -----------------

import textwrap  # noqa: E402  (section-local: the telemetry acceptance)

from cycloneml_tpu.observe import (process_lanes, tracing,  # noqa: E402
                                   validate_chrome_trace)
from cycloneml_tpu.observe.collect import (TraceCollector,  # noqa: E402
                                           clear_offset_samples)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_APP = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import numpy as np
    from cycloneml_tpu.conf import CycloneConf
    from cycloneml_tpu.context import CycloneContext
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression

    pid = os.environ.get("CYCLONE_PROC_ID", "0")
    # the telemetry plane needs no jax.distributed: each proc runs its own
    # local mesh; trace context + collector address + heartbeat target all
    # arrive through the deploy launch env
    conf = (CycloneConf().set("cyclone.master", "local-mesh[2]")
            .set("cyclone.worker.id", f"proc{pid}")
            .set("cyclone.telemetry.collect.intervalMs", "100"))
    ctx = CycloneContext(conf)
    rng = np.random.RandomState(int(pid))
    x = rng.randn(96, 4)
    y = (x @ rng.randn(4) > 0).astype(float)
    LogisticRegression(maxIter=3, regParam=0.01, tol=0.0).fit(
        MLFrame(ctx, {"features": x, "label": y}))
    ctx.stop()   # flushes the span shipper
    print(f"proc {pid} done", flush=True)
""")


def test_deploy_two_process_merged_trace(tmp_path):
    """THE acceptance: a 2-process deploy-harness run produces ONE merged
    Chrome trace that validates, holds span lanes from both processes
    (plus the master), correlates the master-side submit span to
    worker-side spans by trace id + parent link, and keeps per-lane
    timestamps monotonic after clock-offset correction."""
    from cycloneml_tpu.deploy import (MasterDaemon, WorkerDaemon, submit_app,
                                      wait_for_app)
    from cycloneml_tpu.parallel.resilience import (HeartbeatReceiver,
                                                   HeartbeatServer)

    tracing.disable()
    tracer = tracing.enable(max_spans=50_000)
    recv = HeartbeatReceiver(timeout_s=60.0, check_interval_s=5.0)
    hb = HeartbeatServer(recv)
    col = TraceCollector(host_label="master", tracer=tracer)
    master = MasterDaemon(port=0, state_path=str(tmp_path / "master.json"))
    workers = [WorkerDaemon(master.address, worker_id=f"w{i}")
               for i in range(2)]
    app_py = tmp_path / "traced_app.py"
    app_py.write_text(_APP)
    env = {
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # extended heartbeats at 100 ms feed the clock-offset estimate
        "CYCLONE_CONF_cyclone__driver__heartbeatAddress": hb.address,
        "CYCLONE_CONF_cyclone__executor__heartbeatInterval": "100",
    }
    try:
        app_id = submit_app(master.address, str(app_py), n_procs=2, env=env)
        assert wait_for_app(master.address, app_id,
                            timeout_s=240) == "FINISHED"
        # both workers' final flushes may trail the FINISHED report
        deadline = time.time() + 30
        while True:
            hosts = col.hosts()
            got = {h for h, rec in hosts.items() if rec["spans"]}
            if {"proc0", "proc1"} <= got:
                break
            assert time.time() < deadline, f"hosts seen: {hosts}"
            time.sleep(0.2)

        # every process joined ONE distributed trace
        hosts = col.hosts()
        assert {hosts["proc0"]["trace_id"],
                hosts["proc1"]["trace_id"]} == {tracer.trace_id}
        # heartbeat-fed clock offsets exist, with their error bound
        for h in ("proc0", "proc1"):
            assert hosts[h]["offset_err_s"] is not None, \
                f"{h} merged without offset samples"

        path = str(tmp_path / "merged.trace.json")
        col.export(path)
        assert validate_chrome_trace(path) == []
        obj = json.load(open(path))
        lanes = process_lanes(obj)
        assert len(lanes) >= 3  # master + proc0 + proc1, labeled
        labels = " ".join(lanes.values())
        assert "proc0" in labels and "proc1" in labels

        # correlation: the master-submitted step's span id parents the
        # worker-side root (job) spans, whose subtrees hold the dispatches
        xevents = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
        submits = [e for e in xevents if e.get("cat") == "deploy"]
        assert submits, "no master-side submit span in the merged trace"
        submit_id = submits[0]["args"]["span_id"]
        assert submit_id.startswith("master/")
        worker_pids = [p for p, label in lanes.items()
                       if "proc0" in label or "proc1" in label]
        for wpid in worker_pids:
            jobs = [e for e in xevents if e["pid"] == wpid
                    and e.get("cat") == "job"
                    and e["args"].get("parent_id") == submit_id]
            assert jobs, f"lane {lanes[wpid]} has no job span parented " \
                         f"to the submit span"
            # and that job has worker-side dispatch spans under it
            jid = jobs[0]["args"]["span_id"]
            children = [e for e in xevents if e["pid"] == wpid
                        and e["args"].get("parent_id") == jid]
            assert children, f"job span {jid} has no children"

        # per-lane monotonic close times after clock-offset correction
        # (record order IS close order per thread; the correction is a
        # constant per host, so order must survive)
        by_lane = {}
        for e in xevents:
            by_lane.setdefault((e["pid"], e["tid"]), []).append(
                e["ts"] + e["dur"])
        for lane, ends in by_lane.items():
            assert ends == sorted(ends), f"lane {lane} not monotonic"
    finally:
        for w in workers:
            w.stop()
        master.stop()
        col.stop()
        hb.stop()
        recv.stop()
        tracing.disable()
        clear_offset_samples()
