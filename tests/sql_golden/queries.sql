-- basic projection + filter + order
SELECT name, salary FROM emp WHERE salary > 75 ORDER BY salary DESC
-- aggregation with HAVING-style filter via nested ordering
SELECT dept, COUNT(*) AS n, AVG(salary) AS avg_sal FROM emp GROUP BY dept ORDER BY dept
-- join + projection
SELECT e.name, d.floor FROM emp e JOIN dept d ON e.dept = d.dept ORDER BY e.id
-- expression arithmetic and aliasing
SELECT name, salary * 1.1 AS raised FROM emp ORDER BY raised DESC LIMIT 3
-- CASE WHEN
SELECT name, CASE WHEN salary >= 100 THEN 'senior' ELSE 'junior' END AS band FROM emp ORDER BY id
-- IN and BETWEEN
SELECT name FROM emp WHERE dept IN ('eng', 'hr') AND salary BETWEEN 60 AND 125 ORDER BY name
-- LIKE
SELECT name FROM emp WHERE name LIKE '%a%' ORDER BY name
-- global aggregate expressions
SELECT COUNT(*) AS n, MIN(salary) AS lo, MAX(salary) AS hi, SUM(salary) / COUNT(*) AS mean FROM emp
-- distinct
SELECT DISTINCT dept FROM emp ORDER BY dept
