-- basic projection + filter + order
SELECT name, salary FROM emp WHERE salary > 75 ORDER BY salary DESC
-- aggregation with HAVING-style filter via nested ordering
SELECT dept, COUNT(*) AS n, AVG(salary) AS avg_sal FROM emp GROUP BY dept ORDER BY dept
-- join + projection
SELECT e.name, d.floor FROM emp e JOIN dept d ON e.dept = d.dept ORDER BY e.id
-- expression arithmetic and aliasing
SELECT name, salary * 1.1 AS raised FROM emp ORDER BY raised DESC LIMIT 3
-- CASE WHEN
SELECT name, CASE WHEN salary >= 100 THEN 'senior' ELSE 'junior' END AS band FROM emp ORDER BY id
-- IN and BETWEEN
SELECT name FROM emp WHERE dept IN ('eng', 'hr') AND salary BETWEEN 60 AND 125 ORDER BY name
-- LIKE
SELECT name FROM emp WHERE name LIKE '%a%' ORDER BY name
-- global aggregate expressions
SELECT COUNT(*) AS n, MIN(salary) AS lo, MAX(salary) AS hi, SUM(salary) / COUNT(*) AS mean FROM emp
-- distinct
SELECT DISTINCT dept FROM emp ORDER BY dept
-- negative literals and unary minus
SELECT name, -salary AS neg FROM emp WHERE salary > -1 ORDER BY neg LIMIT 2
-- modulo and integer arithmetic
SELECT id, id % 2 AS parity FROM emp ORDER BY id
-- nested expressions with parens
SELECT name, (salary + 10) * 2 AS x FROM emp ORDER BY x DESC LIMIT 2
-- NOT / OR precedence
SELECT name FROM emp WHERE NOT (dept = 'eng' OR salary < 80) ORDER BY name
-- IS NULL / IS NOT NULL on nullable column
SELECT item FROM inv WHERE qty IS NULL ORDER BY item
-- no-sqlite IS NOT NULL with arithmetic
SELECT item, qty + 0 AS q FROM inv WHERE qty IS NOT NULL ORDER BY item
-- no-sqlite aggregates over a column with nulls
SELECT COUNT(*) AS rows_n, SUM(qty) AS total FROM inv WHERE qty IS NOT NULL
-- null comparisons exclude rows
SELECT item FROM inv WHERE qty > 5 ORDER BY item
-- no-sqlite CASE with null branch
SELECT item, CASE WHEN qty IS NULL THEN -1 ELSE qty END AS q FROM inv ORDER BY item
-- string functions
SELECT upper(name) AS u, length(name) AS l FROM emp ORDER BY name LIMIT 3
-- lower + like combined
SELECT lower(dept) AS d FROM emp WHERE dept LIKE 'e%' ORDER BY id
-- abs and round
SELECT id, abs(50 - salary) AS dist FROM emp ORDER BY dist LIMIT 3
-- window: row_number per partition
SELECT name, ROW_NUMBER() OVER (PARTITION BY dept ORDER BY salary DESC) AS rn FROM emp ORDER BY name
-- window: rank with ties
SELECT name, RANK() OVER (ORDER BY grade) AS r FROM scores ORDER BY name
-- window: dense_rank with ties
SELECT name, DENSE_RANK() OVER (ORDER BY grade) AS r FROM scores ORDER BY name
-- window: percent_rank
SELECT name, PERCENT_RANK() OVER (ORDER BY grade) AS pr FROM scores ORDER BY name
-- window: cume_dist
SELECT name, CUME_DIST() OVER (ORDER BY grade) AS cd FROM scores ORDER BY name
-- window: ntile buckets
SELECT name, NTILE(2) OVER (ORDER BY grade) AS bucket FROM scores ORDER BY name
-- window: lag and lead
SELECT name, LAG(salary) OVER (ORDER BY id) AS prev, LEAD(salary) OVER (ORDER BY id) AS next FROM emp ORDER BY id
-- window: lag with offset and default
SELECT name, LAG(salary, 2, 0) OVER (ORDER BY id) AS prev2 FROM emp ORDER BY id
-- window: partition sum (no order -> whole partition)
SELECT name, SUM(salary) OVER (PARTITION BY dept) AS dept_total FROM emp ORDER BY id
-- window: running sum (order -> unbounded preceding to current)
SELECT name, SUM(salary) OVER (ORDER BY id) AS running FROM emp ORDER BY id
-- window: running sum per partition
SELECT name, SUM(salary) OVER (PARTITION BY dept ORDER BY id) AS run FROM emp ORDER BY id
-- window: avg over partition
SELECT name, AVG(salary) OVER (PARTITION BY dept) AS dept_avg FROM emp ORDER BY id
-- window: min and max over partition
SELECT name, MIN(salary) OVER (PARTITION BY dept) AS lo, MAX(salary) OVER (PARTITION BY dept) AS hi FROM emp ORDER BY id
-- window: count over partition
SELECT name, COUNT(*) OVER (PARTITION BY dept) AS dept_n FROM emp ORDER BY id
-- window: expression over window result
SELECT name, salary - AVG(salary) OVER (PARTITION BY dept) AS delta FROM emp ORDER BY id
-- window: row_number over multi-column order
SELECT name, ROW_NUMBER() OVER (ORDER BY dept, salary DESC) AS rn FROM emp ORDER BY rn
-- subquery: IN (SELECT ...)
SELECT name FROM emp WHERE dept IN (SELECT dept FROM dept WHERE floor >= 2) ORDER BY name
-- subquery: NOT IN (SELECT ...)
SELECT name FROM emp WHERE dept NOT IN (SELECT dept FROM dept WHERE floor >= 2) ORDER BY name
-- subquery: scalar in WHERE
SELECT name FROM emp WHERE salary > (SELECT AVG(salary) FROM emp) ORDER BY name
-- subquery: scalar arithmetic in WHERE
SELECT name FROM emp WHERE salary >= (SELECT MAX(salary) FROM emp) - 25 ORDER BY name
-- subquery: scalar in SELECT
SELECT name, salary - (SELECT AVG(salary) FROM emp) AS diff FROM emp ORDER BY id
-- subquery: EXISTS true
SELECT COUNT(*) AS n FROM emp WHERE EXISTS (SELECT dept FROM dept WHERE floor = 1)
-- subquery: EXISTS false
SELECT COUNT(*) AS n FROM emp WHERE EXISTS (SELECT dept FROM dept WHERE floor = 99)
-- subquery: NOT EXISTS
SELECT COUNT(*) AS n FROM emp WHERE NOT EXISTS (SELECT dept FROM dept WHERE floor = 99)
-- subquery in FROM
SELECT dept, n FROM (SELECT dept, COUNT(*) AS n FROM emp GROUP BY dept) WHERE n > 1 ORDER BY dept
-- subquery in FROM joined to a table
SELECT s.dept, s.n, d.floor FROM (SELECT dept, COUNT(*) AS n FROM emp GROUP BY dept) s JOIN dept d ON s.dept = d.dept ORDER BY s.dept
-- nested subqueries
SELECT name FROM emp WHERE salary > (SELECT AVG(salary) FROM (SELECT salary FROM emp WHERE dept = 'eng')) ORDER BY name
-- join: left outer keeps unmatched left rows
SELECT e.name, d.floor FROM emp e LEFT JOIN dept d ON e.dept = d.dept ORDER BY e.id
-- join: right outer keeps unmatched right rows
SELECT d.dept, d.floor, e.name FROM emp e RIGHT JOIN dept d ON e.dept = d.dept ORDER BY d.dept, e.name
-- join: full outer
SELECT d.dept, e.name FROM emp e FULL OUTER JOIN dept d ON e.dept = d.dept ORDER BY d.dept, e.name
-- join: many-to-many duplicate keys
SELECT a.tag, b.val FROM t1 a JOIN t2 b ON a.tag = b.tag ORDER BY a.tag, b.val
-- join: USING syntax
SELECT name, floor FROM emp JOIN dept USING (dept) ORDER BY name
-- join: cross join row count
SELECT COUNT(*) AS n FROM t1 CROSS JOIN t2
-- join: self join
SELECT a.name AS lo_name, b.name AS hi_name FROM emp a JOIN emp b ON a.dept = b.dept WHERE a.salary < b.salary ORDER BY lo_name, hi_name
-- join then aggregate
SELECT d.floor, COUNT(*) AS n FROM emp e JOIN dept d ON e.dept = d.dept GROUP BY d.floor ORDER BY d.floor
-- join with extra filter in WHERE
SELECT e.name FROM emp e JOIN dept d ON e.dept = d.dept WHERE d.floor >= 2 AND e.salary > 80 ORDER BY e.name
-- group by expression
SELECT salary >= 85 AS senior, COUNT(*) AS n FROM emp GROUP BY salary >= 85 ORDER BY senior
-- group by with multiple aggregates
SELECT dept, COUNT(*) AS n, SUM(salary) AS total, MIN(salary) AS lo, MAX(salary) AS hi FROM emp GROUP BY dept ORDER BY dept
-- having filters groups
SELECT dept, COUNT(*) AS n FROM emp GROUP BY dept HAVING COUNT(*) > 1 ORDER BY dept
-- having on avg
SELECT dept, AVG(salary) AS a FROM emp GROUP BY dept HAVING AVG(salary) > 80 ORDER BY dept
-- count distinct
SELECT COUNT(DISTINCT dept) AS nd FROM emp
-- group by two keys
SELECT dept, salary >= 85 AS senior, COUNT(*) AS n FROM emp GROUP BY dept, salary >= 85 ORDER BY dept, senior
-- order by aggregate not in select
SELECT dept FROM emp GROUP BY dept ORDER BY SUM(salary) DESC
-- aggregate expression arithmetic
SELECT dept, SUM(salary) / COUNT(*) AS mean FROM emp GROUP BY dept ORDER BY dept
-- aggregate of expression
SELECT dept, SUM(salary * 2) AS dbl FROM emp GROUP BY dept ORDER BY dept
-- empty group result
SELECT dept, COUNT(*) AS n FROM emp WHERE salary > 1000 GROUP BY dept ORDER BY dept
-- no-sqlite global aggregate over empty input
SELECT COUNT(*) AS n, SUM(salary) AS s FROM emp WHERE salary > 1000
-- union all keeps duplicates
SELECT dept FROM (SELECT dept FROM emp UNION ALL SELECT dept FROM dept) ORDER BY dept
-- union deduplicates
SELECT dept FROM (SELECT dept FROM emp UNION SELECT dept FROM dept) ORDER BY dept
-- union all of filtered branches
SELECT name FROM (SELECT name FROM emp WHERE dept = 'eng' UNION ALL SELECT name FROM emp WHERE salary < 75) ORDER BY name
-- case without else yields null
SELECT name, CASE WHEN salary > 100 THEN 'top' END AS tag FROM emp ORDER BY id
-- case with multiple branches
SELECT name, CASE WHEN salary >= 100 THEN 'a' WHEN salary >= 80 THEN 'b' ELSE 'c' END AS band FROM emp ORDER BY id
-- between boundaries are inclusive
SELECT name FROM emp WHERE salary BETWEEN 80 AND 100 ORDER BY name
-- not between
SELECT name FROM emp WHERE salary NOT BETWEEN 80 AND 100 ORDER BY name
-- not in literal list
SELECT name FROM emp WHERE dept NOT IN ('eng') ORDER BY name
-- not like
SELECT name FROM emp WHERE name NOT LIKE '%a%' ORDER BY name
-- like anchored prefix and suffix
SELECT name FROM emp WHERE name LIKE 'a%' OR name LIKE '%e' ORDER BY name
-- like single-char wildcard
SELECT name FROM emp WHERE name LIKE '_ob' ORDER BY name
-- in list of numbers
SELECT name FROM emp WHERE id IN (1, 3, 5) ORDER BY id
-- order by multiple keys mixed directions
SELECT name, dept, salary FROM emp ORDER BY dept ASC, salary DESC
-- order by expression
SELECT name, salary FROM emp ORDER BY salary % 100 LIMIT 3
-- limit larger than rows
SELECT name FROM emp WHERE dept = 'hr' ORDER BY name LIMIT 10
-- limit zero
SELECT name FROM emp LIMIT 0
-- distinct on expression output
SELECT DISTINCT salary >= 85 AS senior FROM emp ORDER BY senior
-- distinct over join
SELECT DISTINCT d.floor FROM emp e JOIN dept d ON e.dept = d.dept ORDER BY d.floor
-- select star
SELECT * FROM dept ORDER BY dept
-- select star with filter
SELECT * FROM emp WHERE dept = 'hr' ORDER BY id
-- scalar subquery from another table
SELECT name FROM emp WHERE salary > (SELECT MIN(floor) FROM dept) * 20 ORDER BY id
-- window + subquery combined
SELECT name, rn FROM (SELECT name, ROW_NUMBER() OVER (PARTITION BY dept ORDER BY salary DESC) AS rn FROM emp) WHERE rn = 1 ORDER BY name
-- top earner per dept via window in FROM subquery
SELECT dept, name FROM (SELECT dept, name, RANK() OVER (PARTITION BY dept ORDER BY salary DESC) AS r FROM emp) WHERE r = 1 ORDER BY dept
-- aggregate over union
SELECT COUNT(*) AS n FROM (SELECT dept FROM emp UNION ALL SELECT dept FROM dept)
-- arithmetic precedence
SELECT 2 + 3 * 4 AS a, (2 + 3) * 4 AS b FROM dept LIMIT 1
-- comparison chain via AND
SELECT name FROM emp WHERE salary >= 80 AND salary <= 100 AND dept = 'sales' ORDER BY name
-- boolean literals
SELECT name FROM emp WHERE true AND NOT false ORDER BY id LIMIT 2
-- string equality and inequality
SELECT name FROM emp WHERE dept <> 'eng' AND dept != 'hr' ORDER BY name
-- division produces floats
SELECT id, salary / 3 AS third FROM emp ORDER BY id LIMIT 3
-- count star vs count column with nulls
SELECT COUNT(*) AS all_rows, COUNT(qty) AS non_null FROM inv
-- group by over nullable column
SELECT kind, COUNT(*) AS n FROM inv GROUP BY kind ORDER BY kind
-- join on t1/t2 left with missing matches
SELECT a.tag, a.x, b.val FROM t1 a LEFT JOIN t2 b ON a.tag = b.tag ORDER BY a.tag, a.x, b.val
-- no-sqlite integer division widens to double (Spark Division rule; sqlite truncates)
SELECT id, id / 2 AS half FROM emp ORDER BY id
-- no-sqlite string-numeric comparison promotes the string side (PromoteStrings)
SELECT name FROM emp WHERE id = '3'
-- no-sqlite explicit CAST, unparseable strings become null
SELECT CAST(floor AS STRING) AS fs, CAST(dept AS DOUBLE) AS fd FROM dept ORDER BY floor
-- no-sqlite string arithmetic casts to double
SELECT name, id + '10' AS shifted FROM emp ORDER BY id
