"""Resilience tests: checkpoints, heartbeats, retries, elastic mesh rebuild.

Models the reference's failure coverage (ref: FailureSuite.scala task-failure
semantics, DistributedSuite:35 executor loss via local-cluster,
HeartbeatReceiverSuite) with the TPU recovery model: checkpoint + resume
instead of lineage recomputation (SURVEY §5.3).
"""

import os

import numpy as np
import pytest

from cycloneml_tpu.ml.optim.lbfgs import LBFGS, OWLQN, OptimState
from cycloneml_tpu.parallel.resilience import (HealthTracker,
                                               HeartbeatReceiver, retry_step,
                                               train_with_checkpoints)
from cycloneml_tpu.util.checkpoint import TrainingCheckpointer
from cycloneml_tpu.util.events import ListenerBus, WorkerLost


def _quadratic(d=6, seed=3):
    rng = np.random.RandomState(seed)
    a = rng.randn(d, d)
    h = a @ a.T + d * np.eye(d)
    b = rng.randn(d)

    def f(x):
        return 0.5 * x @ h @ x - b @ x, h @ x - b

    return f, np.zeros(d)


# -- checkpointer ---------------------------------------------------------------

def test_checkpointer_save_restore_retention(tmp_path):
    ck = TrainingCheckpointer(str(tmp_path), keep_last=2)
    assert ck.latest_step() is None
    for s in (5, 10, 15):
        ck.save(s, {"x": np.arange(3) * s, "nested": {"v": float(s)}},
                metadata={"loss": 1.0 / s})
    assert ck.steps() == [10, 15]  # retention dropped step 5
    got = ck.restore()
    np.testing.assert_array_equal(got["x"], np.arange(3) * 15)
    assert got["nested"]["v"] == 15.0
    assert ck.metadata(15)["loss"] == pytest.approx(1.0 / 15)
    # idempotent re-save of an existing step is a no-op
    ck.save(15, {"x": np.zeros(1), "nested": {"v": 0.0}})
    np.testing.assert_array_equal(ck.restore(15)["x"], np.arange(3) * 15)
    with pytest.raises(FileNotFoundError):
        TrainingCheckpointer(str(tmp_path / "empty")).restore()


def test_checkpointer_ignores_uncommitted(tmp_path):
    ck = TrainingCheckpointer(str(tmp_path))
    # a crash mid-save leaves only a .tmp dir — never visible as a checkpoint,
    # even when the metadata file was already written inside it
    os.makedirs(tmp_path / "step_000000000007.tmp123")
    (tmp_path / "step_000000000007.tmp123" / "METADATA.json").write_text("{}")
    assert ck.latest_step() is None
    ck.save(8, {"x": 1})  # discovery still works alongside the leftover
    assert ck.steps() == [8]


def test_replay_of_finished_job_is_noop(tmp_path):
    """Re-running a job whose final (converged) state was checkpointed must
    return immediately without extra iterations or gradient evaluations."""
    f, x0 = _quadratic()
    ck = TrainingCheckpointer(str(tmp_path))
    final = train_with_checkpoints(LBFGS(max_iter=40, tol=1e-12), f, x0, ck,
                                   interval=3)
    assert final.converged
    evals = {"n": 0}

    def counting_f(x):
        evals["n"] += 1
        return f(x)

    again = train_with_checkpoints(LBFGS(max_iter=40, tol=1e-12), counting_f,
                                   x0, ck, interval=3)
    assert evals["n"] == 0  # no recompute on replay
    assert again.iteration == final.iteration and again.converged


def test_truncated_legacy_checkpoint_surfaces_checkpoint_corrupt(tmp_path):
    """A pre-checksum checkpoint whose state.pkl was truncated must raise
    CheckpointCorrupt (not EOFError/UnpicklingError) and restore() must
    fall back to an older intact step."""
    import json

    from cycloneml_tpu.util.checkpoint import CheckpointCorrupt

    ck = TrainingCheckpointer(str(tmp_path))
    ck.save(2, {"x": np.arange(4.0)})
    # hand-build a LEGACY (no checksums) newest step with a torn payload
    legacy = tmp_path / "step_000000000005"
    os.makedirs(legacy)
    import pickle
    blob = pickle.dumps({"x": np.arange(8.0)})
    (legacy / "state.pkl").write_bytes(blob[: len(blob) // 2])
    (legacy / "METADATA.json").write_text(json.dumps({"step": 5}))

    assert ck.latest_step() == 5
    with pytest.raises(CheckpointCorrupt, match="does not unpickle"):
        ck.restore(5)
    assert ck.latest_verifiable_step() == 2
    np.testing.assert_array_equal(ck.restore()["x"], np.arange(4.0))


def test_checkpoint_metadata_records_checksums(tmp_path):
    ck = TrainingCheckpointer(str(tmp_path))
    ck.save(1, {"w": np.arange(3.0)})
    files = ck.metadata(1)["files"]
    assert set(files) == {"state.pkl"}
    assert len(files["state.pkl"]["sha256"]) == 64
    assert files["state.pkl"]["bytes"] == os.path.getsize(
        tmp_path / "step_000000000001" / "state.pkl")
    assert ck.verify(1)


def test_checkpointer_device_arrays(ctx, tmp_path):
    import jax.numpy as jnp
    ck = TrainingCheckpointer(str(tmp_path))
    ck.save(1, {"w": jnp.arange(4.0)})
    got = ck.restore(1)
    assert isinstance(got["w"], np.ndarray)
    np.testing.assert_array_equal(got["w"], np.arange(4.0))


# -- heartbeats / health --------------------------------------------------------

def test_heartbeat_expiry_and_revival():
    bus = ListenerBus()  # unstarted → synchronous dispatch
    lost_events = []
    bus.add_listener(lambda e: lost_events.append(e)
                     if isinstance(e, WorkerLost) else None)
    hb = HeartbeatReceiver(timeout_s=0.0, listener_bus=bus)
    cb = []
    hb.on_worker_lost(lambda w, r: cb.append(w))
    hb.register("w0")
    hb.register("w1")
    assert hb.live_workers() == ["w0", "w1"]
    import time
    time.sleep(0.01)
    assert sorted(hb.check_now()) == ["w0", "w1"]
    assert sorted(cb) == ["w0", "w1"]
    assert len(lost_events) == 2 and "heartbeat" in lost_events[0].reason
    # an expired worker's heartbeat is rejected; re-registration revives it
    assert not hb.heartbeat("w0")
    hb.register("w0")
    assert hb.heartbeat("w0")
    assert hb.live_workers() == ["w0"]


def test_health_tracker_exclusion():
    ht = HealthTracker(max_failures=2)
    ht.record_failure("w0")
    assert not ht.is_excluded("w0")
    ht.record_failure("w0")
    assert ht.is_excluded("w0") and ht.excluded() == ["w0"]
    ht.record_success("w0")
    assert not ht.is_excluded("w0")


# -- retries --------------------------------------------------------------------

def test_retry_step_recovers_transient():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("DATA_LOSS: simulated device failure")
        return 42

    failures = []
    assert retry_step(flaky, max_failures=4,
                      on_failure=lambda i, e: failures.append(i)) == 42
    assert failures == [0, 1]


def test_retry_step_gives_up():
    def always():
        raise RuntimeError("broken")

    with pytest.raises(RuntimeError, match="failed 3 times"):
        retry_step(always, max_failures=3, backoff_base_s=0.0)


def test_retry_step_fails_fast_on_permanent():
    """TypeError (and tracing errors) mean the step function itself is
    broken: no retries, the original error propagates untouched."""
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise TypeError("jit got a bad argument")

    with pytest.raises(TypeError, match="bad argument"):
        retry_step(broken, max_failures=5)
    assert calls["n"] == 1  # zero retries


def test_retry_step_fails_fast_on_tracer_error():
    import jax

    def traced_branch():
        @jax.jit
        def f(x):
            if x > 0:  # python branch on a tracer
                return x
            return -x
        return f(1.0)

    with pytest.raises(jax.errors.TracerBoolConversionError):
        retry_step(traced_branch, max_failures=5)


def test_failure_classification():
    from cycloneml_tpu.parallel.faults import (DeviceLostError,
                                               TransientCollectiveError)
    from cycloneml_tpu.parallel.resilience import classify_failure

    assert classify_failure(TransientCollectiveError("x")) == "transient"
    assert classify_failure(OSError("conn reset")) == "transient"
    assert classify_failure(DeviceLostError("gone")) == "device_loss"
    assert classify_failure(RuntimeError("DATA_LOSS: chip fell over")) == \
        "device_loss"
    assert classify_failure(TypeError("bad arg")) == "permanent"


def test_backoff_is_exponential_and_seed_deterministic():
    import random

    from cycloneml_tpu.parallel.resilience import backoff_delay

    a = [backoff_delay(i, 0.1, 5.0, random.Random(42)) for i in range(6)]
    b = [backoff_delay(i, 0.1, 5.0, random.Random(42)) for i in range(6)]
    assert a == b  # same seed, same jitter schedule
    for i, d in enumerate(a):
        lo, hi = 0.05 * 2 ** i, min(5.0, 0.1 * 2 ** i)
        assert lo <= d <= hi
    assert backoff_delay(3, 0.0) == 0.0  # disabled backoff sleeps nothing


# -- exact optimizer resume -----------------------------------------------------

def test_lbfgs_exact_resume():
    f, x0 = _quadratic()
    full = LBFGS(max_iter=40, tol=1e-12).minimize(f, x0)

    # stop after 4 iterations, round-trip the state, resume in a NEW optimizer
    states = []
    for s in LBFGS(max_iter=40, tol=1e-12).iterations(f, x0):
        states.append(s)
        if s.iteration == 4:
            break
    mid = OptimState.from_pytree(states[-1].to_pytree())
    resumed = LBFGS(max_iter=40, tol=1e-12).minimize(f, None, resume=mid)
    np.testing.assert_allclose(resumed.x, full.x, rtol=1e-12, atol=1e-12)
    assert resumed.loss_history == pytest.approx(full.loss_history)
    assert resumed.iteration == full.iteration


def test_owlqn_exact_resume():
    f, x0 = _quadratic(d=8, seed=11)
    opt = lambda: OWLQN(max_iter=60, tol=1e-12, l1_reg=0.05)  # noqa: E731
    full = opt().minimize(f, x0)
    states = []
    for s in opt().iterations(f, x0):
        states.append(s)
        if s.iteration == 3:
            break
    mid = OptimState.from_pytree(states[-1].to_pytree())
    resumed = opt().minimize(f, None, resume=mid)
    np.testing.assert_allclose(resumed.x, full.x, rtol=1e-10, atol=1e-12)
    assert resumed.iteration == full.iteration


# -- checkpointed training loop -------------------------------------------------

def test_train_with_checkpoints_crash_and_resume(tmp_path):
    """Mesh dies mid-training: a fresh process resumes from the newest
    checkpoint and lands on the uninterrupted trajectory."""
    f, x0 = _quadratic(d=10, seed=5)
    baseline = LBFGS(max_iter=50, tol=1e-12).minimize(f, x0)

    evals = {"n": 0}

    def failing_f(x):
        evals["n"] += 1
        if evals["n"] >= 8:
            raise RuntimeError("SLICE_LOST")  # permanent for this 'process'
        return f(x)

    ck = TrainingCheckpointer(str(tmp_path), keep_last=3)
    with pytest.raises(RuntimeError):
        train_with_checkpoints(LBFGS(max_iter=50, tol=1e-12), failing_f, x0,
                               ck, interval=2, max_step_failures=1)
    crashed_at = ck.latest_step()
    assert crashed_at is not None and crashed_at >= 2

    # 'new process': resume from checkpoint with a healthy mesh
    final = train_with_checkpoints(LBFGS(max_iter=50, tol=1e-12), f, x0, ck,
                                   interval=2)
    np.testing.assert_allclose(final.x, baseline.x, rtol=1e-12, atol=1e-12)
    assert final.loss_history == pytest.approx(baseline.loss_history)
    assert ck.latest_step() == final.iteration  # final state checkpointed


def test_permanent_failure_after_progress_aborts(tmp_path):
    """A loss fn that starts failing permanently AFTER some good steps must
    exhaust the retry budget and abort — not loop forever (the rebuilt
    stream's re-yield of the resume point must not reset the count)."""
    f, x0 = _quadratic(d=6, seed=2)
    evals = {"n": 0}

    def dies_later(x):
        evals["n"] += 1
        if evals["n"] > 5:
            raise RuntimeError("permanent")
        return f(x)

    ck = TrainingCheckpointer(str(tmp_path))
    with pytest.raises(RuntimeError, match="failed 4 times"):
        train_with_checkpoints(LBFGS(max_iter=50, tol=1e-12), dies_later, x0,
                               ck, interval=2, max_step_failures=4)
    # exactly budget+good evals: 5 good + 4 failed attempts
    assert evals["n"] == 9


def test_resume_does_not_replay_on_step(tmp_path):
    """The restored checkpoint state was already announced by the previous
    run; the resumed run must not fire on_step for it again."""
    f, x0 = _quadratic(d=6, seed=4)
    ck = TrainingCheckpointer(str(tmp_path))
    first_run = []
    states = []
    for s in LBFGS(max_iter=50, tol=1e-12).iterations(f, x0):
        first_run.append(s.iteration)
        states.append(s)
        if s.iteration == 4:
            ck.save(4, s.to_pytree())
            break
    second_run = []
    train_with_checkpoints(LBFGS(max_iter=50, tol=1e-12), f, x0, ck,
                           interval=3, on_step=lambda s: second_run.append(s.iteration))
    assert second_run[0] == 5  # starts after the checkpointed iteration


def test_train_with_checkpoints_transient_retry(tmp_path):
    f, x0 = _quadratic(d=5, seed=9)
    evals = {"n": 0}

    def flaky_f(x):
        evals["n"] += 1
        if evals["n"] in (3, 11):
            raise RuntimeError("transient")
        return f(x)

    ck = TrainingCheckpointer(str(tmp_path))
    final = train_with_checkpoints(LBFGS(max_iter=50, tol=1e-12), flaky_f, x0,
                                   ck, interval=5, max_step_failures=3)
    baseline = LBFGS(max_iter=50, tol=1e-12).minimize(f, x0)
    # retried steps re-evaluate the loss, so the trajectory may bisect
    # differently only if state leaked — it must not:
    np.testing.assert_allclose(final.x, baseline.x, rtol=1e-10)


def test_logistic_regression_checkpoint_resume(ctx, tmp_path):
    """Estimator-level wiring: fit() with checkpointDir resumes a killed
    training run and lands on the uninterrupted result."""
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression

    rng = np.random.RandomState(3)
    x = rng.randn(200, 6)
    y = (x @ rng.randn(6) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    ck = str(tmp_path / "lr-ck")

    full = LogisticRegression(maxIter=40, tol=1e-9).fit(frame)

    # 'crash' after 3 iterations (checkpoint every 2), then resume to 40
    LogisticRegression(maxIter=3, tol=1e-9, checkpointDir=ck,
                       checkpointInterval=2).fit(frame)
    assert os.listdir(ck)
    resumed = LogisticRegression(maxIter=40, tol=1e-9, checkpointDir=ck,
                                 checkpointInterval=2).fit(frame)
    np.testing.assert_allclose(
        np.asarray(resumed.coefficients), np.asarray(full.coefficients),
        rtol=1e-8)
    # resumed history continues the interrupted run, not a fresh start
    assert resumed.summary.total_iterations == full.summary.total_iterations


def test_checkpoint_fingerprint_guards_reuse(ctx, tmp_path):
    """A checkpoint dir bound to one dataset must refuse to resume a fit on
    different data instead of silently returning the old model."""
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression

    rng = np.random.RandomState(0)
    x = rng.randn(100, 4)
    ck = str(tmp_path / "ck")
    frame_a = MLFrame(ctx, {"features": x,
                            "label": (x[:, 0] > 0).astype(float)})
    frame_b = MLFrame(ctx, {"features": x,
                            "label": (x[:, 1] > 0).astype(float)})
    LogisticRegression(maxIter=5, checkpointDir=ck).fit(frame_a)
    with pytest.raises(ValueError, match="DIFFERENT training run"):
        LogisticRegression(maxIter=5, checkpointDir=ck).fit(frame_b)
    # different hyperparameters on the same data are also a different run
    with pytest.raises(ValueError, match="DIFFERENT training run"):
        LogisticRegression(maxIter=5, regParam=0.5,
                           checkpointDir=ck).fit(frame_a)


# -- distributed end-to-end: failure, mesh rebuild, resume ----------------------

def test_elastic_mesh_rebuild_resume(ctx, tmp_path):
    """Full §5.3 recovery: distributed training on 8 devices, slice 'lost',
    mesh rebuilt at 4 devices, dataset re-placed from its checkpoint, training
    resumed from optimizer checkpoint — same answer as an undisturbed run."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.ml.optim.loss import DistributedLossFunction

    rng = np.random.RandomState(0)
    n, d = 256, 8
    x = rng.randn(n, d)
    y = (x @ rng.randn(d) > 0).astype(np.float64)

    def make_loss(ds):
        return DistributedLossFunction(
            ds, aggregators.binary_logistic(d, fit_intercept=False))

    ds8 = InstanceDataset.from_numpy(ctx, x, y)
    baseline = LBFGS(max_iter=30, tol=1e-9).minimize(make_loss(ds8),
                                                     np.zeros(d))

    data_ck = str(tmp_path / "data")
    ds8.checkpoint(data_ck)
    opt_ck = TrainingCheckpointer(str(tmp_path / "opt"))

    # train 6 steps on the 8-device mesh, checkpointing every 3
    it = LBFGS(max_iter=30, tol=1e-9).iterations(make_loss(ds8), np.zeros(d))
    for s in it:
        if s.iteration % 3 == 0 and s.iteration > 0:
            opt_ck.save(s.iteration, s.to_pytree())
        if s.iteration == 6:
            break

    try:
        # slice lost → rebuild smaller mesh, restore data + optimizer state
        ctx.rebuild_mesh("local-mesh[4]")
        assert ctx.mesh_runtime.n_devices == 4
        ds4 = InstanceDataset.restore(ctx, data_ck)
        assert opt_ck.latest_step() == 6  # train_with_checkpoints restores it
        final = train_with_checkpoints(LBFGS(max_iter=30, tol=1e-9),
                                       make_loss(ds4), None, opt_ck,
                                       interval=5)
        np.testing.assert_allclose(final.x, baseline.x, rtol=1e-5, atol=1e-8)
        assert final.iteration == baseline.iteration
    finally:
        ctx.rebuild_mesh("local-mesh[8]")  # restore fixture invariant


def test_heartbeat_receiver_on_context(ctx):
    hb = ctx.heartbeat_receiver
    hb.register("host-0")
    assert hb.heartbeat("host-0")
    assert "host-0" in hb.live_workers()


def test_heartbeat_over_the_wire():
    """Cross-process leg: a real TCP server feeding the receiver, a real
    sender thread pinging it. Stop the sender -> expiry -> WorkerLost on the
    bus; an expired worker's next ping gets EXPIRED and it re-registers."""
    import time
    from cycloneml_tpu.parallel.resilience import (HeartbeatReceiver,
                                                   HeartbeatSender,
                                                   HeartbeatServer)

    bus = ListenerBus()
    bus.start()
    lost = []
    bus.add_listener(lambda e: lost.append(e.worker_id)
                     if isinstance(e, WorkerLost) else None)

    recv = HeartbeatReceiver(timeout_s=0.8, check_interval_s=0.1,
                             listener_bus=bus)
    server = HeartbeatServer(recv)
    try:
        s1 = HeartbeatSender("w1", server.address, interval_s=0.1)
        s2 = HeartbeatSender("w2", server.address, interval_s=0.1)
        deadline = time.time() + 5
        while set(recv.live_workers()) != {"w1", "w2"}:
            assert time.time() < deadline, recv.live_workers()
            time.sleep(0.05)

        s1.stop()  # "kill" w1: its pings cease
        deadline = time.time() + 5
        while "w1" not in recv.lost_workers():
            recv.check_now()
            assert time.time() < deadline
            time.sleep(0.1)
        bus.wait_until_empty()
        assert lost == ["w1"]
        assert "w2" in recv.live_workers()  # the survivor is untouched

        # a stopped-then-revived worker re-registers through the EXPIRED
        # reply path and becomes live again
        s1b = HeartbeatSender("w1", server.address, interval_s=0.1)
        deadline = time.time() + 5
        while "w1" not in recv.live_workers():
            assert time.time() < deadline
            time.sleep(0.05)
        s1b.stop()
        s2.stop()
    finally:
        server.stop()
        bus.stop()


def _hb_roundtrip(address: str, line: str) -> str:
    """One raw-socket request against a HeartbeatServer (no auth)."""
    import socket
    host, _, port = address.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=5) as s:
        s.sendall((line + "\n").encode())
        f = s.makefile("r")
        try:
            return f.readline().strip()
        finally:
            f.close()


def test_heartbeat_wire_protocol_expiry(monkeypatch):
    """Raw wire protocol: REG→OK, HB→OK, HB after expiry→EXPIRED, re-REG
    revives, garbage→ERR."""
    import time
    from cycloneml_tpu.parallel.resilience import HeartbeatServer

    monkeypatch.delenv("CYCLONE_AUTH_SECRET", raising=False)
    recv = HeartbeatReceiver(timeout_s=0.0)  # everything expires on sweep
    server = HeartbeatServer(recv)
    try:
        assert _hb_roundtrip(server.address, "REG w9") == "OK"
        assert _hb_roundtrip(server.address, "HB w9") == "OK"
        time.sleep(0.01)
        recv.check_now()  # w9 expires
        assert _hb_roundtrip(server.address, "HB w9") == "EXPIRED"
        assert _hb_roundtrip(server.address, "REG w9") == "OK"  # revival
        assert _hb_roundtrip(server.address, "HB w9") == "OK"
        assert _hb_roundtrip(server.address, "BOGUS") == "ERR"
        assert _hb_roundtrip(server.address, "HB a b c") == "ERR"
    finally:
        server.stop()


def test_heartbeat_sender_stops_on_missing_secret(monkeypatch):
    """Server requires the fabric secret, sender resolves none: the first
    reply is the auth challenge, the sender fails loudly (PermissionError)
    and STOPS its loop instead of spinning forever."""
    import time
    from cycloneml_tpu.parallel.resilience import (HeartbeatSender,
                                                   HeartbeatServer)

    monkeypatch.setenv("CYCLONE_AUTH_SECRET", "right-secret")
    recv = HeartbeatReceiver(timeout_s=30.0)
    server = HeartbeatServer(recv)  # binds WITH the secret
    try:
        monkeypatch.delenv("CYCLONE_AUTH_SECRET")
        sender = HeartbeatSender("w0", server.address, interval_s=0.05)
        sender._thread.join(timeout=5)
        assert not sender._thread.is_alive()  # loop stopped itself
        assert recv.live_workers() == []      # never authenticated
        sender.stop()
    finally:
        server.stop()


def test_heartbeat_sender_stops_on_wrong_secret(monkeypatch):
    """A sender with the WRONG secret is denied by the mutual handshake and
    stops its loop (retrying can never succeed)."""
    import time
    from cycloneml_tpu.parallel.resilience import (HeartbeatSender,
                                                   HeartbeatServer)

    monkeypatch.setenv("CYCLONE_AUTH_SECRET", "right-secret")
    recv = HeartbeatReceiver(timeout_s=30.0)
    server = HeartbeatServer(recv)
    try:
        monkeypatch.setenv("CYCLONE_AUTH_SECRET", "wrong-secret")
        sender = HeartbeatSender("w0", server.address, interval_s=0.05)
        sender._thread.join(timeout=5)
        assert not sender._thread.is_alive()
        assert recv.live_workers() == []
        sender.stop()
    finally:
        server.stop()


def test_heartbeat_wire_rtt_report(monkeypatch):
    """The 5-token extended ping ``HB <id> <t> <trace|-> <rtt>`` lands the
    worker's reported round trip in the RECEIVER (master-side straggler
    lane); garbage rtt stays ERR, and '-' means no trace id."""
    from cycloneml_tpu.parallel.resilience import HeartbeatServer

    monkeypatch.delenv("CYCLONE_AUTH_SECRET", raising=False)
    recv = HeartbeatReceiver(timeout_s=30.0)
    server = HeartbeatServer(recv)
    try:
        assert _hb_roundtrip(server.address, "REG wr") == "OK"
        rep = _hb_roundtrip(server.address, "HB wr 123.5 - 0.0042")
        assert rep.split()[0] == "OK"  # extended reply carries t_server
        assert recv.rtts() == {"wr": 0.0042}
        assert recv.trace_ids() == {}  # '-' is the no-trace placeholder
        rep = _hb_roundtrip(server.address, "HB wr 123.6 tr-abc 0.0099")
        assert rep.split()[0] == "OK"
        assert recv.rtts()["wr"] == 0.0099
        assert recv.trace_ids() == {"wr": "tr-abc"}
        # malformed rtt is the legacy ERR contract, and the sample is kept out
        assert _hb_roundtrip(server.address, "HB wr 123.7 - junk") == "ERR"
        assert recv.rtts()["wr"] == 0.0099
        # only LIVE workers feed the lanes: an unregistered/expired
        # sender's rtt never reaches the straggler detector
        rep = _hb_roundtrip(server.address, "HB ghost 1.0 - 0.5")
        assert rep.split()[0] == "EXPIRED"
        assert "ghost" not in recv.rtts()
    finally:
        server.stop()


def test_heartbeat_sender_reports_rtt_to_receiver(monkeypatch):
    """End to end: from the second ping on, the sender's measured RTT of
    the PREVIOUS round trip arrives at the receiver — the data feeding
    cross-host RTT skew comparison (observe/skew.py heartbeat.rtt)."""
    import time
    from cycloneml_tpu.parallel.resilience import (HeartbeatSender,
                                                   HeartbeatServer)

    monkeypatch.delenv("CYCLONE_AUTH_SECRET", raising=False)
    recv = HeartbeatReceiver(timeout_s=30.0)
    server = HeartbeatServer(recv)
    sender = HeartbeatSender("wrtt", server.address, interval_s=0.05)
    try:
        deadline = time.time() + 10
        while "wrtt" not in recv.rtts():
            assert time.time() < deadline, "no RTT report arrived"
            time.sleep(0.02)
        rtt = recv.rtts()["wrtt"]
        assert 0.0 <= rtt < 5.0  # a real loopback round trip
    finally:
        sender.stop()
        server.stop()


# -- elastic liveness re-arm (ISSUE 15 satellite) --------------------------------

def test_returning_worker_rearms_liveness_after_scale_up(ctx):
    """REGRESSION: a worker that left on scale-down and re-registers on
    scale-up must get a FRESH liveness window. Pre-fix, the supervisor
    kept its lost marker forever (surviving-device math never recovered)
    and the HealthTracker kept its strike, so ONE new hiccup on the new
    mesh hit max_failures=2 and excluded the returning worker."""
    import time

    from cycloneml_tpu.parallel.resilience import MeshSupervisor

    recv = HeartbeatReceiver(timeout_s=0.05)  # swept manually
    sup = MeshSupervisor(
        ctx, worker_devices={"w0": 4, "w1": 4},
        worker_hosts={"w0": "hostA", "w1": "hostB"}).attach(recv)
    recv.register("w0")
    recv.register("w1")
    time.sleep(0.06)            # both stale...
    recv.heartbeat("w0")        # ...w0's ping arrives in time...
    recv.check_now()            # ...w1 expires -> supervisor notified
    assert "w1" in sup.lost_workers()
    assert "hostB" in sup.lost_hosts()
    assert sup.surviving_devices() == 4
    assert sup.pending_loss() is not None
    assert recv.heartbeat("w1") is False   # expired: must re-register

    # scale-up: w1 returns and re-registers -> everything re-arms
    recv.register("w1")
    assert "w1" not in sup.lost_workers()
    assert sup.lost_hosts() == {}
    assert sup.surviving_devices() == 8
    assert sup.pending_loss() is None      # nothing left to recover from
    assert recv.heartbeat("w1") is True    # fresh receiver window too

    # fresh failure budget: one NEW strike must not exclude (the pre-fix
    # inherited strike plus this one reached max_failures=2)
    sup.note_worker_lost("w1", "fresh hiccup on the new mesh")
    assert sup.health.is_excluded("w1") is False


def test_readmit_resets_heartbeat_rtt_straggler_lane(ctx):
    """readmit() also restarts the returning worker's heartbeat-RTT
    straggler lane: pre-departure samples (and a latched verdict)
    describe the OLD placement and must not convict the fresh one."""
    from cycloneml_tpu.observe import skew
    from cycloneml_tpu.parallel.resilience import MeshSupervisor

    det = skew.SkewDetector(window=16, min_samples=4)
    prev = skew.install(det)
    try:
        sup = MeshSupervisor(ctx, worker_devices={"w9": 4}).attach_skew(det)
        for _ in range(8):
            det.observe("heartbeat.rtt", "a", 0.001)
            det.observe("heartbeat.rtt", "b", 0.001)
            det.observe("heartbeat.rtt", "w9", 0.050)
        assert ("heartbeat.rtt", "w9") in det.stragglers()
        assert "heartbeat.rtt:w9" in sup.stragglers()
        sup.note_worker_lost("w9", "drained on scale-down")
        sup.readmit("w9")
        # lane forgotten in the DETECTOR and in the supervisor's record
        assert ("heartbeat.rtt", "w9") not in det.stragglers()
        assert "heartbeat.rtt:w9" not in sup.stragglers()
        assert "w9" not in sup.lost_workers()
    finally:
        skew.uninstall(det)
        if prev is not None:
            skew.install(prev)


def test_health_tracker_forgive():
    """forgive() clears the strike history — the readmission primitive."""
    h = HealthTracker(max_failures=2)
    h.record_failure("w")
    h.record_failure("w")
    assert h.is_excluded("w")
    h.forgive("w")
    assert not h.is_excluded("w")
    assert h.excluded() == []
