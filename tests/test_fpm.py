"""FPGrowth / PrefixSpan tests (hand-checked baskets, brute-force oracles —
the reference's FPGrowthSuite/PrefixSpanSuite use the same style of small
enumerable fixtures)."""

import itertools
from collections import Counter

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.fpm import FPGrowth, FPGrowthModel, PrefixSpan


def _brute_force_itemsets(transactions, min_count):
    """Oracle: enumerate all itemsets over observed items."""
    items = sorted({i for t in transactions for i in t})
    out = {}
    for r in range(1, len(items) + 1):
        for combo in itertools.combinations(items, r):
            c = sum(1 for t in transactions if set(combo) <= set(t))
            if c >= min_count:
                out[frozenset(combo)] = c
    return out


BASKETS = [
    ["r", "z", "h", "k", "p"],
    ["z", "y", "x", "w", "v", "u", "t", "s"],
    ["s", "x", "o", "n", "r"],
    ["x", "z", "y", "m", "t", "s", "q", "e"],
    ["z"],
    ["x", "z", "y", "r", "q", "t", "p"],
]


def test_fpgrowth_matches_bruteforce(ctx):
    frame = MLFrame(ctx, {"items": np.array(BASKETS, dtype=object)})
    model = FPGrowth(minSupport=0.5, minConfidence=0.5).fit(frame)
    got = {frozenset(s): c for s, c in model.freq_itemsets}
    want = _brute_force_itemsets(BASKETS, min_count=3)
    assert got == want


def test_fpgrowth_min_support_1(ctx):
    # minSupport so low every observed itemset combination survives
    tx = [["a", "b"], ["a", "c"], ["a", "b", "c"]]
    frame = MLFrame(ctx, {"items": np.array(tx, dtype=object)})
    model = FPGrowth(minSupport=0.34).fit(frame)
    got = {frozenset(s): c for s, c in model.freq_itemsets}
    assert got == _brute_force_itemsets(tx, min_count=2)


def test_fpgrowth_association_rules_and_transform(ctx):
    tx = [["a", "b"], ["a", "b", "c"], ["a", "b", "c"], ["c", "d"], ["a", "d"]]
    frame = MLFrame(ctx, {"items": np.array(tx, dtype=object)})
    model = FPGrowth(minSupport=0.4, minConfidence=0.6).fit(frame)
    rules = {(tuple(r["antecedent"]), tuple(r["consequent"])): r
             for r in model.association_rules}
    # {a}→{b}: support({a,b})=3, support({a})=4 → conf 0.75; lift = .75/(3/5)
    r = rules[(("a",), ("b",))]
    assert r["confidence"] == pytest.approx(3 / 4)
    assert r["lift"] == pytest.approx((3 / 4) / (3 / 5))
    assert r["support"] == pytest.approx(3 / 5)
    # transform: basket {a} should predict b (from a→b)
    pred = model.transform(MLFrame(ctx, {
        "items": np.array([["a"], ["x"]], dtype=object)}))["prediction"]
    assert "b" in pred[0]
    assert list(pred[1]) == []


def test_fpgrowth_persistence(ctx, tmp_path):
    frame = MLFrame(ctx, {"items": np.array(BASKETS, dtype=object)})
    model = FPGrowth(minSupport=0.5).fit(frame)
    path = str(tmp_path / "fp")
    model.save(path)
    m2 = FPGrowthModel.load(path)
    assert {frozenset(s): c for s, c in m2.freq_itemsets} == \
        {frozenset(s): c for s, c in model.freq_itemsets}


# -- PrefixSpan ---------------------------------------------------------------

SEQDB = [
    [["a"], ["a", "b", "c"], ["a", "c"], ["d"], ["c", "f"]],
    [["a", "d"], ["c"], ["b", "c"], ["a", "e"]],
    [["e", "f"], ["a", "b"], ["d", "f"], ["c"], ["b"]],
    [["e"], ["g"], ["a", "f"], ["c"], ["b"], ["c"]],
]


def _brute_force_patterns(db, min_count, max_len):
    """Oracle: BFS over the pattern lattice with subsequence matching."""
    def matches(pattern, seq):
        j = 0
        for ps in pattern:
            while j < len(seq) and not set(ps) <= set(seq[j]):
                j += 1
            if j == len(seq):
                return False
            j += 1
        return True

    items = sorted({i for seq in db for s in seq for i in s})
    found = {}
    frontier = [[]]
    while frontier:
        new_frontier = []
        for pat in frontier:
            cands = [pat + [[i]] for i in items]
            if pat:
                last = pat[-1]
                cands += [pat[:-1] + [sorted(last + [i])] for i in items
                          if i not in last and i > max(last)]
            for cand in cands:
                if sum(len(s) for s in cand) > max_len:
                    continue
                c = sum(1 for seq in db if matches(cand, seq))
                if c >= min_count:
                    key = tuple(tuple(s) for s in cand)
                    if key not in found:
                        found[key] = c
                        new_frontier.append(cand)
        frontier = new_frontier
    return found


def test_prefixspan_matches_bruteforce(ctx):
    ps = PrefixSpan(minSupport=0.5, maxPatternLength=3)
    got = {tuple(tuple(s) for s in pat): c
           for pat, c in ps.find_frequent_sequential_patterns(SEQDB)}
    want = _brute_force_patterns(SEQDB, min_count=2, max_len=3)
    assert got == want
    # the classic fixture facts: <(a)(c)> appears in all 4 sequences
    assert got[(("a",), ("c",))] == 4


def test_prefixspan_multi_item_itemsets(ctx):
    db = [
        [["a", "b"], ["c"]],
        [["a", "b"], ["c"]],
        [["a"], ["b"], ["c"]],
    ]
    ps = PrefixSpan(minSupport=0.6, maxPatternLength=3)
    got = {tuple(tuple(s) for s in pat): c
           for pat, c in ps.find_frequent_sequential_patterns(db)}
    # itemset pattern <(ab)> has support 2; sequence pattern <(a)(c)> support 3
    assert got[(("a", "b"),)] == 2
    assert got[(("a",), ("c",))] == 3
    assert got[(("a", "b"), ("c",))] == 2


def test_prefixspan_frame_input(ctx):
    frame = MLFrame(ctx, {"sequence": np.array(SEQDB, dtype=object)})
    ps = PrefixSpan(minSupport=1.0, maxPatternLength=2)
    got = ps.find_frequent_sequential_patterns(frame)
    # only patterns present in every sequence survive
    for pat, c in got:
        assert c == 4
    assert any(pat == [["a"]] for pat, _ in got)
