"""Config system tests (ref semantics: ConfigBuilder/ConfigEntry/SparkConf)."""

import pytest

from cycloneml_tpu.conf import (
    AGGREGATION_DEPTH, ConfigBuilder, CycloneConf, HEARTBEAT_INTERVAL_MS,
    NETWORK_TIMEOUT_MS, TASK_MAX_FAILURES, registered_entries,
)


def test_defaults():
    conf = CycloneConf(load_defaults=False)
    assert conf.get(AGGREGATION_DEPTH) == 2
    assert conf.get(TASK_MAX_FAILURES) == 4


def test_set_and_typed_read():
    conf = CycloneConf(load_defaults=False)
    conf.set(AGGREGATION_DEPTH, 5)
    assert conf.get(AGGREGATION_DEPTH) == 5
    conf.set("cyclone.treeAggregate.depth", "7")
    assert conf.get(AGGREGATION_DEPTH) == 7


def test_validator():
    conf = CycloneConf(load_defaults=False)
    conf.set(AGGREGATION_DEPTH, 0)
    with pytest.raises(ValueError):
        conf.get(AGGREGATION_DEPTH)


def test_fallback_entry():
    from cycloneml_tpu.conf import ConfigBuilder
    fb = (ConfigBuilder("cyclone.test.fallbackChild")
          .doc("falls back like spark.network.timeout once did")
          .fallback_conf(HEARTBEAT_INTERVAL_MS))
    conf = CycloneConf(load_defaults=False)
    assert conf.get(fb) == conf.get(HEARTBEAT_INTERVAL_MS)
    conf.set(HEARTBEAT_INTERVAL_MS, 777)
    assert conf.get(fb) == 777  # follows the parent until set directly
    conf.set(fb, 1234)
    assert conf.get(fb) == 1234
    # liveness timeout now has a real default well above the heartbeat
    # interval (spurious-expiry guard)
    assert conf.get(NETWORK_TIMEOUT_MS) >= 10 * conf.get(HEARTBEAT_INTERVAL_MS)


def test_clone_isolated():
    a = CycloneConf(load_defaults=False).set("k", "v")
    b = a.clone().set("k", "w")
    assert a.get("k") == "v" and b.get("k") == "w"


def test_registry_has_docs():
    for key, entry in registered_entries().items():
        assert entry.doc, f"{key} missing doc"


def test_duplicate_registration_rejected():
    ConfigBuilder("cyclone.test.dup").doc("x").int_conf(1)
    with pytest.raises(ValueError):
        ConfigBuilder("cyclone.test.dup").doc("x").int_conf(2)


def test_bool_parse():
    conf = CycloneConf(load_defaults=False)
    from cycloneml_tpu.conf import EVENT_LOG_ENABLED
    conf.set("cyclone.eventLog.enabled", "true")
    assert conf.get(EVENT_LOG_ENABLED) is True
