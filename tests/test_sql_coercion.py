"""Analyzer type coercion (round-3 verdict item 8): implicit numeric
widening, string↔numeric comparison/arithmetic promotion, division
semantics, and data-type-mismatch AnalysisExceptions — as ANALYZER rules
that insert explicit Casts (ref catalyst/analysis/TypeCoercion.scala
Division/PromoteStrings/ImplicitTypeCasts; CheckAnalysis mismatch errors),
not eval-time special cases."""

import numpy as np
import pytest

from cycloneml_tpu.sql.analyzer import (AnalysisException, analyze,
                                        expr_type, infer_schema)
from cycloneml_tpu.sql.column import BinaryOp, Cast
from cycloneml_tpu.sql.session import CycloneSession


@pytest.fixture()
def session():
    s = CycloneSession()
    df = s.create_data_frame({
        "i": np.array([1, 2, 3, 4], dtype=np.int64),
        "f": np.array([0.5, 1.5, 2.5, 3.5]),
        "s": np.array(["5", "x", "2.5", None], dtype=object),
        "b": np.array([True, False, True, False]),
        "name": np.array(["a", "b", "c", "d"], dtype=object),
    })
    s.register_temp_view("t", df)
    return s


def test_integer_division_is_double(session):
    # Spark: SELECT 7 / 2 -> 3.5 (Division coerces to double; sqlite3
    # would say 3 — this is the reference's semantics, asserted directly)
    out = session.sql("SELECT i / 2 AS q FROM t").to_dict()["q"]
    np.testing.assert_allclose(out, [0.5, 1.0, 1.5, 2.0])
    assert out.dtype.kind == "f"


def test_string_numeric_comparison_promotes_string(session):
    # PromoteStrings: the STRING side casts to double — '5' = 5 is TRUE,
    # unparseable strings compare as null (never match)
    out = session.sql("SELECT name FROM t WHERE s = 5").to_dict()["name"]
    assert list(out) == ["a"]
    out = session.sql("SELECT name FROM t WHERE s < 3").to_dict()["name"]
    assert list(out) == ["c"]


def test_string_arithmetic_casts_to_double(session):
    out = session.sql("SELECT s + 1 AS v FROM t").to_dict()["v"]
    assert out[0] == 6.0 and out[2] == 3.5
    assert np.isnan(out[1]) and np.isnan(out[3])  # 'x' and NULL -> null


def test_cast_failure_is_null_not_error(session):
    out = session.sql(
        "SELECT CAST(s AS DOUBLE) AS v FROM t").to_dict()["v"]
    assert out[0] == 5.0 and out[2] == 2.5
    assert np.isnan(out[1]) and np.isnan(out[3])


def test_boolean_arithmetic_rejected(session):
    with pytest.raises(AnalysisException, match="data type mismatch"):
        session.sql("SELECT b + 1 FROM t").to_dict()


def test_boolean_ordering_comparison_rejected(session):
    with pytest.raises(AnalysisException, match="data type mismatch"):
        session.sql("SELECT name FROM t WHERE b < i").to_dict()


def test_boolean_equality_with_numeric_allowed(session):
    out = session.sql("SELECT name FROM t WHERE b = 1").to_dict()["name"]
    assert list(out) == ["a", "c"]


def test_and_requires_boolean(session):
    with pytest.raises(AnalysisException, match="must be boolean"):
        session.sql("SELECT name FROM t WHERE i AND b").to_dict()


def test_coercion_inserts_casts_at_analysis(session):
    """The rewrite is visible in the ANALYZED plan — coercion lives in the
    analyzer batch, not in BinaryOp.eval special cases."""
    df = session.sql("SELECT s + 1 AS v FROM t WHERE s = 5")
    plan = analyze(df.plan)

    casts = []

    def walk(e):
        if isinstance(e, Cast):
            casts.append(e)
        for c in e.children:
            walk(c)

    def visit(p):
        for attr in ("exprs", "cond"):
            v = getattr(p, attr, None)
            if v is None:
                continue
            for e in (v if isinstance(v, (list, tuple)) else [v]):
                walk(e)
        for c in p.children:
            visit(c)

    visit(plan)
    assert len(casts) >= 2  # one for the arithmetic, one for the predicate
    assert all(c.to == "double" for c in casts)


def test_infer_schema_and_expr_type(session):
    plan = session.table("t").plan
    schema = infer_schema(plan)
    assert schema == {"i": "int", "f": "float", "s": "str", "b": "bool",
                      "name": "str"}
    agg = analyze(session.sql(
        "SELECT i, COUNT(*) AS c, SUM(f) AS sf FROM t GROUP BY i").plan)
    out_schema = infer_schema(agg)
    assert out_schema["c"] == "int" and out_schema["sf"] == "float"


def test_unknown_types_left_alone(session):
    """Columns whose kind can't be inferred (all-null object) disable
    coercion rather than risking a wrong rewrite."""
    s2 = CycloneSession()
    df = s2.create_data_frame(
        {"u": np.array([None, None], dtype=object),
         "n": np.array([1, 2], dtype=np.int64)})
    s2.register_temp_view("t2", df)
    # no exception, no rewrite: null-kind comparison evaluates as numpy
    out = s2.sql("SELECT n FROM t2 WHERE u = 1").to_dict()["n"]
    assert len(out) == 0


def test_coerced_group_key_keeps_its_name(session):
    """Coercion must not rename operator outputs: upstream projections
    reference the parse-time name (review r4 — KeyError repro)."""
    out = session.sql(
        "SELECT i / 2 AS h, COUNT(*) AS n FROM t GROUP BY i / 2"
    ).to_dict()
    assert sorted(out["h"].tolist()) == [0.5, 1.0, 1.5, 2.0]
    out2 = session.sql(
        "SELECT s + 1 AS k, COUNT(*) AS n FROM t GROUP BY s + 1").to_dict()
    assert len(out2["k"]) == 3  # groups 6.0, 3.5, null
    # big-int string cast stays exact (review r4: the float round-trip
    # corrupted ids above 2^53)
    df = session.create_data_frame(
        {"sid": np.array(["9007199254740993"], dtype=object)})
    session.register_temp_view("big", df)
    v = session.sql("SELECT CAST(sid AS BIGINT) AS v FROM big").to_dict()["v"]
    assert int(v[0]) == 9007199254740993


def test_bigint_cast_overflow_is_null_not_error(session):
    """A string integer outside int64 range casts to NULL (non-ANSI
    Cast.scala overflow semantics), instead of OverflowError at numpy
    array build erroring the whole query (advisor r5)."""
    df = session.create_data_frame({
        "sid": np.array(["12", "99999999999999999999999999",
                         str(-(1 << 64)), "7"], dtype=object)})
    session.register_temp_view("huge", df)
    v = session.sql("SELECT CAST(sid AS BIGINT) AS v FROM huge"
                    ).to_dict()["v"]
    assert v.dtype == np.float64  # NULLs ride the float lane
    assert v[0] == 12.0 and v[3] == 7.0
    assert np.isnan(v[1]) and np.isnan(v[2])
    # boundary values still parse exactly via the int lane
    df2 = session.create_data_frame({
        "sid": np.array([str((1 << 63) - 1), str(-(1 << 63))],
                        dtype=object)})
    session.register_temp_view("edge", df2)
    v2 = session.sql("SELECT CAST(sid AS BIGINT) AS v FROM edge"
                     ).to_dict()["v"]
    assert v2.dtype == np.int64
    assert v2[0] == (1 << 63) - 1 and v2[1] == -(1 << 63)
