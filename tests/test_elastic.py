"""Elastic-mesh unit tests (ISSUE 15): capacity channel, live-state
motion, the runtime stale-program guard, speculative re-dispatch, and
the streamed objective's reshard — the chaos e2e legs live in
tests/test_chaos.py."""

import os
import signal
import threading
import time

import numpy as np
import pytest

from cycloneml_tpu.elastic import (CapacityChannel, CapacityEvent,
                                   Speculator, bitwise_equal, host_bounce,
                                   host_bounce_state)
from cycloneml_tpu.elastic import capacity as ecap
from cycloneml_tpu.elastic import speculation


# -- capacity channel ------------------------------------------------------------

def test_capacity_channel_is_fifo():
    ch = CapacityChannel()
    assert ch.peek() is None and ch.take() is None and len(ch) == 0
    a = CapacityEvent(master="local-mesh[4]", reason="reclaim")
    b = CapacityEvent(master="local-mesh[8]", returning=["w1"])
    ch.announce(a)
    ch.announce(b)
    assert len(ch) == 2
    assert ch.peek() is a          # peek does not consume
    assert ch.take() is a          # FIFO: no coalescing — a scale-down
    assert ch.take() is b          # then scale-up applies in order
    ch.announce(a)
    ch.clear()
    assert len(ch) == 0


def test_scale_to_announces_on_global_channel():
    ch = ecap.channel()
    ch.clear()
    try:
        action = ecap.scale_to("local-mesh[4]", reason="test",
                               returning=["w1"])
        # the FaultInjector calls actions with (point, invocation, **info)
        action(point="elastic.capacity", invocation=7, iteration=6)
        ev = ch.take()
        assert ev is not None and ev.master == "local-mesh[4]"
        assert "elastic.capacity#7" in ev.reason
        assert ev.returning == ["w1"]
    finally:
        ch.clear()


# -- bitwise dedup comparator ----------------------------------------------------

def test_bitwise_equal_semantics():
    a = np.arange(6, dtype=np.float64)
    assert bitwise_equal(a, a.copy())
    assert not bitwise_equal(a, a.astype(np.float32))      # dtype differs
    assert bitwise_equal(float("nan"), float("nan"))       # bit-level
    assert bitwise_equal((a, {"k": 1.0}), (a.copy(), {"k": 1.0}))
    assert not bitwise_equal((a, 1.0), (a, 2.0))
    assert not bitwise_equal({"k": a}, {"j": a})


# -- speculator ------------------------------------------------------------------

def _always_latched():
    return {"g:p": {}}


def _wait_for(predicate, timeout_s=5.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


def test_speculate_concurrent_race_dedups_bitwise():
    sp = Speculator(_always_latched)
    try:
        out = sp.speculate("g", "p", lambda: np.arange(4) * 2.0)
        np.testing.assert_array_equal(out, np.arange(4) * 2.0)
        # the loser dedups OFF the caller's critical path — poll
        assert _wait_for(lambda: sp.stats()["dedup_hits"] == 1)
        st = sp.stats()
        assert st["mismatches"] == 0
        assert st["re_dispatches"][0]["lane"] == "g:p"
        assert st["re_dispatches"][0]["dedup"] is True
    finally:
        sp.close()


def test_speculate_backup_rescues_failed_primary():
    """The classic speculation win: the primary copy dies, the duplicate
    still lands the lane's work."""
    sp = Speculator(_always_latched)
    calls = {"n": 0}

    def flaky():
        with sp._lock:  # deterministic: first caller fails
            calls["n"] += 1
            first = calls["n"] == 1
        if first:
            raise OSError("bad spindle")
        return np.ones(3)

    try:
        out = sp.speculate("g", "p", flaky)
        np.testing.assert_array_equal(out, np.ones(3))
        st = sp.stats()
        assert st["re_dispatches"][0]["winner"] in ("primary", "backup")
        assert st["dedup_hits"] == 0   # only one result to dedup against
    finally:
        sp.close()


def test_speculate_both_copies_fail_raises_primary_error():
    sp = Speculator(_always_latched)

    def dead():
        raise ValueError("lane is broken, not slow")

    try:
        with pytest.raises(ValueError, match="broken"):
            sp.speculate("g", "p", dead)
        assert sp.stats()["re_dispatches"][0]["winner"] is None
    finally:
        sp.close()


def test_speculate_mismatch_keeps_first_result_and_counts():
    """Nondeterministic lane work cannot dedup: first-result-wins holds,
    the mismatch is counted (and logged) instead of silently merged."""
    sp = Speculator(_always_latched)
    seq = iter([np.zeros(2), np.ones(2)])
    lock = threading.Lock()

    def nondet():
        with lock:
            return next(seq)

    try:
        out = sp.speculate("g", "p", nondet, concurrent=False)
        np.testing.assert_array_equal(out, np.zeros(2))  # first wins
        st = sp.stats()
        assert st["mismatches"] == 1 and st["dedup_hits"] == 0
    finally:
        sp.close()


def test_speculation_budget_per_lane_saturates():
    """A permanently convicted lane stops doubling its work after
    max_per_lane re-dispatches (Spark bounds speculative copies too)."""
    sp = Speculator(_always_latched, max_per_lane=2)
    try:
        assert sp.latched("g", "p")
        sp.speculate("g", "p", lambda: 1.0, concurrent=False)
        sp.speculate("g", "p", lambda: 1.0, concurrent=False)
        assert not sp.latched("g", "p")    # budget spent
        # maybe_speculate now runs the work PLAIN
        prev = speculation.install(sp)
        try:
            out = speculation.maybe_speculate("g", "p", lambda: 7.0)
            assert out == 7.0
            assert len(sp.stats()["re_dispatches"]) == 2  # unchanged
        finally:
            speculation.uninstall(sp)
            if prev is not None:
                speculation.install(prev)
    finally:
        sp.close()


def test_maybe_speculate_disarmed_is_plain_call():
    assert speculation.active() is None
    assert speculation.maybe_speculate("g", "p", lambda: 42) == 42


# -- live-state motion -----------------------------------------------------------

def test_host_bounce_pulls_device_leaves_once(ctx):
    import jax
    dev = ctx.mesh_runtime.device_put_replicated(
        {"a": np.arange(8.0), "b": np.ones((2, 3))})
    tree = {"dev": dev, "host": np.full(3, 7.0), "scalar": 1.5}
    out = host_bounce(tree)
    assert isinstance(out["dev"]["a"], np.ndarray)
    assert not isinstance(out["dev"]["a"], jax.Array)
    np.testing.assert_array_equal(out["dev"]["a"], np.arange(8.0))
    assert out["host"] is tree["host"]       # host leaves pass through
    assert out["scalar"] == 1.5


def test_host_bounce_state_roundtrips_optimstate_bitwise():
    from cycloneml_tpu.ml.optim.lbfgs import OptimState
    st = OptimState(x=np.arange(4.0), value=0.5, grad=np.ones(4),
                    iteration=3, loss_history=[1.0, 0.5],
                    hist_s=[np.arange(4.0)], hist_y=[np.ones(4)])
    out = host_bounce_state(st)
    assert out.iteration == 3 and out.value == 0.5
    np.testing.assert_array_equal(out.x, st.x)
    np.testing.assert_array_equal(out.hist_s[0], st.hist_s[0])
    assert host_bounce_state(None) is None


# -- runtime stale-program guard (the JX017 twin) --------------------------------

def test_stale_program_dispatch_raises_classified_error(ctx):
    from cycloneml_tpu import mesh as mesh_mod
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.parallel.collectives import StaleProgramError
    from cycloneml_tpu.parallel.resilience import classify_failure

    rng = np.random.RandomState(0)
    ds = InstanceDataset.from_numpy(ctx, rng.randn(64, 4),
                                    (rng.randn(64) > 0).astype(float))

    def agg(x, y, w):
        import jax.numpy as jnp
        return {"s": jnp.sum(x * w[:, None])}

    call = ds.tree_aggregate_fn(agg)
    before = call()            # live mesh: dispatches fine
    epoch0 = mesh_mod.mesh_epoch()
    try:
        ctx.rebuild_mesh("local-mesh[8]")   # same shape, NEW generation
        assert mesh_mod.mesh_epoch() > epoch0
        with pytest.raises(StaleProgramError, match="mesh epoch"):
            call.compiled(ds.x, ds.y, ds.w)
        # the guard is classified PERMANENT: retrying a stale program
        # re-raises identically — the caller must rebuild it
        try:
            call.compiled(ds.x, ds.y, ds.w)
        except StaleProgramError as e:
            assert classify_failure(e) == "permanent"
        # the sanctioned idiom: REBUILD on the new runtime
        fresh = ds.tree_aggregate_fn(agg)
        after = fresh()
        np.testing.assert_allclose(float(after["s"]), float(before["s"]),
                                   rtol=1e-12)
    finally:
        ctx.rebuild_mesh("local-mesh[8]")


# -- streamed objective reshard --------------------------------------------------

def test_streaming_loss_reshard_rebinds_across_reshape(ctx):
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.oocore import StreamingDataset
    from cycloneml_tpu.oocore.objective import StreamingLossFunction
    from cycloneml_tpu.parallel.collectives import StaleProgramError

    rng = np.random.RandomState(5)
    n, d = 900, 5
    x = rng.randn(n, d)
    y = (x[:, 0] > 0).astype(float)

    def chunks():
        for lo in range(0, n, 300):
            yield x[lo:lo + 300], y[lo:lo + 300], None

    sds = StreamingDataset.from_chunks(ctx, chunks(), d, shard_rows=300)
    try:
        loss = StreamingLossFunction(
            sds, aggregators.binary_logistic(d, fit_intercept=False))
        coef = np.zeros(d)
        ref = loss(coef)
        epochs_before = loss.epochs
        ctx.rebuild_mesh("local-mesh[4]")
        # the held per-shard program closes over the OLD mesh: the
        # runtime guard refuses it instead of running on dead devices
        with pytest.raises(StaleProgramError):
            loss(coef)
        loss.reshard()
        out = loss(coef)
        # stream position (epoch/eval counters) carried over untouched;
        # only psum grouping differs (4 vs 8 devices) -> f64 ulp noise
        assert loss.epochs > epochs_before
        assert out[0] == pytest.approx(ref[0], rel=1e-12)
        np.testing.assert_allclose(out[1], ref[1], rtol=1e-9)
    finally:
        ctx.rebuild_mesh("local-mesh[8]")
        sds.close()


def test_streaming_loss_reshard_rejects_indivisible_geometry(ctx):
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.oocore import StreamingDataset
    from cycloneml_tpu.oocore.objective import StreamingLossFunction

    rng = np.random.RandomState(6)
    x = rng.randn(200, 3)
    y = (x[:, 0] > 0).astype(float)
    sds = StreamingDataset.from_chunks(
        ctx, iter([(x, y, None)]), 3, shard_rows=200)
    try:
        loss = StreamingLossFunction(
            sds, aggregators.binary_logistic(3, fit_intercept=False))

        class _FakeRT:
            data_parallelism = 7   # does not divide padRows (mult. of 64)

        with pytest.raises(ValueError, match="does not divide"):
            loss.reshard(_FakeRT())
    finally:
        sds.close()


# -- conf-armed wiring through the context ---------------------------------------

def test_mesh_supervisor_arms_speculation_from_conf(ctx):
    from cycloneml_tpu.conf import ELASTIC_SPECULATION
    assert speculation.active() is None
    ctx.conf.set(ELASTIC_SPECULATION, True)
    try:
        sup = ctx.mesh_supervisor()
        sp = speculation.active()
        assert sp is not None
        # the armed provider consumes the SUPERVISOR's verdict record
        assert not sp.latched("oocore.stage", "shard0")
        # default capacity channel attached: the process-global one
        ch = ecap.channel()
        ch.clear()
        ch.announce(CapacityEvent(master="local-mesh[8]"))
        assert sup.pending_capacity() is not None
        ch.clear()
    finally:
        ctx.conf.set(ELASTIC_SPECULATION, False)
        sp = speculation.active()
        if sp is not None:
            speculation.uninstall(sp)
            sp.close()
        if sp in getattr(ctx, "_speculators", []):
            ctx._speculators.remove(sp)


# -- stacked/CV fit lanes --------------------------------------------------------

def test_fit_lane_straggler_redispatch_serial_dedup(ctx):
    """A tuning grid point with a latched fit.lane verdict re-dispatches
    its next fit+score SERIALLY (two concurrent SPMD programs would
    deadlock the shared mesh) with first-result-wins; the duplicate
    dedups bitwise and the selected model is unchanged."""
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.evaluation import RegressionEvaluator
    from cycloneml_tpu.ml.regression import LinearRegression
    from cycloneml_tpu.ml.tuning import (ParamGridBuilder,
                                         TrainValidationSplit)

    rng = np.random.RandomState(8)
    x = rng.randn(160, 3)
    y = x @ np.array([1.0, -2.0, 0.5]) + 0.1 * rng.randn(160)
    frame = MLFrame(ctx, {"features": x, "label": y})

    def build():
        linreg = LinearRegression()
        grid = (ParamGridBuilder()
                .add_grid(linreg.get_param("regParam"), [0.0, 50.0])
                .build())
        return TrainValidationSplit(
            estimator=linreg, estimator_param_maps=grid,
            evaluator=RegressionEvaluator(metricName="rmse"), seed=42)

    reference = build().fit(frame)

    sp = Speculator(lambda: {"fit.lane:grid1"})
    prev = speculation.install(sp)
    try:
        model = build().fit(frame)
        st = sp.stats()
        lanes = [r["lane"] for r in st["re_dispatches"]]
        assert "fit.lane:grid1" in lanes       # the latched lane re-ran
        assert "fit.lane:grid0" not in lanes   # unconvicted lane did not
        assert st["dedup_hits"] >= 1 and st["mismatches"] == 0
        assert model.best_model.get("regParam") == \
            reference.best_model.get("regParam")
        assert model.avg_metrics == reference.avg_metrics
    finally:
        speculation.uninstall(sp)
        sp.close()


def test_fit_lanes_feed_skew_detector(ctx):
    """Serial tuning lanes record fit.lane samples — the detection input
    the re-dispatch consumes (one position per grid point)."""
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.evaluation import RegressionEvaluator
    from cycloneml_tpu.ml.regression import LinearRegression
    from cycloneml_tpu.ml.tuning import (ParamGridBuilder,
                                         TrainValidationSplit)
    from cycloneml_tpu.observe import skew

    det = skew.SkewDetector(window=16, min_samples=2)
    prev = skew.install(det)
    try:
        rng = np.random.RandomState(9)
        x = rng.randn(120, 3)
        y = x @ np.array([1.0, -2.0, 0.5])
        frame = MLFrame(ctx, {"features": x, "label": y})
        linreg = LinearRegression()
        grid = (ParamGridBuilder()
                .add_grid(linreg.get_param("regParam"), [0.0, 1.0])
                .build())
        TrainValidationSplit(
            estimator=linreg, estimator_param_maps=grid,
            evaluator=RegressionEvaluator(metricName="rmse"),
            seed=42).fit(frame)
        lanes = det._samples.get("fit.lane", {})
        assert set(lanes) == {"grid0", "grid1"}
        assert all(len(dq) == 1 for dq in lanes.values())
    finally:
        skew.uninstall(det)
        if prev is not None:
            skew.install(prev)


# -- the preemption signal hook --------------------------------------------------

def test_preemption_signal_routes_to_capacity_channel():
    from cycloneml_tpu.multihost import bootstrap

    ch = CapacityChannel()
    prev = signal.getsignal(signal.SIGUSR1)
    try:
        ok = bootstrap.install_preemption_handler(
            lambda: ch.announce(CapacityEvent(
                master="local-mesh[4]", reason="preempt signal")),
            signals=(signal.SIGUSR1,))
        assert ok
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.time() + 2.0
        while len(ch) == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert len(ch) == 1
        assert ch.take().reason == "preempt signal"
    finally:
        signal.signal(signal.SIGUSR1, prev)


def test_preemption_handler_refuses_off_main_thread():
    from cycloneml_tpu.multihost import bootstrap

    out = {}

    def run():
        out["ok"] = bootstrap.install_preemption_handler(
            lambda: None, signals=(signal.SIGUSR1,))

    t = threading.Thread(target=run)
    t.start()
    t.join()
    assert out["ok"] is False
