"""PySpark-compat surface + binary summary metrics + profiler hook."""

import os

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.classification import LogisticRegression
from cycloneml_tpu.ml.classification.logistic_regression import (
    BinaryLogisticRegressionSummary)


def test_spark_session_builder(ctx):
    from cycloneml_tpu.compat import SparkSession, getActiveSession
    spark = (SparkSession.builder.master("local-mesh[8]")
             .appName("compat-app").config("cyclone.custom.flag", "1")
             .getOrCreate())
    assert spark.sparkContext is ctx  # reuses the active context
    df = spark.createDataFrame({"x": [1.0, 2.0, 3.0]})
    assert df.count() == 3
    assert spark.sql is not None
    active = getActiveSession()
    assert active is not None and active.sparkContext is ctx
    # fresh builder per access (no shared mutable conf)
    b1, b2 = SparkSession.builder, SparkSession.builder
    assert b1 is not b2
    # getOrCreate returns the SAME session: temp views carry across calls
    spark.register_temp_view("compat_t", df)
    again = SparkSession.builder.getOrCreate()
    assert again is spark
    assert again.table("compat_t").count() == 3
    assert getActiveSession() is spark


def test_compat_functions_and_window():
    from cycloneml_tpu.compat import SparkSession, Window, col, functions as F
    spark = SparkSession.builder.getOrCreate()
    df = spark.createDataFrame({"k": ["a", "a", "b"], "v": [1.0, 2.0, 3.0]})
    out = df.withColumn(
        "rn", __import__("cycloneml_tpu.sql.window", fromlist=["row_number"])
        .row_number().over(Window.partition_by("k").order_by("v"))).to_dict()
    np.testing.assert_array_equal(out["rn"], [1, 2, 1])
    agg = df.groupBy("k").agg(F.sum("v").alias("s")).order_by("k").collect()
    assert [r.s for r in agg] == [3.0, 3.0]


def test_binary_summary_against_sklearn(ctx):
    from sklearn.metrics import roc_auc_score
    rng = np.random.RandomState(0)
    x = rng.randn(400, 6)
    y = (x @ rng.randn(6) + 0.3 * rng.randn(400) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    model = LogisticRegression(maxIter=30).fit(frame)
    summary = model.evaluate(frame)
    probs = np.asarray(model.transform(frame)["probability"])[:, 1]
    want_auc = roc_auc_score(y, probs)
    assert summary.area_under_roc == pytest.approx(want_auc, abs=1e-9)
    roc = summary.roc
    assert roc[0].tolist() == [0.0, 0.0] and roc[-1].tolist() == [1.0, 1.0]
    assert np.all(np.diff(roc[:, 0]) >= 0)
    pr = summary.pr
    assert pr[0, 0] == 0.0 and pr[-1, 0] == 1.0
    f1 = summary.f_measure_by_threshold()
    best_t = f1[np.argmax(f1[:, 1]), 0]
    assert 0.0 < best_t < 1.0
    assert summary.accuracy > 0.8


def test_binary_summary_known_values():
    scores = np.array([0.9, 0.8, 0.3, 0.2])
    labels = np.array([1.0, 0.0, 1.0, 0.0])
    s = BinaryLogisticRegressionSummary(scores, labels)
    # perfect ordering would be auc=1; this ordering gives 0.75
    assert s.area_under_roc == pytest.approx(0.75)
    np.testing.assert_allclose(s.recall_by_threshold()[:, 1],
                               [0.5, 0.5, 1.0, 1.0])
    assert s.accuracy == pytest.approx(0.5)


def test_evaluate_respects_custom_label_col(ctx):
    rng = np.random.RandomState(4)
    x = rng.randn(150, 3)
    y = (x @ rng.randn(3) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "target": y,
                          "label": np.zeros(150)})  # decoy column
    model = LogisticRegression(maxIter=10, labelCol="target").fit(frame)
    s = model.evaluate(frame)
    assert s.accuracy > 0.9  # scored against 'target', not the decoy


def test_summary_accuracy_respects_threshold(ctx):
    rng = np.random.RandomState(2)
    x = rng.randn(200, 4)
    y = (x @ rng.randn(4) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    model = LogisticRegression(maxIter=20).fit(frame)
    model.set("threshold", 0.95)  # prediction col shifts; accuracy follows
    s = model.evaluate(frame)
    pred = np.asarray(model.transform(frame)["prediction"])
    assert s.accuracy == pytest.approx(float((pred == y).mean()))
    with pytest.raises(ValueError, match="empty"):
        BinaryLogisticRegressionSummary(np.array([]), np.array([]))


def test_count_over_ordered_string_window():
    from cycloneml_tpu.sql import functions as F
    from cycloneml_tpu.sql.session import CycloneSession
    from cycloneml_tpu.sql.window import Window
    s = CycloneSession()
    df = s.create_data_frame({"k": ["a", "a"], "name": ["x", "y"],
                              "t": [1.0, 2.0]})
    out = df.with_column(
        "c", F.count("name").over(Window.partition_by("k").order_by("t")))
    np.testing.assert_array_equal(out.to_dict()["c"], [1, 2])


def test_als_resume_with_smaller_max_iter_rejected(ctx, tmp_path):
    from cycloneml_tpu.ml.recommendation.als import ALS
    rng = np.random.RandomState(0)
    u, i = np.where(rng.rand(20, 15) < 0.6)
    frame = MLFrame(ctx, {"user": u, "item": i,
                          "rating": rng.randn(len(u))})
    ck = str(tmp_path / "ck")
    ALS(rank=2, maxIter=5, seed=1, checkpointDir=ck,
        checkpointInterval=1).fit(frame)
    with pytest.raises(ValueError, match="over-trained"):
        ALS(rank=2, maxIter=3, seed=1, checkpointDir=ck,
            checkpointInterval=1).fit(frame)


def test_multinomial_evaluate_rejected(ctx):
    rng = np.random.RandomState(0)
    x = rng.randn(90, 4)
    y = rng.randint(0, 3, 90).astype(float)
    model = LogisticRegression(maxIter=5, family="multinomial").fit(
        MLFrame(ctx, {"features": x, "label": y}))
    with pytest.raises(ValueError, match="binary-only"):
        model.evaluate(MLFrame(ctx, {"features": x, "label": y}))


def test_profiler_hook(ctx, tmp_path):
    import jax.numpy as jnp
    d = str(tmp_path / "trace")
    with ctx.profile(d):
        float(jnp.sum(jnp.arange(16.0)))
    # a trace directory with at least one artifact was produced
    found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert found
