"""Kafka source (fake consumer) + Python UDF tests."""

from types import SimpleNamespace

import numpy as np
import pytest

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.column import col
from cycloneml_tpu.sql.session import CycloneSession
from cycloneml_tpu.streaming.kafka import KafkaSource


class FakeConsumer:
    """Mimics kafka-python's poll() surface (≈ the reference testing its
    connector against an embedded broker)."""

    def __init__(self):
        self._pending = []
        self.committed = 0

    def feed(self, *records):
        self._pending.extend(records)

    def poll(self, timeout_ms=0):
        out, self._pending = {"tp0": list(self._pending)}, []
        return out

    def commit(self):
        self.committed += 1


def _rec(key, value, offset, ts=0):
    return SimpleNamespace(key=key, value=value, topic="t", partition=0,
                           offset=offset, timestamp=ts)


def test_kafka_source_streaming_query():
    s = CycloneSession()
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    from cycloneml_tpu.streaming.sources import StreamingScan
    from cycloneml_tpu.sql.dataframe import DataFrame
    df = DataFrame(StreamingScan(src, "kafka"), s)
    q = (df.select(col("value"), col("offset"))
         .write_stream.format("memory").start())

    consumer.feed(_rec(b"k1", b"hello", 0), _rec(b"k2", b"world", 1))
    q.process_all_available()
    assert [r[0] for r in q.sink.rows()] == ["hello", "world"]

    consumer.feed(_rec(b"k3", b"again", 2))
    q.process_all_available()
    assert len(q.sink.rows()) == 3
    assert consumer.committed >= 2  # offsets committed after each batch
    q.stop()


def test_kafka_replay_buffer_before_commit():
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    consumer.feed(_rec(b"a", b"1", 0), _rec(b"b", b"2", 1))
    end = src.latest_offset()
    assert end == 2
    batch1 = src.get_batch(0, end)
    batch2 = src.get_batch(0, end)  # replayable until committed
    assert batch1["value"].tolist() == batch2["value"].tolist() == ["1", "2"]
    src.commit(end)
    consumer.feed(_rec(b"c", b"3", 2))
    end2 = src.latest_offset()
    assert src.get_batch(end, end2)["value"].tolist() == ["3"]


def test_kafka_field_decode_contracts():
    """Keys are opaque bytes by default (hashed ids); values decode by
    default (text topics). Each field's type is uniform per configuration,
    never a content-dependent str/bytes mix."""
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    consumer.feed(_rec(b"\x93\xff", b"hello", 0))  # binary key + text value
    end = src.latest_offset()
    batch = src.get_batch(0, end)
    assert batch["key"][0] == b"\x93\xff" and batch["value"][0] == "hello"

    # binary VALUES under decode=True are a configuration error
    consumer.feed(_rec(b"k", b"\x00\x01\xfe", 1))
    with pytest.raises(ValueError, match="decode=False"):
        src.latest_offset()

    consumer2 = FakeConsumer()
    src2 = KafkaSource("t", consumer_factory=lambda: consumer2, decode=False)
    # a payload that HAPPENS to be valid UTF-8 still stays bytes
    consumer2.feed(_rec(b"k", b"\x0a\x03abc", 0), _rec(b"k", b"\x00\xfe", 1))
    end = src2.latest_offset()
    batch = src2.get_batch(0, end)
    assert all(isinstance(v, bytes) for v in batch["value"])
    # empty batches keep int64 schema for the numeric columns
    empty = src2.get_batch(end, end)
    for c in ("partition", "offset", "timestamp"):
        assert empty[c].dtype == np.int64 and len(empty[c]) == 0


def test_kafka_restart_recovers_pending_rows(tmp_path):
    """Exactly-once restart: a new source instance pointed at the same
    checkpoint log dir rebuilds the replay buffer, so engine offsets
    recovered from the query's offset log map to the SAME rows (the round-1
    advisory: in-memory offsets anchored at _base=0 broke this)."""
    log = str(tmp_path / "sources" / "kafka")
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    src.set_log_dir(log)
    consumer.feed(_rec(b"a", b"1", 0), _rec(b"b", b"2", 1))
    end1 = src.latest_offset()
    src.get_batch(0, end1)
    src.commit(end1)  # batch 0 committed
    consumer.feed(_rec(b"c", b"3", 2), _rec(b"d", b"4", 3))
    end2 = src.latest_offset()  # engine logs offset 4, then crashes

    # restart: fresh instance, fresh (empty) consumer — recovery must come
    # entirely from the checkpoint log
    src2 = KafkaSource("t", consumer_factory=FakeConsumer)
    src2.set_log_dir(log)
    assert src2.latest_offset() >= end2
    replay = src2.get_batch(end1, end2)
    assert replay["value"].tolist() == ["3", "4"]
    assert replay["offset"].tolist() == [2, 3]
    src2.commit(end2)

    # second restart after the commit: nothing pending, base preserved
    src3 = KafkaSource("t", consumer_factory=FakeConsumer)
    src3.set_log_dir(log)
    assert src3.latest_offset() == end2
    assert len(src3.get_batch(end2, end2)["value"]) == 0


def test_kafka_restart_filters_committed_wal_rows(tmp_path):
    """Crash between offsets.json write and WAL compaction: recovery must
    drop WAL rows whose Kafka offsets are already below the committed
    per-partition positions."""
    import json as _json
    import os as _os
    log = str(tmp_path / "k")
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    src.set_log_dir(log)
    consumer.feed(_rec(b"a", b"1", 0), _rec(b"b", b"2", 1))
    end = src.latest_offset()
    src.commit(end)
    # simulate the torn state: stuff committed rows back into the WAL
    with open(_os.path.join(log, "wal.jsonl"), "a", encoding="utf-8") as fh:
        fh.write(_json.dumps([{"b64": "YQ=="}, "1", "t", 0, 0, 0]) + "\n")
    src2 = KafkaSource("t", consumer_factory=FakeConsumer)
    src2.set_log_dir(log)
    assert src2.latest_offset() == end  # stale row was filtered, not replayed


def test_kafka_restart_tolerates_torn_wal_tail(tmp_path):
    """A crash mid-append leaves a partial final WAL line; recovery must
    ignore it (standard WAL practice) instead of failing the restart."""
    import os as _os
    log = str(tmp_path / "k")
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    src.set_log_dir(log)
    consumer.feed(_rec(b"a", b"1", 0), _rec(b"b", b"2", 1))
    end = src.latest_offset()
    with open(_os.path.join(log, "wal.jsonl"), "a", encoding="utf-8") as fh:
        fh.write('[{"b64": "YQ==')  # torn mid-write
    src2 = KafkaSource("t", consumer_factory=FakeConsumer)
    src2.set_log_dir(log)
    assert src2.latest_offset() == end
    assert src2.get_batch(0, end)["value"].tolist() == ["1", "2"]


def test_kafka_redelivery_deduped_after_restart(tmp_path):
    """If the restarted consumer re-delivers rows already rebuilt from the
    WAL (failed seek / reset-to-earliest), the per-partition offset filter
    must drop them — engine offsets keep mapping to the same rows."""
    log = str(tmp_path / "k")
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    src.set_log_dir(log)
    consumer.feed(_rec(b"a", b"1", 0), _rec(b"b", b"2", 1))
    end = src.latest_offset()

    consumer2 = FakeConsumer()
    src2 = KafkaSource("t", consumer_factory=lambda: consumer2)
    src2.set_log_dir(log)
    # broker re-delivers everything from the earliest offset, plus one new row
    consumer2.feed(_rec(b"a", b"1", 0), _rec(b"b", b"2", 1), _rec(b"c", b"3", 2))
    end2 = src2.latest_offset()
    assert end2 == end + 1  # the two re-delivered rows were dropped
    assert src2.get_batch(0, end2)["value"].tolist() == ["1", "2", "3"]


def test_kafka_set_log_dir_idempotent(tmp_path):
    """Calling set_log_dir twice must not double-load the replay buffer."""
    log = str(tmp_path / "k")
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    src.set_log_dir(log)
    consumer.feed(_rec(b"a", b"1", 0))
    end = src.latest_offset()
    src.set_log_dir(log)
    assert src.latest_offset() == end
    assert src.get_batch(0, end)["value"].tolist() == ["1"]
    src.close()


def test_kafka_restart_preserves_binary_payloads(tmp_path):
    log = str(tmp_path / "k")
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer, decode=False)
    src.set_log_dir(log)
    consumer.feed(_rec(b"\x93\xff", b"\x00\x01\xfe", 0))
    end = src.latest_offset()
    src2 = KafkaSource("t", consumer_factory=FakeConsumer, decode=False)
    src2.set_log_dir(log)
    batch = src2.get_batch(0, end)
    assert batch["key"][0] == b"\x93\xff"
    assert batch["value"][0] == b"\x00\x01\xfe"


def test_kafka_requires_client_without_factory():
    with pytest.raises(ImportError, match="kafka-python"):
        KafkaSource("t")


# -- UDFs -----------------------------------------------------------------------

def test_udf_single_and_multi_arg():
    s = CycloneSession()
    df = s.create_data_frame({"a": [1.0, 2.0, 3.0], "b": [10.0, 20.0, 30.0]})
    squared = F.udf(lambda v: v * v, name="squared")
    got = df.select(squared(col("a")).alias("sq")).to_dict()["sq"]
    np.testing.assert_allclose(got, [1.0, 4.0, 9.0])

    hyp = F.udf(lambda x, y: (x ** 2 + y ** 2) ** 0.5)
    got = df.select(hyp(col("a"), col("b")).alias("h")).to_dict()["h"]
    np.testing.assert_allclose(got, np.hypot([1, 2, 3], [10, 20, 30]))


def test_udf_string_and_filter():
    s = CycloneSession()
    df = s.create_data_frame({"name": ["ann", "bob"], "n": [1, 2]})
    up = F.udf(str.upper)
    rows = df.with_column("loud", up(col("name"))).collect()
    assert [r.loud for r in rows] == ["ANN", "BOB"]
    flag = F.udf(lambda v: v % 2 == 0)
    assert df.filter(flag(col("n"))).count() == 1


def test_zero_arg_udf_emits_per_row():
    s = CycloneSession()
    df = s.create_data_frame({"x": [1.0, 2.0, 3.0, 4.0]})
    const = F.udf(lambda: 7.0)
    out = df.select(const().alias("o"), col("x")).to_dict()
    assert out["o"].shape == (4,)  # not a ragged 0-length column
    np.testing.assert_allclose(out["o"], 7.0)


def test_udf_composes_with_expressions():
    s = CycloneSession()
    df = s.create_data_frame({"v": [1.0, 2.0]})
    inc = F.udf(lambda v: v + 1)
    out = df.select((inc(col("v")) * 10).alias("x")).to_dict()["x"]
    np.testing.assert_allclose(out, [20.0, 30.0])


class _FakeKinesisClient:
    """Two-shard in-memory Kinesis: iterator tokens are (shard, pos)."""

    def __init__(self):
        self.shards = {"shard-0": [], "shard-1": []}
        self._seq = 0

    def put(self, key: str, data):
        sid = f"shard-{hash(key) % 2}"
        self._seq += 1
        self.shards[sid].append(
            {"Data": data, "PartitionKey": key,
             "SequenceNumber": f"{self._seq:020d}",
             "ApproximateArrivalTimestamp": 1700000000 + self._seq})
        return sid

    def shard_of(self, key: str) -> str:
        return f"shard-{hash(key) % 2}"

    def list_shards(self, StreamName):
        return {"Shards": [{"ShardId": s} for s in self.shards]}

    def get_shard_iterator(self, StreamName, ShardId, ShardIteratorType,
                           StartingSequenceNumber=None):
        recs = self.shards[ShardId]
        if ShardIteratorType == "TRIM_HORIZON":
            pos = 0
        else:  # AFTER_SEQUENCE_NUMBER
            pos = sum(1 for r in recs
                      if r["SequenceNumber"] <= StartingSequenceNumber)
        return {"ShardIterator": f"{ShardId}:{pos}"}

    def get_records(self, ShardIterator, Limit):
        sid, pos = ShardIterator.rsplit(":", 1)
        pos = int(pos)
        recs = self.shards[sid][pos: pos + Limit]
        return {"Records": recs,
                "NextShardIterator": f"{sid}:{pos + len(recs)}"}


def test_kinesis_source_contract(tmp_path):
    """KinesisSource: replayable batches, commit checkpoints per-shard
    sequence numbers, restart resumes AFTER committed records (the KCL
    checkpoint analog; ref external/kinesis-asl)."""
    from cycloneml_tpu.streaming.kinesis import KinesisSource

    fake = _FakeKinesisClient()
    for i in range(6):
        fake.put(f"k{i}", f"payload-{i}".encode())
    src = KinesisSource("s", client_factory=lambda: fake)
    src.set_log_dir(str(tmp_path / "ck"))
    end = src.latest_offset()
    assert end == 6
    b = src.get_batch(0, end)
    assert sorted(b["data"].tolist()) == [f"payload-{i}" for i in range(6)]
    assert b["approximateArrivalTimestamp"].dtype.kind == "i"
    # replayable until commit
    again = src.get_batch(0, end)
    assert again["sequenceNumber"].tolist() == b["sequenceNumber"].tolist()
    src.commit(end)
    assert src.get_batch(end, src.latest_offset())["data"].size == 0

    # new records, then a restart: only post-commit records come back
    for i in range(6, 9):
        fake.put(f"k{i}", f"payload-{i}".encode())
    src2 = KinesisSource("s", client_factory=lambda: fake)
    src2.set_log_dir(str(tmp_path / "ck"))
    end2 = src2.latest_offset()
    got = src2.get_batch(src2._base, end2)["data"].tolist()
    assert sorted(got) == [f"payload-{i}" for i in range(6, 9)]


def test_kinesis_gated_without_client():
    from cycloneml_tpu.streaming.kinesis import KinesisSource
    try:
        import boto3  # noqa: F401
        pytest.skip("boto3 present; gate not exercised")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="boto3"):
        KinesisSource("s")


def test_kinesis_closed_shard_and_numeric_seq(tmp_path):
    """A shard whose iterator chain ends (reshard) must not replay forever,
    and sequence checkpoints compare numerically (review r3)."""
    from cycloneml_tpu.streaming.kinesis import KinesisSource

    fake = _FakeKinesisClient()
    # short sequence numbers force the lexicographic-vs-numeric distinction
    fake.shards["shard-0"] = [
        {"Data": b"a", "PartitionKey": "p", "SequenceNumber": "99",
         "ApproximateArrivalTimestamp": 1},
        {"Data": b"b", "PartitionKey": "p", "SequenceNumber": "100",
         "ApproximateArrivalTimestamp": 2}]

    class _Closing(type(fake)):
        pass

    def closing_get_records(ShardIterator, Limit):
        resp = _FakeKinesisClient.get_records(fake, ShardIterator, Limit)
        sid = ShardIterator.rsplit(":", 1)[0]
        if sid == "shard-1":
            resp["NextShardIterator"] = None  # closed shard
        return resp

    fake.get_records = closing_get_records
    src = KinesisSource("s", client_factory=lambda: fake)
    src.set_log_dir(str(tmp_path / "ck"))
    end = src.latest_offset()
    assert end == 2
    src.get_batch(0, end)
    src.commit(end)
    # numeric comparison kept "100" as the checkpoint (lexicographic would
    # have kept "99")
    assert src._committed_seq["shard-0"] == "100"
    # a closed shard does not duplicate rows on re-poll
    assert src.latest_offset() == 2
    assert src.latest_offset() == 2
