"""Kafka source (fake consumer) + Python UDF tests."""

from types import SimpleNamespace

import numpy as np
import pytest

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.column import col
from cycloneml_tpu.sql.session import CycloneSession
from cycloneml_tpu.streaming.kafka import KafkaSource


class FakeConsumer:
    """Mimics kafka-python's poll() surface (≈ the reference testing its
    connector against an embedded broker)."""

    def __init__(self):
        self._pending = []
        self.committed = 0

    def feed(self, *records):
        self._pending.extend(records)

    def poll(self, timeout_ms=0):
        out, self._pending = {"tp0": list(self._pending)}, []
        return out

    def commit(self):
        self.committed += 1


def _rec(key, value, offset, ts=0):
    return SimpleNamespace(key=key, value=value, topic="t", partition=0,
                           offset=offset, timestamp=ts)


def test_kafka_source_streaming_query():
    s = CycloneSession()
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    from cycloneml_tpu.streaming.sources import StreamingScan
    from cycloneml_tpu.sql.dataframe import DataFrame
    df = DataFrame(StreamingScan(src, "kafka"), s)
    q = (df.select(col("value"), col("offset"))
         .write_stream.format("memory").start())

    consumer.feed(_rec(b"k1", b"hello", 0), _rec(b"k2", b"world", 1))
    q.process_all_available()
    assert [r[0] for r in q.sink.rows()] == ["hello", "world"]

    consumer.feed(_rec(b"k3", b"again", 2))
    q.process_all_available()
    assert len(q.sink.rows()) == 3
    assert consumer.committed >= 2  # offsets committed after each batch
    q.stop()


def test_kafka_replay_buffer_before_commit():
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    consumer.feed(_rec(b"a", b"1", 0), _rec(b"b", b"2", 1))
    end = src.latest_offset()
    assert end == 2
    batch1 = src.get_batch(0, end)
    batch2 = src.get_batch(0, end)  # replayable until committed
    assert batch1["value"].tolist() == batch2["value"].tolist() == ["1", "2"]
    src.commit(end)
    consumer.feed(_rec(b"c", b"3", 2))
    end2 = src.latest_offset()
    assert src.get_batch(end, end2)["value"].tolist() == ["3"]


def test_kafka_field_decode_contracts():
    """Keys are opaque bytes by default (hashed ids); values decode by
    default (text topics). Each field's type is uniform per configuration,
    never a content-dependent str/bytes mix."""
    consumer = FakeConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    consumer.feed(_rec(b"\x93\xff", b"hello", 0))  # binary key + text value
    end = src.latest_offset()
    batch = src.get_batch(0, end)
    assert batch["key"][0] == b"\x93\xff" and batch["value"][0] == "hello"

    # binary VALUES under decode=True are a configuration error
    consumer.feed(_rec(b"k", b"\x00\x01\xfe", 1))
    with pytest.raises(ValueError, match="decode=False"):
        src.latest_offset()

    consumer2 = FakeConsumer()
    src2 = KafkaSource("t", consumer_factory=lambda: consumer2, decode=False)
    # a payload that HAPPENS to be valid UTF-8 still stays bytes
    consumer2.feed(_rec(b"k", b"\x0a\x03abc", 0), _rec(b"k", b"\x00\xfe", 1))
    end = src2.latest_offset()
    batch = src2.get_batch(0, end)
    assert all(isinstance(v, bytes) for v in batch["value"])
    # empty batches keep int64 schema for the numeric columns
    empty = src2.get_batch(end, end)
    for c in ("partition", "offset", "timestamp"):
        assert empty[c].dtype == np.int64 and len(empty[c]) == 0


def test_kafka_requires_client_without_factory():
    with pytest.raises(ImportError, match="kafka-python"):
        KafkaSource("t")


# -- UDFs -----------------------------------------------------------------------

def test_udf_single_and_multi_arg():
    s = CycloneSession()
    df = s.create_data_frame({"a": [1.0, 2.0, 3.0], "b": [10.0, 20.0, 30.0]})
    squared = F.udf(lambda v: v * v, name="squared")
    got = df.select(squared(col("a")).alias("sq")).to_dict()["sq"]
    np.testing.assert_allclose(got, [1.0, 4.0, 9.0])

    hyp = F.udf(lambda x, y: (x ** 2 + y ** 2) ** 0.5)
    got = df.select(hyp(col("a"), col("b")).alias("h")).to_dict()["h"]
    np.testing.assert_allclose(got, np.hypot([1, 2, 3], [10, 20, 30]))


def test_udf_string_and_filter():
    s = CycloneSession()
    df = s.create_data_frame({"name": ["ann", "bob"], "n": [1, 2]})
    up = F.udf(str.upper)
    rows = df.with_column("loud", up(col("name"))).collect()
    assert [r.loud for r in rows] == ["ANN", "BOB"]
    flag = F.udf(lambda v: v % 2 == 0)
    assert df.filter(flag(col("n"))).count() == 1


def test_zero_arg_udf_emits_per_row():
    s = CycloneSession()
    df = s.create_data_frame({"x": [1.0, 2.0, 3.0, 4.0]})
    const = F.udf(lambda: 7.0)
    out = df.select(const().alias("o"), col("x")).to_dict()
    assert out["o"].shape == (4,)  # not a ragged 0-length column
    np.testing.assert_allclose(out["o"], 7.0)


def test_udf_composes_with_expressions():
    s = CycloneSession()
    df = s.create_data_frame({"v": [1.0, 2.0]})
    inc = F.udf(lambda v: v + 1)
    out = df.select((inc(col("v")) * 10).alias("x")).to_dict()["x"]
    np.testing.assert_allclose(out, [20.0, 30.0])
