"""Round-5 optimizer content (r4 verdict weak #4): boolean simplification,
filter pruning, limit combination/pushdown, sort/distinct dedup, IN-
subquery -> left_semi join rewrite, and subquery-plan optimization —
Catalyst's BooleanSimplification / PruneFilters / CombineLimits /
LimitPushDown / EliminateSorts / RewritePredicateSubquery /
OptimizeSubqueries analogs (ref catalyst/optimizer/Optimizer.scala:77)."""

import numpy as np
import pytest

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.column import col
from cycloneml_tpu.sql.optimizer import optimize
from cycloneml_tpu.sql.plan import Filter, Join, Limit, Project
from cycloneml_tpu.sql.session import CycloneSession


@pytest.fixture()
def session():
    s = CycloneSession()
    df = s.create_data_frame({
        "k": np.arange(8, dtype=np.int64),
        "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0]),
        "g": np.array(list("aabbccdd"), dtype=object),
    })
    s.register_temp_view("t", df)
    s.register_temp_view("s", s.create_data_frame(
        {"k2": np.array([2, 3, 5], dtype=np.int64)}))
    return s


def _plan_of(df):
    return df.optimized_plan()


def test_not_pushes_through_demorgan(session):
    """De Morgan splits NOT(a OR b) into conjuncts (enabling per-side
    pushdown); comparisons are deliberately NOT flipped (NaN semantics,
    see test_not_comparison_keeps_nan_rows)."""
    df = session.sql("SELECT k FROM t WHERE NOT (k < 3 OR v >= 7)")
    plan = _plan_of(df)
    s = plan.tree_string()
    assert " or " not in s  # the OR was split by De Morgan
    assert sorted(np.asarray(df.to_dict()["k"]).tolist()) == [3, 4, 5]


def test_true_filter_pruned_false_and_collapses(session):
    df = session.sql("SELECT k FROM t WHERE 1 = 1")
    assert "Filter" not in _plan_of(df).tree_string()
    assert len(df.to_dict()["k"]) == 8
    # a conjunct with literal FALSE folds the whole condition to FALSE
    df2 = session.sql("SELECT k FROM t WHERE k > 2 AND 1 = 2")
    assert len(df2.to_dict()["k"]) == 0


def test_combine_and_push_limits(session):
    df = session.table("t").select("k").limit(5).limit(3)
    plan = _plan_of(df)
    s = plan.tree_string()
    assert s.count("Limit") >= 1
    # limit pushed below the project, min taken
    node = plan
    while not isinstance(node, Limit):
        node = node.children[0]
    assert node.n == 3 or isinstance(plan, Project)
    assert len(df.to_dict()["k"]) == 3


def test_sort_sort_keeps_outer_distinct_dedupes(session):
    t = session.table("t")
    df = t.order_by("v").order_by("k").distinct().distinct()
    s = _plan_of(df).tree_string()
    assert s.count("Sort") == 1
    assert s.count("Distinct") == 1


def test_in_subquery_becomes_semi_join(session):
    df = session.sql("SELECT k, v FROM t WHERE k IN (SELECT k2 FROM s)")
    plan = _plan_of(df)
    joins = []

    def walk(p):
        if isinstance(p, Join):
            joins.append(p)
        for c in p.children:
            walk(c)
    walk(plan)
    assert any(j.how == "left_semi" for j in joins), plan.tree_string()
    out = df.to_dict()
    assert sorted(np.asarray(out["k"]).tolist()) == [2, 3, 5]
    # residual conjuncts survive the rewrite
    df2 = session.sql(
        "SELECT k FROM t WHERE k IN (SELECT k2 FROM s) AND v > 3.5")
    # v = k + 1: k=2 (v=3.0) drops, k=3 (4.0) and k=5 (6.0) survive
    assert sorted(np.asarray(df2.to_dict()["k"]).tolist()) == [3, 5]


def test_subquery_plans_get_optimized(session, tmp_path):
    """OptimizeSubqueries: pushdown reaches the plan held by an
    IN-subquery over a FileScan."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    pq.write_table(pa.table({"k2": np.array([1, 4, 6], dtype=np.int64),
                             "x": np.arange(3.0)}),
                   str(tmp_path / "sub.parquet"))
    sub = session.scan_parquet(str(tmp_path / "sub.parquet")) \
        .filter(col("x") < 2.0).select("k2")
    inner_plan = sub.plan
    from cycloneml_tpu.sql.plan import InSubquery
    t = session.table("t")
    cond = InSubquery(col("k").expr, inner_plan)
    filtered = Filter(t.plan, cond)
    from cycloneml_tpu.sql.dataframe import DataFrame
    df = DataFrame(filtered, session)
    plan = _plan_of(df)
    # find the rewritten semi join's right side: the FileScan must carry
    # the pushed filter
    s = plan.tree_string()
    assert "left_semi" in s or "FileScan" in s
    out = df.to_dict()
    assert sorted(np.asarray(out["k"]).tolist()) == [1, 4]  # 6 filtered by x<2


def test_not_comparison_keeps_nan_rows(session):
    """Review r5: NOT(a < b) must NOT flip to a >= b — the engine's
    two-valued NaN semantics keeps NaN rows under the negation."""
    s2 = CycloneSession()
    s2.register_temp_view("n", s2.create_data_frame(
        {"a": np.array([np.nan, 1.0, 9.0])}))
    out = s2.sql("SELECT a FROM n WHERE NOT (a < 5)").to_dict()["a"]
    assert len(out) == 2 and np.isnan(out[0]) and out[1] == 9.0


def test_limit_not_pushed_past_window(session):
    df = session.sql(
        "SELECT v, SUM(v) OVER () AS s FROM t").limit(2)
    out = df.to_dict()
    assert len(out["s"]) == 2
    np.testing.assert_allclose(out["s"], [36.0, 36.0])  # whole-table sum


def test_semi_join_rewrite_nan_never_matches(session):
    s2 = CycloneSession()
    s2.register_temp_view("p", s2.create_data_frame(
        {"x": np.array([np.nan, 1.0, 2.0])}))
    s2.register_temp_view("q", s2.create_data_frame(
        {"y": np.array([np.nan, 2.0])}))
    out = s2.sql("SELECT x FROM p WHERE x IN (SELECT y FROM q)"
                 ).to_dict()["x"]
    assert out.tolist() == [2.0]


def test_exists_subquery_plan_not_mutated(session):
    """The subquery pass is copy-on-write: optimizing a DataFrame must
    not rewrite the plan object the user's handle still holds."""
    from cycloneml_tpu.sql.plan import ExistsSubquery
    sub_df = session.table("t").filter(col("v") > 100.0).select("k")
    sub_plan = sub_df.plan
    before = sub_plan.tree_string()
    t = session.table("t")
    from cycloneml_tpu.sql.dataframe import DataFrame
    df = DataFrame(Filter(t.plan, ExistsSubquery(sub_plan)), session)
    df.to_dict()
    assert sub_plan.tree_string() == before
