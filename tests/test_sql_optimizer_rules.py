"""Round-5 optimizer content (r4 verdict weak #4): boolean simplification,
filter pruning, limit combination/pushdown, sort/distinct dedup, IN-
subquery -> left_semi join rewrite, and subquery-plan optimization —
Catalyst's BooleanSimplification / PruneFilters / CombineLimits /
LimitPushDown / EliminateSorts / RewritePredicateSubquery /
OptimizeSubqueries analogs (ref catalyst/optimizer/Optimizer.scala:77)."""

import numpy as np
import pytest

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.column import col
from cycloneml_tpu.sql.optimizer import optimize
from cycloneml_tpu.sql.plan import Filter, Join, Limit, Project
from cycloneml_tpu.sql.session import CycloneSession


@pytest.fixture()
def session():
    s = CycloneSession()
    df = s.create_data_frame({
        "k": np.arange(8, dtype=np.int64),
        "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0]),
        "g": np.array(list("aabbccdd"), dtype=object),
    })
    s.register_temp_view("t", df)
    s.register_temp_view("s", s.create_data_frame(
        {"k2": np.array([2, 3, 5], dtype=np.int64)}))
    return s


def _plan_of(df):
    return df.optimized_plan()


def test_not_pushes_through_demorgan(session):
    """De Morgan splits NOT(a OR b) into conjuncts (enabling per-side
    pushdown); comparisons are deliberately NOT flipped (NaN semantics,
    see test_not_comparison_keeps_nan_rows)."""
    df = session.sql("SELECT k FROM t WHERE NOT (k < 3 OR v >= 7)")
    plan = _plan_of(df)
    s = plan.tree_string()
    assert " or " not in s  # the OR was split by De Morgan
    assert sorted(np.asarray(df.to_dict()["k"]).tolist()) == [3, 4, 5]


def test_true_filter_pruned_false_and_collapses(session):
    df = session.sql("SELECT k FROM t WHERE 1 = 1")
    assert "Filter" not in _plan_of(df).tree_string()
    assert len(df.to_dict()["k"]) == 8
    # a conjunct with literal FALSE folds the whole condition to FALSE
    df2 = session.sql("SELECT k FROM t WHERE k > 2 AND 1 = 2")
    assert len(df2.to_dict()["k"]) == 0


def test_combine_and_push_limits(session):
    df = session.table("t").select("k").limit(5).limit(3)
    plan = _plan_of(df)
    s = plan.tree_string()
    assert s.count("Limit") >= 1
    # limit pushed below the project, min taken
    node = plan
    while not isinstance(node, Limit):
        node = node.children[0]
    assert node.n == 3 or isinstance(plan, Project)
    assert len(df.to_dict()["k"]) == 3


def test_sort_sort_keeps_outer_distinct_dedupes(session):
    t = session.table("t")
    df = t.order_by("v").order_by("k").distinct().distinct()
    s = _plan_of(df).tree_string()
    assert s.count("Sort") == 1
    assert s.count("Distinct") == 1


def test_in_subquery_becomes_semi_join(session):
    df = session.sql("SELECT k, v FROM t WHERE k IN (SELECT k2 FROM s)")
    plan = _plan_of(df)
    joins = []

    def walk(p):
        if isinstance(p, Join):
            joins.append(p)
        for c in p.children:
            walk(c)
    walk(plan)
    assert any(j.how == "left_semi" for j in joins), plan.tree_string()
    out = df.to_dict()
    assert sorted(np.asarray(out["k"]).tolist()) == [2, 3, 5]
    # residual conjuncts survive the rewrite
    df2 = session.sql(
        "SELECT k FROM t WHERE k IN (SELECT k2 FROM s) AND v > 3.5")
    # v = k + 1: k=2 (v=3.0) drops, k=3 (4.0) and k=5 (6.0) survive
    assert sorted(np.asarray(df2.to_dict()["k"]).tolist()) == [3, 5]


def test_subquery_plans_get_optimized(session, tmp_path):
    """OptimizeSubqueries: pushdown reaches the plan held by an
    IN-subquery over a FileScan."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    pq.write_table(pa.table({"k2": np.array([1, 4, 6], dtype=np.int64),
                             "x": np.arange(3.0)}),
                   str(tmp_path / "sub.parquet"))
    sub = session.scan_parquet(str(tmp_path / "sub.parquet")) \
        .filter(col("x") < 2.0).select("k2")
    inner_plan = sub.plan
    from cycloneml_tpu.sql.plan import InSubquery
    t = session.table("t")
    cond = InSubquery(col("k").expr, inner_plan)
    filtered = Filter(t.plan, cond)
    from cycloneml_tpu.sql.dataframe import DataFrame
    df = DataFrame(filtered, session)
    plan = _plan_of(df)
    # find the rewritten semi join's right side: the FileScan must carry
    # the pushed filter
    s = plan.tree_string()
    assert "left_semi" in s or "FileScan" in s
    out = df.to_dict()
    assert sorted(np.asarray(out["k"]).tolist()) == [1, 4]  # 6 filtered by x<2


def test_not_comparison_keeps_nan_rows(session):
    """Review r5: NOT(a < b) must NOT flip to a >= b — the engine's
    two-valued NaN semantics keeps NaN rows under the negation."""
    s2 = CycloneSession()
    s2.register_temp_view("n", s2.create_data_frame(
        {"a": np.array([np.nan, 1.0, 9.0])}))
    out = s2.sql("SELECT a FROM n WHERE NOT (a < 5)").to_dict()["a"]
    assert len(out) == 2 and np.isnan(out[0]) and out[1] == 9.0


def test_limit_not_pushed_past_window(session):
    df = session.sql(
        "SELECT v, SUM(v) OVER () AS s FROM t").limit(2)
    out = df.to_dict()
    assert len(out["s"]) == 2
    np.testing.assert_allclose(out["s"], [36.0, 36.0])  # whole-table sum


def test_semi_join_rewrite_nan_never_matches(session):
    s2 = CycloneSession()
    s2.register_temp_view("p", s2.create_data_frame(
        {"x": np.array([np.nan, 1.0, 2.0])}))
    s2.register_temp_view("q", s2.create_data_frame(
        {"y": np.array([np.nan, 2.0])}))
    out = s2.sql("SELECT x FROM p WHERE x IN (SELECT y FROM q)"
                 ).to_dict()["x"]
    assert out.tolist() == [2.0]


def test_exists_subquery_plan_not_mutated(session):
    """The subquery pass is copy-on-write: optimizing a DataFrame must
    not rewrite the plan object the user's handle still holds."""
    from cycloneml_tpu.sql.plan import ExistsSubquery
    sub_df = session.table("t").filter(col("v") > 100.0).select("k")
    sub_plan = sub_df.plan
    before = sub_plan.tree_string()
    t = session.table("t")
    from cycloneml_tpu.sql.dataframe import DataFrame
    df = DataFrame(Filter(t.plan, ExistsSubquery(sub_plan)), session)
    df.to_dict()
    assert sub_plan.tree_string() == before


# -- join reordering (ref ReorderJoin joins.scala:40 / CostBasedJoinReorder)


def _join_chain_sizes(plan):
    """Left-deep inner-join chain → relation row counts, build order."""
    from cycloneml_tpu.sql.optimizer import _estimated_rows
    from cycloneml_tpu.sql.plan import Join as J
    sizes = []

    def walk(p):
        if isinstance(p, J) and p.how == "inner":
            walk(p.children[0])
            sizes.append(_estimated_rows(p.children[1]))
        else:
            sizes.append(_estimated_rows(p))
    walk(plan)
    return sizes


def _find_top_join(plan):
    from cycloneml_tpu.sql.plan import Join as J
    found = []

    def walk(p):
        if isinstance(p, J) and not found:
            found.append(p)
            return
        for c in p.children:
            walk(c)
    walk(plan)
    return found[0]


@pytest.fixture()
def star(session):
    """A star schema with deliberately bad user join order: fact (100
    rows) joined FIRST, tiny dims later."""
    s = session
    rng = np.random.RandomState(0)
    s.register_temp_view("fact", s.create_data_frame({
        "fk1": rng.randint(0, 4, 100).astype(np.int64),
        "fk2": rng.randint(0, 3, 100).astype(np.int64),
        "x": rng.randn(100)}))
    s.register_temp_view("dim1", s.create_data_frame({
        "d1": np.arange(4, dtype=np.int64),
        "n1": np.array(list("abcd"), dtype=object)}))
    s.register_temp_view("dim2", s.create_data_frame({
        "d2": np.arange(3, dtype=np.int64),
        "n2": np.array(list("pqr"), dtype=object)}))
    return s


def test_reorder_joins_smallest_first(star):
    df = star.sql(
        "SELECT n1, n2, x FROM fact "
        "JOIN dim1 ON fact.fk1 = dim1.d1 "
        "JOIN dim2 ON fact.fk2 = dim2.d2")
    sizes = _join_chain_sizes(_find_top_join(df.optimized_plan()))
    # greedy starts from the smallest relation (dim2, 3 rows) and the
    # fact table joins as soon as connectivity requires it
    assert sizes[0] == 3
    # results identical to the unoptimized order
    got = df.to_dict()
    import cycloneml_tpu.sql.optimizer as O
    orig = O.reorder_joins
    O.reorder_joins = lambda p: None
    try:
        want = star.sql(
            "SELECT n1, n2, x FROM fact "
            "JOIN dim1 ON fact.fk1 = dim1.d1 "
            "JOIN dim2 ON fact.fk2 = dim2.d2").to_dict()
    finally:
        O.reorder_joins = orig
    assert list(got) == list(want)
    for c in got:
        l = sorted(map(str, got[c]))
        r = sorted(map(str, want[c]))
        assert l == r, c


def test_reorder_preserves_output_names_and_rows(star):
    """The engine drops the right-side key column of each join, so
    reordering changes WHICH name survives — the rule must restore the
    original output schema via a projection."""
    df = star.sql(
        "SELECT * FROM fact "
        "JOIN dim1 ON fact.fk1 = dim1.d1 "
        "JOIN dim2 ON fact.fk2 = dim2.d2")
    out = df.to_dict()
    assert set(out) == {"fk1", "fk2", "x", "n1", "n2"}
    assert len(out["x"]) == 100
    # fk1 must hold the JOIN KEY values even though the reordered tree
    # surfaced dim1.d1 instead
    np.testing.assert_array_equal(np.sort(np.unique(out["fk1"])),
                                  np.arange(4))


def test_reorder_declines_two_relations_and_outer(star):
    from cycloneml_tpu.sql.optimizer import reorder_joins
    df2 = star.sql("SELECT x, n1 FROM fact JOIN dim1 ON fact.fk1 = dim1.d1")
    assert reorder_joins(_find_top_join(df2.plan)) is None
    dfo = star.sql(
        "SELECT x, n1, n2 FROM fact "
        "LEFT JOIN dim1 ON fact.fk1 = dim1.d1 "
        "LEFT JOIN dim2 ON fact.fk2 = dim2.d2")
    # outer joins are not reorderable; execution still correct
    assert len(dfo.to_dict()["x"]) == 100


def test_reorder_fixed_point(star):
    """Optimizing an already-optimized plan must not keep rewriting
    (projection wrappers piling up would show as tree churn)."""
    from cycloneml_tpu.sql.optimizer import optimize
    df = star.sql(
        "SELECT n1, n2, x FROM fact "
        "JOIN dim1 ON fact.fk1 = dim1.d1 "
        "JOIN dim2 ON fact.fk2 = dim2.d2")
    p1 = df.optimized_plan()
    p2 = optimize(p1)
    assert p2.tree_string() == p1.tree_string()


def test_reorder_same_name_key_pairs(session):
    """A ('k', 'k') join pair is legal (the right key column is dropped);
    edge ownership must resolve per subtree, not by bare name — and the
    equi-condition must never be silently dropped."""
    s = session
    s.register_temp_view("big", s.create_data_frame({
        "k": np.arange(50, dtype=np.int64) % 5,
        "x": np.arange(50, dtype=np.int64)}))
    s.register_temp_view("mid", s.create_data_frame({
        "k": np.arange(5, dtype=np.int64),
        "m": np.arange(5, dtype=np.int64) * 10}))
    s.register_temp_view("tiny", s.create_data_frame({
        "m2": np.array([0, 10], dtype=np.int64)}))
    df = s.sql("SELECT x, m FROM big "
               "JOIN mid ON big.k = mid.k "
               "JOIN tiny ON mid.m = tiny.m2")
    out = df.to_dict()
    # 2 surviving m values × 10 fact rows each
    assert len(out["x"]) == 20
    assert set(out["m"].tolist()) == {0, 10}


def test_reorder_shared_key_names_correct_values(session):
    """Review r5: two dimension tables both calling their key 'k' must
    not cross-wire the restore projection (value-equivalence classes are
    tracked per qualified column, not by bare name)."""
    s = session
    rng = np.random.RandomState(1)
    s.register_temp_view("f2", s.create_data_frame({
        "p": rng.randint(0, 4, 40).astype(np.int64),
        "q": rng.randint(0, 2, 40).astype(np.int64),
        "val": rng.randn(40)}))
    s.register_temp_view("dd1", s.create_data_frame({
        "k": np.arange(4, dtype=np.int64),
        "n1": np.array(list("abcd"), dtype=object)}))
    s.register_temp_view("dd2", s.create_data_frame({
        "k": np.arange(2, dtype=np.int64),
        "n2": np.array(list("pq"), dtype=object)}))
    q = ("SELECT p, q, n1, n2 FROM f2 "
         "JOIN dd1 ON f2.p = dd1.k "
         "JOIN dd2 ON f2.q = dd2.k")
    got = s.sql(q).to_dict()
    import cycloneml_tpu.sql.optimizer as O
    orig = O.reorder_joins
    O.reorder_joins = lambda p: None
    try:
        want = s.sql(q).to_dict()
    finally:
        O.reorder_joins = orig
    # join order changes ROW order (hash joins don't preserve it, as in
    # the reference) — compare the row SETS
    def rows(d):
        return sorted(zip(*(d[c] for c in ("p", "q", "n1", "n2"))))
    assert rows(got) == rows(want)
    # q values must be 0/1 (dd2's domain), never p's 0..3
    assert set(got["q"].tolist()) <= {0, 1}


def test_reorder_considers_whole_chain(session):
    """4-relation chain: the dedicated top-down pass flattens the WHOLE
    chain, so the globally smallest relation leads — a bottom-up rule
    would lock the inner 3-relation subchain first."""
    s = session
    rng = np.random.RandomState(2)
    s.register_temp_view("f4", s.create_data_frame({
        "a": rng.randint(0, 6, 60).astype(np.int64),
        "b": rng.randint(0, 5, 60).astype(np.int64),
        "c": rng.randint(0, 2, 60).astype(np.int64)}))
    s.register_temp_view("da", s.create_data_frame({
        "ka": np.arange(6, dtype=np.int64),
        "na": np.arange(6, dtype=np.int64) * 2}))
    s.register_temp_view("db", s.create_data_frame({
        "kb": np.arange(5, dtype=np.int64),
        "nb": np.arange(5, dtype=np.int64) * 3}))
    s.register_temp_view("dc", s.create_data_frame({
        "kc": np.arange(2, dtype=np.int64),
        "nc": np.arange(2, dtype=np.int64) * 5}))
    df = s.sql("SELECT na, nb, nc FROM f4 "
               "JOIN da ON f4.a = da.ka "
               "JOIN db ON f4.b = db.kb "
               "JOIN dc ON f4.c = dc.kc")
    sizes = _join_chain_sizes(_find_top_join(df.optimized_plan()))
    assert sizes[0] == 2  # dc (2 rows) leads the whole chain
    out = df.to_dict()
    assert len(out["na"]) == 60


# -- r5 second batch: EliminateOuterJoin / ConstantPropagation /
#    SimplifyCasts / LikeSimplification


def test_eliminate_outer_join_downgrades(session):
    """A null-rejecting filter over the outer side downgrades the join
    (ref EliminateOuterJoin, joins.scala): LEFT+reject(right) -> INNER,
    FULL+reject(right) -> LEFT. NOT-wrapped comparisons do NOT downgrade
    (two-valued NaN semantics keeps NaN rows under NOT)."""
    s = session
    s.register_temp_view("lo", s.create_data_frame({
        "k": np.array([1, 2, 3], dtype=np.int64),
        "a": np.array([10.0, 20.0, 30.0])}))
    s.register_temp_view("ro", s.create_data_frame({
        "k2": np.array([1, 2], dtype=np.int64),
        "b": np.array([5.0, 50.0])}))

    def top_join_how(df):
        return _find_top_join(df.optimized_plan()).how

    q = ("SELECT k, a, b FROM lo LEFT JOIN ro ON lo.k = ro.k2 "
         "WHERE b > 4")
    df = s.sql(q)
    assert top_join_how(df) == "inner"
    out = df.to_dict()
    assert sorted(out["k"].tolist()) == [1, 2]  # k=3's NULL b rejected

    # IS NOT NULL also rejects
    df = s.sql("SELECT k, b FROM lo LEFT JOIN ro ON lo.k = ro.k2 "
               "WHERE b IS NOT NULL")
    assert top_join_how(df) == "inner"

    # full outer: rejecting b (right side) kills the left-unmatched
    # null-extended rows — what remains is a RIGHT outer join
    df = s.sql("SELECT k, a, b FROM lo FULL OUTER JOIN ro "
               "ON lo.k = ro.k2 WHERE b > 4")
    assert top_join_how(df) == "right"
    out = df.to_dict()
    assert sorted(x for x in out["b"].tolist()) == [5.0, 50.0]

    # NOT(b < 100) KEEPS NULL rows -> no downgrade
    df = s.sql("SELECT k, b FROM lo LEFT JOIN ro ON lo.k = ro.k2 "
               "WHERE NOT (b < 4)")
    assert top_join_how(df) == "left"
    out = df.to_dict()
    assert len(out["k"]) == 3  # k=3 survives with NULL b


def test_constant_propagation(session):
    df = session.sql("SELECT k FROM t WHERE k = 5 AND v > k - 1")
    # k substitutes into the sibling: v > 4 folds to a literal compare
    plan_s = df.optimized_plan().tree_string()
    assert "k - 1" not in plan_s.replace("k = 5", "")
    assert df.to_dict()["k"].tolist() == [5]


def test_simplify_casts_and_like(session):
    from cycloneml_tpu.sql.column import Cast, col
    from cycloneml_tpu.sql.dataframe import DataFrame
    from cycloneml_tpu.sql.plan import Project
    t = session.table("t")
    e = Cast(Cast(col("v").expr, "bigint"), "bigint")
    df = DataFrame(Project(t.plan, [e]), session)
    s = df.optimized_plan().tree_string()
    assert s.count("cast") <= s.count("CAST") + 1  # nested same-cast gone
    vals = list(df.to_dict().values())[0]
    assert vals.tolist() == [1, 2, 3, 4, 5, 6, 7, 8]

    s2 = CycloneSession()
    s2.register_temp_view("names", s2.create_data_frame({
        "s": np.array(["apple", "grape", "applet", None, "pineapple"],
                      dtype=object)}))
    for pat, want in [("app%", ["apple", "applet"]),
                      ("%ple", ["apple", "pineapple"]),
                      ("%ppl%", ["apple", "applet", "pineapple"])]:
        df = s2.sql(f"SELECT s FROM names WHERE s LIKE '{pat}'")
        plan_s = df.optimized_plan().tree_string()
        assert "like" not in plan_s, (pat, plan_s)  # regex rewritten away
        assert sorted(df.to_dict()["s"].tolist()) == sorted(want), pat
    # single-char wildcard keeps the regex path
    df = s2.sql("SELECT s FROM names WHERE s LIKE 'appl_'")
    assert "like" in df.optimized_plan().tree_string()
    assert df.to_dict()["s"].tolist() == ["apple"]


def test_outer_join_key_filter_does_not_downgrade(session):
    """Review fix: a filter on the JOIN KEY must not downgrade a left
    join — the joined output's key column is never null-extended, so
    'k > 0' rejects nothing the outer join produced."""
    s2 = CycloneSession()
    s2.register_temp_view("lk", s2.create_data_frame({
        "k": np.array([1, 2], dtype=np.int64),
        "v": np.array([10.0, 20.0])}))
    s2.register_temp_view("rk", s2.create_data_frame({
        "k": np.array([1], dtype=np.int64),
        "w": np.array([100.0])}))
    df = s2.sql("SELECT k, v, w FROM lk LEFT JOIN rk ON lk.k = rk.k "
                "WHERE k > 0")
    assert _find_top_join(df.optimized_plan()).how == "left"
    out = df.to_dict()
    assert sorted(out["k"].tolist()) == [1, 2]  # k=2 row survives


def test_like_wildcard_free_becomes_string_equality(session):
    s2 = CycloneSession()
    s2.register_temp_view("names2", s2.create_data_frame({
        "s": np.array(["apple", "applet", None], dtype=object)}))
    df = s2.sql("SELECT s FROM names2 WHERE s LIKE 'apple'")
    plan_s = df.optimized_plan().tree_string()
    assert "like" not in plan_s and "str_eq" in plan_s
    assert df.to_dict()["s"].tolist() == ["apple"]


def test_outer_elimination_enables_reordering(star):
    """Integration: a LEFT join downgraded to INNER by a null-rejecting
    filter joins the reorderable chain — the downgrade runs in the
    rewrite loop, reordering in its later pass."""
    df = star.sql(
        "SELECT n1, n2, x FROM fact "
        "JOIN dim1 ON fact.fk1 = dim1.d1 "
        "LEFT JOIN dim2 ON fact.fk2 = dim2.d2 "
        "WHERE n2 IS NOT NULL")
    top = _find_top_join(df.optimized_plan())
    assert top.how == "inner"  # the LEFT join was downgraded
    sizes = _join_chain_sizes(top)
    # 3-relation chain, led by the FILTERED dim2 (est 3//2=1 — the
    # pushed-down IS NOT NULL shrank its estimate below dim1's 4)
    assert len(sizes) == 3 and sizes[0] == 1
    out = df.to_dict()
    assert len(out["x"]) == 100  # every fk2 matches a dim2 row
