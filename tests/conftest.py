"""Test fixtures.

The ``local-mesh`` fixture replaces the reference's ``local-cluster[n,c,m]``
trick (ref: SparkContext.scala:3058, used by DistributedSuite:35): instead of
spawning worker processes, we force the JAX host platform to expose 8 virtual
CPU devices and run the full SPMD path (shard_map + psum) on a real 8-way
mesh in-process.

Env must be set before jax initializes its backends — hence the top of this
file, which pytest imports before any test module.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: scale/ledger tests (minutes, subprocesses)")

from cycloneml_tpu import mesh as mesh_mod  # noqa: E402
from cycloneml_tpu.conf import CycloneConf  # noqa: E402
from cycloneml_tpu.context import CycloneContext  # noqa: E402


@pytest.fixture(scope="session")
def ctx():
    """Shared context over a local-mesh[8] (≈ SharedSparkContext:24)."""
    conf = CycloneConf().set("cyclone.master", "local-mesh[8]")
    c = CycloneContext(conf)
    yield c
    c.stop()


@pytest.fixture(scope="session", autouse=True)
def thread_audit():
    """Leak check for NON-daemon threads (≈ SparkFunSuite's ThreadAudit,
    SparkFunSuite.scala:44-49). Daemon threads (listener buses, trigger
    loops, metrics) die with the process and are exempt, as the reference
    exempts its known daemon pools."""
    import threading
    # process-lifetime pools, exempt like the reference exempts its known
    # pools (rpc/netty/forkjoin): the shared partition-task executor
    allowed_prefixes = ("cyclone-task",)
    before = {t.name for t in threading.enumerate() if not t.daemon}
    yield
    leaked = [t for t in threading.enumerate()
              if not t.daemon and t.is_alive() and t.name not in before
              and not t.name.startswith(allowed_prefixes)]
    assert not leaked, f"non-daemon threads leaked by tests: {leaked}"
