"""SQL window-function tests, cross-checked against pandas groupby idioms
(the reference's DataFrameWindowFunctionsSuite asserts the same shapes)."""

import numpy as np
import pandas as pd
import pytest

from cycloneml_tpu.sql import functions as F
from cycloneml_tpu.sql.column import col
from cycloneml_tpu.sql.session import CycloneSession
from cycloneml_tpu.sql.window import (Window, dense_rank, lag, lead,
                                      percent_rank, rank, row_number)


@pytest.fixture
def df():
    return CycloneSession().create_data_frame({
        "k": ["a", "a", "a", "b", "b"],
        "t": [3.0, 1.0, 2.0, 2.0, 1.0],
        "v": [30.0, 10.0, 20.0, 200.0, 100.0],
    })


def _pdf(df):
    return pd.DataFrame({c: v for c, v in df.to_dict().items()})


def test_row_number(df):
    w = Window.partition_by("k").order_by("t")
    out = df.with_column("rn", row_number().over(w)).to_dict()
    pdf = _pdf(df)
    want = pdf.groupby("k")["t"].rank(method="first").astype(int)
    np.testing.assert_array_equal(out["rn"], want.to_numpy())


def test_rank_and_dense_rank_with_ties():
    s = CycloneSession()
    df = s.create_data_frame({"k": ["a"] * 4, "t": [1.0, 2.0, 2.0, 3.0]})
    w = Window.partition_by("k").order_by("t")
    out = (df.with_column("r", rank().over(w))
             .with_column("dr", dense_rank().over(w))
             .with_column("pr", percent_rank().over(w)).to_dict())
    np.testing.assert_array_equal(out["r"], [1, 2, 2, 4])
    np.testing.assert_array_equal(out["dr"], [1, 2, 2, 3])
    np.testing.assert_allclose(out["pr"], [0.0, 1 / 3, 1 / 3, 1.0])


def test_lag_lead(df):
    w = Window.partition_by("k").order_by("t")
    out = (df.with_column("prev", lag("v").over(w))
             .with_column("next", lead("v").over(w))
             .order_by("k", "t").to_dict())
    np.testing.assert_allclose(out["prev"], [np.nan, 10.0, 20.0,
                                             np.nan, 100.0])
    np.testing.assert_allclose(out["next"], [20.0, 30.0, np.nan,
                                             200.0, np.nan])
    out2 = df.with_column("p", lag("v", 1, default=-1.0).over(w)).to_dict()
    assert -1.0 in out2["p"]


def test_running_sum_matches_pandas(df):
    w = Window.partition_by("k").order_by("t")
    out = (df.with_column("cum", F.sum("v").over(w))
             .order_by("k", "t").to_dict())
    pdf = _pdf(df).sort_values(["k", "t"])
    want = pdf.groupby("k")["v"].cumsum()
    np.testing.assert_allclose(out["cum"], want.to_numpy())


def test_whole_partition_agg_without_order(df):
    w = Window.partition_by("k")
    out = (df.with_column("total", F.sum("v").over(w))
             .with_column("mx", F.max("v").over(w)).to_dict())
    np.testing.assert_allclose(out["total"], [60.0, 60.0, 60.0, 300.0, 300.0])
    np.testing.assert_allclose(out["mx"], [30.0, 30.0, 30.0, 200.0, 200.0])


def test_running_min_max_avg(df):
    w = Window.partition_by("k").order_by("t")
    out = (df.with_column("mn", F.min("v").over(w))
             .with_column("av", F.avg("v").over(w))
             .order_by("k", "t").to_dict())
    np.testing.assert_allclose(out["mn"], [10.0, 10.0, 10.0, 100.0, 100.0])
    np.testing.assert_allclose(out["av"], [10.0, 15.0, 20.0, 100.0, 150.0])


def test_running_min_max_without_pandas(df, monkeypatch):
    """Ordered-window min/max must work when pandas is absent (it is an
    optional bridge dependency): the numpy per-partition accumulate fallback
    must produce the same result."""
    import builtins
    real_import = builtins.__import__

    def no_pandas(name, *a, **k):
        if name == "pandas" or name.startswith("pandas."):
            raise ImportError("pandas blocked for test")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_pandas)
    w = Window.partition_by("k").order_by("t")
    out = (df.with_column("mn", F.min("v").over(w))
             .with_column("mx", F.max("v").over(w))
             .order_by("k", "t").to_dict())
    np.testing.assert_allclose(out["mn"], [10.0, 10.0, 10.0, 100.0, 100.0])
    np.testing.assert_allclose(out["mx"], [10.0, 20.0, 30.0, 100.0, 200.0])


def test_range_frame_peers_share_value():
    """Ties on the order key take the frame value of the LAST peer (RANGE
    default, as the reference)."""
    s = CycloneSession()
    df = s.create_data_frame({"k": ["a"] * 3, "t": [1.0, 1.0, 2.0],
                              "v": [5.0, 7.0, 1.0]})
    w = Window.partition_by("k").order_by("t")
    out = df.with_column("cum", F.sum("v").over(w)).to_dict()
    np.testing.assert_allclose(out["cum"], [12.0, 12.0, 13.0])


def test_descending_order_and_global_window():
    s = CycloneSession()
    df = s.create_data_frame({"t": [1.0, 3.0, 2.0]})
    out = df.with_column(
        "rn", row_number().over(Window.order_by(col("t").desc()))).to_dict()
    np.testing.assert_array_equal(out["rn"], [3, 1, 2])


def test_count_over_window(df):
    w = Window.partition_by("k").order_by("t")
    out = (df.with_column("c", F.count("*").over(w))
             .order_by("k", "t").to_dict())
    np.testing.assert_array_equal(out["c"], [1, 2, 3, 1, 2])


def test_window_in_select_survives_pruning(df):
    """select() (optimizer prunes columns) must keep partition/order cols
    referenced only by the window spec."""
    w = Window.partition_by("k").order_by("t")
    out = df.select("v", row_number().over(w).alias("rn"))
    got = out.order_by("rn").collect()
    assert [r.rn for r in got][:3] == [1, 1, 2]


def test_descending_string_ties_fall_through(df):
    """Equal string keys under desc() must tie-break to the NEXT order key,
    not freeze in reversed input order."""
    s = CycloneSession()
    d = s.create_data_frame({"g": ["p", "p"], "name": ["b", "b"],
                             "t": [1.0, 2.0]})
    w = Window.partition_by("g").order_by(col("name").desc(), "t")
    out = d.with_column("rn", row_number().over(w)).to_dict()
    np.testing.assert_array_equal(out["rn"], [1, 2])


def test_ntile_and_cume_dist():
    s = CycloneSession()
    d = s.create_data_frame({"k": ["a"] * 5, "t": [1.0, 2.0, 3.0, 4.0, 5.0]})
    from cycloneml_tpu.sql.window import cume_dist, ntile
    w = Window.partition_by("k").order_by("t")
    out = (d.with_column("n2", ntile(2).over(w))
             .with_column("cd", cume_dist().over(w)).to_dict())
    np.testing.assert_array_equal(out["n2"], [1, 1, 1, 2, 2])
    np.testing.assert_allclose(out["cd"], [0.2, 0.4, 0.6, 0.8, 1.0])


def test_filter_not_pushed_below_window(df):
    """A filter above a window projection must NOT push below it — the
    window computes over the pre-filter rows."""
    w = Window.partition_by("k").order_by("t")
    out = (df.with_column("rn", row_number().over(w))
             .filter(col("v") > 15.0)
             .order_by("k", "t").to_dict())
    # a: rows t=2,3 survive with rn computed over ALL three a-rows
    np.testing.assert_array_equal(out["rn"], [2, 3, 1, 2])


def test_window_over_derived_column_survives_collapse(df):
    """Project-collapse substitution must rewrite exprs INSIDE the window
    spec (order key derived in a previous with_column)."""
    w = Window.partition_by("k").order_by("t2")
    out = (df.with_column("t2", col("t") * -1.0)
             .with_column("rn", row_number().over(w))
             .order_by("k", "t").to_dict())
    # t2 = -t: rank 1 goes to the LARGEST t in each partition; rows are
    # then displayed sorted by (k, t) ascending
    np.testing.assert_array_equal(out["rn"], [3, 2, 1, 2, 1])


def test_string_min_max_over_partition(df):
    w = Window.partition_by("k")
    out = df.with_column("mx", F.max("k").over(w)).to_dict()
    assert list(out["mx"]) == ["a", "a", "a", "b", "b"]
    with pytest.raises(ValueError, match="numeric"):
        df.with_column(
            "m", F.max("k").over(Window.partition_by("k").order_by("t"))
        ).to_dict()


def test_non_window_expr_rejected(df):
    with pytest.raises(ValueError, match="not a window function"):
        col("v").over(Window.partition_by("k"))
