"""Collective primitive tests on the 8-device mesh — the data-plane backend
(SURVEY §2.7: psum = treeAggregate, all_gather = barrier allGather,
all_to_all = dense shuffle)."""

import numpy as np

from cycloneml_tpu.parallel import collectives


def test_tree_aggregate_psum_exact(ctx):
    import jax.numpy as jnp
    rt = ctx.mesh_runtime
    x = np.arange(64.0).reshape(16, 4)
    xs = rt.device_put_sharded_rows(x)

    agg = collectives.tree_aggregate(lambda a: jnp.sum(a, axis=0), rt, xs)
    out = np.asarray(agg(xs))
    np.testing.assert_allclose(out, x.sum(axis=0))


def test_tree_aggregate_pytree(ctx):
    import jax.numpy as jnp
    rt = ctx.mesh_runtime
    x = np.ones((16, 2))
    xs = rt.device_put_sharded_rows(x)
    agg = collectives.tree_aggregate(
        lambda a: {"s": jnp.sum(a), "m": jnp.sum(a ** 2)}, rt, xs)
    out = agg(xs)
    assert float(out["s"]) == 32.0 and float(out["m"]) == 32.0


def test_all_gather_hosts(ctx):
    import jax.numpy as jnp
    rt = ctx.mesh_runtime
    x = np.arange(8.0).reshape(8, 1)
    xs = rt.device_put_sharded_rows(x)
    # each device contributes its local sum; gather returns all 8
    out = np.asarray(collectives.all_gather_hosts(
        rt, lambda a: jnp.sum(a, axis=0), xs))
    np.testing.assert_allclose(sorted(out.ravel()), np.arange(8.0))


def test_barrier_completes(ctx):
    collectives.barrier(ctx.mesh_runtime)


def test_all_to_all_repartition(ctx):
    rt = ctx.mesh_runtime
    n = rt.data_parallelism
    # rows labeled by destination shard
    x = np.repeat(np.arange(n), n).astype(np.float64).reshape(n * n, 1)
    # shard i holds rows [i*n, (i+1)*n) = labels i repeated — after a2a each
    # shard holds one row of every label
    xs = rt.device_put_sharded_rows(x)
    out = collectives.all_to_all_repartition(rt, xs)
    host = np.asarray(out).reshape(n, n)
    for shard in range(n):
        np.testing.assert_allclose(sorted(host[shard]), np.arange(n))


def test_sharding_is_distributed(ctx):
    rt = ctx.mesh_runtime
    x = np.zeros((64, 2))
    xs = rt.device_put_sharded_rows(x)
    assert len(xs.sharding.device_set) == 8
