"""Collective primitive tests on the 8-device mesh — the data-plane backend
(SURVEY §2.7: psum = treeAggregate, all_gather = barrier allGather,
all_to_all = dense shuffle)."""

import numpy as np

from cycloneml_tpu.parallel import collectives


def test_tree_aggregate_psum_exact(ctx):
    import jax.numpy as jnp
    rt = ctx.mesh_runtime
    x = np.arange(64.0).reshape(16, 4)
    xs = rt.device_put_sharded_rows(x)

    agg = collectives.tree_aggregate(lambda a: jnp.sum(a, axis=0), rt, xs)
    out = np.asarray(agg(xs))
    np.testing.assert_allclose(out, x.sum(axis=0))


def test_tree_aggregate_pytree(ctx):
    import jax.numpy as jnp
    rt = ctx.mesh_runtime
    x = np.ones((16, 2))
    xs = rt.device_put_sharded_rows(x)
    agg = collectives.tree_aggregate(
        lambda a: {"s": jnp.sum(a), "m": jnp.sum(a ** 2)}, rt, xs)
    out = agg(xs)
    assert float(out["s"]) == 32.0 and float(out["m"]) == 32.0


def test_all_gather_hosts(ctx):
    import jax.numpy as jnp
    rt = ctx.mesh_runtime
    x = np.arange(8.0).reshape(8, 1)
    xs = rt.device_put_sharded_rows(x)
    # each device contributes its local sum; gather returns all 8
    out = np.asarray(collectives.all_gather_hosts(
        rt, lambda a: jnp.sum(a, axis=0), xs))
    np.testing.assert_allclose(sorted(out.ravel()), np.arange(8.0))


def test_barrier_completes(ctx):
    collectives.barrier(ctx.mesh_runtime)


def test_all_to_all_repartition(ctx):
    rt = ctx.mesh_runtime
    n = rt.data_parallelism
    # rows labeled by destination shard
    x = np.repeat(np.arange(n), n).astype(np.float64).reshape(n * n, 1)
    # shard i holds rows [i*n, (i+1)*n) = labels i repeated — after a2a each
    # shard holds one row of every label
    xs = rt.device_put_sharded_rows(x)
    out = collectives.all_to_all_repartition(rt, xs)
    host = np.asarray(out).reshape(n, n)
    for shard in range(n):
        np.testing.assert_allclose(sorted(host[shard]), np.arange(n))


def test_sharding_is_distributed(ctx):
    rt = ctx.mesh_runtime
    x = np.zeros((64, 2))
    xs = rt.device_put_sharded_rows(x)
    assert len(xs.sharding.device_set) == 8


# -- treeAggregate depth: hierarchical (ICI->DCN) vs flat reduction -------------

def test_tree_aggregate_depth_parity_ulp(ctx):
    """The 2-level reduction (psum over data/ICI then replica/DCN) and the
    flat depth=1 psum agree at the ulp level in f64: only the reduction
    GROUPING differs (ISSUE 13 satellite). Seeded non-trivial values so a
    grouping bug cannot hide behind symmetric inputs."""
    import jax.numpy as jnp
    rt = ctx.mesh_runtime
    rng = np.random.RandomState(11)
    x = rng.randn(64, 8)
    xs = rt.device_put_sharded_rows(x)

    hier = collectives.tree_aggregate(
        lambda a: jnp.sum(a, axis=0), rt, xs, depth=2)
    flat = collectives.tree_aggregate(
        lambda a: jnp.sum(a, axis=0), rt, xs, depth=1)
    out2 = np.asarray(hier(xs))
    out1 = np.asarray(flat(xs))
    np.testing.assert_array_almost_equal_nulp(out1, out2, nulp=2)
    np.testing.assert_allclose(out2, x.sum(axis=0), rtol=1e-12)


def test_tree_aggregate_depth_forks_program_identity(ctx):
    """depth participates in program-cache identity: the flat and
    hierarchical reductions are DIFFERENT compiled programs (an XLA
    schedule property), while repeated same-depth calls share one."""
    import jax.numpy as jnp
    rt = ctx.mesh_runtime

    def kernel(a):
        return jnp.sum(a)

    xs = rt.device_put_sharded_rows(np.ones((16, 2)))
    a2 = collectives.tree_aggregate(kernel, rt, xs, depth=2)
    a1 = collectives.tree_aggregate(kernel, rt, xs, depth=1)
    again = collectives.tree_aggregate(kernel, rt, xs, depth=2)
    assert a1 is not a2
    assert again is a2
    assert float(a1(xs)) == float(a2(xs)) == 32.0


def test_tree_aggregate_depth_default_from_conf(ctx):
    """depth=None resolves cyclone.treeAggregate.depth from the active
    context — the conf key is live, not API decoration."""
    import jax.numpy as jnp

    rt = ctx.mesh_runtime

    def kernel(a):
        return jnp.sum(a)

    xs = rt.device_put_sharded_rows(np.ones((16, 2)))
    default = collectives.tree_aggregate(kernel, rt, xs)
    assert default is collectives.tree_aggregate(kernel, rt, xs, depth=2)
    old = ctx.conf.get("cyclone.treeAggregate.depth")
    try:
        ctx.conf.set("cyclone.treeAggregate.depth", 1)
        assert collectives.tree_aggregate(kernel, rt, xs) is \
            collectives.tree_aggregate(kernel, rt, xs, depth=1)
    finally:
        ctx.conf.set("cyclone.treeAggregate.depth", old)


def test_tree_aggregate_depth_preserves_contracts(ctx):
    """depth composes with the n_sharded/with_state contracts (the oocore
    compile-before-operands path and the kmeans state path keep working
    at depth=1)."""
    import jax.numpy as jnp
    rt = ctx.mesh_runtime
    x = np.arange(32.0).reshape(16, 2)
    xs = rt.device_put_sharded_rows(x)
    # n_sharded: compile before operands exist
    agg = collectives.tree_aggregate(
        lambda a: jnp.sum(a, axis=0), rt, n_sharded=1, depth=1)
    np.testing.assert_allclose(np.asarray(agg(xs)), x.sum(axis=0))
    # with_state: psummed stats + row-sharded state
    agg_st = collectives.tree_aggregate(
        lambda a: (jnp.sum(a), a + 1.0), rt, xs,
        with_state=True, depth=1)
    stats, rows = agg_st(xs)
    assert float(stats) == x.sum()
    np.testing.assert_allclose(np.asarray(rows), x + 1.0)


def test_reduction_levels_annotation():
    """The per-level structure the dispatch spans ship to the collector."""
    assert collectives.reduction_levels(2) == (
        ("ici", "data"), ("dcn", "replica"))
    assert collectives.reduction_levels(5) == (
        ("ici", "data"), ("dcn", "replica"))  # two tiers exist
    assert collectives.reduction_levels(1) == (("flat", "data+replica"),)
