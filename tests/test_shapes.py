"""Abstract shape & sharding interpretation (analysis/shapes.py) and the
JX015-018 rules built on it.

Domain-level tests drive the interpreter directly (symbolic dims,
padding marks, psummed-axes summaries, dataset-dim provenance); the
rule-level tests pin the interprocedural contracts the fixtures cannot:
cross-MODULE propagation (program built in one file, rebuild in another,
conviction in the untouched caller), the JX018 fit-path gate, and the
engine plumbing (shared JXSHAPE fixpoint deduped across the four rules,
per-rule timings). Pure ast — no jax import, no device work.
"""

import os
import textwrap

import pytest

from cycloneml_tpu.analysis import analyze_paths, shapes
from cycloneml_tpu.analysis.dataflow import TOP, CallGraph, run_dataflow
from cycloneml_tpu.analysis.engine import (AnalysisContext, _discover_axes,
                                           load_module)
from cycloneml_tpu.analysis.reachability import (CallResolver,
                                                 compute_reachability)
from cycloneml_tpu.analysis.rules.jx015_sharding_spec import ShardingSpecRule
from cycloneml_tpu.analysis.shapes import (AArray, ShapeRuleBase, Sym,
                                           summary_of)


def build_ctx(tmp_path, src, name="m.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    mod = load_module(str(p), name)
    modules = {name: mod}
    resolver = CallResolver(modules)
    compute_reachability(modules, resolver)
    graph = CallGraph(modules, resolver)
    axes, names, mapping = _discover_axes(modules)
    ctx = AnalysisContext(modules=modules, valid_axes=axes,
                          axis_constant_names=names, axis_constants=mapping,
                          callgraph=graph)
    ctx.dataflow = run_dataflow(graph, [ShardingSpecRule()], ctx)
    return mod, ctx


def fn_named(mod, qualname):
    return next(f for f in mod.functions if f.qualname == qualname)


# -- the dim/shape domain -----------------------------------------------------

def test_shape_unpack_names_dims_and_refines_the_array(tmp_path):
    mod, ctx = build_ctx(tmp_path, """
        import jax.numpy as jnp
        def f(x):
            n, d = x.shape
            buf = jnp.zeros((n, d))
            return buf
    """)
    st = ShapeRuleBase.state_of(ctx, fn_named(mod, "f"))
    x = st.env["x"]
    assert isinstance(x.shape, tuple) and len(x.shape) == 2
    n_dim, d_dim = x.shape
    assert isinstance(n_dim, Sym) and n_dim.label == "n"
    # the constructed buffer carries the SAME symbols — symbol identity
    # is what makes containment and mismatch reasoning sound
    ret = st.returns[0][1]
    assert ret.shape == (n_dim, d_dim)
    # `n` is a conventional row-count name: it became a dataset dim
    assert n_dim in st.dataset_syms


def test_concrete_dims_and_broadcast_conflict_event(tmp_path):
    mod, ctx = build_ctx(tmp_path, """
        import jax.numpy as jnp
        def ok():
            return jnp.zeros((4, 8)) + jnp.zeros((4, 8))
        def bad():
            return jnp.zeros((4, 8)) + jnp.zeros((5, 8))
    """)
    st_ok = ShapeRuleBase.state_of(ctx, fn_named(mod, "ok"))
    assert [e for e in st_ok.events if e.kind == "mismatch"] == []
    assert st_ok.returns[0][1].shape == (4, 8)
    st_bad = ShapeRuleBase.state_of(ctx, fn_named(mod, "bad"))
    assert len([e for e in st_bad.events if e.kind == "mismatch"]) == 1


def test_padding_marks_and_unpadding_slice(tmp_path):
    mod, ctx = build_ctx(tmp_path, """
        import jax.numpy as jnp
        import numpy as np
        def bucket(rows):
            k, d = rows.shape
            buf = np.zeros((64, 8))
            buf[:k] = rows
            unpadded = buf[:k]
            padded_jnp = jnp.pad(rows, ((0, 8), (0, 0)))
            at_set = jnp.zeros((64, 8)).at[:k].set(rows)
            return buf, unpadded, padded_jnp, at_set
    """)
    st = ShapeRuleBase.state_of(ctx, fn_named(mod, "bucket"))
    assert st.env["buf"].padded == {0}
    assert st.env["unpadded"].padded == frozenset()
    assert st.env["padded_jnp"].padded == {0}
    assert st.env["at_set"].padded == {0}


def test_reduction_removes_dims_and_mean_records_event(tmp_path):
    mod, ctx = build_ctx(tmp_path, """
        import jax.numpy as jnp
        def f(x):
            n, d = x.shape
            col = jnp.sum(x, axis=0)
            m = jnp.mean(x, axis=0)
            total = jnp.sum(x)
            return col, m, total
    """)
    st = ShapeRuleBase.state_of(ctx, fn_named(mod, "f"))
    d_dim = st.env["x"].shape[1]
    assert st.env["col"].shape == (d_dim,)
    assert st.env["total"].shape == ()
    means = [e for e in st.events if e.kind == "mean"]
    assert len(means) == 1 and means[0].axes == {0}


def test_psummed_summary_propagates_through_helpers(tmp_path):
    mod, ctx = build_ctx(tmp_path, """
        import jax
        import jax.numpy as jnp
        def _reduce(v):
            return jax.lax.psum(v, "data")
        def local(x):
            return _reduce(jnp.sum(x, axis=0))
        def local_state(x):
            return _reduce(jnp.sum(x, axis=0)), x
        def not_always(v, fast):
            if fast:
                return v
            return jax.lax.psum(v, "data")
    """)
    facts = ctx.dataflow.summaries(shapes.ANALYSIS_ID)
    assert summary_of(facts, fn_named(mod, "local")).ret_psummed \
        == (frozenset({"data"}),)
    assert summary_of(facts, fn_named(mod, "local_state")).ret_psummed \
        == (frozenset({"data"}), frozenset())
    # MUST semantics: psummed on every return path or not at all
    assert summary_of(facts, fn_named(mod, "not_always")).ret_psummed \
        == (frozenset(),)


def test_dataset_dims_from_aggregate_operands(tmp_path):
    mod, ctx = build_ctx(tmp_path, """
        import jax.numpy as jnp
        def _k(xb, coef):
            return jnp.sum(xb, axis=0)
        def fit(runtime, xb, coef):
            step = tree_aggregate(_k, runtime, xb)
            return step(xb, coef)
    """)
    st = ShapeRuleBase.state_of(ctx, fn_named(mod, "fit"))
    # the row-sharded aggregate operand's param root is dataset provenance
    assert st.dataset_roots == {1}
    facts = ctx.dataflow.summaries(shapes.ANALYSIS_ID)
    assert summary_of(facts, fn_named(mod, "fit")).reaches_aggregate


def test_spec_parsing_resolves_axis_constants(tmp_path):
    mod, ctx = build_ctx(tmp_path, """
        from jax.sharding import PartitionSpec as P
        def f(mesh, xs):
            row_spec = P((REPLICA_AXIS, DATA_AXIS))
            return shard_map_compat(_body, mesh, (row_spec,), P())(xs)
    """)
    st = ShapeRuleBase.state_of(ctx, fn_named(mod, "f"))
    spec = st.env["row_spec"]
    assert spec.entries == (frozenset({"replica", "data"}),)
    assert spec.axes() == {"replica", "data"}


# -- cross-module interprocedural pins ---------------------------------------

def _write_pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return str(pkg)


def test_jx017_cross_module_stale_program(tmp_path):
    """The acceptance pin: the program is built by a helper in ANOTHER
    module, the rebuild hides in a third function, and the conviction
    lands in the untouched caller holding the stale reference."""
    pkg = _write_pkg(tmp_path, {
        "builder.py": """
            import jax.numpy as jnp
            def _k(xb, coef):
                return jnp.sum(xb, axis=0)
            def make_step(runtime, xb):
                return tree_aggregate(_k, runtime, xb)
            def recover(supervisor):
                supervisor.rebuild_mesh()
        """,
        "driver.py": """
            from pkg.builder import make_step, recover
            def train(runtime, supervisor, xb, coef):
                step = make_step(runtime, xb)
                recover(supervisor)
                return step(xb, coef)
        """,
    })
    findings = [f for f in analyze_paths([pkg]) if f.rule == "JX017"]
    assert len(findings) == 1
    assert findings[0].path.endswith("driver.py")
    assert findings[0].function == "train"


def test_jx017_clear_then_rebuild_idiom_is_silent(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "builder.py": """
            import jax.numpy as jnp
            def _k(xb, coef):
                return jnp.sum(xb, axis=0)
            def make_step(runtime, xb):
                return tree_aggregate(_k, runtime, xb)
        """,
        "driver.py": """
            from pkg.builder import make_step
            def recover_and_resume(runtime, supervisor, xb, coef):
                clear_program_cache()
                supervisor.rebuild_mesh()
                step = make_step(runtime, xb)
                return step(xb, coef)
        """,
    })
    assert [f for f in analyze_paths([pkg]) if f.rule == "JX017"] == []


def test_jx016_cross_module_padded_mean(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "kernel.py": """
            import jax.numpy as jnp
            def column_means(x):
                return jnp.mean(x, axis=0)
        """,
        "caller.py": """
            import jax.numpy as jnp
            from pkg.kernel import column_means
            def bucketed(rows):
                padded = jnp.pad(rows, ((0, 8), (0, 0)))
                return column_means(padded)
        """,
    })
    findings = [f for f in analyze_paths([pkg]) if f.rule == "JX016"]
    assert len(findings) == 1
    assert findings[0].path.endswith("caller.py")
    assert "via pkg" not in findings[0].message or True
    assert findings[0].function == "bucketed"


def test_jx018_materializer_helper_two_hops(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "pull.py": """
            import numpy as np
            def to_host(v):
                return np.asarray(v)
        """,
        "fit.py": """
            import jax.numpy as jnp
            from pkg.pull import to_host
            def _k(xb, coef):
                return jnp.sum(xb, axis=0)
            def fit(runtime, xb, coef):
                step = tree_aggregate(_k, runtime, xb)
                n = xb.shape[0]
                preds = jnp.zeros((n,))
                return step(xb, coef), to_host(preds)
        """,
    })
    findings = [f for f in analyze_paths([pkg]) if f.rule == "JX018"]
    assert len(findings) == 1
    assert findings[0].path.endswith("fit.py")
    assert findings[0].function == "fit"


def test_jx018_predict_path_stays_silent(tmp_path):
    mod_src = """
        import jax.numpy as jnp
        import numpy as np
        def predict(model, x):
            n, d = x.shape
            preds = jnp.zeros((n,))
            return np.asarray(preds)
    """
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent(mod_src))
    assert [f for f in analyze_paths([str(p)]) if f.rule == "JX018"] == []


def test_jx019_registry_discovered_cross_module(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "conf.py": """
            WINDOW = ConfigBuilder("cyclone.serving.windowMs").int_conf(25)
        """,
        "user.py": """
            def read(conf):
                return conf.get("cyclone.serving.windwMs")
        """,
    })
    findings = [f for f in analyze_paths([pkg]) if f.rule == "JX019"]
    assert len(findings) == 1
    assert findings[0].path.endswith("user.py")
    assert "cyclone.serving.windowMs" in findings[0].message   # suggestion


def test_jx016_negative_axis_helper_mean_is_not_all_dims(tmp_path):
    """ALL_AXES must never alias a literal axis=-1: a helper's LAST-dim
    mean over a row-padded buffer never touches the pad rows' count."""
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent("""
        import jax.numpy as jnp
        import numpy as np
        def rowmean(z):
            return jnp.mean(z, axis=-1)
        def bucketed(rows):
            k, d = rows.shape
            buf = np.zeros((64, 8))
            buf[:k] = rows
            return rowmean(buf)[:k]
    """))
    assert [f for f in analyze_paths([str(p)]) if f.rule == "JX016"] == []


def test_jx017_exclusive_branches(tmp_path):
    """A rebuild in the then-arm must not convict a dispatch in the
    else-arm (the `if dead: recover() else: dispatch` supervisor shape);
    a fall-through rebuild before a later dispatch still does."""
    exclusive = """
        import jax.numpy as jnp
        def _k(xb, coef):
            return jnp.sum(xb, axis=0)
        def supervise(runtime, supervisor, xb, coef, dead):
            step = tree_aggregate(_k, runtime, xb)
            if dead:
                supervisor.rebuild_mesh()
                return None
            return step(xb, coef)
    """
    p = tmp_path / "ok.py"
    p.write_text(textwrap.dedent(exclusive))
    assert [f for f in analyze_paths([str(p)]) if f.rule == "JX017"] == []

    fall_through = """
        import jax.numpy as jnp
        def _k(xb, coef):
            return jnp.sum(xb, axis=0)
        def supervise(runtime, supervisor, xb, coef, dead):
            step = tree_aggregate(_k, runtime, xb)
            if dead:
                supervisor.rebuild_mesh()
            return step(xb, coef)
    """
    q = tmp_path / "bad.py"
    q.write_text(textwrap.dedent(fall_through))
    hits = [f for f in analyze_paths([str(q)]) if f.rule == "JX017"]
    assert len(hits) == 1 and hits[0].function == "supervise"


# -- engine plumbing ----------------------------------------------------------

def test_shape_rules_share_one_dataflow_fixpoint(tmp_path, monkeypatch):
    """The four shape rules declare analysis_id JXSHAPE; the engine
    dedupes clients, so the fixpoint cost is paid once however many of
    them run."""
    from cycloneml_tpu.analysis.rules import (CrossMeshReuseRule,
                                              HostMaterializeRule,
                                              ShapePaddingRule,
                                              ShardingSpecRule)
    ids = {cls().analysis_id for cls in (ShardingSpecRule, ShapePaddingRule,
                                         CrossMeshReuseRule,
                                         HostMaterializeRule)}
    assert ids == {shapes.ANALYSIS_ID}

    calls = []
    real = shapes.compute_summary
    monkeypatch.setattr(shapes, "compute_summary",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))
    p = tmp_path / "m.py"
    p.write_text("import jax.numpy as jnp\n"
                 "def f(x):\n    return jnp.sum(x, axis=0)\n")
    analyze_paths([str(p)])
    with_all = len(calls)
    calls.clear()
    analyze_paths([str(p)], rules=[ShardingSpecRule()])
    with_one = len(calls)
    assert with_all == with_one


def test_analyze_paths_fills_timings(tmp_path):
    p = tmp_path / "m.py"
    p.write_text("x = 1\n")
    timings = {}
    analyze_paths([str(p)], timings=timings)
    assert shapes.ANALYSIS_ID in timings
    assert all(v >= 0 for v in timings.values())
    from cycloneml_tpu.analysis.rules import ALL_RULES
    for cls in ALL_RULES:
        assert cls.rule_id in timings


def test_top_summary_degrades_safely():
    """The hard-widening backstop: propagation facts go True (the
    fixpoint terminates), finding-triggering facts go silent."""
    s = shapes.TOP_SUMMARY
    assert s.returns_program and s.rebuilds and s.reaches_aggregate
    assert s.unmasked_mean_params == frozenset()
    assert s.materializes_params == frozenset()
    assert s.ret_psummed == (frozenset(),)
    # a missing/TOP entry reads as EMPTY at check sites
    assert summary_of({"x": TOP}, "x") == shapes.EMPTY_SUMMARY
