"""pandas-API depth (round-3 verdict item 8): indexes, loc/iloc, aligned
Series arithmetic, rolling/expanding, .str/.dt accessors, concat and
pivot_table — each checked against REAL pandas on mixed-dtype frames."""

import numpy as np
import pandas as pd
import pytest

import cycloneml_tpu.pandas as cp
from cycloneml_tpu.pandas import CycloneFrame, concat, pivot_table


@pytest.fixture()
def mixed():
    data = {"k": ["b", "a", "c", "a", "b"],
            "x": [1.0, 2.0, np.nan, 4.0, 5.0],
            "n": [10, 20, 30, 40, 50],
            "s": [" Ab", "cD ", "ef", "GH", "ij"]}
    return CycloneFrame(dict(data)), pd.DataFrame(data)


def test_set_index_reset_index_loc(mixed):
    cf, pdf = mixed
    ci = cf.set_index("k")
    pi = pdf.set_index("k")
    assert ci.columns == list(pi.columns)
    np.testing.assert_array_equal(ci.index, pi.index.to_numpy())
    # scalar label with a unique hit -> row mapping
    row = ci.loc["c"]
    assert row["n"] == 30 and np.isnan(row["x"])
    # label list
    sub = ci.loc[["b", "c"]]
    psub = pi.loc[["b", "c"]]
    np.testing.assert_array_equal(sub["n"].values, psub["n"].to_numpy())
    # label slice is inclusive on both ends (unique index — pandas rejects
    # label slices on non-unique unsorted indexes)
    cu = CycloneFrame({"k": ["p", "q", "r", "s"],
                       "n": [1, 2, 3, 4]}).set_index("k")
    pu = pd.DataFrame({"k": ["p", "q", "r", "s"],
                       "n": [1, 2, 3, 4]}).set_index("k")
    np.testing.assert_array_equal(cu.loc["q":"s"]["n"].values,
                                  pu.loc["q":"s"]["n"].to_numpy())
    # duplicate label returns all matching rows
    dup = ci.loc["a"]
    np.testing.assert_array_equal(dup["n"].values,
                                  pi.loc["a"]["n"].to_numpy())
    # reset_index restores the column
    back = ci.reset_index()
    assert back.columns[0] == "k"
    np.testing.assert_array_equal(back["k"].values, pdf["k"].to_numpy())


def test_iloc(mixed):
    cf, pdf = mixed
    assert cf.iloc[2]["n"] == pdf.iloc[2]["n"]
    np.testing.assert_array_equal(cf.iloc[1:4]["n"].values,
                                  pdf.iloc[1:4]["n"].to_numpy())
    np.testing.assert_array_equal(cf.iloc[[4, 0]]["n"].values,
                                  pdf.iloc[[4, 0]]["n"].to_numpy())
    assert cf.iloc[-1]["n"] == pdf.iloc[-1]["n"]


def test_series_alignment_by_index():
    a = CycloneFrame({"k": ["x", "y", "z"], "v": [1.0, 2.0, 3.0]}
                     ).set_index("k")["v"]
    b = CycloneFrame({"k": ["y", "z", "w"], "v": [10.0, 20.0, 30.0]}
                     ).set_index("k")["v"]
    pa = pd.Series([1.0, 2.0, 3.0], index=["x", "y", "z"])
    pb = pd.Series([10.0, 20.0, 30.0], index=["y", "z", "w"])
    got = a + b
    want = pa + pb
    np.testing.assert_array_equal(got.index, want.index.to_numpy())
    np.testing.assert_allclose(got.values, want.to_numpy())


def test_rolling_expanding(mixed):
    cf, pdf = mixed
    np.testing.assert_allclose(
        cf["x"].rolling(2).sum().values,
        pdf["x"].rolling(2).sum().to_numpy())
    np.testing.assert_allclose(
        cf["n"].rolling(3, min_periods=1).mean().values,
        pdf["n"].rolling(3, min_periods=1).mean().to_numpy())
    np.testing.assert_allclose(
        cf["n"].rolling(3).std().values,
        pdf["n"].rolling(3).std().to_numpy())
    np.testing.assert_allclose(
        cf["n"].expanding().sum().values,
        pdf["n"].expanding().sum().to_numpy())
    # frame-wise rolling covers numeric columns
    fr = cf.rolling(2).max()
    pr = pdf[["x", "n"]].rolling(2).max()
    np.testing.assert_allclose(fr["n"].values, pr["n"].to_numpy())


def test_str_accessor(mixed):
    cf, pdf = mixed
    for op in ("lower", "upper", "strip"):
        np.testing.assert_array_equal(
            getattr(cf["s"].str, op)().values,
            getattr(pdf["s"].str, op)().to_numpy())
    np.testing.assert_array_equal(cf["s"].str.len().values,
                                  pdf["s"].str.len().to_numpy())
    np.testing.assert_array_equal(
        cf["s"].str.contains("[ce]").values,
        pdf["s"].str.contains("[ce]").to_numpy())
    np.testing.assert_array_equal(
        cf["s"].str.startswith(" ").values,
        pdf["s"].str.startswith(" ").to_numpy())
    np.testing.assert_array_equal(
        cf["s"].str.replace("[A-Z]", "_", regex=True).values,
        pdf["s"].str.replace("[A-Z]", "_", regex=True).to_numpy())
    np.testing.assert_array_equal(cf["s"].str.slice(0, 2).values,
                                  pdf["s"].str.slice(0, 2).to_numpy())


def test_dt_accessor():
    ts = ["2024-02-29T13:45:06", "2023-12-31T23:59:59", "2026-07-01T00:00:00"]
    cf = CycloneFrame({"t": np.array(ts, dtype="datetime64[s]")})
    ps = pd.Series(pd.to_datetime(ts))
    for comp in ("year", "month", "day", "hour", "minute", "second",
                 "dayofweek"):
        np.testing.assert_array_equal(
            getattr(cf["t"].dt, comp).values,
            getattr(ps.dt, comp).to_numpy(), err_msg=comp)


def test_concat_rows_and_columns():
    a = CycloneFrame({"x": [1, 2], "y": ["p", "q"]})
    b = CycloneFrame({"x": [3], "z": [9.5]})
    got = concat([a, b])
    want = pd.concat([pd.DataFrame({"x": [1, 2], "y": ["p", "q"]}),
                      pd.DataFrame({"x": [3], "z": [9.5]})])
    assert got.columns == list(want.columns)
    assert [int(v) for v in got["x"].values] == [1, 2, 3]
    # missing columns fill with None, matching pandas' NaN there
    assert got["y"].values[2] is None
    assert bool(want["y"].isna().iloc[2])
    side = concat([a, CycloneFrame({"w": [7, 8]})], axis=1)
    assert side.columns == ["x", "y", "w"]


def test_pivot_table(mixed):
    cf, pdf = mixed
    got = pivot_table(cf, values="n", index="k", columns="s",
                      aggfunc="sum").reset_index()
    want = pd.pivot_table(pdf, values="n", index="k", columns="s",
                          aggfunc="sum")
    for col in want.columns:
        w = want[col].to_numpy(dtype=float)
        g = got[str(col)].values[np.argsort(got["k"].values)]
        np.testing.assert_allclose(
            g, w[np.argsort(want.index.to_numpy())], equal_nan=True)


def test_row_ops_carry_index(mixed):
    cf, _ = mixed
    ci = cf.set_index("k")
    top = ci.sort_values("n", ascending=False).head(2)
    np.testing.assert_array_equal(top.index, np.array(["b", "a"], object))
    masked = ci[ci["n"] > 25]
    np.testing.assert_array_equal(masked.index,
                                  np.array(["c", "a", "b"], object))
    si = ci.sort_index()
    assert si.index.tolist() == ["a", "a", "b", "b", "c"]
    pdf_round = ci.to_pandas()
    assert pdf_round.index.name == "k"


def test_loc_tuple_and_negative_head_tail(mixed):
    """Review r3 regressions: loc[label, cols] on a unique label, and
    pandas' negative-n head/tail semantics."""
    cf, pdf = mixed
    ci = cf.set_index("k")
    got = ci.loc["c", ["n", "x"]]
    assert got["n"] == 30 and np.isnan(got["x"])
    assert ci.loc["c", "n"] == 30
    np.testing.assert_array_equal(cf.head(-1)["n"].values,
                                  pdf.head(-1)["n"].to_numpy())
    np.testing.assert_array_equal(cf.tail(-2)["n"].values,
                                  pdf.tail(-2)["n"].to_numpy())


def test_pivot_table_name_collision_and_count():
    f = CycloneFrame({"k": ["a", "a", "b"], "c": ["k", "z", "k"],
                      "v": [1.0, 2.0, 3.0]})
    pf = pd.DataFrame({"k": ["a", "a", "b"], "c": ["k", "z", "k"],
                       "v": [1.0, 2.0, 3.0]})
    got = pivot_table(f, values="v", index="k", columns="c", aggfunc="sum")
    want = pd.pivot_table(pf, values="v", index="k", columns="c",
                          aggfunc="sum")
    # a pivot column literally named "k" must not clobber the row labels
    np.testing.assert_array_equal(got.index, want.index.to_numpy())
    np.testing.assert_allclose(got["k"].values, want["k"].to_numpy(),
                               equal_nan=True)
    cnt = pivot_table(f, values="v", index="k", columns="c",
                      aggfunc="count")
    wc = pd.pivot_table(pf, values="v", index="k", columns="c",
                        aggfunc="count")
    np.testing.assert_allclose(
        cnt["z"].values, wc["z"].to_numpy(dtype=float), equal_nan=True)


def test_pivot_table_nan_values_skipped():
    f = CycloneFrame({"k": ["a", "a", "b"], "c": ["u", "u", "u"],
                      "v": [1.0, np.nan, 3.0]})
    pf = pd.DataFrame({"k": ["a", "a", "b"], "c": ["u", "u", "u"],
                       "v": [1.0, np.nan, 3.0]})
    for agg in ("sum", "mean", "count"):
        got = pivot_table(f, values="v", index="k", columns="c", aggfunc=agg)
        want = pd.pivot_table(pf, values="v", index="k", columns="c",
                              aggfunc=agg)
        np.testing.assert_allclose(got["u"].values,
                                   want["u"].to_numpy(dtype=float),
                                   equal_nan=True, err_msg=agg)


def test_loc_label_slice_missing_and_nonunique():
    """Missing boundary labels raise KeyError (not IndexError) and label
    slices over a non-unique unsorted index are rejected, matching pandas
    (advisor r3)."""
    cf = CycloneFrame({"k": ["b", "a", "c", "a"],
                       "n": [1, 2, 3, 4]}).set_index("k")
    pdf = pd.DataFrame({"k": ["b", "a", "c", "a"],
                        "n": [1, 2, 3, 4]}).set_index("k")
    with pytest.raises(KeyError):
        cf.loc["zz":"c"]
    with pytest.raises(KeyError):
        cf.loc["b":"zz"]
    # pandas: "Cannot get left slice bound for non-unique label"
    with pytest.raises(KeyError):
        pdf.loc["a":"c"]
    with pytest.raises(KeyError):
        cf.loc["a":"c"]
    # a sorted non-unique index still slices fine in both
    cs = CycloneFrame({"k": ["a", "a", "b", "c"],
                       "n": [1, 2, 3, 4]}).set_index("k")
    ps = pd.DataFrame({"k": ["a", "a", "b", "c"],
                       "n": [1, 2, 3, 4]}).set_index("k")
    np.testing.assert_array_equal(cs.loc["a":"b"]["n"].values,
                                  ps.loc["a":"b"]["n"].to_numpy())
    # on a MONOTONIC index a missing bound slices to its insertion point
    # (searchsorted), matching pandas — no KeyError
    cm = CycloneFrame({"k": ["a", "b", "d"], "n": [1, 2, 3]}).set_index("k")
    pm = pd.DataFrame({"k": ["a", "b", "d"], "n": [1, 2, 3]}).set_index("k")
    for sl in [slice("a", "c"), slice("c", "d"), slice("c", "cc"),
               slice(None, "c"), slice("c", None)]:
        np.testing.assert_array_equal(cm.loc[sl]["n"].values,
                                      pm.loc[sl]["n"].to_numpy())
    # decreasing index slices too
    cd = CycloneFrame({"k": ["d", "b", "a"], "n": [3, 2, 1]}).set_index("k")
    pdd = pd.DataFrame({"k": ["d", "b", "a"], "n": [3, 2, 1]}).set_index("k")
    np.testing.assert_array_equal(cd.loc["c":"a"]["n"].values,
                                  pdd.loc["c":"a"]["n"].to_numpy())


def test_str_accessor_with_nulls():
    """len()/contains()/startswith()/endswith() over columns containing
    None propagate NaN instead of raising on the int64/bool cast
    (advisor r3; pandas object-dtype null semantics)."""
    vals = ["abc", None, "bd"]
    cs = CycloneFrame({"s": vals})["s"]
    # object dtype is the oracle: pandas 3.0's default str dtype fills
    # nulls with False for boolean ops, but our columns are object-backed
    ps = pd.Series(vals, dtype=object)
    got = cs.str.len()
    exp = ps.str.len()
    assert got.values[0] == 3 and got.values[2] == 2
    assert np.isnan(got.values[1]) and np.isnan(exp.iloc[1])
    for meth, arg in [("contains", "b"), ("startswith", "a"),
                      ("endswith", "d")]:
        g = getattr(cs.str, meth)(arg).values
        e = getattr(ps.str, meth)(arg)
        assert list(g[[0, 2]]) == list(e.iloc[[0, 2]])
        assert g[1] is np.nan or (isinstance(g[1], float) and np.isnan(g[1]))
        assert e.iloc[1] is None or (isinstance(e.iloc[1], float)
                                     and np.isnan(e.iloc[1]))


def test_boolean_mask_with_nulls_raises():
    """Masking with a null-carrying boolean result raises like pandas
    instead of truthy-NaN selecting every null row (review r4)."""
    cf = CycloneFrame({"s": ["abc", None, "bd"], "n": [1, 2, 3]})
    with pytest.raises(ValueError, match="NaN"):
        cf[cf["s"].str.contains("b")]
    pdf = pd.DataFrame({"s": pd.Series(["abc", None, "bd"], dtype=object),
                        "n": [1, 2, 3]})
    with pytest.raises(ValueError):
        pdf[pdf["s"].str.contains("b")]
    # a clean boolean mask still selects
    np.testing.assert_array_equal(
        cf[cf["n"] > 1]["n"].values, pdf[pdf["n"] > 1]["n"].to_numpy())


def test_boolean_mask_float_nan_raises():
    """A float mask carrying NaN must raise too (NaN casts to True) —
    review r4 follow-up to the object-mask guard."""
    cf = CycloneFrame({"n": [1, 2, 3]})
    from cycloneml_tpu.pandas.frame import CycloneSeries
    bad = CycloneSeries(np.array([1.0, np.nan, 0.0]), "m")
    with pytest.raises(ValueError, match="NaN"):
        cf[bad]


def test_multiindex_set_reset_loc_unstack():
    """MultiIndex depth (round-3 verdict item 10): set_index([a,b]),
    tuple-label loc, reset_index round-trip, Series.unstack — against
    real pandas."""
    data = {"a": ["x", "x", "y", "y"], "b": [1, 2, 1, 2],
            "v": [10.0, 20.0, 30.0, 40.0]}
    cf = CycloneFrame(dict(data)).set_index(["a", "b"])
    pdf = pd.DataFrame(data).set_index(["a", "b"])
    # index is tuples, names match
    assert list(cf.index) == list(pdf.index)
    # tuple-label loc
    row = cf.loc[("y", 1)]
    assert row["v"] == pdf.loc[("y", 1)]["v"]
    # reset_index restores both columns with narrowed dtypes
    back = cf.reset_index()
    pback = pdf.reset_index()
    assert back.columns == list(pback.columns)
    np.testing.assert_array_equal(back["b"].values, pback["b"].to_numpy())
    # to_pandas produces a real MultiIndex
    assert isinstance(cf.to_pandas().index, pd.MultiIndex)
    # unstack: last level -> columns
    got = cf["v"].unstack()
    want = pdf["v"].unstack()
    assert list(got.index) == list(want.index)
    assert [c for c in got.columns] == list(want.columns)
    np.testing.assert_allclose(
        np.column_stack([got[c].values for c in got.columns]),
        want.to_numpy())
    # missing pairs become NaN
    cf2 = CycloneFrame({"a": ["x", "y"], "b": [1, 2], "v": [1.0, 2.0]}
                       ).set_index(["a", "b"])["v"].unstack()
    pdf2 = pd.DataFrame({"a": ["x", "y"], "b": [1, 2], "v": [1.0, 2.0]}
                        ).set_index(["a", "b"])["v"].unstack()
    np.testing.assert_allclose(
        np.column_stack([cf2[c].values for c in cf2.columns]),
        pdf2.to_numpy(), equal_nan=True)
    # review r4: unstack keeps the remaining level name (reset_index
    # restores the right column), duplicates raise like pandas,
    # loc[(tuple), col] reads a cell, and tuple-label lists select rows
    assert got._index_name == "a"
    assert "a" in got.reset_index().columns
    dup = CycloneFrame({"a": ["x", "x"], "b": [1, 1], "v": [1.0, 2.0]}
                       ).set_index(["a", "b"])["v"]
    with pytest.raises(ValueError, match="duplicate"):
        dup.unstack()
    assert cf.loc[("y", 1), "v"] == pdf.loc[("y", 1), "v"]
    sub = cf.loc[[("x", 1), ("y", 2)]]
    psub = pdf.loc[[("x", 1), ("y", 2)]]
    np.testing.assert_allclose(sub["v"].values, psub["v"].to_numpy())


def test_groupby_apply_scalar_and_series():
    data = {"k": ["b", "a", "b", "a", "a"], "v": [1.0, 2.0, 3.0, 4.0, 6.0],
            "w": [10, 20, 30, 40, 50]}
    cf = CycloneFrame(dict(data))
    pdf = pd.DataFrame(data)
    # scalar return -> Series indexed by group key, sorted key order
    got = cf.groupby("k").apply(lambda g: float(g["v"].max() - g["v"].min()))
    want = pdf.groupby("k").apply(
        lambda g: float(g["v"].max() - g["v"].min()))
    assert list(got.index) == list(want.index)
    np.testing.assert_allclose(got.values, want.to_numpy())
    # Series return -> one row per group
    from cycloneml_tpu.pandas.frame import CycloneSeries
    got2 = cf.groupby("k").apply(lambda g: CycloneSeries(
        np.array([g["v"].sum(), float(len(g))]), None,
        index=np.array(["total", "n"], object)))
    want2 = pdf.groupby("k").apply(lambda g: pd.Series(
        {"total": g["v"].sum(), "n": float(len(g))}))
    assert list(got2.index) == list(want2.index)
    np.testing.assert_allclose(got2["total"].values,
                               want2["total"].to_numpy())
    np.testing.assert_allclose(got2["n"].values, want2["n"].to_numpy())


def test_merge_validate_and_indicator():
    left = {"k": ["a", "b", "c"], "x": [1, 2, 3]}
    right = {"k": ["a", "a", "d"], "y": [10.0, 11.0, 12.0]}
    cl, cr = CycloneFrame(dict(left)), CycloneFrame(dict(right))
    pl, pr = pd.DataFrame(left), pd.DataFrame(right)
    # validate failures match pandas (MergeError is a ValueError subclass)
    with pytest.raises(ValueError, match="right dataset"):
        cl.merge(cr, on="k", validate="one_to_one")
    with pytest.raises(ValueError):
        pl.merge(pr, on="k", validate="one_to_one")
    # 1:m passes on unique-left
    cl.merge(cr, on="k", how="inner", validate="one_to_many")
    # indicator column matches pandas on an outer join
    got = cl.merge(cr, on="k", how="outer", indicator=True)
    want = pl.merge(pr, on="k", how="outer", indicator=True)
    gs = sorted(zip(got["k"].values, got["_merge"].values))
    ws = sorted(zip(want["k"], want["_merge"].astype(str)))
    assert gs == ws


def test_pivot_table_margins():
    data = {"k": ["a", "a", "b"], "c": ["p", "q", "p"],
            "v": [1.0, 2.0, 5.0]}
    cf = CycloneFrame(dict(data))
    pdf = pd.DataFrame(data)
    for fn in ("sum", "mean", "count"):
        got = pivot_table(cf, values="v", index="k", columns="c",
                          aggfunc=fn, margins=True)
        want = pd.pivot_table(pdf, values="v", index="k", columns="c",
                              aggfunc=fn, margins=True)
        assert list(got.index) == list(want.index)
        for c in want.columns:
            np.testing.assert_allclose(
                got[str(c)].values, want[c].to_numpy(dtype=float),
                equal_nan=True, err_msg=f"{fn}/{c}")


# -- r5 tranche: datetime index + resample, merge-on-index, astype,
#    iteration protocols — each parity-tested against REAL pandas
#    (r4 verdict item 8; ref python/pyspark/pandas/frame.py,
#    data_type_ops/, indexes/datetimes.py)

class TestDateRangeParity:
    @pytest.mark.parametrize("kw", [
        dict(start="2024-01-01", periods=5, freq="D"),
        dict(start="2024-01-01", end="2024-01-10", freq="D"),
        dict(start="2024-01-01", periods=8, freq="h"),
        dict(start="2024-01-01", periods=6, freq="15min"),
        dict(start="2024-01-01", end="2024-06-30", freq="ME"),
        dict(start="2024-01-03", periods=4, freq="W"),
        dict(start="2024-02-27", periods=3, freq="2D"),
    ], ids=lambda kw: kw.get("freq"))
    def test_matches_pandas(self, kw):
        ours = cp.date_range(**kw)
        theirs = pd.date_range(**kw).values
        np.testing.assert_array_equal(ours, theirs)


class TestResampleParity:
    def _pair(self):
        ts = pd.date_range("2024-03-01", periods=50, freq="7h")
        rng = np.random.RandomState(0)
        vals = rng.randn(50)
        qty = rng.randint(0, 10, 50).astype(np.float64)
        pdf = pd.DataFrame({"v": vals, "q": qty}, index=ts)
        ours = cp.CycloneFrame({"t": ts.values, "v": vals, "q": qty}
                               ).set_index("t")
        return pdf, ours

    @pytest.mark.parametrize("fn", ["sum", "mean", "count", "min", "max"])
    def test_daily(self, fn):
        pdf, ours = self._pair()
        exp = getattr(pdf.resample("D"), fn)()
        got = getattr(ours.resample("D"), fn)()
        np.testing.assert_array_equal(got.index, exp.index.values)
        for c in ("v", "q"):
            np.testing.assert_allclose(got[c].to_numpy(),
                                       exp[c].to_numpy(), equal_nan=True)

    def test_monthly_and_on_column(self):
        ts = pd.date_range("2024-01-15", periods=10, freq="11D")
        vals = np.arange(10.0)
        pdf = pd.DataFrame({"t": ts, "v": vals})
        exp = pdf.resample("ME", on="t").sum()
        ours = cp.CycloneFrame({"t": ts.values, "v": vals})
        got = ours.resample("ME", on="t").sum()
        np.testing.assert_array_equal(got.index, exp.index.values)
        np.testing.assert_allclose(got["v"].to_numpy(),
                                   exp["v"].to_numpy())

    def test_empty_bins_materialize(self):
        # a 3-day gap: pandas emits the empty day with sum 0 / mean NaN
        ts = pd.to_datetime(["2024-01-01", "2024-01-01", "2024-01-04"])
        vals = np.array([1.0, 2.0, 4.0])
        pdf = pd.DataFrame({"v": vals}, index=ts)
        ours = cp.CycloneFrame({"t": ts.values, "v": vals}).set_index("t")
        for fn in ("sum", "mean"):
            exp = getattr(pdf.resample("D"), fn)()
            got = getattr(ours.resample("D"), fn)()
            assert len(got) == 4
            np.testing.assert_allclose(got["v"].to_numpy(),
                                       exp["v"].to_numpy(), equal_nan=True)


class TestMergeOnIndex:
    def _frames(self):
        left = {"k": np.array(["a", "b", "c", "d"], object),
                "lv": np.arange(4.0)}
        right = {"rv": np.array([10.0, 20.0, 30.0])}
        ridx = np.array(["b", "c", "z"], object)
        pl = pd.DataFrame(left)
        pr = pd.DataFrame(right, index=ridx)
        cl = cp.CycloneFrame(left)
        cr = cp.CycloneFrame({"idx": ridx, **right}).set_index("idx")
        return pl, pr, cl, cr

    @pytest.mark.parametrize("how", ["inner", "left"])
    def test_left_on_right_index(self, how):
        pl, pr, cl, cr = self._frames()
        exp = pl.merge(pr, left_on="k", right_index=True, how=how)
        got = cl.merge(cr, left_on="k", right_index=True, how=how)
        assert sorted(got.columns) == sorted(exp.columns)
        ge = got.sort_values("lv")
        pe = exp.sort_values("lv")
        np.testing.assert_array_equal(ge["k"].to_numpy(),
                                      pe["k"].to_numpy())
        np.testing.assert_allclose(ge["rv"].to_numpy(),
                                   pe["rv"].to_numpy(), equal_nan=True)

    def test_both_indexes(self):
        lidx = np.array(["a", "b", "c"], object)
        l = pd.DataFrame({"lv": [1.0, 2.0, 3.0]}, index=lidx)
        r = pd.DataFrame({"rv": [5.0, 6.0]},
                         index=np.array(["b", "c"], object))
        exp = l.merge(r, left_index=True, right_index=True)
        cl = cp.CycloneFrame({"i": lidx, "lv": np.array([1.0, 2.0, 3.0])}
                             ).set_index("i")
        crr = cp.CycloneFrame({"i": np.array(["b", "c"], object),
                               "rv": np.array([5.0, 6.0])}).set_index("i")
        got = cl.merge(crr, left_index=True, right_index=True)
        ge = got.sort_index()
        pe = exp.sort_index()
        np.testing.assert_array_equal(ge.index, pe.index.values)
        np.testing.assert_allclose(ge["lv"].to_numpy(), pe["lv"].to_numpy())
        np.testing.assert_allclose(ge["rv"].to_numpy(), pe["rv"].to_numpy())


class TestAstypeParity:
    def test_float_to_int_and_back(self):
        data = {"a": np.array([1.0, 2.0, 3.0]),
                "b": np.array([1, 2, 3], dtype=np.int64)}
        exp = pd.DataFrame(data).astype({"a": "int64", "b": "float64"})
        got = cp.CycloneFrame(data).astype({"a": "int64", "b": "float64"})
        assert got["a"].to_numpy().dtype == exp["a"].to_numpy().dtype
        assert got["b"].to_numpy().dtype == exp["b"].to_numpy().dtype
        np.testing.assert_array_equal(got["a"].to_numpy(),
                                      exp["a"].to_numpy())

    def test_nan_to_int_raises_like_pandas(self):
        data = {"a": np.array([1.0, np.nan])}
        with pytest.raises(ValueError, match="non-finite"):
            pd.DataFrame(data).astype("int64")
        with pytest.raises(ValueError, match="non-finite"):
            cp.CycloneFrame(data).astype("int64")

    def test_object_strings_parse(self):
        data = {"a": np.array(["1", "2", "3"], object)}
        exp = pd.DataFrame(data).astype("int64")
        got = cp.CycloneFrame(data).astype("int64")
        np.testing.assert_array_equal(got["a"].to_numpy(),
                                      exp["a"].to_numpy())

    def test_astype_str_preserves_nan(self):
        # pandas >= 2: str cast stringifies values but NaN SURVIVES
        data = {"a": np.array([1.5, np.nan])}
        exp = pd.DataFrame(data).astype(str)["a"].to_numpy()
        got = cp.CycloneFrame(data).astype(str)["a"].to_numpy()
        assert got[0] == exp[0] == "1.5"
        assert isinstance(got[1], float) and np.isnan(got[1])
        assert isinstance(exp[1], float) and np.isnan(exp[1])


class TestIterationParity:
    def _data(self):
        return {"x": np.array([1, 2, 3], dtype=np.int64),
                "y": np.array(["a", "b", "c"], object)}

    def test_iterrows(self):
        data = self._data()
        exp = [(i, row.to_dict()) for i, row in
               pd.DataFrame(data).iterrows()]
        got = [(i, dict(zip(["x", "y"], row.values))) for i, row in
               cp.CycloneFrame(data).iterrows()]
        assert got == exp

    def test_itertuples(self):
        data = self._data()
        exp = [tuple(t) for t in pd.DataFrame(data).itertuples()]
        got = [tuple(t) for t in cp.CycloneFrame(data).itertuples()]
        assert got == exp
        # field access + index=False variant
        t0 = next(iter(cp.CycloneFrame(data).itertuples()))
        assert t0.Index == 0 and t0.x == 1 and t0.y == "a"
        exp2 = [tuple(t) for t in
                pd.DataFrame(data).itertuples(index=False)]
        got2 = [tuple(t) for t in
                cp.CycloneFrame(data).itertuples(index=False)]
        assert got2 == exp2


class TestR5ReviewRegressions:
    def test_resample_multiplier_anchors_start_of_day(self):
        ts = pd.to_datetime(["2024-01-01 00:07", "2024-01-01 00:20"])
        vals = np.array([1.0, 2.0])
        exp = pd.DataFrame({"v": vals}, index=ts).resample("15min").sum()
        got = cp.CycloneFrame({"t": ts.values, "v": vals}
                              ).set_index("t").resample("15min").sum()
        np.testing.assert_array_equal(got.index, exp.index.values)
        np.testing.assert_allclose(got["v"].to_numpy(),
                                   exp["v"].to_numpy())

    def test_resample_skips_nan(self):
        ts = pd.to_datetime(["2024-01-01", "2024-01-01", "2024-01-02"])
        vals = np.array([1.0, np.nan, 5.0])
        pdf = pd.DataFrame({"v": vals}, index=ts)
        ours = cp.CycloneFrame({"t": ts.values, "v": vals}).set_index("t")
        for fn in ("sum", "mean", "count"):
            exp = getattr(pdf.resample("D"), fn)()["v"].to_numpy()
            got = getattr(ours.resample("D"), fn)()["v"].to_numpy()
            np.testing.assert_allclose(got.astype(np.float64),
                                       exp.astype(np.float64),
                                       equal_nan=True)

    def test_date_range_end_periods(self):
        for freq in ("D", "h", "ME"):
            exp = pd.date_range(end="2024-03-10", periods=4,
                                freq=freq).values
            got = cp.date_range(end="2024-03-10", periods=4, freq=freq)
            np.testing.assert_array_equal(got, exp)
        with pytest.raises(ValueError):
            cp.date_range(periods=4)

    def test_mixed_merge_keeps_column_side_index(self):
        left = {"k": np.array(["a", "b", "c"], object),
                "lv": np.arange(3.0)}
        pl = pd.DataFrame(left, index=np.array([10, 11, 12]))
        right = {"rv": np.array([1.0, 2.0])}
        ridx = np.array(["b", "c"], object)
        pr = pd.DataFrame(right, index=ridx)
        exp = pl.merge(pr, left_on="k", right_index=True, how="inner")
        cl0 = cp.CycloneFrame({"i": np.array([10, 11, 12]), **left}
                              ).set_index("i")
        cr = cp.CycloneFrame({"i": ridx, **right}).set_index("i")
        got = cl0.merge(cr, left_on="k", right_index=True, how="inner")
        ge, pe = got.sort_index(), exp.sort_index()
        np.testing.assert_array_equal(ge.index, pe.index.values)
        np.testing.assert_array_equal(ge["k"].to_numpy(),
                                      pe["k"].to_numpy())
