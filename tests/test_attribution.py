"""Attribution-plane tests: scope stack, usage ledger, noop discipline,
UsageReport journal replay, cross-host snapshot merge.

The accounting contract (docs/observability.md "Attribution &
accounting"): every charge lands on the scope row AND the totals row
under one lock, so per-scope sums always match the global ledger;
disabled hot paths pay one module-global read; cumulative UsageReport
events make the rollup journal-replayable.
"""

import threading

import pytest

from cycloneml_tpu.observe import attribution
from cycloneml_tpu.observe.attribution import (EVICTED, NOOP_WINDOW, TOTALS,
                                               UNSCOPED, Scope, UsageLedger,
                                               UsageReporter, merge_snapshots,
                                               usage_delta)

ADDITIVE = ("deviceSeconds", "flops", "bytesAccessed", "h2dBytes",
            "dispatches", "requests", "servingSeconds", "sheds")


@pytest.fixture(autouse=True)
def _clean_global_ledger():
    """Module-global hygiene: no test leaks an installed ledger (or an
    abandoned scope) into the next."""
    attribution.disable()
    assert attribution.current_scope() is None
    yield
    attribution.disable()
    assert attribution.current_scope() is None


def _sum_matches_totals(snap, fields=ADDITIVE, tol=0.01):
    totals = snap[TOTALS]
    for fld in fields:
        want = totals.get(fld, 0)
        got = sum(row.get(fld, 0) for key, row in snap.items()
                  if key != TOTALS)
        if want and abs(got - want) / want > tol:
            return False
    return True


# -- scope stack -----------------------------------------------------------------

def test_scope_nesting_innermost_wins_and_keys_namespace_tenants():
    assert attribution.current_scope() is None
    with attribution.scope("j1", tenant="acme") as outer:
        assert attribution.current_scope() is outer
        assert outer.key == "acme/j1"
        with attribution.scope("j1", tenant="beta") as inner:
            # same job name, different tenant: distinct ledger rows
            assert inner.key == "beta/j1"
            assert attribution.current_scope() is inner
        assert attribution.current_scope() is outer
    assert attribution.current_scope() is None
    assert Scope("solo").key == "solo"  # tenantless keys stay bare


def test_adopt_reenters_a_captured_scope_on_another_thread():
    """The cross-thread leg: capture where work is SUBMITTED, adopt where
    it RUNS (the ShardStream/batcher idiom)."""
    with attribution.scope("xthread", tenant="t") as sc:
        captured = attribution.current_scope()
    seen = []

    def worker():
        assert attribution.current_scope() is None  # fresh thread-local
        with attribution.adopt(captured):
            seen.append(attribution.current_scope())
        with attribution.adopt(None):  # None adopts nothing
            seen.append(attribution.current_scope())

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen == [sc, None]


# -- the disabled / unscoped hot path --------------------------------------------

def test_disabled_hot_path_is_one_shared_noop_window():
    assert attribution.active() is None
    # off: the shared singleton, even under a scope — no allocation
    assert attribution.dispatch_window() is NOOP_WINDOW
    with attribution.scope("ignored"):
        assert attribution.dispatch_window() is NOOP_WINDOW
    # charges fall on the floor without a ledger
    attribution.charge(None, dispatches=1)
    attribution.charge_model(None, "m", requests=1)
    assert attribution.active() is None
    assert NOOP_WINDOW.live is False
    with NOOP_WINDOW as w:  # a usable no-op context manager
        w.annotate_program("pid")


def test_enabled_but_unscoped_dispatch_returns_noop_window():
    attribution.enable()
    try:
        assert attribution.dispatch_window() is NOOP_WINDOW
        with attribution.scope("sc"):
            win = attribution.dispatch_window()
            assert win is not NOOP_WINDOW and win.live
    finally:
        attribution.disable()


def test_enable_is_idempotent():
    led = attribution.enable()
    assert attribution.enable() is led


# -- window charging + costs join ------------------------------------------------

def test_window_charges_device_seconds_and_joins_costs_registry():
    from cycloneml_tpu.observe import costs
    led = attribution.enable()
    pid = "test-attribution-pid"
    with costs._lock:
        costs._registry[pid] = {"flops_total": 120.0,
                                "bytes_accessed_total": 64.0,
                                "peak_bytes": 4096}
    try:
        with attribution.scope("fit", tenant="acme"):
            with attribution.dispatch_window() as win:
                win.annotate_program(pid)
        row = led.row("acme/fit")
        assert row["dispatches"] == 1 and row["deviceSeconds"] > 0
        assert row["flops"] == 120.0 and row["bytesAccessed"] == 64.0
        assert row["hbmPeakBytes"] == 4096
        # an unknown program id still charges time, just no cost join
        with attribution.scope("fit", tenant="acme"):
            with attribution.dispatch_window() as win:
                win.annotate_program("no-such-pid")
        row = led.row("acme/fit")
        assert row["dispatches"] == 2 and row["flops"] == 120.0
        assert _sum_matches_totals(led.snapshot())
    finally:
        with costs._lock:
            costs._registry.pop(pid, None)
        attribution.disable()


# -- ledger semantics ------------------------------------------------------------

def test_charge_lands_on_row_and_totals_atomically():
    led = UsageLedger()
    led.charge(Scope("a"), deviceSeconds=1.5, dispatches=2)
    led.charge(Scope("b", tenant="t"), deviceSeconds=0.5, dispatches=1)
    led.charge(None, reshapes=1)  # scope=None -> the UNSCOPED row
    snap = led.snapshot()
    assert snap["a"]["dispatches"] == 2
    assert snap["t/b"]["tenant"] == "t"
    assert snap[UNSCOPED]["reshapes"] == 1
    assert snap[TOTALS]["deviceSeconds"] == pytest.approx(2.0)
    assert snap[TOTALS]["dispatches"] == 3 and snap[TOTALS]["reshapes"] == 1
    assert _sum_matches_totals(snap)


def test_hbm_peak_merges_by_max_not_sum():
    led = UsageLedger()
    led.charge(Scope("a"), hbmPeakBytes=100)
    led.charge(Scope("a"), hbmPeakBytes=40)   # lower: ignored
    led.charge(Scope("b"), hbmPeakBytes=250)
    snap = led.snapshot()
    assert snap["a"]["hbmPeakBytes"] == 100
    assert snap[TOTALS]["hbmPeakBytes"] == 250  # high-water mark, not 350


def test_concurrent_charges_keep_the_sum_invariant():
    """The 1% acceptance bar, exercised from 8 threads: the single-lock
    both-sides charge means the invariant holds EXACTLY."""
    led = UsageLedger()
    n, per = 8, 200

    def worker(i):
        sc = Scope(f"job-{i % 4}", tenant=f"t{i % 2}")
        for _ in range(per):
            led.charge(sc, deviceSeconds=0.001, dispatches=1, flops=10.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = led.snapshot()
    assert snap[TOTALS]["dispatches"] == n * per
    scope_sum = sum(r["dispatches"] for k, r in snap.items() if k != TOTALS)
    assert scope_sum == n * per
    assert _sum_matches_totals(snap, tol=1e-9)


def test_eviction_folds_into_evicted_row_preserving_sums():
    led = UsageLedger(max_scopes=3)
    for i in range(6):
        led.charge(Scope(f"s{i}"), dispatches=1, deviceSeconds=1.0)
    snap = led.snapshot()
    assert led.scopes_evicted > 0 and EVICTED in snap
    assert len([k for k in snap if k != TOTALS]) <= led.max_scopes + 1
    # evicted work is folded, not lost: sums still match the totals row
    assert snap[TOTALS]["dispatches"] == 6
    assert _sum_matches_totals(snap, tol=1e-9)


def test_charge_model_bounded_with_other_overflow():
    led = UsageLedger(max_models=2)
    sc = Scope("serve", tenant="beta")
    for m in ("m0", "m1", "m2", "m3"):
        led.charge_model(sc, m, requests=5)
    row = led.row("beta/serve")
    assert set(row["models"]) == {"m0", "m1", "(other)"}
    assert row["models"]["(other)"]["requests"] == 10  # m2 + m3 folded
    assert row["requests"] == 20  # the scope row still carries everything
    assert led.totals()["requests"] == 20


def test_row_returns_zero_row_for_unknown_key():
    led = UsageLedger()
    row = led.row("never-charged")
    assert row["dispatches"] == 0 and row["models"] == {}
    # the bracket-delta consumer: zero row before, real row after
    led.charge(Scope("never-charged"), dispatches=3)
    assert usage_delta(row, led.row("never-charged")) == {"dispatches": 3}


def test_usage_delta_drops_zero_fields_and_keeps_peaks():
    before = {"deviceSeconds": 1.0, "dispatches": 2, "hbmPeakBytes": 50,
              "flops": 10.0, "scope": "a", "models": {}}
    after = {"deviceSeconds": 1.5, "dispatches": 2, "hbmPeakBytes": 80,
             "flops": 25.0, "scope": "a", "models": {}}
    d = usage_delta(before, after)
    assert d == {"deviceSeconds": 0.5, "flops": 15.0, "hbmPeakBytes": 80}


# -- cross-host merge -------------------------------------------------------------

def test_merge_snapshots_sums_rows_and_maxes_peaks_across_hosts():
    h0, h1 = UsageLedger(), UsageLedger()
    h0.charge(Scope("fit", tenant="acme"), deviceSeconds=1.0, dispatches=2,
              hbmPeakBytes=100)
    h1.charge(Scope("fit", tenant="acme"), deviceSeconds=0.5, dispatches=1,
              hbmPeakBytes=300)
    h1.charge(Scope("other"), dispatches=4)
    merged = merge_snapshots([h0.snapshot(), h1.snapshot()])
    assert merged["acme/fit"]["dispatches"] == 3
    assert merged["acme/fit"]["deviceSeconds"] == pytest.approx(1.5)
    assert merged["acme/fit"]["hbmPeakBytes"] == 300
    assert merged["other"]["dispatches"] == 4
    assert merged[TOTALS]["dispatches"] == 7
    assert _sum_matches_totals(merged, tol=1e-9)
    # hostile shapes (a torn wire payload) are skipped, not fatal
    assert merge_snapshots([None, {"x": "not-a-row"}, h0.snapshot()])[
        "acme/fit"]["dispatches"] == 2


# -- UsageReport journal replay ---------------------------------------------------

def _reported_store(events):
    from cycloneml_tpu.util.status import AppStatusListener
    listener = AppStatusListener()
    for e in events:
        listener.on_event(e if isinstance(e, dict) else e.to_json())
    return listener.store


def test_usage_report_replay_matches_live_rollup(tmp_path):
    """History-server fidelity for the accounting plane: replay the
    journal into a fresh store and usage_rollup() equals the live
    ledger snapshot (UsageReport is cumulative + REPLACE-folded, so the
    last surviving line is the whole state)."""
    from cycloneml_tpu.util.events import EventJournal, ListenerBus
    from cycloneml_tpu.util.status import AppStatusListener

    led = attribution.enable()
    try:
        led.charge(Scope("fit", tenant="acme"), deviceSeconds=2.0,
                   dispatches=3, flops=99.0)
        led.charge_model(Scope("serve", tenant="beta"), "storm",
                         requests=7, servingSeconds=0.25)

        path = tmp_path / "usage.jsonl"
        journal = EventJournal(str(path))
        bus = ListenerBus()
        live = AppStatusListener()
        bus.add_listener(journal)
        bus.add_listener(live)
        rep = UsageReporter(bus, interval_s=60, host="h0")
        rep.flush()           # intermediate cumulative report
        led.charge(Scope("fit", tenant="acme"), dispatches=1)
        rep.stop()            # final flush on stop
        journal.close()

        live_rollup = live.store.usage_rollup()
        assert live_rollup["acme/fit"]["dispatches"] == 4
        assert live_rollup["beta/serve"]["models"]["storm"]["requests"] == 7
        assert live_rollup == led.snapshot()  # REPLACE-fold == cumulative

        replayed = _reported_store(EventJournal.replay(str(path)))
        assert replayed.usage_rollup() == live_rollup
    finally:
        attribution.disable()


def test_usage_report_replay_tolerates_torn_tail(tmp_path):
    """A process killed mid-write tears the LAST UsageReport line; replay
    must fall back to the previous surviving report, not die or serve
    nothing."""
    from cycloneml_tpu.util.events import EventJournal, ListenerBus

    led = attribution.enable()
    try:
        led.charge(Scope("fit"), dispatches=2)
        path = tmp_path / "torn.jsonl"
        journal = EventJournal(str(path))
        bus = ListenerBus()
        bus.add_listener(journal)
        rep = UsageReporter(bus, interval_s=60, host="h0")
        rep.flush()
        led.charge(Scope("fit"), dispatches=5)
        rep.flush()
        journal.close()

        lines = open(path, encoding="utf-8").read().splitlines()
        torn = tmp_path / "torn2.jsonl"
        torn.write_text("\n".join(lines[:-1]) + "\n"
                        + lines[-1][: len(lines[-1]) // 2],
                        encoding="utf-8")
        replayed = _reported_store(EventJournal.replay(str(torn)))
        rollup = replayed.usage_rollup()
        # the surviving (earlier, cumulative) report still serves
        assert rollup["fit"]["dispatches"] == 2
        assert rollup[TOTALS]["dispatches"] == 2
    finally:
        attribution.disable()


def test_usage_reports_fold_per_host_not_cumulatively_per_line():
    """Two hosts' cumulative reports REPLACE per host and SUM across
    hosts — posting the same host twice must not double-count."""
    from cycloneml_tpu.util.events import UsageReport
    snap_a1 = {"fit": {"scope": "fit", "tenant": "", "dispatches": 1},
               TOTALS: {"scope": TOTALS, "tenant": "", "dispatches": 1}}
    snap_a2 = {"fit": {"scope": "fit", "tenant": "", "dispatches": 5},
               TOTALS: {"scope": TOTALS, "tenant": "", "dispatches": 5}}
    snap_b = {"fit": {"scope": "fit", "tenant": "", "dispatches": 2},
              TOTALS: {"scope": TOTALS, "tenant": "", "dispatches": 2}}
    store = _reported_store([UsageReport(usage=snap_a1, host="a"),
                             UsageReport(usage=snap_b, host="b"),
                             UsageReport(usage=snap_a2, host="a")])
    rollup = store.usage_rollup()
    assert rollup["fit"]["dispatches"] == 7  # a's latest (5) + b (2)
    assert rollup[TOTALS]["dispatches"] == 7


def test_usage_reporter_stop_latch_blocks_late_posts():
    """JX022 latch: flush() after stop() must not land on the bus."""
    posted = []

    class _Bus:
        def post(self, ev):
            posted.append(ev)

    attribution.enable().charge(Scope("x"), dispatches=1)
    try:
        rep = UsageReporter(_Bus(), interval_s=60, host="h")
        rep.stop()          # final flush posts exactly once
        n = len(posted)
        assert n == 1
        rep.flush()         # latched: silently dropped
        rep.stop()          # idempotent
        assert len(posted) == n
    finally:
        attribution.disable()
