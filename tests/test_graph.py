"""GraphX-equivalent tests (ref: graphx/src/test/scala/org/apache/spark/
graphx/ — GraphSuite, PregelSuite, lib/*Suite) on the local-mesh[8] fixture."""

import numpy as np
import pytest

from cycloneml_tpu.graph import Graph, pregel
from cycloneml_tpu.graph import lib as glib


@pytest.fixture(scope="module")
def tri_graph(ctx):
    # 0→1, 0→2, 1→2
    return Graph(ctx, np.array([0, 0, 1]), np.array([1, 2, 2]), n_vertices=3)


def test_degrees(tri_graph):
    assert glib and np.array_equal(tri_graph.out_degrees(), [2, 1, 0])
    assert np.array_equal(tri_graph.in_degrees(), [0, 1, 2])
    assert np.array_equal(tri_graph.degrees(), [2, 2, 2])


def test_reverse_subgraph(ctx, tri_graph):
    rev = tri_graph.reverse()
    assert np.array_equal(rev.out_degrees(), [0, 1, 2])
    sub = tri_graph.subgraph(lambda s, d, a: d != 2)
    assert sub.n_edges == 1 and np.array_equal(sub.out_degrees(), [1, 0, 0])


def test_from_edges_remaps_ids(ctx):
    g = Graph.from_edges(ctx, [(100, 200), (200, 300)])
    assert g.n_vertices == 3
    assert np.array_equal(g.vertex_ids, [100, 200, 300])


def test_pagerank_matches_dense_reference(ctx):
    rng = np.random.RandomState(3)
    n, e = 12, 40
    src = rng.randint(0, n, e).astype(np.int64)
    dst = rng.randint(0, n, e).astype(np.int64)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    g = Graph(ctx, src, dst, n_vertices=n)
    ranks = glib.pagerank(g, num_iter=15)

    # dense numpy replica of Spark's iteration (PageRank.scala run)
    a = np.zeros((n, n))
    for s, d in zip(src, dst):
        a[s, d] += 1.0
    outdeg = a.sum(axis=1)
    inv = np.where(outdeg > 0, 1.0 / np.maximum(outdeg, 1), 0.0)
    r = np.ones(n)
    for _ in range(15):
        r = 0.15 + 0.85 * a.T @ (r * inv)
    assert np.allclose(ranks, r, atol=1e-4)


def test_pagerank_personalized(ctx):
    g = Graph(ctx, np.array([0, 1, 2]), np.array([1, 2, 0]), n_vertices=3)
    r = glib.pagerank(g, num_iter=30, personalized_src=0)
    assert r[0] == max(r)  # mass concentrates at the personalization source


def test_connected_components(ctx):
    g = Graph(ctx, np.array([0, 1, 3]), np.array([1, 2, 4]), n_vertices=6)
    labels = glib.connected_components(g)
    assert np.array_equal(labels, [0, 0, 0, 3, 3, 5])


def test_shortest_paths(ctx):
    # chain 0→1→2→3 plus isolated 4
    g = Graph(ctx, np.array([0, 1, 2]), np.array([1, 2, 3]), n_vertices=5)
    d = glib.shortest_paths(g, landmarks=[3, 1])
    assert np.array_equal(d[:4, 0], [3, 2, 1, 0])
    assert np.isinf(d[4, 0]) and d[0, 1] == 1 and np.isinf(d[2, 1])


def test_triangle_count(ctx):
    # K4: every vertex participates in C(3,2)=3 triangles
    src, dst = zip(*[(i, j) for i in range(4) for j in range(4) if i < j])
    g = Graph(ctx, np.array(src), np.array(dst), n_vertices=4)
    assert np.array_equal(glib.triangle_count(g), [3, 3, 3, 3])
    # 4-cycle: none
    g2 = Graph(ctx, np.array([0, 1, 2, 3]), np.array([1, 2, 3, 0]), n_vertices=4)
    assert np.array_equal(glib.triangle_count(g2), [0, 0, 0, 0])


def test_label_propagation_two_cliques(ctx):
    edges = [(i, j) for i in range(4) for j in range(4) if i < j]
    edges += [(i, j) for i in range(4, 8) for j in range(4, 8) if i < j]
    src, dst = np.array([e[0] for e in edges]), np.array([e[1] for e in edges])
    labels = glib.label_propagation(Graph(ctx, src, dst, n_vertices=8),
                                    max_iter=10)
    assert len(set(labels[:4])) == 1 and len(set(labels[4:])) == 1
    assert labels[0] != labels[4]


def test_scc(ctx):
    # cycle 0→1→2→0, tail 3→0, isolated 4
    g = Graph(ctx, np.array([0, 1, 2, 3]), np.array([1, 2, 0, 0]), n_vertices=5)
    labels = glib.strongly_connected_components(g)
    assert np.array_equal(labels, [0, 0, 0, 3, 4])


def test_svd_plus_plus(ctx):
    # bipartite: users 0-1, items 2-4
    src = np.array([0, 0, 0, 1, 1])
    dst = np.array([2, 3, 4, 2, 3])
    ratings = np.array([5.0, 3.0, 4.0, 4.0, 2.0], dtype=np.float32)
    g = Graph(ctx, src, dst, edge_attr=ratings, n_vertices=5)
    m0 = glib.svd_plus_plus(g, rank=4, max_iter=0)
    m = glib.svd_plus_plus(g, rank=4, max_iter=30)
    assert np.isfinite(m["rmse"]) and m["rmse"] <= m0["rmse"] + 1e-9
    assert m["rmse"] < 1.2


def test_pregel_connected_components(ctx):
    """Drive the generic Pregel API: min-label propagation."""
    import jax.numpy as jnp

    g = Graph(ctx, np.array([0, 1, 3]), np.array([1, 2, 4]), n_vertices=5)

    def vprog(attr, msg, has):
        return jnp.minimum(attr, msg)

    def send_dst(sa, da, e, s_act, d_act):
        return sa, (sa < da).astype(jnp.float32) * s_act

    def send_src(sa, da, e, s_act, d_act):
        return da, (da < sa).astype(jnp.float32) * d_act

    init = jnp.arange(5, dtype=jnp.float32)
    out = pregel(g, init, np.inf, vprog, send_to_dst=send_dst,
                 send_to_src=send_src, merge="min", max_iter=10)
    assert np.array_equal(np.asarray(out), [0, 0, 0, 3, 3])


def test_aggregate_messages_weighted(ctx):
    g = Graph(ctx, np.array([0, 1]), np.array([2, 2]),
              edge_attr=np.array([2.0, 5.0], dtype=np.float32), n_vertices=3)
    import jax.numpy as jnp
    out = g.aggregate_messages(jnp.ones(3, dtype=jnp.float32),
                               to_dst=lambda sa, da, e: sa * e)
    assert np.allclose(out, [0, 0, 7.0])
