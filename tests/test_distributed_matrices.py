"""BlockMatrix/CoordinateMatrix/IndexedRowMatrix + random generators
(ref: mllib/.../linalg/distributed/BlockMatrixSuite.scala etc.,
mllib/random/RandomRDDsSuite.scala)."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.random import RandomDatasets
from cycloneml_tpu.linalg.block import (BlockMatrix, CoordinateMatrix,
                                        IndexedRowMatrix)


@pytest.fixture(scope="module")
def ab(ctx):
    rng = np.random.RandomState(0)
    a = rng.randn(30, 17)
    b = rng.randn(17, 11)
    return (a, b, BlockMatrix.from_numpy(ctx, a), BlockMatrix.from_numpy(ctx, b))


def test_block_matrix_roundtrip(ctx, ab):
    a, _, bm, _ = ab
    assert bm.num_rows() == 30 and bm.num_cols() == 17
    bm.validate()
    assert np.allclose(bm.to_numpy(), a)
    assert np.allclose(bm.to_local_matrix().to_array(), a)


def test_block_matrix_multiply(ctx, ab):
    a, b, bma, bmb = ab
    c = bma.multiply(bmb)
    assert c.num_rows() == 30 and c.num_cols() == 11
    assert np.allclose(c.to_numpy(), a @ b, atol=1e-8)


def test_block_matrix_add_scale_transpose(ctx, ab):
    a, _, bma, _ = ab
    s = bma.add(bma).subtract(bma.scale(0.5))
    assert np.allclose(s.to_numpy(), 1.5 * a)
    t = bma.transpose()
    assert t.num_rows() == 17 and np.allclose(t.to_numpy(), a.T)
    # (AᵀA) via the sharded path
    g = t.multiply(bma)
    assert np.allclose(g.to_numpy(), a.T @ a, atol=1e-8)


def test_block_matrix_mixed_padding_paths(ctx, ab):
    # transpose() output has different physical padding than from_numpy();
    # elementwise ops must align the pads (regression)
    a, _, bma, _ = ab
    other = BlockMatrix.from_numpy(ctx, a.T)
    s = bma.transpose().add(other)
    assert np.allclose(s.to_numpy(), 2.0 * a.T)


def test_block_matrix_conversions(ctx, ab):
    a, _, bma, _ = ab
    irm = bma.to_indexed_row_matrix()
    assert np.allclose(irm.to_numpy(), a)
    cm = bma.to_coordinate_matrix()
    assert np.allclose(cm.to_numpy(), a)


def test_coordinate_matrix(ctx):
    cm = CoordinateMatrix.from_entries(
        ctx, [(0, 0, 1.0), (1, 2, 3.0), (4, 1, -2.0)])
    assert cm.num_rows() == 5 and cm.num_cols() == 3
    t = cm.transpose()
    assert t.num_rows() == 3 and np.allclose(t.to_numpy(), cm.to_numpy().T)
    assert np.allclose(cm.to_block_matrix().to_numpy(), cm.to_numpy())
    es = cm.entries()
    assert (es[1].i, es[1].j, es[1].value) == (1, 2, 3.0)


def test_indexed_row_matrix(ctx):
    rng = np.random.RandomState(1)
    x = rng.randn(20, 6)
    idx = np.arange(20, dtype=np.int64)[::-1].copy()
    irm = IndexedRowMatrix.from_numpy(ctx, idx, x)
    assert irm.num_rows() == 20 and irm.num_cols() == 6
    assert np.allclose(irm.compute_gramian_matrix().to_array(), x.T @ x, atol=1e-8)
    dense = irm.to_numpy()
    assert np.allclose(dense[idx], x)
    svd = irm.compute_svd(3)
    ref = np.linalg.svd(x, compute_uv=False)
    assert np.allclose(np.asarray(svd.s.to_array()), ref[:3], atol=1e-6)


def test_random_normal_moments(ctx):
    ds = RandomDatasets.normal(ctx, 40_000, 4, seed=7, mean=2.0, std=3.0)
    x, _, w = ds.to_numpy()
    assert ds.n_rows == 40_000 and x.shape == (40_000, 4)
    assert np.all(w == 1.0)
    assert abs(x.mean() - 2.0) < 0.1 and abs(x.std() - 3.0) < 0.1


def test_random_determinism_and_shard_independence(ctx):
    a = RandomDatasets.uniform(ctx, 1000, 2, seed=5)
    b = RandomDatasets.uniform(ctx, 1000, 2, seed=5)
    c = RandomDatasets.uniform(ctx, 1000, 2, seed=6)
    assert np.array_equal(a.to_numpy()[0], b.to_numpy()[0])
    assert not np.array_equal(a.to_numpy()[0], c.to_numpy()[0])
    # different shards produced different streams
    xa = a.to_numpy()[0]
    assert len(np.unique(np.round(xa[:, 0], 6))) > 900


def test_random_families(ctx):
    p = RandomDatasets.poisson(ctx, 20_000, seed=1, lam=4.0).to_numpy()[0]
    assert abs(p.mean() - 4.0) < 0.15
    e = RandomDatasets.exponential(ctx, 20_000, seed=2, mean=2.5).to_numpy()[0]
    assert abs(e.mean() - 2.5) < 0.15
    g = RandomDatasets.gamma(ctx, 20_000, seed=3, shape=2.0, scale=1.5).to_numpy()[0]
    assert abs(g.mean() - 3.0) < 0.2
    ln = RandomDatasets.log_normal(ctx, 20_000, seed=4).to_numpy()[0]
    assert abs(ln.mean() - np.exp(0.5)) < 0.2


def test_generate_classification_trains(ctx):
    """Device-generated labeled data feeds any estimator directly (the
    InstanceDataset.to_instance_dataset bridge) and is learnable."""
    from cycloneml_tpu.dataset.random import generate_classification
    from cycloneml_tpu.ml.classification import LogisticRegression

    ds = generate_classification(ctx, 4000, 16, seed=3)
    x, y, w = ds.to_numpy()
    assert x.shape == (4000, 16) and set(np.unique(y)) <= {0.0, 1.0}
    assert ds.to_instance_dataset("anything", "else") is ds
    # host label twins attached: no device readback on y_host
    assert ds._yw_host is not None and len(ds.y_host()) >= 4000
    assert np.array_equal(
        x, generate_classification(ctx, 4000, 16, seed=3).to_numpy()[0])
    m = LogisticRegression(maxIter=20, regParam=0.0).fit(ds)
    pred = (x @ np.asarray(m.coefficients) + m.intercept) > 0
    assert ((pred == (y > 0.5)).mean()) > 0.9
