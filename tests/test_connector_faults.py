"""Connector failure-mode tests (round-3 verdict item 9): fault-injecting
fakes that rebalance mid-stream, re-deliver records, and split shards —
asserting the durable-checkpoint logic yields EXACTLY-ONCE batch contents
across faults and restarts (ref: external/kafka-0-10-sql's exactly-once
offset-range contract; external/kinesis-asl resharding + KCL checkpoints).
"""

import numpy as np
import pytest

from cycloneml_tpu.streaming.kafka import KafkaSource
from cycloneml_tpu.streaming.kinesis import KinesisSource


class FaultyKafkaConsumer:
    """Log-backed fake with kafka-python's poll surface plus fault hooks:
    ``rebalance()`` drops group state so the next poll re-delivers from the
    last broker-committed position; ``rebalance(from_start=True)`` models a
    lost consumer group (auto_offset_reset=earliest) re-delivering the
    WHOLE topic."""

    def __init__(self):
        self.log = {}        # (topic, part) -> [records]
        self.pos = {}        # delivery cursor per partition
        self.committed_pos = {}
        self.commits = 0

    def feed(self, topic, part, *values):
        from types import SimpleNamespace
        tp = (topic, part)
        recs = self.log.setdefault(tp, [])
        for v in values:
            recs.append(SimpleNamespace(
                key=None, value=v, topic=topic, partition=part,
                offset=len(recs), timestamp=1000 + len(recs)))

    def poll(self, timeout_ms=0):
        out = {}
        for tp, recs in self.log.items():
            i = self.pos.get(tp, 0)
            if i < len(recs):
                out[str(tp)] = recs[i:]
                self.pos[tp] = len(recs)
        return out

    def commit(self):
        self.committed_pos = dict(self.pos)
        self.commits += 1

    def rebalance(self, from_start=False):
        self.pos = {} if from_start else dict(self.committed_pos)


def test_kafka_rebalance_redelivery_exactly_once():
    """A group rebalance re-delivers records after the last broker commit;
    the per-partition dedup filter keeps batch contents exactly-once."""
    consumer = FaultyKafkaConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)

    consumer.feed("t", 0, b"a0", b"a1")
    consumer.feed("t", 1, b"b0")
    end1 = src.latest_offset()
    assert end1 == 3
    got1 = sorted(src.get_batch(0, end1)["value"].tolist())
    assert got1 == ["a0", "a1", "b0"]
    src.commit(end1)

    # new records arrive, consumer polls them, THEN the group rebalances
    # before the broker commit: the next poll re-delivers them
    consumer.feed("t", 0, b"a2")
    consumer.feed("t", 1, b"b1", b"b2")
    end2 = src.latest_offset()
    consumer.rebalance()               # re-deliver everything uncommitted
    end2b = src.latest_offset()        # the re-delivery poll
    assert end2b == end2 == 6          # dedup: no phantom growth
    got2 = sorted(src.get_batch(end1, end2)["value"].tolist())
    assert got2 == ["a2", "b1", "b2"]
    src.commit(end2)


def test_kafka_lost_group_full_replay_exactly_once():
    """auto_offset_reset=earliest after total group loss re-delivers the
    whole topic; nothing duplicates."""
    consumer = FaultyKafkaConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    consumer.feed("t", 0, b"x", b"y", b"z")
    end = src.latest_offset()
    src.get_batch(0, end)
    src.commit(end)

    consumer.rebalance(from_start=True)
    consumer.feed("t", 0, b"w")
    end2 = src.latest_offset()
    assert end2 == 4  # exactly one new row despite the full replay
    assert src.get_batch(end, end2)["value"].tolist() == ["w"]


def test_kafka_restart_with_full_redelivery(tmp_path):
    """Crash with uncommitted rows in the WAL; the restarted source's NEW
    consumer replays the topic from offset 0 (no seek on the fake). WAL
    recovery + dedup must reproduce the pending batch exactly once."""
    log = str(tmp_path / "kafka_ck")
    consumer = FaultyKafkaConsumer()
    src = KafkaSource("t", consumer_factory=lambda: consumer)
    src.set_log_dir(log)
    consumer.feed("t", 0, b"r0", b"r1")
    consumer.feed("t", 1, b"s0")
    end1 = src.latest_offset()
    src.get_batch(0, end1)
    src.commit(end1)
    consumer.feed("t", 0, b"r2")
    consumer.feed("t", 1, b"s1")
    end2 = src.latest_offset()  # engine logged end2, then CRASH

    # restart: fresh source; the fake consumer lost its position entirely
    # and re-delivers every record ever written
    consumer.rebalance(from_start=True)
    src2 = KafkaSource("t", consumer_factory=lambda: consumer)
    src2.set_log_dir(log)
    end2b = src2.latest_offset()
    assert end2b == end2  # replayed rows deduped against WAL recovery
    replay = sorted(src2.get_batch(end1, end2)["value"].tolist())
    assert replay == ["r2", "s1"]
    src2.commit(end2)
    # a third instance starts clean: no pending rows, no duplicates
    consumer.rebalance(from_start=True)
    src3 = KafkaSource("t", consumer_factory=lambda: consumer)
    src3.set_log_dir(log)
    assert src3.latest_offset() == end2


class SplittingKinesisClient:
    """Kinesis fake whose shards can SPLIT: the parent's iterator chain
    ends (NextShardIterator None once drained and closed) and two children
    appear in list_shards — the resharding surface of the real service."""

    def __init__(self):
        self._seq = 0
        self.shards = {"shard-p": {"recs": [], "closed": False}}

    def put(self, shard, data):
        self._seq += 1
        self.shards[shard]["recs"].append(
            {"Data": data, "PartitionKey": "k",
             "SequenceNumber": f"{self._seq:020d}",
             "ApproximateArrivalTimestamp": 1700000000 + self._seq})

    def split(self, parent, *children):
        self.shards[parent]["closed"] = True
        for c in children:
            self.shards.setdefault(c, {"recs": [], "closed": False})

    def list_shards(self, StreamName):
        return {"Shards": [{"ShardId": s} for s in self.shards]}

    def get_shard_iterator(self, StreamName, ShardId, ShardIteratorType,
                           StartingSequenceNumber=None):
        recs = self.shards[ShardId]["recs"]
        if ShardIteratorType == "TRIM_HORIZON":
            pos = 0
        else:
            pos = sum(1 for r in recs
                      if int(r["SequenceNumber"])
                      <= int(StartingSequenceNumber))
        return {"ShardIterator": f"{ShardId}:{pos}"}

    def get_records(self, ShardIterator, Limit):
        sid, pos = ShardIterator.rsplit(":", 1)
        sh = self.shards[sid]
        pos = int(pos)
        recs = sh["recs"][pos: pos + Limit]
        new_pos = pos + len(recs)
        drained = new_pos >= len(sh["recs"])
        nxt = None if (sh["closed"] and drained) else f"{sid}:{new_pos}"
        return {"Records": recs, "NextShardIterator": nxt}


def test_kinesis_shard_split_exactly_once(tmp_path):
    fake = SplittingKinesisClient()
    fake.put("shard-p", b"p0")
    fake.put("shard-p", b"p1")
    src = KinesisSource("s", client_factory=lambda: fake)
    src.set_log_dir(str(tmp_path / "ck"))
    end1 = src.latest_offset()
    assert sorted(src.get_batch(0, end1)["data"].tolist()) == ["p0", "p1"]
    src.commit(end1)

    # SPLIT: parent closes, children carry the post-split records
    fake.split("shard-p", "shard-c1", "shard-c2")
    fake.put("shard-c1", b"c1a")
    fake.put("shard-c2", b"c2a")
    end2 = src.latest_offset()
    got = sorted(src.get_batch(end1, end2)["data"].tolist())
    assert got == ["c1a", "c2a"]
    src.commit(end2)

    # the closed parent must not replay on later polls
    fake.put("shard-c1", b"c1b")
    end3 = src.latest_offset()
    assert src.get_batch(end2, end3)["data"].tolist() == ["c1b"]
    src.commit(end3)

    # restart after the split: children resume AFTER their committed
    # sequence numbers, the parent stays consumed — no loss, no dups
    fake.put("shard-c2", b"c2b")
    src2 = KinesisSource("s", client_factory=lambda: fake)
    src2.set_log_dir(str(tmp_path / "ck"))
    end4 = src2.latest_offset()
    got = src2.get_batch(src2._base, end4)["data"].tolist()
    assert got == ["c2b"]


def test_kinesis_split_mid_pending_restart(tmp_path):
    """Crash between consuming post-split records and committing them: the
    restarted source re-reads the children from their committed positions
    and reproduces the pending rows exactly once."""
    fake = SplittingKinesisClient()
    fake.put("shard-p", b"p0")
    src = KinesisSource("s", client_factory=lambda: fake)
    src.set_log_dir(str(tmp_path / "ck"))
    end1 = src.latest_offset()
    src.get_batch(0, end1)
    src.commit(end1)

    fake.split("shard-p", "shard-c1")
    fake.put("shard-c1", b"c0")
    fake.put("shard-c1", b"c1")
    end2 = src.latest_offset()  # consumed but NOT committed -> crash

    src2 = KinesisSource("s", client_factory=lambda: fake)
    src2.set_log_dir(str(tmp_path / "ck"))
    end2b = src2.latest_offset()
    assert end2b - src2._base == 2  # the two pending child rows, once
    got = sorted(src2.get_batch(src2._base, end2b)["data"].tolist())
    assert got == ["c0", "c1"]
    src2.commit(end2b)
    src3 = KinesisSource("s", client_factory=lambda: fake)
    src3.set_log_dir(str(tmp_path / "ck"))
    assert src3.latest_offset() == src3._base  # nothing pending
