"""Tests: DStream API, submit CLI, plugins, resource profiles, PMML export.

Models the reference's coverage (ref: streaming BasicOperationsSuite /
WindowOperationsSuite with ManualClock, SparkSubmitSuite, PMMLModelExport
suites, ResourceProfileSuite).
"""

import os
import sys
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from cycloneml_tpu.streaming.dstream import StreamingContext


@pytest.fixture
def ssc(ctx):
    s = StreamingContext(ctx, batch_duration=0.05)
    yield s
    s.stop()


# -- DStream basic operations (≈ BasicOperationsSuite, ManualClock-style) ------

def test_dstream_map_filter(ssc):
    out = []
    stream = ssc.queue_stream([[1, 2, 3], [4, 5]])
    stream.map(lambda x: x * 10).filter(lambda x: x > 15).collect_to(out)
    ssc.run_one_interval()
    ssc.run_one_interval()
    assert out == [(0, [20, 30]), (1, [40, 50])]


def test_dstream_flatmap_reduce_by_key(ssc):
    out = []
    stream = ssc.queue_stream([["a b a"], ["b b"]])
    (stream.flat_map(str.split).map(lambda w: (w, 1))
     .reduce_by_key(lambda a, b: a + b).collect_to(out))
    ssc.run_one_interval()
    ssc.run_one_interval()
    assert dict(out[0][1]) == {"a": 2, "b": 1}
    assert dict(out[1][1]) == {"b": 2}


def test_dstream_union_count_reduce(ssc):
    out_c, out_r = [], []
    a = ssc.queue_stream([[1, 2]])
    b = ssc.queue_stream([[3]])
    u = a.union(b)
    u.count().collect_to(out_c)
    u.reduce(lambda x, y: x + y).collect_to(out_r)
    ssc.run_one_interval()
    assert out_c == [(0, [3])] and out_r == [(0, [6])]


def test_dstream_window_operations(ssc):
    """(≈ WindowOperationsSuite): sliding window over 3 intervals."""
    out = []
    stream = ssc.queue_stream([[1], [2], [3], [4]])
    stream.window(window_length=3).collect_to(out)
    for _ in range(4):
        ssc.run_one_interval()
    assert out == [(0, [1]), (1, [1, 2]), (2, [1, 2, 3]), (3, [2, 3, 4])]


def test_dstream_slide_suppresses_offbeat_output(ssc):
    """slide=2: no RDD (and no output action) at off-slide intervals —
    not a fabricated empty batch that count() would turn into 0."""
    out = []
    stream = ssc.queue_stream([[1], [2], [3], [4]])
    stream.window(window_length=2, slide=2).count().collect_to(out)
    for _ in range(4):
        ssc.run_one_interval()
    assert out == [(1, [2]), (3, [2])]  # only at slide boundaries


def test_dstream_reduce_by_key_and_window(ssc):
    out = []
    stream = ssc.queue_stream([[("k", 1)], [("k", 2)], [("k", 4)]])
    stream.reduce_by_key_and_window(lambda a, b: a + b, 2).collect_to(out)
    for _ in range(3):
        ssc.run_one_interval()
    assert [dict(b)["k"] for _, b in out] == [1, 3, 6]


def test_dstream_long_window_retention(ssc):
    """Windows wider than the default retention must still see all their
    intervals (retention follows the widest registered window)."""
    out = []
    stream = ssc.queue_stream([[1]] * 120)
    stream.window(window_length=110).count().collect_to(out)
    for _ in range(115):
        ssc.run_one_interval()
    # at t=114 the window covers intervals 5..114 → 110 records
    assert out[-1] == (114, [110])


def test_streaming_context_restart(ctx):
    ssc = StreamingContext(ctx, batch_duration=0.02)
    out = []
    ssc.queue_stream([], default=["t"]).collect_to(out)
    ssc.start()
    import time
    deadline = time.time() + 5
    while time.time() < deadline and not out:
        time.sleep(0.02)
    ssc.stop()
    n = len(out)
    assert n > 0
    ssc.start()  # restart must tick again, not spin down instantly
    deadline = time.time() + 5
    while time.time() < deadline and len(out) <= n:
        time.sleep(0.02)
    ssc.stop()
    assert len(out) > n


def test_dstream_update_state_by_key(ssc):
    """(ref StateDStream updateStateByKey): running counts; None drops."""
    out = []
    stream = ssc.queue_stream([[("a", 1), ("b", 1)], [("a", 1)],
                               [("stop_b", 1)]])

    def update(new_vals, old):
        if old is not None and not new_vals and old >= 99:
            return None
        return (old or 0) + sum(new_vals)

    stream.update_state_by_key(update).collect_to(out)
    for _ in range(3):
        ssc.run_one_interval()
    assert dict(out[0][1]) == {"a": 1, "b": 1}
    assert dict(out[1][1]) == {"a": 2, "b": 1}
    assert dict(out[2][1])["a"] == 2  # state persists without new data


def test_dstream_transform_uses_datasets(ssc):
    out = []
    stream = ssc.queue_stream([[3, 1, 2]])
    stream.transform(lambda ds: ds.map(lambda x: x + 100)).collect_to(out)
    ssc.run_one_interval()
    assert sorted(out[0][1]) == [101, 102, 103]


def test_dstream_foreach_rdd(ssc):
    got = []
    stream = ssc.queue_stream([[1, 2, 3]])
    stream.foreach_rdd(lambda ds, t: got.append((t, ds.count())))
    ssc.run_one_interval()
    assert got == [(0, 3)]


def test_dstream_file_input(ctx, tmp_path):
    ssc = StreamingContext(ctx, 0.05)
    out = []
    (tmp_path / "pre.txt").write_text("old\n")  # pre-existing file skipped
    stream = ssc.text_file_stream(str(tmp_path))
    stream.collect_to(out)
    ssc.run_one_interval()
    (tmp_path / "new.txt").write_text("hello\nworld\n")
    ssc.run_one_interval()
    assert out == [(0, []), (1, ["hello", "world"])]
    ssc.stop()


def test_dstream_real_clock(ctx):
    import time
    ssc = StreamingContext(ctx, batch_duration=0.02)
    out = []
    src = ssc.queue_stream([], default=["tick"])
    src.collect_to(out)
    ssc.start()
    deadline = time.time() + 5
    while time.time() < deadline and len(out) < 3:
        time.sleep(0.02)
    ssc.stop()
    assert len(out) >= 3 and out[0][1] == ["tick"]


# -- submit CLI -----------------------------------------------------------------

def test_submit_runs_app_with_conf(tmp_path, monkeypatch):
    from cycloneml_tpu.submit import submit
    app = tmp_path / "app.py"
    out_file = tmp_path / "out.txt"
    app.write_text(
        "import sys, os\n"
        "from cycloneml_tpu.conf import CycloneConf\n"
        "conf = CycloneConf()\n"
        "open(sys.argv[1], 'w').write(\n"
        "    conf.get('cyclone.app.name') + '|' +\n"
        "    conf.get('cyclone.eventLog.dir') + '|' + sys.argv[2])\n")
    props = tmp_path / "props.conf"
    props.write_text("cyclone.eventLog.dir /tmp/ev-from-props\n")
    for k in list(os.environ):
        if k.startswith("CYCLONE_CONF_"):
            monkeypatch.delenv(k)
    monkeypatch.setattr(sys, "argv", ["cyclone-submit"])
    submit(["--name", "myapp", "--properties-file", str(props),
            "--conf", "cyclone.custom=1", str(app), str(out_file), "ARG"])
    assert out_file.read_text() == "myapp|/tmp/ev-from-props|ARG"
    assert os.environ["CYCLONE_CONF_cyclone__custom"] == "1"


def test_properties_file_value_containing_equals(tmp_path):
    from cycloneml_tpu.submit import parse_properties_file
    p = tmp_path / "p.conf"
    p.write_text("cyclone.extra.opts -Dfoo=bar\n"
                 "cyclone.simple=plain\n"
                 "# comment\n"
                 "cyclone.spaced value with spaces\n"
                 "cyclone.java.style = local[4]\n")
    got = dict(parse_properties_file(str(p)))
    assert got["cyclone.extra.opts"] == "-Dfoo=bar"
    assert got["cyclone.simple"] == "plain"
    assert got["cyclone.spaced"] == "value with spaces"
    assert got["cyclone.java.style"] == "local[4]"  # 'k = v' form


def test_chained_slid_windows(ssc):
    """A window over a slid window must treat the parent's None intervals
    as empty, not crash."""
    out = []
    stream = ssc.queue_stream([[1], [2], [3], [4]])
    stream.window(2, slide=2).window(2, 1).count().collect_to(out)
    for _ in range(4):
        ssc.run_one_interval()
    # inner emits [1,2] at t=1, [3,4] at t=3; outer windows of width 2
    assert out == [(0, [0]), (1, [2]), (2, [2]), (3, [2])]


def test_submit_rejects_bad_conf():
    from cycloneml_tpu.submit import submit
    with pytest.raises(SystemExit):
        submit(["--conf", "novalue", "x.py"])


# -- plugins --------------------------------------------------------------------

class _TestPlugin:
    """Module-level so load_plugins can import it by path."""
    inited = []
    shut = []

    def init(self, ctx, extra_conf):
        _TestPlugin.inited.append(ctx.app_id)

    def shutdown(self):
        _TestPlugin.shut.append(True)

    def registered_metrics(self):
        return {"answer": lambda: 42.0}


def test_plugin_loading(ctx):
    import types
    from cycloneml_tpu.plugin import load_plugins
    mod = types.ModuleType("cyclone_test_plugin_mod")
    mod.TestPlugin = _TestPlugin
    sys.modules["cyclone_test_plugin_mod"] = mod
    plugins = load_plugins(ctx, ["cyclone_test_plugin_mod.TestPlugin",
                                 "no.such.Plugin", ""])
    assert len(plugins) == 1  # broken path logged, not raised
    assert _TestPlugin.inited
    assert ctx.metrics.registry.values()["plugin.answer"] == 42.0
    plugins[0].shutdown()
    assert _TestPlugin.shut


class _BadMetricsPlugin:
    shut = []

    def init(self, ctx, extra_conf):
        pass

    def shutdown(self):
        _BadMetricsPlugin.shut.append(True)

    def registered_metrics(self):
        raise RuntimeError("metrics broke")


def test_plugin_with_broken_metrics_still_shut_down(ctx):
    import types
    from cycloneml_tpu.plugin import load_plugins
    mod = types.ModuleType("cyclone_bad_metrics_mod")
    mod.P = _BadMetricsPlugin
    sys.modules["cyclone_bad_metrics_mod"] = mod
    plugins = load_plugins(ctx, ["cyclone_bad_metrics_mod.P"])
    # init succeeded → the plugin must be tracked so shutdown() runs
    assert len(plugins) == 1


# -- resource profiles ----------------------------------------------------------

def test_resource_profile_builder_and_satisfaction(ctx):
    from cycloneml_tpu.resource import (ResourceProfileBuilder,
                                        ResourceProfileManager)
    p = (ResourceProfileBuilder().devices(4).model_parallel(1)
         .replicas(1).build())
    assert p.id >= 1
    assert ResourceProfileManager.instance().get(p.id) == p
    assert ResourceProfileManager.default_profile().id == 0
    assert p.satisfied_by(ctx.mesh_runtime)  # 8-device mesh, model=1
    big = ResourceProfileBuilder().devices(1000).build()
    assert not big.satisfied_by(ctx.mesh_runtime)
    with pytest.raises(RuntimeError, match="1000 devices"):
        ctx.with_resources(big)
    # satisfied profile is a no-op (same mesh object)
    mesh_before = ctx.mesh_runtime
    assert ctx.with_resources(p).mesh_runtime is mesh_before


def test_probe_raises_before_destructive_rebuild(ctx):
    """An infeasible master must fail BEFORE mesh teardown, not leave the
    context meshless after a destructive reset."""
    from cycloneml_tpu import mesh as mesh_mod
    from cycloneml_tpu.resource import ResourceProfileBuilder
    with pytest.raises(RuntimeError, match="needs 1000 devices"):
        mesh_mod.probe_device_count("local-mesh[1000]")
    mesh_before = ctx.mesh_runtime
    p = ResourceProfileBuilder().replicas(3).build()  # 8 % 3 != 0
    with pytest.raises(RuntimeError, match="divisible"):
        ctx.with_resources(p)
    assert ctx.mesh_runtime is mesh_before  # old mesh untouched


def test_resource_profile_mesh_rebuild(ctx):
    from cycloneml_tpu.resource import ResourceProfileBuilder
    p = ResourceProfileBuilder().model_parallel(2).build()
    try:
        ctx.with_resources(p)
        shape = dict(zip(ctx.mesh_runtime.mesh.axis_names,
                         ctx.mesh_runtime.mesh.devices.shape))
        assert shape["model"] == 2
        # an explicit replicas(1) profile is NOT satisfied by this 2-way
        # model mesh, and a 2-replica ask is not satisfied by replica=1
        two_rep = ResourceProfileBuilder().replicas(2).build()
        assert not two_rep.satisfied_by(ctx.mesh_runtime)
    finally:
        ctx.rebuild_mesh("local-mesh[8]")
    assert ctx.mesh_runtime.n_devices == 8
    assert ctx.listener_bus.wait_until_empty()
    # with_resources rebuilds announce MeshUp like rebuild_mesh does
    assert ctx.status_store.mesh["nDevices"] == 8


# -- PMML -----------------------------------------------------------------------

def _strip_ns(xml):
    return xml.replace(f' xmlns="http://www.dmg.org/PMML-4_2"', "")


def test_pmml_linear_regression():
    from cycloneml_tpu.ml.pmml import to_pmml
    from cycloneml_tpu.ml.regression.linear_regression import LinearRegressionModel
    m = LinearRegressionModel(coefficients=np.array([1.5, -2.0]), intercept=0.5)
    root = ET.fromstring(_strip_ns(to_pmml(m)))
    rm = root.find("RegressionModel")
    assert rm.get("functionName") == "regression"
    table = rm.find("RegressionTable")
    assert float(table.get("intercept")) == 0.5
    coefs = [float(p.get("coefficient"))
             for p in table.findall("NumericPredictor")]
    assert coefs == [1.5, -2.0]


def test_pmml_logistic_and_kmeans(tmp_path):
    from cycloneml_tpu.ml.pmml import to_pmml
    from cycloneml_tpu.ml.classification.logistic_regression import (
        LogisticRegressionModel)
    from cycloneml_tpu.ml.clustering.kmeans import KMeansModel
    lr = LogisticRegressionModel(coefficient_matrix=np.array([[0.3, 0.7]]),
                                 intercept_vector=np.array([0.1]))
    xml = _strip_ns(to_pmml(lr))
    rm = ET.fromstring(xml).find("RegressionModel")
    assert rm.get("normalizationMethod") == "logit"
    assert len(rm.findall("RegressionTable")) == 2  # categories 1 and 0

    km = KMeansModel(centers=np.array([[0.0, 1.0], [5.0, 5.0]]))
    path = str(tmp_path / "km.pmml")
    xml = _strip_ns(to_pmml(km, path))
    cm = ET.fromstring(xml).find("ClusteringModel")
    assert cm.get("numberOfClusters") == "2"
    assert len(cm.findall("Cluster")) == 2
    assert os.path.exists(path)

    with pytest.raises(TypeError, match="not supported"):
        to_pmml(object())


def test_pmml_linear_svc():
    from cycloneml_tpu.ml.classification.linear_svc import LinearSVCModel
    from cycloneml_tpu.ml.pmml import to_pmml
    m = LinearSVCModel(coefficients=np.array([0.4, -1.2]), intercept=0.2)
    rm = ET.fromstring(_strip_ns(to_pmml(m))).find("RegressionModel")
    assert rm.get("modelName") == "linear SVM"
    assert rm.get("normalizationMethod") == "none"
    tables = rm.findall("RegressionTable")
    assert len(tables) == 2
    by_cat = {t.get("targetCategory"): t for t in tables}
    assert float(by_cat["1"].get("intercept")) == 0.2
    coefs = [float(p.get("coefficient"))
             for p in by_cat["1"].findall("NumericPredictor")]
    assert coefs == [0.4, -1.2]
    # category-0 table carries the decision threshold (ref thresholdTable)
    assert float(by_cat["0"].get("intercept")) == 0.0
    m.set("threshold", 0.5)
    rm2 = ET.fromstring(_strip_ns(to_pmml(m))).find("RegressionModel")
    by_cat2 = {t.get("targetCategory"): t
               for t in rm2.findall("RegressionTable")}
    assert float(by_cat2["0"].get("intercept")) == 0.5
