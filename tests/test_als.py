"""ALS tests (BASELINE config 4 family): explicit low-rank recovery, implicit
ranking, ALS-WR regularization behavior, NNLS mode, cold start, persistence."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.recommendation import ALS, ALSModel


def _ratings(seed=51, n_users=40, n_items=30, rank=3, frac=0.5):
    rng = np.random.RandomState(seed)
    u = rng.randn(n_users, rank)
    v = rng.randn(n_items, rank)
    full = u @ v.T
    mask = rng.rand(n_users, n_items) < frac
    users, items = np.nonzero(mask)
    return users, items, full[users, items], full, mask


def test_explicit_recovers_low_rank(ctx):
    users, items, r, full, mask = _ratings()
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    model = ALS(rank=3, maxIter=15, regParam=0.01, seed=1).fit(frame)
    out = model.transform(frame)
    rmse = float(np.sqrt(np.mean((out["prediction"] - r) ** 2)))
    assert rmse < 0.05
    # held-out entries also predicted well (low-rank generalization)
    hu, hi = np.nonzero(~mask)
    hold = MLFrame(ctx, {"user": hu, "item": hi, "rating": full[hu, hi]})
    out_h = model.transform(hold)
    rmse_h = float(np.sqrt(np.nanmean((out_h["prediction"] - full[hu, hi]) ** 2)))
    assert rmse_h < 0.5


def test_regularization_shrinks_factors(ctx):
    users, items, r, _, _ = _ratings(seed=52)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    small = ALS(rank=3, maxIter=10, regParam=0.01, seed=2).fit(frame)
    big = ALS(rank=3, maxIter=10, regParam=10.0, seed=2).fit(frame)
    assert np.linalg.norm(big.user_factors) < np.linalg.norm(small.user_factors)


def test_implicit_ranks_observed_higher(ctx):
    rng = np.random.RandomState(53)
    n_users, n_items = 30, 25
    # block structure: users < 15 like items < 12
    users, items, counts = [], [], []
    for u in range(n_users):
        liked = range(0, 12) if u < 15 else range(12, 25)
        for i in liked:
            if rng.rand() < 0.6:
                users.append(u)
                items.append(i)
                counts.append(rng.randint(1, 5))
    frame = MLFrame(ctx, {"user": np.array(users), "item": np.array(items),
                          "rating": np.array(counts, dtype=float)})
    model = ALS(rank=4, maxIter=10, regParam=0.05, implicitPrefs=True,
                alpha=10.0, seed=3).fit(frame)
    scores = model.user_factors @ model.item_factors.T
    # group-0 users should prefer group-0 items on average
    assert scores[:15, :12].mean() > scores[:15, 12:].mean() + 0.1
    assert scores[15:, 12:].mean() > scores[15:, :12].mean() + 0.1


def test_nonnegative_factors(ctx):
    users, items, r, _, _ = _ratings(seed=54)
    r = np.abs(r) + 0.1
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    model = ALS(rank=3, maxIter=8, regParam=0.1, nonnegative=True, seed=4).fit(frame)
    assert model.user_factors.min() >= 0.0
    assert model.item_factors.min() >= 0.0
    out = model.transform(frame)
    rmse = float(np.sqrt(np.mean((out["prediction"] - r) ** 2)))
    assert rmse < 1.0


def test_cold_start_nan_and_drop(ctx):
    users, items, r, _, _ = _ratings(seed=55)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    model = ALS(rank=3, maxIter=5, seed=5).fit(frame)
    probe = MLFrame(ctx, {"user": np.array([users[0], 9999]),
                          "item": np.array([items[0], 0]),
                          "rating": np.array([1.0, 1.0])})
    out = model.transform(probe)
    assert np.isfinite(out["prediction"][0])
    assert np.isnan(out["prediction"][1])
    model.set("coldStartStrategy", "drop")
    out2 = model.transform(probe)
    assert out2.n_rows == 1


def test_recommend_for_all_users(ctx):
    users, items, r, full, _ = _ratings(seed=56)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    model = ALS(rank=3, maxIter=10, regParam=0.01, seed=6).fit(frame)
    recs = model.recommend_for_all_users(5)
    assert recs.n_rows == 40 * 5
    # top recommendation for user 0 should be among its true top items
    u0 = recs.filter_rows(np.asarray(recs["user"]) == model.user_ids[0])
    top_true = set(np.argsort(-full[0])[:8])
    assert int(u0["item"][0]) in top_true


def test_save_load(ctx, tmp_path):
    users, items, r, _, _ = _ratings(seed=57)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    model = ALS(rank=3, maxIter=5, seed=7).fit(frame)
    p = str(tmp_path / "als")
    model.save(p)
    back = ALSModel.load(p)
    np.testing.assert_allclose(back.user_factors, model.user_factors)
    o1 = model.transform(frame)["prediction"]
    o2 = back.transform(frame)["prediction"]
    np.testing.assert_allclose(o1, o2)


def test_checkpoint_resume_matches_uninterrupted(ctx, tmp_path):
    """checkpointDir lets a killed fit resume mid-training and land on the
    uninterrupted run's factors (deterministic seeded solves)."""
    users, items, r, _, _ = _ratings(seed=3)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    full = ALS(rank=3, maxIter=6, seed=9).fit(frame)

    ck = str(tmp_path / "als-ck")
    ALS(rank=3, maxIter=2, seed=9, checkpointDir=ck,
        checkpointInterval=1).fit(frame)
    resumed = ALS(rank=3, maxIter=6, seed=9, checkpointDir=ck,
                  checkpointInterval=1).fit(frame)
    np.testing.assert_allclose(resumed.user_factors, full.user_factors,
                               rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(resumed.item_factors, full.item_factors,
                               rtol=1e-6, atol=1e-8)


def test_checkpoint_fingerprint_guards_foreign_resume(ctx, tmp_path):
    users, items, r, _, _ = _ratings(seed=3)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    ck = str(tmp_path / "ck")
    ALS(rank=3, maxIter=3, seed=9, checkpointDir=ck,
        checkpointInterval=1).fit(frame)
    # different rank on the same dir must refuse, not crash on shapes
    with pytest.raises(ValueError, match="DIFFERENT ALS run"):
        ALS(rank=4, maxIter=3, seed=9, checkpointDir=ck,
            checkpointInterval=1).fit(frame)
    # different ratings likewise
    frame2 = MLFrame(ctx, {"user": users, "item": items, "rating": r + 1.0})
    with pytest.raises(ValueError, match="DIFFERENT ALS run"):
        ALS(rank=3, maxIter=3, seed=9, checkpointDir=ck,
            checkpointInterval=1).fit(frame2)


def test_chunked_aggregation_matches_unchunked(ctx):
    """A tiny chunk budget (forcing many scan chunks) must produce the same
    factors as the single-chunk path — chunking is a memory layout, not a
    math change."""
    users, items, r, _, _ = _ratings(seed=5)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    big = ALS(rank=3, maxIter=5, seed=2).fit(frame)
    small = ALS(rank=3, maxIter=5, seed=2,
                aggregationChunkBytes=4096).fit(frame)
    np.testing.assert_allclose(small.user_factors, big.user_factors,
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(small.item_factors, big.item_factors,
                               rtol=1e-5, atol=1e-7)


def test_normal_eq_memory_proportional_to_entities(ctx):
    """MovieLens-25M shape (25M ratings, rank 64): compile the user-side
    normal-equation aggregation and assert XLA's planned temp memory is
    entities-proportional, NOT nnz-proportional (VERDICT r1 item 5).

    The un-chunked build materializes (nnz/shard, r, r) ≈ 48 GB per shard;
    the chunked scan needs the (n_users, r, r) accumulator (~2.7 GB) plus
    one chunk. Compile-only: no 25M-row run on the CPU mesh."""
    import jax
    from cycloneml_tpu.ml.recommendation.als import _normal_eq_local
    from cycloneml_tpu.parallel import collectives

    rt = ctx.mesh_runtime
    n_users, rank = 162_541, 64
    shards = rt.data_parallelism
    nnz = 25_000_000
    budget = 256 << 20
    shard0 = -(-nnz // shards)
    n_chunks = max(1, -(-shard0 * rank * rank * 4 // budget))
    chunk = -(-shard0 // n_chunks)
    chunk += (-chunk) % 8
    total = chunk * n_chunks * shards

    local = _normal_eq_local(n_users, rank, n_chunks, False, 1.0)
    prog = collectives.tree_aggregate(
        local, rt, np.zeros(0, np.int32), np.zeros(0, np.int32),
        np.zeros(0, np.float32), np.zeros(0, np.float32))

    S = jax.ShapeDtypeStruct
    row_sharding = rt.data_sharding(extra_axes=0)
    args = (S((total,), np.int32, sharding=row_sharding),
            S((total,), np.int32, sharding=row_sharding),
            S((total,), np.float32, sharding=row_sharding),
            S((total,), np.float32, sharding=row_sharding),
            S((n_users, rank), np.float32, sharding=rt.replicated()),
            S((rank, rank), np.float32, sharding=rt.replicated()))
    compiled = prog.lower(*args).compile()
    ma = compiled.memory_analysis()
    if ma is None or not hasattr(ma, "temp_size_in_bytes"):
        pytest.skip("memory_analysis unavailable on this backend")
    temp = int(ma.temp_size_in_bytes)
    entities_bytes = n_users * rank * rank * 4          # the accumulator
    nnz_bytes_per_shard = shard0 * rank * rank * 4      # the un-chunked blob
    assert temp < 4 * entities_bytes, (temp, entities_bytes)
    assert temp < nnz_bytes_per_shard / 3, (temp, nnz_bytes_per_shard)


@pytest.mark.parametrize("implicit", [False, True])
def test_blocked_matches_replicated(ctx, implicit):
    """Factor-sharded (blocked) ALS must match the replicated path: same
    init, same normal equations, different partitioning — the dst-sharded
    accumulator plus one src all-gather is algebraically identical to the
    replicated psum (ref ALS.scala:1605 block structure)."""
    users, items, r, _, _ = _ratings(seed=53)
    if implicit:
        r = np.abs(r)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    kw = dict(rank=3, maxIter=5, regParam=0.05, seed=4,
              implicitPrefs=implicit, alpha=0.5)
    rep = ALS(shardFactors="never", **kw).fit(frame)
    blk = ALS(shardFactors="always", **kw).fit(frame)
    np.testing.assert_allclose(blk.user_factors, rep.user_factors,
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(blk.item_factors, rep.item_factors,
                               rtol=2e-3, atol=2e-4)


def test_blocked_nonnegative_and_checkpoint(ctx, tmp_path):
    users, items, r, _, _ = _ratings(seed=54)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": np.abs(r)})
    m = ALS(rank=3, maxIter=4, regParam=0.05, seed=5, nonnegative=True,
            shardFactors="always").fit(frame)
    assert (m.user_factors >= 0).all() and (m.item_factors >= 0).all()
    # checkpointed blocked run resumes to the same factors
    ckdir = str(tmp_path / "ck")
    kw = dict(rank=3, maxIter=6, regParam=0.05, seed=6,
              shardFactors="always")
    full = ALS(**kw).fit(frame)
    ALS(maxIter=4, checkpointDir=ckdir, checkpointInterval=2,
        **{k: v for k, v in kw.items() if k != "maxIter"}
        ).set("maxIter", 4).fit(frame)
    resumed = ALS(checkpointDir=ckdir, checkpointInterval=2, **kw).fit(frame)
    np.testing.assert_allclose(resumed.user_factors, full.user_factors,
                               rtol=1e-4, atol=1e-5)


def test_auto_mode_switches_on_threshold(ctx):
    users, items, r, _, _ = _ratings(seed=55)
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    # tiny threshold forces the blocked path through "auto"
    m = ALS(rank=3, maxIter=3, regParam=0.05, seed=7,
            factorShardingThresholdBytes=64).fit(frame)
    rep = ALS(rank=3, maxIter=3, regParam=0.05, seed=7,
              shardFactors="never").fit(frame)
    np.testing.assert_allclose(m.user_factors, rep.user_factors,
                               rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_blocked_als_movielens_scale(ctx):
    """Scaled-down MovieLens-25M-shape run of the factor-sharded trainer:
    2M ratings over the full entity space at rank 16, one iteration, on the
    8-device mesh. The full-shape run (25M ratings x rank 64, explicit
    419.8 s/iter + implicit 344.0 s/iter, peak RSS ~8.5 GB on a 1-core
    driver) is recorded in BASELINE.md's round-3 ledger."""
    n_users, n_items, nnz, rank = 162_541, 62_423, 2_000_000, 16
    rng = np.random.default_rng(1)
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    r = rng.random(nnz) * 4 + 1
    frame = MLFrame(ctx, {"user": users, "item": items, "rating": r})
    m = ALS(rank=rank, maxIter=1, regParam=0.1, seed=2,
            shardFactors="always").fit(frame)
    assert m.user_factors.shape[0] == len(np.unique(users))
    assert np.isfinite(m.user_factors).all()
    assert np.isfinite(m.item_factors).all()
    # predictions on observed entries are finite and in a sane range
    pred = m.transform(frame.limit(10_000))["prediction"]
    assert np.isfinite(pred).all() and abs(float(np.mean(pred))) < 10
