"""Cross-process hash exchange (round-3 verdict item 5).

Two REAL processes shuffle keyed records to each other over TCP and
aggregate/join datasets whose combined size exceeds any single process's
row budget many times over, with bounded RSS — the host-tier analog of the
reference's ShuffleExchangeExec + ExternalSorter pipeline (tensor data
never rides this fabric; it shuffles via XLA collectives on the mesh).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

AGG_WORKER = textwrap.dedent("""
    import json, os, resource, sys
    rank, addr0, addr1, outdir = (int(sys.argv[1]), sys.argv[2],
                                  sys.argv[3], sys.argv[4])
    from cycloneml_tpu.parallel.exchange import exchange_group_by_key
    from cycloneml_tpu.dataset.spill import stable_hash
    base_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024

    N_KEYS, PER_KEY = 50_000, 8           # 400k records per worker
    VALUE = "v" * 200                      # ~200 B payload per record

    def pairs():                           # generated lazily: the dataset
        for i in range(N_KEYS * PER_KEY):  # never exists in memory at once
            yield (rank * 31 + i) % N_KEYS, VALUE

    groups = exchange_group_by_key(pairs(), rank, [addr0, addr1],
                                   n_buckets=64, row_budget=20_000)
    n_keys = n_vals = 0
    key_sum = 0
    for k, vs in groups:
        n_keys += 1
        n_vals += len(vs)
        key_sum += k
        assert all(v == VALUE for v in vs)
    peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
    with open(os.path.join(outdir, f"agg_{rank}.json"), "w") as fh:
        json.dump({"n_keys": n_keys, "n_vals": n_vals, "key_sum": key_sum,
                   "peak_mb": peak_mb, "delta_mb": peak_mb - base_mb}, fh)
""")

JOIN_WORKER = textwrap.dedent("""
    import json, os, sys
    rank, addr0, addr1, outdir = (int(sys.argv[1]), sys.argv[2],
                                  sys.argv[3], sys.argv[4])
    from cycloneml_tpu.parallel.exchange import exchange_join

    # each worker holds HALF of each side (keys interleaved by parity)
    left = [(k, f"L{k}.{rank}") for k in range(rank, 40, 2)]
    right = [(k, f"R{k}.{rank}") for k in range(rank, 60, 2) if k % 3 == 0]
    rows = sorted(exchange_join(left, right, rank, [addr0, addr1],
                                n_buckets=16, row_budget=100))
    with open(os.path.join(outdir, f"join_{rank}.json"), "w") as fh:
        json.dump(rows, fh)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_two(script, tmp_path):
    wp = tmp_path / "worker.py"
    wp.write_text(script)
    addrs = [f"localhost:{_free_port()}", f"localhost:{_free_port()}"]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(wp), str(r), addrs[0], addrs[1], str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=280)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"


def test_two_process_groupby_bounded_rss(tmp_path):
    """800k records (~160 MB with per-value payloads) shuffle between two
    processes and aggregate with a 20k-row budget (40x smaller than the
    data): every key lands on exactly one owner with all 16 values, and
    each worker's peak RSS stays far below the dataset it processed."""
    _run_two(AGG_WORKER, tmp_path)
    res = [json.load(open(tmp_path / f"agg_{r}.json")) for r in range(2)]
    # complete, disjoint ownership of the keyspace
    assert res[0]["n_keys"] + res[1]["n_keys"] == 50_000
    assert res[0]["key_sum"] + res[1]["key_sum"] == sum(range(50_000))
    assert res[0]["n_vals"] + res[1]["n_vals"] == 2 * 50_000 * 8
    # each side held ~80 MB of record payloads; bounded processing keeps
    # the RSS growth OVER the import baseline (the package import is
    # ~150 MB of numpy/jax, unrelated to data volume) at buffers + the
    # 20k-row budget — far below the data processed
    for r in res:
        assert r["delta_mb"] < 60, r


def test_two_process_inner_join(tmp_path):
    _run_two(JOIN_WORKER, tmp_path)
    rows = sorted(sum((json.load(open(tmp_path / f"join_{r}.json"))
                       for r in range(2)), []))
    rows = [(k, tuple(pair)) for k, pair in rows]
    # expected inner join computed directly
    left = [(k, f"L{k}.{k % 2}") for k in range(40)]
    right = [(k, f"R{k}.{k % 2}") for k in range(60) if k % 3 == 0]
    lmap = dict(left)
    expect = sorted((k, (lmap[k], rv)) for k, rv in right if k in lmap)
    assert rows == expect


def test_group_by_key_output_partitions_spill(ctx):
    """In-process shuffle outputs past the row budget become disk-backed
    partitions, and the RDD surface (collect/count/take) streams them."""
    from cycloneml_tpu.conf import SHUFFLE_SPILL_ROW_BUDGET
    from cycloneml_tpu.dataset.dataset import PartitionedDataset
    from cycloneml_tpu.dataset.spill import SpilledPartition

    old = ctx.conf.get(SHUFFLE_SPILL_ROW_BUDGET)
    ctx.conf.set(SHUFFLE_SPILL_ROW_BUDGET, "64")
    try:
        data = [(i % 500, i) for i in range(4000)]
        pd = PartitionedDataset.from_sequence(ctx, data, 2)
        grouped = pd.group_by_key()
        parts = grouped._partitions()
        assert any(isinstance(p, SpilledPartition) for p in parts), \
            [type(p).__name__ for p in parts]
        got = dict(grouped.collect())
        assert len(got) == 500
        assert sorted(got[7]) == list(range(7, 4000, 500))
        assert grouped.count() == 500
        assert len(grouped.take(10)) == 10
    finally:
        ctx.conf.set(SHUFFLE_SPILL_ROW_BUDGET, str(old))


def test_plan_skew_splits_rules():
    """Eligibility mirrors OptimizeSkewedJoin: threshold AND factor x
    median, per side, gated by can_split; larger side wins a tie."""
    from cycloneml_tpu.parallel.exchange import plan_skew_splits
    left = {0: 10_000, 1: 100, 2: 120, 3: 90}
    right = {0: 50_000, 1: 80, 2: 70, 3: 95}
    # both sides skewed on bucket 0: right is larger -> split side 1
    s = plan_skew_splits([left, right], (True, True), 5.0, 1000)
    assert s == {0: 1}
    # right not splittable (left join): left splits
    s = plan_skew_splits([left, right], (True, False), 5.0, 1000)
    assert s == {0: 0}
    # threshold above the hot bucket: nothing splits
    s = plan_skew_splits([left, right], (True, True), 5.0, 10**9)
    assert s == {}
    # factor too high relative to median: nothing splits
    s = plan_skew_splits([{0: 300, 1: 100, 2: 100}, {}], (True, True),
                         5.0, 0)
    assert s == {}


def test_split_bucket_label_routing():
    from cycloneml_tpu.parallel.exchange import split_bucket_label
    n_buckets, n_workers = 16, 3
    seen = set()
    for b in range(n_buckets):
        for p in range(n_workers):
            lab = split_bucket_label(b, p, n_buckets, n_workers)
            assert lab % n_workers == p  # routes to the chosen peer
            assert lab >= n_buckets      # never collides with real buckets
            assert lab not in seen
            seen.add(lab)


def test_byte_based_coalescing(tmp_path):
    """advisoryPartitionSizeInBytes semantics: list partitions merge by
    ESTIMATED bytes; a large byte target collapses small partitions, a
    tiny one keeps them apart."""
    from cycloneml_tpu.parallel.exchange import exchange_group_partitions
    # single-worker exchange: loopback address
    import socket
    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    addrs = [f"127.0.0.1:{port}"]
    pairs = [(k, k * 1.0) for k in range(64)]
    merged = exchange_group_partitions(iter(pairs), 0, addrs, 16,
                                       advisory_bytes=1 << 20)
    assert len(merged) == 1  # everything fits one 1MB-target partition
    pairs = [(k, k * 1.0) for k in range(64)]
    apart = exchange_group_partitions(iter(pairs), 0, addrs, 16,
                                      advisory_bytes=1)
    assert len(apart) == 16  # 1-byte target: no merging across buckets
