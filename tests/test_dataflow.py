"""Dataflow engine unit tests: fact lattice + summary propagation.

Pure ``ast`` like the rest of graftlint's tests — modules are written to
tmp files, parsed through the real loader, and the real clients' (rule
transfer functions') converged summaries are inspected directly. The
fixture-based precision tests live in test_graftlint.py; this file pins
the ENGINE: lattice laws, widening termination, multi-hop propagation,
recursion, and the caller-requeue worklist.
"""

import ast
import os
import textwrap

import pytest

from cycloneml_tpu.analysis.dataflow import (EMPTY, SET_WIDEN_LIMIT, TOP,
                                             CallGraph, JitParams,
                                             join_bools, join_sets,
                                             jit_params_of_function,
                                             module_program_bindings,
                                             parse_jit_params, run_dataflow,
                                             set_contains)
from cycloneml_tpu.analysis.engine import (AnalysisContext, analyze_paths,
                                           load_module)
from cycloneml_tpu.analysis.reachability import (CallResolver,
                                                 compute_reachability)


# -- lattice laws -------------------------------------------------------------

def test_join_sets_laws():
    a = frozenset({1, 2})
    b = frozenset({2, 3})
    c = frozenset({4})
    # commutative, associative, idempotent
    assert join_sets(a, b) == join_sets(b, a) == frozenset({1, 2, 3})
    assert join_sets(join_sets(a, b), c) == join_sets(a, join_sets(b, c))
    assert join_sets(a, a) == a
    # EMPTY is the identity
    assert join_sets(a, EMPTY) == a


def test_join_sets_top_absorbs():
    a = frozenset({1})
    assert join_sets(a, TOP) is TOP
    assert join_sets(TOP, a) is TOP
    assert join_sets(TOP, TOP) is TOP


def test_join_sets_widens_past_limit():
    a = frozenset(range(SET_WIDEN_LIMIT))
    assert join_sets(a, EMPTY) == a            # at the limit: exact
    widened = join_sets(a, frozenset({SET_WIDEN_LIMIT}))
    assert widened is TOP                       # one past: widened


def test_widening_chain_terminates():
    """Monotone join chains reach a fixed point within the bound: after
    widening to TOP every further join is TOP (no infinite ascent)."""
    acc = EMPTY
    seen = set()
    for i in range(SET_WIDEN_LIMIT * 3):
        acc = join_sets(acc, frozenset({i}))
        key = "TOP" if acc is TOP else acc
        if key in ("TOP",):
            break
    assert acc is TOP
    assert join_sets(acc, frozenset({99})) is TOP


def test_set_contains_under_top():
    assert set_contains(TOP, 7)
    assert set_contains(frozenset({7}), 7)
    assert not set_contains(frozenset({7}), 8)
    assert join_bools(False, True) and not join_bools(False, False)


# -- jit-call parsing ---------------------------------------------------------

def _parse_call(src: str) -> ast.Call:
    return ast.parse(src).body[0].value


def test_parse_jit_params_literals():
    jp = parse_jit_params(_parse_call(
        "jax.jit(f, static_argnums=(1, 2), donate_argnums=0)"))
    assert jp.static_argnums == frozenset({1, 2})
    assert jp.donate_argnums == frozenset({0})
    assert jp.statics_known


def test_parse_jit_params_nonliteral_degrades():
    jp = parse_jit_params(_parse_call("jax.jit(f, static_argnums=nums)"))
    assert not jp.statics_known
    assert jp.static_argnums == frozenset()


def test_parse_jit_params_static_argnames():
    jp = parse_jit_params(_parse_call(
        'jax.jit(f, static_argnames=("k", "width"))'))
    assert jp.static_argnames == frozenset({"k", "width"})


# -- engine propagation -------------------------------------------------------

def _modules_from(tmp_path, sources):
    modules = {}
    for name, src in sources.items():
        p = tmp_path / name
        p.write_text(textwrap.dedent(src))
        mod = load_module(str(p), name)
        assert mod is not None
        modules[name] = mod
    resolver = CallResolver(modules)
    compute_reachability(modules, resolver)
    return modules, CallGraph(modules, resolver)


def _converge(modules, graph, rule):
    ctx = AnalysisContext(modules=modules, callgraph=graph)
    result = run_dataflow(graph, [rule], ctx)
    ctx.dataflow = result
    return ctx, result


def _fn(modules, path, qualname):
    for fn in modules[path].functions:
        if fn.qualname == qualname:
            return fn
    raise AssertionError(f"{qualname} not in {path}")


DONATE_CHAIN = """
    import jax

    def _update(state, x):
        return state * 0.9 + x

    _step = jax.jit(_update, donate_argnums=(0,))

    def level1(state, x):
        return _step(state, x)

    def level2(state, x):
        return level1(state, x)

    def level3(state, x):
        return level2(state, x)
"""


def test_donation_summary_propagates_three_hops(tmp_path):
    from cycloneml_tpu.analysis.rules.jx009_use_after_donate import \
        UseAfterDonateRule
    modules, graph = _modules_from(tmp_path, {"m.py": DONATE_CHAIN})
    rule = UseAfterDonateRule()
    _, result = _converge(modules, graph, rule)
    for name in ("level1", "level2", "level3"):
        summary = result.summary("JX009", _fn(modules, "m.py", name))
        assert set_contains(summary, 0), f"{name} should donate param 0"
        assert not set_contains(summary, 1), f"{name} param 1 is not donated"


def test_recursive_functions_converge(tmp_path):
    """Mutual recursion must reach a fixpoint, not loop: neither function
    donates anything, and the engine terminates."""
    from cycloneml_tpu.analysis.rules.jx009_use_after_donate import \
        UseAfterDonateRule
    src = """
        def ping(x, n):
            if n <= 0:
                return x
            return pong(x, n - 1)

        def pong(x, n):
            return ping(x, n - 1)
    """
    modules, graph = _modules_from(tmp_path, {"r.py": src})
    rule = UseAfterDonateRule()
    _, result = _converge(modules, graph, rule)
    assert result.summary("JX009", _fn(modules, "r.py", "ping")) == EMPTY
    assert result.summary("JX009", _fn(modules, "r.py", "pong")) == EMPTY


def test_collective_reach_propagates_and_divergent_returns(tmp_path):
    from cycloneml_tpu.analysis.rules.jx010_collective_divergence import \
        CollectiveDivergenceRule
    src = """
        import jax
        import time

        def _reduce(x):
            return jax.lax.psum(x, "data")

        def outer(x):
            return _reduce(x)

        def harmless(x):
            return x + 1

        def _is_primary():
            return jax.process_index() == 0

        def primary_wrapper():
            return _is_primary()
    """
    modules, graph = _modules_from(tmp_path, {"c.py": src})
    rule = CollectiveDivergenceRule()
    _, result = _converge(modules, graph, rule)
    reaches = lambda n: result.summary(
        "JX010", _fn(modules, "c.py", n))[0]
    divergent = lambda n: result.summary(
        "JX010", _fn(modules, "c.py", n))[1]
    assert reaches("_reduce") and reaches("outer")
    assert not reaches("harmless")
    assert divergent("_is_primary") and divergent("primary_wrapper")
    assert not divergent("outer")


def test_narrow_return_chain(tmp_path):
    from cycloneml_tpu.analysis.rules.jx004_fp64_drift import FP64DriftRule
    src = """
        import jax.numpy as jnp

        def to_storage(x):
            return x.astype(jnp.bfloat16)

        def passthrough(x):
            return to_storage(x)

        def widened(x):
            y = to_storage(x)
            y = y.astype(jnp.float32)
            return y
    """
    modules, graph = _modules_from(tmp_path, {"n.py": src})
    rule = FP64DriftRule()
    _, result = _converge(modules, graph, rule)
    assert result.summary("JX004", _fn(modules, "n.py", "to_storage"))
    assert result.summary("JX004", _fn(modules, "n.py", "passthrough"))
    assert not result.summary("JX004", _fn(modules, "n.py", "widened"))


def test_recompile_sinks_cross_module(tmp_path):
    """static/shape sink positions propagate through a wrapper that lives
    in ANOTHER module (from-import edge)."""
    from cycloneml_tpu.analysis.rules.jx008_recompile import \
        RecompileHazardRule
    kernel = """
        import jax

        def _kernel(x, k):
            return x * k

        prog = jax.jit(_kernel, static_argnums=(1,))

        def run_one(x, k):
            return prog(x, k)
    """
    driver = """
        from kernel import run_one

        def sweep(x, n):
            return [run_one(x, i) for i in range(n)]
    """
    modules, graph = _modules_from(
        tmp_path, {"kernel.py": kernel, "driver.py": driver})
    rule = RecompileHazardRule()
    ctx, result = _converge(modules, graph, rule)
    vk, sk = result.summary("JX008", _fn(modules, "kernel.py", "run_one"))
    assert set_contains(vk, 1), "k lands in prog's static position"
    assert set_contains(sk, 0), "x flows whole into a traced position"
    # ... and end-to-end the comprehension in the OTHER module's driver
    # is recognized as a loop feeding that static position
    findings = [f for f in analyze_paths([str(tmp_path / "kernel.py"),
                                          str(tmp_path / "driver.py")])
                if f.rule == "JX008"]
    assert [(f.path.endswith("driver.py"), f.function)
            for f in findings] == [(True, "sweep")]


def test_module_program_bindings(tmp_path):
    src = """
        import jax

        def f(a, b):
            return a + b

        step = jax.jit(f, donate_argnums=(0,), static_argnums=(1,))
        agg = ds.tree_aggregate_fn(f)
    """
    modules, _ = _modules_from(tmp_path, {"b.py": src})
    table = module_program_bindings(modules["b.py"])
    assert table["step"].donate_argnums == frozenset({0})
    assert table["step"].static_argnums == frozenset({1})
    assert table["agg"] == JitParams()


def test_jit_params_of_decorated_function(tmp_path):
    src = """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
        def stepper(state, k):
            return state * k

        def plain(x):
            return x
    """
    modules, _ = _modules_from(tmp_path, {"d.py": src})
    jp = jit_params_of_function(_fn(modules, "d.py", "stepper"))
    assert jp is not None
    assert jp.static_argnums == frozenset({1})
    assert jp.donate_argnums == frozenset({0})
    assert jit_params_of_function(_fn(modules, "d.py", "plain")) is None


def test_callgraph_reverse_edges(tmp_path):
    src = """
        def leaf(x):
            return x

        def a(x):
            return leaf(x)

        def b(x):
            return leaf(x) + a(x)
    """
    modules, graph = _modules_from(tmp_path, {"g.py": src})
    leaf = _fn(modules, "g.py", "leaf")
    callers = {fn.qualname for fn in graph.callers_of(leaf)}
    assert callers == {"a", "b"}


def test_param_map_handles_methods_and_kwargs(tmp_path):
    src = """
        class Fitter:
            def fit(self, data, weights):
                return data

            def run(self, d):
                return self.fit(d, weights=None)
    """
    modules, graph = _modules_from(tmp_path, {"mm.py": src})
    run = _fn(modules, "mm.py", "Fitter.run")
    (site,) = [s for s in graph.sites(run) if s.name == "self.fit"]
    (target,) = site.targets
    mapping = dict(site.param_map(target))
    # d lands at param index 1 (after self); weights kwarg at index 2
    assert isinstance(mapping[1], ast.Name) and mapping[1].id == "d"
    assert 2 in mapping


def test_interprocedural_finding_lands_in_unchanged_caller(tmp_path):
    """The --changed contract: facts come from the WHOLE file set even
    when only some files are checked — a hazard whose pieces live in two
    files is still caught when only the caller's file is in the check
    set."""
    helper = """
        import jax

        def _update(state, x):
            return state * 0.9 + x

        _step = jax.jit(_update, donate_argnums=(0,))

        def advance(state, x):
            return _step(state, x)
    """
    caller = """
        from helper import advance

        def driver(state, x):
            out = advance(state, x)
            return out + state.sum()
    """
    (tmp_path / "helper.py").write_text(textwrap.dedent(helper))
    (tmp_path / "caller.py").write_text(textwrap.dedent(caller))
    # pass the files directly: module keys are then "helper.py" /
    # "caller.py", matching the `from helper import ...` edge the same
    # way package-rooted paths do in the real tree
    files = [str(tmp_path / "helper.py"), str(tmp_path / "caller.py")]
    findings = analyze_paths(files, only_paths={"caller.py"})
    assert [f.rule for f in findings] == ["JX009"]
    assert findings[0].path.endswith("caller.py")
    # the check set widens over REVERSE call edges: changing only the
    # HELPER (the donation's home) must still surface the finding in its
    # untouched caller — otherwise `--changed` green-lights a change
    # that introduces a use-after-donate two frames away
    findings = analyze_paths(files, only_paths={"helper.py"})
    assert [f.rule for f in findings] == ["JX009"]
    assert findings[0].path.endswith("caller.py")


# -- lockset entry summaries (JX011, the down-direction analysis) -------------

LOCK_CHAIN = """
    import threading


    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._data = {}

        def put(self, k, v):
            with self._lock:
                self._mid(k, v)

        def _mid(self, k, v):
            self._leaf(k, v)

        def _leaf(self, k, v):
            self._data[k] = v

        def racy_size(self):
            return len(self._data)
"""


def test_lockset_entry_summary_propagates_two_hops(tmp_path):
    """JX011's locks-held-at-entry is a DOWN-direction must-analysis: the
    lock taken in `put` reaches `_leaf` through the 2-hop helper chain
    (put -> _mid -> _leaf), so the write in `_leaf` counts as guarded."""
    from cycloneml_tpu.analysis.rules.jx011_lockset_race import \
        LocksetRaceRule
    modules, graph = _modules_from(tmp_path, {"locks.py": LOCK_CHAIN})
    rule = LocksetRaceRule()
    _, result = _converge(modules, graph, rule)
    held = frozenset({"Store._lock"})
    assert result.summary("JX011", _fn(modules, "locks.py",
                                       "Store._mid")) == held
    assert result.summary("JX011", _fn(modules, "locks.py",
                                       "Store._leaf")) == held
    # `put` itself is an entry point: nothing guaranteed at ITS entry
    assert result.summary("JX011", _fn(modules, "locks.py",
                                       "Store.put")) == EMPTY


def test_lockset_two_hop_guard_drives_the_inference(tmp_path):
    """End-to-end: `_leaf`'s 2-hop-guarded write is the majority evidence
    that `_data` is lock-guarded — which is exactly what convicts the
    unguarded `racy_size` read. If entry propagation broke, there would
    be NO guarded access and the rule would stay silent."""
    p = tmp_path / "locks.py"
    p.write_text(textwrap.dedent(LOCK_CHAIN))
    findings = [f for f in analyze_paths([str(p)]) if f.rule == "JX011"]
    assert len(findings) == 1
    assert findings[0].function == "Store.racy_size"
