"""RowMatrix tests (BASELINE config 5 family): Gramian/covariance/PCA/SVD
parity vs numpy/scipy, Lanczos path vs full eigh."""

import numpy as np
import pytest

from cycloneml_tpu.linalg.distributed import RowMatrix


def _rm(ctx, n=200, d=12, seed=41):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d) @ np.diag(np.linspace(3, 0.2, d))
    return RowMatrix.from_numpy(ctx, x), x


def test_gramian(ctx):
    rm, x = _rm(ctx)
    np.testing.assert_allclose(rm.compute_gramian().to_array(), x.T @ x,
                               rtol=1e-10)


def test_covariance(ctx):
    rm, x = _rm(ctx, seed=42)
    np.testing.assert_allclose(rm.compute_covariance().to_array(),
                               np.cov(x, rowvar=False), rtol=1e-8, atol=1e-10)


def test_pca_vs_numpy(ctx):
    rm, x = _rm(ctx, seed=43)
    pcs, var = rm.compute_principal_components_and_variance(3)
    cov = np.cov(x, rowvar=False)
    vals, vecs = np.linalg.eigh(cov)
    order = np.argsort(vals)[::-1]
    ref_var = vals[order] / vals.sum()
    np.testing.assert_allclose(var.to_array(), ref_var[:3], rtol=1e-8)
    for j in range(3):
        ref = vecs[:, order[j]]
        got = pcs.to_array()[:, j]
        assert abs(abs(ref @ got) - 1.0) < 1e-8  # same subspace direction


def test_svd_small_matches_numpy(ctx):
    rm, x = _rm(ctx, seed=44)
    res = rm.compute_svd(5, compute_u=True)
    u_np, s_np, vt_np = np.linalg.svd(x, full_matrices=False)
    np.testing.assert_allclose(res.s.to_array(), s_np[:5], rtol=1e-8)
    # V columns span the same directions
    v = res.V.to_array()
    for j in range(5):
        assert abs(abs(vt_np[j] @ v[:, j]) - 1.0) < 1e-8
    # rank-5 reconstruction matches numpy's rank-5 truncation
    u = res.U.to_numpy()[:, : len(res.s)]
    recon = u @ np.diag(res.s.to_array()) @ v.T
    ref_recon = u_np[:, :5] @ np.diag(s_np[:5]) @ vt_np[:5]
    np.testing.assert_allclose(recon, ref_recon, atol=1e-7)


def test_svd_lanczos_path(ctx):
    rm, x = _rm(ctx, n=100, d=30, seed=45)
    res = rm.compute_svd(4, max_gram_dim=8)  # force Lanczos
    s_np = np.linalg.svd(x, compute_uv=False)
    np.testing.assert_allclose(res.s.to_array(), s_np[:4], rtol=1e-6)


def test_multiply(ctx):
    from cycloneml_tpu.linalg.matrices import Matrices
    rm, x = _rm(ctx, seed=46)
    b = np.random.RandomState(0).randn(x.shape[1], 4)
    out = rm.multiply(Matrices.from_array(b))
    np.testing.assert_allclose(out.to_numpy(), x @ b, rtol=1e-8, atol=1e-9)


def test_column_similarities(ctx):
    rm, x = _rm(ctx, seed=47)
    sim = rm.column_similarities().to_array()
    d = x.shape[1]
    for i in range(d):
        for j in range(i + 1, d):
            ref = x[:, i] @ x[:, j] / np.linalg.norm(x[:, i]) / np.linalg.norm(x[:, j])
            assert sim[i, j] == pytest.approx(ref, rel=1e-8)
    assert np.allclose(np.tril(sim), 0.0)


def test_svd_rcond_truncates_rank(ctx):
    rng = np.random.RandomState(48)
    base = rng.randn(100, 3)
    x = np.hstack([base, base @ rng.randn(3, 3)])  # rank 3 in 6 cols
    rm = RowMatrix.from_numpy(ctx, x)
    res = rm.compute_svd(6, r_cond=1e-6)
    assert len(res.s) == 3




def test_sparse_rowmatrix_svd_and_gramian(ctx):
    """Sparse-tier RowMatrix (BASELINE config 5 path): ELL-backed Lanczos
    singular values match scipy.sparse svds on the same matrix, and the
    small-d sparse Gramian matches the densified oracle."""
    import scipy.sparse as sp
    import scipy.sparse.linalg as spla
    from cycloneml_tpu.dataset.sparse import SparseInstanceDataset

    rng = np.random.RandomState(7)
    n, d, nnz_per_row = 400, 120, 12
    indices = np.stack([rng.choice(d, nnz_per_row, replace=False)
                        for _ in range(n)]).astype(np.int32)
    values = rng.rand(n, nnz_per_row).astype(np.float32) + 0.1
    csr = sp.csr_matrix(
        (values.reshape(-1),
         (np.repeat(np.arange(n), nnz_per_row), indices.reshape(-1))),
        shape=(n, d))

    ds = SparseInstanceDataset.from_ell(ctx, indices, values, n_features=d)
    rm = RowMatrix(ds)

    # gramian (small-d densify path)
    g = rm.compute_gramian().to_array()
    np.testing.assert_allclose(g, (csr.T @ csr).toarray(), rtol=1e-4,
                               atol=1e-4)

    # Lanczos path (force it with max_gram_dim=1)
    k = 5
    res = rm.compute_svd(k, max_gram_dim=1)
    got = res.s.to_array()
    want = np.sort(spla.svds(csr.astype(np.float64), k=k,
                             return_singular_vectors=False))[::-1]
    np.testing.assert_allclose(got, want, rtol=1e-6)

    # hybrid tier rides the same operator
    rows = [(indices[i], values[i]) for i in range(n)]
    hyb = SparseInstanceDataset.from_rows_hybrid(ctx, rows, n_features=d,
                                                 k_ell=6)
    res_h = RowMatrix(hyb).compute_svd(k, max_gram_dim=1)
    np.testing.assert_allclose(res_h.s.to_array(), want, rtol=1e-6)
    # default max_gram_dim takes the small-d GRAMIAN branch — hybrid
    # densify must serve it too (review r4)
    res_g = RowMatrix(hyb).compute_svd(k)
    np.testing.assert_allclose(res_g.s.to_array(), want, rtol=1e-6)
