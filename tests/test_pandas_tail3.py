"""pandas-API long tail, tranche 3 (round-4 verdict item 6 / r5 continuation):
frame & series reductions, rank/quantile/corr/cov, cumulative ops,
shift/diff/pct_change, where/mask/isin/clip, nlargest, duplicated/
drop_duplicates, melt/stack/transpose/join/combine_first, groupby
transform/shift/rank/cumcount/ngroup/filter/size, get_dummies/cut/qcut/
crosstab — every case checked against REAL pandas (3.x semantics).

Ref surface: python/pyspark/pandas/frame.py, series.py, groupby.py,
namespace.py.
"""

import numpy as np
import pandas as pd
import pytest

import cycloneml_tpu.pandas as cp
from cycloneml_tpu.pandas import (CycloneFrame, CycloneSeries, crosstab,
                                  cut, get_dummies, melt, qcut)


@pytest.fixture()
def num():
    data = {"a": [3.0, 1.0, np.nan, 7.0, 5.0],
            "b": [10, 40, 30, 20, 50],
            "c": [1.5, -2.5, 3.5, -4.5, 5.5]}
    return CycloneFrame(dict(data)), pd.DataFrame(data)


@pytest.fixture()
def grouped():
    data = {"k": ["x", "y", "x", "y", "x", "z"],
            "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0],
            "w": [10, 20, 30, 40, 50, 60]}
    return CycloneFrame(dict(data)), pd.DataFrame(data)


def _ser_eq(cs, ps, **kw):
    np.testing.assert_allclose(np.asarray(cs.values, dtype=np.float64),
                               ps.to_numpy(dtype=np.float64), **kw)


# -- series transforms -------------------------------------------------------

def test_series_cumulative_nan_skipping(num):
    cf, pdf = num
    for op in ("cumsum", "cumprod", "cummax", "cummin"):
        _ser_eq(getattr(cf["a"], op)(), getattr(pdf["a"], op)())


def test_series_shift_diff_pct_change(num):
    cf, pdf = num
    _ser_eq(cf["b"].shift(1), pdf["b"].shift(1))
    _ser_eq(cf["b"].shift(-2), pdf["b"].shift(-2))
    _ser_eq(cf["b"].shift(1, fill_value=0), pdf["b"].shift(1, fill_value=0))
    _ser_eq(cf["b"].diff(), pdf["b"].diff())
    _ser_eq(cf["b"].pct_change(), pdf["b"].pct_change())


def test_series_rank_methods(num):
    cf, pdf = num
    v = CycloneSeries([3.0, 1.0, 3.0, np.nan, 2.0, 3.0])
    p = pd.Series([3.0, 1.0, 3.0, np.nan, 2.0, 3.0])
    for m in ("average", "min", "max", "dense", "first"):
        _ser_eq(v.rank(method=m), p.rank(method=m))
    _ser_eq(v.rank(ascending=False), p.rank(ascending=False))


def test_series_quantile_median_var(num):
    cf, pdf = num
    assert cf["a"].quantile(0.25) == pdf["a"].quantile(0.25)
    assert cf["a"].median() == pdf["a"].median()
    assert np.isclose(cf["c"].var(), pdf["c"].var())
    assert np.isclose(cf["c"].prod(), pdf["c"].prod())


def test_series_idx_any_all_between_isin(num):
    cf, pdf = num
    assert cf["a"].idxmax() == pdf["a"].idxmax()
    assert cf["a"].idxmin() == pdf["a"].idxmin()
    assert (cf["b"] > 25).any() == (pdf["b"] > 25).any()
    assert (cf["b"] > 25).all() == (pdf["b"] > 25).all()
    _ser_eq(cf["b"].between(20, 40), pdf["b"].between(20, 40))
    _ser_eq(cf["b"].between(20, 40, inclusive="left"),
            pdf["b"].between(20, 40, inclusive="left"))
    _ser_eq(cf["b"].isin([10, 50]), pdf["b"].isin([10, 50]))


def test_series_where_mask_clip_round_abs(num):
    cf, pdf = num
    _ser_eq(cf["c"].where(cf["c"] > 0), pdf["c"].where(pdf["c"] > 0))
    _ser_eq(cf["c"].mask(cf["c"] > 0, 0.0), pdf["c"].mask(pdf["c"] > 0, 0.0))
    _ser_eq(cf["c"].clip(-2, 3), pdf["c"].clip(-2, 3))
    _ser_eq(cf["c"].abs(), pdf["c"].abs())
    _ser_eq(cf["c"].round(0), pdf["c"].round(0))


def test_series_nlargest_nsmallest_sort_mode():
    v = [5.0, 1.0, np.nan, 5.0, 3.0, 2.0]
    cs, ps = CycloneSeries(v), pd.Series(v)
    _ser_eq(cs.nlargest(3), ps.nlargest(3))
    np.testing.assert_array_equal(cs.nlargest(3).index,
                                  ps.nlargest(3).index.to_numpy())
    _ser_eq(cs.nsmallest(2), ps.nsmallest(2))
    _ser_eq(cs.sort_values(), ps.sort_values().dropna(axis=0, how="all")
            if False else ps.sort_values())
    m = CycloneSeries([2, 1, 2, 3, 3]).mode()
    np.testing.assert_array_equal(m.values,
                                  pd.Series([2, 1, 2, 3, 3]).mode())


def test_series_duplicated_corr_cov():
    v = ["a", "b", "a", "c", "b", "a"]
    cs, ps = CycloneSeries(v), pd.Series(v)
    for keep in ("first", "last", False):
        _ser_eq(cs.duplicated(keep), ps.duplicated(keep))
    np.testing.assert_array_equal(cs.drop_duplicates().values,
                                  ps.drop_duplicates().to_numpy())
    a = [1.0, 2.0, np.nan, 4.0, 5.0]
    b = [2.0, 4.0, 5.0, np.nan, 9.0]
    assert np.isclose(CycloneSeries(a).corr(CycloneSeries(b)),
                      pd.Series(a).corr(pd.Series(b)))
    assert np.isclose(CycloneSeries(a).cov(CycloneSeries(b)),
                      pd.Series(a).cov(pd.Series(b)))


# -- frame reductions & transforms -------------------------------------------

def test_frame_reductions(num):
    cf, pdf = num
    for fn in ("sum", "mean", "std", "var", "median", "min", "max"):
        got = getattr(cf, fn)()
        want = getattr(pdf, fn)()
        np.testing.assert_array_equal(got.index, want.index.to_numpy())
        _ser_eq(got, want)
    _ser_eq(cf.nunique(), pdf.nunique())
    _ser_eq(cf.quantile(0.5), pdf.quantile(0.5))


def test_frame_idxmax_any_all(num):
    cf, pdf = num
    np.testing.assert_array_equal(cf.idxmax().values,
                                  pdf.idxmax().to_numpy())
    np.testing.assert_array_equal(cf.idxmin().values,
                                  pdf.idxmin().to_numpy())
    mask_c, mask_p = cf[["b"]] , pdf[["b"]]
    _ser_eq((cf[["b", "c"]] ).any(), (pdf[["b", "c"]] != 0).any()) \
        if False else None
    got = CycloneFrame({"x": [True, False], "y": [True, True]})
    want = pd.DataFrame({"x": [True, False], "y": [True, True]})
    _ser_eq(got.any(), want.any())
    _ser_eq(got.all(), want.all())


def test_frame_elementwise(num):
    cf, pdf = num
    for args in (("abs",), ("round", 0), ("cumsum",), ("cummax",),
                 ("cummin",), ("diff",), ("shift", 1), ("rank",)):
        got = getattr(cf, args[0])(*args[1:])
        want = getattr(pdf, args[0])(*args[1:])
        for c in cf.columns:
            _ser_eq(got[c], want[c])
    got = cf.clip(-1, 20)
    want = pdf.clip(-1, 20)
    for c in cf.columns:
        _ser_eq(got[c], want[c])


def test_frame_where_mask_isin(num):
    cf, pdf = num
    got = cf[["b", "c"]].where(CycloneFrame({"b": [True] * 5,
                                             "c": [False] * 5}))
    want = pdf[["b", "c"]].where(pd.DataFrame({"b": [True] * 5,
                                               "c": [False] * 5}))
    for c in ("b", "c"):
        _ser_eq(got[c], want[c])
    got = cf.isin({"b": [10, 20]})
    want = pdf.isin({"b": [10, 20]})
    for c in cf.columns:
        _ser_eq(got[c], want[c])


def test_frame_nlargest_dedup(num):
    cf, pdf = num
    np.testing.assert_array_equal(cf.nlargest(3, "b")["b"].values,
                                  pdf.nlargest(3, "b")["b"].to_numpy())
    np.testing.assert_array_equal(cf.nsmallest(2, ["b", "c"])["b"].values,
                                  pdf.nsmallest(2, ["b", "c"])["b"].to_numpy())
    d = {"k": ["a", "b", "a", "a"], "v": [1, 2, 1, 3]}
    cdup, pdup = CycloneFrame(dict(d)), pd.DataFrame(d)
    for keep in ("first", "last", False):
        _ser_eq(cdup.duplicated(keep=keep), pdup.duplicated(keep=keep))
        _ser_eq(cdup.duplicated(subset="k", keep=keep),
                pdup.duplicated(subset="k", keep=keep))
    np.testing.assert_array_equal(
        cdup.drop_duplicates(subset=["k"])["v"].values,
        pdup.drop_duplicates(subset=["k"])["v"].to_numpy())


def test_frame_corr_cov(num):
    cf, pdf = num
    got, want = cf.corr(), pdf.corr()
    for c in got.columns:
        _ser_eq(got[c], want[c], atol=1e-12)
    got, want = cf.cov(), pdf.cov()
    for c in got.columns:
        _ser_eq(got[c], want[c], atol=1e-12)


# -- reshaping ---------------------------------------------------------------

def test_melt(grouped):
    cf, pdf = grouped
    got = cf.melt(id_vars="k")
    want = pdf.melt(id_vars="k")
    assert got.columns == list(want.columns)
    np.testing.assert_array_equal(got["variable"].values,
                                  want["variable"].to_numpy())
    np.testing.assert_array_equal(got["value"].values.astype(np.float64),
                                  want["value"].to_numpy(dtype=np.float64))
    got2 = melt(cf, id_vars=["k"], value_vars=["v"], var_name="var",
                value_name="val")
    want2 = pd.melt(pdf, id_vars=["k"], value_vars=["v"], var_name="var",
                    value_name="val")
    assert got2.columns == list(want2.columns)
    np.testing.assert_array_equal(got2["val"].values,
                                  want2["val"].to_numpy())


def test_stack_transpose(num):
    cf, pdf = num
    got = cf.stack()
    want = pdf.stack()
    np.testing.assert_allclose(got.values.astype(np.float64),
                               want.to_numpy(dtype=np.float64))
    assert list(got.index) == list(want.index)
    t_got, t_want = cf.T, pdf.T
    assert list(t_got.columns) == list(t_want.columns)
    np.testing.assert_array_equal(t_got.index, t_want.index.to_numpy())
    np.testing.assert_allclose(
        np.asarray(t_got[1].values, dtype=np.float64),
        t_want[1].to_numpy(dtype=np.float64))


def test_join_on_index():
    left = CycloneFrame({"k": ["a", "b", "c"], "x": [1, 2, 3]}
                        ).set_index("k")
    right = CycloneFrame({"k": ["a", "c", "d"], "y": [10, 30, 40]}
                         ).set_index("k")
    pl = pd.DataFrame({"k": ["a", "b", "c"], "x": [1, 2, 3]}
                      ).set_index("k")
    pr = pd.DataFrame({"k": ["a", "c", "d"], "y": [10, 30, 40]}
                      ).set_index("k")
    for how in ("left", "inner", "outer"):
        got = left.join(right, how=how).sort_index()
        want = pl.join(pr, how=how).sort_index()
        np.testing.assert_array_equal(got.index, want.index.to_numpy())
        _ser_eq(got["y"], want["y"])
    # overlapping columns demand suffixes
    with pytest.raises(ValueError):
        left.join(CycloneFrame({"k": ["a"], "x": [9]}).set_index("k"))
    got = left.join(CycloneFrame({"k": ["a", "b", "c"], "x": [7, 8, 9]}
                                 ).set_index("k"), lsuffix="_l",
                    rsuffix="_r")
    want = pl.join(pd.DataFrame({"k": ["a", "b", "c"], "x": [7, 8, 9]}
                                ).set_index("k"), lsuffix="_l",
                   rsuffix="_r")
    assert got.columns == list(want.columns)


def test_combine_first():
    a = CycloneFrame({"k": ["a", "b"], "x": [1.0, np.nan]}).set_index("k")
    b = CycloneFrame({"k": ["b", "c"], "x": [5.0, 6.0]}).set_index("k")
    pa = pd.DataFrame({"k": ["a", "b"], "x": [1.0, np.nan]}).set_index("k")
    pb = pd.DataFrame({"k": ["b", "c"], "x": [5.0, 6.0]}).set_index("k")
    got = a.combine_first(b)
    want = pa.combine_first(pb)
    np.testing.assert_array_equal(got.index, want.index.to_numpy())
    _ser_eq(got["x"], want["x"])


def test_conveniences(num):
    cf, pdf = num
    assert cf.copy().equals(cf)
    assert not cf.equals(cf.drop(["a"]))
    c2, p2 = cf.copy(), pdf.copy()
    s_got, s_want = c2.pop("b"), p2.pop("b")
    np.testing.assert_array_equal(s_got.values, s_want.to_numpy())
    assert c2.columns == list(p2.columns)
    c2.insert(0, "z", [9, 9, 9, 9, 9])
    p2.insert(0, "z", [9, 9, 9, 9, 9])
    assert c2.columns == list(p2.columns)
    assert cf.add_prefix("p_").columns == list(pdf.add_prefix("p_").columns)
    assert cf.add_suffix("_s").columns == list(pdf.add_suffix("_s").columns)
    assert len(cf.sample(3, random_state=0)) == 3
    assert len(cf.sample(frac=0.4, random_state=1)) == 2


# -- groupby tranche ---------------------------------------------------------

def test_groupby_scalar_aggs(grouped):
    cf, pdf = grouped
    for fn in ("std", "var", "median", "nunique", "first", "last"):
        got = getattr(cf.groupby("k"), fn)()
        want = getattr(pdf.groupby("k")[["v", "w"]], fn)()
        np.testing.assert_array_equal(got.index, want.index.to_numpy())
        for c in ("v", "w"):
            _ser_eq(got[c], want[c])
    got = cf.groupby("k").size()
    want = pdf.groupby("k").size()
    np.testing.assert_array_equal(got.index, want.index.to_numpy())
    _ser_eq(got, want)


def test_groupby_row_shaped(grouped):
    cf, pdf = grouped
    g_c, g_p = cf.groupby("k"), pdf.groupby("k")
    _ser_eq(g_c.transform("mean")["v"], g_p["v"].transform("mean"))
    _ser_eq(g_c.transform(np.max)["v"], g_p["v"].transform("max"))
    _ser_eq(g_c.cumsum()["v"], g_p["v"].cumsum())
    _ser_eq(g_c.shift(1)["v"], g_p["v"].shift(1))
    _ser_eq(g_c.rank()["v"], g_p["v"].rank())
    _ser_eq(g_c.cumcount(), g_p.cumcount())
    _ser_eq(g_c.ngroup(), g_p.ngroup())


def test_groupby_filter_head(grouped):
    cf, pdf = grouped
    got = cf.groupby("k").filter(lambda f: f["v"].sum() > 6)
    want = pdf.groupby("k").filter(lambda f: f["v"].sum() > 6)
    np.testing.assert_array_equal(got["v"].values, want["v"].to_numpy())
    got = cf.groupby("k").head(1)
    want = pdf.groupby("k").head(1)
    np.testing.assert_array_equal(got["v"].values, want["v"].to_numpy())


# -- encodings / binning -----------------------------------------------------

def test_get_dummies_series_and_frame(grouped):
    cf, pdf = grouped
    got = get_dummies(cf["k"])
    want = pd.get_dummies(pdf["k"])
    assert got.columns == list(want.columns)
    for c in got.columns:
        _ser_eq(got[c], want[c])
    got = get_dummies(cf)
    want = pd.get_dummies(pdf)
    assert got.columns == list(want.columns)
    for c in ("k_x", "k_y", "k_z"):
        _ser_eq(got[c], want[c])


def test_cut_qcut_codes():
    v = [1.0, 4.0, 6.0, 9.0, 2.0, 7.0]
    got = cut(CycloneSeries(v), [0, 3, 6, 10], labels=False)
    want = pd.cut(pd.Series(v), [0, 3, 6, 10], labels=False)
    np.testing.assert_array_equal(got.values, want.to_numpy())
    got = cut(CycloneSeries(v), 3, labels=False)
    want = pd.cut(pd.Series(v), 3, labels=False)
    np.testing.assert_array_equal(got.values, want.to_numpy())
    # custom labels
    got = cut(CycloneSeries(v), [0, 5, 10], labels=["lo", "hi"])
    want = pd.cut(pd.Series(v), [0, 5, 10], labels=["lo", "hi"])
    np.testing.assert_array_equal(got.values.astype(object),
                                  want.astype(object).to_numpy())
    # value AT the leftmost edge of a right-closed binning falls out
    got = cut(CycloneSeries([0.0, 1.0]), [0, 1], labels=False)
    assert got.values[0] == -1 and got.values[1] == 0
    rng = np.random.RandomState(0)
    x = rng.randn(100)
    got = qcut(CycloneSeries(x), 4, labels=False)
    want = pd.qcut(pd.Series(x), 4, labels=False)
    np.testing.assert_array_equal(got.values, want.to_numpy())


def test_crosstab(grouped):
    cf, pdf = grouped
    cf2 = CycloneFrame({"r": ["u", "u", "v", "v", "u", "v"],
                        "c": ["p", "q", "p", "p", "q", "q"]})
    got = crosstab(cf2["r"], cf2["c"])
    want = pd.crosstab(pd.Series(["u", "u", "v", "v", "u", "v"]),
                       pd.Series(["p", "q", "p", "p", "q", "q"]))
    np.testing.assert_array_equal(got.index, want.index.to_numpy())
    for c in got.columns:
        np.testing.assert_array_equal(got[c].values, want[c].to_numpy())


# -- review-fix regressions --------------------------------------------------

def test_cut_integer_bins_edge_values():
    """Interior edges must split [lo, hi] exactly — a value AT a natural
    edge belongs to the LEFT bin (right-closed), matching pandas."""
    got = cut(CycloneSeries([0.0, 1.0, 2.0, 3.0]), 3, labels=False)
    want = pd.cut(pd.Series([0.0, 1.0, 2.0, 3.0]), 3, labels=False)
    np.testing.assert_array_equal(got.values, want.to_numpy())


def test_multikey_groupby_index_is_tuples(grouped):
    cf = CycloneFrame({"a": [1, 1, 2], "b": [1, 2, 2],
                       "v": [1.0, 2.0, 3.0]})
    out = cf.groupby(["a", "b"]).first()
    assert out._index.ndim == 1
    assert out._index[0] == (1, 1)
    sz = cf.groupby(["a", "b"]).size()
    assert sz.index.ndim == 1 and sz.index[2] == (2, 2)


def test_sample_default_is_one_row(num):
    cf, pdf = num
    assert len(cf.sample(random_state=0)) == len(pdf.sample(random_state=0))


def test_duplicated_nan_keys_equal():
    v = [1.0, np.nan, np.nan]
    _ser_eq(CycloneSeries(v).duplicated("first"),
            pd.Series(v).duplicated("first"))
    cf = CycloneFrame({"x": v})
    _ser_eq(cf.duplicated(), pd.DataFrame({"x": v}).duplicated())
    assert len(cf.drop_duplicates()) == 2


def test_transform_skipna_and_count():
    d = {"g": [1, 1, 2], "v": [1.0, np.nan, 3.0]}
    cf, pdf = CycloneFrame(dict(d)), pd.DataFrame(d)
    _ser_eq(cf.groupby("g").transform("count")["v"],
            pdf.groupby("g")["v"].transform("count"))
    _ser_eq(cf.groupby("g").transform("sum")["v"],
            pdf.groupby("g")["v"].transform("sum"))
    _ser_eq(cf.groupby("g").transform("mean")["v"],
            pdf.groupby("g")["v"].transform("mean"))


def test_crosstab_int_columns_keep_type():
    got = crosstab(CycloneSeries(["a", "b", "a"]), CycloneSeries([1, 2, 1]))
    want = pd.crosstab(pd.Series(["a", "b", "a"]), pd.Series([1, 2, 1]))
    assert got.columns == list(want.columns)   # ints, not '1'/'2'
    np.testing.assert_array_equal(got[1].values, want[1].to_numpy())


def test_groupby_first_last_nonnull_and_objects():
    d = {"k": [1, 1, 2], "v": [np.nan, 3.0, 5.0], "s": ["a", "b", "c"]}
    cf, pdf = CycloneFrame(dict(d)), pd.DataFrame(d)
    got, want = cf.groupby("k").first(), pdf.groupby("k").first()
    assert got.columns == list(want.columns)      # object col included
    _ser_eq(got["v"], want["v"])                  # first NON-null
    np.testing.assert_array_equal(got["s"].values, want["s"].to_numpy())
    got, want = cf.groupby("k").last(), pdf.groupby("k").last()
    _ser_eq(got["v"], want["v"])
    np.testing.assert_array_equal(got["s"].values, want["s"].to_numpy())


def test_frame_quantile_list_returns_frame(num):
    cf, pdf = num
    got = cf.quantile([0.25, 0.75])
    want = pdf.quantile([0.25, 0.75])
    assert got.columns == list(want.columns)
    np.testing.assert_array_equal(got.index, want.index.to_numpy())
    for c in got.columns:
        _ser_eq(got[c], want[c])


def test_rename_prefix_preserve_index():
    cf = CycloneFrame({"k": [1, 2, 3], "v": [4, 5, 6]}).set_index("k")
    pdf = pd.DataFrame({"k": [1, 2, 3], "v": [4, 5, 6]}).set_index("k")
    for got, want in ((cf.add_prefix("x_"), pdf.add_prefix("x_")),
                      (cf.rename({"v": "w"}), pdf.rename(columns={"v": "w"})),
                      (cf.drop(["v"]), pdf.drop(columns=["v"])),
                      (cf.fillna(0), pdf.fillna(0))):
        np.testing.assert_array_equal(got.index, want.index.to_numpy())


def test_shift_fill_value_keeps_dtype():
    s = CycloneSeries(np.array([1, 2, 3], dtype=np.int64))
    p = pd.Series(np.array([1, 2, 3], dtype=np.int64))
    got, want = s.shift(1, fill_value=0), p.shift(1, fill_value=0)
    assert got.values.dtype == want.to_numpy().dtype == np.int64
    np.testing.assert_array_equal(got.values, want.to_numpy())


def test_qcut_duplicate_edges():
    tied = [1.0, 1.0, 1.0, 1.0, 2.0]
    with pytest.raises(ValueError, match="must be unique"):
        qcut(CycloneSeries(tied), 4, labels=False)
    got = qcut(CycloneSeries(tied), 4, labels=False, duplicates="drop")
    want = pd.qcut(pd.Series(tied), 4, labels=False, duplicates="drop")
    np.testing.assert_array_equal(got.values, want.to_numpy())


def test_cut_left_closed_max_in_last_bin():
    got = cut(CycloneSeries([0.0, 1.0, 2.0, 3.0]), 3, labels=False,
              right=False)
    want = pd.cut(pd.Series([0.0, 1.0, 2.0, 3.0]), 3, labels=False,
                  right=False)
    np.testing.assert_array_equal(got.values, want.to_numpy())


def test_insert_validates_length_and_allnull_minmax():
    f = CycloneFrame({"a": [1, 2, 3]})
    with pytest.raises(ValueError):
        f.insert(0, "b", [1, 2])
    s = CycloneSeries([np.nan, np.nan])
    assert np.isnan(s.min()) and np.isnan(s.max())
    got = CycloneFrame({"a": [np.nan, np.nan], "b": [1.0, 2.0]}).min()
    want = pd.DataFrame({"a": [np.nan, np.nan], "b": [1.0, 2.0]}).min()
    _ser_eq(got, want)


def test_transpose_duplicate_index_raises_equals_checks_index():
    f = CycloneFrame({"k": [0, 0], "v": [1, 2]}).set_index("k")
    with pytest.raises(ValueError, match="duplicate index"):
        f.transpose()
    a = CycloneFrame({"k": [10, 11], "v": [1, 2]}).set_index("k")
    b = CycloneFrame({"k": [99, 100], "v": [1, 2]}).set_index("k")
    assert not a.equals(b)
    assert a.equals(CycloneFrame({"k": [10, 11], "v": [1, 2]}
                                 ).set_index("k"))


def test_review4_semantics():
    """Round-4 review fixes: skipna any/all, NaN-matching isin, all-null
    quantile, transform median/var, cut label-count validation."""
    assert CycloneSeries(np.array([np.nan, 0.0])).any() \
        == pd.Series([np.nan, 0.0]).any()
    _ser_eq(CycloneSeries(np.array([1.0, np.nan, 3.0])).isin([np.nan, 3.0]),
            pd.Series([1.0, np.nan, 3.0]).isin([np.nan, 3.0]))
    assert np.isnan(CycloneSeries(np.array([np.nan])).quantile(0.5))
    d = {"g": [1, 1, 2], "v": [1.0, 5.0, 3.0]}
    _ser_eq(CycloneFrame(dict(d)).groupby("g").transform("median")["v"],
            pd.DataFrame(d).groupby("g")["v"].transform("median"))
    with pytest.raises(ValueError, match="one fewer"):
        cut(CycloneSeries([1.0, 2.0]), [0, 1, 2], labels=["a", "b", "c"])
