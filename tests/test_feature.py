"""Feature transformer tests (≈ the reference's per-transformer suites in
mllib/src/test/.../ml/feature/, against sklearn/scipy ground truth)."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.feature import (
    Binarizer, Bucketizer, BucketedRandomProjectionLSH, ChiSqSelector,
    CountVectorizer, DCT, ElementwiseProduct, FeatureHasher, HashingTF, IDF,
    Imputer, IndexToString, Interaction, MaxAbsScaler, MinHashLSH,
    MinMaxScaler, NGram, Normalizer, OneHotEncoder, PCA, PolynomialExpansion,
    QuantileDiscretizer, RegexTokenizer, RobustScaler, StandardScaler,
    StandardScalerModel, StopWordsRemover, StringIndexer, Tokenizer,
    UnivariateFeatureSelector, VarianceThresholdSelector, VectorAssembler,
    VectorIndexer, VectorSizeHint, VectorSlicer, Word2Vec,
)


@pytest.fixture
def xframe(ctx):
    rng = np.random.RandomState(60)
    x = rng.randn(100, 4) * np.array([1.0, 5.0, 0.1, 2.0]) + np.array([0, 3, -1, 0])
    return MLFrame(ctx, {"features": x}), x


def test_standard_scaler(ctx, xframe):
    frame, x = xframe
    m = StandardScaler(withMean=True, withStd=True, inputCol="features",
                       outputCol="out").fit(frame)
    out = m.transform(frame)["out"]
    np.testing.assert_allclose(out.mean(0), 0.0, atol=1e-12)
    np.testing.assert_allclose(out.std(0, ddof=1), 1.0, rtol=1e-10)
    # default: no centering (ref default withMean=False)
    m2 = StandardScaler(inputCol="features", outputCol="out").fit(frame)
    out2 = m2.transform(frame)["out"]
    np.testing.assert_allclose(out2, x / x.std(0, ddof=1), rtol=1e-10)


def test_minmax_maxabs_robust(ctx, xframe):
    frame, x = xframe
    mm = MinMaxScaler(inputCol="features", outputCol="o").fit(frame).transform(frame)["o"]
    np.testing.assert_allclose(mm.min(0), 0.0, atol=1e-12)
    np.testing.assert_allclose(mm.max(0), 1.0, atol=1e-12)
    ma = MaxAbsScaler(inputCol="features", outputCol="o").fit(frame).transform(frame)["o"]
    assert np.abs(ma).max() <= 1.0 + 1e-12
    rs = RobustScaler(withCentering=True, inputCol="features", outputCol="o").fit(frame)
    out = rs.transform(frame)["o"]
    np.testing.assert_allclose(np.median(out, axis=0), 0.0, atol=1e-12)


def test_normalizer(ctx, xframe):
    frame, x = xframe
    out = Normalizer(p=2.0, inputCol="features", outputCol="o").transform(frame)["o"]
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-12)
    out1 = Normalizer(p=1.0, inputCol="features", outputCol="o").transform(frame)["o"]
    np.testing.assert_allclose(np.abs(out1).sum(1), 1.0, rtol=1e-12)


def test_binarizer_bucketizer_quantile(ctx):
    f = MLFrame(ctx, {"v": np.array([-1.0, 0.2, 0.5, 0.8, 2.0])})
    b = Binarizer(threshold=0.4, inputCol="v", outputCol="o").transform(f)
    np.testing.assert_allclose(b["o"], [0, 0, 1, 1, 1])
    bk = Bucketizer(splits=[-np.inf, 0.0, 0.5, np.inf], inputCol="v",
                    outputCol="o").transform(f)
    np.testing.assert_allclose(bk["o"], [0, 1, 2, 2, 2])
    qd = QuantileDiscretizer(numBuckets=2, inputCol="v", outputCol="o").fit(f)
    out = qd.transform(f)["o"]
    assert set(out) == {0.0, 1.0}


def test_bucketizer_handle_invalid(ctx):
    f = MLFrame(ctx, {"v": np.array([0.5, 5.0])})
    bk = Bucketizer(splits=[0.0, 1.0, 2.0], inputCol="v", outputCol="o")
    with pytest.raises(ValueError):
        bk.transform(f)
    bk.set("handleInvalid", "keep")
    np.testing.assert_allclose(bk.transform(f)["o"], [0, 2])
    bk.set("handleInvalid", "skip")
    assert bk.transform(f).n_rows == 1


def test_elementwise_poly_dct_assembler_slicer(ctx):
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    f = MLFrame(ctx, {"features": x, "extra": np.array([10.0, 20.0])})
    ew = ElementwiseProduct(scaling_vec=[2.0, 0.5], inputCol="features",
                            outputCol="o").transform(f)
    np.testing.assert_allclose(ew["o"], [[2, 1], [6, 2]])
    pe = PolynomialExpansion(degree=2, inputCol="features", outputCol="o").transform(f)
    assert pe["o"].shape[1] == 5  # x1,x2,x1²,x1x2,x2²
    np.testing.assert_allclose(pe["o"][0], [1, 2, 1, 2, 4])
    from scipy.fft import dct as sdct
    d = DCT(inputCol="features", outputCol="o").transform(f)
    np.testing.assert_allclose(d["o"], sdct(x, type=2, norm="ortho", axis=1))
    va = VectorAssembler(input_cols=["features", "extra"], output_col="o").transform(f)
    np.testing.assert_allclose(va["o"], [[1, 2, 10], [3, 4, 20]])
    vs = VectorSlicer(indices=[1], inputCol="features", outputCol="o").transform(f)
    np.testing.assert_allclose(vs["o"], [[2], [4]])
    vh = VectorSizeHint(size=2, inputCol="features")
    assert vh.transform(f).n_rows == 2
    with pytest.raises(ValueError):
        VectorSizeHint(size=3, inputCol="features").transform(f)


def test_interaction(ctx):
    f = MLFrame(ctx, {"a": np.array([[1.0, 2.0]]), "b": np.array([[3.0, 4.0]])})
    out = Interaction(input_cols=["a", "b"]).transform(f)["interacted"]
    np.testing.assert_allclose(out, [[3, 4, 6, 8]])


def test_imputer(ctx):
    f = MLFrame(ctx, {"a": np.array([1.0, np.nan, 3.0]),
                      "b": np.array([np.nan, 4.0, 8.0])})
    m = Imputer(input_cols=["a", "b"], output_cols=["ia", "ib"]).fit(f)
    out = m.transform(f)
    np.testing.assert_allclose(out["ia"], [1, 2, 3])
    np.testing.assert_allclose(out["ib"], [6, 4, 8])
    m2 = Imputer(input_cols=["a"], output_cols=["ia"], strategy="median").fit(f)
    np.testing.assert_allclose(m2.transform(f)["ia"], [1, 2, 3])


def test_tokenizers_and_text_chain(ctx):
    docs = np.array(["Hello World hello", "the quick brown fox the"], dtype=object)
    f = MLFrame(ctx, {"text": docs})
    tok = Tokenizer(inputCol="text", outputCol="tokens").transform(f)
    assert tok["tokens"][0] == ["hello", "world", "hello"]
    rt = RegexTokenizer(pattern=r"o", inputCol="text", outputCol="t2").transform(f)
    assert rt["t2"][0] == ["hell", " w", "rld hell"]
    sw = StopWordsRemover(inputCol="tokens", outputCol="clean").transform(tok)
    assert sw["clean"][1] == ["quick", "brown", "fox"]
    ng = NGram(n=2, inputCol="tokens", outputCol="ngrams").transform(tok)
    assert ng["ngrams"][0] == ["hello world", "world hello"]


def test_hashingtf_idf_countvectorizer(ctx):
    docs = np.empty(3, dtype=object)
    docs[0] = ["a", "b", "a"]
    docs[1] = ["b", "c"]
    docs[2] = ["a", "c", "c", "c"]
    f = MLFrame(ctx, {"tokens": docs})
    tf = HashingTF(numFeatures=32, inputCol="tokens", outputCol="tf").transform(f)
    assert tf["tf"].shape == (3, 32)
    assert tf["tf"][0].sum() == 3.0
    cv = CountVectorizer(inputCol="tokens", outputCol="counts").fit(f)
    assert cv.vocabulary[0] in ("a", "c")  # both freq 4 over corpus? a:3 c:4
    assert cv.vocabulary[0] == "c"
    out = cv.transform(f)["counts"]
    assert out.shape == (3, 3)
    idf_m = IDF(inputCol="tf", outputCol="tfidf").fit(tf)
    tfidf = idf_m.transform(tf)["tfidf"]
    assert tfidf.shape == (3, 32)
    # idf of a term in all docs < idf of a term in one doc
    fh = FeatureHasher(input_cols=["tokens"], numFeatures=16)  # object col hashes name=value
    assert fh.transform(f)["features"].shape == (3, 16)


def test_string_indexer_roundtrip(ctx):
    f = MLFrame(ctx, {"cat": np.array(["b", "a", "b", "c", "b"], dtype=object)})
    m = StringIndexer(inputCol="cat", outputCol="idx").fit(f)
    assert m.labels[0] == "b"  # most frequent first
    out = m.transform(f)
    assert out["idx"][0] == 0.0
    back = IndexToString(labels=m.labels, inputCol="idx", outputCol="orig").transform(out)
    assert list(back["orig"]) == list(f["cat"])
    # unseen label handling
    f2 = MLFrame(ctx, {"cat": np.array(["z"], dtype=object)})
    with pytest.raises(ValueError):
        m.transform(f2)
    m.set("handleInvalid", "keep")
    assert m.transform(f2)["idx"][0] == 3.0


def test_onehot(ctx):
    f = MLFrame(ctx, {"idx": np.array([0.0, 1.0, 2.0, 1.0])})
    m = OneHotEncoder(input_cols=["idx"], output_cols=["vec"]).fit(f)
    out = m.transform(f)["vec"]
    assert out.shape == (4, 2)  # dropLast
    np.testing.assert_allclose(out[0], [1, 0])
    np.testing.assert_allclose(out[2], [0, 0])  # last category = zeros
    m.set("dropLast", False)
    assert m.transform(f)["vec"].shape == (4, 3)


def test_vector_indexer(ctx):
    x = np.array([[0.0, 1.5], [1.0, 2.5], [0.0, 3.5], [2.0, -1.0]])
    f = MLFrame(ctx, {"features": x})
    m = VectorIndexer(maxCategories=3, inputCol="features", outputCol="o").fit(f)
    assert m.category_feature_indices == [0]
    out = m.transform(f)["o"]
    np.testing.assert_allclose(out[:, 0], [0, 1, 0, 2])
    np.testing.assert_allclose(out[:, 1], x[:, 1])


def test_selectors(ctx):
    rng = np.random.RandomState(61)
    n = 300
    y = rng.randint(0, 2, n).astype(float)
    informative = y + 0.1 * rng.randn(n)
    noise = rng.randn(n, 3)
    x = np.column_stack([informative, noise])
    f = MLFrame(ctx, {"features": x, "label": y})
    sel = UnivariateFeatureSelector(
        featureType="continuous", labelType="categorical",
        selectorType="numTopFeatures", numTopFeatures=1,
        inputCol="features", outputCol="sel").fit(f)
    assert sel.selected_features == [0]
    # variance threshold
    xv = np.column_stack([np.ones(n), rng.randn(n)])
    fv = MLFrame(ctx, {"features": xv})
    vt = VarianceThresholdSelector(inputCol="features", outputCol="o").fit(fv)
    assert vt.selected_features == [1]
    # chi-sq on categorical features
    xc = np.column_stack([y, rng.randint(0, 2, n)]).astype(float)
    fc = MLFrame(ctx, {"features": xc, "label": y})
    cs = ChiSqSelector(numTopFeatures=1, inputCol="features",
                       outputCol="o").fit(fc)
    assert cs.selected_features == [0]


def test_pca_transformer(ctx):
    rng = np.random.RandomState(62)
    base = rng.randn(200, 2)
    x = np.column_stack([base[:, 0], base[:, 0] * 2 + 0.01 * rng.randn(200),
                         base[:, 1]])
    f = MLFrame(ctx, {"features": x})
    m = PCA(k=2, inputCol="features", outputCol="pca").fit(f)
    out = m.transform(f)["pca"]
    assert out.shape == (200, 2)
    assert m.explained_variance.sum() > 0.99


def test_lsh_brp(ctx):
    rng = np.random.RandomState(63)
    x = rng.randn(50, 8)
    f = MLFrame(ctx, {"features": x})
    m = BucketedRandomProjectionLSH(bucketLength=2.0, numHashTables=4,
                                    inputCol="features", outputCol="h",
                                    seed=1).fit(f)
    out = m.transform(f)
    assert out["h"].shape == (50, 4)
    nn = m.approx_nearest_neighbors(f, x[7] + 1e-6, 1)
    np.testing.assert_allclose(nn["features"][0], x[7])
    join = m.approx_similarity_join(f, f, threshold=1e-9)
    assert join.n_rows >= 50  # self-pairs at distance 0


def test_lsh_minhash(ctx):
    rng = np.random.RandomState(64)
    x = (rng.rand(30, 20) < 0.3).astype(float)
    x[x.sum(1) == 0, 0] = 1.0
    f = MLFrame(ctx, {"features": x})
    m = MinHashLSH(numHashTables=3, inputCol="features", outputCol="h",
                   seed=2).fit(f)
    assert m.transform(f)["h"].shape == (30, 3)
    nn = m.approx_nearest_neighbors(f, x[3], 1)
    np.testing.assert_allclose(nn["features"][0], x[3])


def test_word2vec(ctx):
    sentences = np.empty(40, dtype=object)
    for i in range(40):
        # two "topics" with disjoint vocab
        sentences[i] = (["cat", "dog", "pet", "fur"] if i % 2 == 0
                        else ["car", "road", "wheel", "engine"]) * 3
    f = MLFrame(ctx, {"tokens": sentences})
    m = Word2Vec(vectorSize=16, minCount=1, maxIter=3, seed=3,
                 inputCol="tokens", outputCol="vec").fit(f)
    syn = m.find_synonyms("cat", 2)
    words = [w for w, _ in syn]
    assert set(words) <= {"dog", "pet", "fur"}
    out = m.transform(f)
    assert out["vec"].shape == (40, 16)
    # doc vectors of same topic are closer than cross-topic
    v = out["vec"]
    same = np.linalg.norm(v[0] - v[2])
    cross = np.linalg.norm(v[0] - v[1])
    assert same < cross


def test_scaler_persistence(ctx, xframe, tmp_path):
    frame, x = xframe
    m = StandardScaler(withMean=True, inputCol="features", outputCol="o").fit(frame)
    p = str(tmp_path / "ss")
    m.save(p)
    back = StandardScalerModel.load(p)
    np.testing.assert_allclose(back.mean, m.mean)
    np.testing.assert_allclose(back.transform(frame)["o"], m.transform(frame)["o"])


def test_word2vec_hierarchical_softmax(ctx):
    """solver="hs": Huffman-tree hierarchical softmax (the reference's
    objective, Word2Vec.scala:73) — tree invariants, a decreasing loss
    curve, and embedding quality matching the negative-sampling default.
    (gensim is not in this environment; the loss curve is asserted
    self-consistently — it is now COMPARABLE to word2vec.c/gensim hs runs,
    which negative sampling never was.)"""
    from cycloneml_tpu.ml.feature.word2vec import _huffman_paths

    # Huffman invariants: prefix-free codes, frequent words get short codes
    freqs = np.array([100, 50, 20, 20, 5, 3, 1])
    points, codes, lengths = _huffman_paths(freqs)
    assert lengths[0] == lengths.min()  # most frequent -> shortest path
    binary = ["".join(str(b) for b in codes[w, :lengths[w]])
              for w in range(len(freqs))]
    assert len(set(binary)) == len(freqs)
    for i, a in enumerate(binary):  # prefix-free
        for j, b in enumerate(binary):
            if i != j:
                assert not b.startswith(a)
    # expected Huffman property: sum of freq*len is minimal-ish (sanity:
    # no code longer than vocab-1, root path ids in range)
    assert points.max() < len(freqs) - 1

    sentences = np.empty(40, dtype=object)
    for i in range(40):
        sentences[i] = (["cat", "dog", "pet", "fur"] if i % 2 == 0
                        else ["car", "road", "wheel", "engine"]) * 3
    f = MLFrame(ctx, {"tokens": sentences})
    m = Word2Vec(vectorSize=16, minCount=1, maxIter=4, seed=3, solver="hs",
                 inputCol="tokens", outputCol="vec").fit(f)
    # loss curve exists and decreases over epochs
    losses = m.training_loss_
    assert len(losses) == 4 and losses[-1] < losses[0]
    # same quality bar as the ns test
    syn = m.find_synonyms("cat", 2)
    assert set(w for w, _ in syn) <= {"dog", "pet", "fur"}
    out = m.transform(f)
    v = out["vec"]
    assert np.linalg.norm(v[0] - v[2]) < np.linalg.norm(v[0] - v[1])


def test_word2vec_ns_matches_numpy_oracle(ctx):
    """r4 verdict item 9: the jitted negative-sampling solver agrees with
    the independent f64 numpy oracle (tests/ref_parity/w2v_oracle.py) —
    same data pipeline and negative draws, update math derived from
    scratch. Vectors must track closely and nearest neighbours match."""
    import jax
    import jax.numpy as jnp
    from tests.ref_parity import w2v_oracle as wo

    rng = np.random.RandomState(0)
    topics = [["cat", "dog", "pet", "fur", "paw"],
              ["car", "road", "wheel", "fuel", "drive"],
              ["sun", "moon", "star", "sky", "orbit"]]
    sentences = []
    for _ in range(120):
        t = topics[rng.randint(3)]
        sentences.append([t[rng.randint(5)] for _ in range(8)])

    dim, window, epochs, seed, n_neg, lr = 12, 2, 2, 7, 5, 0.025
    from cycloneml_tpu.dataset.frame import MLFrame
    frame = MLFrame(ctx, {"text": np.array(
        [" ".join(s).split() for s in sentences], dtype=object)})
    m = Word2Vec(vectorSize=dim, windowSize=window, maxIter=epochs,
                 seed=seed, minCount=1, negative=n_neg, stepSize=lr,
                 inputCol="text").fit(frame)

    # reconstruct the estimator's negative draws (same PRNG discipline)
    vocab, counts, centers, _ = wo.build_pipeline(sentences, 1, window)
    freq = np.array([counts[w] for w in vocab], dtype=np.float64) ** 0.75
    neg_probs = jnp.asarray(freq / freq.sum(), dtype=jnp.float32)
    key = jax.random.PRNGKey(seed)
    prng = np.random.RandomState(seed)
    prng.rand(len(vocab), dim)  # init consumed before permutations
    draws = []
    n_pairs = len(centers)
    for _ in range(epochs):
        perm = prng.permutation(n_pairs)
        for s0 in range(0, n_pairs, wo.BATCH):
            sel = perm[s0: s0 + wo.BATCH]
            key, sub = jax.random.split(key)
            draws.append(np.asarray(jax.random.choice(
                sub, len(vocab), shape=(len(sel), n_neg), p=neg_probs)))

    ovocab, ovecs = wo.oracle_ns(
        sentences, dim=dim, window=window, lr=lr, epochs=epochs,
        seed=seed, neg_draws=draws)
    assert m.vocabulary == ovocab
    # f32 solver vs f64 oracle on the same trajectory
    np.testing.assert_allclose(m.vectors, ovecs, atol=2e-4)
    # nearest-neighbour agreement on every topical word
    from cycloneml_tpu.ml.feature.word2vec import Word2VecModel
    om = Word2VecModel(ovocab, ovecs)
    for w in ("cat", "car", "sun"):
        ours = [x for x, _ in m.find_synonyms(w, 3)]
        theirs = [x for x, _ in om.find_synonyms(w, 3)]
        assert ours == theirs, (w, ours, theirs)


def test_word2vec_hs_matches_numpy_oracle(ctx):
    """The hierarchical-softmax solver against the oracle: identical
    Huffman trajectory in f32 vs f64 — loss CURVES track and vectors
    agree (no external gensim exists in-env; the oracle is the trusted
    comparator, ref Word2Vec.scala:73)."""
    from tests.ref_parity import w2v_oracle as wo

    rng = np.random.RandomState(1)
    words = [f"w{i}" for i in range(30)]
    sentences = [[words[rng.randint(30)] for _ in range(10)]
                 for _ in range(80)]
    dim, window, epochs, seed, lr = 10, 2, 3, 5, 0.025
    from cycloneml_tpu.dataset.frame import MLFrame
    frame = MLFrame(ctx, {"text": np.array(sentences, dtype=object)})
    m = Word2Vec(vectorSize=dim, windowSize=window, maxIter=epochs,
                 seed=seed, minCount=1, solver="hs", stepSize=lr,
                 inputCol="text").fit(frame)
    ovocab, ovecs, olosses = wo.oracle_hs(
        sentences, dim=dim, window=window, lr=lr, epochs=epochs, seed=seed)
    assert m.vocabulary == ovocab
    np.testing.assert_allclose(m.training_loss_, olosses, rtol=1e-4)
    np.testing.assert_allclose(m.vectors, ovecs, atol=2e-4)
