"""Tree-ensemble suite.

Modeled on the reference's DecisionTreeClassifierSuite /
RandomForestSuite / GBTClassifierSuite approach: small exactly-separable
datasets with structural assertions, plus accuracy/R² checks against
sklearn's exact CART on the same data (the analog of the reference's
R-reference numeric checks), and DefaultReadWriteTest-style persistence
round-trips.
"""

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.classification import (
    DecisionTreeClassificationModel, DecisionTreeClassifier,
    GBTClassifier, RandomForestClassifier,
)
from cycloneml_tpu.ml.regression import (
    DecisionTreeRegressor, GBTRegressor, RandomForestRegressor,
)


def _cls_data(ctx, n=400, d=8, k=2, seed=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    logits = x[:, 0] * 2.0 + x[:, 1] - 0.5 * x[:, 2]
    if k == 2:
        y = (logits > 0).astype(np.float64)
    else:
        y = np.digitize(logits, np.quantile(logits, np.linspace(0, 1, k + 1)[1:-1])
                        ).astype(np.float64)
    return MLFrame(ctx, {"features": x, "label": y}), x, y


def _reg_data(ctx, n=500, d=6, seed=5):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    y = np.where(x[:, 0] > 0, 3.0, -1.0) + np.where(x[:, 1] > 0.5, 2.0, 0.0)
    return MLFrame(ctx, {"features": x, "label": y}), x, y


def test_decision_tree_classifier_separable(ctx):
    frame, x, y = _cls_data(ctx)
    model = DecisionTreeClassifier(maxDepth=6).fit(frame)
    out = model.transform(frame)
    acc = (out["prediction"] == y).mean()
    assert acc > 0.93
    assert model.depth <= 6
    assert model.num_nodes >= 3
    # probabilities are normalized
    p = out["probability"]
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-9)


def test_decision_tree_vs_sklearn(ctx):
    frame, x, y = _cls_data(ctx, n=600)
    ours = DecisionTreeClassifier(maxDepth=4, maxBins=64).fit(frame)
    from sklearn.tree import DecisionTreeClassifier as SkDT
    sk = SkDT(max_depth=4, random_state=0).fit(x, y)
    acc_ours = (ours.transform(frame)["prediction"] == y).mean()
    acc_sk = sk.score(x, y)
    # binned CART should be within a few points of exact CART in-sample
    assert acc_ours >= acc_sk - 0.04


def test_decision_tree_multiclass(ctx):
    frame, x, y = _cls_data(ctx, k=3, n=600)
    model = DecisionTreeClassifier(maxDepth=7, maxBins=48).fit(frame)
    acc = (model.transform(frame)["prediction"] == y).mean()
    assert acc > 0.8
    assert model.num_classes == 3


def test_decision_tree_min_instances(ctx):
    frame, x, y = _cls_data(ctx, n=200)
    big = DecisionTreeClassifier(maxDepth=10, minInstancesPerNode=50).fit(frame)
    small = DecisionTreeClassifier(maxDepth=10, minInstancesPerNode=1).fit(frame)
    assert big.num_nodes < small.num_nodes


def test_decision_tree_pure_node_stops(ctx):
    # one feature perfectly separates → a single split, depth 1
    x = np.array([[0.0], [1.0], [2.0], [10.0], [11.0], [12.0]])
    y = np.array([0.0, 0, 0, 1, 1, 1])
    frame = MLFrame(ctx, {"features": x, "label": y})
    model = DecisionTreeClassifier(maxDepth=5).fit(frame)
    assert model.depth == 1
    assert model.num_nodes == 3


def test_decision_tree_feature_importances(ctx):
    frame, x, y = _cls_data(ctx)
    model = DecisionTreeClassifier(maxDepth=5).fit(frame)
    imp = model.feature_importances
    assert imp.shape == (x.shape[1],)
    np.testing.assert_allclose(imp.sum(), 1.0, atol=1e-9)
    assert imp[0] == imp.max()   # x0 dominates the label


def test_decision_tree_regressor(ctx):
    frame, x, y = _reg_data(ctx)
    model = DecisionTreeRegressor(maxDepth=4).fit(frame)
    pred = model.transform(frame)["prediction"]
    ss_res = ((pred - y) ** 2).sum()
    ss_tot = ((y - y.mean()) ** 2).sum()
    assert 1 - ss_res / ss_tot > 0.97   # piecewise-constant target: near-exact


def test_decision_tree_regressor_vs_sklearn(ctx):
    rng = np.random.RandomState(11)
    x = rng.randn(500, 5)
    y = x[:, 0] ** 2 + 0.5 * x[:, 1] + 0.1 * rng.randn(500)
    frame = MLFrame(ctx, {"features": x, "label": y})
    ours = DecisionTreeRegressor(maxDepth=5, maxBins=64).fit(frame)
    from sklearn.tree import DecisionTreeRegressor as SkDT
    sk = SkDT(max_depth=5, random_state=0).fit(x, y)
    mse_ours = ((ours.transform(frame)["prediction"] - y) ** 2).mean()
    mse_sk = ((sk.predict(x) - y) ** 2).mean()
    assert mse_ours <= mse_sk * 1.35


def test_random_forest_classifier(ctx):
    frame, x, y = _cls_data(ctx, n=500)
    model = RandomForestClassifier(numTrees=15, maxDepth=5, seed=7).fit(frame)
    assert model.num_trees == 15
    acc = (model.transform(frame)["prediction"] == y).mean()
    assert acc > 0.9
    imp = model.feature_importances
    np.testing.assert_allclose(imp.sum(), 1.0, atol=1e-9)


def test_random_forest_subsampling_and_subset(ctx):
    frame, x, y = _cls_data(ctx, n=300)
    model = RandomForestClassifier(
        numTrees=8, maxDepth=4, subsamplingRate=0.7,
        featureSubsetStrategy="sqrt", seed=1).fit(frame)
    acc = (model.transform(frame)["prediction"] == y).mean()
    assert acc > 0.8
    # bootstrap + subsets → trees differ
    f = model._forest
    assert len({int(f.feature[t, 0]) for t in range(f.num_trees)}) > 1


def test_random_forest_regressor(ctx):
    frame, x, y = _reg_data(ctx)
    model = RandomForestRegressor(numTrees=10, maxDepth=5, seed=3).fit(frame)
    pred = model.transform(frame)["prediction"]
    ss_res = ((pred - y) ** 2).sum()
    ss_tot = ((y - y.mean()) ** 2).sum()
    assert 1 - ss_res / ss_tot > 0.9


def test_gbt_classifier(ctx):
    frame, x, y = _cls_data(ctx, n=400)
    model = GBTClassifier(maxIter=15, maxDepth=3, stepSize=0.3).fit(frame)
    out = model.transform(frame)
    acc = (out["prediction"] == y).mean()
    assert acc > 0.95
    assert model.num_trees == 15
    p = out["probability"]
    assert ((p >= 0) & (p <= 1)).all()
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-9)


def test_gbt_improves_over_single_tree(ctx):
    rng = np.random.RandomState(2)
    x = rng.randn(500, 6)
    y = ((x[:, 0] * x[:, 1] + x[:, 2]) > 0).astype(np.float64)  # interaction
    frame = MLFrame(ctx, {"features": x, "label": y})
    dt = DecisionTreeClassifier(maxDepth=3).fit(frame)
    gbt = GBTClassifier(maxIter=25, maxDepth=3, stepSize=0.3).fit(frame)
    acc_dt = (dt.transform(frame)["prediction"] == y).mean()
    acc_gbt = (gbt.transform(frame)["prediction"] == y).mean()
    assert acc_gbt > acc_dt


def test_gbt_regressor_squared_and_absolute(ctx):
    frame, x, y = _reg_data(ctx)
    for loss in ("squared", "absolute"):
        model = GBTRegressor(maxIter=20, maxDepth=3, stepSize=0.3,
                             lossType=loss).fit(frame)
        pred = model.transform(frame)["prediction"]
        ss_res = ((pred - y) ** 2).sum()
        ss_tot = ((y - y.mean()) ** 2).sum()
        assert 1 - ss_res / ss_tot > 0.9, loss


def test_tree_persistence_roundtrip(ctx, tmp_path):
    frame, x, y = _cls_data(ctx)
    model = DecisionTreeClassifier(maxDepth=4).fit(frame)
    p = str(tmp_path / "dt")
    model.save(p)
    loaded = DecisionTreeClassificationModel.load(p)
    np.testing.assert_array_equal(model.transform(frame)["prediction"],
                                  loaded.transform(frame)["prediction"])
    assert loaded.get("maxDepth") == 4


def test_rf_persistence_roundtrip(ctx, tmp_path):
    frame, x, y = _cls_data(ctx, n=200)
    model = RandomForestClassifier(numTrees=5, maxDepth=3, seed=2).fit(frame)
    p = str(tmp_path / "rf")
    model.save(p)
    from cycloneml_tpu.ml.classification import RandomForestClassificationModel
    loaded = RandomForestClassificationModel.load(p)
    np.testing.assert_array_equal(model.transform(frame)["prediction"],
                                  loaded.transform(frame)["prediction"])


def test_gbt_persistence_roundtrip(ctx, tmp_path):
    frame, x, y = _reg_data(ctx, n=200)
    model = GBTRegressor(maxIter=5, maxDepth=3).fit(frame)
    p = str(tmp_path / "gbt")
    model.save(p)
    from cycloneml_tpu.ml.regression import GBTRegressionModel
    loaded = GBTRegressionModel.load(p)
    np.testing.assert_allclose(model.transform(frame)["prediction"],
                               loaded.transform(frame)["prediction"])


def test_tree_determinism(ctx):
    frame, x, y = _cls_data(ctx)
    m1 = RandomForestClassifier(numTrees=5, maxDepth=4, seed=9).fit(frame)
    m2 = RandomForestClassifier(numTrees=5, maxDepth=4, seed=9).fit(frame)
    np.testing.assert_array_equal(m1.transform(frame)["prediction"],
                                  m2.transform(frame)["prediction"])


def test_tree_in_pipeline(ctx):
    from cycloneml_tpu.ml.base import Pipeline
    from cycloneml_tpu.ml.feature.scalers import StandardScaler
    frame, x, y = _cls_data(ctx)
    pipe = Pipeline(stages=[
        StandardScaler(inputCol="features", outputCol="scaled"),
        DecisionTreeClassifier(featuresCol="scaled", maxDepth=4)])
    model = pipe.fit(frame)
    acc = (model.transform(frame)["prediction"] == y).mean()
    assert acc > 0.9


def test_tree_weighted_instances(ctx):
    # zero-weight rows must be ignored: mislabeled rows with w=0 don't hurt
    rng = np.random.RandomState(0)
    x = rng.randn(300, 4)
    y = (x[:, 0] > 0).astype(np.float64)
    y_noisy = y.copy()
    y_noisy[:80] = 1.0 - y_noisy[:80]            # flip labels on 80 rows
    w = np.ones(300)
    w[:80] = 0.0                                  # ...but zero their weight
    f_w = MLFrame(ctx, {"features": x, "label": y_noisy, "w": w})
    m_w = DecisionTreeClassifier(maxDepth=3, weightCol="w").fit(f_w)
    pred = m_w.transform(f_w)["prediction"]
    assert (pred[80:] == y[80:]).mean() > 0.98    # clean rows: near-perfect
    # without the weight column the flipped labels corrupt the fit
    m_plain = DecisionTreeClassifier(maxDepth=3).fit(f_w)
    pred_p = m_plain.transform(f_w)["prediction"]
    assert (pred[80:] == y[80:]).mean() >= (pred_p[80:] == y[80:]).mean()


def test_debug_string(ctx):
    frame, x, y = _cls_data(ctx, n=100)
    model = DecisionTreeClassifier(maxDepth=2).fit(frame)
    s = model.to_debug_string()
    assert "If (feature" in s and "Predict:" in s
