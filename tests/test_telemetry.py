"""Distributed telemetry plane (ISSUE 12): cross-process trace
aggregation, the always-on flight recorder, and straggler/skew detection.

The headline 2-process deploy-harness acceptance
(test_deploy_two_process_merged_trace) lives in test_observability.py —
this file holds the telemetry plane's unit and in-process integration
surface: clock-offset estimation, shipper/collector round trips, the
flight recorder's ring/dump/throttle contracts, and the skew detector's
latched verdicts at the real oocore site under seeded chaos.
"""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from cycloneml_tpu.observe import (flight, process_lanes, skew, tracing,
                                   validate_chrome_trace)
from cycloneml_tpu.observe.collect import (SpanShipper, TraceCollector,
                                           clear_offset_samples,
                                           estimate_offset, offset_samples)
from cycloneml_tpu.observe.skew import SkewDetector
from cycloneml_tpu.util.events import SloBreach, StragglerDetected


# -- clock-offset estimation -----------------------------------------------------

def test_estimate_offset_prefers_low_rtt_samples():
    """The median over the lowest-RTT samples rejects the asymmetric-delay
    outlier a loaded fabric produces; the bound is the worst used RTT/2."""
    samples = [(0.50, 0.0010), (0.52, 0.0020), (0.48, 0.0015),
               (0.51, 0.0012), (0.49, 0.0011),
               (5.00, 0.5000)]   # one congested round trip
    off, err = estimate_offset(samples)
    assert abs(off - 0.50) < 0.02
    assert err is not None and err <= 0.0010  # outlier excluded entirely
    assert estimate_offset([]) == (0.0, None)


def test_merged_trace_corrects_offsets_and_qualifies_ids():
    """Two hosts with a known 10 s clock skew merge onto one timeline:
    per-host lanes labeled, span ids host-qualified, remote parent ids
    passed through, timestamps corrected by the per-host offset."""
    from cycloneml_tpu.observe.export import merged_chrome_trace
    t = 1_000_000.0
    records = [
        {"host": "master", "pid": 11, "offset_s": 0.0, "trace_id": "T",
         "dropped": 0, "tid_names": {1: "main"},
         "spans": [{"id": "s1", "parent": "", "kind": "deploy",
                    "name": "submit", "t0": t, "t1": t + 2.0, "tid": 1,
                    "attrs": {}}]},
        {"host": "w0", "pid": 12, "offset_s": 10.0, "trace_id": "T",
         "dropped": 3, "tid_names": {7: "MainThread"},
         "spans": [{"id": "s1", "parent": "master/s1", "kind": "job",
                    "name": "fit", "t0": t + 10.5, "t1": t + 11.5,
                    "tid": 7, "attrs": {}}]},
    ]
    obj = merged_chrome_trace(records)
    assert validate_chrome_trace(obj) == []
    lanes = process_lanes(obj)
    assert len(lanes) == 2 and any("w0" in v for v in lanes.values())
    evs = {e["args"]["span_id"]: e for e in obj["traceEvents"]
           if e.get("ph") == "X"}
    # ids are host-qualified; the remote parent survives unmangled
    assert set(evs) == {"master/s1", "w0/s1"}
    assert evs["w0/s1"]["args"]["parent_id"] == "master/s1"
    # the worker's 10 s skew is corrected out: its span lands INSIDE the
    # master span's window on the merged timeline
    sub, job = evs["master/s1"], evs["w0/s1"]
    assert sub["ts"] <= job["ts"] <= sub["ts"] + sub["dur"]
    assert obj["otherData"]["trace_id"] == "T"
    assert obj["otherData"]["spans_dropped"] == 3


# -- shipper/collector round trip ------------------------------------------------

def test_shipper_collector_roundtrip(tmp_path):
    """A worker-side tracer drains through the shipper into the collector;
    the merged export validates and carries the worker's spans under its
    own labeled lane."""
    tr = tracing.Tracer(max_spans=1000)
    with tr.span("job", "worker-fit"):
        with tr.span("dispatch", "loss.eval", evals=2):
            pass
    col = TraceCollector(host_label="primary", tracer=tr)  # local lane too
    ship = None
    try:
        ship = SpanShipper(col.address, "w0", interval_s=0.05, tracer=tr)
        deadline = time.time() + 10
        while not col.hosts().get("w0", {}).get("spans"):
            assert time.time() < deadline, "no batch arrived"
            time.sleep(0.05)
        # spans recorded AFTER the first drain ship too (cursor semantics)
        with tr.span("dispatch", "late", evals=1):
            pass
        ship.stop(flush=True)
        assert ship.shipped >= 3 and ship.dropped == 0
        path = str(tmp_path / "merged.trace.json")
        col.export(path)
        assert validate_chrome_trace(path) == []
        obj = json.load(open(path))
        lanes = process_lanes(obj)
        assert len(lanes) == 2  # primary (local tracer) + w0
        names = {e["name"] for e in obj["traceEvents"]
                 if e.get("ph") == "X"}
        assert {"worker-fit", "loss.eval", "late"} <= names
    finally:
        if ship is not None:
            ship.stop(flush=False)
        col.stop()


def test_shipper_buffers_and_drops_bounded_when_collector_away():
    """Drop-counted bounded buffering: with no collector listening the
    shipper retains at most max_buffer wire spans and counts the rest."""
    tr = tracing.Tracer(max_spans=10_000)
    ship = SpanShipper("127.0.0.1:9", "w0", interval_s=0.02,
                       max_batch=8, max_buffer=16, tracer=tr)
    try:
        for i in range(100):
            tr.instant("x", i=i)
        deadline = time.time() + 10
        while ship.dropped == 0:
            assert time.time() < deadline, "no drops counted"
            time.sleep(0.02)
    finally:
        ship.stop(flush=False)
    assert ship.shipped == 0
    assert ship.dropped >= 100 - 16


def test_drop_counters_reach_status_store_and_rest(monkeypatch):
    """Telemetry drop-counter surface (accounting plane satellite): tracer
    ring overflow, shipper delivery loss and collector ingest drops roll
    into ONE TelemetryStatsUpdated payload that the status store folds by
    replacement and /api/v1/telemetry serves."""
    from cycloneml_tpu.observe import collect
    from cycloneml_tpu.observe.attribution import UsageReporter
    from cycloneml_tpu.util.events import ListenerBus
    from cycloneml_tpu.util.status import AppStatusListener, api_v1

    # tracer ring overflow: visible without exporting a trace
    tr = tracing.Tracer(max_spans=8)
    for i in range(32):
        tr.instant("burst", i=i)
    assert tr.spans_dropped > 0

    # shipper delivery loss: collector away, bounded buffer overflows
    ship = SpanShipper("127.0.0.1:9", "w0", interval_s=0.02,
                       max_batch=8, max_buffer=16, tracer=tr)
    try:
        deadline = time.time() + 10
        while True:
            d = ship.delivery_stats()
            # ringMissed: the 24 pre-shipper evictions the cursor never
            # saw; bufferDropped: overflow of the bounded ship buffer
            if d["bufferDropped"] > 0 and d["ringMissed"] > 0:
                break
            assert time.time() < deadline, f"no delivery loss counted: {d}"
            for i in range(8):
                tr.instant("more", i=i)
            time.sleep(0.02)
    finally:
        ship.stop(flush=False)
    dstats = ship.delivery_stats()
    assert dstats["bufferDropped"] > 0 and dstats["buffered"] <= 16

    # collector ingest drops: per-host bound exceeded counts evictions,
    # and the worker's self-reported delivery loss is tracked apart
    monkeypatch.setattr(collect, "MAX_SPANS_PER_HOST", 4)
    col = TraceCollector(host_label="primary")
    try:
        wire = [{"id": f"s{i}", "parent": "", "kind": "dispatch",
                 "name": f"n{i}", "t0": float(i), "t1": float(i) + 0.5,
                 "tid": 1, "attrs": {}} for i in range(10)]
        reply = col._ingest({"kind": "spans", "host": "w0", "pid": 1,
                             "trace_id": "t", "dropped": 5, "spans": wire})
        assert reply["ok"] and reply["received"] == 10
        istats = col.ingest_stats()
        assert istats["ingestDropped"] == 6      # 10 past a bound of 4
        assert istats["shipDropped"] == 5        # worker-reported, apart
        assert istats["batches"] == 1 and istats["hosts"] == 1

        # one rollup payload -> bus -> status store -> REST route
        def stats_fn():
            return {"spansDropped": int(tr.spans_dropped),
                    "shipper": ship.delivery_stats(),
                    "collector": col.ingest_stats()}

        listener = AppStatusListener()
        bus = ListenerBus()
        bus.add_listener(listener)
        rep = UsageReporter(bus, interval_s=60, host="primary",
                            telemetry_fn=stats_fn)
        rep.stop()  # final flush posts the rollup
        served = api_v1(listener.store, "telemetry")
        assert served == listener.store.telemetry_stats()
        assert served["spansDropped"] == tr.spans_dropped
        assert served["shipper"]["bufferDropped"] > 0
        assert served["collector"]["ingestDropped"] == 6
    finally:
        col.stop()


def test_collector_replace_folds_cumulative_usage_per_host():
    """Shipped ledger snapshots are CUMULATIVE: re-ingesting the same
    host must REPLACE its usage, never double-count, and merged_usage
    sums across distinct hosts only."""
    from cycloneml_tpu.observe.attribution import TOTALS

    def _snap(n):
        return {"fit": {"scope": "fit", "tenant": "", "dispatches": n},
                TOTALS: {"scope": TOTALS, "tenant": "", "dispatches": n}}

    col = TraceCollector(host_label="primary")
    try:
        for host, n in (("w0", 1), ("w1", 2), ("w0", 4)):
            col._ingest({"kind": "spans", "host": host, "pid": 1,
                         "trace_id": "t", "spans": [], "usage": _snap(n)})
        merged = col.merged_usage()
        assert merged["fit"]["dispatches"] == 6      # w0 latest (4) + w1 (2)
        assert merged[TOTALS]["dispatches"] == 6
    finally:
        col.stop()


# -- heartbeat-fed clock offset --------------------------------------------------

def test_extended_heartbeat_feeds_offset_samples_and_trace_id():
    """The extended ping round trip yields NTP-style offset samples (same
    machine -> offset ~ 0 within the RTT bound) and announces the sender's
    trace id to the receiver."""
    from cycloneml_tpu.parallel.resilience import (HeartbeatReceiver,
                                                   HeartbeatSender,
                                                   HeartbeatServer)
    tracing.disable()
    tr = tracing.enable(max_spans=1000)
    clear_offset_samples()
    recv = HeartbeatReceiver(timeout_s=30.0, check_interval_s=5.0)
    server = HeartbeatServer(recv)
    sender = HeartbeatSender("wskew", server.address, interval_s=0.05)
    try:
        deadline = time.time() + 10
        while not offset_samples() or "wskew" not in recv.trace_ids():
            assert time.time() < deadline, "no extended-ping evidence"
            time.sleep(0.05)
        assert recv.trace_ids()["wskew"] == tr.trace_id
        off, err = estimate_offset(offset_samples())
        assert err is not None
        assert abs(off) <= max(err, 0.05)  # one clock: offset ~ 0
    finally:
        sender.stop()
        server.stop()
        recv.stop()
        tracing.disable()
        clear_offset_samples()


# -- flight recorder -------------------------------------------------------------

def _collective_prog(ctx):
    import jax.numpy as jnp
    from cycloneml_tpu.parallel.collectives import tree_aggregate

    rt = ctx.mesh_runtime
    data = rt.device_put_sharded_rows(np.ones((64, 2), dtype=np.float64))
    return tree_aggregate(lambda x: {"s": jnp.sum(x)}, rt, data), data


def test_flight_recorder_dumps_ring_on_fault(ctx, tmp_path):
    """Acceptance: with full tracing DISABLED, an injected fault at an
    existing faults.py point dumps the ring — the spans PRECEDING the
    fault plus the injection marker — as a valid Chrome trace."""
    from cycloneml_tpu.parallel.faults import (FaultInjector, FaultSchedule,
                                               TransientCollectiveError)

    tracing.disable()
    flight.reset()
    rec = flight.enable(ring_spans=64)
    flight.configure(dump_dir=str(tmp_path), min_interval_s=0.0)
    try:
        assert tracing.active() is rec and not rec.full
        prog, data = _collective_prog(ctx)
        for _ in range(6):   # the history the dump must preserve
            prog(data)
        sched = FaultSchedule(seed=0)
        sched.at("collectives.step", 1,
                 TransientCollectiveError("injected flake"))
        with FaultInjector(sched):
            with pytest.raises(TransientCollectiveError):
                prog(data)
        dumps = flight.dumps()
        assert len(dumps) == 1 and dumps[0]["reason"] == "fault"
        path = dumps[0]["path"]
        assert path and os.path.exists(path)
        assert validate_chrome_trace(path) == []
        obj = json.load(open(path))
        assert obj["otherData"]["flight_reason"] == "fault"
        kinds = {}
        for e in obj["traceEvents"]:
            if e.get("ph") != "M":
                kinds[e.get("cat")] = kinds.get(e.get("cat"), 0) + 1
        # >= 6 preceding collective dispatches + the fault instant
        assert kinds.get("collective", 0) >= 6
        faults_in_dump = [e for e in obj["traceEvents"]
                          if e.get("cat") == "instant"
                          and e.get("name") == "fault"]
        assert len(faults_in_dump) == 1
    finally:
        flight.disable()
        flight.configure(dump_dir=None, min_interval_s=1.0)
        flight.reset()


def test_flight_only_mode_pays_no_cost_analysis(ctx):
    """The always-on-is-cheap contract: under the flight ring (full
    tracing off) no XLA cost analysis runs, the budget guard stays
    unarmed, and per-job profile rollups do not post."""
    from cycloneml_tpu.conf import CycloneConf
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.observe import costs

    tracing.disable()
    flight.enable(ring_spans=256)
    try:
        before_analyze = costs.analyze_call_count()
        before_profiles = len(ctx.status_store.profiles)
        rng = np.random.RandomState(0)
        x = rng.randn(128, 6)
        y = (x @ rng.randn(6) > 0).astype(float)
        LogisticRegression(maxIter=4, regParam=0.01, tol=0.0).fit(
            MLFrame(ctx, {"features": x, "label": y}))
        ctx.listener_bus.wait_until_empty()
        assert costs.analyze_call_count() == before_analyze
        assert len(ctx.status_store.profiles) == before_profiles
        assert not costs.guard_armed(CycloneConf())
        # ...but the ring DID record the fit's spans
        tr = tracing.active()
        assert tr is not None and not tr.full
        kinds = {s.kind for s in tr.snapshot()}
        assert kinds & {"collective", "dispatch"}, kinds
    finally:
        flight.disable()


def test_tracing_enable_upgrades_flight_ring():
    tracing.disable()
    flight.enable(ring_spans=64)
    ring = tracing.active()
    assert ring is not None and not ring.full
    t = tracing.enable(max_spans=1000)
    try:
        assert t.full and tracing.active() is t and t is not ring
        assert flight.active() is None  # the ring lost to full tracing
        # flight.disable must NOT remove a full tracer
        flight.disable()
        assert tracing.active() is t
    finally:
        tracing.disable()


def test_flight_trigger_throttle():
    tracing.disable()
    flight.reset()
    flight.enable(ring_spans=64)
    flight.configure(dump_dir=None, min_interval_s=60.0)
    try:
        tracing.instant("x")
        assert flight.trigger("serving.shed") is not None
        assert flight.trigger("serving.shed") is None  # throttled
        assert flight.trigger_count() == 2              # ...but counted
    finally:
        flight.disable()
        flight.configure(min_interval_s=1.0)
        flight.reset()


# -- skew detector units ---------------------------------------------------------

def test_skew_detector_latches_slow_lane_once():
    det = SkewDetector(window=16, min_samples=4, mad_factor=4.0,
                       rel_factor=1.5)
    events = []
    det.subscribe(events.append)
    for i in range(8):
        for lane in ("a", "b", "c"):
            det.observe("serving.dispatch", lane, 0.010 + 0.0001 * i)
        det.observe("serving.dispatch", "d", 0.050)
    stragglers = [e for e in events if isinstance(e, StragglerDetected)]
    assert len(stragglers) == 1           # latched: ONE event per episode
    assert stragglers[0].position == "d"
    assert stragglers[0].group == "serving.dispatch"
    assert ("serving.dispatch", "d") in det.stragglers()
    # recovery unlatches (a later relapse may fire again)
    for _ in range(16):
        det.observe("serving.dispatch", "d", 0.010)
    assert det.stragglers() == []


def test_skew_detector_balanced_run_stays_silent():
    """False-positive guard: jittered-but-balanced lanes never convict."""
    det = SkewDetector(window=16, min_samples=4, mad_factor=4.0,
                       rel_factor=1.5)
    events = []
    det.subscribe(events.append)
    rng = np.random.RandomState(7)
    for _ in range(40):
        for lane in ("a", "b", "c", "d"):
            det.observe("oocore.stage", lane,
                        0.010 * (1.0 + 0.2 * rng.rand()))
    assert events == [] and det.stragglers() == []


def test_skew_slo_breach_latches_and_rearms():
    det = SkewDetector(slo_s={"collectives.step": 0.010})
    events = []
    det.subscribe(events.append)
    det.observe("collectives.step", "prog", 0.020)
    det.observe("collectives.step", "prog", 0.020)   # latched: no refire
    assert len(events) == 1 and isinstance(events[0], SloBreach)
    assert events[0].target_s == pytest.approx(0.010)
    det.observe("collectives.step", "prog", 0.005)   # recovery re-arms
    det.observe("collectives.step", "prog", 0.020)
    assert len(events) == 2


def test_skew_slo_only_groups_never_convict_stragglers():
    """collectives.step positions are different PROGRAMS — comparing
    their times cross-lane is meaningless, so the group is SLO-only."""
    det = SkewDetector(window=8, min_samples=2)
    events = []
    det.subscribe(events.append)
    for _ in range(8):
        det.observe("collectives.step", "fast_prog", 0.001)
        det.observe("collectives.step", "slow_prog", 1.000)
    assert not any(isinstance(e, StragglerDetected) for e in events)


def test_skew_detector_bounds_positions():
    det = SkewDetector(window=8, min_samples=2)
    for i in range(600):
        det.observe("serving.dispatch", f"lane{i}", 0.01)
    assert len(det._samples["serving.dispatch"]) <= 256


# -- chaos-injected slow lane (the acceptance path) ------------------------------

def _streaming_fixture(ctx, n=96, d=4, shard_rows=16):
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.oocore.shards import StreamingDataset

    rng = np.random.RandomState(0)
    x = rng.randn(n, d)
    y = (x @ rng.randn(d) > 0).astype(np.float64)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    return StreamingDataset.from_dataset(ds, shard_rows=shard_rows)


def test_oocore_chaos_slow_lane_raises_one_straggler(ctx, tmp_path):
    """Acceptance: a seeded chaos-delayed shard lane (every epoch's visit
    to shard 2 is slowed) raises EXACTLY ONE StragglerDetected with the
    correct position, visible via /api/v1/skew, status-store journal
    replay, and the web UI."""
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.oocore.objective import StreamingLossFunction
    from cycloneml_tpu.parallel.faults import FaultInjector, FaultSchedule
    from cycloneml_tpu.util.events import EventJournal
    from cycloneml_tpu.util.status import AppStatusListener, api_v1
    from cycloneml_tpu.util.webui import StatusWebUI

    det = SkewDetector(bus=ctx.listener_bus, window=32, min_samples=4,
                       mad_factor=4.0, rel_factor=1.5)
    prev = skew.install(det)
    journal_path = str(tmp_path / "events.jsonl")
    journal = EventJournal(journal_path)
    ctx.listener_bus.add_listener(journal)
    sds = _streaming_fixture(ctx)
    try:
        n_shards = sds.n_shards
        assert n_shards == 6
        loss = StreamingLossFunction(
            sds, aggregators.binary_logistic(4, fit_intercept=False))
        epochs = 10
        # the staging thread walks shards in order, so oocore.stage
        # invocation i is shard (i-1) % n_shards — delaying invocations
        # 3, 9, 15, ... slows EXACTLY the shard-2 lane, every epoch
        sched = FaultSchedule(seed=0)
        sched.at("oocore.stage",
                 range(3, epochs * n_shards + 1, n_shards), None,
                 delay_s=0.03)
        with FaultInjector(sched) as inj:
            for _ in range(epochs):
                loss(np.zeros(4))
        assert len(inj.log) == epochs  # the delay fired every epoch
        ctx.listener_bus.wait_until_empty()

        events = [e for e in ctx.status_store.skew_events()
                  if e["group"] == "oocore.stage"]
        stragglers = [e for e in events if e["kind"] == "straggler"]
        assert len(stragglers) == 1, f"expected one event, got {events}"
        assert stragglers[0]["position"] == "shard2"
        assert stragglers[0]["observedS"] > stragglers[0]["medianS"]
        # the REST route serves the same rows
        assert api_v1(ctx.status_store, "skew") == \
            ctx.status_store.skew_events()
        # journal replay rebuilds the verdict (history-server path)
        replayed = AppStatusListener()
        for e in EventJournal.replay(journal_path):
            replayed.on_event(e)
        rep = [e for e in replayed.store.skew_events()
               if e["kind"] == "straggler" and e["group"] == "oocore.stage"]
        assert len(rep) == 1 and rep[0]["position"] == "shard2"
        # the web UI serves the table data and the page section
        ui = StatusWebUI(ctx.status_store)
        try:
            rows = json.loads(urllib.request.urlopen(
                f"{ui.url}api/v1/skew", timeout=5).read())
            assert any(r.get("position") == "shard2" for r in rows)
            page = urllib.request.urlopen(ui.url, timeout=5).read().decode()
            assert 'id="skew"' in page
        finally:
            ui.stop()
        # the MeshSupervisor subscription hook received the verdict
        det2 = SkewDetector(window=32, min_samples=4)
        sup = ctx.mesh_supervisor()
        sup.attach_skew(det2)
        for i in range(8):
            for lane in ("a", "b"):
                det2.observe("oocore.stage", lane, 0.001)
            det2.observe("oocore.stage", "c", 0.050)
        assert "oocore.stage:c" in sup.stragglers()
    finally:
        ctx.listener_bus.remove_listener(journal)
        journal.close()
        sds.close()
        skew.uninstall(det)
        if prev is not None:
            skew.install(prev)


def test_oocore_balanced_run_raises_no_straggler(ctx):
    """The false-positive guard at the REAL site: a balanced streamed run
    (no chaos) must keep the detector silent."""
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.oocore.objective import StreamingLossFunction

    det = SkewDetector(bus=None, window=32, min_samples=4,
                       mad_factor=4.0, rel_factor=1.5)
    prev = skew.install(det)
    sds = _streaming_fixture(ctx)
    try:
        loss = StreamingLossFunction(
            sds, aggregators.binary_logistic(4, fit_intercept=False))
        for _ in range(10):
            loss(np.zeros(4))
        assert det.stragglers() == []
        assert not any(isinstance(e, StragglerDetected)
                       for e in det.events())
    finally:
        sds.close()
        skew.uninstall(det)
        if prev is not None:
            skew.install(prev)


def test_master_side_rtt_skew_latches_straggler():
    """ISSUE 13 satellite: the receiver's per-worker RTT lanes (fed by
    the workers' reported round trips over the extended heartbeat wire)
    are a real cross-lane straggler group — one worker whose RTT median
    pulls away from the fleet latches EXACTLY ONE StragglerDetected,
    which a MeshSupervisor subscription records as mitigation input."""
    from cycloneml_tpu.observe import skew
    from cycloneml_tpu.parallel.resilience import HeartbeatReceiver

    det = SkewDetector(window=16, min_samples=4, mad_factor=4.0,
                       rel_factor=1.5, min_gap_s=0.010)
    events = []
    det.subscribe(events.append)
    prev = skew.install(det)
    recv = HeartbeatReceiver(timeout_s=30.0)
    try:
        for i in range(8):
            for w in ("w0", "w1", "w2"):
                recv.note_rtt(w, 0.004 + 0.0002 * i)   # healthy fleet
            recv.note_rtt("w3", 0.120)                 # congested host
        stragglers = [e for e in events if isinstance(e, StragglerDetected)]
        assert len(stragglers) == 1
        assert stragglers[0].group == "heartbeat.rtt"
        assert stragglers[0].position == "w3"
        assert ("heartbeat.rtt", "w3") in det.stragglers()
        # balanced fleets stay silent: no latch for the healthy workers
        assert not any(s.position in ("w0", "w1", "w2") for s in stragglers)
    finally:
        skew.uninstall(det)
        if prev is not None:
            skew.install(prev)
        recv.stop()


def test_migration_and_precision_events_reach_status_api():
    """BlocksMigrated and PrecisionFallback must fold into the status
    store and surface via the /api/v1/migrations and /api/v1/precision
    routes + web UI sections (graftlint JX021 caught both emitted but
    dropped on the listener floor)."""
    from cycloneml_tpu.util.events import BlocksMigrated, PrecisionFallback
    from cycloneml_tpu.util.status import AppStatusListener, api_v1
    from cycloneml_tpu.util.webui import StatusWebUI

    lst = AppStatusListener()
    lst.on_event(BlocksMigrated(n_datasets=2, bytes=4096, n_devices=3,
                                time_ms=7).to_json())
    lst.on_event(PrecisionFallback(estimator="LinearRegression",
                                   reason="envelope risk 0.31 > 0.25",
                                   time_ms=9).to_json())
    store = lst.store
    assert api_v1(store, "migrations") == [
        {"nDatasets": 2, "bytes": 4096, "nDevices": 3, "time": 7}]
    assert api_v1(store, "precision") == [
        {"estimator": "LinearRegression", "fromDtype": "float8_e4m3fn",
         "toDtype": "bfloat16", "reason": "envelope risk 0.31 > 0.25",
         "time": 9}]
    # accessors hand out copies — a caller mutating a row must not
    # corrupt the store
    api_v1(store, "migrations")[0]["bytes"] = 0
    assert store.migration_events()[0]["bytes"] == 4096
    ui = StatusWebUI(store)
    try:
        rows = json.loads(urllib.request.urlopen(
            f"{ui.url}api/v1/migrations", timeout=5).read())
        assert rows and rows[0]["nDatasets"] == 2
        prec = json.loads(urllib.request.urlopen(
            f"{ui.url}api/v1/precision", timeout=5).read())
        assert prec and prec[0]["estimator"] == "LinearRegression"
        page = urllib.request.urlopen(ui.url, timeout=5).read().decode()
        assert 'id="migr"' in page and 'id="prec"' in page
    finally:
        ui.stop()
