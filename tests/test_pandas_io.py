"""Tests: pandas-style facade + SQL datasources (parquet/json/csv writers).

Parity model: cross-check CycloneFrame results against real pandas where it
is installed (it is in this image), mirroring how the reference's
pandas-on-Spark suites assert against pandas ground truth.
"""

import os

import numpy as np
import pandas as pd
import pytest

from cycloneml_tpu.pandas import CycloneFrame, CycloneSeries
from cycloneml_tpu.sql.session import CycloneSession


@pytest.fixture
def frame():
    return CycloneFrame({
        "a": [3, 1, 2, 1],
        "b": [30.0, 10.0, 20.0, 40.0],
        "k": ["x", "y", "x", "y"],
    })


def test_basic_metadata(frame):
    assert frame.shape == (4, 3)
    assert frame.columns == ["a", "b", "k"]
    assert len(frame) == 4


def test_selection_and_masking(frame):
    assert frame["a"].to_list() == [3, 1, 2, 1]
    sub = frame[["a", "b"]]
    assert sub.columns == ["a", "b"]
    picked = frame[frame["a"] > 1]
    assert picked["a"].to_list() == [3, 2]
    both = frame[(frame["a"] > 0) & (frame["b"] < 25.0)]
    assert both["b"].to_list() == [10.0, 20.0]


def test_series_ops_match_pandas(frame):
    ps = pd.Series([3, 1, 2, 1])
    s = frame["a"]
    assert (s + 1).to_list() == (ps + 1).tolist()
    assert (s * 2).to_list() == (ps * 2).tolist()
    assert s.mean() == ps.mean()
    assert s.std() == pytest.approx(ps.std())
    assert s.nunique() == ps.nunique()
    vc = s.value_counts()
    assert vc.values[0] == 2 and vc.index[0] == 1


def test_assign_setitem_drop_rename(frame):
    out = frame.assign(c=lambda f: f["a"] + f["b"])
    assert out["c"].to_list() == [33.0, 11.0, 22.0, 41.0]
    out["d"] = 7
    assert out["d"].to_list() == [7] * 4
    assert "a" not in out.drop(["a"]).columns
    assert out.rename({"a": "A"}).columns[0] == "A"
    assert frame.columns == ["a", "b", "k"]  # originals untouched


def test_sort_values_matches_pandas(frame):
    pdf = frame.to_pandas()
    got = frame.sort_values(["a", "b"])["b"].to_list()
    want = pdf.sort_values(["a", "b"])["b"].tolist()
    assert got == want
    got_desc = frame.sort_values("b", ascending=False)["b"].to_list()
    assert got_desc == sorted(frame["b"].to_list(), reverse=True)


def test_groupby_matches_pandas(frame):
    pdf = frame.to_pandas()
    got = frame.groupby("k").sum().sort_values("k")
    want = pdf.groupby("k", as_index=False)[["a", "b"]].sum().sort_values("k")
    assert got["a"].to_list() == want["a"].tolist()
    assert got["b"].to_list() == want["b"].tolist()
    m = frame.groupby("k").mean().sort_values("k")
    wm = pdf.groupby("k", as_index=False)[["a", "b"]].mean().sort_values("k")
    np.testing.assert_allclose(m["b"].to_numpy(), wm["b"].to_numpy())
    agg = frame.groupby("k").agg({"b": "max", "a": "min"}).sort_values("k")
    assert agg["b_max"].to_list() == [30.0, 40.0]
    assert agg["a_min"].to_list() == [2, 1]
    cnt = frame.groupby("k").count().sort_values("k")
    assert cnt["a"].to_list() == [2, 2]
    assert cnt["b"].to_list() == [2, 2]  # all non-key columns counted


def test_merge_matches_pandas(frame):
    other = CycloneFrame({"k": ["x", "z"], "extra": [100.0, 200.0]})
    got = frame.merge(other, on="k").sort_values("a")
    pdf = frame.to_pandas().merge(other.to_pandas(), on="k").sort_values("a")
    assert got["extra"].to_list() == pdf["extra"].tolist()
    left = frame.merge(other, on="k", how="left")
    assert left.shape[0] == 4


def test_missing_data():
    f = CycloneFrame({"x": [1.0, np.nan, 3.0], "y": [np.nan, 2.0, 2.0]})
    assert f.isna()["x"].to_list() == [False, True, False]
    assert f.fillna(0.0)["x"].to_list() == [1.0, 0.0, 3.0]
    assert f.dropna().shape == (1, 2)
    assert f["x"].count() == 2


def test_describe_and_apply(frame):
    d = frame.describe()
    assert d["a"].to_list()[0] == 4  # count
    assert d["b"].to_list()[1] == pytest.approx(25.0)  # mean
    doubled = frame[["a", "b"]].apply(lambda s: s.values * 2)
    assert doubled["a"].to_list() == [6, 2, 4, 2]
    rowsum = frame.apply(lambda r: r["a"] + r["b"], axis=1)
    assert rowsum.to_list() == [33.0, 11.0, 22.0, 41.0]


def test_pandas_roundtrip(frame):
    pdf = frame.to_pandas()
    back = CycloneFrame.from_pandas(pdf)
    assert back["k"].to_list() == frame["k"].to_list()
    assert back.to_sql_df().count() == 4


def test_sql_bridge(frame):
    df = frame.to_sql_df()
    assert df.filter("a > 1").count() == 2
    assert df.to_pandas_frame()["a"].to_list() == [3, 1, 2, 1]


# -- datasources ----------------------------------------------------------------

def test_parquet_roundtrip(tmp_path):
    s = CycloneSession()
    df = s.create_data_frame({"x": [1.0, 2.5], "name": ["ab", "cd"],
                              "n": [1, 2]})
    p = str(tmp_path / "data.parquet")
    df.write.parquet(p)
    back = s.read_parquet(p)
    assert back.count() == 2
    rows = back.order_by("n").collect()
    assert rows[0].x == 1.0 and rows[0].name == "ab"
    # parquet round-trips dtypes: n stays integral
    assert back.to_dict()["n"].dtype.kind == "i"


def test_json_roundtrip(tmp_path):
    s = CycloneSession()
    df = s.create_data_frame({"x": [1.5, 2.0], "tag": ["a", "b"]})
    p = str(tmp_path / "data.json")
    df.write.json(p)
    back = s.read_json(p)
    assert back.count() == 2
    assert back.to_dict()["tag"].tolist() == ["a", "b"]
    # integers detected as ints from JSON
    (tmp_path / "ints.json").write_text('{"v": 1}\n{"v": 2}\n')
    assert s.read_json(str(tmp_path / "ints.json")).to_dict()["v"].dtype.kind == "i"
    # whole-valued FLOATS keep their float dtype through a round-trip
    whole = s.create_data_frame({"f": [1.0, 2.0]})
    wp = str(tmp_path / "whole.json")
    whole.write.json(wp)
    assert s.read_json(wp).to_dict()["f"].dtype.kind == "f"


def test_csv_writer_and_save_modes(tmp_path):
    s = CycloneSession()
    df = s.create_data_frame({"a": [1.0, 2.0]})
    p = str(tmp_path / "out.csv")
    df.write.csv(p)
    assert open(p).read().startswith("a\n")
    with pytest.raises(FileExistsError):
        df.write.csv(p)  # default error mode
    df.write.mode("ignore").csv(p)  # no-op
    df.write.mode("overwrite").csv(p)
    df.write.mode("append").csv(p)
    assert os.path.exists(str(tmp_path / "out-part1.csv"))
    with pytest.raises(ValueError, match="save mode"):
        df.write.mode("nope")


def test_append_parts_are_read_back(tmp_path):
    s = CycloneSession()
    df = s.create_data_frame({"v": [1.0]})
    p = str(tmp_path / "d.json")
    df.write.json(p)
    df.write.mode("append").json(p)
    assert s.read_json(p).count() == 2  # appended part not lost
    df.write.mode("overwrite").json(p)
    assert s.read_json(p).count() == 1  # stale parts removed


def test_csv_header_false_and_quoting(tmp_path):
    s = CycloneSession()
    df = s.create_data_frame({"t": ["a,b", "plain"], "v": [1.0, 2.0]})
    p = str(tmp_path / "q.csv")
    df.write.option("header", "false").csv(p)
    body = open(p).read()
    assert not body.startswith("t,")  # string 'false' respected
    assert '"a,b"' in body  # embedded delimiter quoted


def test_setitem_rejects_wrong_length():
    f = CycloneFrame({"a": [1, 2, 3, 4]})
    with pytest.raises(ValueError, match="length"):
        f["d"] = [9, 9]


def test_read_parquet_directory(tmp_path):
    s = CycloneSession()
    s.create_data_frame({"v": [1.0]}).write.parquet(str(tmp_path / "p1.parquet"))
    s.create_data_frame({"v": [2.0]}).write.parquet(str(tmp_path / "p2.parquet"))
    (tmp_path / "_SUCCESS").write_text("")  # marker files skipped
    back = s.read_parquet(str(tmp_path))
    assert sorted(back.to_dict()["v"].tolist()) == [1.0, 2.0]


# -- Hive-style partitioning ----------------------------------------------------

def _part_df(s):
    return s.create_data_frame({
        "dept": ["eng", "eng", "hr", "sales", "sales"],
        "year": [2024, 2025, 2024, 2024, 2025],
        "salary": [10.0, 20.0, 30.0, 40.0, 50.0],
    })


def test_partitioned_parquet_roundtrip(tmp_path):
    from cycloneml_tpu.sql.session import CycloneSession
    s = CycloneSession()
    path = str(tmp_path / "ds")
    _part_df(s).write.partition_by("dept", "year").parquet(path)
    # layout: dept=eng/year=2024/part-0.parquet etc., partition cols dropped
    # from the files themselves
    assert os.path.isdir(os.path.join(path, "dept=eng", "year=2024"))
    import pyarrow.parquet as pq
    one = pq.read_table(os.path.join(path, "dept=eng", "year=2024",
                                     "part-0.parquet"))
    assert one.column_names == ["salary"]

    back = s.read_parquet(path).order_by("salary").to_dict()
    assert back["salary"].tolist() == [10.0, 20.0, 30.0, 40.0, 50.0]
    assert back["dept"].tolist() == ["eng", "eng", "hr", "sales", "sales"]
    assert back["year"].tolist() == [2024, 2025, 2024, 2024, 2025]
    assert back["year"].dtype.kind == "i"  # int inference, as the reference


def test_partitioned_json_and_pruning_by_filter(tmp_path):
    from cycloneml_tpu.sql.session import CycloneSession
    from cycloneml_tpu.sql.column import col
    s = CycloneSession()
    path = str(tmp_path / "j")
    _part_df(s).write.partition_by("dept").json(path)
    df = s.read_json(path)
    out = df.filter(col("dept") == "sales").order_by("salary").to_dict()
    assert out["salary"].tolist() == [40.0, 50.0]


def test_partitioned_save_modes(tmp_path):
    from cycloneml_tpu.sql.session import CycloneSession
    s = CycloneSession()
    path = str(tmp_path / "m")
    w = _part_df(s).write.partition_by("dept")
    w.parquet(path)
    with pytest.raises(FileExistsError):
        _part_df(s).write.partition_by("dept").parquet(path)
    # append adds part files; row count doubles
    _part_df(s).write.mode("append").partition_by("dept").parquet(path)
    assert len(s.read_parquet(path).to_dict()["salary"]) == 10
    # overwrite replaces everything
    _part_df(s).write.mode("overwrite").partition_by("dept").parquet(path)
    assert len(s.read_parquet(path).to_dict()["salary"]) == 5
    # ignore is a no-op
    _part_df(s).write.mode("ignore").partition_by("dept").parquet(path)
    assert len(s.read_parquet(path).to_dict()["salary"]) == 5


def test_partition_by_validation(tmp_path):
    from cycloneml_tpu.sql.session import CycloneSession
    s = CycloneSession()
    with pytest.raises(KeyError, match="partition columns"):
        _part_df(s).write.partition_by("nope").parquet(str(tmp_path / "x"))
    with pytest.raises(ValueError, match="every column"):
        (_part_df(s).write.partition_by("dept", "year", "salary")
         .parquet(str(tmp_path / "y")))


def test_partitioned_ragged_schema_fills_null(tmp_path):
    """A data column present in only some partition files must fill null in
    the others (flat JSON union semantics), never come back ragged."""
    import json as _json
    from cycloneml_tpu.sql.session import CycloneSession
    path = tmp_path / "r"
    (path / "dept=eng").mkdir(parents=True)
    (path / "dept=hr").mkdir(parents=True)
    (path / "dept=eng" / "part-0.json").write_text(
        _json.dumps({"salary": 1.0, "bonus": 5.0}) + "\n")
    (path / "dept=hr" / "part-0.json").write_text(
        _json.dumps({"salary": 2.0}) + "\n")
    s = CycloneSession()
    out = s.read_json(str(path)).order_by("salary").to_dict()
    assert len(out["bonus"]) == 2 == len(out["salary"])
    assert out["bonus"][0] == 5.0 and out["bonus"][1] is None


def test_partitioned_empty_write_reads_back_empty(tmp_path):
    from cycloneml_tpu.sql.session import CycloneSession
    s = CycloneSession()
    empty = s.create_data_frame({"dept": [], "salary": []})
    path = str(tmp_path / "e")
    empty.write.partition_by("dept").parquet(path)
    assert s.read_parquet(path).count() == 0


def test_pmml_logistic_threshold_encoded():
    import xml.etree.ElementTree as ET
    from cycloneml_tpu.ml.classification.logistic_regression import (
        LogisticRegressionModel)
    from cycloneml_tpu.ml.pmml import to_pmml

    def cat0_intercept(m):
        xml = to_pmml(m).replace(
            ' xmlns="http://www.dmg.org/PMML-4_2"', "")
        rm = ET.fromstring(xml).find("RegressionModel")
        by = {t.get("targetCategory"): t
              for t in rm.findall("RegressionTable")}
        return float(by["0"].get("intercept"))

    m = LogisticRegressionModel(coefficient_matrix=np.array([[1.0]]),
                                intercept_vector=np.array([0.0]))
    assert cat0_intercept(m) == pytest.approx(0.0)  # default threshold 0.5
    m.set("threshold", 0.7)
    assert cat0_intercept(m) == pytest.approx(-np.log(1 / 0.7 - 1))


def test_orc_roundtrip_and_partitioned_write(tmp_path):
    s = CycloneSession()
    df = s.create_data_frame({"x": [1.0, 2.5, 3.0], "name": ["ab", "cd", "ab"],
                              "n": [1, 2, 3]})
    p = str(tmp_path / "data.orc")
    df.write.orc(p)
    back = s.read_orc(p)
    assert back.count() == 3
    rows = back.order_by("n").collect()
    assert rows[0].x == 1.0 and rows[0].name == "ab"
    assert back.to_dict()["n"].dtype.kind == "i"
    # save modes apply
    with pytest.raises(FileExistsError):
        df.write.orc(p)
    df.write.mode("append").orc(p)
    assert s.read_orc(p).count() == 6
    # Hive-style partitioned write + discovery read
    d = str(tmp_path / "byname")
    df.write.partition_by("name").orc(d)
    assert os.path.isdir(os.path.join(d, "name=ab"))
    back2 = s.read_orc(d)
    assert back2.count() == 3
    got = back2.order_by("n").to_dict()
    assert got["name"].tolist() == ["ab", "cd", "ab"]
    assert sorted(got["n"].tolist()) == [1, 2, 3]


def test_jdbc_roundtrip_and_partitioned_read(tmp_path):
    s = CycloneSession()
    url = f"jdbc:sqlite:{tmp_path / 'db.sqlite'}"
    df = s.create_data_frame({"id": [1, 2, 3, 4, 5],
                              "v": [0.5, 1.5, 2.5, 3.5, 4.5],
                              "tag": ["a", "b", "a", "b", "a"]})
    df.write.jdbc(url, "t")
    back = s.read_jdbc(url, "t")
    assert back.count() == 5
    assert back.to_dict()["id"].dtype.kind == "i"
    assert back.to_dict()["tag"].tolist() == ["a", "b", "a", "b", "a"]
    # partitioned range read returns the same rows
    part = s.read_jdbc(url, "t", partition_column="id", num_partitions=3)
    assert sorted(part.to_dict()["id"].tolist()) == [1, 2, 3, 4, 5]
    # subquery source, as the reference's "(select ...) alias" form
    sub = s.read_jdbc(url, "(SELECT id, v FROM t WHERE id > 3)")
    assert sorted(sub.to_dict()["id"].tolist()) == [4, 5]
    # save modes on the table
    with pytest.raises(FileExistsError):
        df.write.jdbc(url, "t")
    df.write.mode("append").jdbc(url, "t")
    assert s.read_jdbc(url, "t").count() == 10
    df.write.mode("overwrite").jdbc(url, "t")
    assert s.read_jdbc(url, "t").count() == 5


def test_jdbc_partitioned_read_keeps_null_keys(tmp_path):
    """Rows with a NULL partition column ride the first slice (review r3;
    the reference appends OR IS NULL in JDBCRelation.columnPartition)."""
    import sqlite3
    db = str(tmp_path / "n.db")
    con = sqlite3.connect(db)
    con.execute("CREATE TABLE t (id INTEGER, v REAL)")
    con.executemany("INSERT INTO t VALUES (?, ?)",
                    [(1, 0.5), (2, 1.5), (None, 9.0), (4, 2.5)])
    con.commit(); con.close()
    s = CycloneSession()
    part = s.read_jdbc(f"jdbc:sqlite:{db}", "t",
                       partition_column="id", num_partitions=2)
    assert part.count() == 4
    assert 9.0 in part.to_dict()["v"].tolist()


def test_avro_roundtrip_and_partitioned_write(tmp_path):
    """Pure-Python Avro OCF: nullable unions, NaN<->null, deflate blocks,
    save modes, partitioned writes + discovery."""
    s = CycloneSession()
    df = s.create_data_frame({"x": [1.5, float("nan"), 3.0],
                              "name": ["ab", "cd", None],
                              "n": [10, 20, 30],
                              "flag": [True, False, True]})
    p = str(tmp_path / "data.avro")
    df.write.avro(p)
    back = s.read_avro(p)
    assert back.count() == 3
    got = back.order_by("n").to_dict()
    assert got["n"].dtype.kind == "i" and got["n"].tolist() == [10, 20, 30]
    assert got["flag"].dtype.kind == "b"
    assert got["name"].tolist() == ["ab", "cd", None]
    assert np.isnan(got["x"][1]) and got["x"][0] == 1.5
    with pytest.raises(FileExistsError):
        df.write.avro(p)
    df.write.mode("append").avro(p)
    assert s.read_avro(p).count() == 6
    # spec conformance spot-check: magic + declared deflate codec
    raw = open(p, "rb").read(4)
    assert raw == b"Obj\x01"
    d = str(tmp_path / "byflag")
    df.write.partition_by("flag").avro(d)
    assert s.read_avro(d).count() == 3


def test_filescan_pushdown_parquet_and_jdbc(tmp_path):
    """Lazy connector scans: the optimizer pushes simple predicates and
    required columns into the FileScan; results match the eager path and
    the scan's materialization honors the pushdown (V2 connector
    surface)."""
    from cycloneml_tpu.sql.optimizer import optimize
    from cycloneml_tpu.sql.plan import FileScan

    s = CycloneSession()
    df = s.create_data_frame({"id": np.arange(100, dtype=np.int64),
                              "v": np.arange(100) * 0.5,
                              "tag": [f"t{i % 3}" for i in range(100)]})
    p = str(tmp_path / "d.parquet")
    df.write.parquet(p)

    lazy = s.scan_parquet(p)
    assert lazy.columns == ["id", "v", "tag"]  # header-only schema
    q = lazy.filter("id >= 90").select("id", "v")
    plan = optimize(q.plan)
    scans = [n for n in _walk(plan) if isinstance(n, FileScan)]
    assert scans and ("id", "ge", 90) in scans[0].filters
    assert set(scans[0].columns) <= {"id", "v"}
    rows = q.order_by("id").collect()
    assert len(rows) == 10 and rows[0].id == 90 and rows[0].v == 45.0
    # parity with the eager reader
    eager = s.read_parquet(p).filter("id >= 90").select("id", "v")
    assert sorted(r.id for r in eager.collect()) == sorted(
        r.id for r in q.collect())
    # the scan itself applies pushdown at materialization: fewer rows read
    pushed = FileScan("parquet", p, filters=[("id", "ge", 90)])
    assert len(pushed.execute()["id"]) <= 100  # row-group granularity
    assert (pushed.execute()["id"] >= 0).all()

    # jdbc: WHERE + column list pushed into SQL
    url = f"jdbc:sqlite:{tmp_path / 'p.db'}"
    df.write.jdbc(url, "t")
    jq = s.scan_jdbc(url, "t").filter("id < 5").select("id")
    got = sorted(r.id for r in jq.collect())
    assert got == [0, 1, 2, 3, 4]
    jscan = [n for n in _walk(optimize(jq.plan))
             if isinstance(n, FileScan)][0]
    assert ("id", "lt", 5) in jscan.filters
    # pushed-WHERE materialization returns exactly the matching rows
    assert len(jscan.execute()["id"]) == 5


def test_filescan_orc_avro_execute(tmp_path):
    s = CycloneSession()
    df = s.create_data_frame({"a": [1, 2, 3, 4], "b": ["x", "y", "x", "z"]})
    po = str(tmp_path / "d.orc")
    pa_ = str(tmp_path / "d.avro")
    df.write.orc(po)
    df.write.avro(pa_)
    for fmt, path in (("orc", po), ("avro", pa_)):
        q = getattr(s, f"scan_{fmt}")(path).filter("a > 2")
        rows = q.order_by("a").collect()
        assert [r.a for r in rows] == [3, 4], fmt


def _walk(plan):
    yield plan
    for c in plan.children:
        yield from _walk(c)


def test_filescan_append_siblings_and_partitioned_avro(tmp_path):
    """Review r3: lazy scans must see SaveMode.append part files and
    partitioned avro directories, like the eager readers."""
    s = CycloneSession()
    df = s.create_data_frame({"a": [1, 2], "g": ["x", "y"]})
    for fmt in ("parquet", "orc", "avro"):
        p = str(tmp_path / f"d.{fmt}")
        getattr(df.write, fmt)(p)
        getattr(df.write.mode("append"), fmt)(p)
        assert getattr(s, f"scan_{fmt}")(p).count() == 4, fmt
    d = str(tmp_path / "byg")
    df.write.partition_by("g").avro(d)
    assert s.scan_avro(d).count() == 2
    # filters on the directory path still apply (vectorized residual)
    assert s.scan_avro(d).filter("a > 1").count() == 1


def test_filescan_jdbc_quoted_literals(tmp_path):
    """Pushed WHERE literals ride as bind parameters — quotes in values
    must not break (or be parsed as identifiers by) the engine."""
    url = f"jdbc:sqlite:{tmp_path / 'q.db'}"
    s = CycloneSession()
    tricky = "it's \"q\""
    s.create_data_frame({"id": [1, 2], "tag": [tricky, "plain"]}
                        ).write.jdbc(url, "t")
    from cycloneml_tpu.sql.functions import col
    q = s.scan_jdbc(url, "t").filter(col("tag") == tricky)
    rows = q.collect()
    assert len(rows) == 1 and rows[0].id == 1
    # a value equal to a column NAME must match rows, not the column
    s.create_data_frame({"id": [3], "tag": ["id"]}
                        ).write.mode("append").jdbc(url, "t")
    assert s.scan_jdbc(url, "t").filter(col("tag") == "id").count() == 1


def test_avro_uint64_out_of_range_rejected(tmp_path):
    from cycloneml_tpu.sql.avro import write_avro
    with pytest.raises(ValueError, match="uint64"):
        write_avro({"u": np.array([1 << 63], dtype=np.uint64)},
                   str(tmp_path / "u.avro"))


def test_filescan_ne_not_pushed_null_semantics(tmp_path):
    """col != literal must keep NULL rows (numpy semantics) — native scans
    drop them under SQL three-valued logic, so != is never pushed."""
    from cycloneml_tpu.sql.functions import col
    from cycloneml_tpu.sql.optimizer import optimize
    from cycloneml_tpu.sql.plan import FileScan
    s = CycloneSession()
    df = s.create_data_frame({"id": [1, 2, 3],
                              "tag": np.array(["a", None, "b"], object)})
    url = f"jdbc:sqlite:{tmp_path / 'n.db'}"
    df.write.jdbc(url, "t")
    q = s.scan_jdbc(url, "t").filter(col("tag") != "a")
    scan = [n for n in _walk(optimize(q.plan))
            if isinstance(n, FileScan)][0]
    assert not scan.filters  # nothing pushed
    assert sorted(r.id for r in q.collect()) == [2, 3]


def test_filescan_directory_read_once(tmp_path, monkeypatch):
    """One query over a partitioned dataset reads each part file once,
    shared across analysis, pushdown clones, and execution."""
    from cycloneml_tpu.sql import avro as av
    s = CycloneSession()
    df = s.create_data_frame({"a": [1, 2, 3], "g": ["x", "y", "x"]})
    d = str(tmp_path / "byg")
    df.write.partition_by("g").avro(d)
    calls = {"n": 0}
    orig = av.read_avro_file

    def counting(path):
        calls["n"] += 1
        return orig(path)

    monkeypatch.setattr(av, "read_avro_file", counting)
    rows = s.scan_avro(d).filter("a > 1").select("a").order_by("a").collect()
    assert [r.a for r in rows] == [2, 3]
    assert calls["n"] <= 2, calls  # 2 part files, each read at most once


def test_avro_schema_name_sanitized(tmp_path):
    from cycloneml_tpu.sql.avro import _read_header
    import json as _json
    s = CycloneSession()
    df = s.create_data_frame({"a": [1]})
    p = str(tmp_path / "2-bad name.avro")
    df.write.avro(p)
    with open(p, "rb") as fh:
        meta, _ = _read_header(fh)
    name = _json.loads(meta["avro.schema"])["name"]
    import re
    assert re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", name), name
    assert s.read_avro(p).count() == 1
