"""GradientDescent/Updater tests (ref: GradientDescentSuite, UpdaterSuite —
convergence toward the L-BFGS/closed-form solution, updater semantics)."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
from cycloneml_tpu.ml.optim import aggregators
from cycloneml_tpu.ml.optim.gradient_descent import (GradientDescent,
                                                     L1Updater, SimpleUpdater,
                                                     SquaredL2Updater)
from cycloneml_tpu.ml.optim.lbfgs import LBFGS
from cycloneml_tpu.ml.optim.loss import DistributedLossFunction
from cycloneml_tpu.ml.optim.sparse_aggregators import binary_logistic_sparse


def _data(ctx, n=400, d=5, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    # label noise keeps the unregularized optimum finite (separable data
    # sends LBFGS coefficients to ±inf, which SGD can't chase)
    y = (x @ rng.randn(d) + 1.5 * rng.randn(n) > 0).astype(np.float64)
    return InstanceDataset.from_numpy(ctx, x, y), x, y, d


def test_full_batch_converges_to_lbfgs_solution(ctx):
    ds, x, y, d = _data(ctx)
    agg = aggregators.binary_logistic(d, fit_intercept=False)
    target = LBFGS(max_iter=100, tol=1e-10).minimize(
        DistributedLossFunction(ds, agg), np.zeros(d))
    gd = GradientDescent(step_size=4.0, num_iterations=400,
                         convergence_tol=0.0)
    w, hist = gd.optimize(ds, agg, np.zeros(d))
    assert hist[-1] < hist[0]
    # SGD at stepSize/√t gets close, not exact (same as the reference suite's
    # loose tolerances)
    np.testing.assert_allclose(w, target.x, rtol=0.15, atol=0.05)


def test_minibatch_sampling_still_descends(ctx):
    ds, *_ , d = _data(ctx, n=600)
    agg = aggregators.binary_logistic(d, fit_intercept=False)
    gd = GradientDescent(step_size=2.0, num_iterations=150,
                         mini_batch_fraction=0.3, convergence_tol=0.0,
                         seed=7)
    w, hist = gd.optimize(ds, agg, np.zeros(d))
    assert np.mean(hist[-10:]) < 0.75 * hist[0]


def test_l2_updater_shrinks_weights(ctx):
    ds, *_, d = _data(ctx)
    agg = aggregators.binary_logistic(d, fit_intercept=False)
    free, _ = GradientDescent(step_size=2.0, num_iterations=100,
                              convergence_tol=0.0).optimize(
        ds, agg, np.zeros(d))
    reg, _ = GradientDescent(step_size=2.0, num_iterations=100,
                             reg_param=0.5, updater=SquaredL2Updater(),
                             convergence_tol=0.0).optimize(
        ds, agg, np.zeros(d))
    assert np.linalg.norm(reg) < np.linalg.norm(free)


def test_l1_updater_produces_sparsity(ctx):
    ds, *_, d = _data(ctx, d=8)
    agg = aggregators.binary_logistic(d, fit_intercept=False)
    w, _ = GradientDescent(step_size=1.0, num_iterations=120, reg_param=0.2,
                           updater=L1Updater(),
                           convergence_tol=0.0).optimize(ds, agg, np.zeros(d))
    assert (np.abs(w) < 1e-12).sum() > 0  # exact zeros from soft threshold


def test_updater_semantics_unit():
    w = np.array([1.0, -2.0])
    g = np.array([0.5, 0.5])
    sw, r = SimpleUpdater().compute(w, g, step_size=1.0, iteration=4,
                                    reg_param=0.0)
    np.testing.assert_allclose(sw, w - 0.5 * g)  # eta = 1/√4
    assert r == 0.0
    lw, lr = L1Updater().compute(np.array([0.3, -0.1]), np.zeros(2),
                                 step_size=1.0, iteration=1, reg_param=0.2)
    np.testing.assert_allclose(lw, [0.1, 0.0])  # shrink by 0.2
    l2w, l2r = SquaredL2Updater().compute(w, g, 1.0, 1, reg_param=0.1)
    np.testing.assert_allclose(l2w, w * 0.9 - g)
    assert l2r == pytest.approx(0.05 * float(l2w @ l2w))


def test_gradient_descent_on_sparse_tier(ctx):
    rng = np.random.RandomState(5)
    n, d, k = 300, 20, 4
    rows = []
    dense = np.zeros((n, d))
    for i in range(n):
        idx = np.sort(rng.choice(d, k, replace=False))
        val = rng.randn(k)
        rows.append((idx, val))
        dense[i, idx] = val
    y = (dense @ rng.randn(d) > 0).astype(float)
    sds = SparseInstanceDataset.from_rows(ctx, rows, y=y, n_features=d)
    gd = GradientDescent(step_size=2.0, num_iterations=100,
                         convergence_tol=0.0)
    w, hist = gd.optimize(sds, binary_logistic_sparse(d, False), np.zeros(d))
    assert hist[-1] < 0.7 * hist[0]


def test_convergence_tol_stops_early(ctx):
    ds, *_, d = _data(ctx)
    agg = aggregators.binary_logistic(d, fit_intercept=False)
    _, hist = GradientDescent(step_size=0.5, num_iterations=500,
                              convergence_tol=0.01).optimize(
        ds, agg, np.zeros(d))
    assert len(hist) < 500
