"""LogisticRegression parity tests.

Model: the reference's LogisticRegressionSuite embeds R glmnet coefficients
(SURVEY §4); here the equivalent closed references are sklearn solutions of
the *same objective*, mapped exactly:
  ours: (1/n)Σ logloss + reg·(½‖β‖²)          [standardization=False]
  sklearn: Σ logloss + (1/(2C))‖β‖²  ⇒  C = 1/(reg·n)
"""

import os

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.classification import LogisticRegression, LogisticRegressionModel

REF_LIBSVM = "/root/reference/data/mllib/sample_libsvm_data.txt"


def _binary_frame(ctx, n=500, d=6, seed=7):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d) * rng.uniform(0.5, 3.0, d)[None, :]
    true = rng.randn(d)
    y = (x @ true / np.linalg.norm(true) + 0.5 * rng.randn(n) > 0).astype(np.float64)
    return MLFrame(ctx, {"features": x, "label": y}), x, y


def test_binomial_no_standardization_vs_sklearn(ctx):
    from sklearn.linear_model import LogisticRegression as SkLR
    frame, x, y = _binary_frame(ctx)
    n = len(y)
    reg = 0.05
    lr = LogisticRegression(regParam=reg, standardization=False, tol=1e-10,
                            maxIter=500)
    model = lr.fit(frame)
    sk = SkLR(C=1.0 / (reg * n), tol=1e-12, max_iter=20000).fit(x, y)
    np.testing.assert_allclose(model.coefficients.to_array(), sk.coef_[0], atol=1e-4)
    np.testing.assert_allclose(model.intercept, sk.intercept_[0], atol=1e-4)


def test_binomial_standardization_vs_sklearn_scaled(ctx):
    from sklearn.linear_model import LogisticRegression as SkLR
    frame, x, y = _binary_frame(ctx, seed=8)
    n = len(y)
    reg = 0.1
    model = LogisticRegression(regParam=reg, standardization=True, tol=1e-10,
                               maxIter=500).fit(frame)
    # standardization=True penalises standardized coefs: equivalent to sklearn
    # on x/std with beta_orig = beta_sk/std
    std = x.std(axis=0, ddof=1)
    sk = SkLR(C=1.0 / (reg * n), tol=1e-12, max_iter=20000).fit(x / std, y)
    np.testing.assert_allclose(model.coefficients.to_array(), sk.coef_[0] / std,
                               atol=1e-4)
    np.testing.assert_allclose(model.intercept, sk.intercept_[0], atol=1e-4)


def test_binomial_elasticnet_l1_sparsity(ctx):
    from sklearn.linear_model import LogisticRegression as SkLR
    frame, x, y = _binary_frame(ctx, seed=9)
    n = len(y)
    reg, alpha = 0.1, 1.0  # pure L1
    model = LogisticRegression(regParam=reg, elasticNetParam=alpha,
                               standardization=False, tol=1e-10,
                               maxIter=1000).fit(frame)
    sk = SkLR(C=1.0 / (reg * n), penalty="l1", solver="liblinear",
              tol=1e-10, max_iter=50000).fit(x, y)
    ours = model.coefficients.to_array()
    np.testing.assert_allclose(ours, sk.coef_[0], atol=2e-3)
    assert set(np.nonzero(np.abs(ours) > 1e-6)[0]) == \
        set(np.nonzero(np.abs(sk.coef_[0]) > 1e-6)[0])


def test_multinomial_vs_sklearn(ctx):
    from sklearn.linear_model import LogisticRegression as SkLR
    rng = np.random.RandomState(10)
    n, d, k = 600, 4, 3
    centers = rng.randn(k, d) * 2
    y = rng.randint(0, k, n).astype(np.float64)
    x = centers[y.astype(int)] + rng.randn(n, d)
    frame = MLFrame(ctx, {"features": x, "label": y})
    reg = 0.05
    model = LogisticRegression(regParam=reg, standardization=False,
                               tol=1e-10, maxIter=500).fit(frame)
    assert model.num_classes == 3
    sk = SkLR(C=1.0 / (reg * n), tol=1e-12, max_iter=20000).fit(x, y)
    # compare probabilities (coefficient gauge can differ)
    probs = model._raw_to_probability(model._raw_prediction(x))
    np.testing.assert_allclose(probs, sk.predict_proba(x), atol=1e-4)


def test_multinomial_no_reg_centered(ctx):
    rng = np.random.RandomState(11)
    n, d, k = 300, 3, 3
    y = rng.randint(0, k, n).astype(np.float64)
    x = rng.randn(n, d) + 2.0 * np.eye(k)[y.astype(int), :]
    frame = MLFrame(ctx, {"features": x, "label": y})
    model = LogisticRegression(regParam=0.0, tol=1e-8, maxIter=200).fit(frame)
    cm = model.coefficient_matrix.to_array()
    np.testing.assert_allclose(cm.mean(axis=0), 0.0, atol=1e-8)
    np.testing.assert_allclose(model.intercept_vector.to_array().mean(), 0.0, atol=1e-8)


def test_threshold_and_probability_columns(ctx):
    frame, x, y = _binary_frame(ctx, n=200, seed=12)
    model = LogisticRegression(maxIter=50).fit(frame)
    out = model.transform(frame)
    assert "prediction" in out and "probability" in out and "rawPrediction" in out
    probs = out["probability"]
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-8)
    # extreme thresholds force all-negative / all-positive predictions
    model.set("threshold", 0.9999999)
    assert model.transform(frame)["prediction"].sum() == 0.0
    model.set("threshold", 1e-9)
    assert model.transform(frame)["prediction"].sum() == float(frame.n_rows)
    model.set("threshold", 0.5)
    # predict() agrees with transform() under a non-default threshold
    model.set("threshold", 0.9)
    preds = model.transform(frame)["prediction"]
    assert model.predict(x[0]) == preds[0]
    model.set("threshold", 0.5)


def test_weight_column_equivalence(ctx):
    """Duplicating a row == weighting it 2x (the reference's weighted
    semantics, tested the same way in LogisticRegressionSuite)."""
    rng = np.random.RandomState(13)
    n, d = 120, 3
    x = rng.randn(n, d)
    y = (rng.rand(n) > 0.5).astype(np.float64)
    x_dup = np.vstack([x, x[:40]])
    y_dup = np.concatenate([y, y[:40]])
    w = np.ones(n)
    w[:40] = 2.0
    f_dup = MLFrame(ctx, {"features": x_dup, "label": y_dup})
    f_w = MLFrame(ctx, {"features": x, "label": y, "weight": w})
    # standardization=False so the two objectives are exactly equal (with
    # standardization on, the unbiased weighted variance of 2x-weighted rows
    # differs slightly from duplicated rows — true in the reference as well)
    m1 = LogisticRegression(regParam=0.1, tol=1e-10, maxIter=300,
                            standardization=False).fit(f_dup)
    lr2 = LogisticRegression(regParam=0.1, tol=1e-10, maxIter=300,
                             standardization=False)
    lr2.set("weightCol", "weight")
    m2 = lr2.fit(f_w)
    np.testing.assert_allclose(m1.coefficients.to_array(),
                               m2.coefficients.to_array(), atol=1e-5)


def test_objective_history_decreasing(ctx):
    frame, _, _ = _binary_frame(ctx, seed=14)
    model = LogisticRegression(maxIter=50, regParam=0.01).fit(frame)
    h = model.summary.objective_history
    assert len(h) >= 2
    assert all(b <= a + 1e-12 for a, b in zip(h, h[1:]))
    assert model.summary.total_iterations == len(h) - 1


@pytest.mark.skipif(not os.path.exists(REF_LIBSVM), reason="reference data absent")
def test_sample_libsvm_parity(ctx):
    """BASELINE config 1: LR (L-BFGS) on data/mllib/sample_libsvm_data.txt."""
    from cycloneml_tpu.dataset.io import parse_libsvm
    x, y = parse_libsvm(REF_LIBSVM)
    assert x.shape == (100, 692)
    frame = MLFrame(ctx, {"features": x, "label": y})
    model = LogisticRegression(maxIter=10, regParam=0.3, elasticNetParam=0.8).fit(frame)
    out = model.transform(frame)
    acc = float((out["prediction"] == y).mean())
    assert acc >= 0.97  # reference example converges to ~1.0 on this data
    h = model.summary.objective_history
    assert h[0] > h[-1]


def test_save_load_roundtrip(ctx, tmp_path):
    frame, x, _ = _binary_frame(ctx, n=150, seed=15)
    model = LogisticRegression(maxIter=30, regParam=0.05).fit(frame)
    p = str(tmp_path / "lr_model")
    model.save(p)
    back = LogisticRegressionModel.load(p)
    np.testing.assert_allclose(back.coefficients.to_array(),
                               model.coefficients.to_array())
    assert back.intercept == model.intercept
    np.testing.assert_allclose(
        back.transform(frame)["prediction"], model.transform(frame)["prediction"])
    # estimator round-trip too
    est = LogisticRegression(maxIter=77, regParam=0.123)
    p2 = str(tmp_path / "lr_est")
    est.save(p2)
    est2 = LogisticRegression.load(p2)
    assert est2.get("maxIter") == 77 and est2.get("regParam") == 0.123


def test_pipeline_with_lr(ctx, tmp_path):
    from cycloneml_tpu.ml.base import Pipeline, PipelineModel
    frame, x, y = _binary_frame(ctx, n=150, seed=16)
    pipe = Pipeline([LogisticRegression(maxIter=30)])
    pm = pipe.fit(frame)
    out = pm.transform(frame)
    assert "prediction" in out
    p = str(tmp_path / "pipe_model")
    pm.save(p)
    back = PipelineModel.load(p)
    np.testing.assert_allclose(back.transform(frame)["prediction"], out["prediction"])


# -- coefficient bounds (LBFGS-B path) -----------------------------------------

def _bounded_problem(ctx, n=300, d=5, seed=11):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    true = np.array([2.0, -1.5, 0.8, -0.3, 1.1])
    y = (x @ true + 0.3 * rng.randn(n) > 0).astype(np.float64)
    return MLFrame(ctx, {"features": x, "label": y})


def test_lr_coefficient_bounds_respected(ctx):
    """lowerBounds/upperBounds select the bound-constrained optimizer (ref
    LogisticRegression.scala:788) and the trained coefficients respect the
    box in ORIGINAL feature space."""
    frame = _bounded_problem(ctx)
    lr = LogisticRegression(
        maxIter=100, regParam=0.01, tol=1e-10,
        lowerBoundsOnCoefficients=np.zeros((1, 5)))  # nonnegative
    m = lr.fit(frame)
    coefs = m.coefficients.to_array()
    assert np.all(coefs >= -1e-9), coefs
    # the unbounded fit has negative coefficients, so the box truly binds
    free = LogisticRegression(maxIter=100, regParam=0.01, tol=1e-10).fit(frame)
    assert np.any(free.coefficients.to_array() < 0)
    assert np.any(np.isclose(coefs, 0.0, atol=1e-6))


def test_lr_wide_bounds_match_unbounded(ctx):
    frame = _bounded_problem(ctx, seed=13)
    wide = LogisticRegression(
        maxIter=100, regParam=0.05, tol=1e-10,
        lowerBoundsOnCoefficients=np.full((1, 5), -1e6),
        upperBoundsOnCoefficients=np.full((1, 5), 1e6),
        lowerBoundsOnIntercepts=np.array([-1e6]),
        upperBoundsOnIntercepts=np.array([1e6])).fit(frame)
    free = LogisticRegression(maxIter=100, regParam=0.05, tol=1e-10).fit(frame)
    np.testing.assert_allclose(wide.coefficients.to_array(),
                               free.coefficients.to_array(),
                               rtol=1e-5, atol=1e-7)
    # intercept bounds disable fitWithMean (centered conditioning), so the
    # two runs solve differently-conditioned problems that agree only to
    # optimizer tolerance — same as the reference
    np.testing.assert_allclose(wide.intercept, free.intercept,
                               rtol=1e-4, atol=1e-5)


def test_lr_intercept_bounds(ctx):
    frame = _bounded_problem(ctx, seed=17)
    m = LogisticRegression(
        maxIter=80, tol=1e-9,
        lowerBoundsOnIntercepts=np.array([0.5])).fit(frame)
    assert m.intercept >= 0.5 - 1e-9


def test_lr_bounds_reject_elastic_net(ctx):
    frame = _bounded_problem(ctx)
    with pytest.raises(ValueError, match="none or L2"):
        LogisticRegression(
            regParam=0.1, elasticNetParam=0.5,
            lowerBoundsOnCoefficients=np.zeros((1, 5))).fit(frame)


def test_lr_bounds_shape_validation(ctx):
    frame = _bounded_problem(ctx)
    with pytest.raises(ValueError, match="shape"):
        LogisticRegression(
            lowerBoundsOnCoefficients=np.zeros((1, 3))).fit(frame)
    with pytest.raises(ValueError, match="fitIntercept"):
        LogisticRegression(
            fitIntercept=False,
            lowerBoundsOnIntercepts=np.array([0.0])).fit(frame)


def test_lr_multinomial_bounds(ctx):
    rng = np.random.RandomState(23)
    n, d, k = 400, 4, 3
    x = rng.randn(n, d)
    w = rng.randn(k, d)
    y = np.argmax(x @ w.T + 0.2 * rng.randn(n, k), axis=1).astype(np.float64)
    frame = MLFrame(ctx, {"features": x, "label": y})
    m = LogisticRegression(
        maxIter=100, regParam=0.01, tol=1e-9,
        lowerBoundsOnCoefficients=np.zeros((k, d))).fit(frame)
    cm = m.coefficient_matrix.to_array()
    assert cm.shape == (k, d)
    assert np.all(cm >= -1e-9)
