"""Bit-exact reproductions of the reference's test-data generators, so its
committed R-computed golden constants can be asserted against this
framework's estimators (round-3 verdict item 1)."""
