"""Bit-exact Python ports of the RNGs the reference's test suites draw
their synthetic datasets from:

- ``JavaRandom``: java.util.Random — the 48-bit LCG specified in the JDK
  javadoc (``scala.util.Random`` delegates to it). Used by
  ``generateMultinomialLogisticInput`` / ``LinearDataGenerator`` etc.
- ``XORShiftRandom``: the reference's ``core/src/main/scala/org/apache/
  spark/util/random/XORShiftRandom.scala`` — java.util.Random with
  ``next(bits)`` replaced by a 64-bit xorshift whose seed is hashed with
  scala.util.hashing.MurmurHash3.bytesHash. Spark SQL's ``rand(seed)``
  column draws ``new XORShiftRandom(seed + partitionIndex).nextDouble()``
  per row (``sql/catalyst/.../expressions/randomExpressions.scala:44``),
  and mllib's ``StandardNormalGenerator`` is ``XORShiftRandom
  .nextGaussian`` (``mllib/random/RandomDataGenerator.scala:70``).

These are reimplementations from the published algorithm specs, not
translations: the goal is reproducing the reference's exact test datasets
so its committed R oracle constants apply to our estimators.
"""

import math

_M32 = 0xFFFFFFFF
_M48 = 0xFFFFFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF
_LCG_MULT = 0x5DEECE66D
_LCG_ADD = 0xB


class JavaRandom:
    """java.util.Random: 48-bit LCG; nextGaussian is the Marsaglia polar
    method exactly as the JDK documents it."""

    def __init__(self, seed: int):
        self.set_seed(seed)

    def set_seed(self, seed: int) -> None:
        self._seed = (seed ^ _LCG_MULT) & _M48
        self._next_gaussian = None

    def _next(self, bits: int) -> int:
        self._seed = (self._seed * _LCG_MULT + _LCG_ADD) & _M48
        return self._seed >> (48 - bits)

    def next_int(self) -> int:
        v = self._next(32)
        return v - (1 << 32) if v >= (1 << 31) else v

    def next_double(self) -> float:
        return ((self._next(26) << 27) + self._next(27)) * (2.0 ** -53)

    def next_gaussian(self) -> float:
        if self._next_gaussian is not None:
            g, self._next_gaussian = self._next_gaussian, None
            return g
        while True:
            v1 = 2.0 * self.next_double() - 1.0
            v2 = 2.0 * self.next_double() - 1.0
            s = v1 * v1 + v2 * v2
            if 0.0 < s < 1.0:
                break
        mult = math.sqrt(-2.0 * math.log(s) / s)
        self._next_gaussian = v2 * mult
        return v1 * mult


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def _murmur_mix(h: int, k: int) -> int:
    k = (k * 0xCC9E2D51) & _M32
    k = _rotl32(k, 15)
    k = (k * 0x1B873593) & _M32
    h ^= k
    h = _rotl32(h, 13)
    return (h * 5 + 0xE6546B64) & _M32


def _murmur_mix_last(h: int, k: int) -> int:
    k = (k * 0xCC9E2D51) & _M32
    k = _rotl32(k, 15)
    k = (k * 0x1B873593) & _M32
    return h ^ k


def murmur3_bytes_hash(data: bytes, seed: int) -> int:
    """scala.util.hashing.MurmurHash3.bytesHash (x86_32, little-endian
    4-byte blocks). Returns an unsigned 32-bit value."""
    h = seed & _M32
    n = len(data)
    i = 0
    while n - i >= 4:
        k = (data[i] | (data[i + 1] << 8) | (data[i + 2] << 16)
             | (data[i + 3] << 24))
        h = _murmur_mix(h, k)
        i += 4
    k = 0
    rem = n - i
    if rem == 3:
        k ^= data[i + 2] << 16
    if rem >= 2:
        k ^= data[i + 1] << 8
    if rem >= 1:
        k ^= data[i]
        h = _murmur_mix_last(h, k)
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


_ARRAY_SEED = 0x3C074A61  # MurmurHash3.arraySeed


def _xorshift_hash_seed(seed: int) -> int:
    """XORShiftRandom.hashSeed: murmur the big-endian long bytes twice;
    high word is the SIGN-EXTENDED second hash (Scala Int.toLong)."""
    data = (seed & _M64).to_bytes(8, "big")
    low = murmur3_bytes_hash(data, _ARRAY_SEED)
    high = murmur3_bytes_hash(data, low)
    # (highBits.toLong << 32) | (lowBits & 0xFFFFFFFFL) on SIGNED ints —
    # as unsigned 64-bit two's complement the sign extension is absorbed
    # by the << 32 mask
    return ((high << 32) | low) & _M64


class XORShiftRandom(JavaRandom):
    """The reference's XORShiftRandom: java.util.Random protocol with
    ``next(bits)`` replaced by a 64-bit xorshift returning the LOW bits."""

    def __init__(self, init: int):
        self.set_seed(init)

    def set_seed(self, seed: int) -> None:
        self._seed64 = _xorshift_hash_seed(seed)
        self._next_gaussian = None

    def _next(self, bits: int) -> int:
        s = self._seed64
        s = (s ^ (s << 21)) & _M64
        s ^= s >> 35  # unsigned value, so >> is Java's >>>
        s = (s ^ (s << 4)) & _M64
        self._seed64 = s
        return s & ((1 << bits) - 1)


def parallelize_slice_bounds(length: int, num_slices: int):
    """ParallelCollectionRDD.slice positions (core/.../rdd/
    ParallelCollectionRDD.scala:116): slice i covers
    [i*length//num_slices, (i+1)*length//num_slices)."""
    return [(i * length // num_slices, (i + 1) * length // num_slices)
            for i in range(num_slices)]


def sql_rand_column(seed: int, n_rows: int, n_partitions: int):
    """The ``rand(seed)`` column Spark SQL evaluates over a DataFrame with
    ``n_partitions`` even parallelize partitions: partition p draws from
    ``new XORShiftRandom(seed + p)`` one nextDouble per row."""
    out = []
    for p, (lo, hi) in enumerate(
            parallelize_slice_bounds(n_rows, n_partitions)):
        rng = XORShiftRandom(seed + p)
        out.extend(rng.next_double() for _ in range(hi - lo))
    return out
