"""Bit-exact ports of the reference's synthetic test-data generators.

Each function reproduces the sequence of RNG draws of its Scala
counterpart so the datasets — and therefore the R-computed golden
constants the reference's suites assert against — carry over exactly:

- ``generate_logistic_input`` ≈ ml/classification/
  LogisticRegressionSuite.scala:3021 (object LogisticRegressionSuite)
- ``generate_multinomial_logistic_input`` ≈ same file :3061
- ``generate_linear_input`` ≈ mllib/util/LinearDataGenerator.scala:120
- ``generate_glm_input`` ≈ ml/regression/
  GeneralizedLinearRegressionSuite.scala:1713 (gaussian families only:
  poisson/gamma noise uses commons-math3, out of reproduction scope)
- ``binary_dataset_with_weights`` ≈ ml/classification/
  LogisticRegressionSuite.scala:75 (the ``binaryDataset`` every weighted
  golden LR test fits, including its Spark-SQL ``rand(seed)`` weight
  column over 4 parallelize partitions)
"""

import math

import numpy as np

from tests.ref_parity.scala_rng import (JavaRandom, XORShiftRandom,
                                        sql_rand_column)


def generate_logistic_input(offset, scale, n_points, seed):
    """y = logistic(offset + scale*x), x ~ N(0,1): all gaussians first,
    then one uniform per label draw (the Scala draw order)."""
    rnd = JavaRandom(seed)
    x1 = [rnd.next_gaussian() for _ in range(n_points)]
    y = []
    for i in range(n_points):
        p = 1.0 / (1.0 + math.exp(-(offset + scale * x1[i])))
        y.append(1.0 if rnd.next_double() < p else 0.0)
    return np.array(x1).reshape(-1, 1), np.array(y)


def generate_multinomial_logistic_input(weights, x_mean, x_variance,
                                        add_intercept, n_points, seed):
    """K-class softmax sampling over gaussian features; one row's features
    are drawn fully before the next row (Array.fill order), then labels
    consume one uniform each."""
    rnd = JavaRandom(seed)
    x_dim = len(x_mean)
    w_dim = x_dim + 1 if add_intercept else x_dim
    n_classes = len(weights) // w_dim + 1

    x = np.empty((n_points, x_dim))
    for i in range(n_points):
        for j in range(x_dim):
            x[i, j] = rnd.next_gaussian()
    x = x * np.sqrt(np.asarray(x_variance)) + np.asarray(x_mean)

    y = np.empty(n_points)
    for idx in range(n_points):
        margins = np.zeros(n_classes)
        for i in range(n_classes - 1):
            m = 0.0
            for j in range(x_dim):
                m += weights[i * w_dim + j] * x[idx, j]
            if add_intercept:
                m += weights[(i + 1) * w_dim - 1]
            margins[i + 1] = m
        max_margin = margins.max()
        if max_margin > 0:
            margins -= max_margin
        probs = np.exp(margins)
        cum = np.cumsum(probs / probs.sum())
        p = rnd.next_double()
        y[idx] = int(np.searchsorted(cum, p, side="right"))
    return x, y


def generate_linear_input(intercept, weights, x_mean, x_variance, n_points,
                          seed, eps, sparsity=0.0):
    """label = w·x + intercept + eps*N(0,1); features are uniform draws
    rescaled to the requested mean/variance. Draw order per row: all
    feature uniforms, then the noise gaussian. NOTE the gaussian shares
    the same LCG stream (java.util.Random interleaves them)."""
    if sparsity != 0.0:
        raise NotImplementedError("sparse variant not needed by the goldens")
    rnd = JavaRandom(seed)
    w = np.asarray(weights)
    d = len(w)
    scale = np.sqrt(12.0 * np.asarray(x_variance))
    mean = np.asarray(x_mean)
    X = np.empty((n_points, d))
    y = np.empty(n_points)
    for i in range(n_points):
        for j in range(d):
            X[i, j] = (rnd.next_double() - 0.5) * scale[j] + mean[j]
        y[i] = float(X[i] @ w) + intercept + eps * rnd.next_gaussian()
    return X, y


def generate_glm_input(intercept, coefficients, x_mean, x_variance,
                       n_points, seed, noise_level, family, link):
    """GLM data: features from java.util.Random uniforms; noise from the
    family's generator stream — gaussian uses XORShiftRandom
    (StandardNormalGenerator), poisson/gamma use commons-math3
    Well19937c-backed samplers with the sampled MEAN subtracted
    (GeneralizedLinearRegressionSuite.scala:1728-1744:
    ``label = mu + noiseLevel * (generator.nextValue() - mean)``)."""
    from tests.ref_parity.commons_rng import GammaSampler, PoissonSampler

    class _Gauss:
        def __init__(self, s):
            self._r = XORShiftRandom(s)

        def next_value(self):
            return self._r.next_gaussian()

    if family == "gaussian":
        gen, gen_mean = _Gauss(seed), 0.0
    elif family == "poisson":
        gen, gen_mean = PoissonSampler(1.0, seed), 1.0
    elif family == "gamma":
        gen, gen_mean = GammaSampler(1.0, 1.0, seed), 1.0
    else:
        raise NotImplementedError(family)
    rnd = JavaRandom(seed)
    noise = gen
    w = np.asarray(coefficients)
    d = len(w)
    scale = np.sqrt(12.0 * np.asarray(x_variance))
    mean = np.asarray(x_mean)
    X = np.empty((n_points, d))
    y = np.empty(n_points)
    for i in range(n_points):
        for j in range(d):
            X[i, j] = (rnd.next_double() - 0.5) * scale[j] + mean[j]
        eta = float(X[i] @ w) + intercept
        if link == "identity":
            mu = eta
        elif link == "log":
            mu = math.exp(eta)
        elif link == "sqrt":
            mu = eta * eta
        elif link == "inverse":
            mu = 1.0 / eta
        else:
            raise ValueError(link)
        y[i] = mu + noise_level * (noise.next_value() - gen_mean)
    return X, y


def generate_aft_input(num_features, x_mean, x_variance, n_points, seed,
                       weibull_shape, weibull_scale, exponential_mean):
    """AFTSurvivalRegressionSuite.scala:96 generateAFTInput: features are
    java.util.Random uniforms rescaled to mean/variance; the label is a
    Weibull draw, censored against an Exponential draw — both from their
    OWN commons-math3 Well19937c streams seeded identically. Draw order:
    ALL feature rows first, then (weibull, exponential) pairs per row."""
    from tests.ref_parity.commons_rng import (ExponentialSampler,
                                              WeibullSampler)
    weibull = WeibullSampler(weibull_shape, weibull_scale, seed)
    exponential = ExponentialSampler(exponential_mean, seed)
    rnd = JavaRandom(seed)
    X = np.empty((n_points, num_features))
    for i in range(n_points):
        for j in range(num_features):
            X[i, j] = rnd.next_double()
    X = (X - 0.5) * np.sqrt(12.0 * np.asarray(x_variance)) \
        + np.asarray(x_mean)
    label = np.empty(n_points)
    censor = np.empty(n_points)
    for i in range(n_points):
        w = weibull.next_value()
        e = exponential.next_value()
        label[i] = w
        censor[i] = 1.0 if w <= e else 0.0
    return X, label, censor


# the multinomialDataset family (LogisticRegressionSuite.scala:105-155):
# 3-class softmax draws with a rand(seed) weight column over 4 partitions
_MULTI_COEF = [-0.57997, 0.912083, -0.371077, -0.819866, 2.688191,
               -0.16624, -0.84355, -0.048509, -0.301789, 4.170682]
_MULTI_XMEAN = [5.843, 3.057, 3.758, 1.199]
_MULTI_XVAR = [0.6856, 0.1899, 3.116, 0.581]
_MULTI_SMALLVAR_XMEAN = [5.843, 3.057, 3.758, 10.199]
_MULTI_SMALLVAR_XVAR = [0.6856, 0.1899, 3.116, 0.001]


def multinomial_dataset(seed=42, n_points=10000, small_var=False):
    x_mean = _MULTI_SMALLVAR_XMEAN if small_var else _MULTI_XMEAN
    x_var = _MULTI_SMALLVAR_XVAR if small_var else _MULTI_XVAR
    X, y = generate_multinomial_logistic_input(
        _MULTI_COEF, x_mean, x_var, True, n_points, seed)
    w = np.array(sql_rand_column(seed, n_points, 4))
    return X, y, w


def multinomial_dataset_zero_var(seed=42, n_points=100):
    """multinomialDatasetWithZeroVar: 2 features, one with zero variance,
    weight identically 1.0 (lit(1.0))."""
    X, y = generate_multinomial_logistic_input(
        [-0.57997, 0.912083, -0.371077, -0.16624, -0.84355, -0.048509],
        [5.843, 3.0], [0.6856, 0.0], True, n_points, seed)
    return X, y, np.ones(n_points)


# the binaryDataset shared by every weighted golden LR test
# (LogisticRegressionSuite.scala:75-89): 10k points, seed 42, 4-partition
# DataFrame with a rand(42) weight column
_BINARY_COEF = [-0.57997, 0.912083, -0.371077, -0.819866, 2.688191]
_BINARY_XMEAN = [5.843, 3.057, 3.758, 1.199]
_BINARY_XVAR = [0.6856, 0.1899, 3.116, 0.581]
_SMALLVAR_XMEAN = [5.843, 3.057, 3.758, 10.199]
_SMALLVAR_XVAR = [0.6856, 0.1899, 3.116, 0.0001]


def binary_dataset_with_weights(seed=42, n_points=10000, small_var=False):
    x_mean = _SMALLVAR_XMEAN if small_var else _BINARY_XMEAN
    x_var = _SMALLVAR_XVAR if small_var else _BINARY_XVAR
    X, y = generate_multinomial_logistic_input(
        _BINARY_COEF, x_mean, x_var, True, n_points, seed)
    w = np.array(sql_rand_column(seed, n_points, 4))
    return X, y, w
