"""Slow-but-trusted pure-numpy word2vec oracle (r4 verdict item 9).

An INDEPENDENT reimplementation of the skip-gram objective for both
solvers — negative sampling and hierarchical softmax — written as plain
f64 numpy over explicit per-level math (np.add.at scatters, no jax in
the update path). It mirrors the estimator's data pipeline (vocab order,
pair construction, init, epoch permutations, batch boundaries) so the
TRAJECTORIES are comparable, while deriving every gradient from scratch:

  ns:  L = -log σ(v_c·v_o) - Σ_k log σ(-v_c·v_nk)
  hs:  L = -Σ_l log σ((1-2·code_l)·(v_ctx·v_node_l))   (word2vec.c form)

The one shared input with the estimator is the NEGATIVE index draws
(jax.random.choice is not reproducible in numpy; the indices are data,
not math — the oracle's job is to vouch for the update rule given the
same samples). No external oracle exists in this zero-egress environment
(ref mllib/feature/Word2Vec.scala:73; gensim absent), so this file IS
the trusted comparator the parity tests pin both solvers against.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from cycloneml_tpu.ml.feature.word2vec import _huffman_paths

BATCH = 8192  # the estimator's device batch — shared so batches align


def build_pipeline(sentences: List[List[str]], min_count: int,
                   window: int, max_len: int = 1000):
    """Vocab (count-desc, word asc) + (center, context) pairs, mirroring
    the estimator's construction exactly."""
    sents = [list(map(str, s))[:max_len] for s in sentences]
    counts: Dict[str, int] = {}
    for s in sents:
        for w in s:
            counts[w] = counts.get(w, 0) + 1
    vocab = sorted((w for w, c in counts.items() if c >= min_count),
                   key=lambda w: (-counts[w], w))
    index = {w: i for i, w in enumerate(vocab)}
    centers, contexts = [], []
    for s in sents:
        ids = [index[w] for w in s if w in index]
        for i, c in enumerate(ids):
            for j in range(max(0, i - window),
                           min(len(ids), i + window + 1)):
                if j != i:
                    centers.append(c)
                    contexts.append(ids[j])
    return (vocab, counts, np.asarray(centers, np.int64),
            np.asarray(contexts, np.int64))


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def oracle_ns(sentences, *, dim: int, window: int, lr: float, epochs: int,
              seed: int, neg_draws: List[np.ndarray], min_count: int = 1
              ) -> Tuple[List[str], np.ndarray]:
    """Negative-sampling oracle. ``neg_draws`` supplies the per-batch
    negative index arrays in consumption order (shape (b, k) each)."""
    vocab, _counts, centers, contexts = build_pipeline(
        sentences, min_count, window)
    n_vocab = len(vocab)
    rng = np.random.RandomState(seed)
    w_in = ((rng.rand(n_vocab, dim) - 0.5) / dim)
    w_out = np.zeros((n_vocab, dim))
    draws = iter(neg_draws)
    n_pairs = len(centers)
    for _epoch in range(epochs):
        perm = rng.permutation(n_pairs)
        for s0 in range(0, n_pairs, BATCH):
            sel = perm[s0: s0 + BATCH]
            c_idx, o_idx = centers[sel], contexts[sel]
            n_idx = np.asarray(next(draws), np.int64)
            vc, vo, vn = w_in[c_idx], w_out[o_idx], w_out[n_idx]
            g_pos = (_sigmoid(np.sum(vc * vo, axis=1)) - 1.0)[:, None]
            g_neg = _sigmoid(np.einsum("bd,bkd->bk", vc, vn))[:, :, None]
            d_vc = g_pos * vo + np.sum(g_neg * vn, axis=1)
            np.add.at(w_in, c_idx, -lr * d_vc)
            np.add.at(w_out, o_idx, -lr * (g_pos * vc))
            np.add.at(w_out, n_idx.reshape(-1),
                      -lr * (g_neg * vc[:, None, :]).reshape(-1, dim))
    return vocab, w_in


def oracle_hs(sentences, *, dim: int, window: int, lr: float, epochs: int,
              seed: int, min_count: int = 1
              ) -> Tuple[List[str], np.ndarray, List[float]]:
    """Hierarchical-softmax oracle: per-level Huffman-path updates in f64
    (the context word's input vector trains against the center word's
    path, the word2vec.c orientation). Returns the per-epoch mean loss
    curve too."""
    vocab, counts, centers, contexts = build_pipeline(
        sentences, min_count, window)
    n_vocab = len(vocab)
    rng = np.random.RandomState(seed)
    w_in = ((rng.rand(n_vocab, dim) - 0.5) / dim)
    points, codes, lengths = _huffman_paths(
        np.array([counts[w] for w in vocab], dtype=np.int64))
    w_node = np.zeros((max(n_vocab - 1, 1), dim))
    n_pairs = len(centers)
    losses = []
    for _epoch in range(epochs):
        perm = rng.permutation(n_pairs)
        total = 0.0
        for s0 in range(0, n_pairs, BATCH):
            sel = perm[s0: s0 + BATCH]
            c_idx, ctx_idx = centers[sel], contexts[sel]
            vin = w_in[ctx_idx]
            nodes = points[c_idx]
            code = codes[c_idx].astype(np.float64)
            mask = (np.arange(points.shape[1])[None, :]
                    < lengths[c_idx][:, None]).astype(np.float64)
            vn = w_node[nodes]
            dot = np.einsum("bd,bld->bl", vin, vn)
            g = (_sigmoid(dot) - (1.0 - code)) * mask
            np.add.at(w_in, ctx_idx, -lr * np.einsum("bl,bld->bd", g, vn))
            np.add.at(w_node, nodes.reshape(-1),
                      -lr * (g[:, :, None] * vin[:, None, :]).reshape(
                          -1, dim))
            sign = 1.0 - 2.0 * code
            with np.errstate(over="ignore"):
                total += float(np.sum(
                    mask * np.logaddexp(0.0, -sign * dot)))
        losses.append(total / n_pairs)
    return vocab, w_in, losses
