"""Pure-Python port of the commons-math3 RNG/sampler stack the reference's
data generators draw from (mllib/src/main/scala/org/apache/spark/mllib/
random/RandomDataGenerator.scala: PoissonGenerator/GammaGenerator/
WeibullGenerator/ExponentialGenerator wrap commons-math3 distributions
whose default generator is Well19937c).

Ported pieces, each mirroring its commons-math3 3.x source:
- Well19937c (AbstractWell seeding + the WELL19937c next() with
  Matsumoto-Kurita tempering)
- BitsStreamGenerator.nextDouble / nextGaussian (paired Box-Muller cache)
- PoissonDistribution.sample (Knuth multiplication loop for mean < 40)
- ExponentialDistribution.sample (Ahrens-Dieter SA with the ln2-series
  q_i table)
- GammaDistribution.sample (Marsaglia-Tsang for shape >= 1)
- WeibullDistribution.sample (inverse-CDF)

Validation is end-to-end: the golden-parity suites fit the resulting
datasets against the R constants the reference itself commits at absTol
1e-4 — a wrong port cannot land on those numbers.
"""

from __future__ import annotations

import math

_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF


def _i32(x: int) -> int:
    """Wrap to signed 32-bit (Java int semantics)."""
    x &= _M32
    return x - (1 << 32) if x & 0x80000000 else x


def _i64(x: int) -> int:
    x &= _M64
    return x - (1 << 64) if x & 0x8000000000000000 else x


class Well19937c:
    """commons-math3 o.a.c.math3.random.Well19937c: K=19937, R=624 words,
    AbstractWell int[]-spread seeding, WELL19937c recurrence + tempering."""

    R = 624
    M1 = 70
    M2 = 179
    M3 = 449

    def __init__(self, seed: int | None = None):
        # precomputed index tables (AbstractWell constructor)
        r = self.R
        self._iRm1 = [(i + r - 1) % r for i in range(r)]
        self._iRm2 = [(i + r - 2) % r for i in range(r)]
        self._i1 = [(i + self.M1) % r for i in range(r)]
        self._i2 = [(i + self.M2) % r for i in range(r)]
        self._i3 = [(i + self.M3) % r for i in range(r)]
        self.v = [0] * r
        self.index = 0
        self._next_gaussian = math.nan
        if seed is not None:
            self.set_seed_long(seed)

    # -- seeding (AbstractWell.setSeed) ---------------------------------
    def set_seed_ints(self, seed: list[int]) -> None:
        n = min(len(seed), self.R)
        self.v[:n] = [_i32(s) for s in seed[:n]]
        for i in range(len(seed), self.R):
            el = _i64(self.v[i - len(seed)])  # (long) int — sign extends
            self.v[i] = _i32((1812433253 * (el ^ (el >> 30)) + i) & _M32)
        self.index = 0
        self._next_gaussian = math.nan  # BitsStreamGenerator.clear()

    def set_seed_long(self, seed: int) -> None:
        seed = _i64(seed) & _M64
        self.set_seed_ints([_i32(seed >> 32), _i32(seed & _M32)])

    # -- core (Well19937c.next) -----------------------------------------
    def next_bits(self, bits: int) -> int:
        v = self.v
        index = self.index
        index_rm1 = self._iRm1[index]
        index_rm2 = self._iRm2[index]
        v0 = v[index] & _M32
        v_m1 = v[self._i1[index]] & _M32
        v_m2 = v[self._i2[index]] & _M32
        v_m3 = v[self._i3[index]] & _M32

        z0 = ((0x80000000 & v[index_rm1]) ^ (0x7FFFFFFF & v[index_rm2])) \
            & _M32
        z1 = ((v0 ^ ((v0 << 25) & _M32)) ^ (v_m1 ^ (v_m1 >> 27))) & _M32
        z2 = ((v_m2 >> 9) ^ (v_m3 ^ (v_m3 >> 1))) & _M32
        z3 = (z1 ^ z2) & _M32
        z4 = (z0 ^ (z1 ^ ((z1 << 9) & _M32))
              ^ (z2 ^ ((z2 << 21) & _M32))
              ^ (z3 ^ (z3 >> 21))) & _M32

        v[index] = _i32(z3)
        v[index_rm1] = _i32(z4)
        v[index_rm2] = _i32((v[index_rm2] & _M32) & 0x80000000)
        self.index = index_rm1

        # Matsumoto-Kurita tempering (the "c" variant)
        z4 = (z4 ^ ((z4 << 7) & 0xE46E1700)) & _M32
        z4 = (z4 ^ ((z4 << 15) & 0x9B868000)) & _M32
        return z4 >> (32 - bits)

    # -- BitsStreamGenerator --------------------------------------------
    def next_double(self) -> float:
        high = self.next_bits(26) << 26
        low = self.next_bits(26)
        return (high | low) * (2.0 ** -52)

    def next_gaussian(self) -> float:
        if math.isnan(self._next_gaussian):
            x = self.next_double()
            y = self.next_double()
            alpha = 2 * math.pi * x
            r = math.sqrt(-2 * math.log(y))
            out = r * math.cos(alpha)
            self._next_gaussian = r * math.sin(alpha)
        else:
            out = self._next_gaussian
            self._next_gaussian = math.nan
        return out


# -- ExponentialDistribution: Ahrens-Dieter SA table ---------------------
def _exponential_sa_qi() -> list[float]:
    ln2 = math.log(2.0)
    out = []
    qi = 0.0
    i = 1
    while qi < 1.0:
        qi += ln2 ** i / math.factorial(i)
        out.append(qi)
        i += 1
    return out


_EXP_SA_QI = _exponential_sa_qi()


class ExponentialSampler:
    """ExponentialDistribution(mean).sample() over a shared Well19937c."""

    def __init__(self, mean: float, seed: int):
        self.mean = mean
        self.rng = Well19937c(seed)

    def next_value(self) -> float:
        rng = self.rng
        a = 0.0
        u = rng.next_double()
        while u < 0.5:
            a += _EXP_SA_QI[0]
            u *= 2
        u += u - 1
        if u <= _EXP_SA_QI[0]:
            return self.mean * (a + u)
        i = 0
        u2 = rng.next_double()
        umin = u2
        while True:
            i += 1
            u2 = rng.next_double()
            umin = min(umin, u2)
            if u <= _EXP_SA_QI[i]:
                break
        return self.mean * (a + umin * _EXP_SA_QI[0])


class WeibullSampler:
    """WeibullDistribution(shape, scale).sample(): inverse CDF of one
    uniform (AbstractRealDistribution.sample)."""

    def __init__(self, shape: float, scale: float, seed: int):
        self.shape = shape
        self.scale = scale
        self.rng = Well19937c(seed)

    def next_value(self) -> float:
        p = self.rng.next_double()
        if p == 0.0:
            return 0.0
        if p == 1.0:
            return math.inf
        return self.scale * (-math.log1p(-p)) ** (1.0 / self.shape)


class PoissonSampler:
    """PoissonDistribution(mean).sample(): Knuth multiplication loop for
    mean < 40 (the only regime the suites use; mean=1)."""

    def __init__(self, mean: float, seed: int):
        if mean >= 40:
            raise NotImplementedError("large-mean path not needed")
        self.mean = mean
        self.rng = Well19937c(seed)

    def next_value(self) -> float:
        p = math.exp(-self.mean)
        n = 0
        r = 1.0
        while n < 1000 * self.mean:
            rnd = self.rng.next_double()
            r *= rnd
            if r >= p:
                n += 1
            else:
                return float(n)
        return float(n)


class GammaSampler:
    """GammaDistribution(shape, scale).sample(): Marsaglia-Tsang for
    shape >= 1 (the suites use shape=1)."""

    def __init__(self, shape: float, scale: float, seed: int):
        if shape < 1:
            raise NotImplementedError("Ahrens-Dieter GS path not needed")
        self.shape = shape
        self.scale = scale
        self.rng = Well19937c(seed)

    def next_value(self) -> float:
        d = self.shape - 0.333333333333333333
        c = 1 / (3 * math.sqrt(d))
        while True:
            x = self.rng.next_gaussian()
            v = (1 + c * x) ** 3
            if v <= 0:
                continue
            x2 = x * x
            u = self.rng.next_double()
            if u < 1 - 0.0331 * x2 * x2:
                return self.scale * d * v
            if math.log(u) < 0.5 * x2 + d * (1 - v + math.log(v)):
                return self.scale * d * v
