"""LinearSVC / NaiveBayes / FM / MLP / OneVsRest tests (ref suites:
LinearSVCSuite, NaiveBayesSuite, FMClassifierSuite, FMRegressorSuite,
MultilayerPerceptronClassifierSuite, OneVsRestSuite)."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.classification import (
    FMClassificationModel, FMClassifier, LinearSVC, LinearSVCModel,
    LogisticRegression, MultilayerPerceptronClassificationModel,
    MultilayerPerceptronClassifier, NaiveBayes, NaiveBayesModel, OneVsRest,
    OneVsRestModel,
)
from cycloneml_tpu.ml.regression import FMRegressionModel, FMRegressor


def _binary(ctx, n=500, d=6, seed=3, sep=2.0):
    rng = np.random.RandomState(seed)
    beta = rng.randn(d)
    x = rng.randn(n, d)
    y = (x @ beta + 0.1 * rng.randn(n) > 0).astype(np.float64)
    x[y == 1] += sep * beta / np.linalg.norm(beta) * 0.5
    return MLFrame(ctx, {"features": x, "label": y}), x, y


class TestLinearSVC:
    def test_separates_and_matches_sklearn(self, ctx):
        from sklearn.svm import LinearSVC as SkSVC
        frame, x, y = _binary(ctx)
        ours = LinearSVC(regParam=0.01, maxIter=200, tol=1e-9).fit(frame)
        pred = ours.transform(frame)["prediction"]
        acc = (pred == y).mean()
        sk = SkSVC(C=1.0 / (0.01 * len(y)), loss="hinge", max_iter=20000,
                   tol=1e-10).fit(x, y)
        sk_acc = sk.score(x, y)
        assert acc >= sk_acc - 0.02
        # hinge objective of our solution should be <= sklearn's (we solve
        # the same problem: mean hinge + reg/2 ||b||^2 in standardized space)

    def test_threshold_on_margin(self, ctx):
        frame, x, y = _binary(ctx, seed=4)
        m = LinearSVC(regParam=0.1, maxIter=50).fit(frame)
        hi = m.copy()
        hi.set("threshold", 1e6)
        assert np.all(hi.transform(frame)["prediction"] == 0.0)
        lo = m.copy()
        lo.set("threshold", -1e6)
        assert np.all(lo.transform(frame)["prediction"] == 1.0)

    def test_rejects_multiclass(self, ctx):
        rng = np.random.RandomState(5)
        frame = MLFrame(ctx, {"features": rng.randn(30, 3),
                              "label": rng.randint(0, 3, 30).astype(float)})
        with pytest.raises(ValueError, match="labels in"):
            LinearSVC().fit(frame)

    def test_rejects_plus_minus_one_labels(self, ctx):
        # the ±1 SVM convention must error, not silently corrupt the hinge
        rng = np.random.RandomState(5)
        frame = MLFrame(ctx, {"features": rng.randn(30, 3),
                              "label": rng.choice([-1.0, 1.0], 30)})
        with pytest.raises(ValueError, match="labels in"):
            LinearSVC().fit(frame)

    def test_persistence(self, ctx, tmp_path):
        frame, x, y = _binary(ctx, seed=6)
        m = LinearSVC(regParam=0.05, maxIter=30).fit(frame)
        p = str(tmp_path / "svc")
        m.save(p)
        m2 = LinearSVCModel.load(p)
        np.testing.assert_allclose(m2.coefficients.to_array(),
                                   m.coefficients.to_array())
        assert m2.intercept == m.intercept


class TestNaiveBayes:
    def _counts(self, ctx, n=400, d=12, k=3, seed=7):
        rng = np.random.RandomState(seed)
        profiles = rng.dirichlet(np.ones(d) * 0.4, size=k)
        y = rng.randint(0, k, n).astype(np.float64)
        x = np.stack([rng.multinomial(40, profiles[int(c)]) for c in y]) \
            .astype(np.float64)
        return MLFrame(ctx, {"features": x, "label": y}), x, y

    def test_multinomial_matches_sklearn(self, ctx):
        from sklearn.naive_bayes import MultinomialNB
        frame, x, y = self._counts(ctx)
        ours = NaiveBayes(smoothing=1.0).fit(frame)
        sk = MultinomialNB(alpha=1.0).fit(x, y)
        # priors use the REFERENCE's smoothed formula log(n_c+λ)-log(n+kλ)
        # (sklearn's class_log_prior_ is unsmoothed — small difference)
        counts = np.array([(y == c).sum() for c in range(3)], float)
        expect_pi = np.log(counts + 1.0) - np.log(counts.sum() + 3.0)
        np.testing.assert_allclose(ours.pi, expect_pi, atol=1e-9)
        np.testing.assert_allclose(ours.theta.to_array(),
                                   sk.feature_log_prob_, atol=1e-9)
        pred = ours.transform(frame)["prediction"]
        assert (pred == sk.predict(x)).mean() > 0.98

    def test_bernoulli_matches_sklearn(self, ctx):
        from sklearn.naive_bayes import BernoulliNB
        rng = np.random.RandomState(8)
        x = (rng.rand(300, 10) < 0.3).astype(np.float64)
        y = rng.randint(0, 2, 300).astype(np.float64)
        frame = MLFrame(ctx, {"features": x, "label": y})
        ours = NaiveBayes(modelType="bernoulli", smoothing=1.0).fit(frame)
        sk = BernoulliNB(alpha=1.0).fit(x, y)
        np.testing.assert_allclose(ours.theta.to_array(),
                                   sk.feature_log_prob_, atol=1e-9)
        np.testing.assert_array_equal(
            ours.transform(frame)["prediction"], sk.predict(x))

    def test_gaussian_matches_sklearn(self, ctx):
        from sklearn.naive_bayes import GaussianNB
        rng = np.random.RandomState(9)
        x = np.concatenate([rng.randn(100, 4) - 1, rng.randn(100, 4) + 1])
        y = np.concatenate([np.zeros(100), np.ones(100)])
        frame = MLFrame(ctx, {"features": x, "label": y})
        ours = NaiveBayes(modelType="gaussian").fit(frame)
        sk = GaussianNB().fit(x, y)
        agree = (ours.transform(frame)["prediction"] == sk.predict(x)).mean()
        assert agree > 0.99

    def test_complement_mode(self, ctx):
        from sklearn.naive_bayes import ComplementNB
        frame, x, y = self._counts(ctx, seed=10)
        ours = NaiveBayes(modelType="complement", smoothing=1.0).fit(frame)
        sk = ComplementNB(alpha=1.0, norm=False).fit(x, y)
        agree = (ours.transform(frame)["prediction"] == sk.predict(x)).mean()
        assert agree > 0.95

    def test_rejects_negative_features(self, ctx):
        frame = MLFrame(ctx, {"features": np.array([[1.0, -1.0]]),
                              "label": np.array([0.0])})
        with pytest.raises(ValueError, match="nonnegative"):
            NaiveBayes().fit(frame)

    def test_persistence(self, ctx, tmp_path):
        frame, x, y = self._counts(ctx, seed=11)
        m = NaiveBayes().fit(frame)
        p = str(tmp_path / "nb")
        m.save(p)
        m2 = NaiveBayesModel.load(p)
        np.testing.assert_allclose(m2.theta.to_array(), m.theta.to_array())


class TestFM:
    def test_classifier_learns_xor_interaction(self, ctx):
        # pure pairwise-interaction structure a linear model cannot fit
        rng = np.random.RandomState(12)
        x = rng.choice([-1.0, 1.0], size=(600, 2))
        y = (x[:, 0] * x[:, 1] > 0).astype(np.float64)
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = FMClassifier(factorSize=4, maxIter=200, stepSize=0.1,
                         seed=5).fit(frame)
        acc = (m.transform(frame)["prediction"] == y).mean()
        assert acc > 0.95
        # probabilities well-formed
        prob = m.transform(frame)["probability"]
        assert np.all(np.isclose(prob.sum(1), 1.0))

    def test_regressor_fits_quadratic(self, ctx):
        rng = np.random.RandomState(13)
        x = rng.randn(500, 3)
        y = 2.0 + x @ np.array([1.0, -2.0, 0.5]) + 1.5 * x[:, 0] * x[:, 1]
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = FMRegressor(factorSize=4, maxIter=400, stepSize=0.1,
                        seed=3).fit(frame)
        pred = m.transform(frame)["prediction"]
        r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
        assert r2 > 0.95

    def test_minibatch_and_gd_solver(self, ctx):
        rng = np.random.RandomState(14)
        x = rng.randn(300, 3)
        y = x @ np.array([1.0, 0.5, -1.0])
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = FMRegressor(factorSize=2, maxIter=150, solver="gd",
                        stepSize=0.05, miniBatchFraction=0.5, seed=2).fit(frame)
        pred = m.transform(frame)["prediction"]
        assert np.corrcoef(pred, y)[0, 1] > 0.9

    def test_persistence(self, ctx, tmp_path):
        rng = np.random.RandomState(15)
        x = rng.randn(100, 3)
        y = (x[:, 0] > 0).astype(np.float64)
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = FMClassifier(factorSize=2, maxIter=20, seed=1).fit(frame)
        p = str(tmp_path / "fm")
        m.save(p)
        m2 = FMClassificationModel.load(p)
        np.testing.assert_allclose(m2.factors.to_array(),
                                   m.factors.to_array())
        np.testing.assert_array_equal(m2.transform(frame)["prediction"],
                                      m.transform(frame)["prediction"])


class TestMLP:
    def test_learns_xor(self, ctx):
        rng = np.random.RandomState(16)
        x = rng.choice([-1.0, 1.0], size=(400, 2)) + 0.1 * rng.randn(400, 2)
        y = (x[:, 0] * x[:, 1] > 0).astype(np.float64)
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = MultilayerPerceptronClassifier(
            layers=[2, 8, 2], maxIter=300, seed=5).fit(frame)
        acc = (m.transform(frame)["prediction"] == y).mean()
        assert acc > 0.95

    def test_three_class_blobs(self, ctx):
        rng = np.random.RandomState(17)
        centers = np.array([[0, 4], [-4, -2], [4, -2]], float)
        y = rng.randint(0, 3, 450).astype(np.float64)
        x = centers[y.astype(int)] + 0.5 * rng.randn(450, 2)
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = MultilayerPerceptronClassifier(
            layers=[2, 5, 3], maxIter=200, seed=2).fit(frame)
        out = m.transform(frame)
        assert (out["prediction"] == y).mean() > 0.97
        prob = out["probability"]
        assert np.all(np.isclose(prob.sum(1), 1.0, atol=1e-6))

    def test_initial_weights_and_validation(self, ctx):
        rng = np.random.RandomState(18)
        frame = MLFrame(ctx, {"features": rng.randn(50, 3),
                              "label": rng.randint(0, 2, 50).astype(float)})
        with pytest.raises(ValueError, match="input layer"):
            MultilayerPerceptronClassifier(layers=[4, 2], maxIter=5).fit(frame)
        with pytest.raises(ValueError, match="initialWeights"):
            MultilayerPerceptronClassifier(
                layers=[3, 2], maxIter=5,
                initialWeights=np.zeros(3)).fit(frame)

    def test_persistence(self, ctx, tmp_path):
        rng = np.random.RandomState(19)
        x = rng.randn(80, 3)
        y = (x[:, 0] > 0).astype(np.float64)
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = MultilayerPerceptronClassifier(layers=[3, 4, 2], maxIter=30,
                                           seed=1).fit(frame)
        p = str(tmp_path / "mlp")
        m.save(p)
        m2 = MultilayerPerceptronClassificationModel.load(p)
        np.testing.assert_allclose(m2.weights.to_array(),
                                   m.weights.to_array())
        np.testing.assert_array_equal(m2.transform(frame)["prediction"],
                                      m.transform(frame)["prediction"])


class TestOneVsRest:
    def test_multiclass_via_binary_lr(self, ctx):
        rng = np.random.RandomState(20)
        centers = np.array([[0, 5], [-5, -3], [5, -3]], float)
        y = rng.randint(0, 3, 360).astype(np.float64)
        x = centers[y.astype(int)] + 0.6 * rng.randn(360, 2)
        frame = MLFrame(ctx, {"features": x, "label": y})
        ovr = OneVsRest(classifier=LogisticRegression(maxIter=50))
        model = ovr.fit(frame)
        assert model.num_classes == 3
        acc = (model.transform(frame)["prediction"] == y).mean()
        assert acc > 0.97

    def test_parallelism(self, ctx):
        rng = np.random.RandomState(21)
        y = rng.randint(0, 4, 200).astype(np.float64)
        x = np.eye(4)[y.astype(int)] + 0.1 * rng.randn(200, 4)
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = OneVsRest(classifier=LogisticRegression(maxIter=20),
                      parallelism=4).fit(frame)
        assert (m.transform(frame)["prediction"] == y).mean() > 0.95

    def test_persistence(self, ctx, tmp_path):
        rng = np.random.RandomState(22)
        y = rng.randint(0, 3, 150).astype(np.float64)
        x = np.eye(3)[y.astype(int)] + 0.1 * rng.randn(150, 3)
        frame = MLFrame(ctx, {"features": x, "label": y})
        m = OneVsRest(classifier=LogisticRegression(maxIter=20)).fit(frame)
        p = str(tmp_path / "ovr")
        m.save(p)
        m2 = OneVsRestModel.load(p)
        np.testing.assert_array_equal(m2.transform(frame)["prediction"],
                                      m.transform(frame)["prediction"])
