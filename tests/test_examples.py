"""Smoke tests running the example programs on the test mesh
(≈ the reference running its examples in CI via run-tests)."""

import runpy
import sys

import pytest

EXAMPLES = [
    "examples/logistic_regression_example.py",
    "examples/pipeline_example.py",
    "examples/structured_streaming_wordcount.py",
    "examples/sql_example.py",
    "examples/kmeans_example.py",
    "examples/sparse_logistic_example.py",
    "examples/graph_pagerank.py",
    "examples/window_analytics_example.py",
    "examples/streaming_etl_to_parquet.py",
    "examples/streamed_ingest_monitoring_example.py",
    "examples/sql_server_example.py",
]


@pytest.mark.parametrize("path", EXAMPLES)
def test_example_runs(ctx, path, capsys):
    mod = runpy.run_path(path)
    result = mod["main"]()
    assert result is not None
    out = capsys.readouterr().out
    assert out.strip()  # every example prints something meaningful
