"""Shared-secret authentication across the TCP fabric (round-4 verdict
item 3): one mutual HMAC challenge-response in util/tcp.py covers the
exchange, deploy master, heartbeat, and SQL server wires — wrong-secret
connections are rejected per service, right-secret end-to-end flows stay
green. Ref: common/network-common/.../sasl/SaslRpcHandler.java:44."""

import socket
import threading

import numpy as np
import pytest

from cycloneml_tpu.util.tcp import (client_handshake, connect_authed,
                                    server_handshake, start_tcp_server)

SECRET = "round5-fabric-secret"


def test_handshake_unit_right_and_wrong():
    a, b = socket.socketpair()
    try:
        res = {}
        t = threading.Thread(
            target=lambda: res.update(ok=server_handshake(a, SECRET)))
        t.start()
        client_handshake(b, SECRET)  # no raise
        t.join(5)
        assert res["ok"] is True
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        res = {}
        t = threading.Thread(
            target=lambda: res.update(ok=server_handshake(a, SECRET)))
        t.start()
        with pytest.raises(PermissionError):
            client_handshake(b, "not-the-secret")
        t.join(5)
        assert res["ok"] is False
    finally:
        a.close()
        b.close()


def test_sql_server_auth(monkeypatch):
    from cycloneml_tpu.sql.server import CycloneSQLServer, SQLClient
    from cycloneml_tpu.sql.session import CycloneSession
    s = CycloneSession()
    df = s.create_data_frame({"v": np.array([1.0, 2.0])})
    s.register_temp_view("t", df)
    srv = CycloneSQLServer(s, secret=SECRET)
    try:
        with SQLClient(srv.address, secret=SECRET) as c:
            _, rows = c.execute("SELECT SUM(v) AS sv FROM t")
            assert rows == [[3.0]]
        host, port = srv.address.rsplit(":", 1)
        with pytest.raises(PermissionError):
            connect_authed(host, int(port), secret="wrong")
    finally:
        srv.stop()


def test_heartbeat_auth(monkeypatch):
    monkeypatch.setenv("CYCLONE_AUTH_SECRET", SECRET)
    from cycloneml_tpu.parallel.resilience import (HeartbeatReceiver,
                                                   HeartbeatSender,
                                                   HeartbeatServer)
    recv = HeartbeatReceiver(timeout_s=30)
    srv = HeartbeatServer(recv)
    try:
        sender = HeartbeatSender("w1", srv.address, interval_s=0.1)
        deadline = 50
        import time
        while deadline and "w1" not in recv._last:
            time.sleep(0.1)
            deadline -= 1
        sender.stop()
        assert "w1" in recv._last
        with pytest.raises(PermissionError):
            connect_authed(srv.host, srv.port, secret="wrong")
    finally:
        srv.stop()


def test_deploy_master_auth(monkeypatch):
    monkeypatch.setenv("CYCLONE_AUTH_SECRET", SECRET)
    from cycloneml_tpu.deploy import MasterDaemon, _send
    m = MasterDaemon(port=0)
    try:
        rep = _send(m.address, {"cmd": "STATUS"})
        assert isinstance(rep, dict) and rep  # authed round-trip works
        host, port = m.address.rsplit(":", 1)
        with pytest.raises(PermissionError):
            connect_authed(host, int(port), secret="wrong")
    finally:
        m.stop()


def test_exchange_auth(monkeypatch):
    monkeypatch.setenv("CYCLONE_AUTH_SECRET", SECRET)
    from cycloneml_tpu.parallel.exchange import HashExchange

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    addrs = [f"127.0.0.1:{free_port()}", f"127.0.0.1:{free_port()}"]
    out = {}

    def worker(rank):
        ex = HashExchange(rank, addrs, n_buckets=4, round_id=991991)
        ex.put_all([(i, rank) for i in range(20)])
        out[rank] = {b: list(p) for b, p in ex.finish(timeout=30).items()}

    ts = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    # every key landed with its owner: the authed fabric carried data
    n = sum(len(v) for d in out.values() for v in d.values())
    assert n == 40, out
    host, port = addrs[0].rsplit(":", 1)
    with pytest.raises(PermissionError):
        connect_authed(host, int(port), secret="wrong")


def test_secretless_fabric_stays_open():
    """No secret configured → no handshake, plain protocol (the
    reference's spark.authenticate=false default)."""
    import socketserver

    class Echo(socketserver.StreamRequestHandler):
        def handle(self):
            self.wfile.write(self.rfile.readline())

    srv = start_tcp_server("127.0.0.1", 0, Echo, "echo-test")
    try:
        host, port = srv.server_address
        with connect_authed(host, port, secret=None) as s:
            s.sendall(b"ping\n")
            assert s.makefile("rb").readline() == b"ping\n"
    finally:
        srv.shutdown()
        srv.server_close()


def test_secretless_client_fails_loudly_on_authed_server(monkeypatch):
    """The reverse misconfiguration: server authed, client secretless —
    line clients must raise PermissionError on the challenge instead of
    mis-parsing it / silently spinning (review r5)."""
    from cycloneml_tpu.sql.server import CycloneSQLServer, SQLClient
    from cycloneml_tpu.sql.session import CycloneSession
    srv = CycloneSQLServer(CycloneSession(), secret=SECRET)
    try:
        monkeypatch.delenv("CYCLONE_AUTH_SECRET", raising=False)
        with SQLClient(srv.address) as c:  # no secret resolves
            with pytest.raises(PermissionError, match="requires fabric"):
                c.execute("SELECT 1 AS one")
    finally:
        srv.stop()
    # heartbeat sender: stops its loop on the same detection
    from cycloneml_tpu.parallel.resilience import (HeartbeatReceiver,
                                                   HeartbeatSender,
                                                   HeartbeatServer)
    monkeypatch.setenv("CYCLONE_AUTH_SECRET", SECRET)
    recv = HeartbeatReceiver(timeout_s=30)
    hsrv = HeartbeatServer(recv)
    monkeypatch.delenv("CYCLONE_AUTH_SECRET")
    try:
        import time
        sender = HeartbeatSender("w2", hsrv.address, interval_s=0.05)
        time.sleep(0.8)
        assert "w2" not in recv._last
        assert not sender._thread.is_alive()  # loop stopped loudly
    finally:
        hsrv.stop()


def test_ctas_rejects_base_session_view_name(tmp_path):
    from cycloneml_tpu.sql.session import CycloneSession
    base = CycloneSession(warehouse=str(tmp_path / "wh"))
    df = base.create_data_frame({"v": np.array([1.0])})
    base.register_temp_view("seeded", df)
    child = base.new_session()
    with pytest.raises(ValueError, match="base-session view"):
        child.sql("CREATE TABLE seeded AS SELECT v FROM seeded")
