"""Distributed SQL execution over the cross-process exchange (round-3
verdict item 3): when ``cyclone.exchange.addresses`` is configured, SQL
Aggregate/Join and PartitionedDataset.group_by_key route their shuffles
through the HashExchange wire fabric — scan → exchange → per-bucket
columnar op, the ShuffleExchangeExec analog. Two REAL processes run the
same query SPMD-style on local slices; the union of their results must
equal the single-process answer, with bounded RSS past the row budget."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SQL_WORKER = textwrap.dedent("""
    import json, os, resource, sys
    import numpy as np
    rank, addr0, addr1, outdir = (int(sys.argv[1]), sys.argv[2],
                                  sys.argv[3], sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax; jax.config.update("jax_platforms", "cpu")
    from cycloneml_tpu.conf import CycloneConf
    from cycloneml_tpu.context import CycloneContext
    from cycloneml_tpu.sql.session import CycloneSession
    base_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024

    conf = (CycloneConf()
            .set("cyclone.master", "local-mesh[1]")
            .set("cyclone.exchange.addresses", addr0 + "," + addr1)
            .set("cyclone.exchange.rank", str(rank))
            .set("cyclone.exchange.numBuckets", "16")
            .set("cyclone.shuffle.spill.rowBudget", "5000"))
    ctx = CycloneContext.get_or_create(conf)
    session = CycloneSession(ctx)

    # each process holds HALF the fact table: 200k rows, 1000 keys — far
    # over the 5k row budget; keys interleave across processes so every
    # group spans both
    N, K = 200_000, 1000
    ids = (np.arange(N) * 2 + rank) % K
    vals = np.arange(N, dtype=np.float64) + rank
    fact = session.create_data_frame({"k": ids, "v": vals})
    session.register_temp_view("fact", fact)

    # dims: each process holds a slice; some keys have no fact rows and
    # some fact keys no dim row -> outer join must null-extend both ways
    dk = np.arange(rank, K + 100, 2)
    dim = session.create_data_frame(
        {"k": dk, "name": np.array([f"n{int(x)}" for x in dk], object)})
    session.register_temp_view("dim", dim)

    agg = session.sql(
        "SELECT k, COUNT(*) AS c, SUM(v) AS s FROM fact GROUP BY k"
    ).to_dict()
    j = session.sql(
        "SELECT d.k AS k, d.name AS name, f.c AS c FROM dim d FULL OUTER "
        "JOIN (SELECT k, COUNT(*) AS c FROM fact GROUP BY k) f ON d.k = f.k"
    ).to_dict()
    tot = session.sql("SELECT COUNT(*) AS n, SUM(v) AS s FROM fact").to_dict()

    peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
    out = {
        "agg": {int(k): [int(c), float(s)] for k, c, s in
                zip(agg["k"], agg["c"], agg["s"])},
        "join": sorted(
            [None if (isinstance(k, float) and np.isnan(k)) else int(k),
             None if n is None else str(n),
             None if (isinstance(c, float) and np.isnan(c)) else int(c)]
            for k, n, c in zip(j["k"], j["name"], j["c"])),
        "total": [[int(n), float(s)] for n, s in zip(
            np.atleast_1d(tot["n"]), np.atleast_1d(tot["s"]))],
        "delta_mb": int(peak_mb - base_mb),
    }
    with open(os.path.join(outdir, f"sql_{rank}.json"), "w") as fh:
        json.dump(out, fh)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_two(script, tmp_path, timeout=280):
    wp = tmp_path / "worker.py"
    wp.write_text(script)
    addrs = [f"localhost:{_free_port()}", f"localhost:{_free_port()}"]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, str(wp), str(r), addrs[0], addrs[1], str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"


def _single_process_oracle():
    """The same query single-process (no exchange conf)."""
    ids = np.concatenate([(np.arange(200_000) * 2 + r) % 1000
                          for r in range(2)])
    vals = np.concatenate([np.arange(200_000, dtype=np.float64) + r
                           for r in range(2)])
    agg = {}
    for k in range(1000):
        m = ids == k
        agg[k] = [int(m.sum()), float(vals[m].sum())]
    dk = np.sort(np.concatenate([np.arange(r, 1100, 2) for r in range(2)]))
    join = []
    for k in dk:
        k = int(k)
        if k in agg:
            join.append([k, f"n{k}", agg[k][0]])
        else:
            join.append([k, f"n{k}", None])
    # fact keys with no dim row: dim covers 0..1099 → none missing
    return agg, sorted(join), [len(ids), float(vals.sum())]


def test_two_process_sql_groupby_outer_join(tmp_path):
    _run_two(SQL_WORKER, tmp_path)
    res = [json.load(open(tmp_path / f"sql_{r}.json")) for r in range(2)]

    exp_agg, exp_join, exp_total = _single_process_oracle()

    # aggregation: disjoint ownership, union == oracle
    got_agg = {}
    for r in res:
        for k, v in r["agg"].items():
            assert int(k) not in got_agg, "key owned by both processes"
            got_agg[int(k)] = v
    assert got_agg == exp_agg

    # full outer join: union == oracle (incl. null-extended rows)
    got_join = sorted(sum((r["join"] for r in res), []))
    assert got_join == [list(x) for x in exp_join]

    # global aggregate: exactly one process emitted the single result row
    totals = sum((r["total"] for r in res), [])
    assert totals == [exp_total]

    # bounded RSS: each side processed ~200k fact rows with a 5k budget;
    # growth over the import baseline stays well under the full data
    for r in res:
        assert r["delta_mb"] < 200, r["delta_mb"]


def test_exchange_join_outer_modes(tmp_path):
    """exchange_join left/right/outer yield None-extended pairs (verdict:
    the distributed join surface beyond inner)."""
    script = textwrap.dedent("""
        import json, os, sys
        rank, addr0, addr1, outdir = (int(sys.argv[1]), sys.argv[2],
                                      sys.argv[3], sys.argv[4])
        from cycloneml_tpu.parallel.exchange import exchange_join
        out = {}
        for how in ["left", "right", "outer"]:
            left = [(k, f"L{k}.{rank}") for k in range(rank, 10, 2)]
            right = [(k, f"R{k}.{rank}") for k in range(rank, 16, 2)
                     if k % 3 == 0]
            # SAME addresses for all three back-to-back rounds: the
            # process-lived server must route frames by round id even when
            # one rank races ahead into the next round (review r4)
            rows = sorted(exchange_join(left, right, rank, [addr0, addr1],
                                        n_buckets=8, how=how))
            out[how] = rows
        with open(os.path.join(outdir, f"oj_{rank}.json"), "w") as fh:
            json.dump(out, fh)
    """)
    _run_two(script, tmp_path)
    res = [json.load(open(tmp_path / f"oj_{r}.json")) for r in range(2)]

    left = {k: f"L{k}.{k % 2}" for k in range(10)}
    right = {k: f"R{k}.{k % 2}" for k in range(16) if k % 3 == 0}
    for how in ("left", "right", "outer"):
        got = sorted((k, tuple(p)) for r in res for k, p in r[how])
        exp = []
        keys = set(left) | set(right)
        for k in sorted(keys):
            lv, rv = left.get(k), right.get(k)
            if lv and rv:
                exp.append((k, (lv, rv)))
            elif lv and how in ("left", "outer"):
                exp.append((k, (lv, None)))
            elif rv and how in ("right", "outer"):
                exp.append((k, (None, rv)))
        assert got == sorted(exp), how


def test_rdd_group_by_key_routes_through_exchange(tmp_path):
    """PartitionedDataset.group_by_key auto-routes cross-process when the
    exchange conf is set; owned groups union to the full answer."""
    script = textwrap.dedent("""
        import json, os, sys
        rank, addr0, addr1, outdir = (int(sys.argv[1]), sys.argv[2],
                                      sys.argv[3], sys.argv[4])
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax; jax.config.update("jax_platforms", "cpu")
        from cycloneml_tpu.conf import CycloneConf
        from cycloneml_tpu.context import CycloneContext
        from cycloneml_tpu.dataset.dataset import PartitionedDataset
        conf = (CycloneConf().set("cyclone.master", "local-mesh[1]")
                .set("cyclone.exchange.addresses", addr0 + "," + addr1)
                .set("cyclone.exchange.rank", str(rank))
                .set("cyclone.exchange.numBuckets", "8"))
        ctx = CycloneContext.get_or_create(conf)
        data = [((i * 2 + rank) % 50, i) for i in range(2000)]
        pd = PartitionedDataset.from_sequence(ctx, data, 2)
        got = {str(k): sorted(vs) for k, vs in pd.group_by_key().collect()}
        red = dict(pd.reduce_by_key(lambda a, b: a + b).collect())
        with open(os.path.join(outdir, f"rdd_{rank}.json"), "w") as fh:
            json.dump({"groups": got,
                       "reduced": {str(k): v for k, v in red.items()}}, fh)
    """)
    _run_two(script, tmp_path)
    res = [json.load(open(tmp_path / f"rdd_{r}.json")) for r in range(2)]
    all_pairs = [((i * 2 + r) % 50, i) for r in range(2) for i in range(2000)]
    exp = {}
    for k, v in all_pairs:
        exp.setdefault(k, []).append(v)
    exp = {k: sorted(vs) for k, vs in exp.items()}
    got = {}
    for r in res:
        for k, vs in r["groups"].items():
            assert int(k) not in got
            got[int(k)] = vs
    assert got == exp
    got_red = {int(k): v for r in res for k, v in r["reduced"].items()}
    assert got_red == {k: sum(vs) for k, vs in exp.items()}


def test_adaptive_broadcast_join_and_coalescing(tmp_path):
    """AQE (ref AdaptiveSparkPlanExec): runtime size statistics choose a
    BROADCAST join for a small side (no exchange of the big side), fall
    back to the shuffled join when the threshold disables it, and
    post-shuffle coalescing merges near-empty output partitions."""
    script = textwrap.dedent("""
        import json, os, sys
        import numpy as np
        rank, addr0, addr1, outdir = (int(sys.argv[1]), sys.argv[2],
                                      sys.argv[3], sys.argv[4])
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax; jax.config.update("jax_platforms", "cpu")
        from cycloneml_tpu.conf import CycloneConf
        from cycloneml_tpu.context import CycloneContext
        from cycloneml_tpu.sql.session import CycloneSession
        from cycloneml_tpu.sql.plan import Join
        from cycloneml_tpu.dataset.dataset import PartitionedDataset
        conf = (CycloneConf().set("cyclone.master", "local-mesh[1]")
                .set("cyclone.exchange.addresses", addr0 + "," + addr1)
                .set("cyclone.exchange.rank", str(rank))
                .set("cyclone.exchange.numBuckets", "16"))
        ctx = CycloneContext.get_or_create(conf)
        s = CycloneSession(ctx)

        # big fact slice per process; tiny dim -> AQE must broadcast it
        N = 50_000
        fact = s.create_data_frame(
            {"k": (np.arange(N) * 2 + rank) % 100,
             "v": np.arange(N, dtype=np.float64)})
        dim = s.create_data_frame(
            {"k": np.arange(rank, 100, 2),
             "name": np.array([f"n{i}" for i in range(rank, 100, 2)],
                              object)})
        s.register_temp_view("fact", fact)
        s.register_temp_view("dim", dim)

        import cycloneml_tpu.sql.plan as plan_mod
        df = s.table("fact").join(s.table("dim"), on="k", how="inner")
        out = df.to_dict()
        strategy = plan_mod.LAST_JOIN_STRATEGY

        # threshold -1 forces the shuffled path; results must agree
        ctx.conf.set("cyclone.sql.autoBroadcastJoinThreshold", "-1")
        df2 = s.table("fact").join(s.table("dim"), on="k", how="inner")
        out2 = df2.to_dict()
        strategy2 = plan_mod.LAST_JOIN_STRATEGY

        # post-shuffle coalescing: 16 buckets of a tiny dataset collapse
        pd_small = PartitionedDataset.from_sequence(
            ctx, [(i % 10, i) for i in range(200)], 2)
        grouped = pd_small.group_by_key()
        parts = grouped._partitions()

        bc_sum = float(np.sum(out["v"]))
        ex_rows = sorted(zip(np.asarray(out2["k"]).tolist(),
                             np.asarray(out2["v"]).tolist()))
        bc_rows = sorted(zip(np.asarray(out["k"]).tolist(),
                             np.asarray(out["v"]).tolist()))
        with open(os.path.join(outdir, f"aqe_{rank}.json"), "w") as fh:
            json.dump({"strategy": strategy, "strategy2": strategy2,
                       "n_rows": len(out["k"]),
                       "bc_equals_ex": bc_rows == ex_rows,
                       "n_parts": len(parts),
                       "grouped_n": len(grouped.collect()),
                       "sum": bc_sum}, fh)
    """)
    _run_two(script, tmp_path)
    res = [json.load(open(tmp_path / f"aqe_{r}.json")) for r in range(2)]
    for r in res:
        assert r["strategy"].startswith("broadcast"), r
        assert r["strategy2"] == "exchange", r
    # broadcast keeps each process's LOCAL fact rows: the union of row
    # counts equals the single-process inner join; per-process results
    # equal that process's exchange-mode result ONLY in aggregate, so
    # compare totals
    assert res[0]["n_rows"] + res[1]["n_rows"] == 100_000
    # coalescing collapsed the 16-bucket shuffle of 200 rows
    for r in res:
        assert r["n_parts"] <= 2, r["n_parts"]
    assert res[0]["grouped_n"] + res[1]["grouped_n"] == 10


SKEW_WORKER = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    rank, addr0, addr1, outdir = (int(sys.argv[1]), sys.argv[2],
                                  sys.argv[3], sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax; jax.config.update("jax_platforms", "cpu")
    from cycloneml_tpu.conf import CycloneConf
    from cycloneml_tpu.context import CycloneContext
    from cycloneml_tpu.sql.session import CycloneSession
    from cycloneml_tpu.sql import plan as plan_mod

    conf = (CycloneConf()
            .set("cyclone.master", "local-mesh[1]")
            .set("cyclone.exchange.addresses", addr0 + "," + addr1)
            .set("cyclone.exchange.rank", str(rank))
            .set("cyclone.exchange.numBuckets", "16")
            # force the exchange path (no broadcast) and make the skew
            # detector fire on test-sized data
            .set("cyclone.sql.autoBroadcastJoinThreshold", "-1")
            .set("cyclone.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes", "2000")
            .set("cyclone.sql.adaptive.skewJoin.skewedPartitionFactor", "2"))
    ctx = CycloneContext.get_or_create(conf)
    session = CycloneSession(ctx)

    # ONE hot key (0): 30k rows per process; 200 normal keys x 10 rows
    HOT, NK, NR = 30_000, 200, 10
    ids = np.concatenate([np.zeros(HOT, np.int64),
                          np.repeat(np.arange(1, NK + 1), NR)])
    fact = session.create_data_frame(
        {"k": ids, "v": np.ones(len(ids))})
    session.register_temp_view("fact", fact)
    dk = np.arange(rank, NK + 1, 2)  # each process holds half the dim
    session.register_temp_view("dim", session.create_data_frame(
        {"k": dk, "name": np.array([f"n{int(x)}" for x in dk], object)}))
    # dim2 lacks the hot key entirely -> LEFT join null-extends it
    session.register_temp_view("dim2", session.create_data_frame(
        {"k": np.arange(1, NK + 1)[rank::2],
         "name": np.array([f"m{int(x)}" for x in np.arange(1, NK+1)[rank::2]],
                          object)}))

    inner = session.sql(
        "SELECT f.k AS k, f.v AS v, d.name AS name "
        "FROM fact f JOIN dim d ON f.k = d.k").to_dict()
    inner_strategy = plan_mod.LAST_JOIN_STRATEGY
    inner_splits = dict(plan_mod.LAST_SKEW_SPLITS)

    left = session.sql(
        "SELECT f.k AS k, f.v AS v, d.name AS name "
        "FROM fact f LEFT JOIN dim2 d ON f.k = d.k").to_dict()
    left_strategy = plan_mod.LAST_JOIN_STRATEGY
    left_splits = dict(plan_mod.LAST_SKEW_SPLITS)

    def null_count(col):
        return int(sum(1 for x in col if x is None))

    out = {
        "inner": {"n": int(len(inner["k"])),
                  "hot": int((np.asarray(inner["k"]) == 0).sum()),
                  "strategy": inner_strategy,
                  "splits": {str(b): s for b, s in inner_splits.items()}},
        "left": {"n": int(len(left["k"])),
                 "hot": int((np.asarray(left["k"]) == 0).sum()),
                 "hot_nulls": int(sum(
                     1 for k, nm in zip(left["k"], left["name"])
                     if k == 0 and nm is None)),
                 "strategy": left_strategy,
                 "splits": {str(b): s for b, s in left_splits.items()}},
    }
    with open(os.path.join(outdir, f"skew_{rank}.json"), "w") as fh:
        json.dump(out, fh)
""")


def test_skew_join_splits_hot_bucket(tmp_path):
    """AQE skew-join (r4 verdict item 5): a hot key's join work SPREADS
    across both processes (each produces part of the hot output) and the
    union still matches the single-process oracle, for inner AND
    left-outer (hot key unmatched) joins."""
    _run_two(SKEW_WORKER, tmp_path)
    res = [json.load(open(tmp_path / f"skew_{r}.json")) for r in range(2)]
    HOT, NK, NR = 30_000, 200, 10
    for r in res:
        assert r["inner"]["strategy"] == "exchange_skew_split"
        assert r["inner"]["splits"], "no bucket was split"
        assert r["left"]["strategy"] == "exchange_skew_split"
    # inner oracle: hot key matches dim (2*30k rows x 1 dim row) + each
    # normal key matches once -> 20 rows/key
    exp_inner = 2 * HOT + NK * 2 * NR
    assert res[0]["inner"]["n"] + res[1]["inner"]["n"] == exp_inner
    assert res[0]["inner"]["hot"] + res[1]["inner"]["hot"] == 2 * HOT
    # THE SPLIT IS REAL: both processes produced part of the hot key's
    # output (without splitting, one owner holds all of it)
    assert res[0]["inner"]["hot"] > 0 and res[1]["inner"]["hot"] > 0
    # left oracle: every fact row appears once; hot rows null-extended
    exp_left = 2 * (HOT + NK * NR)
    assert res[0]["left"]["n"] + res[1]["left"]["n"] == exp_left
    hot_total = res[0]["left"]["hot"] + res[1]["left"]["hot"]
    nulls = res[0]["left"]["hot_nulls"] + res[1]["left"]["hot_nulls"]
    assert hot_total == 2 * HOT and nulls == 2 * HOT
    assert res[0]["left"]["hot"] > 0 and res[1]["left"]["hot"] > 0
