"""JX009 should-pass fixtures: donation discipline done right."""
import jax
import jax.numpy as jnp


def _update(state, x):
    return state * 0.9 + x


_step = jax.jit(_update, donate_argnums=(0,))
_plain = jax.jit(_update)


def rebound_from_result(state, xs):
    # the idiom: the donated name is rebound from the program's result,
    # so every dispatch consumes an already-dead buffer
    for x in xs:
        state = _step(state, x)
    return state


def read_before_donate(state, x):
    # reads strictly precede the dispatch that kills the buffer
    norm = jnp.linalg.norm(state)
    state = _step(state, x)
    return state, norm


def no_donation_no_constraint(state, xs):
    # an undonated program leaves its inputs alive
    outs = []
    for x in xs:
        outs.append(_plain(state, x))
    return outs, state


def comprehension_over_undonated(state, xs):
    # an undonated program in a comprehension leaves its inputs alive
    return [_plain(state, x) for x in xs]


def comprehension_donates_its_own_variable(states, x):
    # each iteration donates a FRESH buffer from the iterable — the
    # comprehension variable is rebound per iteration by construction
    return [_step(s, x) for s in states]


def probe_first_item(state, xs):
    # every body path LEAVES the loop on iteration one — there is no
    # second iteration to dispatch the deleted buffer
    for x in xs:
        return _step(state, x)
    return state


def probe_first_item_under_span(state, xs, tracer):
    # a `with` block neither catches nor redirects control flow: the
    # return inside the span idiom still exits the loop on iteration one
    for x in xs:
        with tracer.span("dispatch"):
            return _step(state, x)
    return state


def metadata_read_after_donate(state, x):
    # aval metadata survives deletion — shape/dtype telemetry after the
    # dispatch never touches the donated buffer
    out = _step(state, x)
    return out, state.shape, state.dtype, state.ndim


def _advance(state, x):
    return _step(state, x)


def wrapped_donate_rebound(state, xs):
    # interprocedural donation, but correctly rebound each iteration
    for x in xs:
        state = _advance(state, x)
    return state
