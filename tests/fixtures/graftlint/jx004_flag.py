"""JX004 should-flag fixtures: fp64 drift in device code, no x64 guard."""
import jax
import jax.numpy as jnp


@jax.jit
def f64_dtype_kwarg(x):
    acc = jnp.zeros(x.shape, dtype=jnp.float64)     # JX004
    return acc + x


@jax.jit
def f64_string_dtype(x):
    return x.astype("float64")                       # JX004


@jax.jit
def f64_cast_call(x):
    return jnp.float64(1.5) * x                      # JX004


# the OTHER direction of the tier boundary: bf16 storage is legal, but a
# psum operand at storage width accumulates in 8 mantissa bits mesh-wide
@jax.jit
def narrow_psum_astype(x):
    return jax.lax.psum(x.astype(jnp.bfloat16), "data")        # JX004


@jax.jit
def narrow_psum_asarray(x):
    return jax.lax.psum(jnp.asarray(x, dtype="bfloat16"), "data")  # JX004


# narrowness is a dataflow fact, not a callsite pattern: the cast can
# hide behind a local name ...
@jax.jit
def narrow_psum_via_name(x):
    y = x.astype(jnp.bfloat16)
    return jax.lax.psum(y, "data")                   # JX004


# ... and the mark is judged AT the psum: re-widening afterwards doesn't
# retroactively clean the narrow accumulation that already happened
@jax.jit
def narrow_at_psum_rewidened_later(x):
    y = x.astype(jnp.bfloat16)
    acc = jax.lax.psum(y, "data")                    # JX004
    y = y.astype(jnp.float32)
    return acc + y


# ... or behind a helper function (interprocedural: the hazard is split
# across two defs — the single-function scan PR 6 hand-audited around)
def _to_storage(x):
    return x.astype(jnp.bfloat16)


@jax.jit
def narrow_psum_via_helper(x):
    return jax.lax.psum(_to_storage(x), "data")      # JX004


# ... and an ANNOTATED assignment narrows exactly like the bare form
@jax.jit
def narrow_via_annassign(x):
    y: jax.Array = x.astype(jnp.bfloat16)
    return jax.lax.psum(y, "data")                   # JX004


# the SECOND precision rung: fp8 storage (e4m3/e5m2) psummed un-upcast
# accumulates in 3 (e4m3) or 2 (e5m2) mantissa bits mesh-wide
@jax.jit
def fp8_psum_astype(x):
    return jax.lax.psum(x.astype(jnp.float8_e4m3fn), "data")   # JX004


@jax.jit
def fp8_e5m2_psum_asarray(x):
    return jax.lax.psum(jnp.asarray(x, dtype="float8_e5m2"), "data")  # JX004


@jax.jit
def fp8_psum_via_name(x):
    y = x.astype(jnp.float8_e4m3fn)
    return jax.lax.psum(y, "data")                   # JX004


# the fp8 STREAM's dequant fold gone wrong: dequantizing to f32 and then
# re-narrowing the partial back to codes before the collective puts the
# mesh-wide accumulation back in 3 mantissa bits — the fold must END wide
@jax.jit
def dequant_fold_renarrowed_psum(x8, scale):
    part = (x8.astype(jnp.float32) * scale).astype(jnp.float8_e4m3fn)
    return jax.lax.psum(part, "data")                # JX004
