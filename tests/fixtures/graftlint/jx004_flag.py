"""JX004 should-flag fixtures: fp64 drift in device code, no x64 guard."""
import jax
import jax.numpy as jnp


@jax.jit
def f64_dtype_kwarg(x):
    acc = jnp.zeros(x.shape, dtype=jnp.float64)     # JX004
    return acc + x


@jax.jit
def f64_string_dtype(x):
    return x.astype("float64")                       # JX004


@jax.jit
def f64_cast_call(x):
    return jnp.float64(1.5) * x                      # JX004


# the OTHER direction of the tier boundary: bf16 storage is legal, but a
# psum operand at storage width accumulates in 8 mantissa bits mesh-wide
@jax.jit
def narrow_psum_astype(x):
    return jax.lax.psum(x.astype(jnp.bfloat16), "data")        # JX004


@jax.jit
def narrow_psum_asarray(x):
    return jax.lax.psum(jnp.asarray(x, dtype="bfloat16"), "data")  # JX004
