"""JX004 should-flag fixtures: fp64 drift in device code, no x64 guard."""
import jax
import jax.numpy as jnp


@jax.jit
def f64_dtype_kwarg(x):
    acc = jnp.zeros(x.shape, dtype=jnp.float64)     # JX004
    return acc + x


@jax.jit
def f64_string_dtype(x):
    return x.astype("float64")                       # JX004


@jax.jit
def f64_cast_call(x):
    return jnp.float64(1.5) * x                      # JX004
