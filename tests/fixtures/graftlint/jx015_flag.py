"""JX015 should-flag fixtures: inconsistent shard_map partition specs."""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _ident(x):
    return x


def _local_sum(x):
    return jax.lax.psum(x, "data")


def _local_stats(xb):
    # the collectives.py hierarchical-reduction idiom: local partial,
    # psum over ICI then DCN
    part = jnp.sum(xb, axis=0)
    return psum_over_mesh(part, ("data", "replica"))


def unknown_axis(mesh, xs):
    spec = P("batch")                                           # JX015
    return shard_map_compat(_ident, mesh, (spec,), P())(xs)


def duplicate_axis(mesh, xs):
    spec = P("data", "data")                                    # JX015
    return shard_map_compat(_ident, mesh, (spec,), P())(xs)


def rank_overflow(mesh):
    rows = jnp.zeros((8,))
    return shard_map_compat(_ident, mesh, (P("data", None),), P())(rows)  # JX015


def psummed_out_spec(mesh, xs):
    return shard_map_compat(_local_sum, mesh, (P("data"),), P("data"))(xs)  # JX015


def hierarchical_wrong_out(mesh, xb):
    # the mesh-rebuild-era hazard: the body reduced over BOTH axes, the
    # out_spec still claims the row sharding
    row_spec = P(("replica", "data"))
    return shard_map_compat(_local_stats, mesh, (row_spec,), row_spec)(xb)  # JX015


def _local_flat(xb):
    # the depth=1 flat reduction: ONE psum over the joint axis tuple
    return jax.lax.psum(jnp.sum(xb, axis=0), ("data", "replica"))


def flat_depth1_wrong_out(mesh, xb):
    # the multihost depth=1 spelling of the same hazard: the flat tuple
    # psum reduced over both mesh axes at once, the out_spec still
    # claims the hierarchical row sharding
    row_spec = P(("replica", "data"))
    return shard_map_compat(_local_flat, mesh, (row_spec,), row_spec)(xb)  # JX015
