"""JX012 should-flag fixtures: lock-order cycles and self-deadlocks."""
import threading

_a = threading.Lock()
_b = threading.Lock()


def grab_ab():
    with _a:
        with _b:                     # JX012 (edge a->b of the a/b cycle)
            pass


def grab_ba():
    with _b:
        with _a:                     # JX012 (edge b->a closes the cycle)
            pass


def reacquire_same():
    with _a:
        with _a:                     # JX012 (non-reentrant self-deadlock)
            pass


# -- interprocedural: the inner acquisition is two calls away ----------------

_x = threading.Lock()
_y = threading.Lock()


def _takes_y():
    with _y:
        pass


def _indirect_y():
    _takes_y()


def outer_xy():
    with _x:
        _indirect_y()                # JX012 (x->y via summary, 2 hops)


def outer_yx():
    with _y:
        with _x:                     # JX012 (y->x closes the x/y cycle)
            pass


def reacquire_via_bare_acquire():
    # `.acquire()` is an acquisition too: a with-only model would let
    # this guaranteed self-deadlock through
    with _a:
        _a.acquire()                 # JX012 (acquire of a held Lock)
