"""JX003 should-flag fixtures: PRNG key reuse."""
import jax
import jax.numpy as jnp


def sequential_reuse(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (8,))
    b = jax.random.uniform(key, (8,))       # JX003: same key, second draw
    return a + b


def loop_reuse(seed, steps):
    key = jax.random.PRNGKey(seed)
    total = jnp.zeros((4,))
    for _ in range(steps):
        total += jax.random.normal(key, (4,))   # JX003: identical each iter
    return total


def one_line_reuse(key):
    return jax.random.normal(key, (2,)), jax.random.uniform(key, (2,))  # JX003


def param_key_loop_reuse(key, steps):
    total = jnp.zeros((4,))
    for _ in range(steps):
        total += jax.random.normal(key, (4,))   # JX003: param key, no split
    return total
