"""JX022 should-pass fixtures: disciplined lifecycle use."""
import threading


class Lane:
    def __init__(self):
        self._cv = threading.Condition()
        self._stop = False

    def submit(self, item):
        with self._cv:
            if self._stop:
                raise RuntimeError("stopped")
        return item

    def stop(self):
        with self._cv:
            self._stop = True


class Channel:
    def __init__(self):
        self._lock = threading.Lock()
        self._closed = False

    def close(self):
        # the latch is atomic: check AND transition under one lock
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._teardown()

    def _teardown(self):
        return None


def disciplined(items):
    lane = Lane()
    try:
        for it in items:
            lane.submit(it)
    finally:
        lane.stop()


def builder():
    # escape to the caller: the obligation travels with the instance
    lane = Lane()
    return lane


def registered(server):
    # aliasing store: someone else owns the teardown now
    lane = Lane()
    server.lanes["x"] = lane
    return "ok"


def handed_off(pool):
    # opaque consumer: assume it takes ownership (silence over noise)
    lane = Lane()
    pool.adopt(lane)
    return "ok"


def restarted(items):
    # stop-then-reconstruct: the new instance is live again
    lane = Lane()
    lane.stop()
    lane = Lane()
    for it in items:
        lane.submit(it)
    lane.stop()


class ScaleSupervisor:
    def __init__(self):
        self._cv = threading.Condition()
        self._stop = False

    def announce(self, decision):
        with self._cv:
            if self._stop:
                raise RuntimeError("supervisor stopped")
        return decision

    def stop(self):
        with self._cv:
            self._stop = True


class LatchedAutoscaler:
    """The ISSUE-17 idiom: the decision re-checks the shutdown latch
    and announces under the SAME lock hold, so stop() can never
    interleave between the check and the dispatch."""

    def __init__(self, supervisor):
        self._lock = threading.Lock()
        self._stopped = False
        self._supervisor = supervisor

    def apply(self, decision):
        with self._lock:
            if self._stopped:
                return "held"
            self._supervisor.announce(decision)
        return "announced"

    def stop(self):
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self._supervisor.stop()


def disciplined_decide(events):
    sup = ScaleSupervisor()
    auto = LatchedAutoscaler(sup)
    try:
        for ev in events:
            auto.apply(ev)
    finally:
        auto.stop()
