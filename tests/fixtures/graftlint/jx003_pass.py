"""JX003 should-pass fixtures: correctly threaded keys."""
import random

import jax
import jax.numpy as jnp
import numpy as np


def split_between_draws(seed):
    key = jax.random.PRNGKey(seed)
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (8,))
    key, sub = jax.random.split(key)
    b = jax.random.uniform(sub, (8,))
    return a + b


def split_in_loop(seed, steps):
    key = jax.random.PRNGKey(seed)
    total = jnp.zeros((4,))
    for _ in range(steps):
        key, sub = jax.random.split(key)
        total += jax.random.normal(sub, (4,))
    return total


def fold_in_per_step(seed, steps):
    base = jax.random.PRNGKey(seed)
    total = jnp.zeros((4,))
    for t in range(steps):
        step_key = jax.random.fold_in(base, t)   # derived, not reused
        total += jax.random.normal(step_key, (4,))
    return total


def fresh_key_per_iteration(seed, steps):
    total = jnp.zeros((4,))
    for t in range(steps):
        key = jax.random.PRNGKey(seed * 65537 + t)  # reassigned in body
        total += jax.random.normal(key, (4,))
    return total


def split_fanout_loop(key, n):
    # `for key in split(key, n)` rebinds the key per iteration
    acc = 0.0
    for key in jax.random.split(key, n):
        acc += jax.random.normal(key)
    return acc


def nested_def_has_own_key(key, n):
    # the draw consumes the nested function's parameter, not the
    # enclosing loop's key
    for t in range(n):
        def sample(k):
            return jax.random.normal(k)
        sample(jax.random.fold_in(key, t))


def one_draw_per_branch(key, symmetric):
    # mutually exclusive branches: at most one draw executes per call
    if symmetric:
        return jax.random.normal(key, (2,))
    else:
        return jax.random.uniform(key, (2,))


def stateful_rngs_are_not_keys(xs):
    # np.random / stdlib random are STATEFUL — repeated calls draw fresh
    # samples; they must never be mistaken for jax key consumption
    a = np.random.choice(xs)
    b = np.random.choice(xs)
    c = random.choice(xs)
    d = random.choice(xs)
    rng = np.random.RandomState(0)
    return a + b + c + d + rng.choice(xs) + rng.choice(xs)
