"""JX017 should-flag fixtures: programs dispatched across a mesh rebuild."""
import jax
import jax.numpy as jnp


def _sum_kernel(xb, coef):
    return jnp.sum(xb, axis=0)


def _build_step(runtime, xb):
    # helper returns the compiled aggregation program
    return tree_aggregate(_sum_kernel, runtime, xb)


def _recover(supervisor):
    # helper that (transitively) rebuilds the mesh
    supervisor.rebuild_mesh()


def stale_after_helper_recover(runtime, supervisor, xb, coef):
    # the MeshSupervisor-rebuild hazard, interprocedural on BOTH sides:
    # the program comes from one helper, the rebuild hides in another,
    # and this untouched caller holds the stale reference
    step = _build_step(runtime, xb)
    _recover(supervisor)
    return step(xb, coef)                                       # JX017


def stale_after_reset(runtime, xb, coef):
    step = tree_aggregate(_sum_kernel, runtime, xb)
    mesh.reset()
    return step(xb, coef)                                       # JX017


def loop_rebuild_second_iteration(runtime, supervisor, xb, coef):
    # textually the dispatch precedes the recovery — but the SECOND
    # iteration dispatches the pre-rebuild program
    step = tree_aggregate(_sum_kernel, runtime, xb)
    out = None
    for _ in range(3):
        out = step(xb, coef)                                    # JX017
        _recover(supervisor)
    return out


def rebuild_in_branch_then_dispatch(runtime, supervisor, xb, coef, dead):
    # the rebuild arm FALLS THROUGH: the dispatch below runs after a
    # rebuild on the dead path
    step = tree_aggregate(_sum_kernel, runtime, xb)
    if dead:
        _recover(supervisor)
    return step(xb, coef)                                       # JX017


class Trainer:
    def fit(self, runtime, supervisor, xb, coef):
        self._step = tree_aggregate(_sum_kernel, runtime, xb)
        _recover(supervisor)
        return self._step(xb, coef)                             # JX017


def _recover_host_loss(bootstrap, supervisor):
    # the host-loss recovery helper: abandon the dead rendezvous, then
    # rebuild over the survivors — transitively a mesh rebuild
    bootstrap.abandon()
    supervisor.rebuild_mesh()


def stale_after_host_loss(runtime, bootstrap, supervisor, xb, coef):
    # the multihost hazard: a whole HOST died, recovery rebuilt the mesh
    # over the survivors, and the pre-loss program is dispatched anyway
    step = tree_aggregate(_sum_kernel, runtime, xb)
    _recover_host_loss(bootstrap, supervisor)
    return step(xb, coef)                                       # JX017


def _apply_capacity_event(ctx, event):
    # the elastic re-shard helper: a planned capacity event rebuilds the
    # mesh at the event's target shape — transitively a mesh rebuild
    clear_program_cache()
    ctx.rebuild_mesh(event.master)


def stale_after_capacity_reshape(runtime, ctx, event, xb, coef):
    # the ELASTIC hazard (resume-on-new-mesh): a scale event reshaped the
    # mesh mid-fit and the loop resumes with the pre-reshape program —
    # the re-shard helper rebuilt the MESH but this caller never rebuilt
    # the PROGRAM
    step = tree_aggregate(_sum_kernel, runtime, xb)
    _apply_capacity_event(ctx, event)
    return step(xb, coef)                                       # JX017
