"""JX014 should-flag fixtures: blocking calls inside held-lock regions."""
import threading
import time

import jax

_lock = threading.Lock()


def sleeps_under_lock():
    with _lock:
        time.sleep(0.05)                    # JX014


def waits_on_future_under_lock(fut):
    with _lock:
        return fut.result(timeout=5)        # JX014


def joins_thread_under_lock(worker_thread):
    with _lock:
        worker_thread.join()                # JX014


def syncs_device_under_lock(out):
    with _lock:
        jax.block_until_ready(out)          # JX014


def collective_under_lock(ds, coef):
    with _lock:
        return ds.tree_aggregate(coef)      # JX014 (mesh rendezvous)


def _backoff():
    time.sleep(0.01)


def _retry_with_backoff():
    _backoff()


class Lane:
    def __init__(self):
        self._cv = threading.Condition()
        self._evt = threading.Event()

    def helper_blocks_transitively(self):
        with self._cv:
            _retry_with_backoff()           # JX014 (2 hops to the sleep)

    def waits_on_other_primitive(self):
        with self._cv:
            self._evt.wait(1.0)             # JX014 (not the held lock)
