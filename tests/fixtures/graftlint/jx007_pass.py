"""JX007 pass fixture: host-tier thread pools, serial SPMD loops, and
service threads are all fine — only thread-dispatched SPMD entry points
are the deadlock hazard."""

import concurrent.futures as cf
import threading


def count_rows(part):
    return len(part)


def pool_host_work(parts):
    # host-tier partition work: the callable never touches SPMD dispatch
    with cf.ThreadPoolExecutor(max_workers=8) as pool:
        return list(pool.map(count_rows, parts))


def pool_unresolved(f, parts):
    # function-valued parameter: unresolvable, never flagged
    with cf.ThreadPoolExecutor() as pool:
        return list(pool.map(f, parts))


def serial_fits(est, frames):
    # the sanctioned serial fallback: SPMD fits stay on the caller thread
    return [est.fit(f) for f in frames]


class HeartbeatSender:
    def __init__(self, address):
        self.address = address
        self.running = False

    def _send(self):
        return self.address

    def _loop(self):
        while self.running:
            self._send()

    def start(self):
        self.running = True
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()
        return t
