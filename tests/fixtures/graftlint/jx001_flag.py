"""JX001 should-flag fixtures: implicit host syncs. Never imported."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def traced_float_coercion(x):
    # float() on a traced value inside a jitted function
    scale = float(jnp.max(x))              # JX001
    return x * scale


@jax.jit
def traced_item_pull(x):
    total = jnp.sum(x)
    return x / total.item()                # JX001


@jax.jit
def traced_host_materialize(x):
    host = np.asarray(x * 2.0)             # JX001
    return jnp.asarray(host)


def piecemeal_driver(ds, coef):
    run = ds.tree_aggregate_fn(lambda x, y, w, c: {"loss": 0.0})
    for _ in range(10):
        out = run(coef)
        loss = float(out["loss"])          # pull 1
        count = float(out["count"])        # pull 2 -> JX001 (batch them)
        coef = coef - loss / count
    return coef
