"""JX020 should-flag fixtures: fault-table drift in every direction.

==========================  =============================================
point                       fired from
==========================  =============================================
``demo.used``               the staged dispatch below
``demo.ghost``              registered here, fired nowhere        # JX020
==========================  =============================================
"""


def inject(point, **info):
    """Fixture stand-in for parallel.faults.inject (hosts the table)."""


def classify_failure(exc):
    return "transient"


def staged_dispatch(shard):
    inject("demo.used", shard=shard)
    return shard


def typod_site(shard):
    # one dropped letter: the schedule matches exact strings, never fires
    inject("demo.usedd", shard=shard)                           # JX020
    return shard


def untestable_retry(e):
    # retried boundary with no reachable fault point: chaos can't test it
    kind = classify_failure(e)                                  # JX020
    if kind == "transient":
        return True
    return False
