"""JX010 should-flag fixtures: collectives under host-divergent branches."""
import time

import jax
import jax.numpy as jnp


def primary_only_aggregate(dataset, coef):
    if jax.process_index() == 0:                                # JX010
        return dataset.tree_aggregate(coef)
    return None


def timeout_guarded_psum(x, t0, budget):
    if time.monotonic() - t0 > budget:                          # JX010
        return jax.lax.psum(x, "data")
    return x


def divergent_name_guard(x):
    deadline = time.time() + 5.0
    while time.time() < deadline:                               # JX010
        x = jax.lax.psum(x, "data")
    return x


def primary_only_ternary(dataset, coef):
    # the one-line spelling deadlocks exactly like the block form
    return (dataset.tree_aggregate(coef)                        # JX010
            if jax.process_index() == 0 else None)


def env_gated_collective(dataset, coef):
    import os
    if os.environ.get("CYCLONE_FAST_PATH"):                     # JX010
        return dataset.tree_aggregate(coef)
    return dataset.slow_aggregate(coef)


# -- interprocedural: divergent source and collective both one call away ------

def _is_primary():
    return jax.process_index() == 0


def _reduce_all(x):
    return jax.lax.psum(x, "data")


def wrapped_divergence(x):
    if _is_primary():                                           # JX010
        return _reduce_all(x)
    return x
