"""JX010 should-pass fixtures: mesh-uniform branching around collectives."""
import time

import jax
import jax.numpy as jnp


def config_uniform_branch(dataset, coef, use_fast_path):
    # config flags are identical on every process: the branch is
    # mesh-uniform, every participant dispatches the same program
    if use_fast_path:
        return dataset.tree_aggregate(coef)
    return dataset.slow_aggregate(coef)


def primary_only_host_work(result, path):
    # host-local work under a divergent branch is the LEGAL pattern —
    # no rendezvous is reachable, only process 0 writes the artifact
    if jax.process_index() == 0:
        with open(path, "w") as fh:
            fh.write(str(result))
    return result


def timing_around_uniform_dispatch(dataset, coef):
    # wall-clock read for TELEMETRY, not control flow: the collective
    # dispatch itself is unconditional
    t0 = time.monotonic()
    out = dataset.tree_aggregate(coef)
    elapsed = time.monotonic() - t0
    return out, elapsed


def collective_launders_host_value(dataset, t0, coef):
    # a value reduced THROUGH a collective is mesh-uniform by
    # construction: every participant branches on the same pmax result —
    # the canonical budget-based early-stop idiom
    elapsed = time.monotonic() - t0
    slowest = dataset.tree_aggregate(elapsed)
    if slowest > 1.0:
        return dataset.tree_aggregate(coef)
    return None


def _log_progress(step):
    print("step", step)


def divergent_branch_host_only_helper(step):
    # the helper under the divergent branch never reaches a collective
    if time.monotonic() % 2 < 1:
        _log_progress(step)
    return step
