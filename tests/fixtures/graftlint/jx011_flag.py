"""JX011 should-flag fixtures: accesses outside the inferred guard."""
import threading


class Tally:
    """Majority of `_count` accesses hold `_lock`; the deviants race."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0

    def add(self, v):
        with self._lock:
            self._count += 1
            self._total += v

    def add_many(self, vs):
        with self._lock:
            self._count += len(vs)
            self._total += sum(vs)

    def racy_reset(self):
        self._count = 0                      # JX011 (unguarded write)

    def racy_mean(self):
        return self._total / self._count     # JX011 JX011 (torn pair read)


class Pipeline:
    """Interprocedural: `_append` is only ever called with the lock held,
    so its access is guarded via locks-held-at-entry — but `peek_racy`
    reads the list with no lock at all."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []

    def push(self, v):
        with self._lock:
            self._append(v)

    def _append(self, v):
        self._pending.append(v)

    def drain(self):
        with self._lock:
            out = list(self._pending)
            self._pending = []
        return out

    def size_racy(self):
        return len(self._pending)            # JX011 (unguarded read)


class RacyRollup:
    """A usage-ledger shape that bills only one side of the invariant
    under the lock: charges move rows and totals together guarded, but
    the eviction fold and the totals peek touch the maps bare — exactly
    the races the real UsageLedger's single-lock discipline forbids."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}
        self._totals = {}

    def charge(self, scope, field, v):
        with self._lock:
            row = self._rows.setdefault(scope, {})
            row[field] = row.get(field, 0) + v
            self._totals[field] = self._totals.get(field, 0) + v

    def snapshot(self):
        with self._lock:
            out = {k: dict(v) for k, v in self._rows.items()}
            out["_totals"] = dict(self._totals)
            return out

    def evict_racy(self, scope):
        del self._rows[scope]                # JX011 (unguarded write)

    def peek_racy(self, field):
        return self._totals.get(field, 0)    # JX011 (unguarded read)
