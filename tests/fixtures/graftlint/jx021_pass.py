"""JX021 should-pass fixtures: every emitted event has a handler branch."""


class CycloneEvent:
    def to_json(self):
        return {"Event": type(self).__name__}


class JobStart(CycloneEvent):
    def __init__(self, job_id=0):
        self.job_id = job_id


class StepDone(CycloneEvent):
    def __init__(self, step=0):
        self.step = step


class UsageReport(CycloneEvent):
    """Periodic ledger rollup (observe/attribution.UsageReporter): the
    journal-side consumer REPLACE-folds it per host, so the literal must
    reach a handler like any other event."""

    def __init__(self, host="", rollup=None):
        self.host = host
        self.rollup = rollup or {}


def on_event(e):
    kind = e.get("Event")
    if kind == "JobStart":
        return "job"
    if kind == "StepDone":
        return "step"
    if kind == "UsageReport":
        return "usage"
    return None


def replay_filter(events):
    # journal filters dispatching on the same literals also count as
    # handlers — the name reaches a consumer either way
    return [e for e in events
            if e.get("Event") in ("JobStart", "StepDone", "UsageReport")]


def post_all(bus):
    bus.post(JobStart(job_id=1))
    bus.post(StepDone(step=2))
    bus.post(UsageReport(host="h0", rollup={"_totals": {}}))
