"""JX017 should-pass fixtures: the clear-then-rebuild recovery idiom."""
import jax
import jax.numpy as jnp


def _sum_kernel(xb, coef):
    return jnp.sum(xb, axis=0)


def _recover(supervisor):
    supervisor.rebuild_mesh()


def recover_then_rebuild(runtime, supervisor, xb, coef):
    # the MeshSupervisor.recover idiom: drop the caches, rebuild the
    # mesh, then REBUILD the program before dispatching
    clear_program_cache()
    supervisor.rebuild_mesh()
    step = tree_aggregate(_sum_kernel, runtime, xb)
    return step(xb, coef)


def rebind_after_rebuild(runtime, supervisor, xb, coef):
    step = tree_aggregate(_sum_kernel, runtime, xb)
    out = step(xb, coef)
    _recover(supervisor)
    step = tree_aggregate(_sum_kernel, runtime, xb)
    return out + step(xb, coef)


def no_rebuild_in_sight(runtime, xb, coef):
    step = tree_aggregate(_sum_kernel, runtime, xb)
    out = None
    for _ in range(3):
        out = step(xb, coef)
    return out


def exclusive_branch_recover(runtime, supervisor, xb, coef, dead):
    # the branches are exclusive: the rebuild arm RETURNS, so the
    # dispatch arm only runs when no rebuild happened
    step = tree_aggregate(_sum_kernel, runtime, xb)
    if dead:
        _recover(supervisor)
        return None
    return step(xb, coef)


def loop_rebinds_each_iteration(runtime, supervisor, xb, coef):
    # per-iteration rebuild is safe when the program is REBUILT at the
    # top of every iteration (tree_aggregate's cache makes this cheap)
    out = None
    for _ in range(3):
        step = tree_aggregate(_sum_kernel, runtime, xb)
        out = step(xb, coef)
        _recover(supervisor)
    return out


def host_loss_recover_then_rebuild(runtime, bootstrap, supervisor, xb, coef):
    # the MeshSupervisor host-loss idiom: drop the caches, abandon the
    # dead jax.distributed rendezvous, rebuild the mesh over survivors,
    # then REBUILD the program before dispatching
    clear_program_cache()
    bootstrap.abandon()
    supervisor.rebuild_mesh()
    step = tree_aggregate(_sum_kernel, runtime, xb)
    return step(xb, coef)


def _apply_capacity_event(ctx, event):
    # the elastic re-shard helper: clear, rebuild at the target shape
    clear_program_cache()
    ctx.rebuild_mesh(event.master)


def capacity_reshape_then_rebind(runtime, ctx, event, xb, coef):
    # the ELASTIC resume-on-new-mesh idiom (MeshSupervisor.reshape):
    # clear the cache, reshape the mesh, REBUILD the program on the new
    # runtime, then resume dispatching — the reshard helper's contract
    step = tree_aggregate(_sum_kernel, runtime, xb)
    out = step(xb, coef)
    _apply_capacity_event(ctx, event)
    step = tree_aggregate(_sum_kernel, runtime, xb)
    return out + step(xb, coef)
