"""JX002 should-pass fixtures: static branching that tracing allows."""
import jax
import jax.numpy as jnp


def make_agg(d, fit_intercept):
    def agg(x, y, w, coef):
        if fit_intercept:                  # closure config: static per trace
            beta, b0 = coef[:d], coef[d]
        else:
            beta, b0 = coef, 0.0
        margin = jnp.dot(x, beta) + b0
        return {"loss": jnp.sum(w * (margin - y) ** 2)}
    return agg


@jax.jit
def shape_branch(x):
    if x.ndim == 2:                        # static metadata
        return x.sum(axis=1)
    return x


@jax.jit
def optional_arg(x, mask=None):
    if mask is None:                       # a tracer is never None
        return x
    return x * mask


@jax.jit
def staged_branch(x):
    m = jnp.mean(x)
    return jnp.where(m > 0, x - m, x + m)  # the staged equivalent
