"""JX018 should-pass fixtures: O(d) pulls and predict-path handoffs."""
import jax
import jax.numpy as jnp
import numpy as np


def _grad_kernel(xb, yb, coef):
    return jnp.sum(xb, axis=0)


def fit_pulls_stats_only(runtime, xb, yb, coef):
    # the sanctioned shape: aggregate to O(d) stats, pull THOSE
    step = tree_aggregate(_grad_kernel, runtime, xb, yb)
    stats = step(xb, yb, coef)
    n, d = xb.shape
    grad = jnp.zeros((d,))
    return stats, np.asarray(grad)


def predict_returns_rows(model, x):
    # predict returning n-sized results to the caller IS the API
    # contract — no aggregate dispatched, not a fit path
    n, d = x.shape
    preds = jnp.zeros((n,))
    return np.asarray(preds)


def fit_pulls_bounded_preview(runtime, xb, yb, coef):
    # a bounded slice is O(1), not O(n) — provenance ends at the bound
    step = tree_aggregate(_grad_kernel, runtime, xb, yb)
    out = step(xb, yb, coef)
    head = np.asarray(xb[:64])
    return out, head


def fit_stages_bounded_shards(runtime, xb, yb, coef, shard_rows):
    # the streaming engine's idiom (oocore/): per-shard bounded host
    # staging — every staged slice carries an explicit upper bound, so
    # dataset-dim provenance ends at the shard and the epoch's host
    # working set stays O(shard), never O(n)
    step = tree_aggregate(_grad_kernel, runtime, xb, yb)
    total = step(xb, yb, coef)
    for lo in range(0, xb.shape[0], shard_rows):
        staged = np.asarray(xb[lo:lo + shard_rows])
        jax.device_put(staged)
    return total


def fit_attaches_cached_shard_set(runtime, xb, yb, coef, shard_rows):
    # the shard-set cache idiom (oocore/cache): a fit re-attaching to an
    # existing spill still stages per-shard bounded slices — the cache
    # changes WHERE shards come from, not the O(shard) staging contract
    sds = shard_set_cache().attach(xb, shard_rows=shard_rows)
    step = tree_aggregate(_grad_kernel, runtime, xb, yb)
    total = step(xb, yb, coef)
    for lo in range(0, xb.shape[0], shard_rows):
        staged = np.asarray(xb[lo:lo + shard_rows])
        jax.device_put(staged)
    sds.release()
    return total
