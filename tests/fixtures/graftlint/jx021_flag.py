"""JX021 should-flag fixtures: an event emitted but handled nowhere."""


class CycloneEvent:
    def to_json(self):
        return {"Event": type(self).__name__}


class JobStart(CycloneEvent):
    def __init__(self, job_id=0):
        self.job_id = job_id


class BlocksMoved(CycloneEvent):
    def __init__(self, n=0):
        self.n = n


def on_event(e):
    # the status-store fold dispatches on the literal type name; only
    # JobStart has a branch, so BlocksMoved drifts silently
    kind = e.get("Event")
    if kind == "JobStart":
        return "job"
    return None


def post_all(bus):
    bus.post(JobStart(job_id=1))
    bus.post(BlocksMoved(n=3))                                  # JX021
