"""JX013 should-flag fixtures: queue pops stranded on a path to exit."""
import collections


class Lane:
    def __init__(self):
        self._queue = collections.deque()

    def leaks_on_error_path(self, err):
        r = self._queue.popleft()            # JX013 (stranded on the raise)
        if err:
            raise RuntimeError("dispatch failed")
        r.future.set_result(1)

    def leaks_on_fallthrough(self, flag):
        r = self._queue.popleft()            # JX013 (else path never completes)
        if flag:
            r.future.set_result(0)

    def leaks_on_early_return(self, stopped):
        r = self._queue.popleft()            # JX013 (returns without completing)
        if stopped:
            return None
        r.future.set_exception(RuntimeError("stopped"))
        return r.n

    def loop_never_completes(self, rows):
        while self._queue:
            r = self._queue.popleft()        # JX013 (counted, never completed)
            rows += r.n


def _log_only(r):
    print(r)


class Lane2:
    def __init__(self):
        self._queue = collections.deque()

    def helper_never_completes(self):
        r = self._queue.popleft()            # JX013 (helper only logs it)
        _log_only(r)


class Lane3:
    def __init__(self):
        self._queue = collections.deque()

    def leaks_on_return_inside_try(self, stopped):
        # a clean `return` runs NO except handler — the handler
        # completing the future does not cover this path
        r = self._queue.popleft()            # JX013 (return skips handler)
        try:
            if stopped:
                return None
            r.future.set_result(1)
        except ValueError as e:
            r.future.set_exception(e)
        return r.n
