"""JX001 should-pass fixtures: legitimate host/device boundaries."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def stays_on_device(x):
    # jnp math on traced values: no host sync anywhere
    scale = jnp.max(x)
    return x * scale


@jax.jit
def static_metadata_is_free(x):
    # shape/ndim/dtype reads are static under tracing, and float() of a
    # host config value is not a sync
    rows = float(x.shape[0])
    return x / rows


def host_factory(d, fit_intercept):
    # host-side coercions in a BUILDER (not jit-reachable) are fine
    m = int(d) + int(bool(fit_intercept))
    return np.zeros(m)


def batched_driver(ds, coef):
    run = ds.tree_aggregate_fn(lambda x, y, w, c: {"loss": 0.0})
    for _ in range(10):
        # ONE explicit transfer for the whole output pytree
        out = jax.device_get(run(coef))
        loss = float(out["loss"])
        count = float(out["count"])
        coef = coef - loss / count
    return coef


def single_pull_driver(ds, coef):
    run = ds.tree_aggregate_fn(lambda x, y, w, c: {"loss": 0.0})
    out = run(coef)
    return float(out["loss"])  # a single conversion IS the one transfer
