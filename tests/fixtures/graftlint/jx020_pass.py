"""JX020 should-pass fixtures: a fault table and its sites in agreement.

=================  ==============================================
point              fired from
=================  ==============================================
``demo.dispatch``  the retried dispatch below
``demo.stage``     the staging helper
=================  ==============================================
"""


def inject(point, **info):
    """Fixture stand-in for parallel.faults.inject (hosts the table)."""


def classify_failure(exc):
    return "transient"


def retry_step(fn, attempts=3):
    # higher-order wrapper: the injectable site lives in the callable it
    # is handed, so the retry-boundary belief does not apply to it
    last = None
    for _ in range(attempts):
        try:
            return fn()
        except Exception as e:
            if classify_failure(e) != "transient":
                raise
            last = e
    raise last


def stage(shard):
    inject("demo.stage", shard=shard)
    return shard


def dispatch(batch):
    # the boundary carries its own fault point: retried AND injectable
    inject("demo.dispatch", n=len(batch))
    return retry_step(lambda: batch)


class FaultInjector:
    def fire(self, point, **info):
        return (point, info)


def refire(inj, point):
    # dynamic point names are a schedule replay, not an injection site
    return inj.fire(point)
