"""JX008 should-flag fixtures: compile-cache explosion at jit entries."""
import functools

import jax
import jax.numpy as jnp


def _kernel(x, k):
    return x * k


_prog = jax.jit(_kernel, static_argnums=(1,))


def varying_static_in_loop(x, n):
    out = []
    for i in range(n):
        out.append(_prog(x, i))                    # JX008
    return out


def varying_shape_in_loop(x, n):
    total = 0.0
    for i in range(n):
        total += float(_prog(x[:i], 0))            # JX008
    return total


def derived_varying_static(x, steps):
    for t in range(steps):
        scale = t * 2
        x = _prog(x, scale)                        # JX008
    return x


def unhashable_static(x):
    return _prog(x, [1, 2, 3])                     # JX008


def program_built_in_loop(xs):
    outs = []
    for x in xs:
        prog = jax.jit(_kernel)                    # JX008
        outs.append(prog(x, 2))
    return outs


def varying_static_in_comprehension(x, n):
    # a comprehension iterates exactly like the spelled-out loop
    return [_prog(x, i) for i in range(n)]         # JX008


def varying_static_by_keyword(x, n):
    # JAX keys a keyword call onto the static position just like the
    # positional form
    out = []
    for i in range(n):
        out.append(_prog(x, k=i))                  # JX008
    return out


@functools.partial(jax.jit, static_argnums=(1,))
def _decorated(x, width):
    return jnp.reshape(x, (width, -1))


def decorated_varying_static(x, n):
    acc = []
    for w in range(1, n):
        acc.append(_decorated(x, w))               # JX008
    return acc


# -- interprocedural: the jit entry is one call away --------------------------

def _run_one(x, k):
    # k lands in _prog's static position: calling _run_one with a
    # loop-varying k is a per-iteration recompile, two frames away
    return _prog(x, k)


def sweep_through_wrapper(x, n):
    out = []
    for i in range(n):
        out.append(_run_one(x, i))                 # JX008
    return out
