"""JX009 should-flag fixtures: reads of donated (deleted) buffers."""
import jax
import jax.numpy as jnp


def _update(state, x):
    return state * 0.9 + x


_step = jax.jit(_update, donate_argnums=(0,))


def read_after_donate(state, x):
    new_state = _step(state, x)
    drift = state - new_state                      # JX009
    return new_state, drift


def donated_in_loop_without_rebind(state, xs):
    outs = []
    for x in xs:
        outs.append(_step(state, x))               # JX009
    return outs


def donated_in_loop_with_continue(state, xs, outs):
    # `continue` is NOT a loop exit: the next iteration still dispatches
    # the deleted buffer
    for x in xs:
        outs.append(_step(state, x))               # JX009
        continue
    return outs


def donated_in_comprehension(state, xs):
    # a comprehension cannot rebind `state` per iteration — iteration
    # two dispatches the deleted buffer
    return [_step(state, x) for x in xs]           # JX009


def donated_then_break_then_read(state, xs):
    # `break` (unlike `return`) falls INTO the post-loop code, carrying
    # the deleted buffer with it
    for x in xs:
        out = _step(state, x)
        break
    return state                                   # JX009


def read_in_later_branch(state, x, debug):
    new_state = _step(state, x)
    if debug:
        print(state.sum())                         # JX009
    return new_state


# -- interprocedural: the donation happens one call away ----------------------

def _advance(state, x):
    # donates ITS caller's buffer: state flows into _step's donated slot
    return _step(state, x)


def read_after_wrapped_donate(state, x):
    new_state = _advance(state, x)
    stale = jnp.linalg.norm(state)                 # JX009
    return new_state, stale
