"""JX023 should-flag fixtures: nondeterminism on chaos paths.

===============  ==========================================
point            fired from
===============  ==========================================
``demo.step``    every function below
===============  ==========================================
"""
import random
import time


def inject(point, **info):
    """Fixture stand-in for parallel.faults.inject (hosts the table)."""


def backoff_delay(attempt, base_s=0.05, max_s=5.0, rng=None):
    r = rng if rng is not None else random
    return min(max_s, base_s * (2 ** attempt)) * r.random()


def jittered_step(shard):
    inject("demo.step", shard=shard)
    return random.uniform(0.0, 1.0)                             # JX023


def retry_with_default_rng(shard, attempt):
    inject("demo.step", shard=shard)
    # the helper OFFERS rng plumbing; declining it falls back to the
    # process-global generator inside
    return backoff_delay(attempt)                               # JX023


def clock_branched(shard, t0):
    inject("demo.step", shard=shard)
    if time.monotonic() - t0 > 0.5:                             # JX023
        return "slow"
    return "fast"


def hash_ordered_dispatch(shards):
    inject("demo.step", n=len(shards))
    out = []
    for s in {1, 2, 3} | set(shards):                           # JX023
        out.append(s)
    return out
