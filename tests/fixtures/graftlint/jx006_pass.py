"""JX006 should-pass fixtures: state through the carry; host-side stats."""
import jax
import jax.numpy as jnp


class Model:
    def __init__(self):
        self.n_steps = 0

    def fit_step(self, x):
        # host driver (NOT jitted) may mutate freely
        self.n_steps += 1
        return self._step(x)

    @staticmethod
    @jax.jit
    def _step(x):
        return x * 2.0


@jax.jit
def carry_state(carry, x):
    # state flows through arguments and returns — the staged idiom
    count, total = carry
    return (count + 1, total + jnp.sum(x)), x * 2.0
