"""JX018 should-flag fixtures: O(n) host materialization on fit paths."""
import jax
import jax.numpy as jnp
import numpy as np


def _sum_kernel(xb, yb, coef):
    return jnp.sum(xb, axis=0)


def fit_pulls_residuals(runtime, xb, yb, coef):
    step = tree_aggregate(_sum_kernel, runtime, xb, yb)
    stats = step(xb, yb, coef)
    n, d = xb.shape
    resid = jnp.zeros((n,))
    host = np.asarray(resid)                                    # JX018
    return stats, host


def fit_spills_design_matrix(runtime, xb, yb, coef):
    # the out-of-core spill-path hazard: the WHOLE sharded design matrix
    # pulled to host inside the fit loop
    step = tree_aggregate(_sum_kernel, runtime, xb, yb)
    stats = step(xb, yb, coef)
    spill = xb.tolist()                                         # JX018
    return stats, spill


def _pull(v):
    return np.asarray(v)


def train_epoch(runtime, xb, coef):
    # interprocedural: the materializer hides in a helper
    step = tree_aggregate(_sum_kernel, runtime, xb)
    n = xb.shape[0]
    preds = jnp.zeros((n,))
    return step(xb, coef), _pull(preds)                         # JX018


def fit_accumulates_all_shards(runtime, xb, yb, coef):
    # the naive out-of-core anti-pattern the streaming engine exists to
    # avoid: per-shard partials are bounded, but the host-side epoch
    # buffer re-materializes EVERY shard as one O(n) matrix — the working
    # set the spill was supposed to remove comes straight back
    step = tree_aggregate(_sum_kernel, runtime, xb, yb)
    out = step(xb, yb, coef)
    n, d = xb.shape
    epoch_buf = jnp.zeros((n, d))
    collected = np.asarray(epoch_buf)                           # JX018
    return out, collected
