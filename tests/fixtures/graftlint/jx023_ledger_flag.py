"""JX023 should-flag fixture: a bench-history ledger append whose row
order (and flush jitter) is not canonical — replaying the same runs
produces a different file.

===============  ==========================================
point            fired from
===============  ==========================================
``demo.append``  every function below
===============  ==========================================
"""
import json
import random
import time


def inject(point, **info):
    """Fixture stand-in for parallel.faults.inject (hosts the table)."""


def append_rows_hash_ordered(ledger, rows):
    # rows arrive as a dedup SET; iterating it writes the ledger in
    # hash order — the append-only file is no longer byte-stable
    inject("demo.append", n=len(rows))
    out = []
    for row in set(rows):                                       # JX023
        out.append(json.dumps(row))
    ledger.extend(out)
    return out


def append_with_flush_jitter(ledger, row):
    inject("demo.append", metric=row)
    ledger.append(json.dumps(row))
    return random.uniform(0.0, 0.01)                            # JX023


def append_unless_slow(ledger, row, t0):
    inject("demo.append", metric=row)
    # dropping rows based on a wall-clock read makes ledger CONTENT
    # depend on host speed, not on the measured runs
    if time.monotonic() - t0 > 0.5:                             # JX023
        return 0
    ledger.append(json.dumps(row))
    return 1
