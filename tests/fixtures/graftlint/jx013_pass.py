"""JX013 should-pass fixtures: every pop discharged on every path."""
import collections


class GoodLane:
    def __init__(self):
        self._queue = collections.deque()
        self._results = {}

    def complete_both_paths(self, ok):
        r = self._queue.popleft()
        if ok:
            r.future.set_result(1)
        else:
            r.future.set_exception(RuntimeError("no"))

    def error_path_completes_then_raises(self, err):
        r = self._queue.popleft()
        if err:
            r.future.set_exception(err)
            raise RuntimeError("failed, but the future is complete")
        r.future.set_result(0)

    def requeue_under_backpressure(self, overloaded):
        r = self._queue.popleft()
        if overloaded:
            self._queue.appendleft(r)       # requeue IS the discharge
            return False
        r.future.set_result(1)
        return True

    def handler_completes(self, prog):
        r = self._queue.popleft()
        try:
            r.future.set_result(prog(r.n))
        except Exception as e:
            r.future.set_exception(e)

    def transfer_to_caller(self):
        # returning the request transfers the obligation with it
        return self._queue.popleft()

    def store_for_later(self, key):
        r = self._queue.popleft()
        self._results[key] = r              # escaped: someone holds it

    def drain_loop(self):
        while self._queue:
            r = self._queue.popleft()
            r.future.set_result(None)


def _settle(req, err):
    req.future.set_exception(err)


def _batchwise(batch, err):
    for r in batch:
        r.future.set_exception(err)


class DelegatingLane:
    def __init__(self):
        self._queue = collections.deque()

    def helper_completes(self, err):
        # resolved callee whose summary discharges parameter 0
        r = self._queue.popleft()
        _settle(r, err)

    def helper_completes_batch(self, err):
        # container hand-off to a resolved batch helper
        r = self._queue.popleft()
        _batchwise([r], err)


class FinallyLane:
    def __init__(self):
        self._queue = collections.deque()

    def finally_completes_on_every_return(self, stopped):
        # `finally` runs on BOTH returns — the obligation is discharged
        # whichever way the body exits
        r = self._queue.popleft()
        try:
            if stopped:
                return False
            return True
        finally:
            r.future.set_result(stopped)
