"""JX005 should-flag fixtures: collective over an undeclared mesh axis."""
import jax
import jax.numpy as jnp


def bad_axis_literal(x):
    return jax.lax.psum(x, "dta")                       # JX005: typo


def bad_axis_in_tuple(x):
    return jax.lax.pmean(x, ("data", "replicas"))       # JX005: "replicas"


def bad_axis_kwarg(x):
    return jax.lax.all_gather(x, axis_name="batch")     # JX005


def bad_axis_index():
    return jax.lax.axis_index("modle")                  # JX005: typo


def int_axis_kwarg_does_not_shadow(x):
    # axis=0 is the integer ARRAY axis; the NAME is still positional
    return jax.lax.all_gather(x, "dta", axis=0)         # JX005
