"""JX011 should-pass fixtures: the locking idioms that must stay silent."""
import threading


class Disciplined:
    """Every access of every mutable field holds the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._n = 0

    def add(self, v):
        with self._lock:
            self._items.append(v)
            self._n += 1

    def snapshot(self):
        with self._lock:
            return list(self._items), self._n


class DoubleChecked:
    """The sanctioned racy fast path: peek without the lock, RE-CHECK
    under it before acting — the unguarded read is exempt because the
    same function also reads the field under the inferred guard."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stopped = False

    def stop(self):
        with self._lock:
            self._stopped = True

    def maybe_run(self, work):
        if self._stopped:          # benign peek: re-checked below
            return None
        with self._lock:
            if self._stopped:
                return None
            return work()


class PublishThenRead:
    """Fields written only during construction need no guard — reads
    race with nothing."""

    def __init__(self, conf):
        self._lock = threading.Lock()
        self.window = conf["window"]
        self._things = {}

    def get_window(self):
        return self.window

    def put(self, k, v):
        with self._lock:
            self._things[k] = v


class NoLocksAtAll:
    """Single-threaded by convention: no lock evidence, no inference."""

    def __init__(self):
        self.count = 0

    def bump(self):
        self.count += 1

    def read(self):
        return self.count


class LedgerRollup:
    """The attribution-ledger idiom (observe/attribution.UsageLedger):
    one lock covers BOTH sides of the sum invariant — the scope row and
    the global totals row move together under it, so a reader can never
    observe one side without the other."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}
        self._totals = {}

    def charge(self, scope, **fields):
        with self._lock:
            row = self._rows.setdefault(scope, {})
            for k, v in fields.items():
                row[k] = row.get(k, 0) + v
                self._totals[k] = self._totals.get(k, 0) + v

    def snapshot(self):
        with self._lock:
            out = {k: dict(v) for k, v in self._rows.items()}
            out["_totals"] = dict(self._totals)
            return out


class GuardedHelper:
    """The helper's accesses are guarded interprocedurally — every call
    path holds the lock, so nothing here is a deviant."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}

    def update(self, k, v):
        with self._lock:
            self._apply(k, v)

    def replace(self, items):
        with self._lock:
            self._state.clear()
            for k, v in items:
                self._apply(k, v)

    def _apply(self, k, v):
        self._state[k] = v
