"""JX016 should-flag fixtures: provable dim conflicts, unmasked means
over padded buffers."""
import jax
import jax.numpy as jnp
import numpy as np


def broadcast_conflict():
    a = jnp.zeros((4, 16))
    b = jnp.zeros((8, 16))
    return a + b                                                # JX016


def matmul_inner_conflict():
    x = jnp.zeros((4, 8))
    w = jnp.zeros((16,))
    return x @ w                                                # JX016


def bucket_mean(rows):
    # the serving-bucket idiom gone wrong: rows padded up to the bucket,
    # then a raw mean divides by the bucket size
    k, d = rows.shape
    buf = np.zeros((64, 4))
    buf[:k] = rows
    return jnp.mean(buf, axis=0)                                # JX016


def at_set_mean(rows):
    k, d = rows.shape
    buf = jnp.zeros((64, 4)).at[:k].set(rows)
    return buf.mean(0)                                          # JX016


def _kernel_mean(x):
    return jnp.mean(x, axis=0)


def padded_call_mean(rows):
    # interprocedural: the kernel means over dim 0, the CALLER pads it
    padded = jnp.pad(rows, ((0, 8), (0, 0)))
    return _kernel_mean(padded)                                 # JX016
