"""JX023 should-pass fixtures: chaos paths that replay deterministically.

===============  ==========================================
point            fired from
===============  ==========================================
``demo.step``    every seeded function below
===============  ==========================================
"""
import random
import time

_RNG = random.Random(7)


def inject(point, **info):
    """Fixture stand-in for parallel.faults.inject (hosts the table)."""


def backoff_delay(attempt, base_s=0.05, max_s=5.0, rng=None):
    r = rng if rng is not None else random
    return min(max_s, base_s * (2 ** attempt)) * r.random()


def seeded_jitter(shard):
    inject("demo.step", shard=shard)
    return _RNG.uniform(0.0, 1.0)


def retry_with_seeded_rng(shard, attempt):
    inject("demo.step", shard=shard)
    return backoff_delay(attempt, rng=_RNG)


def deadline_check(shard, deadline_s):
    # timeout bookkeeping is the POINT of the clock read — exempt
    inject("demo.step", shard=shard)
    if time.monotonic() > deadline_s:
        return "expired"
    return "live"


def sorted_dispatch(shards):
    inject("demo.step", n=len(shards))
    return [s for s in sorted(set(shards))]


def unseeded_off_chaos_path(n):
    # reaches no fault point: ordinary code may use the global generator
    return [random.random() for _ in range(n)]
