"""JX007 flag fixture: thread-pool / thread dispatch of SPMD entry points
(the OneVsRest(parallelism=4) collective-rendezvous deadlock pattern)."""

import concurrent.futures as cf
import threading

import jax


def fit_one(est, frame):
    return est.fit(frame)


def pool_map_fit(est, frames):
    with cf.ThreadPoolExecutor(max_workers=4) as pool:
        return list(pool.map(fit_one, frames))  # JX007


def pool_submit_fit(est, frame):
    pool = cf.ThreadPoolExecutor(2)
    fut = pool.submit(fit_one, est, frame)  # JX007
    return fut.result()


def pool_lambda_program(ds, agg, coefs):
    prog = ds.tree_aggregate_fn(agg)
    with cf.ThreadPoolExecutor() as pool:
        return list(pool.map(lambda c: prog(c), coefs))  # JX007


def thread_target_jit(step, x):
    prog = jax.jit(step)
    t = threading.Thread(target=lambda: prog(x))  # JX007
    t.start()
    return t


class GridSearch:
    def __init__(self, est, evaluator):
        self.est = est
        self.evaluator = evaluator

    def _score(self, pair):
        model = self.est.fit(pair[0])
        return self.evaluator.evaluate(model.transform(pair[1]))

    def fan_out(self, pairs):
        with cf.ThreadPoolExecutor(max_workers=8) as pool:
            return list(pool.map(self._score, pairs))  # JX007
