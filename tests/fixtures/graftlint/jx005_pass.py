"""JX005 should-pass fixtures: declared axes and resolvable constants."""
import jax
import jax.numpy as jnp

from cycloneml_tpu.mesh import DATA_AXIS, MODEL_AXIS, REPLICA_AXIS


def good_axis_literals(x):
    x = jax.lax.psum(x, "data")
    x = jax.lax.pmean(x, ("data", "replica"))
    return jax.lax.pmax(x, "model")


def good_axis_constants(x):
    x = jax.lax.psum(x, DATA_AXIS)
    return jax.lax.all_gather(x, REPLICA_AXIS)


def dynamic_axis_is_skipped(x, axes):
    # dataflow the rule does not attempt: variables pass through
    for ax in axes:
        x = jax.lax.psum(x, ax)
    return x


def good_axis_index():
    return jax.lax.axis_index(MODEL_AXIS)
