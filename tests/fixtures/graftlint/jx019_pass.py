"""JX019 should-pass fixtures: registered keys, prefixes, dynamic keys."""


class ConfigBuilder:
    def __init__(self, key):
        self._key = key

    def doc(self, d):
        return self

    def int_conf(self, default=None):
        return self


WINDOW_MS = ConfigBuilder("cyclone.serving.windowMs").int_conf(25)
MAX_BATCH = ConfigBuilder("cyclone.serving.maxBatch").int_conf(512)


def read_registered(conf):
    return conf.get("cyclone.serving.windowMs")


def namespace_scan(conf):
    # a strict PREFIX of a registered key: the startswith idiom
    return [k for k in conf if k.startswith("cyclone.serving.")]


def dynamic_key(conf, name):
    # dynamic keys are not literals — out of scope by construction
    return conf.get(f"cyclone.serving.{name}")


def prose_mention():
    # keys inside prose never fullmatch
    raise ValueError("cyclone.serving.windowMs must be positive, got -1")


def matching_default(conf):
    # inline fallback agrees with the registered default exactly
    return conf.get("cyclone.serving.windowMs", 25)


def computed_default(conf, fallback):
    # dynamic defaults are not literals — out of scope by construction
    return conf.get("cyclone.serving.maxBatch", fallback)
