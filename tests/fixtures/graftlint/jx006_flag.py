"""JX006 should-flag fixtures: trace-time-only side effects."""
import jax
import jax.numpy as jnp

_calls = 0


class Model:
    def __init__(self):
        self.n_steps = 0
        self.history = []

    @jax.jit
    def step(self, x):
        self.n_steps += 1                  # JX006: frozen after first trace
        self.history.append(1)             # JX006: mutates host list at trace
        self.last_loss: float = 0.0        # JX006: annotated, same hazard
        return x * 2.0


@jax.jit
def bump_global(x):
    global _calls
    _calls = _calls + 1                    # JX006: trace-time only
    return x + 1.0
