"""PASS fixture: tracer-aware instrumentation around a jitted dispatch.

Pins the observe/ instrumentation contract: a dispatch wrapper whose
program can be INLINED into a larger jitted program (so the wrapper runs
at trace time with tracer arguments) must branch on the tracer check
BEFORE touching the host-side tracing API — no span, no host clock, no
fault counting on the traced path. The whole file must stay clean under
the full graftlint rule pack (JX001 especially: the span bodies and the
single batched device_get below are host-only code).
"""

import jax
import jax.numpy as jnp

from cycloneml_tpu.observe import tracing


def make_instrumented_program():
    @jax.jit
    def step(x):
        return {"loss": jnp.sum(x * x), "grad": 2.0 * x}

    def dispatch(x):
        if isinstance(x, jax.core.Tracer):
            # trace time (inlined into an outer jit): spans would record
            # meaningless host wall clock and bake host work into tracing
            return step(x)
        with tracing.span("collective", "fixture.step"):
            return step(x)

    dispatch.__wrapped__ = step
    return dispatch


def host_driver(x):
    prog = make_instrumented_program()
    with tracing.span("dispatch", "fixture.eval", evals=1):
        out_dev = prog(x)
        with tracing.span("transfer", "fixture.readback") as tsp:
            out = jax.device_get(out_dev)  # ONE batched pull
            tsp.annotate_bytes(out)
    return out["loss"], out["grad"]


def outer_fused(x):
    # the instrumented program inlined into a larger jitted program: the
    # wrapper's tracer branch keeps trace time span-free
    prog = make_instrumented_program()

    @jax.jit
    def fused(x):
        return prog(x)["loss"] * 2.0

    return fused(x)
