"""JX018/JX001 should-pass fixture: the performance doctor's read-only
span walk. Diagnosis runs over an already-captured span window — pure
host arithmetic, no dispatch, no device pulls, no clocks — so the whole
rule pack must stay silent on it (the observe/diagnose contract)."""


def _median(values):
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    return vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0


def walk_compile_spans(spans):
    # evidence join #1: recompiles past warm-up, grouped by program name
    per_program = {}
    for s in spans:
        if s.kind == "compile":
            per_program[s.name] = per_program.get(s.name, 0) + 1
    return {name: count - 1 for name, count in sorted(per_program.items())
            if count > 1}


def walk_lane_medians(spans, n_lanes):
    # evidence join #2: per-lane staging medians from the trace alone
    lanes = {}
    for s in spans:
        if s.kind == "transfer" and s.name == "oocore.stage":
            shard = s.attrs.get("shard")
            if shard is None:
                continue
            lanes.setdefault(int(shard) % n_lanes, []).append(s.duration_s)
    return {pos: _median(vals) for pos, vals in sorted(lanes.items())}


def convict_stragglers(lane_medians, mad_factor, rel_factor):
    # pure-host conviction: every gate is arithmetic over the join above
    meds = sorted(lane_medians.values())
    if not meds:
        return []
    group = _median(meds)
    mad = _median([abs(v - group) for v in meds])
    return [pos for pos, med in sorted(lane_medians.items())
            if med > group + mad_factor * mad and med > rel_factor * group]
