"""JX014 should-pass fixtures: blocking done right around locks."""
import os
import threading
import time


class WaitLoop:
    """The canonical condition-variable consumer: `wait` RELEASES the
    lock it blocks on — blocking under your own cv is the idiom."""

    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def put(self, v):
        with self._cv:
            self._items.append(v)
            self._cv.notify_all()

    def take(self, deadline):
        with self._cv:
            while not self._items:
                self._cv.wait(timeout=deadline)
            return self._items.pop(0)


class SnapshotThenBlock:
    """Copy under the lock, release, then do the slow thing."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
        self._thread = None

    def flush_slowly(self):
        with self._lock:
            batch = list(self._pending)
            self._pending = []
        time.sleep(0.01)        # blocking, but no lock held
        return batch

    def stop(self):
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=5)   # join AFTER the lock is released


def string_and_path_joins_are_fine(parts, root):
    lock = threading.Lock()
    with lock:
        joined = ", ".join(str(p) for p in parts)
        return os.path.join(root, joined)


class FactoredWaitLoop:
    """The sanctioned cv wait loop FACTORED INTO A HELPER: `wait`
    releases the cv the caller holds, so the helper is not a blocker."""

    def __init__(self):
        self._cv = threading.Condition()
        self._ready = []

    def _wait_ready(self):
        while not self._ready:
            self._cv.wait(0.1)

    def take(self):
        with self._cv:
            self._wait_ready()
            return self._ready.pop(0)
