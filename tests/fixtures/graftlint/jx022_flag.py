"""JX022 should-flag fixtures: lifecycle typestate violations."""
import threading


class Lane:
    """Queue-lane shape: stop() latches the flag, submit() guards on it."""

    def __init__(self):
        self._cv = threading.Condition()
        self._stop = False

    def submit(self, item):
        with self._cv:
            if self._stop:
                raise RuntimeError("stopped")
        return item

    def stop(self):
        with self._cv:
            self._stop = True


class Channel:
    def __init__(self):
        self._lock = threading.Lock()
        self._closed = False

    def close(self):
        # check-then-act with no lock held: two closers both pass the
        # check and both run the teardown body
        if self._closed:
            return
        self._closed = True                                     # JX022
        self._lock = None


def drain_then_submit(items):
    lane = Lane()
    for it in items:
        lane.submit(it)
    lane.stop()
    return lane.submit(None)                                    # JX022


def leaky_worker(items):
    lane = Lane()                                               # JX022
    for it in items:
        lane.submit(it)
    return len(items)


def shutdown_lane(lane):
    lane.stop()


def interprocedural_dispatch(items):
    lane = Lane()
    shutdown_lane(lane)
    return lane.submit(items)                                   # JX022


class ScaleSupervisor:
    """Autoscale-actuator shape (ISSUE 17): announce() guards on the
    stop latch; a decision landing after stop() must die, not thrash a
    torn-down supervisor."""

    def __init__(self):
        self._cv = threading.Condition()
        self._stop = False

    def announce(self, decision):
        with self._cv:
            if self._stop:
                raise RuntimeError("supervisor stopped")
        return decision

    def stop(self):
        with self._cv:
            self._stop = True


def decide_after_shutdown(events):
    sup = ScaleSupervisor()
    for ev in events:
        sup.announce(ev)
    sup.stop()
    return sup.announce("scale-up")                             # JX022
