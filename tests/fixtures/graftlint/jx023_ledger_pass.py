"""JX023 should-pass fixture: the canonical ledger append — sorted row
order, sorted JSON keys, no clocks, no unseeded jitter. Replaying the
same runs rewrites the same bytes (the observe/regress contract).

===============  ==========================================
point            fired from
===============  ==========================================
``demo.append``  every function below
===============  ==========================================
"""
import json


def inject(point, **info):
    """Fixture stand-in for parallel.faults.inject (hosts the table)."""


def canonical_row(row):
    return json.dumps(row, sort_keys=True, separators=(",", ":"))


def append_rows_canonical(ledger, rows):
    # dedup via a set is fine for MEMBERSHIP; the write order comes
    # from sorted(), so the ledger is byte-stable across replays
    inject("demo.append", n=len(rows))
    out = [canonical_row(r) for r in sorted(set(rows))]
    ledger.extend(out)
    return out


def append_if_fresh(ledger, row, seen):
    inject("demo.append", metric=row)
    if row in seen:
        return 0
    ledger.append(canonical_row(row))
    return 1
