"""JX012 should-pass fixtures: acyclic and reentrant acquisition."""
import threading

_first = threading.Lock()
_second = threading.Lock()
_rl = threading.RLock()


def ordered_one():
    # consistent global order: first, then second — everywhere
    with _first:
        with _second:
            pass


def ordered_two():
    with _first:
        with _second:
            pass


def second_alone():
    with _second:
        pass


def reentrant_ok():
    # RLock self-nesting is the documented recursion pattern
    with _rl:
        with _rl:
            pass


class SnapshotThenCall:
    """The recommended inversion fix: copy under the lock, RELEASE, then
    call into the other lock's owner — no edge is ever drawn."""

    def __init__(self, other):
        self._lock = threading.Lock()
        self._items = []
        self.other = other

    def flush(self):
        with self._lock:
            snapshot = list(self._items)
            self._items = []
        for item in snapshot:
            self.other.consume(item)


class CvLoop:
    """Condition() is RLock-backed — re-entry by the holding thread is
    legal, and the wait loop is the canonical consumer."""

    def __init__(self):
        self._cv = threading.Condition()
        self._ready = []

    def put(self, v):
        with self._cv:
            self._ready.append(v)
            self._cv.notify_all()

    def take(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()
            return self._ready.pop(0)
