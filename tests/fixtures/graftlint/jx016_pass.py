"""JX016 should-pass fixtures: masked reductions over padded buffers,
compatible broadcasts."""
import jax.numpy as jnp
import numpy as np


def device_chunk_masked(rows, w):
    # the deviceChunk idiom: pad the last chunk, mask with w=0 — the
    # reductions carry the mask, so padding is bitwise-neutral
    k, d = rows.shape
    buf = np.zeros((64, 8))
    buf[:k] = rows
    wbuf = np.zeros((64,))
    wbuf[:k] = w
    total = jnp.sum(buf * wbuf[:, None], axis=0)
    count = jnp.sum(wbuf)
    return total / count


def sliced_mean(rows):
    # slicing the padding off before the reduction is fine
    k, d = rows.shape
    buf = np.zeros((64, 8))
    buf[:k] = rows
    return jnp.mean(buf[:k], axis=0)


def feature_mean_of_row_padded(rows):
    # mean over the FEATURE dim of a row-padded buffer never touches the
    # pad rows' count
    k, d = rows.shape
    buf = np.zeros((64, 8))
    buf[:k] = rows
    return jnp.mean(buf, axis=1)[:k]


def compatible_broadcast():
    a = jnp.zeros((4, 16))
    b = jnp.zeros((16,))
    return a + b


def symbolic_dims_stay_silent(x, y):
    # distinct symbols MAY be equal at runtime — only provable (concrete)
    # conflicts flag
    n, d = x.shape
    m, k = y.shape
    return jnp.zeros((n, d)) + jnp.zeros((n, d)), jnp.zeros((m, k))
