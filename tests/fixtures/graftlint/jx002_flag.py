"""JX002 should-flag fixtures: Python control flow on traced values."""
import jax
import jax.numpy as jnp


@jax.jit
def branch_on_traced(x):
    m = jnp.mean(x)
    if m > 0:                       # JX002: traced comparison
        return x - m
    return x + m


@jax.jit
def loop_on_traced(x):
    while jnp.sum(x) > 1.0:         # JX002: traced while condition
        x = x * 0.5
    return x


def kernel_factory(d):
    def kernel(x, coef):
        margin = jnp.dot(x, coef)
        if margin.sum() > 0:        # JX002: inside a returned jnp kernel
            return margin
        return -margin
    return kernel
