"""JX015 should-pass fixtures: the repo's shard_map spec idioms."""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _local_psummed(x):
    return jax.lax.psum(jnp.sum(x, axis=0), "data")


def _local_state(x):
    # with_state shape: psummed stats + row-sharded state
    stats = jax.lax.psum(jnp.sum(x, axis=0), "data")
    return stats, x


def reduced_out_replicated(mesh, xs):
    # psummed body + replicated out_spec: the canonical aggregate
    spec = P((REPLICA_AXIS, DATA_AXIS))
    return shard_map_compat(_local_psummed, mesh, (spec,), P())(xs)


def state_keeps_row_sharding(mesh, xs):
    # out element 0 replicated (psummed), element 1 keeps row sharding
    row_spec = P((REPLICA_AXIS, DATA_AXIS))
    out_specs = (P(), row_spec)
    return shard_map_compat(_local_state, mesh, (row_spec,), out_specs)(xs)


def uniform_specs_unknown_count(mesh, arrays):
    # `(spec,) * len(...)` — uniform spec over an unknown operand count
    row_spec = P((REPLICA_AXIS, DATA_AXIS))
    return shard_map_compat(_local_psummed, mesh,
                            (row_spec,) * len(arrays), P())(*arrays)


def rank_matches(mesh):
    rows = jnp.zeros((8, 4))
    return shard_map_compat(_local_psummed, mesh,
                            (P("data", None),), P())(rows)


def _local_flat_psummed(x):
    # the depth=1 flat reduction: one psum over the joint axis tuple
    return jax.lax.psum(jnp.sum(x, axis=0), ("data", "replica"))


def flat_depth1_replicated_out(mesh, xs):
    # the multihost depth=1 idiom: hierarchical row in_spec over BOTH
    # mesh axes, flat tuple psum in the body, replicated out_spec
    spec = P((REPLICA_AXIS, DATA_AXIS))
    return shard_map_compat(_local_flat_psummed, mesh, (spec,), P())(xs)
