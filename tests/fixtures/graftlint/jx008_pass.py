"""JX008 should-pass fixtures: compile-once dispatch discipline."""
import jax
import jax.numpy as jnp


def _kernel(x, k):
    return x * k


_prog = jax.jit(_kernel, static_argnums=(1,))


def loop_invariant_static(x, n, width):
    # the static is hoisted: ONE compile serves every iteration
    out = []
    for _ in range(n):
        out.append(_prog(x, width))
    return out


def varying_traced_scalar(x, n):
    # a Python scalar in a TRACED position is cached on (shape, dtype),
    # not the value — no recompile however it varies
    total = x
    for i in range(n):
        total = _prog(total, 2) + i
    return total


def fixed_shape_slice(x, n, limit):
    # the slice bound is loop-invariant: one shape, one compile
    head = x[:limit]
    out = []
    for _ in range(n):
        out.append(_prog(head, 0))
    return out


def program_built_once(xs):
    # compile-once discipline: build outside, dispatch inside
    prog = jax.jit(_kernel)
    return [prog(x, 2) for x in xs]


def hashable_static(x):
    # tuples hash: a legal static config
    return _prog(x, (1, 2, 3))


def varying_traced_by_keyword(x, n):
    # a keyword onto a TRACED position still caches on (shape, dtype)
    plain = jax.jit(_kernel)
    out = []
    for i in range(n):
        out.append(plain(x, k=i))
    return out


def _run_fixed(x):
    # wrapper passes only traced operands through — no cache-keyed
    # position is reachable from its parameters' VALUES
    return _prog(x, 0)


def sweep_fixed_through_wrapper(x, n):
    out = []
    for _ in range(n):
        out.append(_run_fixed(x))
    return out
