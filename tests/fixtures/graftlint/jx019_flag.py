"""JX019 should-flag fixtures: typo'd cyclone.* conf keys."""


class ConfigBuilder:
    def __init__(self, key):
        self._key = key

    def doc(self, d):
        return self

    def int_conf(self, default=None):
        return self

    def with_alternative(self, key):
        return self


WINDOW_MS = ConfigBuilder("cyclone.serving.windowMs").int_conf(25)
MAX_BATCH = (ConfigBuilder("cyclone.serving.maxBatch")
             .with_alternative("cyclone.serving.batchMax")
             .int_conf(512))


def read_window(conf):
    # one dropped letter: silently reads the default forever
    return conf.get("cyclone.serving.windwMs")                  # JX019


def set_bad_key(conf):
    conf.set("cyclone.serving.maxBach", 256)                    # JX019


def tuple_pair(pairs):
    # submit.py-style (key, value) pair building
    pairs.append(("cyclone.servng.windowMs", 5))                # JX019


def drifted_default(conf):
    # registered default is 25: the inline fallback silently diverges
    return conf.get("cyclone.serving.windowMs", 50)             # JX019


def type_drifted_default(conf):
    # right value, wrong type: 512.0 is not the registered int 512
    return conf.get("cyclone.serving.maxBatch", 512.0)          # JX019
