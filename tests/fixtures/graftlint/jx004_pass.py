"""JX004 should-pass fixtures: guarded or host-side fp64, data-tier dtype."""
import jax
import jax.numpy as jnp
import numpy as np

# module-level guard: fp64 below is a deliberate, visible choice
jax.config.update("jax_enable_x64", True)


@jax.jit
def guarded_f64(x):
    return jnp.zeros(x.shape, dtype=jnp.float64) + x


@jax.jit
def dtype_from_data(x):
    # following the operand's dtype adapts to whatever the tier runs in
    return jnp.zeros(x.shape, dtype=x.dtype) + x


def host_readback(out):
    # np.float64 on the HOST side of the boundary is idiomatic
    return np.asarray(out, dtype=np.float64)


@jax.jit
def bf16_storage_is_legal(x):
    # narrow STORAGE is the data tier's contract (cyclone.data.dtype);
    # only narrow ACCUMULATION across the mesh is the hazard
    return jnp.zeros(x.shape, dtype=jnp.bfloat16) + x.astype(jnp.bfloat16)


@jax.jit
def fp32_accumulated_psum(x):
    # the tier ends at the kernel: upcast BEFORE the collective
    acc = jnp.sum(x.astype(jnp.float32))
    return jax.lax.psum(acc, "data")


@jax.jit
def rewidened_name_is_clean(x):
    # source-order tracking: the re-widening clears the narrow mark
    y = x.astype(jnp.bfloat16)
    y = y.astype(jnp.float32)
    return jax.lax.psum(y, "data")


@jax.jit
def narrowed_after_psum_is_clean(x):
    # position matters: y is WIDE at the collective; the narrowing below
    # it is a later, separate binding (a final-state scan would flag this)
    y = x * 2.0
    acc = jax.lax.psum(y, "data")
    y = x.astype(jnp.bfloat16)
    return acc + y.astype(jnp.float32)


def _to_accumulator(x):
    # helper returns the WIDE tier: psum of its result is legal
    return x.astype(jnp.float32)


@jax.jit
def psum_of_wide_helper(x):
    return jax.lax.psum(_to_accumulator(x), "data")


@jax.jit
def rewiden_via_annassign(x):
    # an ANNOTATED assignment re-widens exactly like the bare form
    y = x.astype(jnp.bfloat16)
    y: jax.Array = y.astype(jnp.float32)
    return jax.lax.psum(y, "data")


@jax.jit
def fp8_storage_is_legal(x):
    # fp8 STORAGE is the second rung of the data tier
    # (cyclone.data.dtype=auto8/float8); only narrow ACCUMULATION across
    # the mesh is the hazard
    return jnp.zeros(x.shape, dtype=jnp.float8_e4m3fn)


@jax.jit
def fp32_accumulated_fp8_psum(x):
    # the tier ends at the kernel, fp8 included: upcast BEFORE the psum
    y = x.astype(jnp.float8_e4m3fn)
    acc = jnp.sum(y.astype(jnp.float32))
    return jax.lax.psum(acc, "data")


@jax.jit
def streamed_dequant_fold_psum(x8, scale):
    # the fp8 shard stream's aggregator read (oocore/): codes dequantize
    # inside the kernel — the set-level scale folds into the f32 upcast
    # and the psum operand is the WIDE reduced partial, never the codes
    part = jnp.sum(x8.astype(jnp.float32) * scale, axis=0)
    return jax.lax.psum(part, "data")
