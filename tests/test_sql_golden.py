"""Golden-file SQL query tests.

Analog of the reference's SQLQueryTestSuite (ref: sql/core/src/test/
resources/sql-tests/ — committed .sql inputs with .out golden results,
regenerated with an env flag and reviewed as diffs). Queries live in
``tests/sql_golden/queries.sql`` (one per line, '--' comments); goldens in
``queries.sql.out``. Regenerate with:

    CYCLONE_REGEN_GOLDEN=1 python -m pytest tests/test_sql_golden.py
"""

import os

import numpy as np
import pytest

from cycloneml_tpu.sql.session import CycloneSession

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "sql_golden")
QUERIES = os.path.join(HERE, "queries.sql")
GOLDEN = QUERIES + ".out"


def _fixture_session() -> CycloneSession:
    s = CycloneSession()
    s.register_temp_view("emp", s.create_data_frame({
        "id": [1, 2, 3, 4, 5],
        "name": ["alice", "bob", "carol", "dan", "eve"],
        "dept": ["eng", "eng", "sales", "sales", "hr"],
        "salary": [120.0, 100.0, 80.0, 85.0, 70.0],
    }))
    s.register_temp_view("dept", s.create_data_frame({
        "dept": ["eng", "sales", "hr", "legal"],
        "floor": [3, 2, 1, 4],
    }))
    return s


def _render(df) -> str:
    batch = df.to_dict()
    cols = list(batch)
    n = len(batch[cols[0]]) if cols else 0
    lines = ["\t".join(cols)]
    for i in range(n):
        lines.append("\t".join(_cell(batch[c][i]) for c in cols))
    return "\n".join(lines)


def _cell(v) -> str:
    if isinstance(v, (float, np.floating)):
        return f"{float(v):g}"
    return str(v)


def _load_queries():
    with open(QUERIES, encoding="utf-8") as fh:
        return [ln.strip() for ln in fh
                if ln.strip() and not ln.strip().startswith("--")]


def test_golden_queries():
    session = _fixture_session()
    blocks = []
    for q in _load_queries():
        blocks.append(f"-- !query\n{q}\n-- !result\n"
                      f"{_render(session.sql(q))}\n")
    rendered = "\n".join(blocks)
    if os.environ.get("CYCLONE_REGEN_GOLDEN"):
        with open(GOLDEN, "w", encoding="utf-8") as fh:
            fh.write(rendered)
        pytest.skip("golden file regenerated")
    with open(GOLDEN, encoding="utf-8") as fh:
        want = fh.read()
    assert rendered == want, (
        "SQL results diverged from the committed golden file; if the change "
        "is intentional regenerate with CYCLONE_REGEN_GOLDEN=1 and review "
        "the diff")
