"""Golden-file SQL query tests + an independent-oracle cross-check.

Analog of the reference's SQLQueryTestSuite (ref: sql/core/src/test/
resources/sql-tests/ — committed .sql inputs with .out golden results,
regenerated with an env flag and reviewed as diffs). Queries live in
``tests/sql_golden/queries.sql`` (one per line, '--' comments; a comment
line starting with '-- no-sqlite' marks the NEXT query as not comparable to
sqlite — engine-specific null/NaN semantics). Goldens in ``queries.sql.out``.
Regenerate with:

    CYCLONE_REGEN_GOLDEN=1 python -m pytest tests/test_sql_golden.py

Beyond the self-referential golden check, every untagged query also runs
through **sqlite3** on the same fixture data and the result SETS must match
— an oracle the engine does not share a line of code with (the reference
compares against Hive/PostgreSQL goldens in the same spirit).
"""

import math
import os
import sqlite3

import numpy as np
import pytest

from cycloneml_tpu.sql.session import CycloneSession

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "sql_golden")
QUERIES = os.path.join(HERE, "queries.sql")
GOLDEN = QUERIES + ".out"

TABLES = {
    "emp": {
        "id": [1, 2, 3, 4, 5],
        "name": ["alice", "bob", "carol", "dan", "eve"],
        "dept": ["eng", "eng", "sales", "sales", "hr"],
        "salary": [120.0, 100.0, 80.0, 85.0, 70.0],
    },
    "dept": {
        "dept": ["eng", "sales", "hr", "legal"],
        "floor": [3, 2, 1, 4],
    },
    # ties for rank-family windows
    "scores": {
        "name": ["ann", "ben", "cal", "deb", "eli"],
        "grade": [90.0, 80.0, 90.0, 70.0, 80.0],
    },
    # nullable numeric column (NaN = engine null) + categorical
    "inv": {
        "item": ["bolt", "nut", "washer", "screw"],
        "qty": [10.0, float("nan"), 3.0, float("nan")],
        "kind": ["metal", "metal", "metal", "wood"],
    },
    # duplicate join keys + unmatched rows on both sides
    "t1": {"tag": ["a", "a", "b", "c"], "x": [1, 2, 3, 4]},
    "t2": {"tag": ["a", "b", "b", "d"], "val": [10, 20, 30, 40]},
}


def _fixture_session() -> CycloneSession:
    s = CycloneSession()
    for name, cols in TABLES.items():
        s.register_temp_view(name, s.create_data_frame(cols))
    return s


def _render(df) -> str:
    batch = df.to_dict()
    cols = list(batch)
    n = len(batch[cols[0]]) if cols else 0
    lines = ["\t".join(cols)]
    for i in range(n):
        lines.append("\t".join(_cell(batch[c][i]) for c in cols))
    return "\n".join(lines)


def _cell(v) -> str:
    if isinstance(v, (float, np.floating)):
        return f"{float(v):g}"
    return str(v)


def _load_queries():
    """[(query, sqlite_comparable)]"""
    out = []
    no_sqlite = False
    with open(QUERIES, encoding="utf-8") as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            if ln.startswith("--"):
                if ln.startswith("-- no-sqlite"):
                    no_sqlite = True
                continue
            out.append((ln, not no_sqlite))
            no_sqlite = False
    return out


def test_golden_queries():
    session = _fixture_session()
    blocks = []
    for q, _ in _load_queries():
        blocks.append(f"-- !query\n{q}\n-- !result\n"
                      f"{_render(session.sql(q))}\n")
    rendered = "\n".join(blocks)
    if os.environ.get("CYCLONE_REGEN_GOLDEN"):
        with open(GOLDEN, "w", encoding="utf-8") as fh:
            fh.write(rendered)
        pytest.skip("golden file regenerated")
    with open(GOLDEN, encoding="utf-8") as fh:
        want = fh.read()
    assert rendered == want, (
        "SQL results diverged from the committed golden file; if the change "
        "is intentional regenerate with CYCLONE_REGEN_GOLDEN=1 and review "
        "the diff")


# -- sqlite oracle --------------------------------------------------------------

def _sqlite_conn():
    conn = sqlite3.connect(":memory:")
    for name, cols in TABLES.items():
        names = list(cols)
        conn.execute(f"CREATE TABLE {name} ({', '.join(names)})")
        rows = zip(*[cols[c] for c in names])
        conn.executemany(
            f"INSERT INTO {name} VALUES ({', '.join('?' * len(names))})",
            [[None if isinstance(v, float) and math.isnan(v) else v
              for v in row] for row in rows])
    return conn


def _norm(v):
    if v is None:
        return "NULL"
    if isinstance(v, (bool, np.bool_)):
        return f"{int(v)}"
    if isinstance(v, (int, np.integer)):
        return f"{v:.6g}"
    if isinstance(v, (float, np.floating)):
        f = float(v)
        return "NULL" if math.isnan(f) else f"{f:.6g}"
    return str(v)


def test_sqlite_cross_check():
    """Every untagged golden query must produce the same multiset of rows as
    sqlite3 on identical data — an oracle with no shared code. Booleans
    normalize to 0/1 (sqlite has no bool), engine-NaN to NULL."""
    session = _fixture_session()
    conn = _sqlite_conn()
    checked = 0
    old_sqlite = sqlite3.sqlite_version_info < (3, 39)
    for q, comparable in _load_queries():
        if not comparable:
            continue
        if old_sqlite and ("FULL OUTER" in q or "RIGHT JOIN" in q):
            continue  # sqlite grew these join types in 3.39 (2022)
        got = session.sql(q).to_dict()
        cols = list(got)
        n = len(got[cols[0]]) if cols else 0
        ours = sorted(tuple(_norm(got[c][i]) for c in cols)
                      for i in range(n))
        theirs = sorted(tuple(_norm(v) for v in row)
                        for row in conn.execute(q).fetchall())
        assert ours == theirs, (
            f"divergence from sqlite on:\n  {q}\n"
            f"ours   : {ours[:8]}\nsqlite : {theirs[:8]}")
        checked += 1
    assert checked >= 90  # the suite must stay broad
