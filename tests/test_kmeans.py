"""KMeans tests (BASELINE config 3 family): correctness vs sklearn on
well-separated blobs, cost parity, cosine mode, weights, persistence."""

import numpy as np
import pytest

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.clustering import KMeans, KMeansModel


def _blobs(ctx, n=600, d=8, k=4, seed=31, spread=0.3):
    rng = np.random.RandomState(seed)
    centers = rng.randn(k, d) * 5
    labels = rng.randint(0, k, n)
    x = centers[labels] + spread * rng.randn(n, d)
    return MLFrame(ctx, {"features": x}), x, labels, centers


def test_recovers_well_separated_blobs(ctx):
    frame, x, labels, true_centers = _blobs(ctx)
    model = KMeans(k=4, seed=1, maxIter=50).fit(frame)
    got = np.asarray(model.cluster_centers_matrix().to_array())
    # each true center has a found center within spread
    for c in true_centers:
        assert np.min(np.linalg.norm(got - c, axis=1)) < 0.5
    # assignments agree with nearest-true-center partition
    pred = model.transform(frame)["prediction"]
    from scipy.stats import mode
    # cluster purity ~ 1
    purity = np.mean([
        mode(labels[pred == c], keepdims=False).count / max((pred == c).sum(), 1)
        for c in np.unique(pred)])
    assert purity > 0.99


def test_cost_close_to_sklearn(ctx):
    from sklearn.cluster import KMeans as SkKMeans
    frame, x, _, _ = _blobs(ctx, seed=32, spread=1.0)
    ours = KMeans(k=4, seed=3, maxIter=100, tol=1e-8).fit(frame)
    sk = SkKMeans(n_clusters=4, n_init=10, tol=1e-10, random_state=0).fit(x)
    our_cost = ours.compute_cost(frame)
    assert our_cost <= sk.inertia_ * 1.05


def test_training_cost_and_iterations_recorded(ctx):
    frame, _, _, _ = _blobs(ctx, seed=33)
    m = KMeans(k=4, maxIter=30).fit(frame)
    assert m.training_cost > 0
    assert 1 <= m.num_iterations <= 30
    assert m.training_cost == pytest.approx(m.compute_cost(frame), rel=1e-4)


def test_random_init_mode(ctx):
    frame, _, _, _ = _blobs(ctx, seed=34)
    m = KMeans(k=4, initMode="random", seed=5, maxIter=50).fit(frame)
    assert len(m.cluster_centers) == 4


def test_cosine_distance_clusters_by_angle(ctx):
    rng = np.random.RandomState(35)
    x = np.vstack([
        np.array([1.0, 0.0])[None, :] * rng.uniform(1, 10, (100, 1)),
        np.array([0.0, 1.0])[None, :] * rng.uniform(1, 10, (100, 1))])
    x += 0.02 * rng.randn(*x.shape)
    frame = MLFrame(ctx, {"features": x})
    m = KMeans(k=2, distanceMeasure="cosine", seed=7, maxIter=30).fit(frame)
    pred = m.transform(frame)["prediction"]
    assert len(set(pred[:100])) == 1 and len(set(pred[100:])) == 1
    assert pred[0] != pred[150]
    # centers are unit-norm in cosine mode
    for c in m.cluster_centers:
        assert np.linalg.norm(c) == pytest.approx(1.0, abs=1e-6)


def test_weighted_kmeans_pulls_centers(ctx):
    x = np.array([[0.0], [1.0], [10.0], [11.0]])
    w = np.array([1.0, 1.0, 100.0, 100.0])
    frame = MLFrame(ctx, {"features": x, "w": w})
    km = KMeans(k=2, maxIter=20, seed=2)
    km.set("weightCol", "w")
    m = km.fit(frame)
    centers = sorted(float(c[0]) for c in m.cluster_centers)
    assert centers[0] == pytest.approx(0.5, abs=1e-6)
    assert centers[1] == pytest.approx(10.5, abs=1e-6)


def test_save_load(ctx, tmp_path):
    frame, _, _, _ = _blobs(ctx, seed=36)
    m = KMeans(k=3, maxIter=10).fit(frame)
    p = str(tmp_path / "km")
    m.save(p)
    back = KMeansModel.load(p)
    np.testing.assert_allclose(back.cluster_centers_matrix().to_array(),
                               m.cluster_centers_matrix().to_array())
    np.testing.assert_allclose(back.transform(frame)["prediction"],
                               m.transform(frame)["prediction"])
