"""Step-level tracing tests: span API, FitProfile, Chrome export, and the
end-to-end acceptance contract (a traced LogisticRegression.fit exports a
valid Chrome trace with >= 4 span kinds whose FitProfile counts agree with
the model summary's dispatch/eval ledger).

The tracer is process-global state like faults._active: every test that
enables it disables it in a finally block so the rest of the suite keeps
the zero-overhead disabled path.
"""

import json
import threading

import numpy as np
import pytest

from cycloneml_tpu.observe import (FitProfile, chrome_trace,
                                   export_chrome_trace, span_kinds, tracing,
                                   validate_chrome_trace)


@pytest.fixture
def tracer():
    tracing.disable()  # defend against a leak from a dirty test
    t = tracing.enable(max_spans=10_000)
    yield t
    tracing.disable()


# -- disabled path ---------------------------------------------------------------

def test_span_api_is_noop_when_disabled():
    tracing.disable()
    assert tracing.active() is None
    s1 = tracing.span("dispatch", "a")
    s2 = tracing.span("collective", "b", attr=1)
    # one shared object, no allocation per call — the zero-overhead contract
    assert s1 is s2 is tracing.NOOP_SPAN
    with s1 as s:
        s.annotate(evals=1)
        s.annotate_bytes({"x": np.zeros(8)})  # must not walk the tree
        assert s.span_id == ""
    tracing.instant("fault", point="collectives.step")
    assert tracing.current_span_id() == ""


def test_instrumented_sites_record_nothing_when_disabled(ctx):
    """A tree_aggregate dispatch with tracing off must leave no trace state
    behind — then the same program dispatched under a tracer records a
    collective span (cache already warm: no compile span)."""
    import jax.numpy as jnp
    from cycloneml_tpu.parallel.collectives import tree_aggregate

    def agg(x):
        return {"s": jnp.sum(x)}

    rt = ctx.mesh_runtime
    data = rt.device_put_sharded_rows(np.ones((64, 2), dtype=np.float32))
    prog = tree_aggregate(agg, rt, data)
    prog(data)  # disabled: nothing recorded anywhere
    t = tracing.enable(max_spans=100)
    try:
        prog(data)
        kinds = {s.kind for s in t.snapshot()}
        assert "collective" in kinds
    finally:
        tracing.disable()


# -- span recording --------------------------------------------------------------

def test_spans_nest_and_annotate(tracer):
    with tracer.span("job", "fit") as job:
        with tracer.span("dispatch", "loss.eval", evals=1) as d:
            tracer.instant("cache.miss")
            d.annotate(extra=7)
    spans = tracer.snapshot()
    by_kind = {s.kind: s for s in spans}
    assert by_kind["dispatch"].parent_id == job.span_id
    assert by_kind["instant"].parent_id == by_kind["dispatch"].span_id
    assert by_kind["dispatch"].attrs == {"evals": 1, "extra": 7}
    assert by_kind["job"].t1 >= by_kind["dispatch"].t1 >= \
        by_kind["dispatch"].t0
    assert tracing.current_span_id() == ""  # stack fully unwound


def test_span_buffer_bound():
    t = tracing.Tracer(max_spans=3)
    for i in range(5):
        t.instant("x", i=i)
    assert len(t.snapshot()) == 3 and t.dropped == 2


def test_span_ring_drops_oldest_and_keeps_monotonic_marks():
    """Overflow semantics pin (ISSUE 12 satellite): the ring drops the
    OLDEST spans, counts them, and sequence positions survive the wrap —
    a mark taken before the wrap still reads exactly the survivors past
    it, never a replay and never a skip."""
    t = tracing.Tracer(max_spans=4)
    for i in range(4):
        t.instant("x", i=i)
    mark = t.mark()
    assert mark == 4
    for i in range(4, 10):
        t.instant("x", i=i)
    # the RECENT window survives; the oldest 6 were dropped and counted
    assert [s.attrs["i"] for s in t.snapshot()] == [6, 7, 8, 9]
    assert t.dropped == 6
    assert t.mark() == 10
    # the pre-wrap mark: positions 4..5 fell off the ring floor, so the
    # read returns the SURVIVING suffix (6..9), not a stale replay
    assert [s.attrs["i"] for s in t.snapshot(since=mark)] == [6, 7, 8, 9]
    assert [s.attrs["i"] for s in t.snapshot(since=8)] == [8, 9]
    assert t.snapshot(since=10) == []
    # clear keeps positions monotonic: an old cursor yields only new spans
    t.clear()
    assert t.dropped == 0
    t.instant("x", i=99)
    assert [s.attrs["i"] for s in t.snapshot(since=mark)] == [99]


def test_concurrent_producers_and_drain_lose_and_duplicate_nothing():
    """ISSUE 12 satellite: N threads record while a collector drains via
    the atomic ``drain(since)`` cursor — every span delivered exactly
    once. (A separate mark()-then-snapshot() pair would double-deliver
    spans recorded between the two calls.)"""
    t = tracing.Tracer(max_spans=100_000)
    n_threads, per_thread = 4, 500
    done = threading.Event()
    collected = []

    def producer(k):
        for i in range(per_thread):
            t.instant("p", k=k, i=i)

    def collector():
        since = 0
        while True:
            spans, since = t.drain(since)
            collected.extend(spans)
            if done.is_set():
                spans, since = t.drain(since)  # final sweep
                collected.extend(spans)
                return

    col = threading.Thread(target=collector)
    col.start()
    producers = [threading.Thread(target=producer, args=(k,))
                 for k in range(n_threads)]
    for p in producers:
        p.start()
    for p in producers:
        p.join()
    done.set()
    col.join()
    keys = [(s.attrs["k"], s.attrs["i"]) for s in collected]
    assert len(keys) == n_threads * per_thread      # none lost...
    assert len(set(keys)) == len(keys)              # ...none double-shipped
    assert t.dropped == 0


def test_export_emits_process_and_thread_metadata(tracer, tmp_path):
    """ISSUE 12 satellite: the Chrome export labels lanes with M-phase
    process_name/thread_name events (Perfetto shows names, not bare
    pids/tids) and the validator accepts them."""
    with tracer.span("job", "fit"):
        pass
    obj = chrome_trace(tracer)
    assert validate_chrome_trace(obj) == []
    meta = [e for e in obj["traceEvents"] if e["ph"] == "M"]
    names = {e["name"] for e in meta}
    assert names == {"process_name", "thread_name"}
    threads = [e for e in meta if e["name"] == "thread_name"]
    assert any(e["args"]["name"] == threading.current_thread().name
               for e in threads)
    # validator rejects a malformed metadata event
    assert validate_chrome_trace(
        {"traceEvents": [{"name": "process_name", "ph": "M", "pid": 1,
                          "args": {}}]})


def test_export_header_and_profile_carry_spans_dropped(tmp_path):
    t = tracing.Tracer(max_spans=2)
    for i in range(5):
        t.instant("x", i=i)
    obj = chrome_trace(t)
    assert obj["otherData"]["spans_dropped"] == 3
    assert obj["otherData"]["trace_id"] == t.trace_id
    prof = t.profile_for(None)
    assert prof.spans_dropped == 3
    # the count survives the dict round trip (status store / journal)
    assert FitProfile.from_dict(prof.to_dict()).spans_dropped == 3


def test_threads_get_independent_context(tracer):
    seen = {}

    def worker(name):
        with tracer.span("job", name) as sp:
            seen[name] = sp.span_id

    ts = [threading.Thread(target=worker, args=(f"j{i}",)) for i in range(4)]
    for x in ts:
        x.start()
    for x in ts:
        x.join()
    roots = [s for s in tracer.snapshot() if s.kind == "job"]
    assert len(roots) == 4
    assert all(not s.parent_id for s in roots)  # no cross-thread bleed


# -- FitProfile ------------------------------------------------------------------

def test_fit_profile_scopes_to_root(tracer):
    with tracer.span("job", "fit-A") as a:
        with tracer.span("dispatch", "loss.eval", evals=3):
            pass
        with tracer.span("transfer", "rb") as t:
            t.annotate(bytes=128)
        tracer.instant("fault", point="collectives.step")
    with tracer.span("job", "fit-B"):
        with tracer.span("dispatch", "loss.eval", evals=5):
            pass
    prof = tracer.profile_for(a.span_id)
    assert prof.dispatch_count == 1 and prof.eval_count == 3
    assert prof.transfer_count == 1 and prof.transfer_bytes == 128
    assert prof.faults_injected == 1
    assert prof.description == "fit-A" and prof.wall_seconds > 0
    everything = tracer.profile_for(None)
    assert everything.dispatch_count == 2 and everything.eval_count == 8


def test_fit_profile_compile_vs_steady(tracer):
    import time
    with tracer.span("dispatch", "lbfgs.chunk", evals=2):
        with tracer.span("compile", "lbfgs.chunk"):
            pass
    with tracer.span("dispatch", "lbfgs.chunk", evals=2) as steady:
        pass
    prof = tracer.profile_for(None)
    assert prof.compile_count == 1
    assert prof.dispatch_count == 2
    # steady excludes the dispatch that paid the compile
    assert prof.steady_seconds == pytest.approx(steady.span.duration_s)


def test_fit_profile_excludes_deeply_nested_compiles_from_steady(tracer):
    """The host L-BFGS shape: dispatch → collective → compile. The compile
    is TWO levels below the dispatch, whose wall time includes the staging
    — it must not count as steady state."""
    import time
    with tracer.span("dispatch", "loss.eval", evals=1):
        with tracer.span("collective", "tree_aggregate"):
            with tracer.span("compile", "tree_aggregate"):
                time.sleep(0.01)
    with tracer.span("dispatch", "loss.eval", evals=1) as steady:
        pass
    prof = tracer.profile_for(None)
    assert prof.compile_count == 1 and prof.dispatch_count == 2
    assert prof.steady_seconds == pytest.approx(steady.span.duration_s)
    assert prof.steady_seconds < 0.01  # staging time fully excluded


def test_fit_profile_roundtrips_dict(tracer):
    with tracer.span("dispatch", "x", evals=1):
        pass
    prof = tracer.profile_for(None)
    again = FitProfile.from_dict(prof.to_dict())
    assert again == prof


# -- Chrome export ---------------------------------------------------------------

def test_chrome_trace_exports_and_validates(tracer, tmp_path):
    with tracer.span("job", "fit"):
        with tracer.span("dispatch", "loss.eval", evals=1):
            tracer.instant("cache.hit")
    path = str(tmp_path / "t.trace.json")
    export_chrome_trace(tracer, path)
    assert validate_chrome_trace(path) == []
    obj = json.load(open(path))
    kinds = span_kinds(obj)
    assert kinds == {"job": 1, "dispatch": 1, "instant": 1}
    evs = {e["name"]: e for e in obj["traceEvents"] if e["ph"] != "M"}
    assert evs["loss.eval"]["args"]["evals"] == 1
    assert evs["loss.eval"]["args"]["parent_id"] == \
        evs["fit"]["args"]["span_id"]
    assert evs["cache.hit"]["ph"] == "i"


def test_validator_rejects_malformed_traces():
    assert validate_chrome_trace({"nope": []})
    assert validate_chrome_trace({"traceEvents": [{"ph": "X"}]})
    assert validate_chrome_trace(
        {"traceEvents": [{"name": "a", "ph": "X", "pid": 1, "ts": 0.0}]}
    )  # X without dur
    assert validate_chrome_trace(
        {"traceEvents": [{"name": "a", "ph": "X", "pid": 1, "ts": 0.0,
                          "dur": 1.0}]}) == []


# -- end-to-end acceptance -------------------------------------------------------

def _fit_traced(ctx, tmp_path, **lr_kwargs):
    from cycloneml_tpu.dataset.frame import MLFrame
    from cycloneml_tpu.ml.classification import LogisticRegression

    rng = np.random.RandomState(0)
    x = rng.randn(128, 6)
    y = (x @ rng.randn(6) > 0).astype(float)
    frame = MLFrame(ctx, {"features": x, "label": y})
    model = LogisticRegression(maxIter=6, regParam=0.01, tol=0.0,
                               **lr_kwargs).fit(frame)
    assert ctx.listener_bus.wait_until_empty()
    return model


def test_traced_fit_exports_chrome_trace_with_4_kinds(ctx, tmp_path):
    """The ISSUE acceptance: one traced LogisticRegression.fit ->
    Chrome-trace JSON with >= 4 distinct span kinds that validates, and a
    FitProfile whose dispatch/eval counts agree with the ledger bench.py
    logs (summary.total_dispatches / total_evals)."""
    tracing.disable()
    tracer = tracing.enable(max_spans=50_000)
    try:
        model = _fit_traced(
            ctx, tmp_path,
            checkpointDir=str(tmp_path / "ckpt"), checkpointInterval=2)
        jobs = [j for j in ctx.status_store.job_list()
                if "LogisticRegression.fit" in j["description"]]
        jid = jobs[-1]["jobId"]
        prof = FitProfile.from_dict(ctx.status_store.profile(jid))

        path = str(tmp_path / "fit.trace.json")
        ctx.export_trace(path)
        assert validate_chrome_trace(path) == []
        kinds = set(span_kinds(path))
        want = {"compile", "dispatch", "collective", "transfer",
                "checkpoint", "job"}
        assert len(kinds & want) >= 4, f"only {sorted(kinds & want)}"
        # the per-fit profile agrees with the counts the summary logs
        assert prof.dispatch_count == model.summary.total_dispatches
        assert prof.eval_count == model.summary.total_evals
        assert prof.checkpoint_saves >= 1
        assert prof.transfer_count >= prof.dispatch_count
        assert prof.wall_seconds > 0
        # events carry span ids joinable onto the trace
        steps = ctx.status_store.steps(jid)
        assert steps and all(st["spanId"] for st in steps)
    finally:
        tracing.disable()


def test_traced_fit_profile_via_webui(ctx, tmp_path):
    """The per-fit profile is served by the REST/web UI surface."""
    import urllib.request
    tracing.disable()
    tracing.enable(max_spans=50_000)
    try:
        _fit_traced(ctx, tmp_path)
        jobs = [j for j in ctx.status_store.job_list()
                if "LogisticRegression.fit" in j["description"]]
        jid = jobs[-1]["jobId"]
        from cycloneml_tpu.util.webui import StatusWebUI
        ui = StatusWebUI(ctx.status_store)
        try:
            body = urllib.request.urlopen(
                f"{ui.url}api/v1/jobs/{jid}/profile", timeout=5).read()
            prof = json.loads(body)
            assert prof["dispatch_count"] >= 1
            assert prof["eval_count"] >= 1
        finally:
            ui.stop()
    finally:
        tracing.disable()


def test_chaos_fault_lands_in_trace(ctx, tmp_path):
    """A chaos run's injected fault + retry become annotations inside the
    training timeline (the readable-chaos-trace contract)."""
    from cycloneml_tpu.dataset.dataset import InstanceDataset
    from cycloneml_tpu.ml.optim import aggregators
    from cycloneml_tpu.ml.optim.lbfgs import LBFGS
    from cycloneml_tpu.ml.optim.loss import DistributedLossFunction
    from cycloneml_tpu.parallel.faults import (FaultInjector, FaultSchedule,
                                               TransientCollectiveError)
    from cycloneml_tpu.parallel.resilience import train_with_checkpoints
    from cycloneml_tpu.util.checkpoint import TrainingCheckpointer

    rng = np.random.RandomState(0)
    d = 6
    x = rng.randn(256, d)
    y = (x @ rng.randn(d) > 0).astype(np.float64)
    ds = InstanceDataset.from_numpy(ctx, x, y)
    tracing.disable()
    tracer = tracing.enable(max_spans=50_000)
    try:
        sched = FaultSchedule(seed=7)
        sched.at("collectives.step", [4],
                 TransientCollectiveError("injected DCN flake"))
        ck = TrainingCheckpointer(str(tmp_path / "ck"))
        loss = DistributedLossFunction(
            ds, aggregators.binary_logistic(d, fit_intercept=False))
        with FaultInjector(sched) as inj:
            train_with_checkpoints(
                LBFGS(max_iter=20, tol=1e-9), loss, np.zeros(d), ck,
                interval=5, max_step_failures=3, backoff_base_s=0.001,
                seed=7)
        assert inj.log  # the fault fired
        names = {s.name for s in tracer.snapshot() if s.kind == "instant"}
        assert "fault" in names and "retry" in names
        prof = tracer.profile_for(None)
        assert prof.faults_injected >= 1 and prof.retries >= 1
        assert prof.checkpoint_saves >= 1
    finally:
        tracing.disable()
