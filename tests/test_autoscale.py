"""Autoscaler + SLO control plane (ISSUE 17): policy hysteresis,
cooldowns, the decision budget's warn-and-hold degradation, bounded
capacity acquisition, the channel's concurrent-producer contract, the
lifecycle latch, event plumbing (store/route/journal), and the
simulation harness's byte-determinism + golden gate.

The closed-loop chaos e2es (breach -> announce -> reshape -> parity)
live in test_chaos.py next to the rest of the elastic suite; this file
pins the control plane's pieces in isolation.
"""

import json
import os
import threading
import time

import pytest

from cycloneml_tpu.elastic.autoscale import Autoscaler
from cycloneml_tpu.elastic.capacity import CapacityChannel, CapacityEvent
from cycloneml_tpu.elastic.policy import (AutoscalePolicy, Signals,
                                          canonical)
from cycloneml_tpu.elastic.simulate import (PolicySimulator, replay,
                                            write_decision_log)
from cycloneml_tpu.parallel.allocation import acquire_devices
from cycloneml_tpu.util.events import (AutoscaleDecision, CapacityAcquired,
                                       EventJournal, ListenerBus)
from cycloneml_tpu.util.status import (AppStatusListener, HistoryProvider,
                                       api_v1)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "autoscale")


def _breach(t_ms, **kw):
    kw.setdefault("serving_p99_ms", 120.0)
    return Signals(t_ms=t_ms, **kw)


def _healthy(t_ms, **kw):
    kw.setdefault("serving_p99_ms", 20.0)
    kw.setdefault("occupancy_fraction", 0.6)
    return Signals(t_ms=t_ms, **kw)


def _policy(**kw):
    kw.setdefault("target_p99_ms", 50.0)
    kw.setdefault("scale_up_after", 3)
    kw.setdefault("scale_down_after", 4)
    kw.setdefault("cooldown_ms", 5000)
    kw.setdefault("max_decisions", 8)
    return AutoscalePolicy(**kw)


# -- policy hysteresis (satellite 4) ----------------------------------------

def test_hysteresis_breach_recover_breach_pins_exactly_n():
    """The flap-proof contract: two sustained breach episodes separated
    by a recovery produce EXACTLY two decisions — the recovery resets
    the streak, and in-episode extra breach ticks are absorbed by the
    post-decision streak reset + cooldown."""
    p = _policy()
    decisions = []
    t = 0
    for phase, n in (("breach", 6), ("healthy", 4), ("breach", 6)):
        for _ in range(n):
            t += 1000
            s = _breach(t) if phase == "breach" else _healthy(t)
            d = p.decide(s)
            if d is not None:
                decisions.append(d)
    assert [d.action for d in decisions] == ["scale-up", "scale-up"]
    assert [d.t_ms for d in decisions] == [3000, 13000]
    assert all(d.reason == "serving-p99" for d in decisions)
    assert all(d.breach_streak == 3 for d in decisions)


def test_alternating_flap_never_reaches_a_verdict():
    """A signal oscillating every tick never builds a streak: zero
    decisions over any horizon — the hysteresis window IS the flap
    filter, no budget even gets consumed."""
    p = _policy(scale_up_after=2, scale_down_after=2)
    for i in range(1, 101):
        s = _breach(i * 1000) if i % 2 else \
            Signals(t_ms=i * 1000, serving_p99_ms=20.0,
                    occupancy_fraction=0.1)
        assert p.decide(s) is None
    assert p.decisions_applied == 0
    assert p.log == []


def test_cooldown_suppresses_refire_until_elapsed():
    """Sustained breach: after a decision the same direction re-fires no
    earlier than cooldown_ms of LOGICAL time later, even though the
    streak requirement is long since met again."""
    p = _policy(scale_up_after=2, cooldown_ms=4000)
    fired = [p.decide(_breach(t * 1000)) for t in range(1, 11)]
    times = [d.t_ms for d in fired if d is not None]
    # t2 (streak 2), then earliest eligible is t6 (6000-2000 >= 4000),
    # then t10 — never the t4/t8 a pure-streak policy would emit
    assert times == [2000, 6000, 10000]


def test_budget_exhaustion_degrades_to_one_latched_warn_hold():
    """Past max_decisions the policy emits EXACTLY ONE warn-hold
    decision and then holds silently — it neither thrashes nor spams."""
    p = _policy(scale_up_after=1, cooldown_ms=1000, max_decisions=2)
    log = [p.decide(_breach(t * 1000)) for t in range(1, 21)]
    fired = [d for d in log if d is not None]
    assert [d.action for d in fired] == \
        ["scale-up", "scale-up", "warn-hold"]
    assert fired[-1].budget_left == 0
    assert p.budget_exhausted
    # the hold is latched: nothing more, ever
    assert all(p.decide(_breach(t * 1000)) is None for t in range(21, 41))


def test_scale_down_needs_sustained_idle_and_real_gauge():
    """The down leg: occupancy below the idle fraction for
    scale_down_after CONSECUTIVE ticks → one scale-down; an unavailable
    gauge (-1, the CPU smoke) can never vote idle."""
    p = _policy(scale_down_after=3)
    idle = [p.decide(Signals(t_ms=t * 1000, occupancy_fraction=0.1))
            for t in range(1, 5)]
    fired = [d for d in idle if d is not None]
    assert [d.action for d in fired] == ["scale-down"]
    assert fired[0].reason == "idle-occupancy"
    assert fired[0].idle_streak == 3

    p2 = _policy(scale_down_after=2)
    assert all(p2.decide(Signals(t_ms=t * 1000, occupancy_fraction=-1.0))
               is None for t in range(1, 20))


def test_breach_priority_serving_over_stragglers_over_step():
    """Reason ranking when several legs breach at once: the
    user-visible serving SLO wins, then straggler pressure, then the
    step-time SLO."""
    p = _policy(scale_up_after=1)
    d = p.decide(Signals(t_ms=1000, serving_p99_ms=120.0,
                         straggler_pressure=3, step_slo_breached=True))
    assert d.reason == "serving-p99"
    p = _policy(scale_up_after=1)
    d = p.decide(Signals(t_ms=1000, straggler_pressure=3,
                         step_slo_breached=True))
    assert d.reason == "straggler-pressure"
    p = _policy(scale_up_after=1)
    d = p.decide(Signals(t_ms=1000, step_slo_breached=True))
    assert d.reason == "step-slo"


# -- bounded acquisition (the allocation tie-in) -----------------------------

def test_acquire_devices_returns_count_when_capacity_arrives():
    """The poll loop sees capacity appear mid-wait and returns the
    available count before the deadline."""
    calls = []

    def avail():
        calls.append(1)
        return 8 if len(calls) >= 3 else 4

    assert acquire_devices(5, timeout_s=5.0, poll_interval_s=0.001,
                           available_fn=avail) == 8


def test_acquire_devices_deadline_expiry_returns_none():
    start = time.monotonic()
    assert acquire_devices(99, timeout_s=0.05, poll_interval_s=0.005,
                           available_fn=lambda: 4) is None
    assert time.monotonic() - start < 2.0   # bounded, not wedged


def test_acquire_devices_cancel_event_aborts_the_wait():
    cancel = threading.Event()
    cancel.set()
    assert acquire_devices(99, timeout_s=30.0, poll_interval_s=0.01,
                           available_fn=lambda: 4, cancel=cancel) is None


# -- the autoscaler runtime --------------------------------------------------

class _Det:
    """Stub skew detector with the snapshot API the autoscaler samples."""

    def __init__(self):
        self.pressure = 0
        self.step = False

    def straggler_pressure(self, groups=None):
        return self.pressure

    def slo_breaches(self, group=None):
        return [("collectives.step", "prog")] if self.step else []


def _autoscaler(policy=None, **kw):
    kw.setdefault("channel", CapacityChannel())
    kw.setdefault("detector", _Det())
    kw.setdefault("used_fn", lambda: 4)
    kw.setdefault("acquire", lambda n, t, cancel=None: 8)
    kw.setdefault("occupancy_fn", lambda: -1.0)
    return Autoscaler(policy or _policy(scale_up_after=2,
                                        cooldown_ms=2000), **kw)


def test_tick_scale_up_acquires_then_announces():
    chan = CapacityChannel()
    det = _Det()
    bus = ListenerBus()
    listener = AppStatusListener()
    bus.add_listener(listener)          # unstarted bus: synchronous
    auto = _autoscaler(channel=chan, detector=det, bus=bus)
    det.pressure = 2
    assert auto.tick(now_ms=1000) is None          # streak 1
    d = auto.tick(now_ms=2000)                     # streak 2 -> decide
    assert d is not None and d.action == "scale-up"
    ev = chan.take()
    assert ev is not None and ev.master == "local-mesh[8]"
    rows = listener.store.autoscale_events()
    assert [r["kind"] for r in rows] == ["capacity", "decision"]
    assert rows[0]["ok"] is True and rows[0]["nDevices"] == 8
    assert rows[1]["outcome"] == "announced"


def test_acquire_deadline_expiry_is_a_clean_noop_and_loop_resumes():
    """Satellite 4's expiry leg: acquire returns None -> no channel
    event, a CapacityAcquired(ok=False) records the attempt, and the
    loop keeps ticking — the NEXT eligible decision (post-cooldown)
    proceeds normally."""
    chan = CapacityChannel()
    det = _Det()
    bus = ListenerBus()
    listener = AppStatusListener()
    bus.add_listener(listener)
    attempts = []                       # first acquire expires, rest ok

    def flaky_acquire(n, t, cancel=None):
        attempts.append(n)
        return None if len(attempts) == 1 else 8

    auto = _autoscaler(channel=chan, detector=det, bus=bus,
                       acquire=flaky_acquire)
    det.pressure = 1
    for t in range(1, 4):
        auto.tick(now_ms=t * 1000)      # decision #1 at t2: expiry
    assert len(chan) == 0               # no half-applied capacity event
    for t in range(4, 6):
        auto.tick(now_ms=t * 1000)      # decision #2 at t4 (cooldown
    assert len(chan) == 1               # elapsed): announced normally
    caps = [r for r in listener.store.autoscale_events()
            if r["kind"] == "capacity"]
    assert [c["ok"] for c in caps] == [False, True]
    outs = [r["outcome"] for r in listener.store.autoscale_events()
            if r["kind"] == "decision"]
    assert outs == ["acquire-timeout", "announced"]


def test_warn_hold_posts_event_with_outcome():
    bus = ListenerBus()
    listener = AppStatusListener()
    bus.add_listener(listener)
    det = _Det()
    auto = _autoscaler(policy=_policy(scale_up_after=1, cooldown_ms=1000,
                                      max_decisions=1),
                       detector=det, bus=bus)
    det.pressure = 1
    for t in range(1, 6):
        auto.tick(now_ms=t * 1000)
    outs = [r["outcome"] for r in listener.store.autoscale_events()
            if r["kind"] == "decision"]
    assert outs == ["announced", "warn-hold"]


def test_stop_latch_blocks_ticks_and_restart():
    chan = CapacityChannel()
    det = _Det()
    auto = _autoscaler(channel=chan, detector=det)
    det.pressure = 1
    auto.stop()
    auto.stop()                          # idempotent
    assert auto.tick(now_ms=1000) is None
    assert auto.tick(now_ms=2000) is None
    assert len(chan) == 0
    with pytest.raises(RuntimeError, match="stopped"):
        auto.start()


def test_stop_between_decide_and_announce_never_lands_on_supervisor():
    """The JX022 race, pinned deterministically: stop() lands while the
    decision is mid-apply (inside the acquire wait) — the announce path
    re-checks the latch under the lock and the decision dies there, so
    a stopped supervisor NEVER receives it."""
    chan = CapacityChannel()
    det = _Det()
    holder = {}

    def acquire_then_stopped(n, t, cancel=None):
        holder["auto"].stop()            # shutdown interleaves mid-apply
        return 8                         # capacity even arrived — too late

    auto = _autoscaler(channel=chan, detector=det,
                       acquire=acquire_then_stopped)
    holder["auto"] = auto
    det.pressure = 1
    auto.tick(now_ms=1000)
    d = auto.tick(now_ms=2000)           # decides, then hits the latch
    assert d is not None and d.action == "scale-up"
    assert len(chan) == 0                # the decision did NOT land


def test_started_loop_ticks_and_stop_joins():
    chan = CapacityChannel()
    det = _Det()
    det.pressure = 1
    auto = _autoscaler(policy=_policy(scale_up_after=1, cooldown_ms=0),
                       channel=chan, detector=det, interval_s=0.01)
    auto.start()
    deadline = time.monotonic() + 5.0
    while len(chan) == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    auto.stop()
    assert len(chan) > 0
    assert auto._thread is None


# -- the capacity channel's concurrent-producer contract (satellite 3) -------

def test_channel_concurrent_producers_fifo_non_coalescing():
    """N producers (autoscaler thread, SIGTERM handler, API callers)
    announcing simultaneously: every event arrives (non-coalescing) and
    each producer's own sequence stays FIFO."""
    chan = CapacityChannel()
    n_producers, per = 8, 50
    start = threading.Barrier(n_producers)

    def produce(pid):
        start.wait()
        for i in range(per):
            chan.announce(CapacityEvent(master=f"m{pid}-{i}",
                                        reason=f"p{pid}"))

    threads = [threading.Thread(target=produce, args=(pid,))
               for pid in range(n_producers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(chan) == n_producers * per
    seen = {pid: [] for pid in range(n_producers)}
    while True:
        ev = chan.take()
        if ev is None:
            break
        pid, i = ev.master[1:].split("-")
        seen[int(pid)].append(int(i))
    for pid in range(n_producers):
        assert seen[pid] == list(range(per)), \
            f"producer {pid} order not FIFO"


def test_channel_reentrant_announce_does_not_deadlock():
    """The SIGTERM-handler hazard: a signal handler runs on the MAIN
    thread between bytecodes, so its announce() can re-enter the lock
    an in-flight announce on the same thread already holds. With the
    RLock this completes; with a plain Lock it deadlocks the process at
    the moment it must drain. Run in a worker and join with a timeout so
    a regression fails fast instead of hanging the suite."""
    chan = CapacityChannel()
    done = threading.Event()

    def handler_during_announce():
        with chan._lock:                 # the in-flight announce's hold
            chan.announce(CapacityEvent(master="preempt",
                                        reason="SIGTERM"))
        done.set()

    t = threading.Thread(target=handler_during_announce, daemon=True)
    t.start()
    assert done.wait(5.0), \
        "reentrant announce deadlocked — CapacityChannel lock must be " \
        "reentrant for signal-handler producers"
    assert len(chan) == 1


# -- event plumbing: store, route, journal round-trip (satellite 2) ----------

def _feed_autoscale(post):
    post(AutoscaleDecision(seq=1, action="scale-up", direction="up",
                           reason="serving-p99", outcome="announced",
                           breach_streak=3))
    post(CapacityAcquired(master="local-mesh[8]", n_devices=8,
                          waited_ms=12.5, ok=True, reason="serving-p99"))
    post(AutoscaleDecision(seq=2, action="warn-hold", direction="up",
                           reason="serving-p99", outcome="warn-hold",
                           breach_streak=4))


def test_autoscale_events_fold_into_store_and_route():
    listener = AppStatusListener()
    _feed_autoscale(listener)
    rows = api_v1(listener.store, "autoscale")
    assert [r["kind"] for r in rows] == ["decision", "capacity",
                                        "decision"]
    assert rows[0]["action"] == "scale-up"
    assert rows[0]["breachStreak"] == 3
    assert rows[1]["master"] == "local-mesh[8]"
    assert rows[1]["waitedMs"] == 12.5
    assert rows[2]["outcome"] == "warn-hold"


def test_autoscale_events_journal_replay_round_trip(tmp_path):
    """History-server parity: the journal replay rebuilds the same
    autoscale rows the live bus produced."""
    path = tmp_path / "app-asc.jsonl"
    journal = EventJournal(str(path))
    bus = ListenerBus()
    live = AppStatusListener()
    bus.add_listener(journal)
    bus.add_listener(live)
    _feed_autoscale(bus.post)            # unstarted bus: synchronous
    journal.close()

    store = HistoryProvider(str(tmp_path)).load("app-asc")
    assert store.autoscale_events() == live.store.autoscale_events()
    assert len(store.autoscale_events()) == 3


def test_autoscale_store_is_bounded():
    listener = AppStatusListener()
    listener.store.max_autoscale_events = 10
    for i in range(50):
        listener(AutoscaleDecision(seq=i, action="scale-up",
                                   outcome="announced"))
    rows = listener.store.autoscale_events()
    assert len(rows) == 10
    assert rows[-1]["seq"] == 49         # newest kept, oldest dropped


# -- simulation determinism (acceptance) -------------------------------------

def _fixture_policy():
    # pinned to scripts/autoscale_sim.py golden_policy(); the golden
    # bytes fail both if either drifts alone
    return AutoscalePolicy(target_p99_ms=50.0, scale_up_after=3,
                           scale_down_after=4, cooldown_ms=5000,
                           max_decisions=3, seed=17)


def test_simulation_replay_is_byte_identical():
    trace = os.path.join(FIXTURES, "trace.jsonl")
    first = replay(trace, policy=_fixture_policy())
    second = replay(trace, policy=_fixture_policy())
    assert "\n".join(first) == "\n".join(second)
    assert len(first) > 1                # header + decisions


def test_simulation_matches_committed_golden(tmp_path):
    """The in-suite twin of `make autoscale-sim`: replaying the
    committed trace must reproduce the committed golden BYTES."""
    trace = os.path.join(FIXTURES, "trace.jsonl")
    golden = os.path.join(FIXTURES, "golden_decisions.jsonl")
    lines = replay(trace, policy=_fixture_policy())
    out = tmp_path / "got.jsonl"
    write_decision_log(lines, str(out))
    with open(golden, "rb") as fh:
        want = fh.read()
    with open(out, "rb") as fh:
        got = fh.read()
    assert got == want, "decision log drifted from committed golden " \
        "(scripts/autoscale_sim.py --update if intended)"


def test_simulator_tolerates_torn_and_metadata_lines():
    sim = PolicySimulator(_policy(scale_up_after=1))
    out = sim.run([
        canonical({"trace": "autoscale.signals", "version": 1}),
        "",
        canonical(_breach(1000).to_json()),
        '{"t_ms": 2000, "serving_p99_',     # torn tail
    ])
    assert len(out) == 2                    # header + the one decision
    assert json.loads(out[1])["action"] == "scale-up"


def test_live_recorded_trace_replays_to_the_same_decisions(tmp_path):
    """The flight-recorder contract end to end: an autoscaler recording
    its own signal trace produces a file whose REPLAY through a fresh
    policy (same knobs) reproduces the live decision log byte-for-byte —
    recorded incidents are debuggable offline."""
    record = tmp_path / "signals.jsonl"
    det = _Det()
    live_policy = _policy(scale_up_after=2, cooldown_ms=2000, seed=3)
    auto = _autoscaler(policy=live_policy, detector=det,
                       record_path=str(record))
    det.pressure = 1
    for t in range(1, 8):
        if t == 5:
            det.pressure = 0             # mid-run recovery, recorded too
        auto.tick(now_ms=t * 1000)
    auto.stop()

    fresh = _policy(scale_up_after=2, cooldown_ms=2000, seed=3)
    with open(record, encoding="utf-8") as fh:
        PolicySimulator(fresh).run(fh)
    live = [canonical(d.to_json()) for d in live_policy.log]
    replayed = [canonical(d.to_json()) for d in fresh.log]
    assert live and live == replayed
