"""Performance doctor + regression sentinel (observe/diagnose, regress).

Three planes of coverage:

- rule units over SYNTHETIC spans: each rule fires on its pathology and
  abstains on healthy input (the clean-fit == zero-findings contract),
- plumbing: canonical-JSON determinism, Chrome-trace round-trip,
  DiagnosisCompleted -> store -> /api/v1/diagnosis -> journal replay,
  SkewDetector.lane_snapshot (one-lock consistency + torn-read hammer),
- the ledger: rows_from_bench meta joins, idempotent append, median+MAD
  drift verdicts in both directions, the non-stationary-history cap,
  and the chaos leg: a seeded fault-injected streamed fit diagnoses to
  EXACTLY the injected pathologies — nothing else.
"""

import itertools
import json
import threading

import numpy as np
import pytest

from cycloneml_tpu.observe import regress, tracing
from cycloneml_tpu.observe.diagnose import (DiagnosisReport, DoctorConfig,
                                            Finding, diagnose,
                                            lane_stats_from_spans,
                                            overlap_fraction)
from cycloneml_tpu.observe.skew import SkewDetector
from cycloneml_tpu.observe.tracing import Span

_ids = itertools.count()


def mk(kind, name, t0=0.0, dur=0.001, **attrs):
    s = Span(f"s{next(_ids)}", "", kind, name, 0, attrs)
    s.t0 = t0
    s.t1 = t0 + dur
    return s


def instant(name, t=0.0, **attrs):
    return mk("instant", name, t0=t, dur=0.0, **attrs)


def _clean_window():
    """What a warm in-core fit's span window looks like."""
    return [mk("job", "LogisticRegression.fit", 0.0, 0.1),
            mk("dispatch", "lbfgs.chunk", 0.01, 0.08),
            mk("transfer", "lbfgs.readback", 0.09, 0.001),
            instant("cache.hit", 0.005, cache="program")]


# -- rule units ----------------------------------------------------------------

def test_clean_window_diagnoses_to_zero_findings():
    report = diagnose(spans=_clean_window(), skew=None, cache_stats=None)
    assert report.findings == []
    assert report.n_spans == 4
    assert "spans" in report.inputs and "profile" in report.inputs


def test_recompile_storm_fires_past_warmup_and_abstains_below():
    warm = [mk("compile", "lbfgs.chunk", 0.0, 0.5)]        # excess 0
    assert diagnose(spans=_clean_window() + warm, skew=None,
                    cache_stats=None).findings == []
    storm = [mk("compile", "lbfgs.chunk", i * 1.0, 0.5) for i in range(3)]
    report = diagnose(spans=_clean_window() + storm, skew=None,
                      cache_stats=None)
    assert report.kinds == ["recompile-storm"]
    (f,) = report.findings
    assert f.severity == "warning"
    assert f.evidence["excess_compiles"] == {"lbfgs.chunk": 2}
    assert f.evidence["total_excess"] == 2


def test_transfer_stall_fires_on_readbacks_not_streaming():
    dispatch = [mk("dispatch", "lbfgs.chunk", i * 0.1, 0.01)
                for i in range(10)]
    readbacks = [mk("transfer", "lbfgs.readback", i * 0.1 + 0.05, 0.02)
                 for i in range(10)]
    report = diagnose(spans=dispatch + readbacks, skew=None,
                      cache_stats=None)
    assert "transfer-stall" in report.kinds
    f = report.findings[report.kinds.index("transfer-stall")]
    assert f.evidence["transfer_count"] == 10
    assert f.evidence["transfer_seconds"] == pytest.approx(0.2)
    # the SAME seconds as oocore.stage staging spans: overlap's problem,
    # not a stall — the rule must exclude the streaming plane
    staging = [mk("transfer", "oocore.stage", i * 0.1 + 0.05, 0.02,
                  shard=i) for i in range(10)]
    assert "transfer-stall" not in diagnose(
        spans=dispatch + staging, skew=None, cache_stats=None).kinds


def test_straggler_convicted_from_trace_spans_alone():
    spans = []
    t = 0.0
    for _ in range(8):                      # 8 samples per lane
        for shard in range(4):
            dur = 0.050 if shard == 0 else 0.005
            spans.append(mk("transfer", "oocore.stage", t, dur, shard=shard))
            t += 0.06
    cfg_spans = spans + _clean_window()
    report = diagnose(spans=cfg_spans, skew=None, cache_stats=None,
                      conf=None)
    # default skew_min_samples=8 is exactly met
    assert "straggler" in report.kinds
    f = report.findings[report.kinds.index("straggler")]
    assert f.evidence["detector"] == "trace"
    assert [b["lane"] for b in f.evidence["outliers"]] == ["shard0"]
    lanes = lane_stats_from_spans(spans)
    assert len(lanes["shard0"]) == 8


def test_straggler_from_live_snapshot_dedups_trace_lane():
    snap = {"oocore.stage": {
        "groupMedianS": 0.005, "madS": 0.0002,
        "lanes": {"shard0": {"n": 8, "medianS": 0.05, "straggler": True,
                             "sloBreached": False},
                  "shard1": {"n": 8, "medianS": 0.005, "straggler": False,
                             "sloBreached": False}}}}
    spans = []
    t = 0.0
    for _ in range(8):
        for shard in range(4):
            dur = 0.050 if shard == 0 else 0.005
            spans.append(mk("transfer", "oocore.stage", t, dur, shard=shard))
            t += 0.06
    report = diagnose(spans=spans, skew=snap, cache_stats=None)
    stragglers = [f for f in report.findings if f.kind == "straggler"]
    # ONE finding: the live latch wins, the trace echo of the SAME lane
    # must not double-report
    assert len(stragglers) == 1
    assert stragglers[0].evidence["detector"] == "live"
    assert stragglers[0].evidence["lanes"] == ["shard0"]


def test_underlap_fires_on_serialized_stream_and_passes_overlapped():
    serial, overlapped = [], []
    for i in range(8):
        serial.append(mk("transfer", "oocore.stage", i * 0.02, 0.01,
                         shard=i))
        serial.append(mk("dispatch", "oocore.shard", i * 0.02 + 0.01, 0.01,
                         shard=i))
        overlapped.append(mk("transfer", "oocore.stage", i * 0.01, 0.01,
                             shard=i))
        overlapped.append(mk("dispatch", "oocore.shard", i * 0.01 + 0.001,
                             0.01, shard=i))
    report = diagnose(spans=serial, skew=None, cache_stats=None)
    assert "under-lapped-streaming" in report.kinds
    f = report.findings[report.kinds.index("under-lapped-streaming")]
    assert f.evidence["overlap_fraction"] < 0.30
    frac, *_ = overlap_fraction(overlapped)
    assert frac > 0.30
    assert "under-lapped-streaming" not in diagnose(
        spans=overlapped, skew=None, cache_stats=None).kinds


def test_serving_pressure_on_shed_and_slo():
    stats = {"models": {"m": {"latencyMs": {"p99": 40.0}}},
             "totals": {"shed": 3, "requests": 100}}
    report = diagnose(spans=[], serving_stats=stats, skew=None,
                      cache_stats=None)
    assert report.kinds == ["serving-pressure"]
    assert report.findings[0].evidence["shed"] == 3
    # healthy batcher: no shed, no SLO configured
    ok = {"models": {"m": {"latencyMs": {"p99": 40.0}}},
          "totals": {"shed": 0, "requests": 100}}
    assert diagnose(spans=[], serving_stats=ok, skew=None,
                    cache_stats=None).findings == []
    # p99 over a configured SLO convicts even with zero shed
    from cycloneml_tpu.observe.diagnose import _rule_serving
    cfg = DoctorConfig(slo_serving_ms=25.0)
    (f,) = _rule_serving(ok, cfg)
    assert f.evidence["worst_p99_ms"] == 40.0
    assert f.evidence["worst_model"] == "m"


def test_precision_churn_counts_fallback_instants():
    spans = _clean_window() + [instant("precision.fallback", 0.02,
                                       dtype="float8_e4m3")]
    report = diagnose(spans=spans, skew=None, cache_stats=None)
    assert report.kinds == ["precision-churn"]
    assert report.findings[0].evidence["fp8_fallbacks"] == 1


def test_cache_restream_fires_on_thrash_not_on_healthy_reuse():
    thrash = {"hits": 0, "misses": 3, "evictionsLru": 2,
              "evictionsCorrupt": 0}
    report = diagnose(spans=[], cache_stats=thrash, skew=None)
    assert report.kinds == ["cache-restream"]
    assert report.findings[0].evidence["misses"] == 3
    healthy = {"hits": 9, "misses": 1, "evictionsLru": 1,
               "evictionsCorrupt": 0}
    assert diagnose(spans=[], cache_stats=healthy, skew=None).findings == []


def test_fault_pressure_joins_chaos_instants_and_stage_retries():
    spans = [instant("fault", 0.01, point="oocore.stage", invocation=1,
                     fault="SlowStep"),
             instant("fault", 0.02, point="oocore.stage", invocation=17,
                     fault="SlowStep"),
             instant("oocore.stage_retry", 0.03, shard=2, attempt=1)]
    report = diagnose(spans=spans, skew=None, cache_stats=None)
    assert report.kinds == ["fault-pressure"]
    ev = report.findings[0].evidence
    assert ev["faults_injected"] == 2
    assert ev["retries"] == 1
    assert ev["points"] == {"oocore.stage": 2}


# -- report plumbing -----------------------------------------------------------

def test_report_canonical_json_is_deterministic_and_round_trips():
    spans = _clean_window() + [
        mk("compile", "lbfgs.chunk", i * 1.0, 0.5) for i in range(3)]
    a = diagnose(spans=spans, skew=None, cache_stats=None, source="trace")
    b = diagnose(spans=spans, skew=None, cache_stats=None, source="trace")
    assert a.to_json() == b.to_json()          # byte-identical
    back = DiagnosisReport.from_dict(json.loads(a.to_json()))
    assert back == a                           # dataclass round-trip
    assert back.findings[0] == Finding.from_dict(
        a.findings[0].to_dict())


def test_chrome_trace_round_trip_preserves_diagnosis():
    """Export the window to Trace Event Format, parse it back, diagnose:
    the offline CLI's path must convict the same kinds with the same
    lanes as the in-process window."""
    from cycloneml_tpu.observe.export import (chrome_trace,
                                              spans_from_chrome_trace)
    spans = [mk("compile", "lbfgs.chunk", i * 1.0, 0.5) for i in range(3)]
    t = 10.0
    for _ in range(8):
        for shard in range(4):
            dur = 0.050 if shard == 0 else 0.005
            spans.append(mk("transfer", "oocore.stage", t, dur, shard=shard))
            t += 0.06
    spans.append(instant("fault", 20.0, point="oocore.stage", invocation=1,
                         fault="SlowStep"))
    live = diagnose(spans=spans, skew=None, cache_stats=None)

    tracer = tracing.Tracer(max_spans=1000)
    parsed = spans_from_chrome_trace(chrome_trace(tracer, spans=spans))
    offline = diagnose(spans=parsed, skew=None, cache_stats=None)
    assert offline.kinds == live.kinds
    assert sorted(set(offline.kinds)) == ["fault-pressure",
                                          "recompile-storm", "straggler"]
    off_straggler = offline.findings[offline.kinds.index("straggler")]
    assert [b["lane"] for b in off_straggler.evidence["outliers"]] \
        == ["shard0"]
    # and the parsed window itself re-diagnoses byte-identically — the
    # CLI invariant `make doctor` leans on
    again = diagnose(spans=spans_from_chrome_trace(
        chrome_trace(tracer, spans=spans)), skew=None, cache_stats=None)
    assert again.to_json() == offline.to_json()


def test_diagnosis_event_reaches_store_api_and_survives_replay(tmp_path):
    from cycloneml_tpu.util.events import (DiagnosisCompleted, EventJournal,
                                           ListenerBus)
    from cycloneml_tpu.util.status import AppStatusListener, api_v1

    report = diagnose(spans=_clean_window() + [
        mk("compile", "lbfgs.chunk", i * 1.0, 0.5) for i in range(3)],
        skew=None, cache_stats=None, source="live")
    path = tmp_path / "events.jsonl"
    journal = EventJournal(str(path))
    live = AppStatusListener()
    bus = ListenerBus()
    bus.add_listener(journal)
    bus.add_listener(live)
    bus.post(DiagnosisCompleted(source=report.source,
                                n_findings=len(report.findings),
                                report=report.to_dict()))
    bus.stop()
    journal.close()

    rows = live.store.diagnosis_reports()
    assert len(rows) == 1
    assert rows[0]["nFindings"] == 1
    assert rows[0]["report"]["findings"][0]["kind"] == "recompile-storm"
    assert api_v1(live.store, "diagnosis") == rows
    # history-server fidelity: replay rebuilds the identical rows
    replayed = AppStatusListener()
    for e in EventJournal.replay(str(path)):
        replayed.on_event(e)
    assert replayed.store.diagnosis_reports() == rows
    # the replayed dict round-trips into the same report object
    assert DiagnosisReport.from_dict(
        replayed.store.diagnosis_reports()[0]["report"]) == report


# -- SkewDetector.lane_snapshot (satellite: one-lock consistency) ---------------

def test_lane_snapshot_reports_medians_and_latched_verdicts():
    det = SkewDetector(window=16, min_samples=4, mad_factor=4.0,
                       rel_factor=1.5)
    for _ in range(8):
        for lane in ("shard1", "shard2", "shard3"):
            det.observe("oocore.stage", lane, 0.010)
        det.observe("oocore.stage", "shard0", 0.050)
    snap = det.lane_snapshot()
    g = snap["oocore.stage"]
    assert g["groupMedianS"] == pytest.approx(0.010)
    assert set(g["lanes"]) == {"shard0", "shard1", "shard2", "shard3"}
    assert g["lanes"]["shard0"]["straggler"] is True
    assert g["lanes"]["shard0"]["medianS"] == pytest.approx(0.050)
    assert g["lanes"]["shard1"]["straggler"] is False
    assert g["lanes"]["shard1"]["n"] == 8
    # the snapshot is exactly what the doctor convicts on
    report = diagnose(spans=[], skew=snap, cache_stats=None)
    assert report.kinds == ["straggler"]
    assert report.findings[0].evidence["lanes"] == ["shard0"]


def test_lane_snapshot_group_filter():
    det = SkewDetector(window=16, min_samples=2)
    for _ in range(4):
        det.observe("oocore.stage", "shard0", 0.01)
        det.observe("serving.dispatch", "m0", 0.02)
    assert set(det.lane_snapshot()) == {"oocore.stage", "serving.dispatch"}
    only = det.lane_snapshot(group="oocore.stage")
    assert set(only) == {"oocore.stage"}


def test_lane_snapshot_hammer_no_torn_reads():
    """Writers observe() while a reader snapshots: every snapshot must be
    internally consistent (a lane present => its stats all present, n
    bounded by the window) — the one-lock contract."""
    det = SkewDetector(window=16, min_samples=2)
    stop = threading.Event()
    errs = []

    def writer(lane):
        rng = np.random.RandomState(hash(lane) % 2**31)
        while not stop.is_set():
            det.observe("oocore.stage", lane, 0.005 + 0.001 * rng.rand())

    threads = [threading.Thread(target=writer, args=(f"shard{i}",),
                                daemon=True) for i in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            snap = det.lane_snapshot()
            try:
                for group, g in snap.items():
                    assert set(g) == {"groupMedianS", "madS", "lanes"}
                    for lane, row in g["lanes"].items():
                        assert set(row) == {"n", "medianS", "straggler",
                                            "sloBreached"}
                        assert 0 < row["n"] <= 16
                        assert row["medianS"] is None or row["medianS"] > 0
            except AssertionError as exc:   # pragma: no cover
                errs.append(exc)
                break
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    assert errs == []


# -- the regression sentinel ----------------------------------------------------

def _bench_block(value, run_id, t, serving=None):
    block = {"metric": "logreg_fit_e2e_throughput", "value": value,
             "unit": "rows_per_s",
             "meta": {"schema_version": 1, "run_id": run_id,
                      "git_sha": "abc1234", "t_logical": t},
             "hardware": {"platform": "cpu", "device_kind": "cpu",
                          "n_devices": 8}}
    if serving:
        block["serving"] = serving
    return block


def test_rows_from_bench_joins_meta_and_gated_submetrics():
    rows = regress.rows_from_bench(_bench_block(
        1000.0, "r10", 10,
        serving={"requests_per_s": 500.0, "p99_ms": 12.5}))
    assert [r["metric"] for r in rows] == [
        "logreg_fit_e2e_throughput", "serving.requests_per_s",
        "serving.p99_ms"]
    head = rows[0]
    assert head["run_id"] == "r10" and head["t_logical"] == 10
    assert head["git_sha"] == "abc1234"
    assert head["hw"] == {"platform": "cpu", "device": "cpu",
                          "n_devices": 8}
    assert head["direction"] == "higher"
    assert rows[2]["direction"] == "lower"      # p99: lower is better
    # canonical rows are byte-stable
    assert regress.canonical_row(head) == regress.canonical_row(
        json.loads(regress.canonical_row(head)))


def test_append_is_idempotent_keyed_by_run_and_metric(tmp_path):
    ledger = str(tmp_path / "hist.jsonl")
    rows = regress.rows_from_bench(_bench_block(1000.0, "r10", 10))
    assert regress.append(ledger, rows) == 1
    assert regress.append(ledger, rows) == 0    # replay adds nothing
    rows2 = regress.rows_from_bench(_bench_block(1100.0, "r11", 11))
    assert regress.append(ledger, rows2) == 1
    assert len(regress.load(ledger)) == 2


def test_detect_verdicts_in_both_directions():
    def series(*values, metric="m", direction="higher"):
        return [{"metric": metric, "value": v, "run_id": f"r{i}",
                 "t_logical": i, "hw": None, "direction": direction}
                for i, v in enumerate(values)]

    # stable history, candidate inside the band
    (v,) = regress.detect(series(100.0, 101.0, 99.0, 100.0, 100.5))
    assert v["verdict"] == "ok" and v["window_n"] == 4
    # a drop past max(4*MAD, 5%) regresses
    (v,) = regress.detect(series(100.0, 101.0, 99.0, 100.0, 60.0))
    assert v["verdict"] == "regression"
    # a jump up is an improvement, never a failure
    (v,) = regress.detect(series(100.0, 101.0, 99.0, 100.0, 160.0))
    assert v["verdict"] == "improvement"
    assert regress.gate(regress.detect(
        series(100.0, 101.0, 99.0, 100.0, 160.0))) == (0, [])
    # lower-is-better metrics invert: p99 doubling IS the regression
    (v,) = regress.detect(series(10.0, 10.2, 9.9, 10.1, 20.0,
                                 direction="lower"))
    assert v["verdict"] == "regression"
    rc, bad = regress.gate([v])
    assert rc == 1 and bad == ["m"]
    # too little history abstains
    (v,) = regress.detect(series(100.0, 95.0))
    assert v["verdict"] == "insufficient-history"


def test_detect_caps_threshold_on_nonstationary_history():
    """A fast-improving history (the committed r02->r05 is 13.9x) has a
    MAD so wide that 4*MAD exceeds the median — uncapped, NO drop could
    ever trip the gate. The cap keeps the sentinel honest."""
    rows = [{"metric": "m", "value": v, "run_id": f"r{i}", "t_logical": i,
             "hw": None, "direction": "higher"}
            for i, v in enumerate([10.0, 40.0, 80.0, 160.0, 20.0])]
    (v,) = regress.detect(rows)
    assert v["verdict"] == "regression"
    assert v["threshold"] <= 0.5 * v["median"]


def test_detect_separates_incomparable_hardware():
    """Rows from different hardware never judge each other."""
    base = {"metric": "m", "direction": "higher"}
    cpu = {"platform": "cpu", "device": "cpu", "n_devices": 8}
    tpu = {"platform": "tpu", "device": "v5e", "n_devices": 8}
    rows = [dict(base, value=100.0 + i, run_id=f"c{i}", t_logical=i, hw=cpu)
            for i in range(4)]
    # the newest row is TPU: its comparable history is empty
    rows.append(dict(base, value=5.0, run_id="t0", t_logical=9, hw=tpu))
    (v,) = regress.detect(rows)
    assert v["verdict"] == "insufficient-history"


def test_ctx_diagnose_posts_report_to_live_status_plane(ctx):
    """The ctx.diagnose() surface: report returned AND visible at
    /api/v1/diagnosis via the event plumbing."""
    from cycloneml_tpu.util.status import api_v1

    storm = _clean_window() + [
        mk("compile", "lbfgs.chunk", i * 1.0, 0.5) for i in range(3)]
    report = ctx.diagnose(spans=storm)
    assert "recompile-storm" in report.kinds
    assert ctx.listener_bus.wait_until_empty()
    rows = api_v1(ctx.status_store, "diagnosis")
    assert rows and rows[-1]["report"] == report.to_dict()
    assert rows[-1]["nFindings"] == len(report.findings)


# -- chaos: injected pathologies and NOTHING else -------------------------------

def test_doctor_over_seeded_chaos_run_flags_exactly_the_injections(ctx):
    """A streamed fit under a seeded FaultSchedule (a delayed staging
    lane + one transient connection reset) must diagnose to EXACTLY
    {straggler, fault-pressure}: the chaos shows up, nothing else false-
    positives, and the same window re-diagnoses byte-identically."""
    from cycloneml_tpu.conf import CycloneConf
    from cycloneml_tpu.ml.classification import LogisticRegression
    from cycloneml_tpu.observe import skew as skew_mod
    from cycloneml_tpu.oocore import StreamingDataset
    from cycloneml_tpu.parallel.faults import (FaultInjector, FaultSchedule,
                                               InjectedConnectionReset)

    rng = np.random.RandomState(3)
    n, d, shard_rows = 4096, 16, 256
    n_shards = n // shard_rows
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ rng.randn(d) > 0).astype(np.float64)

    def chunks():
        for i in range(0, n, shard_rows):
            yield x[i:i + shard_rows], y[i:i + shard_rows], None

    sds = StreamingDataset.from_chunks(ctx, chunks(), d,
                                       shard_rows=shard_rows)
    det = SkewDetector(window=64, min_samples=2, mad_factor=4.0,
                       rel_factor=1.5, min_gap_s=0.010)
    prev = skew_mod.install(det)
    tr = tracing.enable(max_spans=50_000)
    # overlap over a chaos window measures the fault schedule, not the
    # double buffer — gate it off for the exactness assertion
    conf = CycloneConf().set("cyclone.doctor.overlapMin", 0.0)
    try:
        LogisticRegression(maxIter=3, regParam=0.1).fit(sds)   # warm
        sched = FaultSchedule(seed=7)
        # shuffle is off, so invocation order IS shard order — but every
        # retry attempt consumes an invocation number too. The reset at
        # #5 (shard 4, epoch 1) retries once, so epoch 1 spans
        # invocations 1..17 and epoch k >= 2 starts at 18+(k-2)*16:
        # these delays all land on shard 0, the unmasked straggler
        sched.at("oocore.stage", [1] + [18 + k * n_shards
                                        for k in range(32)],
                 delay_s=0.04)
        # one transient reset mid-epoch: staging must retry, not die
        sched.at("oocore.stage", 5, InjectedConnectionReset("peer reset"))
        mark = tr.mark()
        with FaultInjector(sched) as inj:
            model = LogisticRegression(maxIter=3, regParam=0.1).fit(sds)
        assert model.summary.streamed
        assert ("oocore.stage", 5, "InjectedConnectionReset") in inj.log
        spans = tr.snapshot(since=mark)

        report = diagnose(spans=spans, skew=det, cache_stats=None,
                          conf=conf, source="live")
        assert sorted(set(report.kinds)) == ["fault-pressure", "straggler"]
        straggler = report.findings[report.kinds.index("straggler")]
        assert straggler.evidence["detector"] == "live"
        assert straggler.evidence["lanes"] == ["shard0"]
        faults = report.findings[report.kinds.index("fault-pressure")]
        assert faults.evidence["retries"] >= 1            # the reset
        assert faults.evidence["points"]["oocore.stage"] >= 2
        # determinism: the same window re-diagnoses to the same bytes
        again = diagnose(spans=spans, skew=det.lane_snapshot(),
                         cache_stats=None, conf=conf, source="live")
        assert again.to_json() == report.to_json()
    finally:
        tracing.disable()
        skew_mod.install(prev)
        sds.close()
